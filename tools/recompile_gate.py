#!/usr/bin/env python3
"""Dynamic recompile gate: a second epoch must compile NOTHING.

PR 3's streaming design guarantees every chunk of a stream shares one
padded shape, so the per-chunk programs (wire cast, transform chain,
accumulate) compile exactly once — the "second epoch compiles nothing"
invariant, pinned by a tier-1 test since PR 3 and by the compile
observatory's per-fit warmup fence since PR 9. This tool pins it at the
CI level against the REAL streamed CIFAR-shaped path: it runs a smoke
streamed fit twice (fresh ``StreamingDataset`` each epoch, exactly how
``bench.py``'s streamed e2e refits) with the SECOND epoch wrapped in
``expect_no_compiles``, and fails (exit 1) if ``compile.unexpected_total``
grew — naming each offending jit site and the signature delta that
triggered it, which is precisely the evidence a regressed jit memo
(per-instance cache, unstable cache tag, mesh-baked closure) leaves.

Run by ``bin/ci.sh`` between the static layers and tier-1 pytest; also
usable standalone::

    JAX_PLATFORMS=cpu python tools/recompile_gate.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability import (
        compile_observatory,
        expect_no_compiles,
    )
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    rng = np.random.RandomState(0)
    # CIFAR-shaped smoke: uint8 chunks on the wire, f32 compute, a
    # per-chunk featurize in the transform chain — the full streamed
    # program surface (cast + map_chunks + accumulate) in miniature
    n, side, chunk = 256, 8, 64
    imgs = (rng.rand(n, side * side * 3) * 255).astype(np.uint8)
    y = rng.randint(0, 10, n)
    labels = (-np.ones((n, 10)) + 2.0 * np.eye(10)[y]).astype(np.float32)

    def featurize(ad):
        return ad.map_batch(lambda x: jnp.tanh(
            x.astype(jnp.float32) / 255.0))

    def epoch():
        stream = StreamingDataset.from_numpy(
            imgs, chunk_size=chunk, wire_dtype=np.uint8,
            tag="recompile-gate").map_chunks(featurize)
        return fit_streaming(LinearMapEstimator(lam=0.1), stream, labels)

    obs = compile_observatory()
    epoch()  # epoch 1: every per-chunk program compiles once, here
    before = obs.unexpected_total()
    first_epoch_compiles = obs.count_total()
    with expect_no_compiles("recompile-gate:second-epoch"):
        epoch()  # epoch 2: steady state — must compile NOTHING
    unexpected = obs.unexpected_total() - before
    print(f"recompile gate: epoch 1 compiled {first_epoch_compiles} "
          f"program(s); epoch 2 unexpected recompiles: {unexpected}")
    if unexpected:
        for rec in obs.unexpected_records():
            print(f"  UNEXPECTED {rec.get('name')} "
                  f"({rec.get('trigger')}, {rec.get('wall_s', 0.0):.3f}s)"
                  + (f": {rec['delta']}" if rec.get("delta") else ""),
                  file=sys.stderr)
        print("recompile gate FAILED: the second epoch of a fixed-shape "
              "streamed fit recompiled — a jit memo regressed "
              "(per-instance cache / unstable tag / mesh-baked closure); "
              "the deltas above name the drifted signatures",
              file=sys.stderr)
        return 1
    print("recompile gate OK: second epoch compiled nothing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
