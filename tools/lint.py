"""The repo's own static gate — run before every PR.

Three layers, all hermetic (no data, no device buffers):

1. **Pipeline checks**: ``python -m keystone_tpu check`` semantics over
   every registered app (``keystone_tpu.pipelines.CHECK_APPS``) — the
   abstract interpreter plus graph lints must report zero diagnostics.
2. **Custom AST rules** over the ``keystone_tpu`` source tree:
   - ``host-coercion-in-apply``: a device-side ``Transformer.apply``
     body must not call ``np.asarray``/``np.array`` on its item
     argument (forces a per-item device sync; ADVICE r2/r3 lineage).
     HostTransformers are exempt.
   - **recompile hazards** (``analysis.diagnostics.recompile_hazards``,
     tree-wide): ``mesh-closure-jit`` — a module-lifetime ``jax.jit``
     of an ambient-mesh-reading function (the pre-PR-2 ``_bcd_jit_for``
     bug: the first mesh's sharding bakes into the cached trace);
     ``per-instance-jit-memo`` — a compiled program memoized on
     ``self`` with no global cache behind it (the ``_CAST_JIT_CACHE``
     lesson: refits rebuild the instance and recompile);
     ``unstable-jit-cache-tag`` — ``self._cached_jit(tag, ...)`` must
     pass a string-literal tag (computed tags break warm-executable
     reuse across sessions).
   - **donation safety** (``analysis.diagnostics.donation_hazards``,
     tree-wide): ``use-after-donate`` / ``checkpoint-after-donate`` —
     a name passed at a ``donating_jit`` donate position and read (or
     checkpoint-saved) afterwards in the same scope: the buffer is
     dead on TPU/GPU and silently alive on CPU tests. Plus the
     spec-level ``donation-shape-mismatch`` gate: every registered
     ``donating_jit`` site with a shape probe must donate only
     arguments with a shape-compatible output (``jax.eval_shape``,
     device-free — the static promotion of jax's per-compile
     donated-buffer-not-usable warning).
   - ``swallow-all-handler`` (ingest + workflow code only —
     ``loaders/``, ``parallel/``, ``workflow/``): no bare ``except:``
     and no silent ``except Exception: pass`` — exactly where "skip
     the error and keep going" becomes silent data loss. Tolerating a
     failure there goes through the resilience layer (RetryPolicy /
     Quarantine), which accounts for it.
   - ``cast-before-transfer`` (loader + staging code — ``loaders/``,
     ``parallel/``): no host-side float widening in a function that
     also ``device_put``\\ s — widening uint8 records to float before
     the transfer ships 4x the bytes; ship the source dtype and let
     the device cast (``StreamingDataset`` ``wire_dtype`` /
     ``compute_dtype``).
   - ``silent-nan-silencer`` (numeric compute trees — ``nodes/``,
     ``ops/``, ``parallel/``, ``workflow/``): a ``nan_to_num`` or
     ``np.errstate(...='ignore')`` suppression must pair with a
     recorded ``numerics.*`` event in the same scope
     (``record_numerics_event`` / the solver-ledger recorders) —
     suppression can be the right recovery, but it must be ACCOUNTED
     (observability/numerics.py, README 'Numerics health').
   - ``metric-name-drift`` (tree-wide): every
     ``counter/gauge/histogram/timer(...)`` call site must use a name
     (or f-string prefix) from the catalogue in
     ``observability/names.py`` — Prometheus dashboards and the
     benchdiff gate address metrics by name, so an uncatalogued
     literal is a typo or an unreviewed rename.
   - **concurrency safety** (``analysis.concurrency``, PR 7):
     ``guarded-field-race`` — an RMW/compound mutation of a
     ``@guarded_by``-declared field outside its lock (tree-wide; fires
     only on declared classes); ``lock-order-cycle`` +
     ``blocking-under-lock`` — the static lock-acquisition graph from
     ``with``-nesting must be acyclic and no blocking call
     (``queue.get``, ``Event.wait``, ``device_put``, ...) may run
     under an analyzer-known lock (scoped by ``CONCURRENCY_SCOPES``);
     ``non-atomic-guarded-sequence`` — check-then-act on a guarded
     field split across two ``with`` blocks. Deliberate exceptions
     live in the commented ``CONCURRENCY_ALLOWLIST``.
   - **SPMD safety** (``analysis.spmd``, tree-wide):
     ``collective-divergence`` — a collective/barrier site reachable
     under host-divergent control flow (a branch on
     ``process_index()`` or per-host taint): one host skips the
     collective and the rest of the world wedges in it;
     ``unstable-barrier-name`` / ``non-fixed-coordination-shape`` —
     barrier tags must be string literals per call site and
     ``process_allgather`` payloads fixed-shape (the
     ``WorldCoordinator.step`` ``(cursor, done)`` discipline);
     ``unbound-collective-axis`` — ``psum``/``all_gather`` axis names
     must be bound by a mesh axis in scope;
     ``unbarriered-host0-effect`` / ``carry-restore-discipline`` —
     host-0-only world-snapshot effects must be barrier-paired and
     restored carries must re-enter through ``_restore_carry``.
     Deliberate exceptions live in the commented ``SPMD_ALLOWLIST``.
   - **hot-path safety** (``analysis.hotpath``, PR 17): the
     interprocedural request-path pass. From every ``@hotpath``-marked
     serving entry point, the static call graph is walked and each
     reachable call classified: ``hotpath-blocking`` (queue waits,
     joins, sleeps, future ``.result``), ``hotpath-host-sync``
     (``block_until_ready`` / ``device_put`` / numpy coercions — a
     host-device round trip per request), ``hotpath-io`` (filesystem /
     network / pickle on the request path), ``hotpath-lazy-import``
     (a per-request import statement), ``hotpath-unbounded-growth``
     (appending to a container no code path ever shrinks), and
     ``hotpath-lock-held-dispatch`` (a call under a held lock whose
     callee transitively blocks or syncs). Every diagnostic names the
     full call chain from the entry point. Plus the atomic-publication
     pass over ``@published_by`` classes: ``unpublished-write`` /
     ``non-atomic-publication`` / ``torn-publication`` — a published
     field may only change via a single-reference atomic flip under its
     declared lock (the swap discipline hot-swap will ride on).
     Deliberate exceptions live in the commented ``HOTPATH_ALLOWLIST``;
     the full-tree scan must also finish under
     ``HOTPATH_SCAN_BUDGET_S`` (the gate emits its runtime).
3. **ruff** (when installed): style/correctness pass over the package.
   Skipped with a notice when the container lacks ruff — layers 1–2
   are the required gate.

Usage: ``python tools/lint.py [--skip-apps]`` or
``bin/run-pipeline.sh --check`` (which also runs the budgeted
``check --all`` plan gate via ``bin/ci.sh --no-tests``). Exit code
0 = clean.
"""
from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "keystone_tpu"


# -- layer 2: AST rules ------------------------------------------------------

def _class_is_host_transformer(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else getattr(
            base, "id", "")
        if "Host" in str(name):
            return True
    return False


def _iter_transformer_applies(tree: ast.Module):
    """(class, apply FunctionDef) pairs for transformer-looking classes.

    Purely syntactic (no imports): any class whose base name mentions
    Transformer and that defines ``apply(self, item)``; classes whose
    base mentions Host are exempt."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        basenames = " ".join(
            str(b.attr if isinstance(b, ast.Attribute)
                else getattr(b, "id", "")) for b in node.bases)
        if "Transformer" not in basenames:
            continue
        if _class_is_host_transformer(node):
            continue
        for item in node.body:
            if isinstance(item, ast.FunctionDef) and item.name == "apply":
                yield node, item


def _host_coercions_in(fdef: ast.FunctionDef):
    # single source of truth for the coercion pattern lives in the
    # analysis package; this gate only adds the file-walk around it
    from keystone_tpu.analysis.diagnostics import host_coercions_in_funcdef

    yield from host_coercions_in_funcdef(fdef)


def run_ast_rules() -> int:
    from keystone_tpu.analysis.diagnostics import (
        CAST_BEFORE_TRANSFER_SCOPES,
        NAN_SILENCER_SCOPES,
        SWALLOW_ALL_SCOPES,
        donation_hazards,
        float_casts_before_transfer,
        metric_name_drift,
        recompile_hazards,
        silent_nan_silencers,
        swallow_all_handlers,
    )

    failures = 0
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO)
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as exc:
            print(f"{rel}: syntax error: {exc}")
            failures += 1
            continue
        for cls, fdef in _iter_transformer_applies(tree):
            for lineno, what in _host_coercions_in(fdef):
                print(f"{rel}:{lineno}: host-coercion-in-apply: "
                      f"{cls.name}.apply calls {what} on its item "
                      "(per-item device sync; use jnp or HostTransformer)")
                failures += 1
        # recompile hazards + donation safety share one home in the
        # analysis package (single source of truth; tests parse the
        # synthetic offender fixtures through the same functions)
        for lineno, code, msg in recompile_hazards(tree):
            print(f"{rel}:{lineno}: {code}: {msg}")
            failures += 1
        for lineno, code, msg in donation_hazards(tree):
            print(f"{rel}:{lineno}: {code}: {msg}")
            failures += 1
        # metric-name drift is tree-wide: a renamed counter anywhere
        # silently flatlines dashboards/benchdiff (catalogue:
        # observability/names.py)
        for lineno, code, msg in metric_name_drift(tree):
            print(f"{rel}:{lineno}: {code}: {msg}")
            failures += 1
        if rel.parts[:1] == ("keystone_tpu",) and \
                rel.parts[1] in SWALLOW_ALL_SCOPES:
            for lineno, what in swallow_all_handlers(tree):
                print(f"{rel}:{lineno}: swallow-all-handler: {what} in "
                      "ingest/workflow code silently loses failures; "
                      "narrow the exception type, or route it through "
                      "the resilience layer (RetryPolicy/Quarantine)")
                failures += 1
        if rel.parts[:1] == ("keystone_tpu",) and \
                rel.parts[1] in NAN_SILENCER_SCOPES:
            for lineno, what in silent_nan_silencers(tree):
                print(f"{rel}:{lineno}: silent-nan-silencer: {what} "
                      "with no recorded numerics event in scope — "
                      "suppressing non-finites without accounting hides "
                      "real breakdowns; pair it with "
                      "record_numerics_event(...) (observability/"
                      "numerics.py, README 'Numerics health')")
                failures += 1
        if rel.parts[:1] == ("keystone_tpu",) and \
                rel.parts[1] in CAST_BEFORE_TRANSFER_SCOPES:
            for lineno, what in float_casts_before_transfer(tree):
                print(f"{rel}:{lineno}: cast-before-transfer: {what} in "
                      "a function that device_puts — widening on the "
                      "host ships 4x the bytes the source held; ship "
                      "the source dtype and cast on device "
                      "(StreamingDataset wire_dtype/compute_dtype, "
                      "README 'Streaming ingest')")
                failures += 1
    return failures


# -- layer 2a: concurrency passes --------------------------------------------

def run_concurrency_rules() -> int:
    """The three concurrency-safety pass families over the package tree
    (single source of truth in ``analysis.concurrency``; the synthetic
    offender fixtures under tests/lint_fixtures pin each rule's firing
    shape)."""
    from keystone_tpu.analysis.concurrency import scan_package

    failures = 0
    for hit in scan_package(PKG):
        print(f"{hit['file']}:{hit['lineno']}: {hit['code']}: "
              f"{hit['message']}")
        failures += 1
    print(f"concurrency passes: {failures} failure(s)")
    return failures


# -- layer 2a': SPMD-safety passes -------------------------------------------

def run_spmd_rules() -> int:
    """The four SPMD-safety pass families over the package tree
    (single source of truth in ``analysis.spmd``: collective
    divergence, barrier-name/coordination-shape stability, collective
    axis bindings, world-checkpoint consistency; offender fixtures
    under tests/lint_fixtures pin each rule's firing shape, and the
    divergent dryrun worker reproduces the hang dynamically)."""
    from keystone_tpu.analysis.spmd import scan_package

    failures = 0
    for hit in scan_package(PKG):
        print(f"{hit['file']}:{hit['lineno']}: {hit['code']}: "
              f"{hit['message']}")
        failures += 1
    print(f"spmd passes: {failures} failure(s)")
    return failures


# -- layer 2a'': hot-path + publication passes -------------------------------

def run_hotpath_rules() -> int:
    """The interprocedural hot-path pass + the atomic-publication pass
    over the package tree (single source of truth in
    ``analysis.hotpath``; offender fixtures under tests/lint_fixtures
    pin each rule's firing shape). The scan is also WALL-BUDGETED: the
    whole-tree walk must finish under ``HOTPATH_SCAN_BUDGET_S`` so the
    gate can never quietly become the slow part of CI — an over-budget
    scan is itself a failure."""
    import time

    from keystone_tpu.analysis.hotpath import (
        HOTPATH_SCAN_BUDGET_S,
        scan_package,
    )

    failures = 0
    t0 = time.perf_counter()
    for hit in scan_package(PKG):
        print(f"{hit['file']}:{hit['lineno']}: {hit['code']}: "
              f"{hit['message']}")
        failures += 1
    elapsed = time.perf_counter() - t0
    if elapsed > HOTPATH_SCAN_BUDGET_S:
        print(f"hotpath-scan-over-budget: full-tree scan took "
              f"{elapsed:.2f}s > {HOTPATH_SCAN_BUDGET_S:.0f}s budget")
        failures += 1
    print(f"hotpath passes: {failures} failure(s) in {elapsed:.2f}s "
          f"(budget {HOTPATH_SCAN_BUDGET_S:.0f}s)")
    return failures


# -- layer 2b: donation shape gate (spec-level, eval_shape) ------------------

def _donating_modules():
    """Dotted names of every package module that builds a donating_jit
    wrapper, discovered from the same AST pass the hazard rules use —
    a new donation site anywhere in the tree is probed automatically,
    never silently skipped by a stale hardcoded list."""
    from keystone_tpu.analysis.diagnostics import donating_names

    mods = []
    for path in sorted(PKG.rglob("*.py")):
        try:
            if not donating_names(ast.parse(path.read_text())):
                continue
        except SyntaxError:
            continue  # reported by run_ast_rules
        rel = path.relative_to(REPO).with_suffix("")
        parts = list(rel.parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        mods.append(".".join(parts))
    return mods


def run_donation_shape_gate() -> int:
    """Every registered ``donating_jit`` site with a shape probe must
    donate only arguments that have a shape-compatible output —
    verified abstractly via ``jax.eval_shape`` (no device buffers).
    The static promotion of the `_gram_bcd` per-finalize runtime warn:
    an incompatible donation is never honored by XLA, it only buys a
    donated-buffer-not-usable warning per compile on TPU/GPU. Sites
    WITHOUT a probe are reported so a donation can never dodge the
    gate by simply not declaring one."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import importlib

    for mod in _donating_modules():
        importlib.import_module(mod)
    from keystone_tpu.utils.donation import (
        donation_shape_mismatches,
        registered_donations,
    )

    failures = 0
    probed = 0
    for site in registered_donations():
        if site.probe is None:
            print(f"{site.module}: donation-without-probe: "
                  f"{site.name} donates argnums "
                  f"{site.donate_argnums} but registers no shape "
                  "probe — pass probe= so the gate can verify the "
                  "donation statically")
            failures += 1
            continue
        probed += 1
        for what in donation_shape_mismatches(site):
            print(f"{site.module}: donation-shape-mismatch: {what} "
                  "(XLA cannot honor it; drop the argnum from "
                  "donate_argnums)")
            failures += 1
    print(f"donation shape gate: {probed} probed site(s), "
          f"{failures} failure(s)")
    return failures


# -- layer 1: pipeline checks ------------------------------------------------

def run_pipeline_checks() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from keystone_tpu.pipelines import CHECK_APPS

    failures = 0
    for name in sorted(CHECK_APPS):
        target = CHECK_APPS[name]()
        report = target.pipeline.check(target.input_spec, name=name)
        status = "ok" if report.ok else "FAIL"
        print(f"check {name}: {status} "
              f"({report.resolved_nodes()}/"
              f"{len(report.analysis.graph.nodes)} specs resolved)")
        if not report.ok:
            for d in report.diagnostics:
                print(f"  {d}")
            failures += 1
    return failures


# -- layer 3: ruff -----------------------------------------------------------

def run_ruff() -> int:
    ruff = shutil.which("ruff")
    if ruff is None:
        print("ruff: not installed; skipping style pass "
              "(AST rules + pipeline checks are the required gate)")
        return 0
    proc = subprocess.run(
        [ruff, "check", "--select", "E9,F63,F7,F82", str(PKG)],
        capture_output=True, text=True)
    if proc.stdout.strip():
        print(proc.stdout)
    return 0 if proc.returncode == 0 else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    failures = run_ast_rules()
    failures += run_concurrency_rules()
    failures += run_spmd_rules()
    failures += run_hotpath_rules()
    failures += run_donation_shape_gate()
    failures += run_ruff()
    if "--skip-apps" not in argv:
        failures += run_pipeline_checks()
    if failures:
        print(f"\nlint: {failures} failure(s)")
        return 1
    print("\nlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
