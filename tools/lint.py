"""The repo's own static gate — run before every PR.

Three layers, all hermetic (no data, no device buffers):

1. **Pipeline checks**: ``python -m keystone_tpu check`` semantics over
   every registered app (``keystone_tpu.pipelines.CHECK_APPS``) — the
   abstract interpreter plus graph lints must report zero diagnostics.
2. **Custom AST rules** over the ``keystone_tpu`` source tree:
   - ``host-coercion-in-apply``: a device-side ``Transformer.apply``
     body must not call ``np.asarray``/``np.array`` on its item
     argument (forces a per-item device sync; ADVICE r2/r3 lineage).
     HostTransformers are exempt.
   - ``unstable-jit-cache-tag``: ``self._cached_jit(tag, ...)`` must
     pass a string-literal tag — a computed tag makes the global jit
     cache key unstable across sessions, so warm-executable reuse
     silently stops working.
   - ``swallow-all-handler`` (ingest + workflow code only —
     ``loaders/``, ``parallel/``, ``workflow/``): no bare ``except:``
     and no silent ``except Exception: pass`` — exactly where "skip
     the error and keep going" becomes silent data loss. Tolerating a
     failure there goes through the resilience layer (RetryPolicy /
     Quarantine), which accounts for it.
   - ``cast-before-transfer`` (loader + staging code — ``loaders/``,
     ``parallel/``): no host-side float widening in a function that
     also ``device_put``\\ s — widening uint8 records to float before
     the transfer ships 4x the bytes; ship the source dtype and let
     the device cast (``StreamingDataset`` ``wire_dtype`` /
     ``compute_dtype``).
3. **ruff** (when installed): style/correctness pass over the package.
   Skipped with a notice when the container lacks ruff — layers 1–2
   are the required gate.

Usage: ``python tools/lint.py [--skip-apps]`` or
``bin/run-pipeline.sh --check``. Exit code 0 = clean.
"""
from __future__ import annotations

import ast
import os
import shutil
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "keystone_tpu"


# -- layer 2: AST rules ------------------------------------------------------

def _class_is_host_transformer(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else getattr(
            base, "id", "")
        if "Host" in str(name):
            return True
    return False


def _iter_transformer_applies(tree: ast.Module):
    """(class, apply FunctionDef) pairs for transformer-looking classes.

    Purely syntactic (no imports): any class whose base name mentions
    Transformer and that defines ``apply(self, item)``; classes whose
    base mentions Host are exempt."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        basenames = " ".join(
            str(b.attr if isinstance(b, ast.Attribute)
                else getattr(b, "id", "")) for b in node.bases)
        if "Transformer" not in basenames:
            continue
        if _class_is_host_transformer(node):
            continue
        for item in node.body:
            if isinstance(item, ast.FunctionDef) and item.name == "apply":
                yield node, item


def _host_coercions_in(fdef: ast.FunctionDef):
    # single source of truth for the coercion pattern lives in the
    # analysis package; this gate only adds the file-walk around it
    from keystone_tpu.analysis.diagnostics import host_coercions_in_funcdef

    yield from host_coercions_in_funcdef(fdef)


def _unstable_jit_tags(tree: ast.Module):
    """``self._cached_jit(<non-literal>, ...)`` call sites."""
    for call in ast.walk(tree):
        if not (isinstance(call, ast.Call) and call.args):
            continue
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "_cached_jit"):
            continue
        tag = call.args[0]
        if not (isinstance(tag, ast.Constant) and isinstance(tag.value, str)):
            yield call.lineno


def run_ast_rules() -> int:
    from keystone_tpu.analysis.diagnostics import (
        CAST_BEFORE_TRANSFER_SCOPES,
        SWALLOW_ALL_SCOPES,
        float_casts_before_transfer,
        swallow_all_handlers,
    )

    failures = 0
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO)
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as exc:
            print(f"{rel}: syntax error: {exc}")
            failures += 1
            continue
        for cls, fdef in _iter_transformer_applies(tree):
            for lineno, what in _host_coercions_in(fdef):
                print(f"{rel}:{lineno}: host-coercion-in-apply: "
                      f"{cls.name}.apply calls {what} on its item "
                      "(per-item device sync; use jnp or HostTransformer)")
                failures += 1
        for lineno in _unstable_jit_tags(tree):
            print(f"{rel}:{lineno}: unstable-jit-cache-tag: _cached_jit "
                  "tag must be a string literal (computed tags break "
                  "warm-executable reuse across sessions)")
            failures += 1
        if rel.parts[:1] == ("keystone_tpu",) and \
                rel.parts[1] in SWALLOW_ALL_SCOPES:
            for lineno, what in swallow_all_handlers(tree):
                print(f"{rel}:{lineno}: swallow-all-handler: {what} in "
                      "ingest/workflow code silently loses failures; "
                      "narrow the exception type, or route it through "
                      "the resilience layer (RetryPolicy/Quarantine)")
                failures += 1
        if rel.parts[:1] == ("keystone_tpu",) and \
                rel.parts[1] in CAST_BEFORE_TRANSFER_SCOPES:
            for lineno, what in float_casts_before_transfer(tree):
                print(f"{rel}:{lineno}: cast-before-transfer: {what} in "
                      "a function that device_puts — widening on the "
                      "host ships 4x the bytes the source held; ship "
                      "the source dtype and cast on device "
                      "(StreamingDataset wire_dtype/compute_dtype, "
                      "README 'Streaming ingest')")
                failures += 1
    return failures


# -- layer 1: pipeline checks ------------------------------------------------

def run_pipeline_checks() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from keystone_tpu.pipelines import CHECK_APPS

    failures = 0
    for name in sorted(CHECK_APPS):
        target = CHECK_APPS[name]()
        report = target.pipeline.check(target.input_spec, name=name)
        status = "ok" if report.ok else "FAIL"
        print(f"check {name}: {status} "
              f"({report.resolved_nodes()}/"
              f"{len(report.analysis.graph.nodes)} specs resolved)")
        if not report.ok:
            for d in report.diagnostics:
                print(f"  {d}")
            failures += 1
    return failures


# -- layer 3: ruff -----------------------------------------------------------

def run_ruff() -> int:
    ruff = shutil.which("ruff")
    if ruff is None:
        print("ruff: not installed; skipping style pass "
              "(AST rules + pipeline checks are the required gate)")
        return 0
    proc = subprocess.run(
        [ruff, "check", "--select", "E9,F63,F7,F82", str(PKG)],
        capture_output=True, text=True)
    if proc.stdout.strip():
        print(proc.stdout)
    return 0 if proc.returncode == 0 else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    failures = run_ast_rules()
    failures += run_ruff()
    if "--skip-apps" not in argv:
        failures += run_pipeline_checks()
    if failures:
        print(f"\nlint: {failures} failure(s)")
        return 1
    print("\nlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
