"""Calibrate the auto-solver cost-model weights on THIS chip
(VERDICT r3 next#2; reference weights were calibrated on 16x EC2
r3.4xlarge — ``LeastSquaresEstimator.scala:17,26-31`` — and encode a
2015 CPU-cluster cost surface that has nothing to do with a TPU).

The reference cost form is kept (it is what the solvers' ``cost()``
methods implement):

    cost = iters * ( max(cpu_w * flops, mem_w * elements_scanned)
                     + net_w * elements_over_network )

On TPU the three weights have direct hardware meanings:

    cpu_w  = seconds per MXU flop at solver precision (HIGHEST)
    mem_w  = seconds per f32 element streamed from HBM
    net_w  = seconds per f32 element over ICI (all-reduce leg)

This tool measures the first two directly (a compute-bound HIGHEST
Gram for the flop rate; a bandwidth-bound reduction for the stream
rate), derives the third from the chip generation's published ICI
bandwidth (not measurable on a single chip; the value only matters
multi-chip where log2(machines) > 0), then VALIDATES: it times the
three dense solver options end-to-end at several (n, d) shapes and
checks the fitted model ranks them like the measurements do.

Data is generated ON DEVICE (the axon tunnel uploads at single-digit
MB/s) and every timed region ends with a scalar pull (bench.py _fence
rationale).

Usage: python tools/calibrate_cost_model.py [--small]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import random

sys.path.insert(0, ".")  # repo root

from keystone_tpu.ops import linalg  # noqa: E402
from keystone_tpu.parallel.dataset import ArrayDataset  # noqa: E402

SMALL = "--small" in sys.argv


from tools._bench import device_arrays as _device_arrays  # noqa: E402,F401
from tools._bench import fence, timeit  # noqa: E402


# -- primitive rates -------------------------------------------------------

def measure_flop_rate():
    """Sustained solver-precision (HIGHEST) MXU rate on a Gram at the
    solver's own shape class. FLOOR-CANCELLED: the axon tunnel adds
    ~20 ms of dispatch latency per timed call, which at these shapes is
    comparable to the compute itself — so the rate is taken from the
    DIFFERENCE between two row counts, where the per-call latency
    cancels (r5: the single-shape estimate read 18.5 TFLOPS for a
    ~40 TFLOPS gram)."""
    n_small, n_large, d = ((4_096, 16_384, 1_024) if SMALL
                           else (16_384, 49_152, 4_096))
    g = jax.jit(linalg.gram)
    dts = {}
    for n in (n_small, n_large):
        A = random.normal(random.PRNGKey(0), (n, d), jnp.float32)
        fence(A)
        dts[n] = timeit(g, A)
    return 2.0 * (n_large - n_small) * d * d / (dts[n_large] - dts[n_small])


def measure_stream_rate():
    """Sustained HBM read rate (f32 elements/s) on a bandwidth-bound
    reduction — floor-cancelled like the flop rate (the single-size
    estimate read 12.7 GB/s for a ~2 TB/s stream: pure dispatch
    floor)."""
    e_small = (8 << 20) if SMALL else (32 << 20)
    e_large = (32 << 20) if SMALL else (160 << 20)

    @jax.jit
    def scan_sum(x):
        return jnp.sum(x)

    dts = {}
    for elems in (e_small, e_large):
        A = random.normal(random.PRNGKey(1), (elems,), jnp.float32)
        fence(A)
        dts[elems] = timeit(scan_sum, A, iters=4)
    return (e_large - e_small) / (dts[e_large] - dts[e_small])


def measure_dispatch_latency():
    """Seconds per serial device round: the time of a trivial jitted op
    (all latency, no compute). This is the ``lat_w`` the TPU cost
    extension charges per dispatch round — the term that lets the model
    rank latency-dominated small-d solves (the scan-based BCD's 3
    rounds beat the exact solver's ~10 at every d tested)."""
    x = random.normal(random.PRNGKey(2), (128,), jnp.float32)
    fence(x)

    @jax.jit
    def bump(v):
        return v + 1.0

    return timeit(bump, x, iters=8)


#: Published per-chip ICI bandwidth by generation (bytes/s, one
#: direction). Used for net_w only — a single-chip calibration cannot
#: measure ICI; on one chip every log2(machines) term is zero anyway.
_ICI_BYTES_PER_S = {
    "v4": 3 * 2 * 37.5e9,   # 3 links x 75 GB/s bidirectional
    "v5 lite": 1600e9 / 8 / 2,  # 1600 Gbps total, half per direction
    "v5": 4800e9 / 8 / 2,
    "v6": 4 * 2 * 56.0e9,
}


def derive_net_weight():
    kind = jax.devices()[0].device_kind.lower()
    for tag, rate in _ICI_BYTES_PER_S.items():
        if tag in kind:
            return 4.0 / rate  # seconds per f32 element
    return 4.0 / 100e9


# -- end-to-end solver timings --------------------------------------------

def solver_options(lam=0.1):
    from keystone_tpu.nodes.learning.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.nodes.learning.linear import (
        BlockLeastSquaresEstimator,
        LinearMapEstimator,
    )

    return [
        ("dense_lbfgs", DenseLBFGSwithL2(lam=lam, num_iterations=20)),
        ("block_ls", BlockLeastSquaresEstimator(1000, 3, lam=lam)),
        ("exact", LinearMapEstimator(lam=lam)),
    ]


def time_solvers(n, d, k=10):
    X = random.normal(random.PRNGKey(2), (n, d), jnp.float32)
    Y = random.normal(random.PRNGKey(3), (n, k), jnp.float32)
    fence((X, Y))
    ds = ArrayDataset(X, n)
    labels = ArrayDataset(Y, n)
    out = {}
    for name, solver in solver_options():
        dt = timeit(lambda: solver._fit(ds, labels), iters=2)
        out[name] = dt
        print(f"  n={n} d={d} {name:12s} {dt * 1e3:9.1f} ms", flush=True)
    return out


def predicted_ranking(n, d, k, cpu_w, mem_w, net_w, lat_w):
    costs = {
        name: solver.cost(n, d, k, 1.0, 1, cpu_w, mem_w, net_w,
                          lat_w=lat_w)
        for name, solver in solver_options()
    }
    return sorted(costs, key=costs.get), costs


def main():
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    flop_rate = measure_flop_rate()
    stream_rate = measure_stream_rate()
    lat_w = measure_dispatch_latency()
    cpu_w = 1.0 / flop_rate
    mem_w = 1.0 / stream_rate
    net_w = derive_net_weight()
    print(f"MXU rate (HIGHEST gram, floor-cancelled): "
          f"{flop_rate / 1e12:.2f} TFLOPS -> cpu_w = {cpu_w:.3e} s/flop",
          flush=True)
    print(f"HBM stream rate (floor-cancelled): "
          f"{stream_rate * 4 / 1e9:.1f} GB/s -> mem_w = {mem_w:.3e} s/elem",
          flush=True)
    print(f"dispatch latency: lat_w = {lat_w:.3e} s/round", flush=True)
    print(f"ICI (spec-derived): net_w = {net_w:.3e} s/elem", flush=True)

    shapes = [(65_536, 256), (65_536, 1_024), (32_768, 4_096)]
    if SMALL:
        shapes = [(8_192, 256), (8_192, 1_024)]
    agree = 0
    for n, d in shapes:
        measured = time_solvers(n, d)
        m_rank = sorted(measured, key=measured.get)
        p_rank, p_costs = predicted_ranking(n, d, 10, cpu_w, mem_w,
                                            net_w, lat_w)
        ok = m_rank[0] == p_rank[0]
        agree += ok
        print(f"  -> measured fastest: {m_rank[0]}, model picks: "
              f"{p_rank[0]}  {'OK' if ok else 'MISMATCH'}", flush=True)
        print(f"     predicted costs: "
              + ", ".join(f"{k2}={v:.3f}s" for k2, v in p_costs.items()),
              flush=True)
    print()
    print("ship these as the TPU defaults in "
          "keystone_tpu/nodes/learning/least_squares.py:", flush=True)
    print(f"DEFAULT_CPU_WEIGHT = {cpu_w:.3e}", flush=True)
    print(f"DEFAULT_MEM_WEIGHT = {mem_w:.3e}", flush=True)
    print(f"DEFAULT_NETWORK_WEIGHT = {net_w:.3e}", flush=True)
    print(f"DEFAULT_LAT_WEIGHT = {lat_w:.3e}", flush=True)
    print(f"model-vs-measurement agreement: {agree}/{len(shapes)} shapes",
          flush=True)


if __name__ == "__main__":
    main()
