"""Calibrate the auto-solver cost-model weights on THIS chip
(VERDICT r3 next#2; reference weights were calibrated on 16x EC2
r3.4xlarge — ``LeastSquaresEstimator.scala:17,26-31`` — and encode a
2015 CPU-cluster cost surface that has nothing to do with a TPU).

The reference cost form is kept (it is what the solvers' ``cost()``
methods implement):

    cost = iters * ( max(cpu_w * flops, mem_w * elements_scanned)
                     + net_w * elements_over_network )

On TPU the three weights have direct hardware meanings:

    cpu_w  = seconds per MXU flop at solver precision (HIGHEST)
    mem_w  = seconds per f32 element streamed from HBM
    net_w  = seconds per f32 element over ICI (all-reduce leg)

This tool measures the first two directly (a compute-bound HIGHEST
Gram for the flop rate; a bandwidth-bound reduction for the stream
rate), derives the third from the chip generation's published ICI
bandwidth (not measurable on a single chip; the value only matters
multi-chip where log2(machines) > 0), then VALIDATES: it times the
three dense solver options end-to-end at several (n, d) shapes and
checks the fitted model ranks them like the measurements do.

Data is generated ON DEVICE (the axon tunnel uploads at single-digit
MB/s) and every timed region ends with a scalar pull (bench.py _fence
rationale).

Floor-cancelled differences are GUARDED (ADVICE r5 low#3): tunnel
jitter can make dt_large - dt_small near-zero or negative, which would
silently print nonsensical (even negative) weights; each pair is
re-measured once and the run aborts with a clear message if the
difference stays non-positive, and every derived rate is bounds-checked
before the ship block is printed.

Besides the copy-pasteable ship block, the tool writes a calibration
ARTIFACT (JSON with the four weights plus timestamp / hostname /
device): ``keystone_tpu.nodes.learning.least_squares`` loads it in
preference to the shipped defaults, and pipeline traces report its
provenance with every solver decision.

Usage: python tools/calibrate_cost_model.py [--small] [--out PATH]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import random

sys.path.insert(0, ".")  # repo root

from keystone_tpu.ops import linalg  # noqa: E402
from keystone_tpu.parallel.dataset import ArrayDataset  # noqa: E402

SMALL = "--small" in sys.argv


from tools._bench import device_arrays as _device_arrays  # noqa: E402,F401
from tools._bench import fence, timeit  # noqa: E402


# -- primitive rates -------------------------------------------------------

def _floor_cancelled(label, measure):
    """rate = numer / (dt_large - dt_small) with a jitter guard:
    ``measure()`` returns (dt_small, dt_large, numer); a non-positive
    difference (tunnel jitter swamping the size delta) is re-measured
    once, then aborts — a negative weight must never reach the ship
    block or the artifact."""
    for attempt in (0, 1):
        dt_small, dt_large, numer = measure()
        if dt_large > dt_small:
            return numer / (dt_large - dt_small)
        print(f"WARNING: {label}: dt_large ({dt_large * 1e3:.1f} ms) <= "
              f"dt_small ({dt_small * 1e3:.1f} ms) — tunnel jitter "
              "swamped the floor-cancelled difference; "
              + ("retrying once" if attempt == 0 else "aborting"),
              flush=True)
    raise SystemExit(
        f"calibration aborted: {label} unmeasurable on this host (the "
        "large-shape timing is not slower than the small-shape timing "
        "after a retry). Re-run when the tunnel/host is quieter; do NOT "
        "hand-edit weights from a run that printed this message.")


def _sanity_bound(name, value, lo, hi, unit):
    """Abort before printing/shipping a physically implausible rate."""
    if not (lo <= value <= hi) or not np.isfinite(value):
        raise SystemExit(
            f"calibration aborted: {name} = {value:.3e} {unit} is outside "
            f"the plausible range [{lo:.0e}, {hi:.0e}] — the measurement "
            "is untrustworthy (tunnel jitter, thermal throttling, or a "
            "mis-detected device). Re-run; do not ship these weights.")
    return value


def measure_flop_rate():
    """Sustained solver-precision (HIGHEST) MXU rate on a Gram at the
    solver's own shape class. FLOOR-CANCELLED: the axon tunnel adds
    ~20 ms of dispatch latency per timed call, which at these shapes is
    comparable to the compute itself — so the rate is taken from the
    DIFFERENCE between two row counts, where the per-call latency
    cancels (r5: the single-shape estimate read 18.5 TFLOPS for a
    ~40 TFLOPS gram)."""
    n_small, n_large, d = ((4_096, 16_384, 1_024) if SMALL
                           else (16_384, 49_152, 4_096))
    g = jax.jit(linalg.gram)

    def measure():
        dts = {}
        for n in (n_small, n_large):
            A = random.normal(random.PRNGKey(0), (n, d), jnp.float32)
            fence(A)
            dts[n] = timeit(g, A)
        return (dts[n_small], dts[n_large],
                2.0 * (n_large - n_small) * d * d)

    # plausible sustained MXU rates: ~GFLOPS (CPU smoke) to <2 PFLOPS
    return _sanity_bound("MXU flop rate",
                         _floor_cancelled("MXU flop rate", measure),
                         1e8, 2e15, "FLOPS")


def measure_stream_rate():
    """Sustained HBM read rate (f32 elements/s) on a bandwidth-bound
    reduction — floor-cancelled like the flop rate (the single-size
    estimate read 12.7 GB/s for a ~2 TB/s stream: pure dispatch
    floor)."""
    e_small = (8 << 20) if SMALL else (32 << 20)
    e_large = (32 << 20) if SMALL else (160 << 20)

    @jax.jit
    def scan_sum(x):
        return jnp.sum(x)

    def measure():
        dts = {}
        for elems in (e_small, e_large):
            A = random.normal(random.PRNGKey(1), (elems,), jnp.float32)
            fence(A)
            dts[elems] = timeit(scan_sum, A, iters=4)
        return dts[e_small], dts[e_large], float(e_large - e_small)

    # ~4 MB/s (broken) .. 4 TB/s-class HBM in f32 elements/s
    return _sanity_bound("HBM stream rate",
                         _floor_cancelled("HBM stream rate", measure),
                         1e6, 1e13, "elements/s")


def measure_dispatch_latency():
    """Seconds per serial device round: the time of a trivial jitted op
    (all latency, no compute). This is the ``lat_w`` the TPU cost
    extension charges per dispatch round — the term that lets the model
    rank latency-dominated small-d solves (the scan-based BCD's 3
    rounds beat the exact solver's ~10 at every d tested)."""
    x = random.normal(random.PRNGKey(2), (128,), jnp.float32)
    fence(x)

    @jax.jit
    def bump(v):
        return v + 1.0

    return timeit(bump, x, iters=8)


#: Published per-chip ICI bandwidth by generation (bytes/s, one
#: direction). Used for net_w only — a single-chip calibration cannot
#: measure ICI; on one chip every log2(machines) term is zero anyway.
_ICI_BYTES_PER_S = {
    "v4": 3 * 2 * 37.5e9,   # 3 links x 75 GB/s bidirectional
    "v5 lite": 1600e9 / 8 / 2,  # 1600 Gbps total, half per direction
    "v5": 4800e9 / 8 / 2,
    "v6": 4 * 2 * 56.0e9,
}


def derive_net_weight():
    kind = jax.devices()[0].device_kind.lower()
    for tag, rate in _ICI_BYTES_PER_S.items():
        if tag in kind:
            return 4.0 / rate  # seconds per f32 element
    return 4.0 / 100e9


# -- end-to-end solver timings --------------------------------------------

def solver_options(lam=0.1):
    from keystone_tpu.nodes.learning.lbfgs import DenseLBFGSwithL2
    from keystone_tpu.nodes.learning.linear import (
        BlockLeastSquaresEstimator,
        LinearMapEstimator,
    )

    return [
        ("dense_lbfgs", DenseLBFGSwithL2(lam=lam, num_iterations=20)),
        ("block_ls", BlockLeastSquaresEstimator(1000, 3, lam=lam)),
        ("exact", LinearMapEstimator(lam=lam)),
    ]


def time_solvers(n, d, k=10):
    X = random.normal(random.PRNGKey(2), (n, d), jnp.float32)
    Y = random.normal(random.PRNGKey(3), (n, k), jnp.float32)
    fence((X, Y))
    ds = ArrayDataset(X, n)
    labels = ArrayDataset(Y, n)
    out = {}
    for name, solver in solver_options():
        dt = timeit(lambda: solver._fit(ds, labels), iters=2)
        out[name] = dt
        print(f"  n={n} d={d} {name:12s} {dt * 1e3:9.1f} ms", flush=True)
    return out


def predicted_ranking(n, d, k, cpu_w, mem_w, net_w, lat_w):
    costs = {
        name: solver.cost(n, d, k, 1.0, 1, cpu_w, mem_w, net_w,
                          lat_w=lat_w)
        for name, solver in solver_options()
    }
    return sorted(costs, key=costs.get), costs


def write_artifact(path, weights, agreement, shapes_checked):
    """Persist the calibration as the JSON artifact that
    ``least_squares.load_calibration`` picks up, stamped with enough
    provenance (timestamp, hostname, device) for the observability layer
    to report where a solver decision's weights came from."""
    import datetime
    import json
    import os
    import socket

    blob = dict(weights)
    blob.update({
        "device": jax.devices()[0].device_kind,
        "hostname": socket.gethostname(),
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(),
        "agreement": f"{agreement}/{shapes_checked}",
        "small": SMALL,
        "tool": "tools/calibrate_cost_model.py",
    })
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(blob, f, indent=2)
    os.replace(tmp, path)
    return path


def main():
    from keystone_tpu.nodes.learning.least_squares import (
        DEFAULT_CALIBRATION_PATH,
    )

    out_path = DEFAULT_CALIBRATION_PATH
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--out requires a path")
        out_path = sys.argv[i + 1]

    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    flop_rate = measure_flop_rate()
    stream_rate = measure_stream_rate()
    lat_w = _sanity_bound("dispatch latency", measure_dispatch_latency(),
                          1e-7, 1.0, "s/round")
    cpu_w = 1.0 / flop_rate
    mem_w = 1.0 / stream_rate
    net_w = derive_net_weight()
    print(f"MXU rate (HIGHEST gram, floor-cancelled): "
          f"{flop_rate / 1e12:.2f} TFLOPS -> cpu_w = {cpu_w:.3e} s/flop",
          flush=True)
    print(f"HBM stream rate (floor-cancelled): "
          f"{stream_rate * 4 / 1e9:.1f} GB/s -> mem_w = {mem_w:.3e} s/elem",
          flush=True)
    print(f"dispatch latency: lat_w = {lat_w:.3e} s/round", flush=True)
    print(f"ICI (spec-derived): net_w = {net_w:.3e} s/elem", flush=True)

    shapes = [(65_536, 256), (65_536, 1_024), (32_768, 4_096)]
    if SMALL:
        shapes = [(8_192, 256), (8_192, 1_024)]
    agree = 0
    for n, d in shapes:
        measured = time_solvers(n, d)
        m_rank = sorted(measured, key=measured.get)
        p_rank, p_costs = predicted_ranking(n, d, 10, cpu_w, mem_w,
                                            net_w, lat_w)
        ok = m_rank[0] == p_rank[0]
        agree += ok
        print(f"  -> measured fastest: {m_rank[0]}, model picks: "
              f"{p_rank[0]}  {'OK' if ok else 'MISMATCH'}", flush=True)
        print(f"     predicted costs: "
              + ", ".join(f"{k2}={v:.3f}s" for k2, v in p_costs.items()),
              flush=True)
    print()
    print("ship these as the TPU defaults in "
          "keystone_tpu/nodes/learning/least_squares.py:", flush=True)
    print(f"DEFAULT_CPU_WEIGHT = {cpu_w:.3e}", flush=True)
    print(f"DEFAULT_MEM_WEIGHT = {mem_w:.3e}", flush=True)
    print(f"DEFAULT_NETWORK_WEIGHT = {net_w:.3e}", flush=True)
    print(f"DEFAULT_LAT_WEIGHT = {lat_w:.3e}", flush=True)
    print(f"model-vs-measurement agreement: {agree}/{len(shapes)} shapes",
          flush=True)
    if 2 * agree <= len(shapes):
        # the agreement check used to gate a human copy-pasting the ship
        # block; now that the artifact is auto-loaded it must gate the
        # write — weights that mis-rank the measured solvers on most
        # validation shapes would silently mis-rank every future solve
        print(f"NOT writing calibration artifact: model-vs-measurement "
              f"agreement {agree}/{len(shapes)} is too low to trust "
              "(rates may be individually plausible but jitter-skewed). "
              "Re-run on a quieter host; shipped defaults stay active.",
              flush=True)
        return
    weights = {"cpu_weight": cpu_w, "mem_weight": mem_w,
               "network_weight": net_w, "lat_weight": lat_w}
    path = write_artifact(out_path, weights, agree, len(shapes))
    print(f"calibration artifact written to {path} — "
          "LeastSquaresEstimator loads it automatically (override with "
          "$KEYSTONE_COST_CALIBRATION); pipeline traces report its "
          "provenance with every solver decision", flush=True)


if __name__ == "__main__":
    main()
