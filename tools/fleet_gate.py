#!/usr/bin/env python3
"""CI drill for the serving fleet (``bin/ci.sh``): kill one replica.

End-to-end, out of process — the production topology at miniature
scale:

1. spawn THREE replica servers as SUBPROCESSES
   (``python -m keystone_tpu.serving.replica``), each a full
   ``ServingPlane`` behind the real-HTTP predict + admin surfaces;
2. register three models with the in-process ``FleetController``
   (canonical-bytes contract: one pickled working copy per model,
   sha256-stamped), solve placement under finite per-replica budgets,
   and admit every copy over ``/admin/admit`` — each replica's
   reported sha must equal the canonical sha (bit-identical
   admission, verified by the controller);
3. front the fleet with the real-HTTP ``FleetRouter`` and drive a
   seeded loadgen trace through it (``HttpServingClient`` — the
   request path is loadgen -> router socket -> replica socket ->
   plane);
4. mid-replay, SIGKILL the replica hosting the most models — no
   drain, no goodbye, a real process death;
5. the reactor tick (``FleetAutoscaler``) must classify the death,
   drop the corpse from the routing membership, re-solve placement
   over the survivors, and re-admit the lost models from canonical
   bytes — sha-verified again on the new hosts;
6. after the window: every model answers 200 through the router, the
   re-admitted copies' shas match the canonical bytes, the p99 of
   served requests stays under the drill floor, and EVERY outcome in
   the replay is classified — zero unclassified damage, zero raw
   errors (the router shields a backend death by spilling; a refusal
   reaches the client as a counted 429/503, never a stack trace).

Exit 0 clean; exit 1 with a named reason otherwise.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

READY_TIMEOUT_S = 240.0
N_REPLICAS = 3
#: name -> (d, k): three models, distinct shapes, one hot
DIMS = {"alpha": (24, 3), "beta": (32, 4), "gamma": (16, 2)}
P99_FLOOR_MS = 500.0


def _fail(procs, reason: str) -> int:
    print(f"fleet gate: FAIL: {reason}", file=sys.stderr)
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
    return 1


def _spawn_replica() -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "keystone_tpu.serving.replica",
         "--port", "0", "--max-batch", "16", "--queue-depth", "128"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)


def _read_bind_line(proc: subprocess.Popen, deadline: float):
    """The replica prints ``replica on HOST:PORT`` before anything
    else; select-gate the read so a wedged boot fails the gate, not
    the CI wall clock."""
    import select

    while time.monotonic() < deadline:
        readable, _, _ = select.select(
            [proc.stdout], [], [],
            max(0.0, min(1.0, deadline - time.monotonic())))
        if not readable:
            if proc.poll() is not None:
                return None
            continue
        line = proc.stdout.readline()
        if not line:
            return None
        print(f"  replica: {line.rstrip()}")
        m = re.match(r"replica on ([\d.]+):(\d+)", line)
        if m:
            return m.group(1), int(m.group(2))
    return None


def main() -> int:
    import threading

    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability.metrics import MetricsRegistry
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.serving.fleet import FleetAutoscaler, FleetController
    from keystone_tpu.serving.loadgen import (
        HttpServingClient,
        LoadSpec,
        generate_trace,
        replay,
    )
    from keystone_tpu.serving.router import (
        FleetRouter,
        HttpReplicaClient,
        serve_router,
    )

    reg = MetricsRegistry.get_or_create()
    deaths0 = reg.counter("fleet.replica_deaths_total").value

    # 1. three real replica processes (spawned together: the jax boot
    # cost parallelizes; binds are read one by one afterwards)
    deadline = time.monotonic() + READY_TIMEOUT_S
    procs = [_spawn_replica() for _ in range(N_REPLICAS)]
    clients = []
    for i, proc in enumerate(procs):
        bound = _read_bind_line(proc, deadline)
        if bound is None:
            return _fail(procs, f"replica {i} never printed its bind "
                                "line (boot wedge or crash)")
        host, port = bound
        clients.append(HttpReplicaClient(f"r{i}", host, port,
                                         stats_ttl_s=0.05))
    print(f"fleet gate: {N_REPLICAS} replicas up on ports "
          f"{[c.port for c in clients]}")

    router_server = None
    try:
        # 2. canonical registration + solved placement + sha-verified
        # admission over the admin surface
        router = FleetRouter(clients, spill_queue_depth=8)
        controller = FleetController(router)
        registered = {}
        for seed, (name, (d, k)) in enumerate(sorted(DIMS.items())):
            r = np.random.RandomState(seed)
            X = r.rand(96, d).astype(np.float32)
            Y = r.rand(96, k).astype(np.float32)
            fitted = LinearMapEstimator(lam=1e-3).with_data(
                ArrayDataset.from_numpy(X),
                ArrayDataset.from_numpy(Y)).fit()
            qps = 300.0 if name == "alpha" else 0.0
            registered[name] = controller.register(
                name, fitted,
                jax.ShapeDtypeStruct((d,), np.float32),
                qps=qps, warmup_s=1.0 if qps else 0.0)
        biggest = max(m.charge_nbytes for m in registered.values())
        for client in clients:
            controller.set_budget(client.replica_id, 3.3 * biggest)
        steps = controller.rebalance()
        if not steps:
            return _fail(procs, "initial rebalance applied no steps")
        canonical = {name: m.sha256 for name, m in registered.items()}
        for client in clients:
            for name, sha in client.model_shas().items():
                if sha != canonical[name]:
                    return _fail(
                        procs, f"replica {client.replica_id} hosts "
                               f"{name!r} with sha {sha[:12]} != "
                               f"canonical {canonical[name][:12]}")
        table = router.state()["models"]
        missing = [m for m in DIMS if not table.get(m)]
        if missing:
            return _fail(procs, f"models {missing} unroutable after "
                                "initial placement")
        print(f"fleet gate: placement applied ({len(steps)} steps), "
              f"table {{m: [r...]}} = "
              f"{ {m: table[m] for m in sorted(table)} }")

        # 3. the router front door + the seeded HTTP load window
        router_server = serve_router(router)
        rport = router_server.server_port
        spec = LoadSpec(seed=31, duration_s=3.0, rate_rps=90.0,
                        arrival="poisson",
                        models=tuple(sorted(DIMS)), zipf_s=1.2,
                        sizes=(1, 2, 4))
        trace = generate_trace(spec)
        data = {name: np.random.RandomState(100 + i).rand(
                    8, DIMS[name][0]).astype(np.float32)
                for i, name in enumerate(sorted(DIMS))}

        autoscaler = FleetAutoscaler(controller, sustain_ticks=10 ** 6)
        killed = {}

        def killer():
            time.sleep(1.5)
            count = {}
            for reps in controller.placement.assignments.values():
                for rid in reps:
                    count[rid] = count.get(rid, 0) + 1
            victim = max(sorted(count), key=lambda rid: count[rid])
            idx = next(i for i, c in enumerate(clients)
                       if c.replica_id == victim)
            procs[idx].kill()  # SIGKILL: no drain, no goodbye
            procs[idx].wait()
            killed["victim"] = victim
            # 4. the reactor tick IS the recovery path under test
            try:
                killed["action"] = autoscaler.tick()
            except BaseException as exc:  # noqa: BLE001 - gate verdict
                killed["error"] = f"{type(exc).__name__}: {exc}"

        thread = threading.Thread(target=killer, daemon=True,
                                  name="fleet-gate-killer")
        thread.start()
        report = replay(trace, HttpServingClient("127.0.0.1", rport),
                        lambda m, n: data[m][:n], senders=6,
                        submit_timeout_s=5.0, result_timeout_s=30.0)
        thread.join(timeout=60.0)

        # 5. recovery happened, and it was the reactor that did it
        if "error" in killed:
            return _fail(procs, f"recovery raised {killed['error']}")
        if killed.get("action") != "death":
            return _fail(procs, "reactor tick did not classify the "
                                f"kill as a death "
                                f"(got {killed.get('action')!r})")
        deaths = reg.counter("fleet.replica_deaths_total").value - deaths0
        if deaths != 1:
            return _fail(procs, f"expected exactly 1 counted death, "
                                f"got {deaths:g}")
        victim = killed["victim"]
        if victim in router.replica_ids():
            return _fail(procs, f"dead replica {victim!r} still in "
                                "the routing membership")
        table = router.state()["models"]
        missing = [m for m in DIMS if not table.get(m)]
        if missing:
            return _fail(procs, f"models {missing} unroutable after "
                                "the death — redistribution incomplete")
        # the re-admitted copies are bit-identical to canonical bytes
        for client in clients:
            if client.replica_id == victim:
                continue
            for name, sha in client.model_shas().items():
                if sha != canonical[name]:
                    return _fail(
                        procs, f"post-death copy of {name!r} on "
                               f"{client.replica_id} has sha "
                               f"{sha[:12]} != canonical "
                               f"{canonical[name][:12]} — migration "
                               "broke bit-identity")
        # every model still answers THROUGH the router
        import http.client

        for name in sorted(DIMS):
            payload = json.dumps(
                {"instances": [[0.5] * DIMS[name][0]]}).encode()
            conn = http.client.HTTPConnection("127.0.0.1", rport,
                                              timeout=10)
            conn.request("POST", f"/predict/{name}", body=payload)
            rsp = conn.getresponse()
            body = rsp.read()
            conn.close()
            if rsp.status != 200:
                return _fail(procs, f"post-death probe for {name!r} "
                                    f"answered {rsp.status}: "
                                    f"{body[:120].decode(errors='replace')}")

        # 6. the window's verdict: classified or served, nothing else
        oc = report.outcomes
        if oc["unclassified"]:
            return _fail(procs, f"{oc['unclassified']} UNCLASSIFIED "
                                f"outcome(s): {report.errors[:4]}")
        if oc["error"]:
            return _fail(procs, f"{oc['error']} raw error(s) leaked "
                                "through the router during the death "
                                f"window: {report.errors[:4]}")
        if oc["ok"] == 0:
            return _fail(procs, "no request succeeded — the fleet "
                                "never served")
        p99 = report.p99_ms()
        if p99 > P99_FLOOR_MS:
            return _fail(procs, f"p99 {p99:.1f}ms over the "
                                f"{P99_FLOOR_MS:.0f}ms drill floor")
        refused = oc["rejected"] + oc["warming"] + oc["not_admitted"]
        print(f"fleet gate: PASS (killed {victim}, "
              f"{oc['ok']} served, {refused} classified refusal(s), "
              f"p99 {p99:.1f}ms, re-placement sha-verified)")
        return 0
    finally:
        if router_server is not None:
            router_server.shutdown()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


if __name__ == "__main__":
    sys.exit(main())
