"""Bench-regression gate CLI (thin wrapper).

The implementation lives in ``keystone_tpu.observability.benchdiff``
(so ``python -m keystone_tpu benchdiff`` and this script are the same
tool); this wrapper exists for the tools/ convention::

    python tools/bench_compare.py BENCH_r03.json BENCH_r05.json

Exit codes: 0 = every shared metric improved or within its noise band,
1 = usage/load error or cross-host refusal (pass ``--force``),
2 = at least one metric regressed beyond its band. See the module
docstring of ``observability/benchdiff.py`` for the band model.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from keystone_tpu.observability.benchdiff import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
