"""Generate the descriptor-level SIFT golden (VERDICT r2 next#4).

An INDEPENDENT NumPy/SciPy dense-SIFT reference — same algorithm as
``keystone_tpu/ops/sift.py`` (the vl_phow recipe of the reference's
``cpp/VLFeat.cxx``: per-scale Gaussian smooth at sigma=bin/6, gradient
orientation soft-assignment to 8 bins, 4x4 spatial bins with bilinear
triangle weighting, L2->clamp 0.2->renorm, contrast threshold 0.005,
quantize min(512 v, 255)) — but computed through a DIFFERENT code path:

* scipy.ndimage.convolve1d for the Gaussian/triangle smoothing (vs XLA
  ``conv_general_dilated``),
* generic bilinear ``scipy.ndimage.map_coordinates`` sampling at every
  bin center (vs the production kernel's shared-fractional-offset
  pre-interpolation + integer strided slices).

Agreement therefore cross-checks the production kernel's TPU-oriented
restructurings against a direct implementation of the same math, at
descriptor level on the real ``gantrycrane.png`` fixture — the closest
available analogue of the reference's VLFeatSuite golden (the actual
VLFeat binary is unbuildable in this zero-egress image; this generator
is checked in so the artifact is reproducible).

Writes tests/resources/sift_golden_gantrycrane.npz.
"""
import os

import numpy as np
from PIL import Image
from scipy.ndimage import convolve1d, map_coordinates

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NBP, NBO = 4, 8
MAGNIF = 6.0
CONTRAST = 0.005

# modest config keeps the artifact small while covering the multi-scale,
# contrast-threshold and quantization paths
STEP, BIN, NUM_SCALES, SCALE_STEP = 8, 6, 3, 1


def gaussian_taps(sigma):
    if sigma < 1e-8:
        return np.ones(1)
    radius = int(np.ceil(4.0 * sigma))
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


def triangle_taps(bin_size):
    t = np.arange(-(bin_size - 1), bin_size, dtype=np.float64)
    return np.maximum(0.0, 1.0 - np.abs(t) / bin_size)


def keypoint_centers(dim, lo, hi, step, extent):
    half = extent / 2.0
    first, last = lo + half, hi - half
    if last < first:
        return np.zeros(0)
    count = int((last - first) // step) + 1
    return first + step * np.arange(count)


def dsift_one_scale(img, step, bin_size, lo):
    h, w = img.shape
    taps = gaussian_taps(bin_size / MAGNIF)
    smoothed = convolve1d(img, taps, axis=0, mode="nearest")
    smoothed = convolve1d(smoothed, taps, axis=1, mode="nearest")

    gy, gx = np.gradient(smoothed)
    mag = np.sqrt(gx * gx + gy * gy)
    ang = np.arctan2(gy, gx) % (2 * np.pi)
    a = ang * (NBO / (2 * np.pi))
    lo_bin = np.floor(a).astype(int) % NBO
    frac = a - np.floor(a)
    omaps = np.zeros((NBO,) + img.shape)
    for o in range(NBO):
        omaps[o] = mag * (np.where(lo_bin == o, 1 - frac, 0)
                          + np.where((lo_bin + 1) % NBO == o, frac, 0))

    tri = triangle_taps(bin_size)
    sm = np.stack([
        convolve1d(convolve1d(m, tri, axis=0, mode="nearest"),
                   tri, axis=1, mode="nearest")
        for m in omaps
    ])

    extent = bin_size * NBP
    ys = keypoint_centers(h, lo, h - 1, step, extent)
    xs = keypoint_centers(w, lo, w - 1, step, extent)
    offs = (np.arange(NBP) - (NBP - 1) / 2.0) * bin_size
    if len(ys) == 0 or len(xs) == 0:
        return np.zeros((0, NBP * NBP * NBO), np.float32)

    yy, xx = np.meshgrid(ys, xs, indexing="ij")
    descs = []
    for by in offs:
        for bx in offs:
            coords = np.stack([(yy + by).ravel(), (xx + bx).ravel()])
            vals = np.stack([
                map_coordinates(sm[o], coords, order=1, mode="nearest")
                for o in range(NBO)
            ])  # (8, N) — generic bilinear sampling at bin centers
            descs.append(vals.T)
    return np.concatenate(descs, axis=1)  # (N, 128)


def normalize_quantize(desc):
    norm = np.linalg.norm(desc, axis=1, keepdims=True)
    d = np.minimum(desc / np.maximum(norm, 1e-12), 0.2)
    d = d / np.maximum(np.linalg.norm(d, axis=1, keepdims=True), 1e-12)
    d = np.where(norm / (NBP * NBP) < CONTRAST, 0.0, d)
    return np.minimum(512.0 * d, 255.0)


def main():
    img_path = os.path.join(ROOT, "tests/resources/images/gantrycrane.png")
    rgb = np.asarray(Image.open(img_path).convert("RGB"), np.float64) / 255.0
    gray = 0.299 * rgb[..., 0] + 0.587 * rgb[..., 1] + 0.114 * rgb[..., 2]

    outs, prenorms = [], []
    for scale in range(NUM_SCALES):
        s = STEP + scale * SCALE_STEP
        bs = BIN + 2 * scale
        lo = max((1 + NUM_SCALES * 2) - scale * 3, 0)
        raw = dsift_one_scale(gray, s, bs, lo)
        prenorms.append(np.linalg.norm(raw, axis=1) / (NBP * NBP))
        outs.append(normalize_quantize(raw))
    desc = np.concatenate(outs, axis=0).T.astype(np.float32)  # (128, N)
    prenorm = np.concatenate(prenorms)

    out_path = os.path.join(
        ROOT, "tests/resources/sift_golden_gantrycrane.npz")
    np.savez_compressed(
        out_path,
        descriptors=desc.astype(np.float16),  # <=0.125 quantized-unit storage error
        prenorm=prenorm.astype(np.float32),
        config=np.asarray([STEP, BIN, NUM_SCALES, SCALE_STEP]),
    )
    n_zeroed = int((prenorm < CONTRAST).sum())
    print(f"golden: {desc.shape} descriptors, {n_zeroed} low-contrast, "
          f"{os.path.getsize(out_path) / 1024:.0f} KiB -> {out_path}")


if __name__ == "__main__":
    main()
