"""Train + evaluate + ship the averaged-perceptron NER
(VERDICT r4 next#5; companion of ``tools/train_pos.py``).

Trains on ``tests/resources/ner_train_corpus.txt``, evaluates
token-level precision/recall/F1 on the held-out
``tests/resources/ner_tagged_sample.txt`` against the rule-based
stand-in, and writes the gzip-JSON artifact the default ``NER`` node
loads. Usage: python tools/train_ner.py [--no-save]
"""
import os
import sys

sys.path.insert(0, ".")

from keystone_tpu.nodes.nlp.corenlp import RuleBasedNerModel  # noqa: E402
from keystone_tpu.nodes.nlp.perceptron_ner import (  # noqa: E402
    AveragedPerceptronNerModel,
    read_labeled_file,
)

RES = os.path.join("tests", "resources")


def token_f1(model, sentences):
    tp = fp = fn = 0
    for sent in sentences:
        words = [w for w, _ in sent]
        gold = [lab for _, lab in sent]
        pred = model.best_sequence(words).labels
        assert len(pred) == len(gold)
        for g, p in zip(gold, pred):
            if p != "O" and p == g:
                tp += 1
            elif p != "O":
                fp += 1
            if g != "O" and p != g:
                fn += 1
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    return f1, precision, recall


def main():
    train = read_labeled_file(os.path.join(RES, "ner_train_corpus.txt"))
    heldout = read_labeled_file(os.path.join(RES, "ner_tagged_sample.txt"))
    print(f"train: {len(train)} sentences, heldout: {len(heldout)}")

    rule = RuleBasedNerModel()
    rf1, rp, rr = token_f1(rule, heldout)
    print(f"rule-based held-out: F1 {rf1:.4f} (P {rp:.3f} R {rr:.3f})")

    best = None
    for epochs in (5, 8, 12):
        model = AveragedPerceptronNerModel.train(train, epochs=epochs)
        tf1, _, _ = token_f1(model, train)
        hf1, hp, hr = token_f1(model, heldout)
        print(f"epochs {epochs:2d}: train F1 {tf1:.4f}, held-out F1 "
              f"{hf1:.4f} (P {hp:.3f} R {hr:.3f})")
        if best is None or hf1 > best[0]:
            best = (hf1, epochs, model)

    hf1, epochs, model = best
    print(f"best: epochs={epochs} held-out F1 {hf1:.4f} "
          f"(rule-based {rf1:.4f})")
    if "--no-save" not in sys.argv:
        model.save()
        print("saved ->",
              "keystone_tpu/nodes/nlp/data/ner_perceptron.json.gz")


if __name__ == "__main__":
    main()
