"""Shared measurement helpers for the tools/ scripts.

The axon tunnel's ``block_until_ready`` can return BEFORE device
execution completes, so every timed region must end with a small data
pull; and fitted models are NOT registered pytrees, so finding their
device arrays requires walking object attributes, not tree leaves.
Both gotchas live here once (ADVICE r4 medium + the r5 review).
"""
import time

import jax
import jax.numpy as jnp


def device_arrays(obj, _seen=None):
    """Collect arrays reachable from ``obj``, recursing into plain
    containers AND object attributes (fitted models hand ``tree_leaves``
    the model object itself)."""
    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return []
    _seen.add(id(obj))
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        return [obj]
    out = []
    if isinstance(obj, dict):
        vals = obj.values()
    elif isinstance(obj, (list, tuple)):
        vals = obj
    elif hasattr(obj, "__dict__"):
        vals = vars(obj).values()
    else:
        return out
    for v in vals:
        out.extend(device_arrays(v, _seen))
    return out


def fence(tree):
    """Force completion of everything producing ``tree``. Only DEVICE
    arrays are pulled — ``jnp.asarray`` on a host ndarray would upload
    it through the ~5-10 MB/s tunnel inside the timed window. ONE
    combined scalar pull: its value depends on every input buffer, so
    one tunnel round trip forces all producing computations."""
    arrays = []
    for leaf in jax.tree_util.tree_leaves(tree):
        arrays.extend(a for a in device_arrays(leaf)
                      if isinstance(a, jax.Array))
    if not arrays:
        return
    float(sum(jnp.sum(a.ravel()[:1].astype(jnp.float32)) for a in arrays))


def timeit(fn, *args, iters=3):
    """Mean seconds per call over ``iters`` back-to-back dispatches
    (pipelined — one fence at the end, matching how production streams
    work onto the chip)."""
    fence(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / iters
