#!/usr/bin/env python3
"""Dynamic numerics gate: an injected NaN must trip; a clean fit must not.

The numerics plane (``observability/numerics.py``) promises that a NaN
born in chunk k of a streamed fit raises :class:`NumericsError` naming
the chunk and stream — with a post-mortem carrying the recent health
series — instead of surfacing as garbage weights at finalize. This tool
pins that promise at the CI level against the real streamed path, both
directions:

* **clean leg** — the recompile-gate smoke fit runs with numerics ON:
  it must complete, health words must have been pulled
  (``numerics.health_words`` > 0 — the plane actually ran, it was not
  silently disabled), and NO post-mortem may be written.
* **poisoned leg** — the same fit with one ``kind="corrupt"`` fault
  injected at the ``ingest.stage`` site (``resilience/faults.py``:
  NaN into the first float element of one chunk's host data, the
  deterministic "NaN born in chunk k" failure). The fit must raise
  ``NumericsError`` naming BOTH the poisoned chunk index and the
  stream tag, and the attached post-mortem artifact must embed the
  health series with the poisoned chunk's non-finite count.

Run by ``bin/ci.sh`` next to the recompile gate; also standalone::

    JAX_PLATFORMS=cpu python tools/numerics_gate.py
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: chunk index the fault plan poisons (0-based; `after=` skips visits)
POISON_CHUNK = 2


def _smoke_fit(tag):
    import jax.numpy as jnp
    import numpy as np

    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    rng = np.random.RandomState(0)
    n, d, chunk = 1024, 64, 64  # 16 chunks: the deferred-D2H window
    X = rng.rand(n, d).astype(np.float32)
    y = rng.randint(0, 10, n)
    labels = (-np.ones((n, 10)) + 2.0 * np.eye(10)[y]).astype(np.float32)

    def featurize(ad):
        return ad.map_batch(lambda x: jnp.tanh(x))

    stream = StreamingDataset.from_numpy(
        X, chunk_size=chunk, tag=tag).map_chunks(featurize)
    return fit_streaming(LinearMapEstimator(lam=0.1), stream, labels)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("KEYSTONE_NUMERICS", None)  # the plane must be ON
    # isolate the gate's post-mortems so the clean-leg "no artifact"
    # assertion cannot be confused by a developer's real dumps
    pm_dir = tempfile.mkdtemp(prefix="keystone-numerics-gate-")
    os.environ["KEYSTONE_POSTMORTEM_DIR"] = pm_dir

    from keystone_tpu.observability import MetricsRegistry
    from keystone_tpu.observability.numerics import NumericsError
    from keystone_tpu.resilience.faults import FaultPlan

    reg = MetricsRegistry.get_or_create()

    # -- clean leg --------------------------------------------------------
    _smoke_fit("numerics-gate-clean")
    words = reg.counter("numerics.health_words").value
    dumped = os.listdir(pm_dir)
    print(f"numerics gate: clean fit OK ({words:g} health word(s) "
          f"pulled, {len(dumped)} post-mortem(s))")
    if not words:
        print("numerics gate FAILED: the clean fit pulled zero health "
              "words — the numerics plane did not run (disabled? the "
              "fit_streaming wiring regressed?)", file=sys.stderr)
        return 1
    if dumped:
        print(f"numerics gate FAILED: a CLEAN fit wrote post-mortem(s) "
              f"{dumped} — the tripwire fired on healthy data",
              file=sys.stderr)
        return 1

    # -- poisoned leg -----------------------------------------------------
    tag = "numerics-gate-poisoned"
    try:
        with FaultPlan(seed=7).add(
                "ingest.stage", kind="corrupt",
                after=POISON_CHUNK, count=1):
            _smoke_fit(tag)
    except NumericsError as exc:
        msg = str(exc)
        path = getattr(exc, "postmortem_path", None)
        ok = True
        if f"chunk {POISON_CHUNK}" not in msg or tag not in msg:
            print(f"numerics gate FAILED: tripwire fired but named "
                  f"neither chunk {POISON_CHUNK} nor stream {tag!r}: "
                  f"{msg}", file=sys.stderr)
            ok = False
        if path is None or not os.path.exists(path):
            print("numerics gate FAILED: tripwire fired without a "
                  "post-mortem artifact", file=sys.stderr)
            ok = False
        else:
            with open(path) as f:
                blob = json.load(f)
            series = (blob.get("context") or {}).get("recent_health") or []
            bad = [e for e in series
                   if e.get("chunk") == POISON_CHUNK
                   and (e.get("nan") or e.get("inf"))]
            if not bad:
                print("numerics gate FAILED: post-mortem health series "
                      f"does not show chunk {POISON_CHUNK} non-finite "
                      f"({len(series)} entries)", file=sys.stderr)
                ok = False
        if not ok:
            return 1
        print(f"numerics gate OK: injected NaN in chunk {POISON_CHUNK} "
              f"tripped NumericsError naming chunk+stream; post-mortem "
              f"at {path} carries the health series")
        return 0
    print("numerics gate FAILED: the poisoned fit completed without "
          "raising NumericsError — the tripwire is dead (the injected "
          "NaN would have reached the fitted weights)", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
