"""Per-stage profile of the ImageNet SIFT/LCS/FV featurization path
(VERDICT r4 next#1: "publish a per-stage profile, then attack the
dominant stage").

Method: the stages run fused inside one jit in production, so timing
them one jit per stage would charge each stage the ~18-20 ms axon
dispatch floor. Instead this times CUMULATIVE PREFIXES of the pipeline
(smooth; +orient; +sample; +norm; +PCA; +FV), each as one jitted
program over the same image batch, and reports adjacent differences —
the floor and the shared input staging cancel.

Stages (per scale s: bin = bin_size + 2s, step = step + s*scale_step),
as implemented by the band-matmul kernel in ``keystone_tpu/ops/sift.py``:
  smooth    Gaussian blur as band matmuls          (MXU)
  orient    gradient -> 8 soft-assigned magnitude maps
  sample    triangle binning + frac shift + strided sampling,
            folded into T_y @ omaps @ T_x^T        (MXU)
  norm      L2-clamp-renorm-quantize in the binned layout
  pca       signed Hellinger + 64x128 projection
  fv        GMM posteriors + s0/s1/s2 moments -> 2048-dim FV

Host-side (tar decode, grayscale) is profiled separately by the loader
bench (`bench.py --loader`); LCS is timed whole (it is one box-filter
program).

Usage: python tools/profile_imagenet.py [--small] [--images N]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from keystone_tpu.ops import sift as S  # noqa: E402

SMALL = "--small" in sys.argv
N_IMGS = int(sys.argv[sys.argv.index("--images") + 1]) \
    if "--images" in sys.argv else (4 if SMALL else 16)
H, W = (160, 160) if SMALL else (480, 640)
STEP, BIN, NSCALES, SSTEP = 4, 6, 5, 1
DESC_DIM, VOCAB = 64, 16


from tools._bench import fence, timeit  # noqa: E402


def scale_plan():
    out = []
    for sc in range(NSCALES):
        st, bs, lo = S._scale_params(sc, STEP, BIN, NSCALES, SSTEP)
        out.append((st, bs, lo))
    return out


def prefix_fn(depth, pca=None, gmm=None):
    """Build the featurizer truncated after `depth` stages (1=smooth ...
    7=fv). Returns a per-image function for vmap."""
    plan = scale_plan()

    def one(img):
        per_scale = []
        for st, bs, lo in plan:
            Gy = jnp.asarray(S._smooth_band(H, bs))
            Gx = jnp.asarray(S._smooth_band(W, bs))
            sm = jnp.einsum("ih,hw,jw->ij", Gy, img, Gx, precision=S._PRECISION)
            if depth == 1:
                per_scale.append(jnp.sum(sm))
                continue
            om = S._orientation_maps(sm)
            if depth == 2:
                per_scale.append(jnp.sum(om))
                continue
            Ty, ny = S._sampling_operator(H, lo, st, bs)
            Tx, nx = S._sampling_operator(W, lo, st, bs)
            bins = jnp.einsum("ph,ohw,qw->opq", jnp.asarray(Ty), om,
                              jnp.asarray(Tx), precision=S._PRECISION)
            if depth == 3:
                per_scale.append(jnp.sum(bins))
                continue
            per_scale.append(S._normalize_quantize_binned(
                bins.reshape(S.NBO, S.NBP, ny, S.NBP, nx)))
        if depth <= 3:
            return jnp.stack(per_scale).sum()
        desc = jnp.concatenate(per_scale, axis=1)     # (128, N)
        if depth == 4:
            return desc
        desc = jnp.sign(desc) * jnp.sqrt(jnp.abs(desc))
        proj = pca @ desc                             # (64, N)
        if depth == 5:
            return proj
        from keystone_tpu.nodes.images.fisher_vector import _fisher_vector
        out = _fisher_vector(proj, *gmm, 1e-2).reshape(-1)
        out = out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)
        out = jnp.sign(out) * jnp.sqrt(jnp.abs(out))
        return out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)

    return one


def main():
    print(f"device: {jax.devices()[0].device_kind}; batch {N_IMGS} "
          f"{H}x{W}, step {STEP} bin {BIN} scales {NSCALES}(+{SSTEP})",
          flush=True)
    rng = np.random.RandomState(0)
    imgs = jax.device_put(rng.rand(N_IMGS, H, W).astype(np.float32))
    fence(imgs)
    pca = jax.device_put(rng.randn(DESC_DIM, 128).astype(np.float32) / 11.3)
    gmm = tuple(jax.device_put(a) for a in (
        rng.randn(DESC_DIM, VOCAB).astype(np.float32),
        (0.5 + rng.rand(DESC_DIM, VOCAB)).astype(np.float32),
        (np.ones(VOCAB) / VOCAB).astype(np.float32)))

    names = ["smooth", "orient", "sample", "norm", "pca", "fv"]
    cum = []
    for depth in range(1, 7):
        fn = jax.jit(jax.vmap(prefix_fn(depth, pca, gmm)))
        dt = timeit(fn, imgs)
        cum.append(dt)
        stage_ms = 1e3 * (dt - (cum[-2] if len(cum) > 1 else 0.0))
        print(f"  prefix {depth} (+{names[depth-1]:9s}): "
              f"{1e3 * dt:8.1f} ms cum  | +{stage_ms:7.1f} ms", flush=True)

    total = cum[-1]
    print(f"full featurize: {1e3 * total / N_IMGS:.2f} ms/img "
          f"= {N_IMGS / total:.1f} img/s/chip", flush=True)

    # batch-64 measurement (VERDICT r5 item 3): the bigger vmap batch
    # amortizes per-dispatch overhead ~+10% — worth taking only when the
    # host can feed it, which bench.py's rehearsal section validates via
    # the streaming prefetcher; here the delta itself is recorded.
    # Skipped in --small (tiny shapes make the comparison meaningless).
    if not SMALL and N_IMGS != 64:
        imgs64 = jax.device_put(rng.rand(64, H, W).astype(np.float32))
        fence(imgs64)
        fn64 = jax.jit(jax.vmap(prefix_fn(6, pca, gmm)))
        dt64 = timeit(fn64, imgs64)
        print(f"batch 64: {1e3 * dt64 / 64:.2f} ms/img "
              f"= {64 / dt64:.1f} img/s/chip "
              f"({100.0 * (64 / dt64) / (N_IMGS / total) - 100.0:+.1f}% "
              f"vs batch {N_IMGS})", flush=True)

    # LCS branch, timed whole
    from keystone_tpu.nodes.images.extractors import LCSExtractor
    lcs = LCSExtractor()
    imgs_rgb = jax.device_put(
        rng.rand(N_IMGS, H, W, 3).astype(np.float32))
    fence(imgs_rgb)
    lcs_fn = jax.jit(jax.vmap(lcs.apply))
    dt = timeit(lcs_fn, imgs_rgb)
    print(f"LCS whole: {1e3 * dt / N_IMGS:.2f} ms/img "
          f"= {N_IMGS / dt:.1f} img/s/chip", flush=True)

    # parity: prefix-6 must match the production featurizer
    from keystone_tpu.nodes.images.extractors import SIFTExtractor
    from keystone_tpu.nodes.images.fisher_vector import _fisher_vector
    sx = SIFTExtractor(step=STEP, bin_size=BIN, num_scales=NSCALES,
                       scale_step=SSTEP)

    def prod(img):
        d = sx.apply(img)
        d = jnp.sign(d) * jnp.sqrt(jnp.abs(d))
        p = pca @ d
        out = _fisher_vector(p, *gmm, 1e-2).reshape(-1)
        out = out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)
        out = jnp.sign(out) * jnp.sqrt(jnp.abs(out))
        return out / jnp.maximum(jnp.linalg.norm(out), 2.2e-16)

    a = np.asarray(jax.jit(jax.vmap(prefix_fn(6, pca, gmm)))(imgs[:2]))
    b = np.asarray(jax.jit(jax.vmap(prod))(imgs[:2]))
    err = float(np.max(np.abs(a - b)))
    print(f"parity prefix-6 vs production: max abs delta {err:.2e}",
          flush=True)
    assert err < 1e-4, err

    # Device-mode precision parity gate (ADVICE medium#2): the shipped
    # Precision.HIGH band matmuls must keep quantized descriptors within
    # the golden test's envelope of a HIGHEST (6-pass, ~f32) reference —
    # the same bound test_dense_sift_descriptor_golden_gantrycrane pins
    # against VLFeat (diff.max <= 2 quantization levels, mean <= 0.15).
    # On CPU the flag is a no-op (exact equality); on TPU this is the
    # automated check that bf16 drift cannot ship unnoticed.
    def sift_at(precision):
        return jax.jit(jax.vmap(
            lambda g: S.dense_sift(g, STEP, BIN, NSCALES, SSTEP,
                                   precision=precision)))(imgs[:2])

    hi = np.asarray(sift_at(jax.lax.Precision.HIGH))
    ref = np.asarray(sift_at(jax.lax.Precision.HIGHEST))
    diff = np.abs(hi - ref)
    print(f"precision parity HIGH vs HIGHEST: max {diff.max():.3f} "
          f"mean {diff.mean():.4f} (envelope: max <= 2.0, mean <= 0.15)",
          flush=True)
    assert diff.max() <= 2.0, diff.max()
    assert diff.mean() <= 0.15, diff.mean()

    kernel_gates(imgs, gmm)


def kernel_gates(imgs, gmm):
    """PR 13 parity gates: every Pallas kernel must reproduce its
    einsum fallback inside its envelope ON THIS DEVICE, every profile —
    the banded SIFT against the descriptor golden envelope, the fused
    FV against a tight absolute bound, the quantized predict against
    argmax agreement + an error bound. On TPU the compiled kernels run;
    elsewhere the kernel bodies run on the interpreter over a cropped
    batch (interpret-mode at full VGA is minutes per image)."""
    from keystone_tpu.nodes.images.fisher_vector import _fisher_vector
    from keystone_tpu.ops.pallas_kernels import use_pallas

    on_tpu = use_pallas()
    banded_mode = "banded" if on_tpu else "banded_interpret"
    fv_mode = "pallas" if on_tpu else "pallas_interpret"

    # banded SIFT GEMM vs einsum: the golden envelope (quantized
    # descriptor levels), same bound as the precision gate above
    crop = imgs[:2] if on_tpu else imgs[:1, :96, :128]
    def sift_mode(mode):
        return jax.jit(jax.vmap(
            lambda g: S.dense_sift(g, STEP, BIN, NSCALES, SSTEP,
                                   kernel_mode=mode)))(crop)

    banded = np.asarray(sift_mode(banded_mode))
    ref = np.asarray(sift_mode("einsum"))
    diff = np.abs(banded - ref)
    print(f"banded-kernel parity vs einsum: max {diff.max():.3f} "
          f"mean {diff.mean():.4f} (envelope: max <= 2.0, mean <= 0.15)",
          flush=True)
    assert diff.max() <= 2.0, diff.max()
    assert diff.mean() <= 0.15, diff.mean()

    # fused GMM-posterior + FV kernel vs the split fallback
    rng = np.random.RandomState(7)
    proj = jnp.asarray(rng.randn(DESC_DIM, 2048).astype(np.float32))
    fused = np.asarray(_fisher_vector(proj, *gmm, 1e-2,
                                      kernel_mode=fv_mode))
    split = np.asarray(_fisher_vector(proj, *gmm, 1e-2,
                                      kernel_mode="einsum"))
    err = np.abs(fused - split)
    print(f"fused-FV parity vs fallback: max {err.max():.2e} "
          f"mean {err.mean():.2e} (envelope: max <= 1e-3)", flush=True)
    assert err.max() <= 1e-3, err.max()

    # quantized predict: argmax agreement + error bound vs f32 apply
    # at the rehearsal solve shape (separable teacher labels — ties on
    # noise would measure argmax fragility, not quantization). The
    # quantized leg goes through apply_dataset — the PRODUCTION batch
    # dispatch, which is the path that actually reaches
    # quantized_affine_pallas on TPU (per-item apply is always the
    # dequantizing fallback).
    from keystone_tpu.nodes.learning.linear import LinearMapper
    from keystone_tpu.parallel.dataset import ArrayDataset

    n, d, k = 512, 1024, 100
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32) / np.sqrt(d)
    b = rng.randn(k).astype(np.float32) * 0.01
    ds = ArrayDataset.from_numpy(X)
    f32 = LinearMapper(W, intercept=b).apply_dataset(ds).numpy()
    for dtype, min_agree, max_rel in (("bf16", 0.999, 0.02),
                                      ("int8", 0.98, 0.03)):
        q = LinearMapper(W, intercept=b, weight_dtype=dtype)
        out = q.apply_dataset(ds).numpy()
        agree = float((f32.argmax(1) == out.argmax(1)).mean())
        rel = float(np.abs(out - f32).max() / np.abs(f32).max())
        # the per-item path must match the batched kernel path too
        item = np.asarray(q.apply(jnp.asarray(X[0])))
        item_delta = float(np.abs(item - out[0]).max())
        print(f"quantized predict {dtype} (apply_dataset dispatch): "
              f"argmax agreement {agree:.4f} (>= {min_agree}), max rel "
              f"err {rel:.4f} (<= {max_rel}), item-vs-batch "
              f"{item_delta:.2e}", flush=True)
        assert agree >= min_agree, (dtype, agree)
        assert rel <= max_rel, (dtype, rel)
        assert item_delta <= 1e-4, (dtype, item_delta)


if __name__ == "__main__":
    main()
