#!/usr/bin/env python3
"""Elastic-resume CI gate: kill one host mid-fit, relaunch, resume —
the resumed weights must be BIT-IDENTICAL to the uninterrupted run.

The dynamic pin for the elastic multi-host plane
(``parallel/distributed.py``), the cross-process complement of the
recompile and numerics gates. Five worlds of 2 CPU processes (2
virtual devices each) run the same shard-local streamed LinearMap fit
through the real ``jax.distributed`` + gloo path:

1. **uninterrupted** — the reference weights;
2. **killed** — a ``host_death`` fault takes out process 1 entering
   coordination round 2 (exit code 117, after exactly 2 coordinated
   checkpoints); the launcher applies gang semantics and reaps the
   wedged survivor — the world snapshot (per-host cursors + carries,
   merged by host 0 from the durably-renamed sidecars) is what
   survives;
3. **relaunched** — the same world resumes from the shared
   ``StreamCheckpoint``: every worker must report ``resumed=1`` and
   ``unexpected_compiles=0`` (the PR 9 warmup fence stays clean across
   a resume), and host 0's weights must equal run 1's bit for bit;
4. **killed mid-overlap** — the kill lands at round 2's AWAIT point,
   i.e. BETWEEN a round's dispatch and its await under the overlapped
   loop (PR 18): round 2's allgather and the lagged carry snapshot are
   both in flight when the host dies — the hardest window, because the
   surviving sidecars may legitimately trail the live cursor by one
   round (the overlap's lagged-snapshot contract);
5. **relaunched again** — resume from the mid-overlap kill's snapshot:
   sidecar-trailing resume replays the un-snapshotted round and must
   STILL produce bit-identical weights with a clean fence (resume
   re-accumulates from the quiesced boundary, never from a torn one).

Exit 1 names the divergent artifact (which run, which file, max
delta). Run by ``bin/ci.sh``; standalone::

    python tools/elastic_gate.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N, D, K, CHUNK = 192, 12, 3, 16
KILL_ROUND = 2


def _check_world(world, codes, name, expect_resumed):
    for pid, code in enumerate(codes):
        if code != 0:
            print(world.output(pid)[-2000:], file=sys.stderr)
            print(f"elastic gate FAILED: {name} run process {pid} "
                  f"exited {code} (log above)", file=sys.stderr)
            return False
        line = [l for l in world.output(pid).splitlines()
                if l.startswith("ELASTIC_OK")]
        if not line:
            print(f"elastic gate FAILED: {name} run process {pid} "
                  "printed no ELASTIC_OK line", file=sys.stderr)
            return False
        fields = dict(kv.split("=", 1) for kv in line[0].split()[1:])
        if int(fields["unexpected_compiles"]) != 0:
            print(f"elastic gate FAILED: {name} run process {pid} saw "
                  f"{fields['unexpected_compiles']} unexpected "
                  "recompile(s) under the fit fence — the distributed "
                  "path must compile only in round 1", file=sys.stderr)
            return False
        if int(fields["resumed"]) != expect_resumed:
            print(f"elastic gate FAILED: {name} run process {pid} "
                  f"reported resumed={fields['resumed']}, expected "
                  f"{expect_resumed} — the relaunched world did not "
                  "restore the shared StreamCheckpoint",
                  file=sys.stderr)
            return False
    return True


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from keystone_tpu.parallel.distributed import DryrunWorld
    from keystone_tpu.resilience.faults import HOST_DEATH_EXIT_CODE

    workdir = tempfile.mkdtemp(prefix="keystone-elastic-gate-")
    rng = np.random.RandomState(0)
    npz = os.path.join(workdir, "data.npz")
    np.savez(npz, X=rng.randn(N, D).astype(np.float32),
             Y=rng.randn(N, K).astype(np.float32))
    ckdir = os.path.join(workdir, "ck")
    out_a = os.path.join(workdir, "uninterrupted.npz")
    out_c = os.path.join(workdir, "resumed.npz")
    base = [sys.executable, "-m", "keystone_tpu.parallel.dryrun_worker",
            "--data", npz, "--chunk-size", str(CHUNK)]

    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=workdir, grace_s=20)
    print("elastic gate: run 1/5 — uninterrupted 2-process streamed fit")
    codes = world.launch(base + ["--out", out_a]).wait(timeout_s=300)
    if not _check_world(world, codes, "uninterrupted", expect_resumed=0):
        return 1

    print(f"elastic gate: run 2/5 — kill process 1 at round {KILL_ROUND}")
    codes = world.launch(
        base + ["--checkpoint-dir", ckdir, "--checkpoint-every", "1",
                "--die-process", "1",
                "--die-at-round", str(KILL_ROUND)]).wait(timeout_s=300)
    if world.host_death_exits(codes) != [1]:
        print(f"elastic gate FAILED: expected process 1 to die of "
              f"host_death (exit {HOST_DEATH_EXIT_CODE}), got exit "
              f"codes {codes}", file=sys.stderr)
        return 1
    if not os.path.exists(os.path.join(ckdir, "stream_fit.ckpt")):
        print("elastic gate FAILED: the killed world left no shared "
              f"world snapshot under {ckdir} — nothing to resume from",
              file=sys.stderr)
        return 1

    print("elastic gate: run 3/5 — relaunch the world, resume, compare")
    codes = world.launch(
        base + ["--checkpoint-dir", ckdir, "--checkpoint-every", "1",
                "--out", out_c]).wait(timeout_s=300)
    if not _check_world(world, codes, "resumed", expect_resumed=1):
        return 1

    w_a = np.load(out_a)["weights"]
    w_c = np.load(out_c)["weights"]
    if not (w_a == w_c).all():
        delta = float(np.abs(w_a - w_c).max())
        print(f"elastic gate FAILED: resumed weights diverge from the "
              f"uninterrupted run (max |delta| {delta:.3e}; divergent "
              f"artifact: {out_c} vs reference {out_a}) — the "
              "kill-and-resume path is no longer bit-identical",
              file=sys.stderr)
        return 1
    if os.path.exists(os.path.join(ckdir, "stream_fit.ckpt")):
        print("elastic gate FAILED: the world snapshot survived a "
              "successful finalize (stale snapshots must be cleared)",
              file=sys.stderr)
        return 1

    # -- the overlap window: kill BETWEEN dispatch and await -----------------
    ckdir2 = os.path.join(workdir, "ck-overlap")
    out_e = os.path.join(workdir, "resumed-overlap.npz")
    print(f"elastic gate: run 4/5 — kill process 1 at round "
          f"{KILL_ROUND}'s await (mid-overlap: allgather + carry "
          "snapshot in flight)")
    codes = world.launch(
        base + ["--checkpoint-dir", ckdir2, "--checkpoint-every", "1",
                "--die-process", "1",
                "--die-at-await-round", str(KILL_ROUND)]
    ).wait(timeout_s=300)
    if world.host_death_exits(codes) != [1]:
        print(f"elastic gate FAILED: expected process 1 to die of "
              f"host_death at the await point (exit "
              f"{HOST_DEATH_EXIT_CODE}), got exit codes {codes}",
              file=sys.stderr)
        return 1
    if not os.path.exists(os.path.join(ckdir2, "stream_fit.ckpt")):
        print("elastic gate FAILED: the mid-overlap kill left no "
              f"shared world snapshot under {ckdir2} — nothing to "
              "resume from", file=sys.stderr)
        return 1

    print("elastic gate: run 5/5 — relaunch after the mid-overlap "
          "kill, resume, compare")
    codes = world.launch(
        base + ["--checkpoint-dir", ckdir2, "--checkpoint-every", "1",
                "--out", out_e]).wait(timeout_s=300)
    if not _check_world(world, codes, "overlap-resumed",
                        expect_resumed=1):
        return 1
    w_e = np.load(out_e)["weights"]
    if not (w_a == w_e).all():
        delta = float(np.abs(w_a - w_e).max())
        print(f"elastic gate FAILED: weights resumed from a "
              f"mid-overlap kill diverge from the uninterrupted run "
              f"(max |delta| {delta:.3e}; divergent artifact: {out_e} "
              f"vs reference {out_a}) — the lagged-snapshot resume is "
              "no longer bit-identical", file=sys.stderr)
        return 1
    if os.path.exists(os.path.join(ckdir2, "stream_fit.ckpt")):
        print("elastic gate FAILED: the overlap-run world snapshot "
              "survived a successful finalize (stale snapshots must "
              "be cleared)", file=sys.stderr)
        return 1
    print("elastic gate OK: killed worlds (round entry AND "
          "mid-overlap await) resumed to bit-identical weights, "
          "fence clean, snapshots cleared")
    return 0


if __name__ == "__main__":
    sys.exit(main())
