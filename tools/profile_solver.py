"""One-off profiler for the block-LS solver's constituent ops at CIFAR
scale (n=50k, bs=4096, k=10). Data is generated ON DEVICE (the axon dev
tunnel uploads at single-digit MB/s; a host-generated 800 MB block would
time the tunnel). Timings end with a 4-byte scalar pull (bench.py _fence
rationale).

Usage: python tools/profile_solver.py [--small]
"""
import sys
import time

import jax
import jax.numpy as jnp
from jax import random

HIGHEST = jax.lax.Precision("highest")
SMALL = "--small" in sys.argv
n, bs, k = (5_000, 1024, 10) if SMALL else (50_000, 4096, 10)


def fence(tree):
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "dtype")]
    float(sum(jnp.sum(x.astype(jnp.float32)) for x in leaves))


def bench(name, fn, *args, iters=5, flops=None):
    fence(fn(*args))  # compile + warm
    fence(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(out)
    dt = (time.perf_counter() - t0) / iters
    rate = f"  {flops / dt / 1e12:7.2f} TFLOPS(nominal)" if flops else ""
    print(f"{name:28s} {dt * 1e3:9.2f} ms{rate}", flush=True)
    return dt


A = random.normal(random.PRNGKey(0), (n, bs), jnp.float32)
Y = random.normal(random.PRNGKey(1), (n, k), jnp.float32)
fence((A, Y))

gram_flops = 2.0 * n * bs * bs


@jax.jit
def gram_full(A):
    return jnp.einsum("nd,ne->de", A, A, precision=HIGHEST)


def make_syrk(tile):
    T = bs // tile

    @jax.jit
    def g(A):
        ts = [A[:, i * tile:(i + 1) * tile] for i in range(T)]
        blk = {}
        for i in range(T):
            for j in range(i, T):
                blk[(i, j)] = jnp.einsum(
                    "nd,ne->de", ts[i], ts[j], precision=HIGHEST)
        rows = [
            jnp.concatenate(
                [blk[(i, j)] if i <= j else blk[(j, i)].T for j in range(T)],
                axis=1)
            for i in range(T)
        ]
        return jnp.concatenate(rows, axis=0)

    return g


@jax.jit
def chol(G):
    return jax.scipy.linalg.cho_factor(
        G + 0.1 * jnp.eye(G.shape[0], dtype=G.dtype), lower=True)[0]


@jax.jit
def cho_solve_(L, R):
    return jax.scipy.linalg.cho_solve((L, True), R)


@jax.jit
def cross_resid(A, W, Y):
    tgt = Y - A @ W
    return jnp.einsum("nd,nk->dk", A, tgt, precision=HIGHEST)


t_full = bench("gram full einsum", gram_full, A, flops=gram_flops)
for tile in (512, 1024):
    frac = (bs // tile) * (bs // tile + 1) / 2 / (bs // tile) ** 2
    t = bench(f"gram syrk tile={tile}", make_syrk(tile), A, flops=gram_flops)
    print(f"  (computed fraction {frac:.3f}, ideal {t_full * frac * 1e3:.1f} ms)")

G = gram_full(A)
fence(G)
L = chol(G)
fence(L)
W0 = jnp.zeros((bs, k), jnp.float32)
bench("cholesky factor", chol, G, flops=bs ** 3 / 3)
bench("cho_solve rhs k=10", cho_solve_, L, random.normal(random.PRNGKey(2), (bs, k), jnp.float32))
bench("cross+residual", cross_resid, A, W0, Y, flops=4.0 * n * bs * k)
print("done", flush=True)
