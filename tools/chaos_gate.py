#!/usr/bin/env python3
"""CI chaos gate for the serving plane (``bin/ci.sh``).

Runs the full ``serving/scenarios`` catalogue at bounded seeds, IN
PROCESS — :class:`~keystone_tpu.resilience.faults.FaultPlan` is
process-global, so the injections cannot be installed into a
subprocess server. Each run replays a deterministic load trace
(bursty/diurnal/Zipf arrivals, churn under live load) against a fresh
plane under that scenario's seeded fault plan, then judges the
scenario's p99/availability FLOORS plus its own invariant checks
(backpressure observed, rollback observed, worker survived, ...).

The contract, inherited from the PR 7/11 chaos soaks: every run ends
CLEAN or in a CLASSIFIED failure — a floor violation writes a
post-mortem naming scenario and seed, and the gate exits 1 naming the
violated floor. An UNCLASSIFIED outcome (a request that died outside
the typed verdict set) is itself a floor violation; silent damage
never passes.

Exit 0 when every scenario x seed run is clean; exit 1 otherwise.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# post-mortems from gated runs land somewhere writable and named, not
# wherever the runner's cwd happens to be
os.environ.setdefault(
    "KEYSTONE_POSTMORTEM_DIR",
    tempfile.mkdtemp(prefix="keystone-chaos-gate-"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=2,
                    help="seeds per scenario (0..N-1, default 2)")
    ap.add_argument("--scenario", action="append", default=None,
                    help="run only this scenario (repeatable)")
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="stretch (>1) or compress (<1) arrival clocks")
    args = ap.parse_args(argv)

    from keystone_tpu.serving.scenarios import (
        SCENARIOS,
        load_catalogue,
        run_scenario,
    )

    load_catalogue()
    names = sorted(SCENARIOS)
    if args.scenario:
        missing = [n for n in args.scenario if n not in SCENARIOS]
        if missing:
            print(f"chaos gate: FAIL: unknown scenario(s) {missing}; "
                  f"catalogue: {names}", file=sys.stderr)
            return 1
        names = sorted(set(args.scenario))
    if len(SCENARIOS) < 6:
        print(f"chaos gate: FAIL: catalogue has {len(SCENARIOS)} "
              "scenarios < 6 — the suite shrank", file=sys.stderr)
        return 1

    print(f"chaos gate: {len(names)} scenario(s) x {args.seeds} seed(s) "
          f"(post-mortems -> {os.environ['KEYSTONE_POSTMORTEM_DIR']})")
    failures = []
    t_gate = time.perf_counter()
    for name in names:
        for seed in range(args.seeds):
            t0 = time.perf_counter()
            res = run_scenario(name, seed, time_scale=args.time_scale)
            wall = time.perf_counter() - t0
            verdict = ("CLEAN" if res.clean else
                       f"CLASSIFIED(post-mortem="
                       f"{res.postmortem_path or 'MISSING'})")
            print(f"chaos gate: {name} seed={seed} "
                  f"p99={res.p99_ms:.1f}ms floor<={res.floors.p99_ms:.0f} "
                  f"avail={res.availability:.3f} "
                  f"floor>={res.floors.availability:.2f} "
                  f"inj={res.injections} {wall:.1f}s -> {verdict}")
            if res.clean:
                continue
            for v in res.violations:
                print(f"chaos gate:   violated: {v}", file=sys.stderr)
            if not res.postmortem_path:
                print("chaos gate:   AND the violation wrote no "
                      "post-mortem — unclassified damage",
                      file=sys.stderr)
            failures.append((name, seed, res.violations))
    if failures:
        floors = "; ".join(
            f"{n}/seed{s}: {', '.join(v)}" for n, s, v in failures)
        print(f"chaos gate: FAIL: {len(failures)} run(s) violated "
              f"their floors — {floors}", file=sys.stderr)
        return 1
    print(f"chaos gate: PASS ({len(names) * args.seeds} runs clean "
          f"in {time.perf_counter() - t_gate:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
