#!/usr/bin/env python3
"""1-vs-N-process streamed-fit scaling bench on the CPU dryrun harness.

Runs the same shard-local streamed LinearMap fit at world size 1 and
world size N (default 2) through ``parallel.distributed.DryrunWorld``
+ ``parallel.dryrun_worker`` — real ``jax.distributed`` + gloo, real
coordination rounds, real finalize tree-reduce — and emits the
benchdiff-parseable metric lines MULTICHIP_r06+ records::

    {"metric": "elastic_streamed_images_per_sec_1p", "value": ...}
    {"metric": "elastic_streamed_images_per_sec_2p", "value": ...}
    {"metric": "elastic_scaling_efficiency", "value": ...}
    {"metric": "coord_overhead_share", "value": ...}
    {"metric": "coord_overlap_occupancy", "value": ...}

``elastic_scaling_efficiency`` = (N-process img/s) / (N x 1-process
img/s). On the CPU sim every "host" shares one machine, so the number
is a COORDINATION-OVERHEAD floor, not a hardware scaling claim: it
bounds what the round barriers + carry merge cost when the compute
itself cannot speed up. On real pod hardware the same harness measures
true scaling.

Both worlds fit WARM by default (``--cold`` disables): the worker runs
one untimed fit first, so the timed number is the steady state — per-
chunk accumulate with coordination overlapped behind it — rather than
each process's one-off trace/compile wall amortized over the row count
(which is what put MULTICHIP_r06 at 0.27: ~2s of per-process fixed cost
against ~2ms/chunk of actual work). The ``coord_overhead_share`` /
``coord_overlap_occupancy`` pair (blocked-await wall over round wall,
and its complement) is forwarded from the N-process world so the
artifact records WHY the efficiency moved — PERFORMANCE.md rule 17:
measure the await, not the round.

    JAX_PLATFORMS=cpu python tools/elastic_bench.py [--processes N]
    [--rows R] [--dim D] [--chunk-size C] [--cold]
"""
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _run_world(nproc, npz, chunk, workdir, warmup=True):
    from keystone_tpu.parallel.distributed import DryrunWorld

    # the numerics health plane stays OFF in both worlds: its per-chunk
    # health-word D2H hits a ~30ms fixed latency under the initialized
    # gloo runtime (a distributed-client transfer path, paid even at
    # world size 1) that buries the per-chunk compute either world
    # actually does — the scaling ratio would measure that stall, not
    # coordination. The plane's cost has its own banded line
    # (numerics_overhead_share, bench.py) on real hardware.
    world = DryrunWorld(num_processes=nproc, devices_per_process=2,
                        workdir=workdir, grace_s=30,
                        env={"KEYSTONE_NUMERICS": "0"})
    cmd = [sys.executable, "-m", "keystone_tpu.parallel.dryrun_worker",
           "--data", npz, "--chunk-size", str(chunk), "--bench"]
    if warmup:
        cmd.append("--warmup")
    world.launch(cmd)
    codes = world.wait(timeout_s=900)
    if any(codes):
        for p in range(nproc):
            print(world.output(p)[-1500:], file=sys.stderr)
        raise SystemExit(f"elastic bench: world size {nproc} failed "
                         f"(exit codes {codes})")
    out = world.output(0)
    m = re.search(r'^\{.*"elastic_streamed_images_per_sec".*\}$', out,
                  re.MULTILINE)
    if not m:
        raise SystemExit(f"elastic bench: world size {nproc} emitted "
                         "no metric line")
    blob = json.loads(m.group(0))
    fence = [l for l in out.splitlines() if l.startswith("ELASTIC_OK")]
    coord = [json.loads(l) for l in out.splitlines()
             if l.startswith('{') and '"coord_' in l]
    return float(blob["value"]), fence, coord


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    args = sys.argv[1:]

    def _flag(name, default, cast=int):
        if name in args:
            i = args.index(name)
            v = cast(args[i + 1])
            del args[i:i + 2]
            return v
        return default

    nproc = _flag("--processes", 2)
    rows = _flag("--rows", 32768)
    dim = _flag("--dim", 64)
    chunk = _flag("--chunk-size", 256)
    warmup = "--cold" not in args

    import numpy as np

    workdir = tempfile.mkdtemp(prefix="keystone-elastic-bench-")
    rng = np.random.RandomState(0)
    npz = os.path.join(workdir, "data.npz")
    np.savez(npz, X=rng.randn(rows, dim).astype(np.float32),
             Y=rng.randn(rows, 8).astype(np.float32))

    print(f"elastic bench: {rows}x{dim} f32, chunk {chunk}, "
          f"world sizes 1 and {nproc} (CPU dryrun, "
          f"{'warm steady-state' if warmup else 'cold'})")
    ips_1, _, _ = _run_world(1, npz, chunk, workdir, warmup=warmup)
    ips_n, fence, coord = _run_world(nproc, npz, chunk, workdir,
                                     warmup=warmup)
    for line in fence:
        print(line)
    efficiency = ips_n / (nproc * ips_1) if ips_1 else 0.0
    print(json.dumps({"metric": "elastic_streamed_images_per_sec_1p",
                      "value": ips_1, "rows": rows, "dim": dim,
                      "warm": warmup}))
    print(json.dumps({"metric":
                      f"elastic_streamed_images_per_sec_{nproc}p",
                      "value": ips_n, "rows": rows, "dim": dim,
                      "warm": warmup}))
    print(json.dumps({"metric": "elastic_scaling_efficiency",
                      "value": efficiency, "processes": nproc,
                      "note": "cpu-sim: coordination-overhead floor, "
                              "hosts share one machine; warm per-chunk "
                              "wall is dispatch-latency-bound under the "
                              "gloo runtime, so N hosts overlapping "
                              "that latency can exceed 1.0 — the claim "
                              "is 'coordination adds ~nothing', not "
                              "'extra hardware appeared'"}))
    for blob in coord:
        print(json.dumps(blob))
    return 0


if __name__ == "__main__":
    sys.exit(main())
