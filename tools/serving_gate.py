#!/usr/bin/env python3
"""CI smoke gate for the serving plane (``bin/ci.sh``).

End-to-end, out of process — the exact deployment shape:

1. fit two small pipelines, save them with ``utils.checkpoint.
   save_pipeline`` (the artifact format ``serve`` loads);
2. start ``python -m keystone_tpu serve`` as a SUBPROCESS on an
   ephemeral port (the server binds before admitting, so ``/healthz``
   observably reports 503 warming during the warmup compiles);
3. wait for readiness (``/healthz`` 200) with a hard timeout — a hung
   warmup fails the gate, not the CI wall clock;
4. drive requests across >= 2 request shapes (different buckets) and
   BOTH models, checking response shapes AND that every predict
   response carries a distinct non-empty ``X-Keystone-Trace`` header
   (the PR 16 request-path handle round-trips end to end);
5. scrape ``/metrics`` and assert ``keystone_compile_unexpected_total``
   is 0 — the server arms the warmup fence after admission, so ANY
   steady-state recompile shows up here — and that the serving
   counters saw the traffic;
6. scrape ``/slo`` and assert a clean run reports availability 1.0
   with zero violations;
7. IN PROCESS (FaultPlan is process-global, so the straggler cannot be
   installed in the subprocess server): run a tight-policy plane under
   a ``serve.dispatch`` straggler injection and assert the SLO trips —
   a violation is recorded naming the model and the violated window,
   and its post-mortem artifact exists on disk embedding the exemplar
   span trees.

Exit 0 clean; exit 1 with a named reason otherwise.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

READY_TIMEOUT_S = 240.0
DIMS = {"alpha": (24, 3), "beta": (32, 4)}


def _fail(proc, reason: str) -> int:
    print(f"serving gate: FAIL: {reason}", file=sys.stderr)
    if proc is not None:
        proc.terminate()
        try:
            out = proc.stdout.read() if proc.stdout else ""
        except Exception:
            out = ""
        if out:
            print(f"server output:\n{out}", file=sys.stderr)
    return 1


def _get(url: str, timeout: float = 5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as rsp:
            return rsp.status, rsp.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def main() -> int:
    # 0. the static precondition, BEFORE any jax/device work: the
    # request path the rest of this gate is about to exercise must be
    # statically clean — every call reachable from a @hotpath serving
    # entry point free of unallowlisted blocking/host-sync/IO/alloc
    # hazards, and every @published_by field on the swap discipline.
    # Cheap (AST-only, ~1s) and it fails the gate with named chains
    # instead of a mystery latency regression three phases later.
    import time as _time

    from keystone_tpu.analysis.hotpath import (
        HOTPATH_SCAN_BUDGET_S,
        scan_package,
    )

    t0 = _time.perf_counter()
    hotpath_hits = scan_package(os.path.join(REPO, "keystone_tpu"))
    scan_s = _time.perf_counter() - t0
    if hotpath_hits:
        for hit in hotpath_hits:
            print(f"  {hit['file']}:{hit['lineno']}: {hit['code']}: "
                  f"{hit['message']}", file=sys.stderr)
        return _fail(None, f"{len(hotpath_hits)} hot-path/publication "
                           "diagnostic(s) — fix or allowlist before "
                           "driving load")
    if scan_s > HOTPATH_SCAN_BUDGET_S:
        return _fail(None, f"hot-path scan took {scan_s:.2f}s > "
                           f"{HOTPATH_SCAN_BUDGET_S:.0f}s budget")
    print(f"serving gate: hot-path scan clean in {scan_s:.2f}s "
          f"(budget {HOTPATH_SCAN_BUDGET_S:.0f}s)")

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.utils.checkpoint import save_pipeline

    tmp = tempfile.mkdtemp(prefix="keystone-serving-gate-")
    specs = []
    for name, (d, k) in DIMS.items():
        r = np.random.RandomState(d)
        X = r.rand(96, d).astype(np.float32)
        Y = r.rand(96, k).astype(np.float32)
        fitted = LinearMapEstimator(lam=1e-3).with_data(
            ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()
        path = os.path.join(tmp, f"{name}.pkl")
        save_pipeline(fitted, path)
        specs.append(f"{name}={path}@{d}:float32")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "keystone_tpu", "serve", *specs,
         "--port", "0", "--hbm-budget", "64MiB", "--max-batch", "16",
         "--weight-dtype", "bf16"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)
    try:
        # 1. the bind line prints BEFORE admission. readline() alone
        # would block past the deadline if the server wedges before
        # its first line (jax init hang), so the wait is select-gated:
        # the hard timeout holds from the first byte, not the second.
        import select

        deadline = time.monotonic() + READY_TIMEOUT_S
        port = None
        while time.monotonic() < deadline:
            readable, _, _ = select.select(
                [proc.stdout], [], [],
                max(0.0, min(1.0, deadline - time.monotonic())))
            if not readable:
                if proc.poll() is not None:
                    return _fail(proc, "server exited before binding")
                continue
            line = proc.stdout.readline()
            if not line:
                return _fail(proc, "server exited before binding")
            print(f"  server: {line.rstrip()}")
            if line.startswith("serving on "):
                port = int(line.rsplit(":", 1)[1])
                break
        if port is None:
            return _fail(proc, "no 'serving on' line before timeout")
        base = f"http://127.0.0.1:{port}"

        # 2. /healthz is a REAL readiness gate: poll until 200, with
        # the not-ready phase (503 warming) logged when observed
        saw_warming = False
        while True:
            if time.monotonic() > deadline:
                return _fail(
                    proc, f"/healthz not ready in {READY_TIMEOUT_S:.0f}s")
            try:
                status, body = _get(base + "/healthz", timeout=2.0)
            except (urllib.error.URLError, OSError):
                time.sleep(0.2)
                continue
            if status == 503:
                saw_warming = True
                time.sleep(0.2)
                continue
            if status == 200:
                break
            return _fail(proc, f"/healthz returned {status}")
        print(f"serving gate: ready on port {port} "
              f"(warming observed: {saw_warming})")

        # 3. drive both models across >= 2 request shapes (buckets);
        # every response must echo a distinct trace id header
        sent = 0
        trace_ids = set()
        for name, (d, k) in DIMS.items():
            for n in (1, 3, 7, 11):  # buckets 8 and 16 on the sim mesh
                payload = json.dumps(
                    {"instances": [[0.5] * d] * n}).encode()
                req = urllib.request.Request(
                    f"{base}/predict/{name}", data=payload,
                    headers={"Content-Type": "application/json"})
                for _ in range(3):
                    with urllib.request.urlopen(req, timeout=30) as rsp:
                        out = json.loads(rsp.read())
                        trace_id = rsp.headers.get("X-Keystone-Trace")
                    preds = out.get("predictions")
                    if (out.get("rows") != n or len(preds) != n
                            or len(preds[0]) != k):
                        return _fail(
                            proc, f"bad predict response for {name} "
                                  f"n={n}: rows={out.get('rows')}")
                    if not trace_id:
                        return _fail(
                            proc, f"predict response for {name} n={n} "
                                  "carried no X-Keystone-Trace header")
                    trace_ids.add(trace_id)
                    sent += 1
        if len(trace_ids) != sent:
            return _fail(
                proc, f"trace ids not distinct: {len(trace_ids)} unique "
                      f"across {sent} requests")
        print(f"serving gate: {sent} requests served across "
              f"{len(DIMS)} models and 2 buckets "
              f"({len(trace_ids)} distinct trace ids)")

        # 4. the fence verdict: zero steady-state recompiles
        status, body = _get(base + "/metrics")
        if status != 200:
            return _fail(proc, f"/metrics returned {status}")
        metrics = {}
        for line in body.decode().splitlines():
            if line.startswith("#") or " " not in line:
                continue
            key, value = line.rsplit(" ", 1)
            try:
                metrics[key] = float(value)
            except ValueError:
                continue
        # counters gain a "_total" suffix in the exposition
        # (metrics.to_prometheus), so the dotted catalogue name
        # compile.unexpected_total scrapes as ..._total_total
        unexpected = metrics.get(
            "keystone_compile_unexpected_total_total", 0.0)
        if unexpected:
            return _fail(
                proc, f"{unexpected:.0f} fenced steady-state "
                      "recompile(s) — pad-to-bucket warmup missed a "
                      "program")
        served = metrics.get("keystone_serving_requests_total_total", 0.0)
        if served < sent:
            return _fail(
                proc, f"serving.requests_total={served:.0f} < "
                      f"{sent} requests the gate sent")

        # 5. a clean run's SLO surface: availability 1.0, no violations
        status, body = _get(base + "/slo")
        if status != 200:
            return _fail(proc, f"/slo returned {status}")
        slo = json.loads(body)
        if slo.get("availability") != 1.0:
            return _fail(
                proc, f"clean run reports availability "
                      f"{slo.get('availability')} != 1.0")
        if slo.get("violations"):
            return _fail(
                proc, f"clean run reports {len(slo['violations'])} SLO "
                      "violation(s)")
        print(f"serving gate: /slo clean (availability=1.0, "
              f"burn_rate={slo.get('burn_rate')})")
        print(f"serving gate: PASS subprocess phase "
              f"(requests={served:.0f}, unexpected recompiles=0)")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    # 6. the straggler phase, in process: inject a serve.dispatch
    # straggler under a tight policy and require the SLO plane to do
    # its whole job — trip, name the model and window, write the
    # post-mortem with exemplars embedded
    return _straggler_phase()


def _straggler_phase() -> int:
    import jax

    import numpy as np

    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability.slo import SloPolicy
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.resilience.faults import FaultPlan
    from keystone_tpu.serving import ServingPlane

    d, k = 16, 3
    r = np.random.RandomState(7)
    X = r.rand(96, d).astype(np.float32)
    Y = r.rand(96, k).astype(np.float32)
    fitted = LinearMapEstimator(lam=1e-3).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()
    policy = SloPolicy(latency_threshold_ms=50.0,
                       availability_target=0.95, window=8, min_count=8)
    plane = ServingPlane(max_batch=16, slo_policy=policy)
    plane.start()
    try:
        plane.admit("straggle", fitted,
                    jax.ShapeDtypeStruct((d,), np.float32))
        plane.predict("straggle", X[:4])  # clean warm request
        with FaultPlan(0) as fp:
            fp.add("serve.dispatch", kind="straggler", delay_s=0.2)
            for _ in range(10):
                plane.predict("straggle", X[:4], timeout_s=60.0)
        violations = plane.slo.state()["violations"]
        if not violations:
            print("serving gate: FAIL: injected serve.dispatch "
                  "straggler did not trip the SLO", file=sys.stderr)
            return 1
        v = violations[0]
        if v.get("model") != "straggle" or "window" not in v:
            print(f"serving gate: FAIL: violation names neither model "
                  f"nor window: {v}", file=sys.stderr)
            return 1
        pm_path = v.get("postmortem")
        if not pm_path or not os.path.exists(pm_path):
            print(f"serving gate: FAIL: SLO violation wrote no "
                  f"post-mortem artifact ({pm_path!r})", file=sys.stderr)
            return 1
        with open(pm_path) as f:
            pm = json.load(f)
        ctx = pm.get("context", {})
        if ctx.get("model") != "straggle":
            print("serving gate: FAIL: post-mortem context does not "
                  f"name the model: {ctx.get('model')!r}",
                  file=sys.stderr)
            return 1
        if not ctx.get("window", {}).get("count"):
            print("serving gate: FAIL: post-mortem context does not "
                  "carry the violated window", file=sys.stderr)
            return 1
        exemplars = ctx.get("exemplars") or []
        if not any(e.get("model") == "straggle" and e.get("phases_ms")
                   for e in exemplars):
            print("serving gate: FAIL: post-mortem embeds no exemplar "
                  "span tree for the slow model", file=sys.stderr)
            return 1
        print(f"serving gate: PASS (straggler tripped SLO: "
              f"availability={v['window']['availability']}, "
              f"post-mortem={os.path.basename(pm_path)}, "
              f"{len(exemplars)} exemplars)")
        return 0
    finally:
        plane.close()


if __name__ == "__main__":
    sys.exit(main())
