"""Train the in-tree averaged-perceptron POS tagger and ship the
artifact (VERDICT r3 next#9).

Trains on tests/resources/pos_train_corpus.txt (130 hand-tagged
sentences authored in-tree), evaluates on the HELD-OUT gold sample
tests/resources/pos_tagged_sample.txt, prints both numbers, and — when
the held-out accuracy beats the rule-based stand-in — writes
keystone_tpu/nodes/nlp/data/pos_perceptron.json.gz.

Usage: python tools/train_pos.py [--no-save]
"""
import sys

sys.path.insert(0, ".")

from keystone_tpu.nodes.nlp.corenlp import RuleBasedPosModel  # noqa: E402
from keystone_tpu.nodes.nlp.perceptron_pos import (  # noqa: E402
    AveragedPerceptronPosModel,
    read_tagged_file,
)

TRAIN = "tests/resources/pos_train_corpus.txt"
EVAL = "tests/resources/pos_tagged_sample.txt"


def accuracy(model, sentences):
    total = correct = 0
    for sent in sentences:
        words = [w for w, _ in sent]
        pred = model.best_sequence(words).tags
        total += len(sent)
        correct += sum(g == p for (_, g), p in zip(sent, pred))
    return correct / total


def main():
    train = read_tagged_file(TRAIN)
    heldout = read_tagged_file(EVAL)
    print(f"train: {len(train)} sentences, "
          f"{sum(len(s) for s in train)} tokens")
    print(f"eval (held out): {len(heldout)} sentences, "
          f"{sum(len(s) for s in heldout)} tokens")

    model = AveragedPerceptronPosModel.train(train, epochs=8)
    train_acc = accuracy(model, train)
    held_acc = accuracy(model, heldout)
    rule_acc = accuracy(RuleBasedPosModel(), heldout)
    print(f"perceptron train accuracy:    {train_acc:.4f}")
    print(f"perceptron held-out accuracy: {held_acc:.4f}")
    print(f"rule-based held-out accuracy: {rule_acc:.4f}")

    if held_acc <= rule_acc:
        print("NOT saving: perceptron does not beat the rule-based model")
        return 1
    if "--no-save" not in sys.argv:
        model.save()
        print("saved keystone_tpu/nodes/nlp/data/pos_perceptron.json.gz")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
