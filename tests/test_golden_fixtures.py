"""Tests against the reference's own golden fixtures (ported verbatim from
``/root/reference/src/test/resources``), as SURVEY §4 prescribes.

These anchor the implementation to *independent* artifacts rather than
same-author numpy ports:

- ``images/convolved.gantrycrane.csv`` — SciPy-generated convolution golden
  (reference ``ConvolverSuite.scala`` "convolutions should match scipy").
- ``aMat.csv``/``bMat.csv`` (+ ``-1class``/``Shuffled`` variants) — weighted
  least-squares fixtures (reference ``BlockWeightedLeastSquaresSuite.scala``).
- ``images/voc_codebook/{means.csv,variances.csv,priors}`` — the VOC GMM
  codebook (reference ``EncEvalSuite.scala``). Note: the reference's FV-sum
  golden (40.109097) needs ``images/feats.csv``, which is absent from the
  reference checkout itself, so that exact scalar is not reproducible here;
  the codebook still pins loader orientation and the FV feature layout.
"""
import os

import numpy as np
import pytest

RES = os.path.join(os.path.dirname(__file__), "resources")


def _load(name):
    return np.loadtxt(os.path.join(RES, name), delimiter=",", ndmin=2)


# ---------------------------------------------------------------- convolver


def test_convolver_matches_scipy_golden():
    """Reference ConvolverSuite.scala:100-137: convolving gantrycrane.png
    with the ascending 3x3x3 kernel must reproduce the SciPy golden CSV
    ((row, col, value) triplets of output channel 0) exactly.

    The golden is a true convolution (all three axes flipped); the
    Convolver correlates, so the filter row is the flipped kernel —
    the same role ``flipFilters = true`` plays in the reference.
    """
    from PIL import Image

    from keystone_tpu.nodes.images.core import Convolver

    im = np.asarray(
        Image.open(os.path.join(RES, "images", "gantrycrane.png"))
    ).astype(np.float32)
    raw = _load(os.path.join("images", "convolved.gantrycrane.csv"))
    H, W = int(raw[:, 0].max()) + 1, int(raw[:, 1].max()) + 1
    golden = np.zeros((H, W))
    golden[raw[:, 0].astype(int), raw[:, 1].astype(int)] = raw[:, 2]

    k = np.arange(27, dtype=np.float32).reshape(3, 3, 3)  # (dy, dx, c)
    filt = k[::-1, ::-1, ::-1].reshape(1, -1)
    conv = Convolver(filt, im.shape[0], im.shape[1], 3, normalize_patches=False)
    out = np.asarray(conv.apply(im))
    assert out.shape == (H, W, 1)
    np.testing.assert_allclose(out[..., 0], golden, rtol=1e-6, atol=1e-3)


# ------------------------------------------------------- weighted solvers


def _weighted_gradient(X, L, W, b, lam, mw):
    """Gradient of the mixture-weighted objective at (W, b), f64.

    Mirrors BlockWeightedLeastSquaresSuite.computeGradient: example i of
    class c gets weight negWt + mw/n_c on column c and negWt = (1-mw)/n
    elsewhere; grad = X^T ((XW + b - L) .* Wts) + lam * W.
    """
    X = X.astype(np.float64)
    L = L.astype(np.float64)
    n, k = L.shape
    y = np.argmax(L, axis=1)
    counts = np.bincount(y, minlength=k)
    neg = (1.0 - mw) / n
    wts = np.full((n, k), neg)
    wts[np.arange(n), y] = neg + mw / counts[y]
    resid = X @ W + b - L
    return X.T @ (resid * wts) + lam * W


@pytest.fixture(scope="module")
def ab_fixture():
    return _load("aMat.csv"), _load("bMat.csv")


def test_block_weighted_zero_gradient_on_fixture(ab_fixture):
    """BlockWeightedLeastSquaresSuite 'solution should have zero gradient'."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    A, B = ab_fixture
    model = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=10, lam=0.1, mixture_weight=0.3
    ).fit_arrays(A.astype(np.float32), B.astype(np.float32))
    g = _weighted_gradient(
        A, B, np.asarray(model.weights, np.float64),
        np.asarray(model.intercept, np.float64), 0.1, 0.3,
    )
    assert np.linalg.norm(g.ravel()) < 1e-2


def test_per_class_matches_block_weighted_on_fixture(ab_fixture):
    """'Per-class solver solution should match BlockWeighted solver'."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.nodes.learning.per_class_weighted import (
        PerClassWeightedLeastSquaresEstimator,
    )

    A, B = ab_fixture
    A32, B32 = A.astype(np.float32), B.astype(np.float32)
    wsq = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=5, lam=0.1, mixture_weight=0.3
    ).fit_arrays(A32, B32)
    pcs = PerClassWeightedLeastSquaresEstimator(
        block_size=4, num_iter=5, lam=0.1, mixture_weight=0.3
    ).fit_arrays(A32, B32)
    diff = np.linalg.norm(
        (np.asarray(wsq.weights) - np.asarray(pcs.weights)).ravel()
    )
    assert diff < 1e-4  # reference: 1e-6 in f64; f32 solves here
    assert abs(
        np.linalg.norm(np.asarray(wsq.intercept))
        - np.linalg.norm(np.asarray(pcs.intercept))
    ) < 1e-4


def test_block_weighted_block_size_not_dividing(ab_fixture):
    """'should work with nFeatures not divisible by blockSize' (12 % 5)."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.nodes.learning.per_class_weighted import (
        PerClassWeightedLeastSquaresEstimator,
    )

    A, B = ab_fixture
    A32, B32 = A.astype(np.float32), B.astype(np.float32)
    for est_cls in (
        BlockWeightedLeastSquaresEstimator,
        PerClassWeightedLeastSquaresEstimator,
    ):
        model = est_cls(
            block_size=5, num_iter=10, lam=0.1, mixture_weight=0.3
        ).fit_arrays(A32, B32)
        g = _weighted_gradient(
            A, B, np.asarray(model.weights, np.float64),
            np.asarray(model.intercept, np.float64), 0.1, 0.3,
        )
        assert np.linalg.norm(g.ravel()) < 1e-1


def test_block_weighted_one_class_fixture():
    """'should work with 1 class only' — must not crash, finite output."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    A = _load("aMat-1class.csv").astype(np.float32)
    B = _load("bMat-1class.csv").astype(np.float32)
    model = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=10, lam=0.1, mixture_weight=0.3
    ).fit_arrays(A, B)
    assert np.isfinite(np.asarray(model.weights)).all()
    assert np.isfinite(np.asarray(model.intercept)).all()


def test_shuffled_fixture_equals_grouped(ab_fixture):
    """'groupByClasses should work correctly': fitting on the shuffled
    fixture must give the same model as on the class-grouped one."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    A, B = ab_fixture
    As = _load("aMatShuffled.csv").astype(np.float32)
    Bs = _load("bMatShuffled.csv").astype(np.float32)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=4, num_iter=5, lam=0.1, mixture_weight=0.3
    )
    m_grouped = est.fit_arrays(A.astype(np.float32), B.astype(np.float32))
    m_shuffled = est.fit_arrays(As, Bs)
    np.testing.assert_allclose(
        np.asarray(m_grouped.weights), np.asarray(m_shuffled.weights),
        rtol=1e-4, atol=1e-4,
    )


# ------------------------------------------------------------ voc codebook


def test_voc_codebook_load_and_fisher_vector():
    """EncEvalSuite.scala:17-40: load the VOC GMM codebook (means stored
    (dim, centers) = (80, 256)) and run the Fisher Vector path on it."""
    from keystone_tpu.nodes.images.fisher_vector import FisherVector
    from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel

    gmm = GaussianMixtureModel.load(
        os.path.join(RES, "images", "voc_codebook", "means.csv"),
        os.path.join(RES, "images", "voc_codebook", "variances.csv"),
        os.path.join(RES, "images", "voc_codebook", "priors"),
    )
    assert gmm.dim == 80 and gmm.k == 256
    assert abs(gmm.weights.sum() - 1.0) < 1e-3
    assert (gmm.variances > 0).all()

    rng = np.random.RandomState(0)
    descriptors = (
        gmm.means.T[rng.randint(0, 256, 50)]
        + 0.1 * rng.randn(50, 80).astype(np.float32)
    ).astype(np.float32)
    fv = np.asarray(FisherVector(gmm).apply(descriptors.T))  # (D, nDesc) in
    assert fv.shape == (80, 2 * 256)
    assert np.isfinite(fv).all()


def test_gmm_data_fixture_two_cluster_recovery():
    """GaussianMixtureModelSuite.scala 'GMM Two Centers dataset 3': on
    gmm_data.txt with k=2, minClusterSize=1, stopTolerance=0, 30 iters,
    both means are ~(0,0), variances are {(1,25),(25,1)} (one component
    elongated per axis), and weights are ~0.5/0.5 — reference tolerances
    0.5 / 2.0 / 0.05."""
    from keystone_tpu.nodes.learning.gmm import GaussianMixtureModelEstimator

    X = np.loadtxt(os.path.join(RES, "gmm_data.txt")).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(
        k=2, min_cluster_size=1, stop_tolerance=0.0, max_iterations=30,
        seed=0,
    ).fit_matrix(X)
    means = gmm.means.T      # (k, d)
    variances = gmm.variances.T
    np.testing.assert_allclose(means, np.zeros((2, 2)), atol=0.5)
    want = np.array([[1.0, 25.0], [25.0, 1.0]])
    ok_order1 = np.allclose(variances, want, atol=2.0)
    ok_order2 = np.allclose(variances, want[::-1], atol=2.0)
    assert ok_order1 or ok_order2, f"variances {variances}"
    np.testing.assert_allclose(gmm.weights, [0.5, 0.5], atol=0.05)


def test_lda_iris_matches_published_eigenvectors():
    """LinearDiscriminantAnalysisSuite.scala:12-37: LDA(2) on standardized
    iris must reproduce the published discriminant directions (Raschka's
    LDA tutorial golden, an implementation-independent anchor), up to sign,
    at 1e-4."""
    from keystone_tpu.nodes.learning.classifiers import (
        LinearDiscriminantAnalysis,
    )
    from keystone_tpu.parallel.dataset import ArrayDataset

    rows = [
        l.strip()
        for l in open(os.path.join(RES, "iris.data"))
        if l.strip()
    ]
    X = np.array([[float(v) for v in r.split(",")[:-1]] for r in rows])
    y = np.array(
        [1 if r.endswith("setosa") else 2 if r.endswith("versicolor") else 3
         for r in rows]
    )
    Xs = (X - X.mean(0)) / X.std(0, ddof=1)
    model = LinearDiscriminantAnalysis(2)._fit(
        ArrayDataset.from_numpy(np.asarray(Xs, np.float32)),
        ArrayDataset.from_numpy(y.astype(np.int32)),
    )
    W = np.asarray(model.weights if hasattr(model, "weights") else model.W)
    W = W / np.linalg.norm(W, axis=0)
    major = np.array([-0.1498, -0.1482, 0.8511, 0.4808])
    minor = np.array([0.0095, 0.3272, -0.5748, 0.75])
    for col, want in ((W[:, 0], major), (W[:, 1], minor)):
        assert (
            np.allclose(col, want, atol=1e-4)
            or np.allclose(-col, want, atol=1e-4)
        ), f"got {col}, want ±{want}"


def test_dense_sift_descriptor_golden_gantrycrane():
    """Descriptor-level SIFT parity on the real gantrycrane.png fixture
    (VERDICT r2 next#4; reference anchor: VLFeatSuite golden tests).

    The golden (tests/resources/sift_golden_gantrycrane.npz, generated
    by tools/make_sift_golden.py — checked in for reproducibility) is an
    independent NumPy/SciPy implementation of the same vl_phow recipe:
    scipy convolve1d smoothing and generic bilinear map_coordinates
    sampling at every bin center, vs the production kernel's XLA convs
    and shared-fractional-offset strided-slice sampling. Asserts
    agreement in quantized units across all three scales, including the
    contrast-threshold zeroing and the min(512 v, 255) quantization."""
    from PIL import Image

    from keystone_tpu.ops.sift import CONTRAST_THRESHOLD, dense_sift

    g = np.load(os.path.join(RES, "sift_golden_gantrycrane.npz"))
    want = g["descriptors"].astype(np.float32)  # (128, N) quantized
    prenorm = g["prenorm"]
    step, bin_size, num_scales, scale_step = (int(v) for v in g["config"])

    rgb = np.asarray(
        Image.open(os.path.join(RES, "images/gantrycrane.png"))
        .convert("RGB"), np.float32) / 255.0
    gray = 0.299 * rgb[..., 0] + 0.587 * rgb[..., 1] + 0.114 * rgb[..., 2]

    got = np.asarray(dense_sift(
        gray, step=step, bin_size=bin_size,
        num_scales=num_scales, scale_step=scale_step))
    assert got.shape == want.shape, (got.shape, want.shape)

    # descriptors sitting within f32 noise of the contrast threshold can
    # legitimately flip between zeroed and kept; exclude the borderline
    solid = np.abs(prenorm - CONTRAST_THRESHOLD) > 1e-4
    assert solid.sum() > 3000  # the exclusion must stay a sliver
    diff = np.abs(got[:, solid] - want[:, solid])
    # f64 golden vs f32 production plus f16 golden storage puts values
    # within ~1 quantized unit; a real algorithm regression (grid shift,
    # window change, norm bug) moves many entries by tens of units
    assert diff.max() <= 2.0, diff.max()
    assert diff.mean() <= 0.15, diff.mean()

    # the contrast path is genuinely exercised: golden zeroes a visible
    # fraction, and the kernel zeroes exactly the same solid columns
    zero_want = (want[:, solid].sum(0) == 0)
    zero_got = (got[:, solid].sum(0) == 0)
    assert zero_want.sum() > 100
    assert np.array_equal(zero_want, zero_got)


@pytest.mark.slow
def test_dense_sift_high_precision_parity():
    """Device-mode parity gate for the shipped Precision.HIGH band
    matmuls (ADVICE medium#2): quantized descriptors at HIGH must stay
    within the golden envelope of a HIGHEST (6-pass, ~f32) reference on
    the same input. On CPU the precision flag is a no-op, so this is
    exact there; on TPU (where tier-2 runs @slow tests on device) it
    pins the "within envelope either way" claim the HIGH default rides
    on. The same gate runs in every tools/profile_imagenet.py profile."""
    import jax

    from keystone_tpu.ops.sift import dense_sift

    rng = np.random.RandomState(0)
    gray = rng.rand(160, 160).astype(np.float32)
    hi = np.asarray(dense_sift(gray, precision=jax.lax.Precision.HIGH))
    ref = np.asarray(dense_sift(gray, precision=jax.lax.Precision.HIGHEST))
    assert hi.shape == ref.shape
    diff = np.abs(hi - ref)
    assert diff.max() <= 2.0, diff.max()
    assert diff.mean() <= 0.15, diff.mean()
