"""Test harness: simulate an 8-device TPU mesh on CPU.

The analogue of the reference's ``LocalSparkContext`` trait
(``src/test/scala/pipelines/LocalSparkContext.scala:9-26``): the full
distributed code path (sharding, collectives, mesh solvers) runs in one
process over 8 virtual devices.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # tests never touch the real TPU
# Hermeticity: LeastSquaresEstimator loads the per-host cost-model
# calibration artifact (~/.keystone_tpu/...) when present; a machine
# that has run tools/calibrate_cost_model.py must not change
# shipped-default cost-model test outcomes. Point the lookup at a
# nonexistent path unless a test overrides it explicitly.
os.environ["KEYSTONE_COST_CALIBRATION"] = (
    "/nonexistent/keystone-test-calibration.json")
# Crash post-mortems (observability/postmortem.py) default to
# ~/.keystone_tpu/postmortems; tests deliberately trigger the failure
# paths that dump them, so point the dumps at a throwaway temp dir —
# a test run must not litter (or depend on) the host's artifact dir.
import tempfile  # noqa: E402

os.environ.setdefault(
    "KEYSTONE_POSTMORTEM_DIR",
    tempfile.mkdtemp(prefix="keystone-test-postmortems-"))
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter start (axon TPU
# plugin), so the env vars above can be too late; force via config too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end test")


@pytest.fixture(autouse=True)
def fresh_env():
    """Reset global pipeline state between tests (the reference stops and
    recreates its SparkContext per test)."""
    from keystone_tpu.nodes.learning.least_squares import (
        clear_calibration_cache,
    )
    from keystone_tpu.observability.compilelog import (
        reset_compile_observatory,
    )
    from keystone_tpu.observability.metrics import MetricsRegistry
    from keystone_tpu.observability.numerics import reset_health_series
    from keystone_tpu.observability.reqtrace import reset_exemplars
    from keystone_tpu.observability.timeline import reset_flight_recorder
    from keystone_tpu.workflow.env import PipelineEnv

    PipelineEnv.reset()
    MetricsRegistry.reset()
    reset_flight_recorder()
    reset_compile_observatory()
    reset_health_series()
    reset_exemplars()
    clear_calibration_cache()
    yield
    PipelineEnv.reset()
    MetricsRegistry.reset()
    reset_flight_recorder()
    reset_compile_observatory()
    reset_health_series()
    reset_exemplars()
    clear_calibration_cache()


@pytest.fixture
def mesh8():
    from keystone_tpu.parallel.mesh import make_mesh, mesh_scope

    with mesh_scope(make_mesh(jax.devices()[:8])) as m:
        yield m
