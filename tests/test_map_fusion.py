"""Map-chain fusion (workflow/optimizer/fusion.py): linear chains of
default-semantics transformers collapse into one jitted node, without
changing results; boundary nodes (multi-consumer, sinks, Cacher,
apply_dataset overriders, host stages) do not fuse."""
import numpy as np
import pytest

from keystone_tpu.nodes.util import MaxClassifier
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.workflow.common import Cacher
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.optimizer.fusion import (
    FusedTransformer,
    MapFusionRule,
)
from keystone_tpu.workflow.optimizer.rule import Batch, FixedPoint, Optimizer
from keystone_tpu.workflow.transformer import (
    HostTransformer,
    LambdaTransformer,
    Transformer,
)


def t(fn, name):
    return LambdaTransformer(fn, name)


class FusionOnly(Optimizer):
    @property
    def batches(self):
        return [Batch("fuse", FixedPoint(100), [MapFusionRule()])]


def fuse(graph):
    return FusionOnly().execute(graph)


def test_chain_fuses_to_one_node():
    pipe = (t(lambda x: x + 1, "a") >> t(lambda x: x * 2, "b")
            >> t(lambda x: x - 3, "c"))
    g = fuse(pipe.graph)
    assert len(g.nodes) == 1
    (op,) = [g.get_operator(n) for n in g.nodes]
    assert isinstance(op, FusedTransformer)
    assert [s.label() for s in op.stages] == ["a", "b", "c"]
    # semantics preserved, batch and datum paths
    ds = ArrayDataset.from_numpy(np.arange(8.0).reshape(8, 1))
    fitted = pipe.fit()
    out = np.asarray(fitted.apply(ds).get().numpy())
    np.testing.assert_allclose(out, (np.arange(8.0).reshape(8, 1) + 1) * 2 - 3)
    assert float(fitted.apply_datum(np.array([5.0])).get()) == (5 + 1) * 2 - 3


def test_multi_consumer_not_fused():
    """After CSE merges the shared prefix (as DefaultOptimizer does
    before fusing), the two-consumer node must NOT fuse into either
    branch — that would recompute it."""
    from keystone_tpu.workflow.optimizer.rules import EquivalentNodeMergeRule
    from keystone_tpu.workflow.pipeline import Pipeline

    class CseThenFuse(Optimizer):
        @property
        def batches(self):
            return [
                Batch("cse", FixedPoint(100), [EquivalentNodeMergeRule()]),
                Batch("fuse", FixedPoint(100), [MapFusionRule()]),
            ]

    a = t(lambda x: x + 1, "a").to_pipeline()
    b = a >> t(lambda x: x * 2, "b")
    c = a >> t(lambda x: x * 3, "c")
    both = Pipeline.gather([b, c])
    g = CseThenFuse().execute(both.graph)
    labels = sorted(op.label() for op in
                    (g.get_operator(n) for n in g.nodes))
    assert "a" in labels  # shared prefix kept as its own node
    assert "b" in labels and "c" in labels


def test_cacher_breaks_chain():
    pipe = (t(lambda x: x + 1, "a") >> Cacher("mid")
            >> t(lambda x: x * 2, "b"))
    g = fuse(pipe.graph)
    kinds = [type(g.get_operator(n)).__name__ for n in g.nodes]
    assert "Cacher" in kinds
    assert len(g.nodes) == 3  # nothing fused across the cache point


def test_host_transformer_not_fused():
    class H(HostTransformer):
        def apply(self, x):
            return x + 1

    pipe = t(lambda x: x * 2, "a") >> H()
    g = fuse(pipe.graph)
    assert len(g.nodes) == 2


def test_fused_eq_key_enables_cse():
    # same underlying stage objects -> equal keys (CSE can merge);
    # different stages -> different keys
    a, b, c = t(lambda x: x, "a"), t(lambda x: x, "b"), t(lambda x: x, "c")
    assert (FusedTransformer([a, b]).eq_key()
            == FusedTransformer([a, b]).eq_key())
    assert (FusedTransformer([a, b]).eq_key()
            != FusedTransformer([a, c]).eq_key())


def test_fused_instance_reused_across_binds():
    """The optimizer re-runs per bind; the SAME FusedTransformer object
    (and so its warm jit cache) must come back for the same chain."""
    from keystone_tpu.workflow.optimizer.fusion import fused_transformer

    a, b = t(lambda x: x + 1, "a"), t(lambda x: x * 2, "b")
    assert fused_transformer([a, b]) is fused_transformer([a, b])

    pipe = a >> b
    ds = ArrayDataset.from_numpy(np.ones((4, 1)))
    ops1 = _fused_ops_of_bound(pipe, ds)
    ops2 = _fused_ops_of_bound(pipe, ds)
    assert ops1 and ops1 == ops2  # same instances, not fresh copies


def _fused_ops_of_bound(pipe, ds):
    bound = pipe.apply(ds)
    bound.get()
    g = bound._executor.graph  # optimized graph
    ops = [g.get_operator(n) for n in sorted(g.nodes, key=lambda n: n.id)]
    return [op for op in ops if isinstance(op, FusedTransformer)]


def test_default_optimizer_matches_noop_end_to_end():
    """Full app parity: default optimizer (with fusion) == NoOpOptimizer."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.stats import StandardScaler
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.workflow.optimizer.default import NoOpOptimizer

    rng = np.random.RandomState(0)
    X = rng.randn(64, 12).astype(np.float32)
    y = rng.randint(0, 4, 64)
    ds = ArrayDataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromIntLabels(4).apply_dataset(
        ArrayDataset.from_numpy(y.astype(np.int32)))

    def build():
        feat = (t(lambda x: x * 2.0, "scale")
                >> t(lambda x: x + 1.0, "shift")
                >> t(lambda x: np.tanh(1) * x, "gain"))
        return (feat.and_then(StandardScaler(), ds)
                .and_then(BlockLeastSquaresEstimator(8, 1, 0.1), ds, labels)
                >> MaxClassifier())

    env = PipelineEnv.get_or_create()
    preds = {}
    for name, opt in (("noop", NoOpOptimizer()), ("default", None)):
        env.clear_state()
        if opt is not None:
            env.set_optimizer(opt)
        else:
            from keystone_tpu.workflow.optimizer.default import (
                DefaultOptimizer,
            )

            env.set_optimizer(DefaultOptimizer())
        fitted = build().fit()
        preds[name] = np.asarray(fitted.apply(ds).get().numpy())
    np.testing.assert_array_equal(preds["noop"], preds["default"])


def test_fitted_pipeline_fuses_model_chain():
    """After fit(), the transformer-only graph fuses scaler-like chains
    downstream of the (formerly) estimator node."""
    pipe = (t(lambda x: x + 1, "a")
            >> t(lambda x: x * 2, "b")
            >> t(lambda x: x - 1, "c")
            >> t(lambda x: x / 2, "d"))
    fitted = pipe.fit()
    bound = fitted.apply(ArrayDataset.from_numpy(np.ones((4, 2))))
    out = np.asarray(bound.get().numpy())
    np.testing.assert_allclose(out, ((1 + 1) * 2 - 1) / 2 * np.ones((4, 2)))
    fused = _fused_ops_of_bound(fitted.to_pipeline(),
                                ArrayDataset.from_numpy(np.ones((4, 2))))
    assert len(fused) == 1 and len(fused[0].stages) == 4


def test_gather_branches_fuse_to_one_node():
    """gather(N fusable branches) + the downstream combiner collapse
    into ONE node (GatherFusionRule + MapFusionRule), with identical
    batch and datum results."""
    import jax.numpy as jnp

    from keystone_tpu.nodes.util import VectorCombiner
    from keystone_tpu.workflow.optimizer.default import DefaultOptimizer
    from keystone_tpu.workflow.optimizer.fusion import (
        FusedGatherTransformer,
    )
    from keystone_tpu.workflow.pipeline import Pipeline

    branches = [
        t(lambda x, s=s: x * s, f"scale{s}") >> t(jnp.sin, f"sin{s}")
        for s in (1.0, 2.0, 3.0)
    ]
    pipe = Pipeline.gather(branches) >> VectorCombiner()

    g = DefaultOptimizer().execute(pipe.graph)
    assert len(g.nodes) == 1
    (op,) = [g.get_operator(n) for n in g.nodes]
    assert isinstance(op, FusedTransformer)
    assert any(isinstance(s, FusedGatherTransformer) for s in op.stages)

    X = np.linspace(0.0, 1.0, 12).reshape(6, 2).astype(np.float32)
    expect = np.concatenate([np.sin(X * s) for s in (1.0, 2.0, 3.0)], axis=-1)
    fitted = pipe.fit()
    out = np.asarray(fitted.apply(ArrayDataset.from_numpy(X)).get().numpy())
    np.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-6)
    one = np.asarray(fitted.apply_datum(X[2]).get())
    np.testing.assert_allclose(one, expect[2], rtol=1e-6, atol=1e-6)


def test_gather_host_branch_not_fused():
    """A gather with a non-fusable (host-stage) branch keeps its node
    structure; only all-fusable same-upstream gathers collapse."""
    from keystone_tpu.nodes.util import VectorCombiner
    from keystone_tpu.workflow.optimizer.fusion import GatherFusionRule
    from keystone_tpu.workflow.pipeline import Pipeline

    class HostAdd(HostTransformer):
        def apply(self, x):
            return x + 1.0

    host = HostAdd()
    dev = t(lambda x: x * 2.0, "dev")
    g = (Pipeline.gather([host, dev]) >> VectorCombiner()).graph
    assert len(GatherFusionRule().apply(g).nodes) == len(g.nodes)

    # all-fusable control: the same shape with two device branches fuses
    g2 = (Pipeline.gather([t(lambda x: x + 1.0, "a"), dev])
          >> VectorCombiner()).graph
    assert len(GatherFusionRule().apply(g2).nodes) < len(g2.nodes)


def test_batched_jit_shared_across_equal_instances():
    """Equal-config node instances built in later pipelines reuse the
    SAME jitted callable (the warm XLA executable), so a rebuilt/refit
    pipeline does not recompile its transformer stages."""
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromIntLabels

    a = ClassLabelIndicatorsFromIntLabels(7)
    b = ClassLabelIndicatorsFromIntLabels(7)
    c = ClassLabelIndicatorsFromIntLabels(9)
    assert a is not b
    assert a._batched() is b._batched()
    assert a._batched() is not c._batched()


def _double_for_vmap_cache_test(x):
    return x * 2.0


def test_masked_vmap_jit_cached_per_function():
    """ArrayDataset.map with a stable-identity function reuses one jit
    wrapper instead of building (and compiling) a fresh one per call;
    per-call fresh objects (lambdas/locals) are NOT cached, so they
    can't accumulate dead entries."""
    from keystone_tpu.parallel import dataset as ds_mod
    from keystone_tpu.parallel.dataset import ArrayDataset

    ds = ArrayDataset.from_numpy(np.arange(16, dtype=np.float32))
    ds.map(_double_for_vmap_cache_test)
    jfn = ds_mod._VMAP_JIT_CACHE.get(_double_for_vmap_cache_test)
    assert jfn is not None
    ds.map(_double_for_vmap_cache_test)
    assert ds_mod._VMAP_JIT_CACHE.get(_double_for_vmap_cache_test) is jfn

    before = len(ds_mod._VMAP_JIT_CACHE)
    ds.map(lambda x: x * 3.0)
    ds.map(lambda x: x * 3.0)
    assert len(ds_mod._VMAP_JIT_CACHE) == before


def test_app_rebuild_compiles_nothing(mesh8):
    """End-to-end pin of PERFORMANCE.md rule 5: rebuilding and refitting
    an app in the same process must reuse every compiled program."""
    import io
    import logging

    import jax

    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.pipelines.images.mnist.random_fft import (
        MnistRandomFFTConfig,
        run,
    )
    from keystone_tpu.workflow.env import PipelineEnv

    rng = np.random.RandomState(0)
    protos = rng.rand(10, 784).astype(np.float32)

    def split(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 10, n)
        X = np.clip(protos[y] + 0.3 * r.randn(n, 784), 0, 1).astype(
            np.float32)
        return LabeledData(ArrayDataset.from_numpy(X),
                           ArrayDataset.from_numpy(y.astype(np.int32)))

    # well-posed sizes (n > d): an underdetermined solve at tiny lam
    # NaNs out in f32 and would test the NaN-token path, not reuse
    train, test = split(1024, 1), split(128, 2)
    config = MnistRandomFFTConfig(num_ffts=1, block_size=512, lam=1e-2)
    run(config, train=train, test=test)  # warm build
    PipelineEnv.get_or_create().clear_state()

    jax.config.update("jax_log_compiles", True)
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    loggers = [logging.getLogger("jax._src.interpreters.pxla"),
               logging.getLogger("jax._src.dispatch")]
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.WARNING)
    try:
        run(config, train=train, test=test)
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    compiles = [ln for ln in buf.getvalue().splitlines() if "Compiling" in ln]
    assert not compiles, compiles


def test_fused_chain_shares_program_across_refits(mesh8):
    # Fitted chains thread params as jit ARGUMENTS: two fused
    # scaler >> linear-model chains with DIFFERENT fitted content must
    # share ONE compiled program (content-free key) and still produce
    # their own correct outputs.
    import importlib

    import jax.numpy as jnp

    from keystone_tpu.nodes.learning.linear import LinearMapper
    from keystone_tpu.nodes.stats import StandardScalerModel
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.optimizer.fusion import FusedTransformer

    tmod = importlib.import_module("keystone_tpu.workflow.transformer")
    tmod.clear_jit_cache()

    rng = np.random.RandomState(0)
    X = rng.randn(32, 16).astype(np.float32)
    ds = ArrayDataset.from_numpy(X)

    def chain(seed):
        r = np.random.RandomState(seed)
        mean = r.randn(16).astype(np.float32)
        std = (0.5 + r.rand(16)).astype(np.float32)
        W = r.randn(16, 4).astype(np.float32)
        b = r.randn(4).astype(np.float32)
        fused = FusedTransformer(
            [StandardScalerModel(mean, std), LinearMapper(W, intercept=b)])
        want = ((X - mean) / std) @ W + b
        return fused, want

    f1, want1 = chain(1)
    got1 = np.asarray(f1._batched()(jnp.asarray(X)))
    n_after_first = len(tmod._JIT_CACHE)
    f2, want2 = chain(2)
    got2 = np.asarray(f2._batched()(jnp.asarray(X)))
    assert np.allclose(got1, want1, atol=1e-4)
    assert np.allclose(got2, want2, atol=1e-4)
    # second chain (different content) added NO new program
    assert len(tmod._JIT_CACHE) == n_after_first, (
        n_after_first, len(tmod._JIT_CACHE))


def test_config_shim_keeps_scalar_config():
    """ADVICE r3: 0-d numpy scalars are config, not fitted state — the
    shim must keep them (coerced to Python scalars) or cached fused
    programs AttributeError at trace time for numpy-configured nodes."""
    from keystone_tpu.nodes.learning.linear import LinearMapper
    from keystone_tpu.workflow.transformer import config_shim

    node = LinearMapper(np.eye(2, dtype=np.float32))
    node.alpha = np.float32(0.25)          # 0-d numpy scalar config
    node.names = ("a", "b")                # plain config survives
    import jax.numpy as jnp
    node.learned_scale = jnp.float32(2.0).reshape(())  # 0-d DEVICE array: fitted, must drop
    node.beta = np.array(1.5, dtype=np.float64)  # 0-d HOST ndarray config (ADVICE r4)
    shim = config_shim(node)
    assert shim.alpha == 0.25 and isinstance(shim.alpha, float)
    assert shim.beta == 1.5 and isinstance(shim.beta, float)
    assert shim.names == ("a", "b")
    assert not hasattr(shim, "learned_scale")
    assert not hasattr(shim, "weights") or getattr(
        shim, "weights", None) is None or np.ndim(shim.weights) == 0


def test_lru_memo_rejects_none_and_is_locked():
    """ADVICE r3: stored None used to read as a miss; now put() refuses
    None and get/put are lock-protected for the loader thread pools."""
    from keystone_tpu.utils.lru import LruMemo

    memo = LruMemo(max_entries=2)
    with pytest.raises(ValueError):
        memo.put("k", None)
    memo.put("a", 1)
    memo.put("b", 2)
    assert memo.get("a") == 1
    memo.put("c", 3)  # evicts LRU ("b": "a" was touched)
    assert memo.get("b") is None and memo.get("a") == 1 and memo.get("c") == 3


def test_fused_prefix_chain_hits_saved_state(mesh8):
    """Regression for the CHANGES.md PR 1 cache-miss: prefixes are
    canonical under map fusion, so a pipeline whose pre-estimator chain
    fuses still re-matches its saved fitted state when the SAME pipeline
    is rebuilt from scratch (SavedStateLoadRule hits, no refit)."""
    from keystone_tpu.observability.metrics import MetricsRegistry
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.estimator import LambdaEstimator

    fits = []

    def fit_fn(ds):
        fits.append(1)
        m = float(np.mean(ds.numpy()))
        return t(lambda x, m=m: x - m, "center")

    est = LambdaEstimator(fit_fn, "E")
    a, b = t(lambda x: x + 1.0, "a"), t(lambda x: x * 2.0, "b")
    train = ArrayDataset.from_numpy(
        np.arange(8.0).reshape(8, 1).astype(np.float32), tag="fused-prefix")

    out1 = (a >> b).and_then(est, train)(train).get().numpy()
    assert len(fits) == 1
    # rebuild from scratch: raw graph is unfused, saved state was keyed
    # on the executor's FUSED graph — canonical prefixes must match
    out2 = (a >> b).and_then(est, train)(train).get().numpy()
    assert len(fits) == 1, "fused pre-estimator chain missed saved state"
    np.testing.assert_allclose(out1, out2)
    hits = MetricsRegistry.get_or_create().counter(
        "executor.prefix_hits").value
    assert hits >= 1


def test_fused_gather_prefix_hits_saved_state(mesh8):
    """Gather-fusion variant (the MNIST/TIMIT shape): branches + gather
    collapse into one FusedGatherTransformer, and the estimator
    downstream still re-matches saved state across rebuilds."""
    from keystone_tpu.nodes.util import VectorCombiner
    from keystone_tpu.observability.metrics import MetricsRegistry
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.estimator import LambdaEstimator
    from keystone_tpu.workflow.pipeline import Pipeline

    fits = []

    def fit_fn(ds):
        fits.append(1)
        return t(lambda x: x, "id")

    # stages are hoisted: LambdaTransformer's identity is its function
    # object, and a fresh lambda per build would change the prefix
    # legitimately (different node content, not a fusion artifact)
    g1, g2 = t(lambda x: x + 1.0, "g1"), t(lambda x: x * 2.0, "g2")
    combiner, est = VectorCombiner(), LambdaEstimator(fit_fn, "E")

    def build():
        feat = Pipeline.gather([g1, g2]) >> combiner
        return feat.and_then(est, train)

    train = ArrayDataset.from_numpy(
        np.arange(8.0).reshape(8, 1).astype(np.float32),
        tag="fused-gather-prefix")
    out1 = build()(train).get().numpy()
    assert len(fits) == 1
    out2 = build()(train).get().numpy()
    assert len(fits) == 1, "fused gather chain missed saved state"
    np.testing.assert_allclose(out1, out2)
