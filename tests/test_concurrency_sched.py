"""Deterministic-interleaving regression schedules + seeded stress
(tests/sched.py harness over utils.guarded's instrumented primitives).

Each historical race carries a schedule that REPRODUCES it on an
un-fixed offender copy and passes on shipped HEAD:

* PR 4: ``PipelineTrace.record_resilience`` read-modify-write on the
  stats dict from concurrent ingest worker threads (caught by review
  then; machine-found and schedule-pinned now).
* PR 3: the producer/consumer residency-ledger close race — a consumer
  closing the shared ledger while the producer is still mid-stage
  permanently inflates it (fixed by join-before-close + the producer's
  self-close; the schedule shows the un-fixed teardown leaking).

Plus: the ``_CAST_JIT_CACHE`` check-then-act double-create fixed this
PR, TracedLock/TracedSemaphore semantics and contention telemetry, the
seeded chaos fuzz of the prefetcher's slot-gated staging (bounded here,
200 seeds under ``slow``), and the interpreter-exit teardown subprocess
pin (leaked non-daemon H2D pool threads)."""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import sched as sched_mod
from sched import DeterministicScheduler, ScheduleError, chaos

from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.observability.trace import PipelineTrace
from keystone_tpu.utils import guarded
from keystone_tpu.utils.guarded import TracedLock, TracedSemaphore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_hook():
    """The yield hook is process-global: never leak one across tests."""
    yield
    guarded.set_sched_hook(None)


def test_harness_is_ours_not_stdlib_sched():
    # tests/sched.py shadows the (practically unused) stdlib `sched`
    # module inside the test tree; make the shadowing explicit so a
    # future import surprise fails here, not somewhere weird
    assert hasattr(sched_mod, "DeterministicScheduler")


# -- scheduler basics --------------------------------------------------------

def test_scripted_picks_order_is_deterministic():
    log = []

    def worker(tag, sched):
        sched.yield_point(f"{tag}.mid")
        log.append(tag)

    sched = DeterministicScheduler(picks=["b", "b", "a", "a"])
    sched.spawn(worker, "a", sched, name="a")
    sched.spawn(worker, "b", sched, name="b")
    with sched:
        sched.run()
    assert log == ["b", "a"]


def test_seeded_schedules_replay_exactly():
    def run_once(seed):
        log = []

        def worker(tag, sched):
            for i in range(3):
                sched.yield_point(f"{tag}.{i}")
                log.append(f"{tag}{i}")

        sched = DeterministicScheduler(seed=seed)
        for t in ("a", "b", "c"):
            sched.spawn(worker, t, sched, name=t)
        with sched:
            sched.run()
        return log

    assert run_once(7) == run_once(7)
    # different seeds explore different interleavings (not a proof,
    # but 3 threads x 3 yields has 1680 orders; identical would be odd)
    assert any(run_once(7) != run_once(s) for s in range(1, 6))


def test_unregistered_threads_pass_through_yield_points():
    sched = DeterministicScheduler()
    done = threading.Event()

    def outsider():
        sched.yield_point("outsider")  # must be a no-op
        done.set()

    t = threading.Thread(target=outsider)
    t.start()
    t.join(timeout=5)
    assert done.is_set()


def test_spawned_thread_exception_propagates():
    def boom(sched):
        sched.yield_point("pre")
        raise ValueError("from schedule")

    sched = DeterministicScheduler()
    sched.spawn(boom, sched, name="boom")
    with sched, pytest.raises(ValueError, match="from schedule"):
        sched.run()


def test_traced_lock_waiters_park_instead_of_blocking():
    """A thread blocked on a TracedLock held by a parked sibling parks
    at a yield point — the property that keeps the scheduler live (a
    plain Lock here would stall the schedule and raise)."""
    lock = TracedLock("t.park")
    order = []

    def holder(sched):
        with lock:
            sched.yield_point("holding")
            order.append("holder")

    def waiter():
        with lock:
            order.append("waiter")

    sched = DeterministicScheduler(picks=["h", "h", "w", "h"])
    sched.spawn(holder, sched, name="h")
    sched.spawn(waiter, name="w")
    with sched:
        sched.run()
    assert order == ["holder", "waiter"]


# -- historical race 1: PR 4 record_resilience RMW ---------------------------

class _YieldingDict(dict):
    """Marks the racy read inside the RMW window as a yield point (the
    loom-style 'atomic access is a scheduling point' trick) — the SAME
    instrumented dict backs the offender and the shipped code, so the
    only difference under the schedule is the lock."""

    def __init__(self, sched):
        super().__init__()
        self._sched = sched

    def get(self, key, default=None):
        value = super().get(key, default)
        # park AFTER the read, INSIDE the read-modify-write window:
        # the value this thread will add to is already fetched
        self._sched.yield_point("stats.get")
        return value


class _UnfixedTrace(PipelineTrace):
    """The pre-PR-4 record_resilience: same body, no lock."""

    def record_resilience(self, entry):
        event = str(entry.get("event", "other"))
        self.resilience_stats[event] = (
            self.resilience_stats.get(event, 0) + 1)
        self.resilience.append(entry)


_RACE_SCHEDULE = ["a", "b"] * 12  # interleave every yield point


def _drive_two_records(trace_obj, picks):
    sched = DeterministicScheduler(picks=list(picks))
    trace_obj.resilience_stats = _YieldingDict(sched)
    for name in ("a", "b"):
        sched.spawn(trace_obj.record_resilience, {"event": "retry"},
                    name=name)
    with sched:
        sched.run()
    return int(trace_obj.resilience_stats.get("retry", 0))


def test_pr4_rmw_race_reproduces_on_unfixed_copy():
    # both threads read 0 before either writes: one update is lost —
    # deterministically, under the scripted interleaving
    assert _drive_two_records(_UnfixedTrace(), _RACE_SCHEDULE) == 1


def test_pr4_rmw_race_fixed_on_head():
    # same schedule, same instrumented dict — the TracedLock serializes
    # the RMW, so the count is exact
    assert _drive_two_records(PipelineTrace(), _RACE_SCHEDULE) == 2


def test_pr4_fix_survives_seeded_random_schedules():
    for seed in range(40):
        tr = PipelineTrace()
        sched = DeterministicScheduler(seed=seed)
        tr.resilience_stats = _YieldingDict(sched)
        for name in ("a", "b", "c"):
            sched.spawn(tr.record_resilience, {"event": "retry"},
                        name=name)
        with sched:
            sched.run()
        assert tr.resilience_stats.get("retry") == 3, f"seed {seed}"
        assert tr.resilience_stats["retry"] == len(tr.resilience)


# -- historical race 2: PR 3 producer/consumer ledger close ------------------

def _ledger():
    from keystone_tpu.parallel.streaming import _IterLedger, _Residency

    return _Residency(), _IterLedger()


_CLOSE_SCHEDULE = ["consumer", "consumer", "producer"] + ["producer"] * 8


def test_pr3_ledger_close_race_reproduces_on_unfixed_teardown():
    """The pre-round-2 teardown: the consumer closes the shared ledger
    WITHOUT joining the producer and the producer never self-closes —
    a stage() landing after close() inflates the shared residency
    forever (the next epoch's budget assert would trip spuriously)."""
    res, it = _ledger()

    def producer(sched):
        sched.yield_point("mid-stage")  # the producer is inside _stage
        res.stage(it, 100.0)

    def consumer():
        res.close(it)  # un-fixed: no join, no producer self-close

    sched = DeterministicScheduler(picks=list(_CLOSE_SCHEDULE))
    sched.spawn(producer, sched, name="producer")
    sched.spawn(consumer, name="consumer")
    with sched:
        sched.run()
    assert res.live() == 100.0  # leaked — the bug, reproduced


def test_pr3_ledger_close_fixed_shape_survives_both_orders():
    """The shipped teardown contract (producer self-closes when it
    observes stop; close() is idempotent) drains the ledger under the
    exact leaking schedule AND the benign one."""
    for picks in (_CLOSE_SCHEDULE, ["producer"] * 8 + ["consumer"] * 4):
        res, it = _ledger()
        stop = threading.Event()

        def producer(sched):
            sched.yield_point("mid-stage")
            res.stage(it, 100.0)
            if stop.is_set():
                res.close(it)  # the shipped produce() finally

        def consumer():
            stop.set()
            res.close(it)

        sched = DeterministicScheduler(picks=list(picks))
        sched.spawn(producer, sched, name="producer")
        sched.spawn(consumer, name="consumer")
        with sched:
            sched.run()
        assert res.live() == 0.0, picks


def test_pr3_real_stream_early_exit_drains_ledger(mesh8):
    """Shipped end-to-end: breaking out of a real prefetched stream
    leaves zero residual residency, under seeded chaos at every
    lock/semaphore operation."""
    from keystone_tpu.parallel.streaming import StreamingDataset

    X = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    for seed in range(8):
        stream = StreamingDataset.from_numpy(X, chunk_size=16, mesh=mesh8)
        with chaos(seed=seed):
            for i, chunk in enumerate(stream.chunks()):
                if i == 1:
                    break  # early exit with chunks still staged
        deadline = time.monotonic() + 5.0
        while stream.buffered_nbytes() and time.monotonic() < deadline:
            time.sleep(0.01)  # producer may still be unwinding
        assert stream.buffered_nbytes() == 0.0, f"seed {seed}"


# -- this PR's fix: unlocked Histogram RMW -----------------------------------

class _HistogramRmwReplica:
    """The Histogram.observe count update, desugared (`+= 1` IS
    read-then-write) with the racy window marked — un-fixed (no lock)
    vs fixed (the shipped locked structure, with a TracedLock so the
    waiter parks for the scheduler)."""

    def __init__(self, locked):
        self.count = 0
        self.locked = locked
        self._lock = TracedLock("hist.replica")

    def observe(self, sched):
        if self.locked:
            with self._lock:
                c = self.count
                sched.yield_point("rmw")
                self.count = c + 1
        else:
            c = self.count
            sched.yield_point("rmw")
            self.count = c + 1


def _drive_observes(locked, picks):
    h = _HistogramRmwReplica(locked)
    sched = DeterministicScheduler(picks=list(picks))
    for name in ("a", "b"):
        sched.spawn(h.observe, sched, name=name)
    with sched:
        sched.run()
    return h.count


def test_histogram_rmw_race_reproduces_unlocked():
    assert _drive_observes(False, ["a", "b"] * 8) == 1  # lost update


def test_histogram_rmw_fixed_shape_survives():
    assert _drive_observes(True, ["a", "b"] * 8) == 2
    for seed in range(20):
        h = _HistogramRmwReplica(True)
        sched = DeterministicScheduler(seed=seed)
        for name in ("a", "b", "c"):
            sched.spawn(h.observe, sched, name=name)
        with sched:
            sched.run()
        assert h.count == 3, f"seed {seed}"


def test_shipped_histogram_exact_under_thread_hammer():
    from keystone_tpu.observability.metrics import Histogram

    h = Histogram("hammer")
    n, per = 8, 5000
    threads = [threading.Thread(
        target=lambda: [h.observe(1.0) for _ in range(per)])
        for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert h.count == n * per
    assert h.total == float(n * per)


# -- this PR's fix: quarantine manifest write racing state() -----------------

def test_quarantine_manifest_write_race(tmp_path):
    """Pre-PR-7, the JSONL manifest append ran OUTSIDE the lock: a
    checkpoint's state() snapshot could count a record whose manifest
    line was not yet written (replayed resume then trusts a manifest
    missing a known-bad record). The schedule reproduces the
    inconsistency on the un-fixed copy; shipped HEAD holds
    state-never-leads-manifest under the same schedule."""
    import json

    from keystone_tpu.resilience.quarantine import Quarantine

    class UnfixedQuarantine(Quarantine):
        def quarantine(self, source, reason, site="ingest.decode",
                       _sched=None):
            entry = {"source": str(source), "reason": str(reason),
                     "site": site}
            with self._lock:
                if entry["source"] in self._keys:
                    return
                self._keys.add(entry["source"])
                self.bad_count += 1
                self.records.append(entry)
            _sched.yield_point("pre-manifest")  # lock dropped, file not written
            with open(self.manifest_path, "a") as f:
                f.write(json.dumps(entry) + "\n")

    # 3 worker grants park it exactly PAST the count mutation (lock
    # released) and BEFORE the manifest write; the snapshotter then
    # observes; remaining grants let the worker finish
    picks = ["worker"] * 3 + ["snap"] * 4 + ["worker"] * 4
    bad_path = tmp_path / "bad.jsonl"
    bad_path.touch()
    q_bad = UnfixedQuarantine(max_bad_fraction=1.0,
                              manifest_path=str(bad_path))
    sched = DeterministicScheduler(picks=list(picks))
    seen = {}

    def worker():
        q_bad.quarantine("tar::bad.jpg", "truncated", _sched=sched)

    def snapshotter():
        state = q_bad.state()
        lines = [ln for ln in bad_path.read_text().splitlines() if ln]
        seen["state_bad"] = state["bad_count"]
        seen["manifest_lines"] = len(lines)

    sched.spawn(worker, name="worker")
    sched.spawn(snapshotter, name="snap")
    with sched:
        sched.run()
    # reproduced: the snapshot counted a record the manifest lacks
    assert seen["state_bad"] == 1 and seen["manifest_lines"] == 0

    good_path = tmp_path / "good.jsonl"
    good_path.touch()
    q_ok = Quarantine(max_bad_fraction=1.0, manifest_path=str(good_path))
    sched2 = DeterministicScheduler(picks=list(picks))
    seen2 = {}

    def worker2():
        q_ok.quarantine("tar::bad.jpg", "truncated")

    def snapshotter2():
        state = q_ok.state()
        lines = [ln for ln in good_path.read_text().splitlines() if ln]
        seen2["state_bad"] = state["bad_count"]
        seen2["manifest_lines"] = len(lines)

    sched2.spawn(worker2, name="worker")
    sched2.spawn(snapshotter2, name="snap")
    with sched2:
        sched2.run()
    # shipped: whatever the snapshot counted is durably in the manifest
    assert seen2["manifest_lines"] >= seen2["state_bad"]
    assert seen2["state_bad"] == 1 or seen2["manifest_lines"] == 1


# -- this PR's fix: _CAST_JIT_CACHE double-create ----------------------------

def test_cast_program_build_race_yields_one_program():
    """Two prefetch threads racing a cold cast cache must end up with
    the SAME compiled program object: jax's trace cache keys on the
    function object, so a per-thread wrapper recompiles the cast every
    chunk (the check-then-act fixed this PR)."""
    import jax

    from keystone_tpu.parallel import streaming

    streaming._CAST_JIT_CACHE.clear()
    _, treedef = jax.tree_util.tree_flatten({"x": np.zeros(2, np.uint8)})
    casts = (np.dtype(np.float32),)
    got = {}

    def build(name):
        got[name] = streaming._cast_program(treedef, casts)

    sched = DeterministicScheduler(picks=["a", "b"] * 10)
    sched.spawn(build, "a", name="a")
    sched.spawn(build, "b", name="b")
    with sched:
        sched.run()
    assert got["a"] is got["b"]


# -- this PR's fix: _JitSite.capture_stats lost update (PR 9 allowlist) ------

class _FakeJitted:
    """Stands in for the site's jitted callable: ``lower().compile()``
    parks INSIDE the capture window — the cache check is done, the
    publish has not happened — which is exactly where the pre-PR-10
    blind overwrite raced."""

    def __init__(self, sched):
        self._sched = sched

    def lower(self, *args, **kwargs):
        return self

    def compile(self):
        self._sched.yield_point("aot.compile")
        return object()


def _unfixed_capture(site, sig_key):
    """The pre-PR-10 publication: blind ``stats[sig_key] = stats``
    overwrite after the compile — value-equal, but two racing captures
    end up holding two DISTINCT dicts and the first writer's is
    orphaned (the allowlisted lost update, now fixed by the
    setdefault-adopt in ``_JitSite._adopt_stats``)."""
    from keystone_tpu.observability import compilelog

    with site._site_lock:
        cached = site.stats.get(sig_key)
        lower = site.avals.get(sig_key)
    if cached is not None:
        return cached
    la, lk = lower
    compiled = site.jitted.lower(*la, **lk).compile()
    stats = compilelog.executable_stats(compiled)
    with site._site_lock:
        site.stats[sig_key] = stats
    return stats


def _drive_capture_race(fixed, monkeypatch, picks=None, seed=0,
                        names=("a", "b")):
    from keystone_tpu.observability import compilelog
    from keystone_tpu.observability.compilelog import _JitSite

    sched = (DeterministicScheduler(picks=list(picks))
             if picks is not None else DeterministicScheduler(seed=seed))
    site = _JitSite("race-site", _FakeJitted(sched))
    site.avals["sig"] = ((), {})
    # fresh value-equal dict per capture, like a real executable_stats
    monkeypatch.setattr(compilelog, "executable_stats",
                        lambda compiled: {"flops": 1.0})
    got = {}

    def run(name):
        got[name] = (site.capture_stats("sig") if fixed
                     else _unfixed_capture(site, "sig"))

    for name in names:
        sched.spawn(run, name, name=name)
    with sched:
        sched.run()
    return got, site


def test_capture_stats_lost_update_reproduces_on_unfixed_copy(monkeypatch):
    got, site = _drive_capture_race(False, monkeypatch,
                                    picks=["a", "b"] * 8)
    published = site.stats["sig"]
    # value-equal, but the loser's dict was orphaned by the overwrite:
    # exactly one caller holds the published object
    assert got["a"] == got["b"]
    assert sum(got[n] is published for n in ("a", "b")) == 1


def test_capture_stats_single_identity_on_head(monkeypatch):
    # same schedule, same racy window — the setdefault-adopt under one
    # lock hold makes every caller hold THE published dict
    got, site = _drive_capture_race(True, monkeypatch,
                                    picks=["a", "b"] * 8)
    published = site.stats["sig"]
    assert got["a"] is published and got["b"] is published


def test_capture_stats_fix_survives_seeded_schedules(monkeypatch):
    for seed in range(20):
        got, site = _drive_capture_race(
            True, monkeypatch, seed=seed, names=("a", "b", "c"))
        published = site.stats["sig"]
        assert all(got[n] is published for n in ("a", "b", "c")), \
            f"seed {seed}"
        assert len(site.stats) == 1


def test_metrics_registry_singleton_survives_thread_hammer():
    MetricsRegistry.reset()
    seen = []
    barrier = threading.Barrier(8)

    def grab():
        barrier.wait()
        seen.append(MetricsRegistry.get_or_create())

    threads = [threading.Thread(target=grab) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert all(r is seen[0] for r in seen)


# -- TracedLock / TracedSemaphore semantics + telemetry ----------------------

def test_traced_lock_contention_feeds_metrics_and_trace():
    MetricsRegistry.reset()
    lock = TracedLock("test.contended")
    entered = threading.Event()
    with PipelineTrace("locks") as tr:
        def contender():
            entered.set()
            with lock:
                pass

        lock.acquire()
        t = threading.Thread(target=contender)
        t.start()
        entered.wait(timeout=5)
        time.sleep(0.05)  # let the contender reach the blocking acquire
        lock.release()
        t.join(timeout=5)
    reg = MetricsRegistry.get_or_create()
    hist = reg.histogram("lock.wait_s.test.contended")
    assert hist.count == 1
    assert reg.counter("lock.contended_total").value >= 1
    assert tr.lock_waits["test.contended"]["count"] == 1
    assert "contended locks" in tr.summary()
    # and the wait table round-trips through the JSON artifact
    back = PipelineTrace.from_json(tr.to_json())
    assert back.lock_waits["test.contended"]["count"] == 1


def test_traced_lock_uncontended_fast_path_records_nothing():
    MetricsRegistry.reset()
    lock = TracedLock("test.quiet")
    for _ in range(100):
        with lock:
            pass
    assert "lock.wait_s.test.quiet" not in \
        MetricsRegistry.get_or_create().snapshot()["histograms"]


def test_traced_lock_instrumentation_opt_out(monkeypatch):
    monkeypatch.setattr(guarded, "_TRACE_CONTENTION", False)
    MetricsRegistry.reset()
    lock = TracedLock("test.optout")
    lock.acquire()
    t = threading.Thread(target=lambda: (lock.acquire(), lock.release()))
    t.start()
    time.sleep(0.05)
    lock.release()
    t.join(timeout=5)
    assert "lock.wait_s.test.optout" not in \
        MetricsRegistry.get_or_create().snapshot()["histograms"]


def test_traced_semaphore_semantics():
    sem = TracedSemaphore("test.slots", 1)
    assert sem.acquire(timeout=0.1)
    t0 = time.perf_counter()
    assert not sem.acquire(timeout=0.05)
    assert time.perf_counter() - t0 >= 0.04
    sem.release()
    assert sem.acquire(blocking=False)
    sem.release()


def test_traced_lock_timeout_and_nonblocking():
    lock = TracedLock("test.timeouts")
    lock.acquire()
    assert not lock.acquire(blocking=False)
    assert not lock.acquire(timeout=0.05)
    lock.release()
    assert lock.acquire(timeout=0.05)
    lock.release()


# -- seeded fuzz of the prefetcher's slot-gated staging ----------------------

def _fuzz_one_seed(seed, X, mesh):
    from keystone_tpu.parallel.streaming import StreamingDataset

    stream = StreamingDataset.from_numpy(X, chunk_size=16, mesh=mesh)
    with chaos(seed=seed):
        parts = [c.numpy() for c in stream.chunks()]
    got = np.concatenate(parts, axis=0)
    np.testing.assert_array_equal(got, X)
    assert stream.buffered_nbytes() == 0.0


def test_prefetcher_fuzz_bounded_seeds(mesh8):
    """The tier-1 / ci.sh bounded slice of the stress suite: full
    passes must deliver every row in order with a drained ledger under
    seeded perturbation at every lock/semaphore site."""
    X = np.arange(48 * 8, dtype=np.float32).reshape(48, 8)
    for seed in range(25):
        _fuzz_one_seed(seed, X, mesh8)


def test_prefetcher_fuzz_wire_cast_seeds(mesh8):
    """A few seeds through the wire-dtype path too (covers the cast
    build lock + hand_off transient accounting under perturbation)."""
    from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming
    from keystone_tpu.nodes.stats import StandardScaler

    X = (np.arange(48 * 8) % 251).astype(np.uint8).reshape(48, 8)
    for seed in range(5):
        stream = StreamingDataset.from_numpy(
            X, chunk_size=16, mesh=mesh8,
            wire_dtype=np.uint8, compute_dtype=np.float32)
        with chaos(seed=seed):
            model = fit_streaming(StandardScaler(), stream)
        np.testing.assert_allclose(
            np.asarray(model.mean), X.astype(np.float32).mean(axis=0),
            rtol=1e-5)
        assert stream.buffered_nbytes() == 0.0


@pytest.mark.slow
def test_prefetcher_fuzz_200_schedules(mesh8):
    """The full stress suite: >= 200 seeded schedules over the
    prefetcher's slot-gated staging (acceptance bar), full passes and
    early exits alternating."""
    from keystone_tpu.parallel.streaming import StreamingDataset

    X = np.arange(48 * 8, dtype=np.float32).reshape(48, 8)
    for seed in range(200):
        if seed % 4 == 3:
            stream = StreamingDataset.from_numpy(
                X, chunk_size=16, mesh=mesh8)
            with chaos(seed=seed):
                for i, _ in enumerate(stream.chunks()):
                    if i == 1:
                        break
            deadline = time.monotonic() + 5.0
            while stream.buffered_nbytes() and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert stream.buffered_nbytes() == 0.0, f"seed {seed}"
        else:
            _fuzz_one_seed(seed, X, mesh8)


# -- PR 8: flight-recorder ring-buffer writer race ---------------------------

class _RingRmwReplica:
    """The FlightRecorder.record slot-write + index-bump, desugared
    (``ring[idx] = span; idx = (idx + 1) % cap`` IS read-then-write on
    the shared index) with the racy window marked — un-fixed (no lock)
    vs fixed (the shipped locked structure, TracedLock so waiters park
    for the scheduler)."""

    def __init__(self, locked, capacity=4):
        self.ring = [None] * capacity
        self.idx = 0
        self.total = 0
        self.locked = locked
        self._lock = TracedLock("ring.replica")

    def _record(self, sched, name):
        i = self.idx
        sched.yield_point("ring.rmw")  # both threads read the same idx
        self.ring[i % len(self.ring)] = name
        self.idx = i + 1
        self.total += 1

    def record(self, sched, name):
        if self.locked:
            with self._lock:
                self._record(sched, name)
        else:
            self._record(sched, name)


def _drive_ring(locked, picks, names=("a", "b")):
    ring = _RingRmwReplica(locked)
    sched = DeterministicScheduler(picks=list(picks))
    for name in names:
        sched.spawn(ring.record, sched, f"span-{name}", name=name)
    with sched:
        sched.run()
    return ring


def test_ring_writer_race_reproduces_unlocked():
    """Both writers read idx=0 before either bumps: the second write
    lands in the SAME slot — one span silently lost, deterministically,
    under the scripted interleaving."""
    ring = _drive_ring(False, ["a", "b"] * 8)
    stored = [s for s in ring.ring if s is not None]
    assert len(stored) == 1  # one of the two spans overwrote the other


def test_ring_writer_race_fixed_shape_survives():
    ring = _drive_ring(True, ["a", "b"] * 8)
    stored = [s for s in ring.ring if s is not None]
    assert sorted(stored) == ["span-a", "span-b"]
    for seed in range(20):
        ring = _RingRmwReplica(True)
        sched = DeterministicScheduler(seed=seed)
        for name in ("a", "b", "c"):
            sched.spawn(ring.record, sched, f"span-{name}", name=name)
        with sched:
            sched.run()
        stored = [s for s in ring.ring if s is not None]
        assert len(stored) == 3 and ring.idx == 3, f"seed {seed}"


def test_ring_wraparound_race_two_threads():
    """Threads racing the WRAPAROUND boundary (capacity 2, three
    records): the locked shape keeps the exact count and retains
    exactly `capacity` spans; the un-fixed shape under the same
    schedule collapses the index (all writers saw idx=0)."""
    for picks in (["a", "b", "c"] * 6, ["c", "b", "a"] * 6):
        ring = _RingRmwReplica(True, capacity=2)
        sched = DeterministicScheduler(picks=list(picks))
        for name in ("a", "b", "c"):
            sched.spawn(ring.record, sched, f"span-{name}", name=name)
        with sched:
            sched.run()
        assert ring.total == 3 and ring.idx == 3, picks
        assert sum(s is not None for s in ring.ring) == 2  # last two
        broken = _RingRmwReplica(False, capacity=2)
        sched = DeterministicScheduler(picks=list(picks))
        for name in ("a", "b", "c"):
            sched.spawn(broken.record, sched, f"span-{name}", name=name)
        with sched:
            sched.run()
        assert broken.idx < 3, picks  # lost index bumps, reproduced


def test_shipped_flight_recorder_exact_under_thread_hammer():
    """The REAL FlightRecorder under a thread hammer: the total count
    is exact (no lost updates) and the ring retains exactly capacity
    spans after overflow."""
    from keystone_tpu.observability.timeline import FlightRecorder

    rec = FlightRecorder(capacity=64, enabled=True)
    n, per = 8, 500
    threads = [threading.Thread(
        target=lambda: [rec.record("s", "hammer", 0.0, 0.0)
                        for _ in range(per)])
        for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert rec.total_recorded == n * per
    assert len(rec.spans()) == 64
    assert rec.dropped() == n * per - 64


# -- interpreter-exit teardown (satellite) -----------------------------------

_EXIT_SCRIPT = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import time
import numpy as np
from keystone_tpu.parallel.streaming import StreamingDataset
from keystone_tpu.parallel.mesh import h2d_pool

def slow_chunks():
    for _ in range(1000):
        time.sleep(0.02)
        yield np.ones((16, 4), np.float32)

s = StreamingDataset(slow_chunks, chunk_size=16)
it = s.chunks()
next(it)          # prefetch producer live, H2D pool built
assert h2d_pool() is not None
print("MID-STREAM-EXIT")
# exit with the stream active: the registered teardown must stop the
# producer and shut the non-daemon pool down without hanging or
# spewing thread-join noise
"""


def test_interpreter_exit_under_active_stream_is_clean():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-c", _EXIT_SCRIPT], capture_output=True,
        text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    assert "MID-STREAM-EXIT" in proc.stdout
    for noise in ("Exception in thread", "cannot join",
                  "cannot schedule new futures", "Traceback"):
        assert noise not in proc.stderr, proc.stderr[-2000:]


def test_interpreter_exit_under_active_stream_flushes_flight_recorder(
        tmp_path):
    """PR 8 extension of the teardown pin: an exit under an active
    stream must FLUSH the flight recorder to a post-mortem before the
    H2D pool dies (the stream-stop teardown runs first by registration
    order, and the dump happens inside it). The dumped timeline carries
    the ingest spans the stream produced — evidence survives the kill."""
    import glob
    import json

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KEYSTONE_POSTMORTEM_DIR=str(tmp_path),
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, "-c", _EXIT_SCRIPT], capture_output=True,
        text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    dumps = glob.glob(str(
        tmp_path / "postmortem-exit_under_active_stream-*.json"))
    assert len(dumps) == 1, dumps
    blob = json.loads(open(dumps[0]).read())
    assert blob["context"]["live_streams"] == 1
    # the stream staged at least one chunk before the exit: its ingest
    # span is in the flushed timeline, and the metrics snapshot
    # counted it
    cats = {e.get("cat") for e in blob["flight_recorder"]["traceEvents"]}
    assert "ingest" in cats
    assert blob["metrics"]["counters"]["streaming.chunks_total"] >= 1
    # no join noise: the dump happened BEFORE pool teardown, not during
    for noise in ("Exception in thread", "cannot schedule new futures"):
        assert noise not in proc.stderr, proc.stderr[-2000:]


def test_h2d_pool_shutdown_is_idempotent_and_rebuilds(monkeypatch):
    from keystone_tpu.parallel import mesh

    monkeypatch.delenv("KEYSTONE_H2D_THREADS", raising=False)
    pool = mesh.h2d_pool()
    assert pool is not None
    mesh.shutdown_h2d_pool()
    mesh.shutdown_h2d_pool()  # idempotent
    fresh = mesh.h2d_pool()
    assert fresh is not None and fresh is not pool
    # leave a live pool behind for other tests
