"""End-to-end application pipeline tests on tiny synthetic datasets
(the reference's apps are its integration tests; these are scaled-down
versions exercising every pipeline's full DAG)."""
import numpy as np

from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.loaders.image_loader_utils import (
    LabeledImage,
    MultiLabeledImage,
)
from keystone_tpu.loaders.timit import TimitFeaturesData
from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset


def _cifar_like(n=48, size=32, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.int32)
    imgs = rng.rand(n, size, size, 3).astype(np.float32) * 50
    # make classes separable: add label-dependent mean shift
    imgs += labels[:, None, None, None] * 12.0
    return LabeledData(
        data=ArrayDataset.from_numpy(imgs),
        labels=ArrayDataset.from_numpy(labels),
    )


def test_timit_pipeline(mesh8):
    from keystone_tpu.pipelines.speech.timit import TimitConfig, run

    rng = np.random.RandomState(0)
    n, d, k = 64, 20, 4
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, k, n).astype(np.int32)
    X += y[:, None] * 2.0  # separable
    data = TimitFeaturesData(
        train=LabeledData(ArrayDataset.from_numpy(X),
                          ArrayDataset.from_numpy(y)),
        test=LabeledData(ArrayDataset.from_numpy(X),
                         ArrayDataset.from_numpy(y)),
    )
    cfg = TimitConfig(num_cosines=3, num_epochs=2, lam=0.01)
    cfg.num_cosine_features = 64
    _, metrics = run(cfg, data=data, num_classes=k, input_dim=d)
    assert metrics.total_error < 0.2


def test_random_cifar_pipeline(mesh8):
    from keystone_tpu.pipelines.images.cifar.random_cifar import (
        RandomCifarConfig,
        run,
    )

    data = _cifar_like(n=40)
    cfg = RandomCifarConfig(num_filters=8, lam=0.01)
    _, train_eval, test_eval = run(cfg, train=data, test=data)
    assert train_eval.total_error <= 0.2


def test_random_patch_cifar_augmented(mesh8):
    from keystone_tpu.pipelines.images.cifar.random_patch_cifar_augmented import (
        AugmentedConfig,
        run,
    )

    data = _cifar_like(n=24)
    cfg = AugmentedConfig(
        num_filters=8, lam=0.01, num_random_patches_augment=2)
    _, test_eval = run(cfg, train=data, test=data)
    assert test_eval.total_error <= 0.7  # well below the 0.9 random baseline


def _toy_images(n, seed=0, size=56):
    rng = np.random.RandomState(seed)
    imgs = []
    for i in range(n):
        img = rng.rand(size, size, 3).astype(np.float32) * 255
        imgs.append(img)
    return imgs


def test_voc_sift_fisher_pipeline(mesh8):
    from keystone_tpu.pipelines.images.voc.voc_sift_fisher import (
        SIFTFisherConfig,
        run,
    )

    rng = np.random.RandomState(0)
    imgs = _toy_images(8)
    train = HostDataset([
        MultiLabeledImage(img, [int(i % 3)], f"im{i}.jpg")
        for i, img in enumerate(imgs)
    ])
    cfg = SIFTFisherConfig(
        lam=0.5, desc_dim=8, vocab_size=2,
        num_pca_samples=400, num_gmm_samples=400, block_size=256)
    _, ap = run(cfg, train=train, test=train,
                sift_kwargs=dict(step=12, num_scales=2))
    assert ap.shape == (20,)
    assert np.all(np.isfinite(ap))


def test_imagenet_sift_lcs_fv_pipeline(mesh8):
    from keystone_tpu.pipelines.images.imagenet.sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )

    imgs = _toy_images(8, size=56)
    train = HostDataset([
        LabeledImage(img, int(i % 2), f"c{i%2}/im{i}.jpg")
        for i, img in enumerate(imgs)
    ])
    cfg = ImageNetSiftLcsFVConfig(
        lam=1e-3, mixture_weight=0.25, desc_dim=8, vocab_size=2,
        lcs_stride=12, lcs_border=20,
        num_pca_samples=400, num_gmm_samples=400, block_size=128)
    _, err = run(cfg, train=train, test=train, num_classes=2, top_k=1,
                 sift_kwargs=dict(step=12, num_scales=2))
    assert np.isfinite(err)


def test_voc_pca_gmm_csv_preload_skips_refit(mesh8, tmp_path, monkeypatch):
    """VERDICT r3 missing #2 (reference VOCSIFTFisher.scala:50-76): fit
    once, save the PCA/GMM as CSV artifacts, rerun with the files wired
    — the estimators must never fit again and the APs must match."""
    from keystone_tpu.nodes.images.fisher_vector import FisherVector
    from keystone_tpu.nodes.learning.pca import BatchPCATransformer
    from keystone_tpu.pipelines.images.voc import voc_sift_fisher as app
    from keystone_tpu.utils.checkpoint import save_pca_csv
    from keystone_tpu.workflow.env import PipelineEnv
    from keystone_tpu.workflow.expression import TransformerExpression

    imgs = _toy_images(8)
    train = HostDataset([
        MultiLabeledImage(img, [int(i % 3)], f"im{i}.jpg")
        for i, img in enumerate(imgs)
    ])
    cfg = app.SIFTFisherConfig(
        lam=0.5, desc_dim=8, vocab_size=2,
        num_pca_samples=400, num_gmm_samples=400, block_size=256)
    kw = dict(step=12, num_scales=2)
    env = PipelineEnv.get_or_create()
    env.clear_state()
    _, ap0 = app.run(cfg, train=train, test=train, sift_kwargs=kw)

    # harvest the fitted transformers out of the prefix table
    pca_mat = gmm = None
    for expr in env.state.values():
        if isinstance(expr, TransformerExpression) and expr.computed:
            node = expr.get()
            if isinstance(node, BatchPCATransformer):
                pca_mat = node.pca_mat
            if isinstance(node, FisherVector):
                gmm = node.gmm
    assert pca_mat is not None and gmm is not None

    paths = {k: str(tmp_path / f"{k}.csv")
             for k in ("pca", "mean", "var", "wts")}
    save_pca_csv(pca_mat, paths["pca"])
    gmm.save(paths["mean"], paths["var"], paths["wts"])

    env.clear_state()

    def _no_fit(self, *a, **k):  # any refit is the bug
        raise AssertionError("estimator fit despite preloaded artifacts")

    monkeypatch.setattr(app.ColumnPCAEstimator, "fit_datasets", _no_fit)
    monkeypatch.setattr(app.GMMFisherVectorEstimator, "fit_datasets", _no_fit)
    cfg2 = app.SIFTFisherConfig(
        lam=0.5, desc_dim=8, vocab_size=2,
        num_pca_samples=400, num_gmm_samples=400, block_size=256,
        pca_file=paths["pca"], gmm_mean_file=paths["mean"],
        gmm_var_file=paths["var"], gmm_wts_file=paths["wts"])
    _, ap1 = app.run(cfg2, train=train, test=train, sift_kwargs=kw)
    np.testing.assert_allclose(ap1, ap0, atol=1e-4)
