"""Tar-based image loader tests with generated archives (mirrors the
reference's ImageNetLoaderSuite / VOCLoaderSuite against stored tars)."""
import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders import (
    VOCDataPath,
    VOCLabelPath,
    imagenet_loader,
    iter_tar_images,
    parse_voc_labels,
    voc_loader,
)


def _png_bytes(rgb):
    from PIL import Image as PILImage

    buf = io.BytesIO()
    PILImage.fromarray(rgb.astype(np.uint8)).save(buf, format="PNG")
    return buf.getvalue()


def _write_tar(path, entries):
    with tarfile.open(path, "w") as tf:
        for name, data in entries:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def test_iter_tar_images(tmp_path):
    rng = np.random.RandomState(0)
    img = rng.randint(0, 255, (8, 9, 3))
    tar = tmp_path / "imgs.tar"
    _write_tar(str(tar), [
        ("a/x.png", _png_bytes(img)),
        ("a/not_an_image.txt", b"hello"),  # undecodable: skipped
    ])
    items = list(iter_tar_images(str(tar)))
    assert len(items) == 1
    name, arr = items[0]
    assert name == "a/x.png" and arr.shape == (8, 9, 3)
    np.testing.assert_allclose(arr, img, atol=1.0)


def test_imagenet_loader(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "n01.tar"
    _write_tar(str(tar), [
        ("n01440764/im1.png", _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
        ("n01443537/im2.png", _png_bytes(rng.randint(0, 255, (7, 5, 3)))),
    ])
    labels = tmp_path / "labels.txt"
    labels.write_text("n01440764 7\nn01443537 42\n")
    ds = imagenet_loader(str(tmp_path), str(labels))
    items = ds.collect()
    assert sorted(it.label for it in items) == [7, 42]
    assert all(it.image.ndim == 3 for it in items)


def test_voc_loader_multilabel(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "voc.tar"
    _write_tar(str(tar), [
        ("VOCdevkit/VOC2007/JPEGImages/000001.jpg",
         _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
        ("VOCdevkit/VOC2007/JPEGImages/000002.jpg",
         _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
    ])
    labels = tmp_path / "labels.csv"
    # header + rows: col1 = 1-based class, col4 = quoted filename
    labels.write_text(
        'id,class,x,y,fname\n'
        '1,3,0,0,"000001.jpg"\n'
        '2,5,0,0,"000001.jpg"\n'
        '3,1,0,0,"000002.jpg"\n')
    lm = parse_voc_labels(str(labels))
    assert lm["000001.jpg"] == [2, 4] and lm["000002.jpg"] == [0]

    ds = voc_loader(
        VOCDataPath(str(tar), "VOCdevkit"), VOCLabelPath(str(labels)))
    items = sorted(ds.collect(), key=lambda it: it.filename)
    assert items[0].labels == [2, 4]
    assert items[1].labels == [0]


def test_voc_loader_prefix_filter(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "voc.tar"
    _write_tar(str(tar), [
        ("VOCdevkit/VOC2007/JPEGImages/000001.jpg",
         _png_bytes(rng.randint(0, 255, (4, 4, 3)))),
        ("other/junk.png", _png_bytes(rng.randint(0, 255, (4, 4, 3)))),
    ])
    labels = tmp_path / "labels.csv"
    labels.write_text('h\n1,1,0,0,"000001.jpg"\n')
    ds = voc_loader(
        VOCDataPath(str(tar), "VOCdevkit"), VOCLabelPath(str(labels)))
    assert len(ds) == 1  # name prefix filtered out the junk entry


def test_load_tar_files_raises_when_nothing_readable(tmp_path):
    # A directly-named (or all-junk) path that cannot be opened as a tar
    # must error loudly, not return an empty dataset.
    import tarfile

    import pytest as _pytest

    from keystone_tpu.loaders.image_loader_utils import load_tar_files

    bad = tmp_path / "notatar.bin"
    bad.write_bytes(b"junk" * 100)
    with _pytest.raises(tarfile.ReadError):
        load_tar_files([str(bad)], lambda n: 0, lambda img, lab, name: (img, lab))
