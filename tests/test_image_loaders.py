"""Tar-based image loader tests with generated archives (mirrors the
reference's ImageNetLoaderSuite / VOCLoaderSuite against stored tars)."""
import io
import os
import tarfile

import numpy as np
import pytest

from keystone_tpu.loaders import (
    VOCDataPath,
    VOCLabelPath,
    imagenet_loader,
    iter_tar_images,
    parse_voc_labels,
    voc_loader,
)


def _png_bytes(rgb):
    from PIL import Image as PILImage

    buf = io.BytesIO()
    PILImage.fromarray(rgb.astype(np.uint8)).save(buf, format="PNG")
    return buf.getvalue()


def _write_tar(path, entries):
    with tarfile.open(path, "w") as tf:
        for name, data in entries:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def test_iter_tar_images(tmp_path):
    rng = np.random.RandomState(0)
    img = rng.randint(0, 255, (8, 9, 3))
    tar = tmp_path / "imgs.tar"
    _write_tar(str(tar), [
        ("a/x.png", _png_bytes(img)),
        ("a/not_an_image.txt", b"hello"),  # undecodable: skipped
    ])
    items = list(iter_tar_images(str(tar)))
    assert len(items) == 1
    name, arr = items[0]
    assert name == "a/x.png" and arr.shape == (8, 9, 3)
    np.testing.assert_allclose(arr, img, atol=1.0)


def test_imagenet_loader(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "n01.tar"
    _write_tar(str(tar), [
        ("n01440764/im1.png", _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
        ("n01443537/im2.png", _png_bytes(rng.randint(0, 255, (7, 5, 3)))),
    ])
    labels = tmp_path / "labels.txt"
    labels.write_text("n01440764 7\nn01443537 42\n")
    ds = imagenet_loader(str(tmp_path), str(labels))
    items = ds.collect()
    assert sorted(it.label for it in items) == [7, 42]
    assert all(it.image.ndim == 3 for it in items)


def test_voc_loader_multilabel(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "voc.tar"
    _write_tar(str(tar), [
        ("VOCdevkit/VOC2007/JPEGImages/000001.jpg",
         _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
        ("VOCdevkit/VOC2007/JPEGImages/000002.jpg",
         _png_bytes(rng.randint(0, 255, (6, 6, 3)))),
    ])
    labels = tmp_path / "labels.csv"
    # header + rows: col1 = 1-based class, col4 = quoted filename
    labels.write_text(
        'id,class,x,y,fname\n'
        '1,3,0,0,"000001.jpg"\n'
        '2,5,0,0,"000001.jpg"\n'
        '3,1,0,0,"000002.jpg"\n')
    lm = parse_voc_labels(str(labels))
    assert lm["000001.jpg"] == [2, 4] and lm["000002.jpg"] == [0]

    ds = voc_loader(
        VOCDataPath(str(tar), "VOCdevkit"), VOCLabelPath(str(labels)))
    items = sorted(ds.collect(), key=lambda it: it.filename)
    assert items[0].labels == [2, 4]
    assert items[1].labels == [0]


def test_voc_loader_prefix_filter(tmp_path):
    rng = np.random.RandomState(0)
    tar = tmp_path / "voc.tar"
    _write_tar(str(tar), [
        ("VOCdevkit/VOC2007/JPEGImages/000001.jpg",
         _png_bytes(rng.randint(0, 255, (4, 4, 3)))),
        ("other/junk.png", _png_bytes(rng.randint(0, 255, (4, 4, 3)))),
    ])
    labels = tmp_path / "labels.csv"
    labels.write_text('h\n1,1,0,0,"000001.jpg"\n')
    ds = voc_loader(
        VOCDataPath(str(tar), "VOCdevkit"), VOCLabelPath(str(labels)))
    assert len(ds) == 1  # name prefix filtered out the junk entry


def test_load_tar_files_raises_when_nothing_readable(tmp_path):
    # A directly-named (or all-junk) path that cannot be opened as a tar
    # must error loudly, not return an empty dataset.
    import tarfile

    import pytest as _pytest

    from keystone_tpu.loaders.image_loader_utils import load_tar_files

    bad = tmp_path / "notatar.bin"
    bad.write_bytes(b"junk" * 100)
    with _pytest.raises(tarfile.ReadError):
        load_tar_files([str(bad)], lambda n: 0, lambda img, lab, name: (img, lab))


def _write_cifar_bin(path, n=24, seed=0):
    """Synthesize a binary CIFAR file (reference record layout:
    1 label byte + 3 row-major 32x32 planes, CifarLoader.scala:14-51)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    planes = rng.randint(0, 256, (n, 3, 32, 32)).astype(np.uint8)
    rec = np.concatenate([labels[:, None], planes.reshape(n, -1)], axis=1)
    path.write_bytes(rec.tobytes())
    return planes.transpose(0, 2, 3, 1), labels


def test_cifar_loader_float_and_packed_agree(tmp_path):
    """packed=True keeps uint8 (4x smaller); values are identical after
    the on-device float conversion."""
    import numpy as np

    from keystone_tpu.loaders.cifar_loader import cifar_loader

    expect_imgs, expect_labels = _write_cifar_bin(tmp_path / "b1.bin")
    f = cifar_loader(str(tmp_path / "b1.bin"))
    p = cifar_loader(str(tmp_path / "b1.bin"), packed=True)

    import jax

    assert jax.tree_util.tree_leaves(p.data.data)[0].dtype == np.uint8
    assert jax.tree_util.tree_leaves(f.data.data)[0].dtype == np.float32
    np.testing.assert_array_equal(np.asarray(f.labels.numpy()), expect_labels)
    np.testing.assert_array_equal(np.asarray(p.labels.numpy()), expect_labels)
    np.testing.assert_array_equal(f.data.numpy(), expect_imgs.astype(np.float32))
    np.testing.assert_array_equal(p.data.numpy(), expect_imgs)

    # device-side float op sees identical values from either layout
    scaled_f = f.data.map(lambda x: x / 255.0).numpy()
    scaled_p = p.data.map(lambda x: x / 255.0).numpy()
    np.testing.assert_allclose(scaled_f, scaled_p, rtol=1e-6)


def test_cifar_packed_pipeline_parity(tmp_path):
    """The real LinearPixels app path (GrayScaler -> vectorize -> solve)
    gives the same predictions from packed-u8 and f32 datasets."""
    import numpy as np

    from keystone_tpu.loaders.cifar_loader import cifar_loader
    from keystone_tpu.nodes.images.core import (
        GrayScaler,
        ImageVectorizer,
        PixelScaler,
    )
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromIntLabels

    _write_cifar_bin(tmp_path / "b1.bin", n=40)
    preds = {}
    for packed in (False, True):
        d = cifar_loader(str(tmp_path / "b1.bin"), packed=packed)
        feat = ImageVectorizer().apply_dataset(
            GrayScaler().apply_dataset(PixelScaler().apply_dataset(d.data)))
        labels = ClassLabelIndicatorsFromIntLabels(10).apply_dataset(d.labels)
        model = LinearMapEstimator(lam=10.0).fit(feat, labels)
        preds[packed] = np.asarray(model.apply_dataset(feat).numpy())
    np.testing.assert_allclose(preds[False], preds[True], rtol=1e-4, atol=1e-4)


def test_archive_listing_host_strided(tmp_path, monkeypatch):
    """Multi-host SPMD: each process lists its strided share of the
    archives (CLUSTER.md 'Data'); single-host sees everything."""
    import jax

    from keystone_tpu.loaders.image_loader_utils import list_archive_paths

    for i in range(5):
        (tmp_path / f"shard{i}.tar").write_bytes(b"x")
    all_paths = list_archive_paths(str(tmp_path))
    assert len(all_paths) == 5

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    mine = list_archive_paths(str(tmp_path))
    assert [p.split("shard")[1] for p in mine] == ["1.tar", "3.tar"]
    assert len(list_archive_paths(str(tmp_path), process_shard=False)) == 5

    # fewer archives than hosts -> loud failure at the loader, not a
    # collective hang downstream
    import pytest

    monkeypatch.setattr(jax, "process_count", lambda: 8)
    with pytest.raises(ValueError, match="no archives"):
        list_archive_paths(str(tmp_path / "shard0.tar"))
