"""Static HBM resource planner (keystone_tpu/analysis/resources):
device-free plans over every bundled app, budget gating through
``check --budget`` / ``Pipeline.check(hbm_budget=...)`` (exit 2 /
``hbm-budget`` diagnostic BEFORE any device work), and the
plan-vs-measured parity contract on streamed fits — the static plan
must bound the runtime residency ledger's peak from above, tightly."""
import time

import jax
import numpy as np
import pytest

from keystone_tpu.__main__ import _parse_bytes, check_main
from keystone_tpu.analysis import plan_graph
from keystone_tpu.analysis.resources import (
    ResourceEffect,
    StreamGeometry,
    element_nbytes,
    gram_carry_nbytes,
    padded_rows,
)
from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.observability import PipelineTrace
from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming
from keystone_tpu.pipelines import CHECK_APPS, resolve_check_app


# -- plan resolution over the registry --------------------------------------

@pytest.mark.parametrize("app", sorted(CHECK_APPS))
def test_every_app_resolves_a_plan(app, mesh8):
    target = CHECK_APPS[app]()
    report = target.pipeline.check(target.input_spec, name=target.name)
    plan = report.plan
    assert plan is not None
    assert plan.fit_peak_nbytes >= 0.0
    assert plan.entries  # one entry per planned node
    # the JSON form carries the plan alongside the diagnostics
    blob = report.to_dict()
    assert blob["plan"]["fit_peak_nbytes"] == plan.fit_peak_nbytes


def test_array_app_plans_charge_real_bytes(mesh8):
    # dense apps have fully resolved byte counts: the fit peak must at
    # least cover the training dataset itself
    target = resolve_check_app("mnist.random_fft")()
    report = target.pipeline.check(target.input_spec, name="mnist")
    plan = report.plan
    assert not plan.unresolved, plan.unresolved
    train_bytes = padded_rows(60_000, 8) * 784 * 4
    assert plan.fit_peak_nbytes >= train_bytes
    # fitted models persist (apply-path residency) and the per-item
    # activation bound is known for the serving path
    assert plan.model_nbytes > 0
    assert plan.apply_item_nbytes > 0


def test_plan_is_device_free(mesh8):
    before = {id(a) for a in jax.live_arrays()}
    target = resolve_check_app("mnist.random_fft")()
    report = target.pipeline.check(target.input_spec,
                                   hbm_budget=float(1 << 40))
    assert report.ok and report.plan is not None
    new = [a for a in jax.live_arrays() if id(a) not in before]
    assert not new, [(a.shape, a.dtype) for a in new[:5]]


# -- budget gating ----------------------------------------------------------

def test_hbm_budget_diagnostic_fires(mesh8):
    target = resolve_check_app("mnist.random_fft")()
    report = target.pipeline.check(target.input_spec,
                                   hbm_budget=float(1 << 20))  # 1 MiB
    codes = {d.code for d in report.diagnostics}
    assert "hbm-budget" in codes
    over = [d for d in report.diagnostics if d.code == "hbm-budget"]
    assert over[0].severity == "error"
    assert over[0].node_id == report.plan.peak_node


def test_check_cli_budget_exit_codes(mesh8, capsys):
    # over budget -> exit 2, predicted before any device work
    before = {id(a) for a in jax.live_arrays()}
    rc = check_main(["mnist.random_fft", "--budget", "1MiB"])
    assert rc == 2
    assert "OVER BUDGET" in capsys.readouterr().out
    assert not [a for a in jax.live_arrays() if id(a) not in before]
    # generous budget -> clean
    assert check_main(["mnist.random_fft", "--budget", "1TiB"]) == 0
    # malformed budget -> usage error
    assert check_main(["mnist.random_fft", "--budget", "much"]) == 2


def test_check_budget_verifies_per_host_charge(mesh8, capsys):
    """ISSUE 18 acceptance: ``check --budget --shards N`` verifies the
    per-host charge device-free. ``data_shards`` reaches the plan (the
    pad-to-shard width changes the charged rows), the CLI accepts the
    spelling, and the serving admission arithmetic derived FROM that
    plan divides the shardable fitted state across the shard count."""
    from keystone_tpu.analysis.resources import (
        serving_residency_nbytes,
        sharded_apply_nbytes,
    )

    target = resolve_check_app("mnist.random_fft")()
    # a 7-shard world pads 60000 rows to 60004: the plumbed-through
    # width is visible in the plan's charged bytes
    r7 = target.pipeline.check(target.input_spec, data_shards=7)
    r8 = target.pipeline.check(target.input_spec, data_shards=8)
    assert r7.plan.fit_peak_nbytes > r8.plan.fit_peak_nbytes
    # the per-host serving charge from the SAME device-free plan: a
    # fitted block model's shardable state divides across the shards,
    # so the 8-shard charge undercuts the replicated one
    X = np.random.RandomState(0).rand(64, 96).astype(np.float32)
    Y = np.random.RandomState(1).rand(64, 8).astype(np.float32)
    fitted = BlockLeastSquaresEstimator(32, num_iter=1, lam=1e-3)\
        .with_data(StreamingDataset.from_numpy(X, chunk_size=32)
                   .materialize(),
                   StreamingDataset.from_numpy(Y, chunk_size=32)
                   .materialize()).fit()
    report = fitted.check(jax.ShapeDtypeStruct((96,), np.float32))
    graph = fitted.to_pipeline().graph
    from keystone_tpu.analysis.resources import fitted_model_nbytes

    model_b = fitted_model_nbytes(graph)
    shardable, gather = sharded_apply_nbytes(graph)
    assert shardable > 0 and 0 < gather < shardable
    charge1 = serving_residency_nbytes(model_b, report.plan, 16)
    charge8 = serving_residency_nbytes(
        model_b, report.plan, 16, data_shards=8,
        shardable_nbytes=shardable, gather_nbytes=gather)
    assert charge8 is not None and charge1 is not None
    assert charge8 < charge1
    assert charge8 == pytest.approx(
        model_b - shardable + shardable / 8 + gather
        + 2 * report.plan.apply_item_nbytes)  # ceil(16/8) rows
    # the CLI spelling: --shards plumbs through with --budget
    assert check_main(["mnist.random_fft", "--budget", "1TiB",
                       "--shards", "8"]) == 0
    assert check_main(["mnist.random_fft", "--budget", "1MiB",
                       "--shards", "8"]) == 2
    capsys.readouterr()


def test_parse_bytes_spellings():
    assert _parse_bytes("1024") == 1024
    assert _parse_bytes("4k") == 4096
    assert _parse_bytes("512MiB") == 512 * (1 << 20)
    assert _parse_bytes("16GiB") == 16 * (1 << 30)
    assert _parse_bytes("2g") == 2 * (1 << 30)
    with pytest.raises(ValueError):
        _parse_bytes("sixteen")


# -- effect derivation units -------------------------------------------------

def test_element_nbytes_and_helpers():
    el = {"x": jax.ShapeDtypeStruct((32, 32, 3), np.uint8),
          "y": jax.ShapeDtypeStruct((10,), np.float32)}
    assert element_nbytes(el) == 32 * 32 * 3 + 40
    from keystone_tpu.analysis.spec import DatasetSpec, Unknown

    assert element_nbytes(Unknown("host")) is None
    specs = [DatasetSpec(jax.ShapeDtypeStruct((128,), np.float32), n=64),
             DatasetSpec(jax.ShapeDtypeStruct((10,), np.float32), n=64)]
    assert gram_carry_nbytes(specs) == 4 * (128 * 128 + 128 * 10 + 138)


def test_stream_geometry_plan_math():
    # u8 wire, f32 compute: depth*w + 4w + w transient
    g = StreamGeometry(chunk_rows=256, prefetch_depth=2,
                       wire_row_nbytes=3072.0, work_row_nbytes=12288.0,
                       cast=True)
    w = 256 * 3072.0
    assert g.plan_nbytes() == 2 * w + 4 * w + w
    # no cast: the documented (depth + 1) * chunk budget unit
    g2 = StreamGeometry(chunk_rows=256, prefetch_depth=2,
                        wire_row_nbytes=3072.0, work_row_nbytes=3072.0)
    assert g2.plan_nbytes() == 3 * w


def test_liveness_releases_dead_values(mesh8):
    # source -> a -> b chain over a known-n dataset: at b's step the
    # source is already released (its last consumer was a), so the peak
    # is the widest CONSECUTIVE pair, not the sum of every node
    from keystone_tpu.analysis import spec_dataset
    from keystone_tpu.workflow.transformer import LambdaTransformer

    n = 800
    pipe = (LambdaTransformer(lambda x: x * 2.0, "a")
            >> LambdaTransformer(lambda x: x.sum(axis=-1), "b"))
    report = pipe.check(spec_dataset((64,), np.float32, n=n).spec)
    wide = padded_rows(n, 8) * 64 * 4
    # peak = input + same-width intermediate; b's scalar output and the
    # released input never stack on top
    assert report.plan.fit_peak_nbytes == pytest.approx(2 * wide)


# -- estimator carry accounting ---------------------------------------------

def test_estimator_carry_rides_the_plan(mesh8):
    from keystone_tpu.analysis import spec_dataset
    from keystone_tpu.nodes.util import (
        ClassLabelIndicatorsFromIntLabels,
        MaxClassifier,
    )

    d, n, k = 256, 4096, 10
    train = spec_dataset((d,), np.float32, n=n)
    labels = ClassLabelIndicatorsFromIntLabels(k)(
        spec_dataset((), np.int32, n=n))
    pipe = LinearMapEstimator(0.0).with_data(train, labels) \
        >> MaxClassifier()
    report = pipe.check(jax.ShapeDtypeStruct((d,), np.float32))
    est = [e for e in report.plan.entries
           if e["operator"] == "LinearMapEstimator"]
    assert len(est) == 1
    assert est[0]["carry_nbytes"] == 4 * (d * d + d * k + d + k)
    assert est[0]["out_nbytes"] == 4 * (d * k + d + k)
    assert report.plan.model_nbytes >= est[0]["out_nbytes"]


# -- Pallas kernel workspace charges (PR 13 satellite) -----------------------

def test_fv_apply_workspace_rides_the_plan(mesh8):
    """The FV apply's kernel/fallback workspace is charged at the
    Delegate node: on CPU (no Pallas dispatch) that is the (nDesc, K)
    posterior matrix the split fallback materializes, scaled by the
    padded batch inside the one batched program."""
    from keystone_tpu.analysis import spec_dataset
    from keystone_tpu.analysis.resources import fv_apply_transient_nbytes
    from keystone_tpu.nodes.images.fisher_vector import (
        GMMFisherVectorEstimator,
    )

    d, nd, k, n = 64, 200, 33, 32
    train = spec_dataset((d, nd), np.float32, n=n)
    pipe = GMMFisherVectorEstimator(k).with_data(train)
    report = pipe.check(jax.ShapeDtypeStruct((d, nd), np.float32))
    delegates = [e for e in report.plan.entries
                 if e["operator"] == "Delegate"
                 and "kernel workspace" in e["note"]]
    assert delegates, report.plan.entries
    per_item = fv_apply_transient_nbytes(d, k, nd)
    assert per_item == 4.0 * nd * k  # CPU: the fallback's q matrix
    # the apply-path source has unknown n -> charged once per item
    assert delegates[0]["transient_nbytes"] == per_item


def test_sift_band_constants_ride_the_plan(mesh8):
    """A SIFT node charges its per-config band-operator constants as a
    transient (same arrays feed the einsum and the banded kernel)."""
    from keystone_tpu.analysis.resources import sift_band_operator_nbytes
    from keystone_tpu.nodes.images.extractors import SIFTExtractor

    h, w = 64, 80
    node = SIFTExtractor(step=8, bin_size=4, num_scales=2, scale_step=1)
    report = node.check(jax.ShapeDtypeStruct((h, w), np.float32))
    entries = [e for e in report.plan.entries
               if e["operator"] == "SIFTExtractor"]
    assert len(entries) == 1
    want = sift_band_operator_nbytes(h, w, 8, 4, 2, 1)
    assert want > 0
    assert entries[0]["transient_nbytes"] == want


# -- streamed plan vs measured ledger (satellite: parity test) ---------------

def _slow(ad):
    time.sleep(0.01)  # let the producer saturate the double buffer
    return ad


def test_streamed_plan_bounds_measured_peak(mesh8):
    """Streamed CIFAR-shaped fit under an asserted budget: the static
    plan must bound the measured ledger peak from above (hard
    guarantee: the slot semaphore can never stage past the plan) and,
    with a saturated buffer, from below within 1.5x (the acceptance
    tolerance — the plan is tight, not just safe)."""
    n, chunk, depth = 2048, 256, 2
    rng = np.random.RandomState(0)
    imgs = (rng.rand(n, 32 * 32 * 3) * 255).astype(np.uint8)
    y = rng.randint(0, 10, n)
    L = np.eye(10, dtype=np.float32)[y]
    stream = StreamingDataset.from_numpy(
        imgs, chunk_size=chunk, prefetch_depth=depth,
        compute_dtype=np.float32, tag="plan-parity")
    plan = stream.static_plan_nbytes()
    # u8 wire + f32 working copy + u8 transient during the cast
    w = chunk * 32 * 32 * 3
    assert plan == pytest.approx(depth * w + 4 * w + w)
    with PipelineTrace("parity") as tr:
        fit_streaming(BlockLeastSquaresEstimator(1024, 1, lam=0.1),
                      stream.map_chunks(_slow), L, hbm_budget=plan)
    measured = stream.peak_device_nbytes
    assert 0 < measured <= plan
    assert plan <= 1.5 * measured, (plan, measured)
    # the trace closed the loop: plan recorded next to the measurement
    [entry] = tr.streamed_fits
    assert entry["static_plan_nbytes"] == plan
    assert entry["peak_device_nbytes"] == measured
    assert "plan/measured" in tr.summary()
    # round-trips with the artifact
    from keystone_tpu.observability import PipelineTrace as PT

    assert PT.from_json(tr.to_json()).streamed_fits == [entry]


def test_static_budget_rejects_before_any_staging(mesh8):
    """Over-budget geometry dies on the STATIC check: no chunk is ever
    decoded or staged (the source would record the attempt)."""
    pulls = []

    def source():
        pulls.append(1)
        yield {"x": np.zeros((64, 8), np.float32)}

    stream = StreamingDataset.from_chunks(source, chunk_size=64)
    stream._element_probe = lambda: {
        "x": jax.ShapeDtypeStruct((8,), np.float32)}
    with pytest.raises(MemoryError, match="before any chunk"):
        fit_streaming(_Scaler(), stream, hbm_budget=64.0)
    assert not pulls  # rejected device-free, source untouched


def _Scaler():
    from keystone_tpu.nodes.stats import StandardScaler

    return StandardScaler()


def test_derived_view_shares_root_plan(mesh8):
    X = np.random.RandomState(0).rand(512, 16).astype(np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=64,
                                         prefetch_depth=2)
    view = stream.map_chunks(lambda ad: ad)
    assert view.static_plan_nbytes() == stream.static_plan_nbytes()
    assert stream.static_plan_nbytes() == 3 * 64 * 16 * 4


def test_opaque_stream_has_no_plan_but_runtime_budget_holds(mesh8):
    def source():
        yield np.zeros((64, 8), np.float32)

    stream = StreamingDataset.from_chunks(source, chunk_size=64)
    assert stream.static_plan_nbytes() is None
    with pytest.raises(MemoryError, match="HBM budget"):
        fit_streaming(_Scaler(), stream, hbm_budget=16.0)


# -- graph-level streaming plan ---------------------------------------------

def test_plan_charges_stream_not_logical_size(mesh8):
    """A streamed training input charges its residency bound — depth+1
    chunks — not n * element (the whole point of streaming)."""
    chunk = 128
    X = np.zeros((256, 64), np.float32)  # only shapes matter
    stream = StreamingDataset.from_numpy(X, chunk_size=chunk)
    pipe = _Scaler().with_data(stream)
    report = pipe.to_pipeline().check(
        jax.ShapeDtypeStruct((64,), np.float32))
    ds_entries = [e for e in report.plan.entries
                  if e["operator"] == "Dataset"]
    assert len(ds_entries) == 1
    assert ds_entries[0]["out_nbytes"] == 3 * 128 * 64 * 4
    assert report.plan.fit_peak_nbytes < 64 * 64 * 4 * 100_000
