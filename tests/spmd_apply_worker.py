"""Worker for the cross-process sharded-apply parity test (ISSUE 18
tentpole b): each member of a 2-process ``DryrunWorld`` builds the
WORLD data mesh (data axis spanning both hosts' devices), places the
same fitted mappers' weights row-sharded across it, and applies its
LOCAL row block through ``sharded_apply`` — the real
``host_local_array_to_global_array`` + in-body ``all_gather`` path the
single-process 8-virtual-device tests (``test_spmd_apply.py``) can
only approximate.

Parity is asserted IN the worker at the acceptance bar: <= 1e-5
against the single-host ``model.apply`` of the same local rows, with
IDENTICAL prediction argmax, across bucket sizes including ragged
tails (local row counts not divisible by the per-host device count).
A green exit prints ``SPMD_APPLY_OK``.

Usage (the launcher appends the positionals)::

    python tests/spmd_apply_worker.py <process_id> <num_processes> <port>
"""
import sys

import numpy as np


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    import jax

    from keystone_tpu.parallel.mesh import (
        initialize_distributed,
        mesh_scope,
        world_data_mesh,
    )

    initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()

    from keystone_tpu.nodes.learning.linear import (
        BlockLinearMapper,
        LinearMapper,
    )
    from keystone_tpu.nodes.stats import StandardScalerModel
    from keystone_tpu.parallel.spmd_apply import sharded_apply

    d, k = 37, 5  # divides neither the shard count nor the 16-row blocks
    rng = np.random.RandomState(0)  # same fitted state on every host
    affine = LinearMapper(
        rng.randn(d, k).astype(np.float32),
        intercept=rng.randn(k).astype(np.float32),
        feature_scaler=StandardScalerModel(
            rng.randn(d).astype(np.float32),
            (0.5 + rng.rand(d)).astype(np.float32)))
    w = rng.randn(d, k).astype(np.float32)
    block = BlockLinearMapper(
        [w[lo:lo + 16] for lo in range(0, d, 16)], block_size=16,
        intercept=rng.randn(k).astype(np.float32),
        feature_means=rng.randn(d).astype(np.float32))

    mesh = world_data_mesh()
    checked = 0
    with mesh_scope(mesh):
        # local row counts per bucket: every host the same count (the
        # PR 15 bucket contract); 13 is a ragged tail for the 2 local
        # devices, 1 the degenerate pad
        for n_local in (1, 8, 13):
            # per-host data differs (seeded by pid): the global batch
            # is the process-major concat, each host reads back only
            # its own rows
            x = np.random.RandomState(100 + 10 * pid + n_local).randn(
                n_local, d).astype(np.float32)
            for model in (affine, block):
                ref = np.asarray(model.apply(x))
                got = np.asarray(sharded_apply(model, x, mesh))
                assert got.shape == ref.shape, (got.shape, ref.shape)
                rel = (np.abs(ref - got).max()
                       / max(float(np.abs(ref).max()), 1.0))
                assert rel <= 1e-5, (
                    f"pid {pid} bucket {n_local} "
                    f"{type(model).__name__}: delta {rel}")
                assert (np.argmax(ref, axis=1)
                        == np.argmax(got, axis=1)).all()
                checked += 1

    print(f"SPMD_APPLY_OK pid={pid} world={nproc} cases={checked}",
          flush=True)


if __name__ == "__main__":
    main()
