"""The serving chaos suite (ISSUE 19): deterministic trace-replay
loadgen, serve.* fault sites, graceful degradation, and the scenario
catalogue.

The acceptance pins:

* loadgen determinism — same seed replays the identical event
  sequence (first events pinned literally); a different seed differs;
* graceful degradation under injected faults — deadline-expired
  requests shed BEFORE dispatch (zero device time), 429s carry a
  drain-rate Retry-After, a poisoned batch fails classified (500 +
  post-mortem) without wedging the worker, admission/eviction stay
  atomic under mid-warmup faults;
* the two real bugs the suite caught, pinned as regressions:
  (1) a kind="hang" injection at serve.dispatch ignored plane
  shutdown — close() burned its whole join timeout because the
  inject() call passed no abort callback;
  (2) a failed batch SLO-recorded every member request, including
  ones whose futures had already resolved (recorded good earlier in
  the same batch) — double-counting that skewed availability windows;
* interleaving coverage on the real TracedLock yield points
  (tests/sched.py): shed-vs-dispatch exclusivity under seeded chaos
  schedules, and warmup-rollback atomicity under the deterministic
  scheduler;
* the catalogue itself: >= 6 registered scenarios, and a bounded run
  ends clean or classified-with-post-mortem.
"""
import threading
import time

import numpy as np
import pytest

import jax

from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.resilience.faults import FaultPlan
from keystone_tpu.resilience.retry import TransientError
from keystone_tpu.serving import (
    DeadlineExpiredError,
    MicroBatcher,
    PoisonedBatchError,
    QueueFullError,
    ServingPlane,
)
from keystone_tpu.serving.loadgen import (
    ChurnEvent,
    LoadSpec,
    RequestEvent,
    generate_trace,
)

from tests.sched import DeterministicScheduler, chaos

D, K = 6, 2


def _make_fitted(d=D, k=K, seed=0, n=96):
    r = np.random.RandomState(seed)
    X = r.rand(n, d).astype(np.float32)
    Y = r.rand(n, k).astype(np.float32)
    fitted = LinearMapEstimator(lam=1e-3).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()
    return fitted, X


def _sample(d=D):
    return jax.ShapeDtypeStruct((d,), np.float32)


@pytest.fixture
def plane_factory():
    planes = []

    def make(**kw):
        kw.setdefault("max_batch", 8)
        plane = ServingPlane(**kw)
        planes.append(plane)
        return plane

    yield make
    for plane in planes:
        plane.close()


def _serving_plane(make, name="m", **kw):
    fitted, X = _make_fitted()
    plane = make(**kw)
    plane.start()
    plane.admit(name, fitted, _sample())
    return plane, X


# -- loadgen determinism ----------------------------------------------------

_PIN_SPEC = dict(seed=0, duration_s=1.0, rate_rps=50.0,
                 arrival="poisson", models=("a", "b", "c"),
                 zipf_s=1.2, sizes=(1, 2, 4))


def test_loadgen_same_seed_identical_and_pinned():
    t1 = generate_trace(LoadSpec(**_PIN_SPEC))
    t2 = generate_trace(LoadSpec(**_PIN_SPEC))
    assert t1.arrivals == t2.arrivals
    assert t1.churn == t2.churn
    # the first events, pinned literally: a refactor that changes RNG
    # draw ORDER silently reshuffles every scenario's traffic and
    # invalidates the recorded floors — it must fail here by value
    first = t1.arrivals[0]
    assert first == RequestEvent(
        t_s=pytest.approx(0.015917490163262202), model="a", n=2, seq=0)
    assert t1.arrivals[1].model == "a" and t1.arrivals[1].n == 1
    assert t1.arrivals[2].t_s == pytest.approx(0.05950056833866034)
    # Zipf popularity is skewed but not degenerate
    models = {ev.model for ev in t1.arrivals}
    assert "a" in models and len(models) >= 2


def test_loadgen_different_seed_differs():
    spec1 = LoadSpec(**_PIN_SPEC)
    spec2 = LoadSpec(**{**_PIN_SPEC, "seed": 1})
    assert generate_trace(spec1).arrivals != generate_trace(spec2).arrivals


def test_loadgen_spec_validation_and_churn_ordering():
    with pytest.raises(ValueError):
        LoadSpec(**{**_PIN_SPEC, "arrival": "flat"})
    with pytest.raises(ValueError):
        LoadSpec(**{**_PIN_SPEC, "rate_rps": 0.0})
    spec = LoadSpec(**{**_PIN_SPEC, "churn": (
        ChurnEvent(t_s=0.5, action="evict", model="a"),
        ChurnEvent(t_s=0.7, action="readmit", model="a"))})
    trace = generate_trace(spec)
    assert [c.action for c in trace.churn] == ["evict", "readmit"]
    # arrivals are time-ordered with sequential seq
    ts = [ev.t_s for ev in trace.arrivals]
    assert ts == sorted(ts)
    assert [ev.seq for ev in trace.arrivals] == list(range(len(ts)))


# -- graceful degradation ---------------------------------------------------

def test_queue_full_carries_retry_after_hint():
    b = MicroBatcher(queue_depth=1, submit_timeout_s=0.01)
    b.submit("m", np.zeros((1, D), np.float32), 1)
    with pytest.raises(QueueFullError) as ei:
        b.submit("m", np.zeros((1, D), np.float32), 1)
    # never-drained queue: the hint falls back to the submit timeout
    assert ei.value.retry_after_s > 0
    b.close()


def test_deadline_shed_before_dispatch(plane_factory):
    plane, X = _serving_plane(plane_factory)
    reg = MetricsRegistry.get_or_create()
    shed0 = reg.counter("serving.shed_total").value
    expired0 = reg.counter("serving.deadline_expired_total").value
    collected = []
    orig_collect = plane._collect

    def counting_collect(entry, ds, rows):
        collected.append(rows)
        return orig_collect(entry, ds, rows)

    plane._collect = counting_collect
    # a deadline that is already past when the worker reads its clock:
    # the request must fail 504-shaped without touching the device
    req = plane.submit_request("m", X[:2], deadline_ms=1e-4)
    with pytest.raises(DeadlineExpiredError):
        req.future.result(timeout=10.0)
    assert collected == []  # zero device dispatches for the shed batch
    assert reg.counter("serving.shed_total").value == shed0 + 1
    assert (reg.counter("serving.deadline_expired_total").value
            == expired0 + 1)
    # the worker is untouched: the next undeadlined request serves
    out = plane.predict("m", X[:3], timeout_s=10.0)
    assert np.asarray(out).shape == (3, K)


def test_poisoned_batch_fails_classified_and_worker_survives(
        plane_factory):
    plane, X = _serving_plane(plane_factory,
                              postmortem_min_interval_s=0.0)
    reg = MetricsRegistry.get_or_create()
    poisoned0 = reg.counter("serving.poisoned_batches_total").value
    with FaultPlan(0) as fp:
        fp.add("serve.dispatch", kind="corrupt", count=1)
        with pytest.raises(PoisonedBatchError) as ei:
            plane.predict("m", X[:4], timeout_s=10.0)
    # classified: the error carries its post-mortem artifact
    assert getattr(ei.value, "postmortem_path", None)
    assert (reg.counter("serving.poisoned_batches_total").value
            == poisoned0 + 1)
    # the worker survives: the very next batch serves clean
    out = plane.predict("m", X[:4], timeout_s=10.0)
    assert np.isfinite(np.asarray(out)).all()


def test_regression_hang_injection_aborts_on_close(plane_factory):
    # REAL BUG (found by the straggler scenario work): the
    # serve.dispatch inject() passed no abort callback, so a
    # kind="hang" fault ignored plane shutdown and close() burned its
    # entire worker-join timeout waiting out the hang
    plane, X = _serving_plane(plane_factory)
    with FaultPlan(0) as fp:
        fp.add("serve.dispatch", kind="hang", delay_s=8.0, count=1)
        plane.submit("m", X[:2])
        time.sleep(0.3)  # let the worker enter the hung dispatch
        worker = plane._worker
        t0 = time.perf_counter()
        plane.close()
        elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, (
        f"close() took {elapsed:.1f}s under a hung dispatch — the "
        "hang abort regressed")
    assert worker is not None and not worker.is_alive()


def test_regression_failed_batch_records_each_request_once(
        plane_factory):
    # REAL BUG (found by the chaos suite): the batch except path
    # SLO-recorded ok=False for EVERY member request, including ones
    # whose futures had already resolved and been recorded good
    # earlier in _serve_batch — each late-epilogue failure
    # double-counted the whole batch and skewed availability windows
    plane, X = _serving_plane(plane_factory,
                              postmortem_min_interval_s=0.0)
    reg = MetricsRegistry.get_or_create()
    errors0 = reg.counter("serving.errors_total").value

    def boom(*a, **kw):
        raise RuntimeError("late epilogue failure")

    plane._record_batch_trace = boom
    good0, bad0 = plane.slo.totals()
    out = plane.predict("m", X[:2], timeout_s=10.0)  # client still wins
    assert np.asarray(out).shape == (2, K)
    deadline = time.monotonic() + 5.0
    while (reg.counter("serving.errors_total").value == errors0
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert reg.counter("serving.errors_total").value == errors0 + 1
    good, bad = plane.slo.totals()
    assert good - good0 == 1
    assert bad - bad0 == 0, (
        "a request whose future already resolved was re-recorded "
        "ok=False by the failure epilogue")


def test_admit_fault_mid_warmup_rolls_back_atomically(plane_factory):
    fitted, X = _make_fitted()
    plane = plane_factory()
    plane.start()
    with FaultPlan(0) as fp:
        # after=1 skips the pre-mutation visit: the error lands on the
        # FIRST warmup-bucket visit, mid-warmup by construction
        fp.add("serve.admit", kind="error", after=1, count=1)
        with pytest.raises(TransientError):
            plane.admit("m", fitted, _sample())
        assert fp.injections("serve.admit") == 1
    s = plane.state()
    assert "m" not in {m["name"] for m in s["models"]}
    assert s["warming"] == 0
    assert plane.ledger.used() == 0, "failed admission kept its charge"
    assert plane.ready()
    # nothing half-registered: the same admission succeeds on retry
    plane.admit("m", fitted, _sample())
    out = plane.predict("m", X[:2], timeout_s=10.0)
    assert np.asarray(out).shape == (2, K)


def test_evict_fault_leaves_model_serving(plane_factory):
    plane, X = _serving_plane(plane_factory)
    with FaultPlan(0) as fp:
        fp.add("serve.evict", kind="error", count=1)
        with pytest.raises(TransientError):
            plane.evict("m")
    s = plane.state()
    assert "m" in {m["name"] for m in s["models"]}
    assert "m" not in s["evicted"]
    out = plane.predict("m", X[:2], timeout_s=10.0)
    assert np.asarray(out).shape == (2, K)
    plane.evict("m")  # the clean eviction still works afterwards
    assert "m" in plane.state()["evicted"]


def test_state_stays_coherent_mid_warmup(plane_factory):
    fitted, X = _make_fitted()
    plane = plane_factory()
    plane.start()
    hold = threading.Event()
    release = threading.Event()
    orig_warm = plane._warm

    def slow_warm(entry):
        hold.set()
        assert release.wait(10.0)
        return orig_warm(entry)

    plane._warm = slow_warm
    t = threading.Thread(
        target=lambda: plane.admit("m", fitted, _sample()), daemon=True)
    t.start()
    assert hold.wait(10.0)
    # one lock hold computes the whole verdict: a warming model is
    # counted in `warming`, absent from BOTH the ready and evicted
    # lists, and readiness is false — never a half-published mixture
    s = plane.state()
    assert s["warming"] == 1
    assert not s["ready"]
    warming_names = {m["name"] for m in s["models"] if not m["ready"]}
    ready_names = {m["name"] for m in s["models"] if m["ready"]}
    # coherent mid-warmup instant: "m" may appear in the model list
    # only as not-ready, never ready, and never as evicted
    assert "m" not in ready_names
    assert "m" not in s["evicted"]
    release.set()
    t.join(timeout=30.0)
    s = plane.state()
    assert s["ready"] and s["warming"] == 0
    assert "m" in {m["name"] for m in s["models"] if m["ready"]}
    assert warming_names <= {"m"}


# -- interleavings on the real yield points ---------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_shed_vs_dispatch_exclusive_under_chaos(seed, plane_factory):
    """Seeded perturbation at every TracedLock/TracedSemaphore yield
    point while borderline-deadline requests race the worker: each
    request resolves EXACTLY once, to either a real output or a
    DeadlineExpiredError — and a request that was expired when its
    batch formed never reaches dispatch (the scenarios' dispatch guard
    watches every batch)."""
    from keystone_tpu.serving.scenarios import _guard_dispatch

    plane, X = _serving_plane(plane_factory)
    violations = []
    _guard_dispatch(plane, violations)
    reqs = []
    with chaos(seed):
        for i in range(24):
            # deadlines straddle the worker's take latency, so some
            # requests shed and some serve, schedule-dependently
            reqs.append(plane.submit_request(
                "m", X[:1 + i % 3], deadline_ms=0.05 + (i % 5) * 0.2))
        outcomes = {"ok": 0, "shed": 0}
        for req in reqs:
            try:
                out = req.future.result(timeout=10.0)
                assert np.asarray(out).shape == (req.n, K)
                outcomes["ok"] += 1
            except DeadlineExpiredError:
                outcomes["shed"] += 1
    assert outcomes["ok"] + outcomes["shed"] == len(reqs)
    assert violations == [], violations
    # the plane survived the storm
    assert np.asarray(plane.predict("m", X[:2])).shape == (2, K)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_warmup_rollback_atomic_under_scheduler(seed, plane_factory):
    """Deterministic-scheduler interleavings of a mid-warmup admission
    fault against a concurrent submitter: whatever the schedule, the
    submitter only ever sees typed routing verdicts, and the failed
    admission leaves NOTHING behind — no entry, no warming count, no
    ledger charge — so the retry admission succeeds."""
    fitted, X = _make_fitted()
    plane = plane_factory()
    plane.start()
    submit_verdicts = []

    def admitter():
        with pytest.raises(TransientError):
            plane.admit("m", fitted, _sample())

    def submitter():
        for _ in range(4):
            try:
                plane.predict("m", X[:2], timeout_s=5.0)
                submit_verdicts.append("ok")
            except Exception as exc:
                submit_verdicts.append(type(exc).__name__)

    with FaultPlan(seed) as fp:
        fp.add("serve.admit", kind="error", after=1, count=1)
        sched = DeterministicScheduler(seed=seed)
        sched.spawn(admitter, name="admit")
        sched.spawn(submitter, name="submit")
        with sched:
            sched.run()
        assert fp.injections("serve.admit") == 1
    # the submitter saw only typed verdicts, never a raw internal error
    assert set(submit_verdicts) <= {"ok", "ModelNotAdmitted",
                                    "ModelWarming"}
    s = plane.state()
    assert "m" not in {m["name"] for m in s["models"]}
    assert s["warming"] == 0
    assert plane.ledger.used() == 0
    plane.admit("m", fitted, _sample())
    assert np.asarray(plane.predict("m", X[:2])).shape == (2, K)


# -- the catalogue ----------------------------------------------------------

def test_catalogue_registers_required_scenarios():
    from keystone_tpu.serving.scenarios import SCENARIOS, load_catalogue

    load_catalogue()
    assert len(SCENARIOS) >= 8
    assert {"burst", "diurnal", "zipf_churn", "straggler_dispatch",
            "poisoned_batch", "overload_shed",
            "replica_death", "migration_under_load"} <= set(SCENARIOS)
    for sc in SCENARIOS.values():
        assert sc.floors.p99_ms > 0
        assert 0 < sc.floors.availability <= 1.0


def test_catalogue_scenario_runs_clean_or_classified():
    from keystone_tpu.serving.scenarios import run_scenario

    res = run_scenario("burst", seed=0, duration_s=0.4)
    # a bounded run either holds its floors or fails CLASSIFIED: the
    # violation writes a post-mortem naming scenario and seed
    if not res.clean:
        assert res.postmortem_path, res.violations
    assert res.report.outcomes["unclassified"] == 0
    assert res.p99_ms >= 0.0 and 0.0 <= res.availability <= 1.0
