"""NLP node tests (mirrors the reference's nlp suites: TokenizerSuite,
NGramsFeaturizerSuite, NGramsHashingTFSuite, WordFrequencyEncoderSuite,
StupidBackoffSuite, NaiveBitPackIndexerSuite)."""
import numpy as np
import pytest

from keystone_tpu.nodes.nlp import (
    HashingTF,
    LowerCase,
    NaiveBitPackIndexer,
    NGram,
    NGramsCounts,
    NGramsFeaturizer,
    NGramsHashingTF,
    StupidBackoffEstimator,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
    java_string_hash,
)
from keystone_tpu.nodes.stats import TermFrequency
from keystone_tpu.nodes.util import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseVector,
    Sparsify,
    sparse_batch,
)
from keystone_tpu.parallel.dataset import HostDataset


def test_tokenizer_trim_lowercase():
    assert Trim().apply("  hi there ") == "hi there"
    assert LowerCase().apply("MiXeD") == "mixed"
    assert Tokenizer().apply("Hello, world! it's fine") == [
        "Hello", "world", "it", "s", "fine"]
    assert Tokenizer(r"\s+").apply("a b  c") == ["a", "b", "c"]


def test_java_string_hash():
    # values verified against JVM String.hashCode
    assert java_string_hash("Seq") == 83007
    assert java_string_hash("a") == 97
    assert java_string_hash("ab") == 3105
    assert java_string_hash("") == 0


def test_ngrams_featurizer_orders():
    grams = NGramsFeaturizer([1, 2, 3]).apply(["a", "b", "c"])
    assert grams == [("a",), ("a", "b"), ("a", "b", "c"),
                     ("b",), ("b", "c"), ("c",)]
    bigrams = NGramsFeaturizer([2]).apply(["a", "b", "c"])
    assert bigrams == [("a", "b"), ("b", "c")]


def test_ngrams_featurizer_rejects_bad_orders():
    with pytest.raises(AssertionError):
        NGramsFeaturizer([1, 3])
    with pytest.raises(AssertionError):
        NGramsFeaturizer([0, 1])


def test_ngrams_counts_sorted_desc():
    docs = HostDataset([
        NGramsFeaturizer([1]).apply("a b a c a b".split()),
    ])
    pairs = NGramsCounts().apply_dataset(docs).collect()
    assert pairs[0] == (NGram(("a",)), 3)
    assert pairs[1] == (NGram(("b",)), 2)
    assert pairs[2] == (NGram(("c",)), 1)


def test_ngrams_hashing_tf_equals_featurize_then_hash():
    doc = "the quick brown fox jumps over the lazy dog the quick".split()
    for orders in ([1, 2], [2, 3], [1, 2, 3, 4]):
        fused = NGramsHashingTF(orders, 1 << 12).apply(doc)
        staged = HashingTF(1 << 12).apply(NGramsFeaturizer(orders).apply(doc))
        assert fused == staged


def test_hashing_tf_counts():
    sv = HashingTF(1000).apply(["x", "y", "x"])
    assert sv.size == 1000 and sv.values.sum() == 3.0


def test_term_frequency_weighting():
    out = TermFrequency(lambda x: np.log(x) + 1).apply(["a", "a", "b"])
    assert out[0][0] == "a" and abs(out[0][1] - (np.log(2) + 1)) < 1e-12
    assert out[1] == ("b", 1.0)


def test_word_frequency_encoder():
    docs = HostDataset(["b a a c a b".split(), "a d".split()])
    model = WordFrequencyEncoder().fit(docs)
    # 'a' x4 -> 0, 'b' x2 -> 1, then 'c', 'd' by first appearance
    assert model.apply(["a", "b", "c", "d", "zzz"]) == [0, 1, 2, 3, -1]
    assert model.unigram_counts[0] == 4
    assert model.unigram_counts[1] == 2


def test_sparse_vectorizer_and_common_features():
    data = HostDataset([
        [("a", 1.0), ("b", 2.0)],
        [("a", 1.0), ("c", 1.0)],
        [("a", 1.0), ("b", 1.0)],
    ])
    vec = CommonSparseFeatures(2).fit(data)
    sv = vec.apply([("a", 5.0), ("c", 9.0), ("b", 1.0)])
    # feature space = {a:0, b:1}; c dropped
    assert sv.size == 2
    np.testing.assert_array_equal(sv.indices, [0, 1])
    np.testing.assert_array_equal(sv.values, [5.0, 1.0])

    vec_all = AllSparseFeatures().fit(data)
    assert vec_all.apply([("c", 1.0)]).todense().tolist() == [0.0, 0.0, 1.0]


def test_sparsify_and_batch():
    sv = Sparsify().apply(np.array([0.0, 3.0, 0.0, 2.0], np.float32))
    assert sv.nnz == 2
    idx, vals, size = sparse_batch([sv, SparseVector([0], [1.0], 4)])
    assert idx.shape == vals.shape == (2, 2) and size == 4
    np.testing.assert_array_equal(idx[0], [1, 3])
    np.testing.assert_array_equal(vals[1], [1.0, 0.0])


def test_naive_bitpack_indexer():
    idx = NaiveBitPackIndexer()
    for ngram in ([5], [5, 9], [5, 9, 123]):
        packed = idx.pack(ngram)
        assert idx.ngram_order(packed) == len(ngram)
        for pos, w in enumerate(ngram):
            assert idx.unpack(packed, pos) == w
    tri = idx.pack([5, 9, 123])
    assert idx.ngram_order(idx.remove_farthest_word(tri)) == 2
    assert idx.unpack(idx.remove_farthest_word(tri), 0) == 9
    assert idx.unpack(idx.remove_current_word(tri), 1) == 9


def _fit_backoff(corpus, orders=(2, 3)):
    tokens = [line.split() for line in corpus]
    unigrams = {}
    for line in tokens:
        for w in line:
            unigrams[w] = unigrams.get(w, 0) + 1
    grams = HostDataset([NGramsFeaturizer(list(orders)).apply(t) for t in tokens])
    counts = NGramsCounts().apply_dataset(grams)
    return StupidBackoffEstimator(unigrams).fit(counts), unigrams


def test_stupid_backoff_seen_trigram():
    model, unigrams = _fit_backoff(["a b c d", "a b c e"])
    # S(c | a b) = freq(abc)/freq(ab) = 2/2 = 1
    assert model.score(NGram(("a", "b", "c"))) == pytest.approx(1.0)
    # S(d | b c) = freq(bcd)/freq(bc) = 1/2
    assert model.score(NGram(("b", "c", "d"))) == pytest.approx(0.5)


def test_stupid_backoff_backs_off():
    model, unigrams = _fit_backoff(["a b c d", "a b c e"])
    n = sum(unigrams.values())
    # unseen trigram (d, b, c): backoff to (b, c): freq(bc)/freq(b)=2/2
    assert model.score(NGram(("d", "b", "c"))) == pytest.approx(0.4 * 1.0)
    # unseen everywhere: alpha^2 * unigram score
    assert model.score(NGram(("e", "d", "a"))) == pytest.approx(
        0.4 * 0.4 * unigrams["a"] / n)
    # scores in [0, 1]
    for g, s in model.scores.items():
        assert 0.0 <= s <= 1.0
