"""Learning node tests vs closed-form / sklearn-style golden checks
(mirrors the reference's PCA/KMeans/GMM/LBFGS suites)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning import (
    ApproximatePCAEstimator,
    DenseLBFGSwithL2,
    DistributedPCAEstimator,
    GaussianMixtureModelEstimator,
    KMeansPlusPlusEstimator,
    LinearDiscriminantAnalysis,
    LocalLeastSquaresEstimator,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
    PCAEstimator,
)
from keystone_tpu.parallel.dataset import ArrayDataset


# -- PCA -------------------------------------------------------------------

def pca_problem(n=300, d=10, seed=0):
    rng = np.random.RandomState(seed)
    base = rng.randn(n, 3).astype(np.float32)
    mix = rng.randn(3, d).astype(np.float32) * 3
    return base @ mix + 0.05 * rng.randn(n, d).astype(np.float32)


def numpy_pca(X, dims):
    Xc = X - X.mean(0)
    _, _, vt = np.linalg.svd(Xc, full_matrices=False)
    pca = vt.T
    col_max, abs_max = pca.max(0), np.abs(pca).max(0)
    return pca * np.where(col_max == abs_max, 1.0, -1.0)[None, :][:, : dims][
        ..., : dims
    ] if False else (pca * np.where(col_max == abs_max, 1.0, -1.0))[:, :dims]


def test_local_pca_matches_numpy():
    X = pca_problem()
    model = PCAEstimator(3).fit(X)
    expect = numpy_pca(X, 3)
    np.testing.assert_allclose(np.abs(model.pca_mat), np.abs(expect), rtol=5e-2, atol=5e-2)
    # sign convention: largest-|.| entry of each column positive
    for j in range(3):
        col = model.pca_mat[:, j]
        assert col[np.argmax(np.abs(col))] > 0


def test_distributed_pca_matches_local():
    X = pca_problem(n=512, d=8, seed=1)
    local = PCAEstimator(3).fit(X)
    dist = DistributedPCAEstimator(3).fit(ArrayDataset.from_numpy(X))
    np.testing.assert_allclose(
        np.abs(dist.pca_mat), np.abs(local.pca_mat), rtol=5e-2, atol=5e-2
    )


def test_approximate_pca_spans_same_subspace():
    X = pca_problem(n=400, d=12, seed=2)
    exact = PCAEstimator(3).fit(X).pca_mat
    approx = ApproximatePCAEstimator(3, q=5, seed=0).fit(X).pca_mat
    # subspace angle check: projections should be ~equal
    P_exact = exact @ exact.T
    P_approx = approx @ approx.T
    np.testing.assert_allclose(P_exact, P_approx, atol=0.05)


# -- KMeans ----------------------------------------------------------------

def test_kmeans_recovers_separated_clusters():
    rng = np.random.RandomState(3)
    centers = np.array([[0, 0], [10, 10], [-10, 10]], np.float32)
    labels = rng.randint(0, 3, 600)
    X = centers[labels] + 0.3 * rng.randn(600, 2).astype(np.float32)
    model = KMeansPlusPlusEstimator(3, 20, seed=0).fit(X)
    # each found center close to a true center
    found = model.means
    for c in centers:
        assert np.min(np.linalg.norm(found - c, axis=1)) < 0.5
    # assignment is a one-hot of the nearest center
    a = model(X[:8]).numpy()
    assert a.shape == (8, 3)
    np.testing.assert_allclose(a.sum(axis=1), 1.0)


def test_kmeans_one_round_is_kmeanspp_init():
    rng = np.random.RandomState(4)
    X = rng.randn(50, 4).astype(np.float32)
    m1 = KMeansPlusPlusEstimator(5, 1, seed=7).fit(X)
    m2 = KMeansPlusPlusEstimator(5, 1, seed=7).fit(X)
    np.testing.assert_array_equal(m1.means, m2.means)  # deterministic


# -- GMM -------------------------------------------------------------------

def test_gmm_recovers_two_gaussians():
    """Reference EncEvalSuite-style synthetic 2-Gaussian recovery."""
    rng = np.random.RandomState(5)
    n = 2000
    comp = rng.rand(n) < 0.4
    X = np.where(
        comp[:, None],
        rng.randn(n, 2) * 0.5 + np.array([5.0, 5.0]),
        rng.randn(n, 2) * 1.0 + np.array([-3.0, 0.0]),
    ).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(2, max_iterations=50, seed=1).fit(X)
    means = gmm.means.T  # (k, d)
    # one mean near each true center
    assert min(np.linalg.norm(means - [5, 5], axis=1).min(),
               np.linalg.norm(means - [-3, 0], axis=1).min()) < 0.5
    assert np.linalg.norm(means - [5, 5], axis=1).min() < 0.5
    assert np.linalg.norm(means - [-3, 0], axis=1).min() < 0.5
    w = sorted(gmm.weights)
    assert abs(w[0] - 0.4) < 0.1 and abs(w[1] - 0.6) < 0.1
    # posteriors are a thresholded distribution
    q = gmm(X[:5]).numpy()
    np.testing.assert_allclose(q.sum(axis=1), 1.0, rtol=1e-4)


def test_gmm_load_csv(tmp_path):
    means = np.array([[1.0, 2.0], [3.0, 4.0]])
    variances = np.array([[0.1, 0.2], [0.3, 0.4]])
    weights = np.array([0.5, 0.5])
    np.savetxt(tmp_path / "m.csv", means, delimiter=",")
    np.savetxt(tmp_path / "v.csv", variances, delimiter=",")
    np.savetxt(tmp_path / "w.csv", weights[None], delimiter=",")
    from keystone_tpu.nodes.learning import GaussianMixtureModel

    gmm = GaussianMixtureModel.load(
        str(tmp_path / "m.csv"), str(tmp_path / "v.csv"), str(tmp_path / "w.csv")
    )
    assert gmm.k == 2 and gmm.dim == 2


# -- LBFGS -----------------------------------------------------------------

def test_dense_lbfgs_matches_ridge():
    rng = np.random.RandomState(6)
    n, d, k = 300, 20, 3
    A = rng.randn(n, d).astype(np.float32)
    W_true = rng.randn(d, k).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    Y = A @ W_true + b + 0.01 * rng.randn(n, k).astype(np.float32)
    lam = 0.01
    model = DenseLBFGSwithL2(num_iterations=200, lam=lam, convergence_tol=1e-8).fit(A, Y)
    # closed form: centered ridge with lambda * n (loss has 1/n on data term)
    Am, Ym = A.mean(0), Y.mean(0)
    Ac, Yc = (A - Am).astype(np.float64), (Y - Ym).astype(np.float64)
    expect = np.linalg.solve(Ac.T @ Ac + lam * n * np.eye(d), Ac.T @ Yc)
    np.testing.assert_allclose(model.weights, expect, rtol=5e-2, atol=5e-2)
    pred = model(A).numpy()
    expect_pred = (A - Am) @ expect + Ym
    np.testing.assert_allclose(pred, expect_pred, rtol=5e-2, atol=5e-2)


# -- Classifiers -----------------------------------------------------------

def test_naive_bayes_matches_manual():
    rng = np.random.RandomState(7)
    X = rng.randint(0, 5, size=(100, 6)).astype(np.float32)
    y = rng.randint(0, 3, size=100).astype(np.int32)
    model = NaiveBayesEstimator(3, lam=1.0).fit(X, y)
    # manual multinomial NB
    for c in range(3):
        nc = (y == c).sum()
        pi_c = np.log((nc + 1.0) / (100 + 3 * 1.0))
        np.testing.assert_allclose(model.pi[c], pi_c, rtol=1e-5)
        sums = X[y == c].sum(0)
        theta_c = np.log((sums + 1.0) / (sums.sum() + 6 * 1.0))
        np.testing.assert_allclose(model.theta[c], theta_c, rtol=1e-4)
    scores = model(X[:4]).numpy()
    assert scores.shape == (4, 3)


def test_logistic_regression_separable():
    rng = np.random.RandomState(8)
    n = 400
    y = rng.randint(0, 3, n).astype(np.int32)
    centers = np.array([[2, 0], [-2, 2], [0, -3]], np.float32)
    X = centers[y] + 0.3 * rng.randn(n, 2).astype(np.float32)
    model = LogisticRegressionEstimator(3, reg_param=1e-3, num_iters=100).fit(X, y)
    preds = model(X).numpy()
    assert (preds == y).mean() > 0.95


def test_lda_separates_classes():
    rng = np.random.RandomState(9)
    n = 300
    y = rng.randint(0, 2, n).astype(np.int32)
    X = np.concatenate(
        [rng.randn(n, 1).astype(np.float32) + 6 * y[:, None], rng.randn(n, 4).astype(np.float32)],
        axis=1,
    )
    model = LinearDiscriminantAnalysis(1).fit(X, y)
    proj = X @ model.weights
    m0, m1 = proj[y == 0].mean(), proj[y == 1].mean()
    s = 0.5 * (proj[y == 0].std() + proj[y == 1].std())  # within-class spread
    assert abs(m0 - m1) / s > 3.0  # strong separation along learned axis


def test_local_least_squares_dual_matches_primal():
    rng = np.random.RandomState(10)
    n, d, k = 40, 200, 2
    A = rng.randn(n, d).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    lam = 1.0
    model = LocalLeastSquaresEstimator(lam).fit(A, Y)
    Am, Ym = A.mean(0), Y.mean(0)
    Ac, Yc = (A - Am).astype(np.float64), (Y - Ym).astype(np.float64)
    expect = np.linalg.solve(Ac.T @ Ac + lam * np.eye(d), Ac.T @ Yc)
    np.testing.assert_allclose(model.weights, expect, rtol=2e-2, atol=2e-2)


def test_kmeans_emptied_cluster_keeps_center():
    """A center that captures zero points during Lloyd's must keep its
    previous position, not divide 0/0 into NaN (which would poison the
    GMM kmeans++ init path)."""
    # EXACT duplicates, k=3: two centers must land on identical
    # coordinates, argmin ties route all mass to the first, and the
    # duplicate center is guaranteed empty every Lloyd step
    X = np.repeat(
        np.array([[0.0, 0.0], [10.0, 10.0]], np.float32), 40, axis=0)
    import jax
    import jax.numpy as jnp

    model = KMeansPlusPlusEstimator(3, 25, seed=1).fit(X)
    assert np.isfinite(np.asarray(model.means)).all()
    # every point's ASSIGNED CENTER has finite coordinates (the one-hot
    # itself is always finite, so assert through the means)
    assign = np.asarray(jax.vmap(model.apply)(jnp.asarray(X)))
    assert np.isfinite(assign @ np.asarray(model.means)).all()


def test_naive_bayes_sparse_matches_dense(mesh8):
    """The sparse host path (text pipeline) must produce the same model
    and scores as the dense device path."""
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.util.sparse import SparseVector
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset

    rng = np.random.RandomState(0)
    n, d, k = 48, 30, 4
    dense = (rng.rand(n, d) < 0.2).astype(np.float32) * rng.randint(
        1, 4, (n, d))
    y = rng.randint(0, k, n).astype(np.int32)
    sparse_items = [
        SparseVector(np.nonzero(row)[0], row[np.nonzero(row)[0]], d)
        for row in dense
    ]

    est = NaiveBayesEstimator(k)
    m_dense = est.fit(ArrayDataset.from_numpy(dense),
                      ArrayDataset.from_numpy(y))
    m_sparse = est.fit(HostDataset(sparse_items),
                       ArrayDataset.from_numpy(y))
    np.testing.assert_allclose(m_sparse.pi, m_dense.pi, rtol=1e-5)
    np.testing.assert_allclose(m_sparse.theta, m_dense.theta, rtol=1e-5)

    dense_scores = m_dense.apply_dataset(
        ArrayDataset.from_numpy(dense)).numpy()
    sparse_scores = m_sparse.apply_dataset(HostDataset(sparse_items))
    np.testing.assert_allclose(
        np.asarray(sparse_scores.numpy()), dense_scores, rtol=1e-4,
        atol=1e-4)
    one = np.asarray(m_sparse.apply(sparse_items[0]))
    np.testing.assert_allclose(one, dense_scores[0], rtol=1e-4, atol=1e-4)


def test_logistic_sparse_matches_dense(mesh8):
    """Sparse COO logistic regression must converge to the dense path's
    model (same objective, same optimizer)."""
    from keystone_tpu.nodes.learning import LogisticRegressionEstimator
    from keystone_tpu.nodes.util.sparse import SparseVector
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset

    rng = np.random.RandomState(1)
    n, d, k = 64, 24, 3
    dense = (rng.rand(n, d) < 0.3).astype(np.float32) * rng.rand(n, d)
    protos = rng.randn(k, d).astype(np.float32)
    y = rng.randint(0, k, n).astype(np.int32)
    dense += protos[y] * 0.5  # separable signal
    sparse_items = [
        SparseVector(np.nonzero(row)[0], row[np.nonzero(row)[0]], d)
        for row in dense
    ]

    est = LogisticRegressionEstimator(num_classes=k, reg_param=1e-2,
                                      num_iters=60)
    m_dense = est.fit(ArrayDataset.from_numpy(dense),
                      ArrayDataset.from_numpy(y))
    m_sparse = est.fit(HostDataset(sparse_items),
                       ArrayDataset.from_numpy(y))
    np.testing.assert_allclose(
        m_sparse.weights, m_dense.weights, rtol=1e-3, atol=1e-3)

    dense_pred = np.asarray(m_dense.apply_dataset(
        ArrayDataset.from_numpy(dense)).numpy())
    sparse_pred = np.asarray(
        m_sparse.apply_dataset(HostDataset(sparse_items)).numpy())
    np.testing.assert_array_equal(sparse_pred, dense_pred)
    one = int(m_sparse.apply(sparse_items[0]))
    assert one == dense_pred[0]
