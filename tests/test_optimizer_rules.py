"""Optimizer rule tests, mirroring the reference's optimizer suites."""
import numpy as np

from keystone_tpu import ArrayDataset, Transformer
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.expression import DatumExpression
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatumOperator, ExpressionOperator
from keystone_tpu.workflow.optimizer.rules import (
    EquivalentNodeMergeRule,
    SavedStateLoadRule,
    UnusedBranchRemovalRule,
)
from keystone_tpu.workflow.prefix import compute_prefix


class T(Transformer):
    def __init__(self, tag):
        self.tag = tag

    def apply(self, x):
        return x


def test_equivalent_node_merge():
    g = Graph()
    g, src = g.add_source()
    g, a1 = g.add_node(T("a"), (src,))
    g, a2 = g.add_node(T("a"), (src,))
    g, b1 = g.add_node(T("b"), (a1,))
    g, b2 = g.add_node(T("b"), (a2,))
    g, s1 = g.add_sink(b1)
    g, s2 = g.add_sink(b2)
    out = g
    # run to fixpoint manually (merging a's makes b's equal)
    for _ in range(5):
        nxt = EquivalentNodeMergeRule().apply(out)
        if nxt == out:
            break
        out = nxt
    assert len(out.nodes) == 2  # one a, one b
    assert out.get_sink_dependency(s1) == out.get_sink_dependency(s2)


def test_merge_requires_equal_params():
    g = Graph()
    g, src = g.add_source()
    g, a1 = g.add_node(T("a"), (src,))
    g, a2 = g.add_node(T("b"), (src,))
    g, s1 = g.add_sink(a1)
    g, s2 = g.add_sink(a2)
    out = EquivalentNodeMergeRule().apply(g)
    assert len(out.nodes) == 2


def test_unused_branch_removal():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(T("a"), (src,))
    g, dead = g.add_node(T("dead"), (src,))
    g, dead2 = g.add_node(T("dead2"), (dead,))
    g, sink = g.add_sink(a)
    out = UnusedBranchRemovalRule().apply(g)
    assert set(out.nodes) == {a}
    assert src in out.sources  # sources are kept


def test_saved_state_load_substitutes_expression():
    env = PipelineEnv.get_or_create()
    g = Graph()
    g, const = g.add_node(DatumOperator(1.0), ())
    g, a = g.add_node(T("a"), (const,))
    g, sink = g.add_sink(a)
    prefix = compute_prefix(g, a)
    assert prefix is not None
    env.state[prefix] = DatumExpression(42.0, eager=True)
    out = SavedStateLoadRule().apply(g)
    op = out.get_operator(a)
    assert isinstance(op, ExpressionOperator)
    assert op.expression.get() == 42.0


def test_prefix_none_below_source():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(T("a"), (src,))
    assert compute_prefix(g, a) is None


def test_prefix_stable_across_equal_graphs():
    def build():
        g = Graph()
        # distinct datum objects -> distinct data identities
        g, c = g.add_node(DatumOperator(np.zeros(3)), ())
        g, a = g.add_node(T("a"), (c,))
        return g, a, c

    g1, a1, c1 = build()
    g2, a2, c2 = build()
    # DatumOperator identity differs -> prefixes differ (bound to data id)
    p1 = compute_prefix(g1, a1)
    p2 = compute_prefix(g2, a2)
    assert p1 != p2
    # but same graph gives same prefix
    assert compute_prefix(g1, a1) == p1
