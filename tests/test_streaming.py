"""Streaming chunked execution: prefetcher mechanics, streamed-vs-
resident estimator parity, out-of-core HBM bounds, and the
non-streamable-fit lint (ISSUE 3 tentpole); dtype-on-the-wire staging,
per-shard H2D, donated carries and the cast-before-transfer lint
(ISSUE 5 tentpole)."""
import threading
import time

import jax
import numpy as np
import pytest

from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.nodes.learning.linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
)
from keystone_tpu.nodes.stats import StandardScaler, StandardScalerModel
from keystone_tpu.parallel.dataset import (
    ArrayDataset,
    device_nbytes,
    ensure_array,
)
from keystone_tpu.parallel.streaming import (
    StreamingDataset,
    fit_streaming,
    is_streamable,
)


def _xy(n=600, d=24, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = (rng.randn(n, d) * (1.0 + rng.rand(d)) + rng.randn(d)).astype(
        np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W + 0.1 * rng.randn(n, k)).astype(np.float32)
    return X, Y


# -- prefetcher mechanics ---------------------------------------------------

def test_chunks_order_shapes_and_ragged_tail():
    X = np.arange(100 * 4, dtype=np.float32).reshape(100, 4)
    stream = StreamingDataset.from_numpy(X, chunk_size=32)
    chunks = list(stream.chunks())
    # ragged tail: 32, 32, 32, 4 — every chunk padded to one shape
    assert [c.n for c in chunks] == [32, 32, 32, 4]
    assert len({c.padded_n for c in chunks}) == 1
    got = np.concatenate([c.numpy() for c in chunks])
    np.testing.assert_array_equal(got, X)
    # the tail chunk's pad rows hold zeros (the invariant reductions use)
    tail = np.asarray(chunks[-1].data)
    assert np.all(tail[chunks[-1].n:] == 0)


def test_chunk_size_rounds_to_shard_multiple():
    X = np.zeros((40, 2), np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=10)
    assert stream.chunk_size % 8 == 0  # 8-device test mesh


def test_reiteration_and_unknown_n_learned():
    X = np.random.RandomState(0).rand(50, 3).astype(np.float32)

    def factory():
        for lo in range(0, 50, 16):
            yield X[lo:lo + 16]

    stream = StreamingDataset.from_chunks(factory, chunk_size=16)
    with pytest.raises(TypeError):
        len(stream)  # n unknown before a pass
    assert sum(c.n for c in stream.chunks()) == 50
    assert len(stream) == 50  # a completed pass pins n
    # second epoch re-opens the source
    assert sum(c.n for c in stream.chunks()) == 50


def test_source_error_propagates():
    def factory():
        yield np.zeros((8, 2), np.float32)
        raise RuntimeError("decode failed")

    stream = StreamingDataset.from_chunks(factory, chunk_size=8)
    with pytest.raises(RuntimeError, match="decode failed"):
        list(stream.chunks())


def test_early_break_stops_producer():
    started = threading.active_count()

    def factory():
        for _ in range(1000):
            yield np.zeros((8, 2), np.float32)

    stream = StreamingDataset.from_chunks(factory, chunk_size=8)
    for i, _ in enumerate(stream.chunks()):
        if i == 2:
            break
    deadline = time.time() + 5.0
    while threading.active_count() > started and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= started


def test_map_is_lazy_and_chunkwise():
    X = np.random.RandomState(0).rand(64, 5).astype(np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=24).map(
        lambda x: x * 2.0)
    got = stream.materialize().numpy()
    np.testing.assert_allclose(got, X * 2.0, rtol=1e-6)


# -- streamed-vs-resident estimator parity ----------------------------------

@pytest.mark.parametrize("chunk_size", [64, 96, 200])
def test_least_squares_streamed_matches_resident(chunk_size):
    """Acceptance: streamed LeastSquares fit matches the device-resident
    fit within 1e-5 relative weight error with identical argmax
    predictions, across chunk sizes including a ragged last chunk."""
    X, Y = _xy()
    ds, ls = ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)
    resident = LinearMapEstimator(lam=0.1)._fit(ds, ls)
    streamed = fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=chunk_size),
        StreamingDataset.from_numpy(Y, chunk_size=chunk_size))
    w_r = np.asarray(resident.weights)
    w_s = np.asarray(streamed.weights)
    assert np.abs(w_r - w_s).max() <= 1e-5 * np.abs(w_r).max()
    pred_r = np.argmax(np.asarray(
        ensure_array(resident.apply_dataset(ds)).numpy()), axis=1)
    pred_s = np.argmax(np.asarray(
        ensure_array(streamed.apply_dataset(ds)).numpy()), axis=1)
    np.testing.assert_array_equal(pred_r, pred_s)


@pytest.mark.parametrize("chunk_size", [96, 250])
def test_block_ls_streamed_matches_resident(chunk_size):
    X, Y = _xy()
    ds, ls = ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)
    est = BlockLeastSquaresEstimator(10, 3, lam=0.1)
    resident = est._fit(ds, ls)
    streamed = fit_streaming(
        BlockLeastSquaresEstimator(10, 3, lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=chunk_size), ls)
    w_r = np.asarray(resident.weights)
    w_s = np.asarray(streamed.weights)
    assert np.abs(w_r - w_s).max() <= 1e-5 * np.abs(w_r).max()
    # block structure preserved
    assert len(streamed.block_weights) == len(resident.block_weights)
    pred_r = np.argmax(np.asarray(
        ensure_array(resident.apply_dataset(ds)).numpy()), axis=1)
    pred_s = np.argmax(np.asarray(
        ensure_array(streamed.apply_dataset(ds)).numpy()), axis=1)
    np.testing.assert_array_equal(pred_r, pred_s)


def test_scaler_streamed_matches_resident():
    X, _ = _xy()
    resident = StandardScaler()._fit(ArrayDataset.from_numpy(X))
    streamed = fit_streaming(
        StandardScaler(), StreamingDataset.from_numpy(X, chunk_size=88))
    np.testing.assert_allclose(resident.mean, streamed.mean, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(resident.std, streamed.std, rtol=1e-5,
                               atol=1e-5)


def test_auto_solver_streamed_finalize_and_decision():
    """LeastSquaresEstimator streams via the shared Gram carry and picks
    a gram-capable solver by cost model at finalize, recording the
    decision with shape_source=streamed."""
    from keystone_tpu.observability import PipelineTrace

    X, Y = _xy(n=400)
    est = LeastSquaresEstimator(lam=0.1)
    assert is_streamable(est)
    with PipelineTrace("t") as tr:
        model = fit_streaming(
            est, StreamingDataset.from_numpy(X, chunk_size=160), Y)
    assert len(tr.solver_decisions) == 1
    d = tr.solver_decisions[0]
    assert d["shape_source"] == "streamed"
    assert d["n"] == 400
    assert d["chosen"] in ("LinearMapEstimator",
                           "BlockLeastSquaresEstimator")
    # the fitted model predicts like the resident exact solve
    resident = LinearMapEstimator(lam=0.1)._fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    ds = ArrayDataset.from_numpy(X)
    pred_r = np.argmax(np.asarray(
        ensure_array(resident.apply_dataset(ds)).numpy()), axis=1)
    pred_s = np.argmax(np.asarray(
        ensure_array(model.apply_dataset(ds)).numpy()), axis=1)
    assert (pred_r == pred_s).mean() > 0.99


def test_label_estimator_fit_routes_streams():
    """LabelEstimator.fit / Estimator.fit route StreamingDatasets through
    the protocol (resident labels are sliced chunk-wise)."""
    X, Y = _xy(n=300)
    model = LinearMapEstimator(lam=0.1).fit(
        StreamingDataset.from_numpy(X, chunk_size=128), Y)
    resident = LinearMapEstimator(lam=0.1)._fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    assert np.abs(np.asarray(model.weights)
                  - np.asarray(resident.weights)).max() <= 1e-4
    scaler = StandardScaler().fit(
        StreamingDataset.from_numpy(X, chunk_size=128))
    assert isinstance(scaler, StandardScalerModel)


def test_streamed_labels_with_resident_data_raise():
    """The chunk loop is data-driven: streamed labels + resident data
    is rejected with a clear error at fit time AND flagged statically."""
    from keystone_tpu.analysis.diagnostics import check_graph

    X, Y = _xy(n=160)
    lstream = StreamingDataset.from_numpy(Y, chunk_size=80)
    with pytest.raises(TypeError, match="labels are a StreamingDataset"):
        LinearMapEstimator(lam=0.1).fit(X, lstream)
    p = LinearMapEstimator(lam=0.1).with_data(
        ArrayDataset.from_numpy(X), lstream)
    rep = check_graph(
        p._graph, {p._source: jax.ShapeDtypeStruct((24,), np.float32)},
        name="labels-stream")
    hits = [d for d in rep.diagnostics if d.code == "non-streamable-fit"]
    assert hits and "LABELS" in hits[0].message


def test_misaligned_label_stream_raises():
    X, Y = _xy(n=200)
    with pytest.raises(ValueError, match="misaligned|ended"):
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=64),
            StreamingDataset.from_numpy(Y[:100], chunk_size=64))


# -- per-chunk transformer application --------------------------------------

def test_transformer_chain_applies_per_chunk():
    """scaler >> linear model applied through apply_dataset on a stream
    matches the resident application exactly (per-chunk structure-keyed
    programs, padded rows re-masked)."""
    X, Y = _xy(n=200)
    ds = ArrayDataset.from_numpy(X)
    scaler = StandardScaler()._fit(ds)
    model = LinearMapEstimator(lam=0.1)._fit(ds, ArrayDataset.from_numpy(Y))
    resident = model.apply_dataset(scaler.apply_dataset(ds)).numpy()
    stream = StreamingDataset.from_numpy(X, chunk_size=64)
    streamed = model.apply_dataset(
        scaler.apply_dataset(stream)).materialize().numpy()
    np.testing.assert_allclose(resident, streamed, rtol=2e-5, atol=2e-5)


def test_fused_chain_streams_per_chunk():
    """A FusedTransformer (scaler >> linear model) applies per chunk
    through ONE param-threaded program and matches the resident fused
    output — fusion and streaming compose."""
    from keystone_tpu.nodes.learning.linear import LinearMapper
    from keystone_tpu.workflow.optimizer.fusion import FusedTransformer

    rng = np.random.RandomState(3)
    X = rng.randn(200, 16).astype(np.float32)
    fused = FusedTransformer([
        StandardScalerModel(rng.randn(16).astype(np.float32),
                            (0.5 + rng.rand(16)).astype(np.float32)),
        LinearMapper(rng.randn(16, 4).astype(np.float32),
                     intercept=rng.randn(4).astype(np.float32)),
    ])
    resident = fused.apply_dataset(ArrayDataset.from_numpy(X)).numpy()
    streamed = fused.apply_dataset(
        StreamingDataset.from_numpy(X, chunk_size=64)).materialize().numpy()
    np.testing.assert_allclose(resident, streamed, rtol=1e-5, atol=1e-5)


def test_host_transformer_rejects_stream():
    from keystone_tpu.workflow.transformer import HostTransformer

    class H(HostTransformer):
        def apply(self, x):
            return x

    X, _ = _xy(n=64)
    with pytest.raises(TypeError, match="host stage"):
        H().apply_dataset(StreamingDataset.from_numpy(X, chunk_size=32))


def test_second_streamed_epoch_compiles_nothing():
    """Acceptance: zero recompiles on the second streamed epoch — all
    chunks (ragged tail included) share one padded shape, so the chain's
    structure-keyed programs compile once in epoch one."""
    import io
    import logging

    X, Y = _xy(n=300)
    ds = ArrayDataset.from_numpy(X)
    scaler = StandardScaler()._fit(ds)
    model = LinearMapEstimator(lam=0.1)._fit(ds, ArrayDataset.from_numpy(Y))

    def epoch():
        stream = StreamingDataset.from_numpy(X, chunk_size=128)
        out = model.apply_dataset(scaler.apply_dataset(stream))
        for chunk in out.chunks():
            jax.block_until_ready(chunk.data)
        # a streamed refit epoch too: accumulate + finalize
        fit_streaming(LinearMapEstimator(lam=0.1),
                      StreamingDataset.from_numpy(X, chunk_size=128), Y)

    epoch()  # warm: one compile per chunk-shape program

    jax.config.update("jax_log_compiles", True)
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    loggers = [logging.getLogger("jax._src.interpreters.pxla"),
               logging.getLogger("jax._src.dispatch")]
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.WARNING)
    try:
        epoch()
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    compiles = [ln for ln in buf.getvalue().splitlines()
                if "Compiling" in ln]
    assert not compiles, compiles


# -- out-of-core HBM bounds -------------------------------------------------

def test_device_residency_bounded():
    """Acceptance: device_nbytes of the stream never exceeds the budget
    (prefetch buffer + chunk working set) while a fit runs on data whose
    TOTAL size exceeds that budget many times over."""
    n, d, chunk, depth = 2048, 16, 64, 2
    X = np.random.RandomState(0).rand(n, d).astype(np.float32)
    Y = np.random.RandomState(1).rand(n, 2).astype(np.float32)
    stream = StreamingDataset.from_numpy(
        X, chunk_size=chunk, prefetch_depth=depth)
    chunk_bytes = chunk * d * 4
    budget = (depth + 1) * chunk_bytes + 4096
    total_bytes = n * d * 4
    assert total_bytes > 5 * budget  # the dataset genuinely exceeds it
    seen = []
    probe = stream.map_chunks(
        lambda ad: (seen.append(device_nbytes(stream)), ad)[1])
    fit_streaming(LinearMapEstimator(lam=0.1), probe, Y,
                  hbm_budget=budget)
    assert seen and max(seen) <= budget
    assert stream.peak_device_nbytes <= budget


def test_residency_holds_depth_plus_one_with_slow_consumer():
    """The documented bound is (prefetch_depth + 1) chunks — depth
    staged-or-queued plus one working. A consumer slower than the
    producer must not let the producer stage a (depth + 2)th chunk
    (staging is slot-gated BEFORE device_put, not after)."""
    n, d, chunk, depth = 512, 8, 64, 2
    X = np.random.RandomState(0).rand(n, d).astype(np.float32)
    stream = StreamingDataset.from_numpy(
        X, chunk_size=chunk, prefetch_depth=depth)
    chunk_bytes = chunk * d * 4
    bound = (depth + 1) * chunk_bytes
    peaks = []
    for _ in stream.chunks():
        time.sleep(0.05)  # slow consumer: the producer runs far ahead
        peaks.append(stream.buffered_nbytes())
    assert max(peaks) <= bound, (max(peaks), bound)
    assert stream.peak_device_nbytes <= bound, (
        stream.peak_device_nbytes, bound)


def test_labels_longer_than_stream_raise():
    X, Y = _xy(n=200)
    # streamed labels longer than the data stream
    with pytest.raises(ValueError, match="misaligned"):
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X[:128], chunk_size=64),
            StreamingDataset.from_numpy(Y, chunk_size=64))
    # resident labels longer than the data stream
    with pytest.raises(ValueError, match="misaligned|truncate"):
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X[:128], chunk_size=64), Y)


def test_hbm_budget_violation_raises():
    X, Y = _xy(n=256)
    stream = StreamingDataset.from_numpy(X, chunk_size=64)
    with pytest.raises(MemoryError, match="HBM budget"):
        fit_streaming(LinearMapEstimator(lam=0.1), stream, Y,
                      hbm_budget=16.0)  # absurdly small


def test_ensure_array_refuses_silent_materialize():
    X, _ = _xy(n=64)
    with pytest.raises(TypeError, match="materialize"):
        ensure_array(StreamingDataset.from_numpy(X, chunk_size=32))


# -- observability ----------------------------------------------------------

def test_stream_metrics_and_trace_chunks():
    from keystone_tpu.observability import MetricsRegistry, PipelineTrace

    X, _ = _xy(n=200)
    with PipelineTrace("stream-test") as tr:
        list(StreamingDataset.from_numpy(
            X, chunk_size=64, tag="unit").chunks())
    assert len(tr.chunks) == 4
    assert {c["source"] for c in tr.chunks} == {"unit"}
    assert all("ingest_stall_s" in c and "prefetch_occupancy" in c
               for c in tr.chunks)
    assert tr.ingest_stall_s() >= 0.0
    # round trip
    rt = type(tr).from_json(tr.to_json())
    assert len(rt.chunks) == 4
    assert "streamed ingest" in tr.summary()
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["streaming.chunks_total"] >= 4
    assert "streaming.ingest_stall_s" in snap["histograms"]


def test_concurrent_derived_iterations_keep_ledger_consistent():
    """Two concurrent iterations over views derived from ONE root (data
    + labels split of a zipped stream) must compose in the shared
    residency ledger: never negative, and back to zero when both
    finish."""
    X, Y = _xy(n=256)
    both = StreamingDataset.from_numpy({"x": X, "y": Y}, chunk_size=64)

    def pick(key):
        return lambda ad: ArrayDataset(
            ad.data[key], ad.n, ad.mesh, _already_sharded=True)

    xs, ys = both.map_chunks(pick("x")), both.map_chunks(pick("y"))
    lows = []
    probe = xs.map_chunks(
        lambda ad: (lows.append(both.buffered_nbytes()), ad)[1])
    model = fit_streaming(LinearMapEstimator(lam=0.1), probe, ys)
    assert min(lows) >= 0.0
    assert both.buffered_nbytes() == 0.0
    resident = LinearMapEstimator(lam=0.1)._fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    assert np.abs(np.asarray(model.weights)
                  - np.asarray(resident.weights)).max() <= 1e-4


def test_trace_chunk_entries_are_bounded():
    from keystone_tpu.observability import PipelineTrace

    tr = PipelineTrace("cap")
    for i in range(tr.CHUNK_TAIL + 100):
        tr.record_chunk({"chunk": i, "ingest_stall_s": 0.001,
                         "nbytes": 10.0, "prefetch_occupancy": 1})
    assert len(tr.chunks) == tr.CHUNK_TAIL
    # aggregates stay exact over ALL chunks
    assert tr.chunk_stats["count"] == tr.CHUNK_TAIL + 100
    assert abs(tr.ingest_stall_s()
               - 0.001 * (tr.CHUNK_TAIL + 100)) < 1e-9
    rt = PipelineTrace.from_json(tr.to_json())
    assert rt.chunk_stats["count"] == tr.CHUNK_TAIL + 100


# -- static analysis / lint -------------------------------------------------

def test_dataset_spec_streaming_flag():
    from keystone_tpu.analysis.spec import DatasetSpec, dataset_spec

    X, _ = _xy(n=80)
    spec = dataset_spec(StreamingDataset.from_numpy(X, chunk_size=40))
    assert isinstance(spec, DatasetSpec)
    assert spec.streaming and spec.n == 80
    assert spec.element.shape == (24,)
    assert "streaming" in repr(spec)


def test_non_streamable_fit_lint_fires_and_names_node():
    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.nodes.learning.pca import ColumnPCAEstimator

    X, _ = _xy(n=80)
    stream = StreamingDataset.from_numpy(X, chunk_size=40)
    p = ColumnPCAEstimator(4).with_data(stream)
    rep = check_graph(
        p._graph, {p._source: jax.ShapeDtypeStruct((24,), np.float32)},
        name="pca-stream")
    hits = [d for d in rep.diagnostics if d.code == "non-streamable-fit"]
    assert len(hits) == 1
    assert "ColumnPCAEstimator" in hits[0].operator
    assert "accumulate" in hits[0].message


def test_streamable_fit_lint_clean():
    from keystone_tpu.analysis.diagnostics import check_graph

    X, Y = _xy(n=80)
    p = LinearMapEstimator(lam=0.1).with_data(
        StreamingDataset.from_numpy(X, chunk_size=40),
        StreamingDataset.from_numpy(Y, chunk_size=40))
    rep = check_graph(
        p._graph, {p._source: jax.ShapeDtypeStruct((24,), np.float32)},
        name="lin-stream")
    assert not [d for d in rep.diagnostics
                if d.code == "non-streamable-fit"]


def test_host_stage_on_stream_lint_fires():
    """A HostTransformer fed a stream fails at runtime; the static
    checker must say so BEFORE execution, naming the stage (the
    streaming flag also survives the host stage, so downstream
    diagnostics are not mis-attributed)."""
    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.nodes.util.sparse import Sparsify

    X, Y = _xy(n=80)
    stream = StreamingDataset.from_numpy(X, chunk_size=40)
    g = LinearMapEstimator(lam=0.1).with_data(
        stream, ArrayDataset.from_numpy(Y))._graph
    # splice the host stage between the stream and the estimator
    est_node = next(
        n for n in g.nodes
        if type(g.get_operator(n)).__name__ == "LinearMapEstimator")
    deps = g.get_dependencies(est_node)
    g2, host_node = g.add_node(Sparsify(), (deps[0],))
    g2 = g2.set_dependencies(est_node, (host_node,) + tuple(deps[1:]))
    rep = check_graph(g2, {}, name="host-on-stream")
    hits = [d for d in rep.diagnostics
            if d.code == "host-stage-on-stream"]
    assert len(hits) == 1 and "Sparsify" in hits[0].operator


def test_trace_summary_tolerates_trimmed_solver_decisions():
    from keystone_tpu.observability import PipelineTrace

    tr = PipelineTrace("trimmed")
    tr.record_solver_decision({"n": 10, "d": 4, "k": 2,
                               "chosen": "LinearMapEstimator"})
    assert "sparsity=?" in tr.summary()


def test_non_streamable_runtime_error_is_clear():
    from keystone_tpu.nodes.learning.pca import ColumnPCAEstimator

    X, _ = _xy(n=80)
    with pytest.raises(TypeError) as exc:
        ColumnPCAEstimator(4).fit(
            StreamingDataset.from_numpy(X, chunk_size=40))
    msg = str(exc.value)
    assert "ColumnPCAEstimator" in msg
    assert "accumulate" in msg and "non-streamable-fit" in msg


def test_pipeline_streamed_fit_never_materializes(monkeypatch):
    """Full graph path: an auto-solver pipeline fit on a StreamingDataset
    must pick a STREAMABLE solver (static choice restricted to the
    gram-capable surface; the Densify prefix passes streams through) and
    must never materialize the stream."""
    from keystone_tpu import Pipeline, transformer
    from keystone_tpu.observability import PipelineTrace

    X, Y = _xy(n=320, d=16, k=3)
    train = StreamingDataset.from_numpy(X, chunk_size=128, tag="pipe")
    labels = ArrayDataset.from_numpy(Y)

    def boom(self):
        raise AssertionError("stream was materialized during pipeline fit")

    monkeypatch.setattr(StreamingDataset, "materialize", boom)
    ident = transformer(lambda x: x * 1.0)
    with PipelineTrace("pipe") as tr:
        pipe = ident.and_then(LeastSquaresEstimator(lam=1e-2),
                              train, labels)
        fitted = pipe.fit()
        out = fitted.apply(ArrayDataset.from_numpy(X)).get().numpy()
    assert out.shape == (320, 3)
    assert tr.solver_decisions, "no solver decision traced"
    d = tr.solver_decisions[-1]
    assert d["chosen"] in ("LinearMapEstimator",
                           "BlockLeastSquaresEstimator"), d
    assert d.get("streaming_restricted") is True
    assert len(tr.chunks) > 0  # the fit actually consumed the stream


# -- dtype on the wire (ISSUE 5) --------------------------------------------

def _integral_xy(n=600, d=24, k=3, seed=0):
    """(X, Y) where X holds exact uint8-representable values, so a
    uint8 wire round-trips losslessly."""
    rng = np.random.RandomState(seed)
    X = rng.randint(0, 256, size=(n, d)).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W + 0.1 * rng.randn(n, k)).astype(np.float32)
    return X, Y


def test_wire_dtype_narrows_transfer_and_restores_dtype():
    """A uint8 wire ships 1 byte/element (streaming.h2d_bytes counts
    actual wire bytes) while consumers still see float32 chunks with
    the exact source values."""
    from keystone_tpu.observability import MetricsRegistry

    X, _ = _integral_xy(n=96, d=8)
    reg = MetricsRegistry.get_or_create()
    h2d = reg.counter("streaming.h2d_bytes")
    before = h2d.value
    stream = StreamingDataset.from_numpy(X, chunk_size=32,
                                         wire_dtype=np.uint8)
    chunks = list(stream.chunks())
    assert all(np.asarray(c.data).dtype == np.float32 for c in chunks)
    got = np.concatenate([c.numpy() for c in chunks])
    np.testing.assert_array_equal(got, X)
    shipped = h2d.value - before
    expected = sum(c.padded_n for c in chunks) * X.shape[1]  # 1 B/elem
    assert shipped == expected, (shipped, expected)


def test_wire_h2d_bytes_quarter_of_f32_wire():
    """Acceptance: uint8 wire bytes are exactly 1/4 of the f32 wire for
    the same source."""
    from keystone_tpu.observability import MetricsRegistry

    X, _ = _integral_xy(n=128, d=16)
    h2d = MetricsRegistry.get_or_create().counter("streaming.h2d_bytes")

    def shipped(**kw):
        before = h2d.value
        list(StreamingDataset.from_numpy(X, chunk_size=64, **kw).chunks())
        return h2d.value - before

    wide = shipped()  # native f32 wire
    narrow = shipped(wire_dtype=np.uint8)
    assert wide == 4 * narrow, (wide, narrow)


def test_compute_dtype_casts_on_device():
    """A native-uint8 source with compute_dtype=f32 yields f32 chunks
    (the fused device cast), with the wire staying uint8."""
    from keystone_tpu.observability import MetricsRegistry

    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, size=(48, 6, 5), dtype=np.uint8)
    h2d = MetricsRegistry.get_or_create().counter("streaming.h2d_bytes")
    before = h2d.value
    stream = StreamingDataset.from_numpy(imgs, chunk_size=16,
                                         compute_dtype=np.float32)
    chunks = list(stream.chunks())
    assert all(np.asarray(c.data).dtype == np.float32 for c in chunks)
    got = np.concatenate([c.numpy() for c in chunks])
    np.testing.assert_array_equal(got, imgs.astype(np.float32))
    # wire stayed uint8: 1 byte per element
    assert h2d.value - before == sum(
        c.padded_n for c in chunks) * 6 * 5


def test_per_leaf_wire_policy_leaves_labels_untouched():
    """A pytree wire policy narrows only the leaves it names: the image
    leaf ships uint8 while the float label leaf rides untouched (a
    uniform dtype applied to mixed trees would corrupt labels > 255)."""
    rng = np.random.RandomState(5)
    X = rng.randint(0, 256, size=(96, 8)).astype(np.float32)
    Y = (1000.0 * rng.rand(96, 2)).astype(np.float32)  # > 255: must
    stream = StreamingDataset.from_numpy(                # not narrow
        {"x": X, "y": Y}, chunk_size=32,
        wire_dtype={"x": np.uint8, "y": None})
    parts = [c.numpy() for c in stream.chunks()]
    got_x = np.concatenate([p["x"] for p in parts])
    got_y = np.concatenate([p["y"] for p in parts])
    np.testing.assert_array_equal(got_x, X)  # u8 round trip (integral)
    np.testing.assert_array_equal(got_y, Y)  # bit-exact: never cast
    # a mismatched policy structure fails loudly at stage time
    bad = StreamingDataset.from_numpy(
        {"x": X, "y": Y}, chunk_size=32,
        wire_dtype={"x": np.uint8, "z": None})
    with pytest.raises(ValueError, match="policy structure"):
        list(bad.chunks())
    # per-leaf policies serialize into the resume fingerprint
    assert "uint8" in stream.wire_dtype_name()


def test_wire_dtype_streamed_fit_parity():
    """Streamed fit over a uint8 wire matches the resident fit on the
    identical (integral) data — the narrowing is lossless end to end,
    donated-carry accumulate included."""
    X, Y = _integral_xy()
    resident = LinearMapEstimator(lam=0.1)._fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    streamed = fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=96,
                                    wire_dtype=np.uint8), Y)
    w_r, w_s = np.asarray(resident.weights), np.asarray(streamed.weights)
    assert np.abs(w_r - w_s).max() <= 1e-5 * np.abs(w_r).max()


def test_residency_accounts_post_cast_working_copy():
    """The HBM ledger charges the post-cast (f32) working chunk, not
    just the narrow uint8 wire bytes — wire narrowing must never hide
    device cost from hbm_budget asserts."""
    rng = np.random.RandomState(2)
    imgs = rng.randint(0, 256, size=(256, 16), dtype=np.uint8)
    stream = StreamingDataset.from_numpy(imgs, chunk_size=64,
                                         compute_dtype=np.float32)
    lives = []
    probe = stream.map_chunks(
        lambda ad: (lives.append(stream.buffered_nbytes()), ad)[1])
    for _ in probe.chunks():
        pass
    work_f32 = 64 * 16 * 4
    assert max(lives) >= work_f32  # working copy counted at f32 width
    assert stream.peak_device_nbytes >= work_f32


def test_full_chunk_skips_host_pad(monkeypatch):
    """Satellite: a chunk that already has exactly chunk_size rows must
    not touch the pad path at all (ragged tails still do)."""
    import keystone_tpu.parallel.streaming as streaming_mod

    def boom(*a, **k):
        raise AssertionError("full chunk paid the host pad copy")

    monkeypatch.setattr(streaming_mod, "_pad_to", boom)
    X = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
    chunks = list(StreamingDataset.from_numpy(X, chunk_size=32).chunks())
    assert [c.n for c in chunks] == [32, 32]
    # ragged tail DOES pad — the monkeypatched pad must fire
    with pytest.raises(AssertionError, match="host pad"):
        list(StreamingDataset.from_numpy(
            X[:40], chunk_size=32).chunks())


def test_multi_axis_mesh_streamed_parity():
    """Acceptance: streamed-vs-resident weight parity on a multi-axis
    (data=4, model=2) mesh — per-shard staging incl. ragged tails,
    uint8 wire, donated-carry accumulate — for LinearMap, BlockLS and
    the auto solver."""
    from keystone_tpu.parallel.mesh import make_mesh, mesh_scope

    from keystone_tpu.observability import MetricsRegistry

    X, Y = _integral_xy(n=520, d=24, k=3, seed=4)  # 520: ragged tail
    with mesh_scope(make_mesh(jax.devices()[:8], data=4, model=2)):
        # h2d counts what actually crosses the wire: P('data') rows
        # replicate over the model axis, so model=2 ships 2x the bytes
        h2d = MetricsRegistry.get_or_create().counter(
            "streaming.h2d_bytes")
        before = h2d.value
        chunks = list(StreamingDataset.from_numpy(
            X, chunk_size=96, wire_dtype=np.uint8).chunks())
        assert h2d.value - before == 2 * sum(
            c.padded_n for c in chunks) * X.shape[1]
        ds, ls = ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)
        # the auto solver streams through the gram carry and finalizes
        # with an exact-ridge-equivalent solver (d=24 -> one BCD block),
        # so the exact resident solve is its parity reference
        ests = [(LinearMapEstimator(lam=0.1), LinearMapEstimator(lam=0.1)),
                (BlockLeastSquaresEstimator(10, 3, lam=0.1),
                 BlockLeastSquaresEstimator(10, 3, lam=0.1)),
                (LeastSquaresEstimator(lam=0.1),
                 LinearMapEstimator(lam=0.1))]
        for est, ref in ests:
            resident = ref._fit(ds, ls)
            stream = StreamingDataset.from_numpy(
                X, chunk_size=96, wire_dtype=np.uint8)
            assert stream.chunk_size % 4 == 0  # data-shard multiple
            streamed = fit_streaming(est, stream, Y)
            w_r = np.asarray(getattr(resident, "weights"))
            w_s = np.asarray(getattr(streamed, "weights"))
            assert np.abs(w_r - w_s).max() <= 1e-5 * np.abs(w_r).max(), \
                type(est).__name__
            pred_r = np.argmax(np.asarray(
                ensure_array(resident.apply_dataset(ds)).numpy()), axis=1)
            pred_s = np.argmax(np.asarray(
                ensure_array(streamed.apply_dataset(ds)).numpy()), axis=1)
            np.testing.assert_array_equal(pred_r, pred_s)


def test_trace_chunks_carry_h2d_and_stage_lanes():
    from keystone_tpu.observability import PipelineTrace

    X, _ = _integral_xy(n=128, d=8)
    with PipelineTrace("wire") as tr:
        list(StreamingDataset.from_numpy(
            X, chunk_size=64, wire_dtype=np.uint8, tag="wire").chunks())
    assert tr.chunks
    for c in tr.chunks:
        assert c["h2d_bytes"] > 0
        assert c["stage_lanes"] >= 1
        assert c["stage_s"] >= 0.0
        # post-cast working footprint is 4x the uint8 wire bytes
        assert c["nbytes"] == 4 * c["h2d_bytes"]
    assert tr.chunk_stats["h2d_bytes"] == sum(
        c["h2d_bytes"] for c in tr.chunks)
    rt = PipelineTrace.from_json(tr.to_json())
    assert rt.chunk_stats["h2d_bytes"] == tr.chunk_stats["h2d_bytes"]
    assert "h2d" in tr.summary()


def test_stream_spec_carries_wire_dtype_not_narrowing():
    """DatasetSpec records the deliberate uint8 wire separately; the
    element reports the post-cast dtype so the dtype-narrowing lint has
    nothing to fire on."""
    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.analysis.spec import dataset_spec

    X, Y = _integral_xy(n=80)
    stream = StreamingDataset.from_numpy(X, chunk_size=40,
                                         wire_dtype=np.uint8)
    spec = dataset_spec(stream)
    assert spec.wire_dtype == "uint8"
    assert np.dtype(spec.element.dtype) == np.float32  # post-cast view
    assert "wire=uint8" in repr(spec)
    p = LinearMapEstimator(lam=0.1).with_data(
        stream, ArrayDataset.from_numpy(Y))
    rep = check_graph(p._graph, {}, name="wire-narrow")
    assert not [d for d in rep.diagnostics if d.code == "dtype-narrowing"]


def test_fingerprint_folds_wire_dtype(tmp_path):
    """Fix-forward from PR 4: a checkpoint written under a uint8 wire
    refuses to resume a run reconfigured to an f32 wire."""
    from keystone_tpu.resilience.stream_checkpoint import (
        CheckpointMismatchError,
        StreamCheckpoint,
        fit_fingerprint,
    )

    X, Y = _integral_xy(n=160)
    est = LinearMapEstimator(lam=0.1)
    narrow = StreamingDataset.from_numpy(X, chunk_size=80,
                                         wire_dtype=np.uint8)
    wide = StreamingDataset.from_numpy(X, chunk_size=80)
    fp_narrow = fit_fingerprint(est, narrow, Y)
    fp_wide = fit_fingerprint(est, wide, Y)
    assert fp_narrow != fp_wide
    ckpt = StreamCheckpoint(str(tmp_path))
    ckpt.save(fp_narrow, 1, (np.zeros(2),))
    with pytest.raises(CheckpointMismatchError):
        ckpt.load(fp_wide)
    # the LABELS stream's wire policy is numeric identity too
    ldata = StreamingDataset.from_numpy(X, chunk_size=80)
    fp_lab_narrow = fit_fingerprint(
        est, ldata,
        StreamingDataset.from_numpy(Y, chunk_size=80,
                                    wire_dtype=np.uint8))
    fp_lab_wide = fit_fingerprint(
        est, ldata, StreamingDataset.from_numpy(Y, chunk_size=80))
    assert fp_lab_narrow != fp_lab_wide


def test_donation_disabled_on_cpu_and_by_env(monkeypatch):
    """Donation resolves lazily per backend: the CPU test backend never
    requests it (no per-dispatch warnings), KEYSTONE_DONATE_CARRY=0
    disables it everywhere, and the donating wrapper is numerically the
    plain function."""
    from keystone_tpu.utils.donation import donating_jit, donation_enabled

    assert donation_enabled() is False  # cpu backend
    monkeypatch.setenv("KEYSTONE_DONATE_CARRY", "0")
    assert donation_enabled() is False

    fn = donating_jit(lambda a, b: a + b, donate_argnums=(0,))
    a = np.arange(4.0, dtype=np.float32)
    out = fn(a, a)
    np.testing.assert_array_equal(np.asarray(out), a + a)
    # on cpu the input buffer survives the call (no donation happened)
    np.testing.assert_array_equal(a, np.arange(4.0, dtype=np.float32))


def test_wire_cast_program_shared_across_streams():
    """Regression (caught by the PR 5 drive): the wire->compute cast
    program must be memoized GLOBALLY by (structure, dtypes) — a fresh
    StreamingDataset per refit must not recompile the cast, or the
    zero-recompile second epoch breaks for every wire-narrowed
    stream."""
    import io
    import logging

    X, Y = _integral_xy(n=256, d=8)

    def refit():
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=128,
                                        wire_dtype=np.uint8), Y)

    refit()  # warm
    jax.config.update("jax_log_compiles", True)
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    loggers = [logging.getLogger("jax._src.interpreters.pxla"),
               logging.getLogger("jax._src.dispatch")]
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.WARNING)
    try:
        refit()  # brand-new stream instance, same shape family
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    compiles = [ln for ln in buf.getvalue().splitlines()
                if "Compiling" in ln]
    assert not compiles, compiles


# -- cast-before-transfer lint (satellite) -----------------------------------

def test_cast_before_transfer_lint_fires_on_offender():
    import ast

    from keystone_tpu.analysis.diagnostics import (
        float_casts_before_transfer,
    )

    src = (
        "def stage(x, sh):\n"
        "    arr = np.stack(x).astype(np.float32)\n"
        "    return jax.device_put(arr, sh)\n"
    )
    hits = float_casts_before_transfer(ast.parse(src))
    assert hits and hits[0][0] == 2
    # dtype= keyword form fires too
    src2 = (
        "def stage(x, sh):\n"
        "    arr = np.asarray(x, dtype=np.float32)\n"
        "    return jax.device_put(arr, sh)\n"
    )
    assert float_casts_before_transfer(ast.parse(src2))
    # no device_put in scope: the cast alone is fine
    src3 = "def decode(x):\n    return np.asarray(x, dtype=np.float32)\n"
    assert not float_casts_before_transfer(ast.parse(src3))
    # narrowing casts are fine next to device_put
    src4 = (
        "def stage(x, sh):\n"
        "    return jax.device_put(np.stack(x).astype(np.uint8), sh)\n"
    )
    assert not float_casts_before_transfer(ast.parse(src4))
    # the astype(dtype=...) keyword spelling fires too
    src5 = (
        "def stage(x, sh):\n"
        "    arr = np.stack(x).astype(dtype=np.float32)\n"
        "    return jax.device_put(arr, sh)\n"
    )
    assert float_casts_before_transfer(ast.parse(src5))
    # scopes are separate: a cast in the outer body and a device_put
    # inside an unrelated nested closure must NOT conflate
    src6 = (
        "def outer(x, sh):\n"
        "    table = np.asarray(x, dtype=np.float32)\n"
        "    def helper(y):\n"
        "        return jax.device_put(y, sh)\n"
        "    return table, helper\n"
    )
    assert not float_casts_before_transfer(ast.parse(src6))


def test_staging_tree_clean_of_cast_before_transfer():
    """The scoped tree (loaders/, parallel/) holds no widening cast in
    any device_put-ing function — the pattern this PR removed."""
    import ast
    from pathlib import Path

    import keystone_tpu
    from keystone_tpu.analysis.diagnostics import (
        CAST_BEFORE_TRANSFER_SCOPES,
        float_casts_before_transfer,
    )

    pkg = Path(keystone_tpu.__file__).parent
    offenders = []
    for scope in CAST_BEFORE_TRANSFER_SCOPES:
        for path in sorted((pkg / scope).rglob("*.py")):
            tree = ast.parse(path.read_text())
            offenders += [f"{path.name}:{lineno} {what}"
                          for lineno, what in
                          float_casts_before_transfer(tree)]
    assert not offenders, offenders


def test_stream_tar_images_uint8_wire(tmp_path):
    """The default tar streaming path decodes uint8, ships uint8, and
    hands consumers float32 [0, 255] chunks — the in-tree offender this
    PR narrows (4x fewer wire bytes than the old f32 staging)."""
    import io as _io
    import tarfile

    from PIL import Image as PILImage

    from keystone_tpu.loaders.image_loader_utils import stream_tar_images
    from keystone_tpu.observability import MetricsRegistry

    side, n_imgs = 8, 6
    rng = np.random.RandomState(0)
    arrays = []
    tar_path = tmp_path / "imgs.tar"
    with tarfile.open(tar_path, "w") as tf:
        for i in range(n_imgs):
            arr = (rng.rand(side, side, 3) * 255).astype(np.uint8)
            arrays.append(arr)
            buf = _io.BytesIO()
            PILImage.fromarray(arr).save(buf, format="PNG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img{i:03d}.png")
            info.size = len(data)
            tf.addfile(info, _io.BytesIO(data))

    h2d = MetricsRegistry.get_or_create().counter("streaming.h2d_bytes")
    before = h2d.value
    stream = stream_tar_images([str(tar_path)], chunk_size=2, n=n_imgs)
    chunks = list(stream.chunks())
    got = np.concatenate([c.numpy() for c in chunks])
    assert got.dtype == np.float32  # consumers keep the f32 contract
    np.testing.assert_array_equal(
        got, np.stack(arrays).astype(np.float32))  # PNG+u8 is lossless
    shipped = h2d.value - before
    expected = sum(c.padded_n for c in chunks) * side * side * 3  # u8
    assert shipped == expected, (shipped, expected)


# -- loader glue ------------------------------------------------------------

def test_stream_tar_images(tmp_path):
    import io as _io
    import tarfile

    from PIL import Image as PILImage

    from keystone_tpu.loaders.image_loader_utils import stream_tar_images

    side, n_imgs = 16, 10
    rng = np.random.RandomState(0)
    tar_path = tmp_path / "imgs.tar"
    with tarfile.open(tar_path, "w") as tf:
        for i in range(n_imgs):
            arr = (rng.rand(side, side, 3) * 255).astype(np.uint8)
            buf = _io.BytesIO()
            PILImage.fromarray(arr).save(buf, format="PNG")
            data = buf.getvalue()
            info = tarfile.TarInfo(f"img{i:03d}.png")
            info.size = len(data)
            tf.addfile(info, _io.BytesIO(data))

    stream = stream_tar_images([str(tar_path)], chunk_size=4, n=n_imgs)
    chunks = list(stream.chunks())
    assert [c.n for c in chunks] == [4, 4, 2]
    assert all(np.asarray(c.data).shape[1:] == (side, side, 3)
               for c in chunks)
    # decoded content round-trips (PNG is lossless)
    total = sum(c.n for c in chunks)
    assert total == n_imgs
