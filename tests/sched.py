"""Deterministic-interleaving schedule harness (loom-style, scaled to
this repo's needs).

The concurrency passes (``analysis.concurrency``) prove the DECLARED
lock discipline is honored; this harness proves the discipline is
SUFFICIENT — by forcing the thread interleavings that break undisciplined
code. Two modes:

* :class:`DeterministicScheduler` — cooperative scheduling of threads
  it spawned. Exactly one spawned thread runs between *yield points*;
  at each yield point the scheduler picks who runs next, either from an
  explicit ``picks`` script (a regression schedule: the exact
  interleaving that reproduces a historical race) or from a seeded RNG
  (a random schedule; N seeds = N distinct interleavings, each
  replayable from its seed). Yield points come from two places: the
  instrumented primitives (``utils.guarded.TracedLock`` /
  ``TracedSemaphore`` call the installed hook at every
  acquire/wait/release — entering ``with sched:`` installs it), and
  explicit ``sched.yield_point(tag)`` calls marking the racy window in
  offender copies (the way loom models an atomic access). Threads the
  scheduler did not spawn pass through yield points untouched.

  A thread that blocks in a REAL primitive while another holds it
  would stall the scheduler's quiescence detection — that is why
  TracedLock spins through the hook instead of blocking when a hook is
  installed: lock waits park at yield points like everything else.

* :func:`chaos` — seeded perturbation at the same yield points (tiny
  sleeps / GIL yields drawn from one seeded RNG) for stressing REAL
  threaded code paths end to end (the prefetcher fuzz), where full
  cooperative control is impossible because library internals also
  block. Not a total order like the scheduler, but seeded: a failing
  seed reliably perturbs the same sites.

Used by tests/test_concurrency_sched.py: each historical race carries a
regression schedule that reproduces it on an un-fixed offender copy and
passes on HEAD, and the prefetcher survives a seeded many-schedule
fuzz.
"""
from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from keystone_tpu.utils.guarded import set_sched_hook


class ScheduleError(RuntimeError):
    """The schedule could not make progress (a real deadlock, a pick
    naming no parked thread, or max_steps exhausted)."""


class _TState:
    __slots__ = ("name", "thread", "parked", "finished", "tag")

    def __init__(self, name: str):
        self.name = name
        self.thread: Optional[threading.Thread] = None
        self.parked = False
        self.finished = False
        self.tag = ""


class DeterministicScheduler:
    """Cooperative seeded/scripted scheduler; see module docstring.

    Usage (a regression schedule)::

        sched = DeterministicScheduler(picks=["a", "b", "a", "b"])
        sched.spawn(writer_one, name="a")
        sched.spawn(writer_two, name="b")
        with sched:           # installs the TracedLock yield hook
            sched.run()

    ``picks`` entries are thread names (or substrings); when the script
    runs out, the seeded RNG picks. ``run`` re-raises the first
    exception a spawned thread died with.
    """

    def __init__(self, seed: int = 0,
                 picks: Optional[List[str]] = None,
                 max_steps: int = 20000):
        self._rng = random.Random(seed)
        self._picks = list(picks or [])
        self._max_steps = int(max_steps)
        self._cv = threading.Condition()
        self._by_thread: Dict[threading.Thread, _TState] = {}
        self._states: List[_TState] = []
        self._errors: List[tuple] = []
        self._stopping = False
        self.steps: List[str] = []  # granted (name, tag) log, for debug

    # -- building ----------------------------------------------------------
    def spawn(self, fn: Callable[..., Any], *args: Any,
              name: Optional[str] = None, **kwargs: Any) -> str:
        name = name or f"t{len(self._states)}"
        st = _TState(name)

        def body():
            self._park(st, "start")  # every thread starts parked
            try:
                fn(*args, **kwargs)
            except BaseException as exc:
                with self._cv:
                    self._errors.append((name, exc))
            finally:
                with self._cv:
                    st.finished = True
                    st.parked = False
                    self._cv.notify_all()

        st.thread = threading.Thread(
            target=body, name=f"sched-{name}", daemon=True)
        self._states.append(st)
        self._by_thread[st.thread] = st
        return name

    # -- yield points ------------------------------------------------------
    def yield_point(self, tag: str = "") -> None:
        """Park the calling thread until the scheduler grants it. A
        no-op for threads this scheduler did not spawn (so installing
        the global hook cannot disturb unrelated background threads)
        and while the scheduler is unwinding after an error."""
        st = self._by_thread.get(threading.current_thread())
        if st is None or self._stopping:
            return
        self._park(st, tag)

    def _park(self, st: _TState, tag: str) -> None:
        with self._cv:
            st.parked = True
            st.tag = tag
            self._cv.notify_all()
            while st.parked and not self._stopping:
                self._cv.wait(0.5)

    # -- driving -----------------------------------------------------------
    def _choose(self, parked: List[_TState]) -> _TState:
        while self._picks:
            pick = self._picks.pop(0)
            for st in parked:
                if st.name == pick or pick in st.name:
                    return st
            # the picked thread already finished (or is not parked at
            # this step) — scripts may be written loosely; fall through
            # to the next pick rather than deadlocking the schedule
        return self._rng.choice(sorted(parked, key=lambda s: s.name))

    def run(self, timeout: float = 30.0) -> None:
        for st in self._states:
            st.thread.start()
        deadline = time.monotonic() + timeout
        steps = 0
        try:
            with self._cv:
                while True:
                    if self._errors:
                        break
                    live = [s for s in self._states if not s.finished]
                    if not live:
                        break
                    parked = [s for s in live if s.parked]
                    if len(parked) < len(live):
                        # someone is still running between yield points
                        if not self._cv.wait(
                                timeout=max(deadline - time.monotonic(),
                                            0.01)):
                            raise ScheduleError(
                                "schedule stalled: threads "
                                f"{[s.name for s in live if not s.parked]}"
                                " neither parked nor finished within "
                                f"{timeout:g}s — a real block outside "
                                "the instrumented primitives?")
                        continue
                    steps += 1
                    if steps > self._max_steps:
                        raise ScheduleError(
                            f"schedule exceeded {self._max_steps} steps "
                            "(livelock? every thread spinning on a held "
                            "lock)")
                    nxt = self._choose(parked)
                    self.steps.append(f"{nxt.name}:{nxt.tag}")
                    nxt.parked = False
                    self._cv.notify_all()
        finally:
            # unwind: release every parked thread so it can finish (or
            # die) on its own — they are daemonic, so a thread stuck on
            # a real lock cannot hang the test session
            with self._cv:
                self._stopping = True
                for s in self._states:
                    s.parked = False
                self._cv.notify_all()
            for s in self._states:
                s.thread.join(timeout=2.0)
        if self._errors:
            name, exc = self._errors[0]
            raise exc

    # -- hook install ------------------------------------------------------
    def __enter__(self) -> "DeterministicScheduler":
        set_sched_hook(self.yield_point)
        return self

    def __exit__(self, *exc) -> None:
        set_sched_hook(None)


@contextlib.contextmanager
def chaos(seed: int = 0, sleep_p: float = 0.3, max_sleep_s: float = 1e-4):
    """Seeded perturbation at every TracedLock/TracedSemaphore yield
    point: with probability ``sleep_p`` a tiny seeded sleep, with the
    same probability a bare GIL yield, else nothing. The draw sequence
    is deterministic per seed; the resulting interleaving is not a
    total order (real primitives still block), but N seeds reliably
    explore N different perturbation patterns of the real code path —
    the fuzz mode for the prefetcher's slot-gated staging."""
    rng = random.Random(seed)
    lock = threading.Lock()

    def hook(tag: str) -> None:
        with lock:
            r = rng.random()
        if r < sleep_p:
            time.sleep(r * max_sleep_s)
        elif r < 2 * sleep_p:
            time.sleep(0)  # bare GIL yield

    set_sched_hook(hook)
    try:
        yield
    finally:
        set_sched_hook(None)
