"""SPMD-safety static passes (analysis/spmd.py, the ISSUE 12
tentpole): each of the four families — collective divergence,
barrier-name/coordination-shape stability, sharding-flow (AST axis
bindings + the spec-level ``DatasetSpec.sharded`` lattice), and
world-checkpoint consistency — fires on its synthetic offender fixture
(tests/lint_fixtures) and reports the shipped package tree clean; the
deliberately divergent dryrun worker
(tests/spmd_divergent_worker.py) is statically flagged here and
dynamically deadlocked-and-reaped by the @slow test alongside the
elastic suite (tests/test_elastic.py)."""
import ast
import pathlib

import jax
import numpy as np
import pytest

from keystone_tpu.analysis.spmd import (
    SPMD_ALLOWLIST,
    barrier_stability,
    collective_axis_bindings,
    collective_carriers,
    collective_divergence,
    scan_package,
    sharding_flow_lint,
    unawaited_collective,
    world_checkpoint_consistency,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"


def _tree(name):
    return ast.parse((FIXTURES / f"{name}.py").read_text())


# -- pass 1: collective divergence -------------------------------------------

def test_collective_divergence_fires_on_offender():
    hits = collective_divergence(_tree("spmd_divergence_offender"))
    assert {c for _, c, _ in hits} == {"collective-divergence"}
    # the four bug shapes: direct branch, taint flow, one call hop,
    # per-host loop bound — and NOT the uniform/rebind/fs-only shapes
    assert len(hits) == 4
    msgs = " ".join(m for _, _, m in hits)
    assert "branch_on_process_index" in msgs
    assert "taint_flows_through_locals" in msgs
    assert "one_hop_divergence" in msgs
    assert "per_host_loop_bound" in msgs
    assert "uniform_world_size_gate" not in msgs
    assert "host0_filesystem_only" not in msgs
    assert "rebind_kills_taint" not in msgs


def test_divergence_names_both_condition_and_collective():
    hits = collective_divergence(_tree("spmd_divergence_offender"))
    direct = next(m for _, _, m in hits if "branch_on_process_index" in m)
    assert "`sync_global_devices`" in direct       # the collective
    assert "`process_index() == 0`" in direct      # the branch condition


def test_divergence_one_hop_budget():
    carriers = collective_carriers(_tree("spmd_divergence_offender"))
    assert "_announce" in carriers


def test_divergence_allowlist_suppresses_with_entry():
    hits = collective_divergence(
        _tree("spmd_divergence_offender"),
        allowlist={"branch_on_process_index:sync_global_devices",
                   "taint_flows_through_locals:barrier",
                   "per_host_loop_bound:process_allgather"})
    assert len(hits) == 1
    assert "one_hop_divergence" in hits[0][2]


def test_collective_result_launders_divergence():
    """The replicated result of a coordination collective is
    world-uniform: gating later collectives on it is the ROUND-LOOP
    idiom (fit_streaming's checkpoint rounds), never flagged."""
    src = (
        "def round_loop(world, ckpt, done):\n"
        "    state = world.step(cursor=1, done=done)\n"
        "    if state.all_done:\n"
        "        world.barrier('finalize')\n")
    assert collective_divergence(ast.parse(src)) == []


def test_tuple_assign_taints_elementwise():
    """`pid, nproc = process_index(), process_count()` must taint only
    pid — gating on world size stays the safe idiom."""
    src = (
        "def worker(world):\n"
        "    rank, nproc = process_index(), process_count()\n"
        "    if nproc > 1:\n"
        "        world.barrier('enter')\n")
    assert collective_divergence(ast.parse(src)) == []
    src_bad = src.replace("nproc > 1", "rank > 0")
    hits = collective_divergence(ast.parse(src_bad))
    assert [c for _, c, _ in hits] == ["collective-divergence"]


# -- pass 2: barrier / coordination-shape stability --------------------------

def test_barrier_stability_fires_on_offender():
    hits = barrier_stability(_tree("spmd_barrier_offender"))
    codes = sorted(c for _, c, _ in hits)
    assert codes == ["non-fixed-coordination-shape"] * 2 + \
        ["unstable-barrier-name"] * 2
    msgs = " ".join(m for _, _, m in hits)
    assert "per_round_tag" in msgs
    assert "computed_coordinator_tag" in msgs
    assert "shard_local_payload" in msgs
    assert "appended_payload" in msgs
    assert "fixed_shape_round" not in msgs   # literal-length payload
    assert "literal_tags" not in msgs


def test_world_coordinator_funnel_is_the_only_allowlisted_tag():
    """The shipped tree's one deliberate non-literal barrier tag is the
    WorldCoordinator.barrier funnel (callers' literalness is enforced
    at their call sites); the allowlist carries exactly that entry and
    removing it makes the funnel fire — the entry is load-bearing."""
    assert "WorldCoordinator.barrier:sync_global_devices" \
        in SPMD_ALLOWLIST
    tree = ast.parse(
        (REPO / "keystone_tpu/parallel/distributed.py").read_text())
    assert barrier_stability(tree) == []
    unsuppressed = barrier_stability(tree, allowlist=())
    assert [c for _, c, _ in unsuppressed] == ["unstable-barrier-name"]
    assert "WorldCoordinator.barrier" in unsuppressed[0][2]


# -- pass 3 (AST): collective axis bindings ----------------------------------

def test_unbound_axis_fires_on_offender():
    hits = collective_axis_bindings(_tree("spmd_axis_offender"))
    assert {c for _, c, _ in hits} == {"unbound-collective-axis"}
    msgs = " ".join(m for _, _, m in hits)
    assert "'batch'" in msgs and "'replica'" in msgs
    # canonical axes and the locally bound Mesh axis are in scope
    assert "'data'" not in msgs and "'rows'" not in msgs


def test_shipped_shard_map_axes_are_bound():
    """ops/linalg.py's TSQR shard_map all-gathers over 'data' — bound
    by every mesh in this repo; the pass agrees."""
    tree = ast.parse((REPO / "keystone_tpu/ops/linalg.py").read_text())
    assert collective_axis_bindings(tree) == []


# -- pass 3 (spec): sharding-flow lattice ------------------------------------

def _analyzed(op, dep_spec_list):
    """One-node graph: sources bound to dep_spec_list, op consuming
    them, analyzed; returns the analysis object."""
    from keystone_tpu.analysis.interpreter import analyze
    from keystone_tpu.workflow.graph import Graph

    g = Graph()
    sources = []
    for _ in dep_spec_list:
        g, s = g.add_source()
        sources.append(s)
    g, node = g.add_node(op, tuple(sources))
    g, _ = g.add_sink(node)
    return analyze(g, dict(zip(sources, dep_spec_list)))


def _sharded_stream_spec(d=12):
    from keystone_tpu.analysis.spec import DatasetSpec

    return DatasetSpec(jax.ShapeDtypeStruct((d,), np.float32), n=None,
                       streaming=True, sharded=True)


def test_cross_host_materialization_fires():
    """A consumer collapsing a process-shard-local stream into a
    resident dataset is flagged: the result is one host's fraction
    presented as the whole."""
    from keystone_tpu.analysis.spec import DatasetSpec
    from keystone_tpu.workflow.operators import Operator

    class MaterializeOp(Operator):
        def execute(self, deps):
            raise NotImplementedError

        def abstract_eval(self, dep_specs):
            return DatasetSpec(dep_specs[0].element, n=128,
                               sparsity=1.0)  # resident: stream gone

    analysis = _analyzed(MaterializeOp(), [_sharded_stream_spec()])
    hits = sharding_flow_lint(analysis)
    assert [d.code for d in hits] == ["cross-host-materialization"]
    assert hits[0].severity == "error"
    assert "ONE host's fraction" in hits[0].message


def test_implicit_replication_fires_on_mixed_zip():
    """A transformer zipping a sharded stream with a non-sharded
    dataset warns: each host would pair its shard against the same
    replicated rows."""
    from keystone_tpu.analysis.spec import DatasetSpec
    from keystone_tpu.workflow.operators import TransformerOperator

    class ZipOp(TransformerOperator):
        def single_transform(self, inputs):
            return inputs[0] + inputs[1]

    resident = DatasetSpec(jax.ShapeDtypeStruct((12,), np.float32),
                           n=128, sparsity=1.0)
    analysis = _analyzed(ZipOp(), [_sharded_stream_spec(), resident])
    hits = sharding_flow_lint(analysis)
    assert [d.code for d in hits] == ["implicit-replication"]
    assert hits[0].severity == "warning"


def test_sharded_provenance_propagates_and_streamable_fit_is_clean():
    """The lattice: mapping a sharded stream keeps the provenance
    (TransformerOperator.abstract_eval), and a STREAMABLE estimator on
    a sharded stream raises no sharding-flow diagnostic (the
    distributed fit tree-reduces its carries)."""
    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.analysis.spec import DatasetSpec
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.workflow.operators import TransformerOperator

    class Identity(TransformerOperator):
        def single_transform(self, inputs):
            return inputs[0]

    analysis = _analyzed(Identity(), [_sharded_stream_spec()])
    node = next(iter(analysis.graph.nodes))
    out = analysis.value(node)
    assert isinstance(out, DatasetSpec) and out.sharded and out.streaming
    assert sharding_flow_lint(analysis) == []

    # end-to-end through check_graph: streamable labeled fit on a
    # sharded stream — no sharding-flow diagnostics (the estimator
    # exemption), and the spmd lints ride the standard check report
    from keystone_tpu.parallel.streaming import StreamingDataset

    X = np.random.RandomState(0).rand(64, 12).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[np.arange(64) % 4]
    stream = StreamingDataset.from_numpy(X, chunk_size=32, tag="spmd")
    stream.process_sharded = True
    p = LinearMapEstimator(lam=0.1).with_data(stream, Y)
    rep = check_graph(p._graph, name="sharded-fit")
    assert not [d for d in rep.diagnostics
                if d.code in ("cross-host-materialization",
                              "implicit-replication")]


def test_check_graph_carries_sharding_flow_lint():
    """check_graph (the `check` CLI engine) includes the sharding-flow
    family: a materializing consumer of a sharded stream turns the
    report red."""
    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.analysis.spec import DatasetSpec
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import Operator

    class MaterializeOp(Operator):
        def execute(self, deps):
            raise NotImplementedError

        def abstract_eval(self, dep_specs):
            return DatasetSpec(dep_specs[0].element, n=64, sparsity=1.0)

    g = Graph()
    g, s = g.add_source()
    g, node = g.add_node(MaterializeOp(), (s,))
    g, _ = g.add_sink(node)
    rep = check_graph(g, {s: _sharded_stream_spec()}, name="mat")
    assert not rep.ok
    assert "cross-host-materialization" in {d.code for d in rep.diagnostics}


# -- pass 4: world-checkpoint consistency ------------------------------------

def test_checkpoint_consistency_fires_on_offender():
    hits = world_checkpoint_consistency(_tree("spmd_checkpoint_offender"))
    codes = sorted(c for _, c, _ in hits)
    assert codes == ["carry-restore-discipline",
                     "unbarriered-host0-effect",
                     "unbarriered-host0-effect"]
    offenders = {m.split()[0] for _, _, m in hits}
    assert offenders == {"unbarriered_merge", "unbarriered_clear",
                         "raw_carry_restore"}


def test_merge_needs_both_sides_clear_needs_before():
    """merge_hosts reads peers' sidecars AND writes what peers resume
    from: barrier before and after; clear only destroys — barrier
    before suffices (the fit_streaming finalize-clear shape)."""
    src = (
        "def half_bracketed(world, ckpt):\n"
        "    world.barrier('sidecars')\n"
        "    if world.pid == 0:\n"
        "        ckpt.merge_hosts(2)\n")
    hits = world_checkpoint_consistency(ast.parse(src))
    assert len(hits) == 1 and "after" in hits[0][2]
    assert "before" not in hits[0][2].split("no world barrier")[1][:20]


def test_checkpoint_allowlist_suppresses():
    hits = world_checkpoint_consistency(
        _tree("spmd_checkpoint_offender"),
        allowlist={"unbarriered_merge:merge_hosts",
                   "unbarriered_clear:clear",
                   "raw_carry_restore:carry"})
    assert hits == []


# -- pass 5: unawaited coordination handles ----------------------------------

def test_unawaited_collective_fires_on_offender():
    hits = unawaited_collective(_tree("spmd_unawaited_offender"))
    codes = sorted(c for _, c, _ in hits)
    assert codes == ["stale-coordination-read"] + \
        ["unawaited-collective"] * 3
    msgs = " ".join(m for _, _, m in hits)
    # the four hazard shapes: discarded handle, rebind-before-await,
    # mid-flight result read, scope-exit leak — and NOT the pipelined
    # loop or the inline dispatch+await
    assert "discarded_dispatch" in msgs
    assert "rebound_before_await" in msgs
    assert "result_read_mid_flight" in msgs
    assert "scope_exit_leak" in msgs
    assert "pipelined_loop_is_clean" not in msgs
    assert "inline_await_is_clean" not in msgs


def test_unawaited_alias_transfer_and_post_loop_await_are_clean():
    """The software-pipeline idiom WITHOUT a drain-at-break: the handle
    alias-transfers through ``pending = new`` each round and the final
    round is awaited after the loop — one await per handle, clean."""
    src = (
        "def pipelined(world, chunks):\n"
        "    pending = None\n"
        "    for idx, _ in enumerate(chunks):\n"
        "        new = world.step_begin(cursor=idx, done=False)\n"
        "        if pending is not None:\n"
        "            world.step_await(pending)\n"
        "        pending = new\n"
        "    if pending is not None:\n"
        "        world.step_await(pending)\n")
    assert unawaited_collective(ast.parse(src)) == []
    # dropping the post-loop await leaks the last round's handle
    bad = src[:src.rindex("    if pending is not None:")]
    hits = unawaited_collective(ast.parse(bad))
    assert [c for _, c, _ in hits] == ["unawaited-collective"]
    assert "escape the scope unawaited" in hits[0][2]


def test_unawaited_allowlist_suppresses_by_scope():
    hits = unawaited_collective(
        _tree("spmd_unawaited_offender"),
        allowlist={"discarded_dispatch:step_begin",
                   "rebound_before_await:step_begin",
                   "result_read_mid_flight:step_begin",
                   "scope_exit_leak:step_begin"})
    assert hits == []


def test_shipped_overlap_loop_is_unawaited_clean():
    """The real overlapped round loop (parallel/streaming.py) and the
    coordinator itself must scan clean — the pass protects the overlap,
    it must not flag it."""
    for rel in ("parallel/streaming.py", "parallel/distributed.py"):
        tree = ast.parse((REPO / "keystone_tpu" / rel).read_text())
        assert unawaited_collective(tree) == [], rel


def test_nested_defs_are_their_own_scanned_scopes():
    """Review regression: the streaming hot path is closure-heavy
    (produce/put/accumulate_one), so nested defs must be enumerated
    and scanned as scopes of their own — a divergent barrier inside a
    closure must not escape the pass, and the hit names the dotted
    qualname an allowlist entry would use."""
    src = (
        "def outer():\n"
        "    def inner():\n"
        "        if process_index() == 0:\n"
        "            sync_global_devices('oops')\n"
        "    return inner\n")
    hits = collective_divergence(ast.parse(src))
    assert [c for _, c, _ in hits] == ["collective-divergence"]
    assert hits[0][2].startswith("outer.inner ")
    assert collective_divergence(
        ast.parse(src), allowlist={
            "outer.inner:sync_global_devices"}) == []


def test_rebind_after_conditional_dynamic_bind_is_clean():
    """Review regression: the dynamic-shape fold is TEXTUAL order — a
    rebind from a fixed-shape expression between a conditional
    dynamic bind and the gather kills the mark (BFS state used to
    false-positive here, breaking the CI gate on correct code)."""
    src = (
        "def f(flag, data):\n"
        "    if flag:\n"
        "        xs = list(data)\n"
        "    xs = fixed_summary()\n"
        "    process_allgather(xs)\n")
    assert barrier_stability(ast.parse(src)) == []
    # without the rebind the dynamic bind reaches the gather: fires
    bad = src.replace("    xs = fixed_summary()\n", "")
    assert [c for _, c, _ in barrier_stability(ast.parse(bad))] == \
        ["non-fixed-coordination-shape"]


def test_step_does_not_satisfy_the_before_barrier():
    """Review regression: the 'before' barrier must order the LAST
    preceding sidecar write — `world.step` earlier in the round loop
    (which every distributed fit has) is a rendezvous, not a
    durability barrier, and a named barrier BEFORE the write orders
    nothing either."""
    body = (
        "def round_loop(world, ckpt, idx, carry):\n"
        "    state = world.step(cursor=idx, done=False)\n"
        "{extra}"
        "    ckpt.save_host('fp', world.pid, idx, carry)\n"
        "{between}"
        "    if world.pid == 0:\n"
        "        ckpt.merge_hosts(world.nproc)\n"
        "    world.barrier('ckpt-world')\n")
    unordered = body.format(extra="", between="")
    hits = world_checkpoint_consistency(ast.parse(unordered))
    assert len(hits) == 1 and "before" in hits[0][2]
    early = body.format(extra="    world.barrier('early')\n", between="")
    hits = world_checkpoint_consistency(ast.parse(early))
    assert len(hits) == 1 and "before" in hits[0][2]
    bracketed = body.format(
        extra="", between="    world.barrier('ckpt-sidecars')\n")
    assert world_checkpoint_consistency(ast.parse(bracketed)) == []


def test_conditional_kill_does_not_launder_fallthrough():
    """Review regression: a rebind inside ONE branch must not kill the
    taint for the fall-through path (any-path join); a rebind on BOTH
    paths legitimately does."""
    src = (
        "def f(world):\n"
        "    rank = process_index()\n"
        "    if maybe():\n"
        "        rank = 0\n"
        "    if rank == 0:\n"
        "        world.barrier('x')\n")
    hits = collective_divergence(ast.parse(src))
    assert [c for _, c, _ in hits] == ["collective-divergence"]
    both = src.replace(
        "    if rank == 0:",
        "    else:\n        rank = 0\n    if rank == 0:")
    assert collective_divergence(ast.parse(both)) == []


def test_annassign_augassign_walrus_binds_are_tainted():
    """Review regression: `rank: int = process_index()`,
    `rank += process_index()`, and `(rank := process_index())` all
    bind the seed — a one-character annotation must not defeat the
    pass."""
    ann = (
        "def f(world):\n"
        "    rank: int = process_index()\n"
        "    if rank == 0:\n"
        "        world.barrier('x')\n")
    assert len(collective_divergence(ast.parse(ann))) == 1
    aug = ann.replace("    rank: int = process_index()\n",
                      "    rank = 0\n    rank += process_index()\n")
    assert len(collective_divergence(ast.parse(aug))) == 1
    walrus = (
        "def f(world):\n"
        "    if (rank := process_index()) == 0:\n"
        "        world.barrier('x')\n"
        "    if rank == 0:\n"
        "        world.barrier('y')\n")
    assert len(collective_divergence(ast.parse(walrus))) == 2


def test_module_level_statements_are_scanned():
    """Review regression: a script-style module body executing a
    divergent collective at import time is a scope of its own
    (`<module>`), not a blind spot."""
    src = (
        "import jax\n"
        "if process_index() == 0:\n"
        "    sync_global_devices('x')\n")
    hits = collective_divergence(ast.parse(src))
    assert [c for _, c, _ in hits] == ["collective-divergence"]
    assert hits[0][2].startswith("<module> ")
    assert collective_divergence(
        ast.parse(src),
        allowlist={"<module>:sync_global_devices"}) == []


def test_keyword_spelled_tags_and_payloads_are_checked():
    """Review regression: `sync_global_devices(name=...)` /
    `world.barrier(name=...)` / `process_allgather(in_tree=...)` are
    the same hazards as the positional spellings."""
    assert [c for _, c, _ in barrier_stability(ast.parse(
        "def f(i):\n    sync_global_devices(name=f'round-{i}')\n"))] \
        == ["unstable-barrier-name"]
    assert [c for _, c, _ in barrier_stability(ast.parse(
        "def f(world, t):\n    world.barrier(name=t)\n"))] \
        == ["unstable-barrier-name"]
    assert [c for _, c, _ in barrier_stability(ast.parse(
        "def f(rs):\n    xs = [r.key for r in rs]\n"
        "    process_allgather(in_tree=xs)\n"))] \
        == ["non-fixed-coordination-shape"]
    assert barrier_stability(ast.parse(
        "def f():\n    sync_global_devices(name='fixed')\n")) == []


def test_host0_gate_taint_is_as_of_the_gate():
    """Review regression: pass 4 folds taint up to each gate — a
    LATER uniform rebind of the gating name must not mask an earlier
    unbarriered host-0 effect, while a rebind BEFORE the gate still
    launders (the shared textual discipline)."""
    src = (
        "def f(ckpt, n):\n"
        "    rank = process_index()\n"
        "    if rank == 0:\n"
        "        ckpt.merge_hosts(n)\n"
        "    rank = 0\n")
    hits = world_checkpoint_consistency(ast.parse(src))
    assert len(hits) == 1 and "unbarriered-host0-effect" == hits[0][1]
    before = src.replace("    if rank == 0:",
                         "    rank = 0\n    if rank == 0:")
    assert world_checkpoint_consistency(ast.parse(before)) == []


# -- the divergent dryrun worker is statically flagged -----------------------

def test_divergent_worker_is_statically_flagged():
    """The deliberately divergent dryrun worker
    (tests/spmd_divergent_worker.py, deadlocked for real by the @slow
    test in test_elastic.py) is exactly the hazard class pass 1
    catches: the host-0-only sync_global_devices is flagged, the
    matched enter barrier is not."""
    tree = ast.parse(
        (REPO / "tests" / "spmd_divergent_worker.py").read_text())
    hits = collective_divergence(tree)
    assert [c for _, c, _ in hits] == ["collective-divergence"]
    assert "process_index() == 0" in hits[0][2]
    # the matched barrier two lines up is NOT part of the hit
    src_lines = (REPO / "tests" / "spmd_divergent_worker.py"
                 ).read_text().splitlines()
    assert "host0-only" in src_lines[hits[0][0] - 1]


# -- the tree is clean -------------------------------------------------------

def test_package_tree_is_spmd_clean():
    """All four families over the shipped tree: zero diagnostics (the
    one deliberate exception — the WorldCoordinator.barrier funnel —
    lives in the commented SPMD_ALLOWLIST)."""
    hits = scan_package(REPO / "keystone_tpu")
    assert hits == [], hits


def test_scan_schema_and_offenders_report(tmp_path):
    """scan_package returns the {file, lineno, code, message} shape the
    lint gate and the check CLI's `spmd` JSON key consume."""
    pkg = tmp_path / "pkg"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "parallel" / "divergent.py").write_text(
        (FIXTURES / "spmd_divergence_offender.py").read_text())
    hits = scan_package(pkg)
    assert {h["code"] for h in hits} == {"collective-divergence"}
    for h in hits:
        assert set(h) == {"file", "lineno", "code", "message"}
        assert h["file"].endswith("divergent.py")
        assert isinstance(h["lineno"], int) and h["lineno"] > 0


# -- wiring: lint + check CLI ------------------------------------------------

def test_lint_gate_runs_spmd_passes(tmp_path, monkeypatch):
    """tools/lint.py fails when a package module has an SPMD
    diagnostic (wired like the concurrency passes)."""
    import sys

    sys.path.insert(0, str(REPO / "tools"))
    try:
        import lint
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "keystone_tpu"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "parallel" / "bad.py").write_text(
        (FIXTURES / "spmd_checkpoint_offender.py").read_text())
    monkeypatch.setattr(lint, "REPO", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    assert lint.run_spmd_rules() > 0


@pytest.mark.slow
def test_check_cli_json_carries_spmd_key(tmp_path):
    """`python -m keystone_tpu check <app> --json` grows the `spmd`
    key (clean today) next to `concurrency`/`metrics_names`, exit
    codes preserved — the schema the CI consumers parse."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "check",
         "mnist.random_fft", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    blob = json.loads(out.read_text())
    assert blob["spmd"] == []
    assert isinstance(blob["spmd"], list)
    assert blob["concurrency"] == []  # neighbours unchanged
