"""Native host runtime tests: bit-parity of the C++ paths with the pure
Python implementations (the analogue of the reference's VLFeatSuite /
EncEvalSuite golden checks against its JNI library)."""
import numpy as np
import pytest

import keystone_tpu.native as kn
from keystone_tpu.nodes.nlp.hashing import (
    HashingTF,
    NGramsHashingTF,
    java_string_hash,
)


@pytest.fixture(scope="module")
def native_lib():
    if not kn.available():
        pytest.skip("native library not built and no toolchain")
    return kn


def test_native_cifar_decode_parity(native_lib):
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, 7 * 3073, dtype=np.uint8).tobytes()
    imgs, labels = kn.cifar_decode(raw)
    arr = np.frombuffer(raw, np.uint8).reshape(7, 3073)
    want = arr[:, 1:].reshape(7, 3, 32, 32).transpose(0, 2, 3, 1)
    np.testing.assert_array_equal(imgs, want.astype(np.float32))
    np.testing.assert_array_equal(labels, arr[:, 0].astype(np.int32))


def test_native_string_hash_parity(native_lib):
    toks = ["", "a", "Seq", "hello world", "wörld", "日本語", "🚀rocket"]
    got = kn.java_hash_tokens(toks)
    want = [java_string_hash(t) for t in toks]
    assert got.tolist() == want


def test_native_ngram_features_parity(native_lib):
    doc = "the quick brown fox jumps over the lazy dog the quick".split()
    for orders in ([1], [1, 2], [2, 3, 4]):
        feats = kn.ngram_hash_features(doc, orders, 1 << 14)
        sv = NGramsHashingTF(orders, 1 << 14).apply(doc)
        idx, counts = np.unique(feats, return_counts=True)
        np.testing.assert_array_equal(idx, sv.indices)
        np.testing.assert_array_equal(counts.astype(np.float32), sv.values)


def test_ngram_hashing_node_native_equals_python(native_lib):
    # the node's native fast path must equal its python fallback exactly
    doc = "a b c a b a".split()
    node = NGramsHashingTF([1, 2], 64)
    with_native = node.apply(doc)
    saved = kn._lib, kn._load_failed
    try:
        kn._lib, kn._load_failed = None, True
        without = node.apply(doc)
    finally:
        kn._lib, kn._load_failed = saved
    assert with_native == without


def test_native_csv_parse(tmp_path, native_lib):
    p = tmp_path / "m.csv"
    p.write_text("1.5,2.25,3\n-4,5e-3,6\n")
    out = kn.csv_parse(str(p))
    np.testing.assert_allclose(out, [[1.5, 2.25, 3], [-4, 5e-3, 6]])


def test_native_csv_parse_rejects_empty_trailing_field(tmp_path, native_lib):
    # "1,\n2,\n" has an empty trailing field per row; strtof would skip the
    # newline and swallow the next row's value, yielding [[1,2]] silently.
    # The strict parser must bail to numpy, which raises.
    p = tmp_path / "bad.csv"
    p.write_text("1,\n2,\n")
    with pytest.raises(ValueError):
        kn.csv_parse(str(p), num_cols=2)
