"""AutoCacheRule tests (mirrors the reference's AutoCacheRuleSuite:
hand-built graphs + synthetic Profile maps exercise cache selection and
estimation deterministically without real profiling)."""
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.workflow.common import Cacher
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.optimizer.auto_cache import (
    AutoCacheRule,
    Profile,
    SampleProfile,
    _children_with_multiplicity,
    estimate_cached_run_time,
    generalize_profiles,
    get_runs,
    make_cached_graph,
    profile_graph,
)
from keystone_tpu.workflow.transformer import transformer


def _diamond_graph(mesh):
    """data -> a -> (b, c) -> d ; a is consumed twice."""
    data = ArrayDataset.from_numpy(
        np.arange(32, dtype=np.float32).reshape(32, 1), mesh)
    g = Graph()
    g, src = g.add_node(DatasetOperator(data), ())
    g, a = g.add_node(transformer(lambda x: x + 1.0), (src,))
    g, b = g.add_node(transformer(lambda x: x * 2.0), (a,))
    g, c = g.add_node(transformer(lambda x: x * 3.0), (a,))
    g, d = g.add_node(transformer(lambda x: x[0:1] * 1.0), (b,))
    g, sink1 = g.add_sink(d)
    g, sink2 = g.add_sink(c)
    return g, (src, a, b, c, d)


def test_get_runs_counts_reuse(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    children = _children_with_multiplicity(g)
    weights = {n: 1 for n in g.nodes}
    runs = get_runs(g, children, frozenset(), weights)
    assert runs[a] == 2  # two consumers
    assert runs[b] == runs[c] == runs[d] == 1
    # caching b and c makes a's count collapse to 2 (each cached child
    # contributes its weight once)
    runs2 = get_runs(g, children, frozenset({b, c}), weights)
    assert runs2[a] == 2


def test_get_runs_weighted(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    children = _children_with_multiplicity(g)
    weights = {n: 1 for n in g.nodes}
    weights[b] = 5  # e.g. an iterative solver making 5 passes
    runs = get_runs(g, children, frozenset(), weights)
    assert runs[a] == 6  # 5 from b + 1 from c


def test_generalize_profiles_linear():
    samples = [
        SampleProfile(2, Profile(ns=20.0, mem=200.0)),
        SampleProfile(4, Profile(ns=40.0, mem=400.0)),
    ]
    p = generalize_profiles(100, samples)
    assert p.ns == pytest.approx(1000.0, rel=1e-6)
    assert p.mem == pytest.approx(10000.0, rel=1e-6)


def test_estimate_cached_run_time_synthetic(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    children = _children_with_multiplicity(g)
    profiles = {n: Profile(ns=10.0, mem=1.0) for n in g.nodes}
    t_nocache = estimate_cached_run_time(g, children, frozenset(), profiles)
    t_cache_a = estimate_cached_run_time(g, children, frozenset({a}), profiles)
    assert t_cache_a < t_nocache  # caching the reused node helps


def test_make_cached_graph_inserts_cacher(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    out = make_cached_graph(g, frozenset({a}))
    cachers = [n for n in out.nodes
               if isinstance(out.get_operator(n), Cacher)]
    assert len(cachers) == 1
    # b and c now consume the cacher, which consumes a
    assert out.get_dependencies(cachers[0]) == (a,)
    for n in (b, c):
        assert out.get_dependencies(n) == (cachers[0],)


def test_aggressive_cache_rule(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    out = AutoCacheRule(AutoCacheRule.AGGRESSIVE).apply(g)
    cachers = [n for n in out.nodes
               if isinstance(out.get_operator(n), Cacher)]
    assert len(cachers) == 1  # only 'a' is reused


def test_greedy_cache_respects_budget(mesh8):
    g, (src, a, b, c, d) = _diamond_graph(mesh8)
    # zero budget: nothing cached
    out = AutoCacheRule(AutoCacheRule.GREEDY, max_mem=0.0).apply(g)
    assert not [n for n in out.nodes
                if isinstance(out.get_operator(n), Cacher)]
    # generous budget: the reused node gets cached
    out2 = AutoCacheRule(AutoCacheRule.GREEDY, max_mem=1e12).apply(g)
    assert [n for n in out2.nodes
            if isinstance(out2.get_operator(n), Cacher)]


def test_profile_graph_measures_all_nodes(mesh8):
    g, ids = _diamond_graph(mesh8)
    profiles = profile_graph(g, scales=(1, 2))
    assert set(ids) <= set(profiles)
    assert all(p.ns >= 0 and p.mem >= 0 for p in profiles.values())
