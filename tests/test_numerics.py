"""Numerics & data-health observatory (PR 10): on-device health words
with the deferred-D2H tripwire, the solver conditioning ledger, and
PSI distribution-drift detection.

Acceptance pins: an injected-NaN streamed fit raises ``NumericsError``
naming chunk+stream with a post-mortem carrying the health series; the
drift scenario passes both directions (shifted trips, unshifted replay
does not) with the baseline sketch surviving checkpoint/resume
bit-identically; health reductions add zero post-warmup compiles (the
PR 9 fence stays clean); breakdown events round-trip through trace
JSON and appear in the Prometheus exposition."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu import Pipeline, PipelineTrace, Transformer
from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.observability import MetricsRegistry
from keystone_tpu.observability.numerics import (
    DriftBaseline,
    HealthMonitor,
    NumericsError,
    SketchTracker,
    check_fitted,
    check_node_output,
    drift_threshold,
    health_word,
    last_health_age_s,
    numerics_active,
    numerics_suppressed,
    postmortem_report,
    recent_health,
    score_drift,
    word_stats,
)
from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming
from keystone_tpu.resilience.faults import FaultPlan


def _xy(n=256, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, d).astype(np.float32)
    y = rng.randint(0, k, n)
    Y = (-np.ones((n, k)) + 2.0 * np.eye(k)[y]).astype(np.float32)
    return X, Y


# -- health words -------------------------------------------------------------

def test_health_word_counts_and_moments():
    x = np.array([1.0, -3.0, np.nan, np.inf, 2.0], np.float32)
    s = word_stats(np.asarray(health_word((x,))))
    assert s["finite"] == 3 and s["nan"] == 1 and s["inf"] == 1
    assert s["min"] == -3.0 and s["max"] == 2.0 and s["absmax"] == 3.0
    fin = np.array([1.0, -3.0, 2.0])
    assert s["mean"] == pytest.approx(fin.mean())
    assert s["var"] == pytest.approx(fin.var(), rel=1e-5)


def test_health_word_multi_leaf_aggregates():
    a = np.ones((4, 4), np.float32)
    b = np.full((3,), np.nan, np.float32)
    s = word_stats(np.asarray(health_word((a, b))))
    assert s["finite"] == 16 and s["nan"] == 3 and s["inf"] == 0


def test_health_word_nothing_finite():
    s = word_stats(np.asarray(health_word(
        (np.full((4,), np.nan, np.float32),))))
    assert s["finite"] == 0 and s["nan"] == 4
    assert s["min"] == 0.0 and s["max"] == 0.0  # guarded display values


def test_health_word_counts_exact_past_f32_precision():
    # summing >2^24 ones in f32 is inexact, and a rounded finite count
    # would make the DERIVED inf count nonzero — a spurious tripwire on
    # clean data. The counts accumulate in int32, so a 2^24+3-element
    # leaf reports exactly zero non-finites.
    n = (1 << 24) + 3
    s = word_stats(np.asarray(health_word((np.ones(n, np.float32),))))
    assert s["nan"] == 0 and s["inf"] == 0
    assert s["finite"] == pytest.approx(n, rel=1e-6)


def test_health_word_mask_excludes_pad_rows():
    """Zero-pad rows (the ArrayDataset ragged-tail invariant) must not
    distort the diagnostic stats: README tells users to read the
    post-mortem series' min/mean trend, and a spurious min=0.0 on the
    padded chunk before a failure points the diagnosis the wrong way."""
    X = np.full((6, 4), 2.5, np.float32)
    X[4:] = 0.0  # pad rows
    mask = np.array([1, 1, 1, 1, 0, 0], np.float32)
    s = word_stats(np.asarray(health_word((X,), mask)))
    assert s["finite"] == 16  # 4 live rows x 4 cols
    assert s["min"] == 2.5 and s["max"] == 2.5 and s["mean"] == 2.5
    assert s["var"] == pytest.approx(0.0)
    # a NaN in a PAD row is synthetic, never a tripwire
    X[5, 0] = np.nan
    s = word_stats(np.asarray(health_word((X,), mask)))
    assert s["nan"] == 0
    # ...but a NaN in a LIVE row still counts
    X[0, 0] = np.nan
    s = word_stats(np.asarray(health_word((X,), mask)))
    assert s["nan"] == 1
    # a leaf whose leading dim is not the row axis keeps the unmasked
    # reduction (trace-time shape decision, no crash)
    s = word_stats(np.asarray(health_word(
        (np.ones((3,), np.float32),), mask)))
    assert s["finite"] == 3


def test_streamed_ragged_tail_series_is_mask_weighted():
    # 300 rows of strictly-positive data AND labels in 64-row chunks:
    # the last chunk pads 20 rows with zeros, which must not show up
    # as min=0 in that chunk's series entry
    X = np.full((300, 8), 3.0, np.float32)
    Y = np.full((300, 4), 2.0, np.float32)
    fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=64, tag="ragged"), Y)
    series = [e for e in recent_health() if e.get("source") == "ragged"]
    assert len(series) == 5
    tail = series[-1]
    assert tail["min"] > 0.0  # data leaf's live min, not the pad's 0.0
    assert tail["finite"] < series[0]["finite"]  # fewer live rows


def test_monitor_defers_the_pull():
    m = HealthMonitor("s", defer=3)
    clean = np.ones((8,), np.float32)
    for i in range(3):
        m.observe(i, clean)
    assert m.checked == 0  # all words still in flight
    m.observe(3, clean)
    assert m.checked == 1  # the window overflowed by one
    m.flush()
    assert m.checked == 4
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.health_words"] == 4
    assert last_health_age_s() >= 0.0


def test_monitor_tripwire_names_chunk_and_source():
    m = HealthMonitor("bad-stream", defer=2)
    m.observe(0, np.ones((4,), np.float32))
    m.observe(1, np.array([1.0, np.nan], np.float32))
    with pytest.raises(NumericsError) as exc:
        m.flush()
    msg = str(exc.value)
    assert "chunk 1" in msg and "bad-stream" in msg
    path = exc.value.postmortem_path
    assert path and os.path.exists(path)
    with open(path) as f:
        blob = json.load(f)
    series = blob["context"]["recent_health"]
    assert any(e.get("chunk") == 1 and e.get("nan") for e in series)
    # the dump also carries the plane's own snapshot for machine-plane
    # crashes ("were the numbers healthy when the machine died?")
    assert blob["numerics"]["enabled"] is True


# -- the injected-NaN streamed fit (acceptance) -------------------------------

def test_streamed_fit_tripwire_names_chunk_with_postmortem():
    X, Y = _xy(n=320, d=16)
    with FaultPlan(seed=3).add("ingest.stage", kind="corrupt",
                               after=1, count=1):
        with pytest.raises(NumericsError) as exc:
            fit_streaming(
                LinearMapEstimator(lam=0.1),
                StreamingDataset.from_numpy(X, chunk_size=64,
                                            tag="poisoned"),
                Y)
    msg = str(exc.value)
    assert "chunk 1" in msg and "poisoned" in msg
    assert exc.value.postmortem_path
    with open(exc.value.postmortem_path) as f:
        blob = json.load(f)
    assert any(e.get("chunk") == 1 and e.get("nan")
               for e in blob["context"]["recent_health"])
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.nan_total"] >= 1
    assert snap["counters"]["numerics.nonfinite"] >= 1


def test_clean_streamed_fit_no_tripwire_no_postmortem(tmp_path,
                                                      monkeypatch):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    X, Y = _xy(n=256, d=16)
    model = fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=64, tag="clean"), Y)
    assert np.isfinite(np.asarray(model.weights)).all()
    assert os.listdir(str(tmp_path)) == []
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.health_words"] >= 4


def test_numerics_suppressed_fit_skips_the_plane():
    X, Y = _xy(n=128, d=8)
    with numerics_suppressed():
        assert not numerics_active()
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=64, tag="off"), Y)
    snap = MetricsRegistry.get_or_create().snapshot()
    assert "numerics.health_words" not in snap["counters"]
    assert recent_health() == []


# -- traced-executor node tripwire --------------------------------------------

class _MakeNaN(Transformer):
    def apply(self, x):
        return x * jnp.float32(np.inf) * 0.0  # inf * 0 -> NaN


class _Identity(Transformer):
    def apply(self, x):
        return x


def test_traced_node_output_tripwire_names_node():
    pipe = _Identity().and_then(_MakeNaN())
    x = np.ones((8, 4), np.float32)
    with PipelineTrace("t"):
        with pytest.raises(NumericsError) as exc:
            pipe.apply(x).numpy()
    assert "_MakeNaN" in str(exc.value)
    assert exc.value.postmortem_path


def test_untraced_run_is_unchecked():
    # zero-overhead contract: without a trace the executor never
    # health-checks, so the NaN flows through (the streamed/monitor
    # paths are the always-on guards; node checks ride the trace)
    pipe = _Identity().and_then(_MakeNaN())
    out = np.asarray(pipe.apply(np.ones((4, 2), np.float32)).numpy())
    assert np.isnan(out).all()


def test_check_node_output_direct():
    entry = check_node_output(np.ones((4,), np.float32), "n#1")
    assert entry["finite"] == 4
    with pytest.raises(NumericsError, match="n#2"):
        check_node_output(np.array([np.nan], np.float32), "n#2")
    assert check_node_output("not-an-array", "n#3") is None


def test_check_fitted_raises_on_nonfinite_model():
    class M:
        def __init__(self):
            self.weights = np.array([[1.0, np.nan]], np.float32)

    with pytest.raises(NumericsError, match="fitted model"):
        check_fitted(M(), "bad-fit")
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.nonfinite_model"] >= 1


# -- solver conditioning ledger -----------------------------------------------

def _singular_solve():
    from keystone_tpu.ops.linalg import ridge_cho_solve

    # duplicate feature columns with lam ~ 0: the near-exact rank
    # deficiency regime — f32 Cholesky hands back a collapsed pivot and
    # the clamped-eigh recovery branch runs (one breakdown event)
    rng = np.random.RandomState(0)
    A = rng.rand(32, 4).astype(np.float32)
    A = np.concatenate([A, A], axis=1)  # exact duplicates
    G = jnp.asarray(A.T @ A)
    C = jnp.asarray((A.T @ rng.rand(32, 3)).astype(np.float32))
    return ridge_cho_solve(G, C, 0.0, site="test_singular")


def test_breakdown_lands_in_ledger_and_trace():
    with PipelineTrace("t") as tr:
        W = np.asarray(_singular_solve())
    assert np.isfinite(W).all()  # the recovery still recovers
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.breakdown_total"] >= 1
    assert snap["counters"]["numerics.solves_total"] >= 1
    events = [e for e in tr.numerics if e["event"] == "breakdown"]
    assert events and events[0]["site"] == "test_singular"
    # collapsed pivot: a tiny ratio, or None when the factor itself
    # went NaN (sanitized — a bare NaN token would corrupt the JSON
    # artifacts the event lands in) — either way NOT a healthy value
    ratio = events[0]["pivot_ratio"]
    assert ratio is None or not (ratio >= 1e-3)
    assert tr.numerics_stats["breakdown"] >= 1


def test_nan_pivot_ratio_sanitized_in_events():
    """A NaN Cholesky factor yields a NaN ratio; the breakdown event
    must carry None instead — trace/Perfetto/post-mortem artifacts are
    strict JSON and one bare NaN token would corrupt the whole file."""
    from keystone_tpu.observability.numerics import _blocks_cb, _solve_cb

    with PipelineTrace("t") as tr:
        _solve_cb("nan-site", np.asarray(False), np.asarray(np.nan),
                  np.asarray(-1.0))
        _blocks_cb("nan-blocks", np.asarray([False]),
                   np.asarray([np.nan]))
    events = [e for e in tr.numerics if e["event"] == "breakdown"]
    assert len(events) == 2
    assert all(e["pivot_ratio"] is None for e in events)
    # the serialized trace must parse as STRICT JSON (no NaN literals)
    json.loads(tr.to_json(),
               parse_constant=lambda s: pytest.fail(f"bare {s} token"))


def test_healthy_solve_records_no_breakdown():
    from keystone_tpu.ops.linalg import ridge_cho_solve

    G = jnp.eye(8, dtype=jnp.float32) * 4.0
    C = jnp.ones((8, 2), jnp.float32)
    np.asarray(ridge_cho_solve(G, C, 0.1))
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.solves_total"] >= 1
    assert "numerics.breakdown_total" not in snap["counters"]
    # healthy solves report their pivot ratio and relative residual
    assert snap["histograms"]["numerics.pivot_ratio"]["count"] >= 1
    assert snap["histograms"]["numerics.pivot_ratio"]["min"] > 1e-3
    assert snap["histograms"]["numerics.residual_rel"]["max"] < 1e-3


def test_breakdown_trace_json_roundtrip_and_summary():
    with PipelineTrace("t") as tr:
        np.asarray(_singular_solve())
    blob = json.loads(tr.to_json())
    assert any(e["event"] == "breakdown" for e in blob["numerics"])
    tr2 = PipelineTrace.from_json(json.dumps(blob))
    assert tr2.numerics_stats == tr.numerics_stats
    assert any(e["event"] == "breakdown" for e in tr2.numerics)
    assert "numerics events" in tr2.summary()
    # legacy artifact (no stats block): rebuilt from the tail
    del blob["numerics_stats"]
    tr3 = PipelineTrace.from_json(json.dumps(blob))
    assert tr3.numerics_stats.get("breakdown", 0) >= 1


def test_prometheus_exposition_carries_numerics():
    from keystone_tpu.ops.linalg import ridge_cho_solve

    np.asarray(_singular_solve())  # breakdown counter
    np.asarray(ridge_cho_solve(  # healthy: pivot/residual histograms
        jnp.eye(8, dtype=jnp.float32), jnp.ones((8, 2), jnp.float32),
        0.1))
    text = MetricsRegistry.get_or_create().to_prometheus()
    assert "keystone_numerics_breakdown_total" in text
    assert "keystone_numerics_pivot_ratio" in text
    assert "keystone_numerics_solves_total" in text


def test_per_class_weighted_solves_reach_ledger():
    """The per-class reweighted BCD was the one recovery site outside
    the conditioning ledger — every `_finite_or_eigh_solve` user must
    report (one stacked callback after the lax.map, never per class).
    Duplicate feature columns with lam=0 collapse a pivot in every
    class's block, so the breakdown is visible."""
    from keystone_tpu.nodes.learning.per_class_weighted import (
        PerClassWeightedLeastSquaresEstimator,
    )

    rng = np.random.RandomState(0)
    half = rng.randn(96, 4).astype(np.float32)
    X = np.concatenate([half, half], axis=1)
    y = rng.randint(0, 3, 96)
    L = -np.ones((96, 3), np.float32)
    L[np.arange(96), y] = 1.0
    PerClassWeightedLeastSquaresEstimator(
        block_size=8, num_iter=1, lam=0.0,
        mixture_weight=0.5).fit_arrays(X, L)
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.breakdown_total"] >= 1
    assert snap["counters"]["numerics.solves_total"] >= 3  # >= k blocks


def test_streamed_blockls_breakdowns_reach_ledger():
    # the streamed BlockLS finalize runs the gram-form BCD: duplicate
    # columns inside one block put a breakdown on the gram_bcd site
    from keystone_tpu.nodes.learning.linear import (
        BlockLeastSquaresEstimator,
    )

    rng = np.random.RandomState(0)
    half = rng.rand(256, 8).astype(np.float32)
    X = np.concatenate([half, half], axis=1)
    _, Y = _xy(n=256)
    fit_streaming(
        BlockLeastSquaresEstimator(16, 1, lam=0.0),
        StreamingDataset.from_numpy(X, chunk_size=64, tag="dup"), Y)
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["numerics.breakdown_total"] >= 1


# -- distribution drift -------------------------------------------------------

def _fit_with_baseline(X, Y, tag="drift-fit", chunk=64):
    return fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=chunk, tag=tag), Y)


def test_drift_scenario_both_directions():
    """Acceptance: a mean/scale-shifted stream scores above the
    threshold; an unshifted replay stays below."""
    rng = np.random.RandomState(0)
    X = rng.rand(1024, 32).astype(np.float32)
    _, Y = _xy(n=1024)
    model = _fit_with_baseline(X, Y)
    base = model.numerics_baseline
    assert isinstance(base, DriftBaseline) and base.rows == 1024

    replay = score_drift(
        base, StreamingDataset.from_numpy(
            rng.rand(512, 32).astype(np.float32), chunk_size=64))
    assert not replay["warned"]
    assert replay["psi_max"] < drift_threshold()

    shifted = score_drift(
        base, StreamingDataset.from_numpy(
            (rng.rand(512, 32) * 1.5 + 0.5).astype(np.float32),
            chunk_size=64))
    assert shifted["warned"]
    assert shifted["psi_max"] > drift_threshold()
    # separation is wide, not marginal: thresholds have headroom
    assert shifted["psi_max"] > 10 * replay["psi_max"]
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["gauges"]["numerics.drift_score"] == pytest.approx(
        shifted["psi_max"])
    assert snap["counters"]["numerics.drift_warn"] >= 1
    assert snap["counters"]["numerics.fit_baseline"] == 1


def test_drift_baseline_survives_checkpoint_resume_bit_identical(
        tmp_path):
    """Acceptance: kill-and-resume carries the baseline sketch
    bit-identically — the resumed fit's counts/geometry EQUAL the
    uninterrupted fit's, not merely approximate them."""
    X, Y = _xy(n=320, d=16)

    def stream():
        return StreamingDataset.from_numpy(X, chunk_size=64, tag="kr")

    base = fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y)
    ckdir = str(tmp_path / "ck")
    with FaultPlan().add("ingest.produce", after=2, count=1,
                         error=RuntimeError):
        with pytest.raises(RuntimeError, match="injected fault"):
            fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y,
                          checkpoint_dir=ckdir, checkpoint_every=1)
    resumed = fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y,
                            checkpoint_dir=ckdir, checkpoint_every=1)
    b0, b1 = base.numerics_baseline, resumed.numerics_baseline
    assert np.array_equal(b0.counts, b1.counts)  # bit-identical
    assert np.array_equal(b0.interior, b1.interior)
    assert np.array_equal(b0.cols, b1.cols)
    assert b0.rows == b1.rows
    # and the restored sketch still scores identically
    probe = np.random.RandomState(9).rand(128, 16).astype(np.float32)
    assert score_drift(b0, probe)["psi_max"] == pytest.approx(
        score_drift(b1, probe)["psi_max"])


def test_baseline_merge_and_geometry_guard():
    rng = np.random.RandomState(0)
    X, Y = _xy(n=256, d=8)
    b1 = _fit_with_baseline(X, Y, tag="m1").numerics_baseline
    b2 = _fit_with_baseline(X, Y, tag="m2").numerics_baseline
    # same data, same chunking -> same edges: mergeable, counts sum
    merged = b1.merge(b2)
    assert merged.rows == b1.rows + b2.rows
    assert np.array_equal(merged.counts, b1.counts + b2.counts)
    other = _fit_with_baseline(
        rng.rand(256, 8).astype(np.float32) * 100.0, Y,
        tag="m3").numerics_baseline
    with pytest.raises(ValueError, match="geometry"):
        b1.merge(other)


def test_score_drift_requires_a_baseline_and_2d_data():
    with pytest.raises(ValueError, match="no drift baseline"):
        score_drift(None, np.ones((4, 2), np.float32))
    X, Y = _xy(n=128, d=8)
    base = _fit_with_baseline(X, Y, tag="req").numerics_baseline
    with pytest.raises(ValueError, match="2-D"):
        score_drift(base, np.ones((4, 2, 2), np.float32))


def test_score_drift_rejects_narrower_feature_space():
    """jax's gather CLAMPS out-of-bounds column indices instead of
    raising, so scoring a narrower matrix would silently compare every
    tail column against the last in-range column's histogram — it must
    raise instead."""
    X, Y = _xy(n=128, d=16)
    base = _fit_with_baseline(X, Y, tag="dim").numerics_baseline
    assert int(base.cols.max()) == 15
    with pytest.raises(ValueError, match="feature"):
        score_drift(base, np.ones((32, 8), np.float32))


def test_sketch_disables_on_ineligible_data():
    tr = SketchTracker(source="t")

    class Chunk:
        data = {"a": jnp.ones((4, 2)), "b": jnp.ones((4,))}
        mask = jnp.ones(4)
        n = 4

    tr.update(Chunk)
    assert tr.disabled and tr.baseline() is None and tr.state() is None


def test_sketch_rejects_too_few_bins():
    with pytest.raises(ValueError, match="bins"):
        SketchTracker(bins=2)


# -- gating & env knobs -------------------------------------------------------

def test_numerics_disabled_fit_completes_with_garbage(monkeypatch):
    # KEYSTONE_NUMERICS=0 documents the opt-out: the poisoned fit runs
    # to completion (the pre-PR-10 behavior: garbage weights, silence)
    monkeypatch.setenv("KEYSTONE_NUMERICS", "0")
    X, Y = _xy(n=256, d=8)
    with FaultPlan().add("ingest.stage", kind="corrupt", after=1,
                         count=1):
        model = fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=64, tag="off"), Y)
    assert not np.isfinite(np.asarray(model.weights)).all()
    assert getattr(model, "numerics_baseline", None) is None


def test_drift_threshold_env_validation(monkeypatch):
    monkeypatch.setenv("KEYSTONE_DRIFT_THRESHOLD", "0.5")
    assert drift_threshold() == 0.5
    monkeypatch.setenv("KEYSTONE_DRIFT_THRESHOLD", "nope")
    with pytest.raises(ValueError, match="float"):
        drift_threshold()
    monkeypatch.setenv("KEYSTONE_DRIFT_THRESHOLD", "-1")
    with pytest.raises(ValueError, match="> 0"):
        drift_threshold()


def test_defer_env_validation(monkeypatch):
    monkeypatch.setenv("KEYSTONE_NUMERICS_DEFER", "0")
    with pytest.raises(ValueError, match=">= 1"):
        HealthMonitor("s")
    monkeypatch.setenv("KEYSTONE_NUMERICS_DEFER", "x")
    with pytest.raises(ValueError, match="integer"):
        HealthMonitor("s")


# -- the fence stays clean (acceptance) ---------------------------------------

def test_health_reductions_add_zero_post_warmup_compiles():
    """The PR 9 fence: with numerics ON, a second epoch of a
    fixed-shape streamed fit compiles NOTHING — the health word and
    sketch programs are module-global and warm up during chunk 1 of
    epoch 1, before the fit fence arms."""
    from keystone_tpu.observability import (
        compile_observatory,
        expect_no_compiles,
    )

    X, Y = _xy(n=256, d=16)

    def epoch():
        return fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=64, tag="fence"),
            Y)

    epoch()
    obs = compile_observatory()
    before = obs.unexpected_total()
    with expect_no_compiles("numerics-fence-test"):
        model = epoch()
    assert obs.unexpected_total() == before
    assert model.numerics_baseline is not None  # the plane really ran


# -- post-mortem CLI + sampler probe ------------------------------------------

def test_postmortem_report_renders_health_series(capsys):
    m = HealthMonitor("cli-stream", defer=1)
    m.observe(0, np.ones((4,), np.float32))
    m.observe(1, np.array([np.nan, 1.0], np.float32))
    with pytest.raises(NumericsError) as exc:
        m.flush()
    assert postmortem_report([exc.value.postmortem_path]) == 0
    out = capsys.readouterr().out
    assert "numerics_tripwire" in out
    assert "health series" in out and "cli-stream" in out
    assert "nan_total=1" in out


def test_postmortem_report_bad_inputs(capsys):
    assert postmortem_report([]) == 1
    assert postmortem_report(["/nonexistent/x.json"]) == 1


def test_sampler_publishes_health_age():
    from keystone_tpu.observability.sampler import TelemetrySampler

    values = TelemetrySampler(interval_s=0.1).sample_once()
    assert values["numerics.health_age_s"] == -1.0  # plane not run yet
    m = HealthMonitor("age", defer=1)
    m.observe(0, np.ones((2,), np.float32))
    m.flush()
    values = TelemetrySampler(interval_s=0.1).sample_once()
    assert 0.0 <= values["numerics.health_age_s"] < 60.0
