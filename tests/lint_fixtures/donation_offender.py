"""Lint fixture: donation-safety offenders, in the bug shapes the
``use-after-donate`` / ``checkpoint-after-donate`` pass exists to catch.

A ``donating_jit`` argument's buffer is DEAD after the call on TPU/GPU
— and silently alive on CPU, which is why this class of bug survives a
CPU test suite and must be caught statically. Parsed (never imported at
runtime) by tests/test_analysis_passes.py.
"""
import jax.numpy as jnp

from keystone_tpu.utils.donation import donating_jit


def _update_impl(carry, chunk):
    return carry + jnp.sum(chunk, axis=0)


_update = donating_jit(_update_impl, donate_argnums=(0,))


def good_loop(carry, chunks):
    # the canonical SAFE pattern: the donated name is rebound from the
    # call's result, so no stale buffer is ever read
    for chunk in chunks:
        carry = _update(carry, chunk)
    return carry


def bad_use_after_donate(carry, chunk):
    out = _update(carry, chunk)
    return out, carry.sum()  # BUG: `carry`'s buffer is dead here


def bad_checkpoint_after_donate(ckpt, carry, chunk):
    out = _update(carry, chunk)
    ckpt.save("cursor", carry)  # BUG: snapshots a donated (dead) buffer
    return out
