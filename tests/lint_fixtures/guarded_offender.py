"""Synthetic offender for the guarded-by race pass
(``analysis.concurrency.guarded_field_races``): a class that DECLARES a
lock discipline and then mutates guarded fields outside it — the exact
shapes that bit this repo (the PR 4 ``record_resilience``
read-modify-write, the unlocked ``Histogram`` tail appends fixed in
PR 7). Never imported; parsed as AST by tests and compiled by the
schedule-harness regression tests."""
import threading

from keystone_tpu.utils.guarded import guarded_by


@guarded_by("_lock", "count", "tail", "stats")
class RacyLedger:
    def __init__(self):
        # __init__ is exempt: the object is not shared yet
        self._lock = threading.Lock()
        self.count = 0
        self.tail = []
        self.stats = {}

    def bump(self):
        self.count += 1  # guarded-field-race: RMW, no lock

    def push(self, x):
        self.tail.append(x)  # guarded-field-race: compound mutation

    def merge(self, key):
        # guarded-field-race: the PR 4 record_resilience shape — a
        # dict read-modify-write outside the declared lock
        self.stats[key] = self.stats.get(key, 0) + 1

    def locked_bump(self):
        with self._lock:
            self.count += 1  # clean: the declared discipline, honored

    def rebind(self, fresh):
        # clean: a plain rebind is not an RMW (last writer wins is the
        # semantics, like Gauge.set)
        self.tail = list(fresh)
