"""Lint fixture: the ``_CAST_JIT_CACHE`` lesson — a compiled program
memoized on ``self`` with no global cache behind it. Every refit builds
a fresh instance, so the memo never hits and the program recompiles per
fit (caught by the verify drive in PR 5, fixed by a module-level
structure-keyed LruMemo). Parsed only, never imported at runtime.
"""
import jax


class RefittableStage:
    def __init__(self, scale):
        self.scale = scale
        self._program = None

    def apply(self, x):
        return x * self.scale

    def batched(self):
        if self._program is None:
            # BUG: per-instance memo of a jitted program — a refit
            # constructs a new instance and recompiles from scratch
            self._program = jax.jit(jax.vmap(self.apply))
        return self._program
