"""Synthetic offender for the world-checkpoint consistency pass
(``analysis/spmd.py``): host-0-only snapshot effects (``merge_hosts``,
checkpoint ``clear``) not barrier-paired — peers race the shared
snapshot files — and a restored checkpoint carry fed onward without
the ``_restore_carry`` replicated-``device_put`` discipline (every
resume would compile a second accumulate program under the warmup
fence). The correctly bracketed / correctly restored spellings must
NOT fire. Never imported; parsed as AST by tests/tools."""


def _restore_carry(host_carry, mesh):  # stand-in: parsed, never run
    raise NotImplementedError


def unbarriered_merge(world, ckpt):
    # BUG: no barrier before (sidecars may still be in flight) and
    # none after (a peer can resume a half-merged world snapshot)
    if world.pid == 0:
        ckpt.merge_hosts(world.nproc)


def unbarriered_clear(world, ckpt):
    if world.pid == 0:
        ckpt.clear()  # BUG: peers may not be past finalize yet


def bracketed_merge(world, ckpt):
    # the fit_streaming discipline: sidecar barrier, host-0 merge,
    # world barrier — clean
    world.barrier("ckpt-sidecars")
    if world.pid == 0:
        ckpt.merge_hosts(world.nproc)
    world.barrier("ckpt-world")


def barriered_clear(world, ckpt):
    world.barrier("finalize-clear")
    if world.pid == 0:
        ckpt.clear()  # every host is past finalize: clean


def raw_carry_restore(ckpt, fingerprint, mesh):
    snap = ckpt.load(fingerprint)
    if snap is not None:
        carry = snap["carry"]  # BUG: raw host arrays re-enter the jit
    return carry


def disciplined_carry_restore(ckpt, fingerprint, mesh):
    snap = ckpt.load_world(fingerprint, 0, 2)
    if snap is not None:
        carry = (None if snap["carry"] is None
                 else _restore_carry(snap["carry"], mesh))  # clean
    return carry
