"""Synthetic offender for ``hotpath-unbounded-growth``
(``analysis.hotpath.hotpath_hazards``): a ``@hotpath`` entry appending
to a ``self`` container the class never shrinks anywhere and never
bounds — the ``_phase_hists`` leak shape the first tree scan found in
``ServingPlane`` (fixed in PR 17 by pruning at evict/admit-victim/
warmup-rollback). The sibling field with a drain path, and the
``deque(maxlen=...)`` field, pin the two non-firing shapes. Never
imported by the package; parsed/compiled by tests only."""
from collections import deque

from keystone_tpu.utils.guarded import hotpath


class LeakyLedger:
    def __init__(self):
        self._seen = []
        self._seen_index = {}
        self._retired = []
        self._recent = deque(maxlen=64)

    @hotpath
    def record(self, rid):
        self._seen.append(rid)  # hotpath-unbounded-growth: no drain path
        self._retired.append(rid)  # clean: retire() pops it
        self._recent.append(rid)  # clean: deque(maxlen=) declares a bound
        self._seen_index[rid] = True  # hotpath-unbounded-growth: keyed store

    def retire(self):
        return self._retired.pop()
