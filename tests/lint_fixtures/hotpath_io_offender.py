"""Synthetic offender for ``hotpath-io``
(``analysis.hotpath.hotpath_hazards``): a ``@hotpath`` entry doing
filesystem, console, and serialization I/O per request — ``open``,
``.read``, ``print``, and a ``pickle`` round trip through the module
alias table. Never imported by the package; parsed/compiled by tests
only."""
import pickle

from keystone_tpu.utils.guarded import hotpath


class ChattyHandler:
    @hotpath
    def handle(self, path):
        print("request", path)  # hotpath-io: console write per request
        with open(path, "rb") as f:  # hotpath-io: filesystem open
            raw = f.read()  # hotpath-io: file read
        return pickle.loads(raw)  # hotpath-io: deserialization
