"""Synthetic offender for the ``silent-nan-silencer`` pass
(``analysis.diagnostics.silent_nan_silencers``): NaN-suppressing calls
with no recorded ``numerics.*`` event in scope. Parsed by tests, never
imported."""

import numpy as np

from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.observability.numerics import record_numerics_event


def silent_patch(x):
    # offender: non-finites replaced, nobody ever learns they existed
    return np.nan_to_num(x, nan=0.0)


def silent_errstate(a, b):
    # offender: divide-by-zero warnings suppressed with no event
    with np.errstate(divide="ignore", invalid="ignore"):
        return a / b


def accounted_patch(x):
    # fine: the suppression is recorded into the numerics funnel
    bad = int(np.sum(~np.isfinite(x)))
    if bad:
        record_numerics_event("nonfinite", count=bad)
    return np.nan_to_num(x, nan=0.0)


def accounted_via_counter(x):
    # fine: a numerics.* counter in scope counts as accounting
    reg = MetricsRegistry.get_or_create()
    reg.counter("numerics.nan_total").inc(int(np.isnan(x).sum()))
    return np.nan_to_num(x)


def raising_errstate(a, b):
    # fine: errstate(all='raise') is the OPPOSITE of suppression
    with np.errstate(all="raise"):
        return a / b
