"""Synthetic offender for the atomic-publication pass
(``analysis.hotpath.published_field_hazards``): a class that DECLARES
``@published_by`` — its fields are read LOCK-FREE on the hot path, so
every write must be a single-reference atomic flip under the declared
lock — and then violates each clause: ``unpublished-write`` (a flip
outside the lock), ``non-atomic-publication`` (an in-place mutation
readers observe piecewise), ``torn-publication`` (two published fields
flipped in separate statements — version skew for a reader between
them). ``clean_flip`` pins the discipline ROADMAP item 1's hot-swap
must follow. Never imported by the package; parsed/compiled by tests
only."""
import threading

from keystone_tpu.utils.guarded import published_by


@published_by("_lock", "_live", "_epoch")
class TornPlane:
    def __init__(self):
        # __init__ is exempt: the object is not shared yet
        self._lock = threading.Lock()
        self._live = {}
        self._epoch = 0

    def unlocked_flip(self, snap):
        self._live = snap  # unpublished-write: no lock held

    def piecewise(self, name, entry):
        with self._lock:
            self._live.update({name: entry})  # non-atomic-publication

    def torn_swap(self, snap, epoch):
        with self._lock:
            # torn-publication: two published fields in two statements
            self._live = snap
            self._epoch = epoch

    def clean_flip(self, snap):
        with self._lock:
            self._live = dict(snap)  # clean: ONE atomic rebind under lock

    def clean_drop_locked(self, name):
        self._live.pop(name, None)  # clean: *_locked holds the declared
        # lock by convention, and a single-key pop is one dict-slot write
