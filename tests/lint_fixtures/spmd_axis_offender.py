"""Synthetic offender for the unbound-collective-axis pass
(``analysis/spmd.py``): a ``shard_map`` body whose ``psum`` /
``all_gather`` axis name is bound by no mesh axis this module ever
constructs — the trace-time unbound-axis error CI's single-host path
never executes. Collectives over the canonical ('data', 'model') axes
and over an axis a local ``Mesh(...)`` binds must NOT fire. Never
imported; parsed as AST by tests/tools."""
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def make_local_mesh(devices):
    # binds 'rows': collectives over it are in scope for this module
    return Mesh(devices, ("rows",))


def unbound_axis_body(x):
    return jax.lax.psum(x, "batch")  # BUG: no mesh here binds 'batch'


def unbound_gather(r):
    return jax.lax.all_gather(r, "replica", axis=0)  # BUG: unbound


def canonical_axes_body(x, r):
    # the repo's canonical mesh axes (parallel/mesh.py): clean
    s = jax.lax.psum(x, "data")
    return s + jnp.sum(jax.lax.all_gather(r, "model", axis=0))


def locally_bound_axis(x):
    return jax.lax.psum(x, "rows")  # bound by make_local_mesh: clean
