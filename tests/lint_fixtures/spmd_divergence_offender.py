"""Synthetic offender for the collective-divergence pass
(``analysis/spmd.py``): collectives reachable under host-divergent
control flow — the branch-on-``process_index`` hang, the taint-flow
variant (a local derived from the process index), the one-call-hop
variant (a helper that performs the collective), and a per-host LOOP
bound around a collective. The world-uniform shapes (gating on
``process_count() > 1``, a host-0 block with only filesystem work, a
rebind that kills the taint) must NOT fire. Never imported; parsed as
AST by tests/tools."""
import numpy as np


def sync_global_devices(tag):  # stand-in: parsed, never run
    raise NotImplementedError


def process_index():
    raise NotImplementedError


def process_count():
    raise NotImplementedError


def process_allgather(x):
    raise NotImplementedError


def _announce():
    # a direct collective inside a helper: calling THIS under a
    # divergent branch is the one-call-hop offender shape
    sync_global_devices("announce")


def branch_on_process_index(world):
    sync_global_devices("enter")  # matched on every host: clean
    if process_index() == 0:
        sync_global_devices("host0-only")  # BUG: peers never match it


def taint_flows_through_locals(world):
    rank = process_index()
    am_leader = rank == 0
    if am_leader:
        world.barrier("leader-only")  # BUG: taint propagated to the gate


def one_hop_divergence():
    if process_index() == 0:
        _announce()  # BUG: the helper's collective diverges all the same


def per_host_loop_bound(my_chunks):
    # my_chunks is a per-host count by convention (seeded via the
    # divergent name below): the loop runs a different number of
    # rounds per host, so the collective inside mismatches
    pid = process_index()
    for _ in range(pid):
        process_allgather(np.zeros(3))  # BUG: per-host round count


def uniform_world_size_gate(world):
    # process_count is the SAME on every host: gating a collective on
    # it is the safe idiom, never flagged
    if process_count() > 1:
        sync_global_devices("world-enter")


def host0_filesystem_only(ckpt, n):
    # a host-0 block with no collective inside: pass 1 stays silent
    # (pass 4 owns the barrier-pairing question)
    world_barrier_placeholder = None
    if process_index() == 0:
        np.save("/tmp/out.npy", np.zeros(n))


def rebind_kills_taint(world):
    rank = process_index()
    rank = 0  # rebound from a uniform value: taint dies here
    if rank == 0:
        sync_global_devices("everyone")  # clean: every host takes this
