"""Synthetic offender for ``hotpath-host-sync``
(``analysis.hotpath.hotpath_hazards``): a ``@hotpath`` entry that
coerces through a numpy alias (the silent device->host drag), calls
``block_until_ready`` (the explicit round trip), and ``device_put``
(the H2D half). Never imported by the package; parsed/compiled by
tests only."""
import numpy as np

from keystone_tpu.utils.guarded import hotpath


class SyncyPlane:
    @hotpath
    def respond(self, out, sharding):
        host = np.asarray(out)  # hotpath-host-sync: implicit coercion
        out.block_until_ready()  # hotpath-host-sync: explicit sync
        staged = out.device_put(sharding)  # hotpath-host-sync: transfer
        return host, staged
