"""Lint fixture: the pre-PR-2 ``_bcd_jit_for`` recompile bug, in its
original shape — a module-level ``jax.jit`` of a solver that reads the
ambient global mesh (here one call away, through ``_class_spec``).

jax's trace cache is keyed on the function object plus avals, NOT on
the ambient mesh the trace bakes in: the first mesh's sharding
constraints stick to the cached jaxpr, and a fit on a second mesh at
the same shapes silently reuses them. The fix (today's
``ops/linalg.py::_bcd_jit_for``) keys the jit per mesh through an
``lru_cache`` factory taking the mesh as a parameter.

This module exists to be PARSED by tests/test_analysis_passes.py (the
recompile-hazard pass must fire on it); it is never imported at
runtime.
"""
import jax

from keystone_tpu.parallel.mesh import get_mesh


def _class_spec(k):
    # reads the AMBIENT mesh: whatever mesh is global at trace time
    # bakes into any jit trace that calls through here
    mesh = get_mesh()
    return None if k % 2 else mesh


def bcd_core(blocks, Y, lam):
    spec = _class_spec(Y.shape[1])
    if spec is not None:
        Y = jax.lax.with_sharding_constraint(Y, spec)
    return [b @ Y * lam for b in blocks]


# BUG (pre-PR-2 form): one module-lifetime jit whose cached trace bakes
# the first mesh's constraints — the recompile-hazard lint flags this
_BCD_JIT = jax.jit(bcd_core)
