"""Synthetic offender for ``hotpath-lazy-import``
(``analysis.hotpath.hotpath_hazards``): a ``@hotpath`` entry executing
an ``import`` statement per request — the exact shape the first tree
scan found on the real serving path (per-request ``MetricsRegistry``
imports in the batcher, per-shard ``record_span`` imports in
``shard_put``), fixed by hoisting in PR 17. Never imported by the
package; parsed/compiled by tests only."""
from keystone_tpu.utils.guarded import hotpath


class LazyLoader:
    @hotpath
    def predict(self, x):
        import json  # hotpath-lazy-import: per-request import machinery

        return json.dumps(x)
