"""Deliberate async-coordination hazards for the unawaited-collective
pass (analysis/spmd.py, pass 5): dispatched round handles that never
reach their ``step_await``, plus a pending ``result`` read mid-flight.
Scanned as text by tests/test_spmd_passes.py; never imported or run.
The clean shapes at the bottom are the shipped pipelined round loop
(parallel/streaming.py) in miniature — the pass must stay silent on
them or it would flag the very overlap it exists to protect.
"""


def discarded_dispatch(world, cursor):
    # handle dropped on the floor: peers block in this allgather and
    # the result is never read — the next boundary folds a stale view
    world.step_begin(cursor=cursor, done=False)


def rebound_before_await(world):
    handle = world.step_begin(cursor=0, done=False)
    handle = world.step_begin(cursor=1, done=False)  # round 0 lost
    return world.step_await(handle)


def result_read_mid_flight(world):
    handle = world.step_begin(cursor=0, done=False)
    rows = handle.result  # races the in-flight allgather (still None)
    world.step_await(handle)
    return rows


def scope_exit_leak(world):
    handle = world.step_begin(cursor=0, done=False)
    return handle.round  # round number is host-side; await never runs


def pipelined_loop_is_clean(world, chunks):
    # the fit_streaming overlap shape: dispatch round k+1, await round
    # k, alias-transfer the handle, drain the extra round at the break
    # — every handle reaches exactly one await
    pending = None
    for idx, _ in enumerate(chunks):
        new_pending = world.step_begin(cursor=idx, done=False)
        if pending is not None:
            state = world.step_await(pending)
            if state.all_done:
                world.step_await(new_pending)
                break
        pending = new_pending


def inline_await_is_clean(world):
    # dispatch+await in one expression is a complete (synchronous)
    # round, not a leak
    return world.step_await(world.step_begin(cursor=0, done=True))
