"""Synthetic offender for the non-atomic guarded sequence pass
(``analysis.concurrency.guarded_sequence_hazards``): a check-then-act
on a guarded field split across two ``with`` blocks on the same lock —
every individual access is locked, but the lock is released between
the check and the act, so the check is stale. Never imported; parsed
as AST by tests/tools."""
import threading

from keystone_tpu.utils.guarded import guarded_by


@guarded_by("_lock", "items")
class SplitCheckThenAct:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def drain_one(self):
        with self._lock:
            pending = len(self.items)  # the check, locked
        if pending:
            with self._lock:
                # non-atomic-guarded-sequence: another thread may have
                # drained the last item while the lock was released
                return self.items.pop()
        return None

    def drain_one_atomic(self):
        # clean: the lock spans the decision
        with self._lock:
            if self.items:
                return self.items.pop()
        return None
