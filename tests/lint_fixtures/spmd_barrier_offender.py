"""Synthetic offender for the barrier-stability pass
(``analysis/spmd.py``): non-literal ``sync_global_devices`` /
``world.barrier`` tags (per-round names recompile the barrier program
and let two hosts compute different tags), and ``process_allgather``
payloads whose shape derives from shard-local data (a dynamically
sized list, an array built over one) — the fixed-shape
``(cursor, done)`` coordination invariant, violated. The literal-tag
and fixed-shape spellings must NOT fire. Never imported; parsed as
AST by tests/tools."""
import numpy as np


def sync_global_devices(tag):  # stand-in: parsed, never run
    raise NotImplementedError


def process_allgather(x):
    raise NotImplementedError


def per_round_tag(round_idx):
    sync_global_devices(f"round-{round_idx}")  # BUG: non-literal tag


def computed_coordinator_tag(world, phase):
    world.barrier(phase + "-done")  # BUG: computed tag at the call site


def shard_local_payload(records):
    good = [r.key for r in records]  # per-host length
    process_allgather(np.array(good))  # BUG: shape = this host's count


def appended_payload(stream):
    pending = []
    for chunk in stream:
        pending.append(chunk.n)
    process_allgather(pending)  # BUG: dynamically sized container


def fixed_shape_round(cursor, done):
    # the WorldCoordinator.step discipline: a literal-length payload
    # compiles once and matches on every host — never flagged
    process_allgather(np.array([int(cursor), 1 if done else 0],
                               np.int64))


def literal_tags(world):
    sync_global_devices("keystone-finalize")  # literal: clean
    world.barrier("ckpt-sidecars")            # literal: clean
