"""Synthetic offender for the ``metric-name-drift`` pass
(``analysis.diagnostics.metric_name_drift``): metric factory calls
whose names are NOT in the ``observability/names.py`` catalogue.
Parsed by tests, never imported."""

from keystone_tpu.observability.metrics import MetricsRegistry

reg = MetricsRegistry.get_or_create()

# uncatalogued literal: a typo'd counter name (drifted from
# streaming.chunks_total) — the dashboard scraping the real name
# flatlines silently
reg.counter("streaming.chunk_total").inc()

# uncatalogued literal gauge
reg.gauge("ingest.depth").set(2)

# f-string that does not open with a catalogued prefix: the family was
# never declared in METRIC_PREFIXES
kind = "decode"
reg.histogram(f"pool.wait_s.{kind}").observe(0.01)


def fine_paths():
    # catalogued literal: NOT flagged
    reg.counter("streaming.chunks_total").inc()
    # catalogued prefix family: NOT flagged
    event = "retry"
    reg.counter(f"resilience.{event}").inc()
    # fully dynamic name: uncheckable, passes through
    name = "anything"
    reg.histogram(name).observe(1.0)
