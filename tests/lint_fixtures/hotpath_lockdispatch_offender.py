"""Synthetic offender for ``hotpath-lock-held-dispatch``
(``analysis.hotpath.hotpath_hazards``): a ``@hotpath`` entry that
calls a helper while holding ``self._lock`` — and the helper
TRANSITIVELY syncs with the device (``block_until_ready`` one more hop
down), so every thread contending the lock stalls for the device round
trip. The unlocked call to the same helper pins that the rule is about
the held lock, not the helper. Never imported by the package;
parsed/compiled by tests only."""
import threading

from keystone_tpu.utils.guarded import hotpath


class DispatchUnderLock:
    def __init__(self):
        self._lock = threading.Lock()

    @hotpath
    def flush(self, batch):
        with self._lock:
            self._dispatch(batch)  # hotpath-lock-held-dispatch

    @hotpath
    def flush_unlocked(self, batch):
        # clean at this line: same callee, lock released first (the
        # helper's own host-sync hazard still fires, on ITS line)
        return self._dispatch(batch)

    def _dispatch(self, batch):
        return self._gather(batch)

    def _gather(self, batch):
        batch.block_until_ready()  # hotpath-host-sync, two hops down
        return batch
