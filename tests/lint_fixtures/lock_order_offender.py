"""Synthetic offender for the lock-order / blocking-under-lock passes
(``analysis.concurrency``): two locks acquired in both orders across
two methods (a deadlock waiting for the right schedule), plus blocking
calls — ``queue.get``, ``Event.wait``, ``device_put`` — made while
holding an analyzer-known lock. Never imported; parsed as AST by
tests/tools."""
import threading

_MODULE_LOCK = threading.Lock()


class DeadlockPair:
    def __init__(self):
        self._ingest = threading.Lock()
        self._ledger = threading.Lock()

    def producer_side(self):
        with self._ingest:
            with self._ledger:  # ingest -> ledger
                pass

    def consumer_side(self):
        with self._ledger:
            with self._ingest:  # ledger -> ingest: the cycle
                pass

    def stalls_everyone(self, q, ev, jax, chunk):
        with self._ingest:
            item = q.get(timeout=1.0)      # blocking-under-lock
            ev.wait()                      # blocking-under-lock
            staged = jax.device_put(chunk)  # blocking-under-lock
            return item, staged

    def module_nesting(self):
        with _MODULE_LOCK:
            with self._ingest:  # module lock -> instance lock edge
                pass
