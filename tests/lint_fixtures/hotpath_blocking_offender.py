"""Synthetic offender for ``hotpath-blocking``
(``analysis.hotpath.hotpath_hazards``): a class whose ``@hotpath``
entry points reach blocking primitives — a semaphore acquire, an event
wait, a future ``.result``, a queue ``.get``, and (through a helper,
pinning the interprocedural chain naming) a ``sleep``. Never imported
by the package; parsed/compiled by tests only."""
import threading
import time

from keystone_tpu.utils.guarded import hotpath


class SlowGate:
    def __init__(self):
        self._slots = threading.Semaphore(4)
        self._done = threading.Event()

    @hotpath
    def handle(self, fut):
        self._slots.acquire()  # hotpath-blocking: semaphore backpressure
        self._done.wait(1.0)  # hotpath-blocking: event wait
        return fut.result()  # hotpath-blocking: future join

    @hotpath
    def drain(self, q):
        return q.get()  # hotpath-blocking: queue get

    @hotpath
    def submit(self, item):
        # clean at this line — the hazard is INSIDE the helper, and the
        # diagnostic must name the chain SlowGate.submit -> SlowGate._stall
        return self._stall(item)

    def _stall(self, item):
        time.sleep(0.01)  # hotpath-blocking, reached interprocedurally
        return item
