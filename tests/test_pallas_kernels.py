"""Pallas kernel tests (interpreter mode on CPU; the real-TPU path is
exercised by bench.py and the driver's compile check)."""
import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.ops.pallas_kernels import gram_cross, gram_cross_pallas


@pytest.mark.parametrize("n,d,k", [(100, 37, 5), (513, 128, 16), (7, 3, 2)])
def test_gram_cross_pallas_interpret(n, d, k):
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    g, c = gram_cross_pallas(jnp.asarray(X), jnp.asarray(Y), interpret=True)
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=2e-4, atol=2e-4)


def test_gram_cross_fallback_matches():
    rng = np.random.RandomState(1)
    X = rng.randn(64, 10).astype(np.float32)
    Y = rng.randn(64, 3).astype(np.float32)
    g, c = gram_cross(jnp.asarray(X), jnp.asarray(Y))  # cpu fallback path
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=1e-4, atol=1e-4)


def test_fused_cifar_featurize_matches_composed_ops():
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import fused_cifar_featurize

    rng = np.random.RandomState(0)
    B, K, S = 3, 32, 6
    imgs = rng.rand(B, 32, 32, 3).astype(np.float32) * 255
    filters = rng.randn(K, S * S * 3).astype(np.float32)
    got = np.asarray(fused_cifar_featurize(
        jnp.asarray(imgs), jnp.asarray(filters), interpret=True))

    def one(img):
        conv = filter_bank_convolve(
            jnp.asarray(img), jnp.asarray(filters), S, 3, True, None, 10.0)
        pos = jnp.maximum(0.0, conv - 0.25)
        neg = jnp.maximum(0.0, -conv - 0.25)
        return np.asarray(pool_image(
            jnp.concatenate([pos, neg], -1), 13, 14, "identity", "sum"
        )).reshape(-1)

    want = np.stack([one(i) for i in imgs])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_fused_node_off_tpu_composes(mesh8):
    from keystone_tpu.nodes.images.core import FusedConvRectifyPool
    from keystone_tpu.parallel.dataset import ArrayDataset

    rng = np.random.RandomState(0)
    imgs = rng.rand(8, 32, 32, 3).astype(np.float32)
    filters = rng.randn(16, 108).astype(np.float32)
    node = FusedConvRectifyPool(filters, 32, 6)
    out = node.apply_dataset(ArrayDataset.from_numpy(imgs)).numpy()
    assert out.shape == (8, 2 * 2 * 2 * 16)
    single = np.asarray(node.apply(imgs[0]))
    np.testing.assert_allclose(out[0], single, rtol=1e-4, atol=1e-4)


def test_fused_featurize_whitener_means_parity():
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import fused_cifar_featurize

    rng = np.random.RandomState(2)
    B, K, S = 2, 16, 6
    imgs = rng.rand(B, 32, 32, 3).astype(np.float32) * 255
    filters = rng.randn(K, S * S * 3).astype(np.float32)
    means = rng.randn(S * S * 3).astype(np.float32)
    got = np.asarray(fused_cifar_featurize(
        jnp.asarray(imgs), jnp.asarray(filters),
        whitener_means=jnp.asarray(means), interpret=True))

    def one(img):
        conv = filter_bank_convolve(
            jnp.asarray(img), jnp.asarray(filters), S, 3, True,
            jnp.asarray(means), 10.0)
        pos = jnp.maximum(0.0, conv - 0.25)
        neg = jnp.maximum(0.0, -conv - 0.25)
        return np.asarray(pool_image(
            jnp.concatenate([pos, neg], -1), 13, 14, "identity", "sum"
        )).reshape(-1)

    want = np.stack([one(i) for i in imgs])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_gram_vmem_guard_boundary():
    """The fused gram kernel's (d, d)+(d, k) accumulators are VMEM-
    resident for the whole grid; beyond the measured budget the TPU
    compiler crashes with a scoped-vmem OOM, so the wrappers must fall
    back to the einsum path instead of attempting the kernel."""
    from keystone_tpu.ops.pallas_kernels import gram_fits_vmem

    assert gram_fits_vmem(512, 16)
    assert gram_fits_vmem(896, 128)
    assert not gram_fits_vmem(1024, 16)   # measured compile failure
    assert not gram_fits_vmem(4096, 10)   # ImageNet-scale solve dims
    assert not gram_fits_vmem(3072, 10)   # LinearPixels dims


def test_gram_vmem_guard_counts_input_tiles():
    """Small-d / large-k shapes blow VMEM through the streamed Y block,
    not the accumulators — the budget must count input tiles too."""
    from keystone_tpu.ops.pallas_kernels import gram_fits_vmem

    assert not gram_fits_vmem(128, 6912)


# -- shared fits-vmem predicate (PR 13 satellite) ---------------------------


def test_fits_vmem_boundary_is_exact(monkeypatch):
    """Every kernel dispatcher asks the ONE shared predicate; pin the
    fallback trigger exactly at the boundary via the env override
    (read live, so setting it mid-process takes effect)."""
    from keystone_tpu.ops import pallas_kernels as pk

    cases = {
        "gram": (lambda: pk.gram_fits_vmem(512, 16),
                 (512 + 2 * pk.ROW_TILE) * (512 + 128)),
        "banded": (lambda: pk.banded_fits_vmem(480, 480, 5120),
                   2 * (pk.BAND_TILE_M * pk.BAND_TILE_N
                        + pk.BAND_TILE_L * pk.BAND_TILE_N
                        + pk.BAND_TILE_M * pk.BAND_TILE_L)),
        "fv": (lambda: pk.fv_fits_vmem(64, 16),
               4 * 128 * 128 + 2 * 128 * pk.FV_TILE
               + 3 * pk.FV_TILE * 128 + 128),
        "quant": (lambda: pk.quant_fits_vmem(64, 16, 1),
                  128 * 128 * 1.25 + 2 * pk.QUANT_TILE * 256 + 2 * 256),
    }
    for name, (predicate, slots) in cases.items():
        monkeypatch.setenv("KEYSTONE_GRAM_VMEM_SLOTS", str(int(slots)))
        assert predicate(), f"{name}: must fit AT its own footprint"
        monkeypatch.setenv("KEYSTONE_GRAM_VMEM_SLOTS", str(int(slots) - 1))
        assert not predicate(), f"{name}: must fall back one slot under"


# -- banded GEMM (PR 13 tentpole 1) -----------------------------------------


def _random_band(rng, m, l, bw):
    band = np.zeros((m, l), np.float32)
    for j in range(m):
        lo = max(0, min(j, l - 1) - bw)
        hi = min(l, min(j, l - 1) + bw + 1)
        band[j, lo:hi] = rng.randn(hi - lo)
    return band


@pytest.mark.parametrize("m,l,n,bw", [
    (128, 128, 64, 9),    # single tile pair
    (300, 300, 70, 21),   # ragged everything
    (97, 97, 33, 5),      # all dims under one tile
    (256, 512, 130, 41),  # rectangular, multi-tile band
])
def test_banded_matmul_interpret(m, l, n, bw):
    from keystone_tpu.ops.pallas_kernels import banded_matmul

    rng = np.random.RandomState(0)
    band = _random_band(rng, m, l, bw)
    X = rng.randn(l, n).astype(np.float32)
    out = np.asarray(banded_matmul(band, jnp.asarray(X), interpret=True))
    np.testing.assert_allclose(out, band @ X, rtol=2e-4, atol=2e-4)


def test_band_tile_map_covers_every_live_tile():
    """Correctness invariant of the trace-time tile map: every nonzero
    (row tile, col tile) block of the band is visited by some inner
    step, and no column tile is visited twice for one row tile."""
    from keystone_tpu.ops.pallas_kernels import (
        BAND_TILE_L,
        BAND_TILE_M,
        band_tile_map,
    )

    rng = np.random.RandomState(1)
    band = np.zeros((512, 640), np.float32)
    for j in range(512):
        c = min(int(j * 1.2), 639)
        band[j, max(0, c - 30):c + 31] = 1.0
    band[250:260, :] = 0.0  # an all-zero row tile region
    starts, max_count = band_tile_map(band)
    n_col_tiles = 640 // BAND_TILE_L
    for i in range(512 // BAND_TILE_M):
        visited = {int(starts[i]) + j for j in range(max_count)}
        assert len(visited) == max_count  # distinct -> never double-added
        assert all(0 <= c < n_col_tiles for c in visited)
        rows = band[i * BAND_TILE_M:(i + 1) * BAND_TILE_M]
        for c in range(n_col_tiles):
            if rows[:, c * BAND_TILE_L:(c + 1) * BAND_TILE_L].any():
                assert c in visited, (i, c)


@pytest.mark.parametrize("h,w", [(96, 128), (90, 110)])
def test_dense_sift_banded_matches_einsum(h, w):
    """The banded kernel's descriptors must sit inside the golden
    envelope of the einsum path (max <= 2 quantization levels, mean <=
    0.15 — the same bound the HIGH-vs-HIGHEST gate uses); measured
    deltas are ~1e-5."""
    from keystone_tpu.ops.sift import dense_sift

    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.rand(h, w).astype(np.float32))
    kw = dict(step=4, bin_size=4, num_scales=2, scale_step=1)
    a = np.asarray(dense_sift(img, kernel_mode="einsum", **kw))
    b = np.asarray(dense_sift(img, kernel_mode="banded_interpret", **kw))
    assert a.shape == b.shape and a.shape[1] > 0
    diff = np.abs(a - b)
    assert diff.max() <= 2.0 and diff.mean() <= 0.15
    np.testing.assert_allclose(b, a, atol=5e-3)


def test_sift_kernel_mode_auto_dispatch(monkeypatch):
    """Auto mode: einsum on CPU; banded on (mocked) TPU for images big
    enough to skip tiles, einsum for CIFAR-size images where the band
    IS the whole matrix."""
    from keystone_tpu.ops import pallas_kernels as pk
    from keystone_tpu.ops import sift as S

    assert S._resolve_kernel_mode(None, 480, 640) == "einsum"  # CPU
    monkeypatch.setattr(pk, "use_pallas", lambda: True)
    assert S._resolve_kernel_mode(None, 480, 640) == "banded"
    assert S._resolve_kernel_mode(None, 32, 32) == "einsum"
    monkeypatch.setenv("KEYSTONE_GRAM_VMEM_SLOTS", "1")
    assert S._resolve_kernel_mode(None, 480, 640) == "einsum"


# -- fused GMM-posterior + FV moments (PR 13 tentpole 2) --------------------


def _gmm_params(rng, d, k):
    return (rng.randn(d, k).astype(np.float32),
            (0.5 + rng.rand(d, k)).astype(np.float32),
            (rng.dirichlet(np.ones(k))).astype(np.float32))


@pytest.mark.parametrize("d,k,n", [(64, 16, 513), (32, 8, 100), (7, 3, 12)])
def test_fv_moments_pallas_interpret(d, k, n):
    """Kernel moments == fallback (posterior matrix) moments at mixed
    shapes including ragged descriptor counts (n not a tile multiple:
    the kernel must mask padded descriptor columns — a zero descriptor
    still has a nonzero posterior)."""
    from keystone_tpu.nodes.learning.gmm import _posteriors
    from keystone_tpu.ops.pallas_kernels import fv_moments_pallas

    rng = np.random.RandomState(0)
    X = rng.randn(d, n).astype(np.float32)
    means, variances, weights = _gmm_params(rng, d, k)
    q = np.asarray(_posteriors(
        jnp.asarray(X.T), jnp.asarray(means.T), jnp.asarray(variances.T),
        jnp.asarray(weights), 1e-4))
    s0, s1, s2 = fv_moments_pallas(
        jnp.asarray(X), jnp.asarray(means), jnp.asarray(variances),
        jnp.asarray(weights), threshold=1e-4, interpret=True)
    np.testing.assert_allclose(np.asarray(s0), q.sum(0),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(s1), X @ q, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(s2), (X * X) @ q,
                               rtol=2e-4, atol=2e-4)


def test_fisher_vector_fused_matches_fallback():
    """End-to-end FV parity, per item and under vmap (the production
    featurizer vmaps the encoder over an image batch)."""
    import jax

    from keystone_tpu.nodes.images.fisher_vector import _fisher_vector

    rng = np.random.RandomState(1)
    d, k, n, batch = 64, 16, 200, 3
    Xb = rng.randn(batch, d, n).astype(np.float32)
    means, variances, weights = _gmm_params(rng, d, k)
    args = (jnp.asarray(means), jnp.asarray(variances),
            jnp.asarray(weights))

    def fused(x):
        return _fisher_vector(x, *args, 1e-4,
                              kernel_mode="pallas_interpret")

    def fallback(x):
        return _fisher_vector(x, *args, 1e-4, kernel_mode="einsum")

    a = np.asarray(jax.vmap(fallback)(jnp.asarray(Xb)))
    b = np.asarray(jax.vmap(fused)(jnp.asarray(Xb)))
    assert a.shape == (batch, d, 2 * k)
    np.testing.assert_allclose(b, a, rtol=2e-3, atol=2e-4)


# -- quantized predict (PR 13 tentpole 3) -----------------------------------


def test_quantized_affine_pallas_interpret():
    """Kernel == dequantizing-einsum fallback (bit-compatible: the same
    dequantize-then-f32-matmul math) for int8 and bf16 weights at a
    ragged batch size."""
    from keystone_tpu.ops.pallas_kernels import quantized_affine_pallas

    rng = np.random.RandomState(0)
    n, d, k = 77, 50, 11
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    mean = rng.randn(d).astype(np.float32)
    inv = (1.0 + rng.rand(d)).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    scale = (np.abs(W).max(axis=0) / 127.0).astype(np.float32)
    Wq = np.clip(np.round(W / scale), -127, 127).astype(np.int8)
    got = np.asarray(quantized_affine_pallas(
        jnp.asarray(X), jnp.asarray(Wq), jnp.asarray(scale),
        jnp.asarray(mean), jnp.asarray(inv), jnp.asarray(b),
        interpret=True))
    want = ((X - mean) * inv) @ (Wq.astype(np.float32) * scale) + b
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    Wb = jnp.asarray(W, jnp.bfloat16)
    got = np.asarray(quantized_affine_pallas(
        jnp.asarray(X), Wb, jnp.ones((k,), jnp.float32),
        jnp.asarray(mean), jnp.asarray(inv), jnp.asarray(b),
        interpret=True))
    want = ((X - mean) * inv) @ np.asarray(Wb.astype(jnp.float32)) + b
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("weight_dtype,min_agree,max_rel", [
    ("bf16", 1.0, 0.02), ("int8", 0.98, 0.03)])
def test_quantized_predict_parity_gate(weight_dtype, min_agree, max_rel,
                                       mesh8):
    """The serving-plane parity bar: quantized apply must agree with
    the f32 apply on argmax and stay inside a relative error bound,
    per item AND on the batched dataset path, with the quantization
    error recorded into the numerics funnel."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.observability import MetricsRegistry
    from keystone_tpu.parallel.dataset import ArrayDataset

    rng = np.random.RandomState(0)
    n, d, k = 256, 64, 10
    X = rng.randn(n, d).astype(np.float32)
    # a separable teacher task: agreement on pure-noise labels would
    # measure near-tie argmax flips, not quantization quality
    teacher = rng.randn(d, k).astype(np.float32)
    Y = -np.ones((n, k), np.float32)
    Y[np.arange(n), (X @ teacher).argmax(1)] = 1.0
    model = LinearMapEstimator(1e-3).fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    quant = LinearMapEstimator(1e-3, weight_dtype=weight_dtype).fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    assert quant.weight_dtype == weight_dtype

    reg = MetricsRegistry.get_or_create()
    events0 = reg.counter("numerics.quant_error").value
    a = model.apply_dataset(ArrayDataset.from_numpy(X)).numpy()
    b = quant.apply_dataset(ArrayDataset.from_numpy(X)).numpy()
    assert (a.argmax(1) == b.argmax(1)).mean() >= min_agree
    assert np.abs(a - b).max() / np.abs(a).max() <= max_rel
    # the quantization error landed in the numerics funnel
    assert reg.counter("numerics.quant_error").value >= events0 + 1
    assert reg.gauge("numerics.quant_rel_error").value > 0.0
    # per-item path agrees with the batch path
    pi = np.asarray(quant.apply(jnp.asarray(X[0])))
    np.testing.assert_allclose(pi, b[0], rtol=1e-4, atol=1e-4)


def test_weight_dtype_contract():
    """Config validation + program identity: a typo fails eagerly;
    differently-quantized models never share struct-keyed programs;
    pickling re-quantizes on first use (the cache is a _jit_ key)."""
    import pickle

    from keystone_tpu.nodes.learning.linear import (
        BlockLinearMapper,
        LinearMapper,
        _canon_weight_dtype,
    )

    with pytest.raises(ValueError):
        _canon_weight_dtype("float16")
    assert _canon_weight_dtype("bfloat16") == "bf16"
    assert _canon_weight_dtype(np.int8) == "int8"
    assert _canon_weight_dtype(None) is None

    W = np.eye(4, dtype=np.float32)
    m32 = LinearMapper(W)
    m8 = LinearMapper(W, weight_dtype="int8")
    assert m32.struct_key() != m8.struct_key()
    assert m32.eq_key() != m8.eq_key()
    bm = BlockLinearMapper([W[:2], W[2:]], 2, weight_dtype="bf16")
    assert bm.struct_key() != BlockLinearMapper([W[:2], W[2:]], 2).struct_key()

    m8.apply_params()  # builds + caches the quantized params
    clone = pickle.loads(pickle.dumps(m8))
    assert clone.weight_dtype == "int8"
    assert "_jit_affine_params" not in clone.__dict__
    x = np.ones(4, np.float32)
    np.testing.assert_allclose(np.asarray(clone.apply(jnp.asarray(x))),
                               np.asarray(m8.apply(jnp.asarray(x))))


def test_bench_metric_names_catalogued():
    """The rename protection BENCH_METRIC_NAMES promises, enforced:
    every catalogued kernel bench line must appear in bench.py (a
    rename without touching the catalogue fails here, instead of
    silently resetting the benchdiff baseline as a 'new' metric)."""
    import pathlib

    from keystone_tpu.observability.names import BENCH_METRIC_NAMES

    src = pathlib.Path(__file__).parent.parent.joinpath(
        "bench.py").read_text()
    for name in BENCH_METRIC_NAMES:
        # the predict lines are emitted via one f-string over the
        # dtype tags: check the f-string spelling for those
        head, _, tail = name.partition("_quantized_")
        pattern = name if not tail else \
            f'{head}_quantized_{{tag}}_{tail.split("_", 1)[1]}'
        assert name in src or pattern in src, (
            f"{name}: catalogued in names.BENCH_METRIC_NAMES but not "
            f"emitted by bench.py — rename both sides together")
