"""Pallas kernel tests (interpreter mode on CPU; the real-TPU path is
exercised by bench.py and the driver's compile check)."""
import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.ops.pallas_kernels import gram_cross, gram_cross_pallas


@pytest.mark.parametrize("n,d,k", [(100, 37, 5), (513, 128, 16), (7, 3, 2)])
def test_gram_cross_pallas_interpret(n, d, k):
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    g, c = gram_cross_pallas(jnp.asarray(X), jnp.asarray(Y), interpret=True)
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=2e-4, atol=2e-4)


def test_gram_cross_fallback_matches():
    rng = np.random.RandomState(1)
    X = rng.randn(64, 10).astype(np.float32)
    Y = rng.randn(64, 3).astype(np.float32)
    g, c = gram_cross(jnp.asarray(X), jnp.asarray(Y))  # cpu fallback path
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=1e-4, atol=1e-4)
