"""Pallas kernel tests (interpreter mode on CPU; the real-TPU path is
exercised by bench.py and the driver's compile check)."""
import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.ops.pallas_kernels import gram_cross, gram_cross_pallas


@pytest.mark.parametrize("n,d,k", [(100, 37, 5), (513, 128, 16), (7, 3, 2)])
def test_gram_cross_pallas_interpret(n, d, k):
    rng = np.random.RandomState(0)
    X = rng.randn(n, d).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    g, c = gram_cross_pallas(jnp.asarray(X), jnp.asarray(Y), interpret=True)
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=2e-4, atol=2e-4)


def test_gram_cross_fallback_matches():
    rng = np.random.RandomState(1)
    X = rng.randn(64, 10).astype(np.float32)
    Y = rng.randn(64, 3).astype(np.float32)
    g, c = gram_cross(jnp.asarray(X), jnp.asarray(Y))  # cpu fallback path
    np.testing.assert_allclose(np.asarray(g), X.T @ X, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(c), X.T @ Y, rtol=1e-4, atol=1e-4)


def test_fused_cifar_featurize_matches_composed_ops():
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import fused_cifar_featurize

    rng = np.random.RandomState(0)
    B, K, S = 3, 32, 6
    imgs = rng.rand(B, 32, 32, 3).astype(np.float32) * 255
    filters = rng.randn(K, S * S * 3).astype(np.float32)
    got = np.asarray(fused_cifar_featurize(
        jnp.asarray(imgs), jnp.asarray(filters), interpret=True))

    def one(img):
        conv = filter_bank_convolve(
            jnp.asarray(img), jnp.asarray(filters), S, 3, True, None, 10.0)
        pos = jnp.maximum(0.0, conv - 0.25)
        neg = jnp.maximum(0.0, -conv - 0.25)
        return np.asarray(pool_image(
            jnp.concatenate([pos, neg], -1), 13, 14, "identity", "sum"
        )).reshape(-1)

    want = np.stack([one(i) for i in imgs])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_fused_node_off_tpu_composes(mesh8):
    from keystone_tpu.nodes.images.core import FusedConvRectifyPool
    from keystone_tpu.parallel.dataset import ArrayDataset

    rng = np.random.RandomState(0)
    imgs = rng.rand(8, 32, 32, 3).astype(np.float32)
    filters = rng.randn(16, 108).astype(np.float32)
    node = FusedConvRectifyPool(filters, 32, 6)
    out = node.apply_dataset(ArrayDataset.from_numpy(imgs)).numpy()
    assert out.shape == (8, 2 * 2 * 2 * 16)
    single = np.asarray(node.apply(imgs[0]))
    np.testing.assert_allclose(out[0], single, rtol=1e-4, atol=1e-4)


def test_fused_featurize_whitener_means_parity():
    from keystone_tpu.ops.image_ops import filter_bank_convolve, pool_image
    from keystone_tpu.ops.pallas_kernels import fused_cifar_featurize

    rng = np.random.RandomState(2)
    B, K, S = 2, 16, 6
    imgs = rng.rand(B, 32, 32, 3).astype(np.float32) * 255
    filters = rng.randn(K, S * S * 3).astype(np.float32)
    means = rng.randn(S * S * 3).astype(np.float32)
    got = np.asarray(fused_cifar_featurize(
        jnp.asarray(imgs), jnp.asarray(filters),
        whitener_means=jnp.asarray(means), interpret=True))

    def one(img):
        conv = filter_bank_convolve(
            jnp.asarray(img), jnp.asarray(filters), S, 3, True,
            jnp.asarray(means), 10.0)
        pos = jnp.maximum(0.0, conv - 0.25)
        neg = jnp.maximum(0.0, -conv - 0.25)
        return np.asarray(pool_image(
            jnp.concatenate([pos, neg], -1), 13, 14, "identity", "sum"
        )).reshape(-1)

    want = np.stack([one(i) for i in imgs])
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_gram_vmem_guard_boundary():
    """The fused gram kernel's (d, d)+(d, k) accumulators are VMEM-
    resident for the whole grid; beyond the measured budget the TPU
    compiler crashes with a scoped-vmem OOM, so the wrappers must fall
    back to the einsum path instead of attempting the kernel."""
    from keystone_tpu.ops.pallas_kernels import gram_fits_vmem

    assert gram_fits_vmem(512, 16)
    assert gram_fits_vmem(896, 128)
    assert not gram_fits_vmem(1024, 16)   # measured compile failure
    assert not gram_fits_vmem(4096, 10)   # ImageNet-scale solve dims
    assert not gram_fits_vmem(3072, 10)   # LinearPixels dims


def test_gram_vmem_guard_counts_input_tiles():
    """Small-d / large-k shapes blow VMEM through the streamed Y block,
    not the accumulators — the budget must count input tiles too."""
    from keystone_tpu.ops.pallas_kernels import gram_fits_vmem

    assert not gram_fits_vmem(128, 6912)
