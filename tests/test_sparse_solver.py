"""SparseLBFGSwithL2 tests (mirrors the reference's LBFGSSuite sparse
cases)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning.lbfgs import SparseLBFGSwithL2
from keystone_tpu.nodes.util.sparse import Sparsify
from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset


def _sparse_problem(seed=0, n=64, d=20, k=3, density=0.3):
    rng = np.random.RandomState(seed)
    X = ((rng.rand(n, d) < density) * rng.randn(n, d)).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W + 0.5).astype(np.float32)
    return X, W, Y


def test_sparse_lbfgs_recovers_solution(mesh8):
    X, Wtrue, Y = _sparse_problem()
    sp = Sparsify()
    ds = HostDataset([sp.apply(x) for x in X])
    model = SparseLBFGSwithL2(
        fit_intercept=True, num_iterations=200, lam=0.0
    ).fit(ds, ArrayDataset.from_numpy(Y))
    pred = X @ model.weights + model.intercept
    np.testing.assert_allclose(pred, Y, atol=1e-4)
    np.testing.assert_allclose(model.intercept, 0.5, atol=1e-4)


def test_sparse_lbfgs_no_intercept(mesh8):
    X, Wtrue, Y = _sparse_problem()
    Y = (X @ Wtrue).astype(np.float32)  # no offset
    sp = Sparsify()
    ds = HostDataset([sp.apply(x) for x in X])
    model = SparseLBFGSwithL2(
        fit_intercept=False, num_iterations=200, lam=0.0
    ).fit(ds, ArrayDataset.from_numpy(Y))
    assert model.intercept is None
    np.testing.assert_allclose(X @ model.weights, Y, atol=1e-4)


def test_sparse_lbfgs_matches_dense(mesh8):
    from keystone_tpu.nodes.learning import DenseLBFGSwithL2

    X, _, Y = _sparse_problem(seed=3)
    lam = 0.1
    sp = Sparsify()
    sparse_model = SparseLBFGSwithL2(
        fit_intercept=False, num_iterations=300, lam=lam
    ).fit(HostDataset([sp.apply(x) for x in X]), ArrayDataset.from_numpy(Y))
    dense_model = DenseLBFGSwithL2(
        fit_intercept=False, num_iterations=300, lam=lam
    ).fit(ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    np.testing.assert_allclose(
        sparse_model.weights, np.asarray(dense_model.weights), atol=2e-3)


def test_sparse_mapper_batch_apply(mesh8):
    X, _, Y = _sparse_problem()
    sp = Sparsify()
    ds = HostDataset([sp.apply(x) for x in X])
    model = SparseLBFGSwithL2(num_iterations=50).fit(
        ds, ArrayDataset.from_numpy(Y))
    # batch apply on dense arrays (the TPU path densifies into the GEMM)
    out = model.apply_dataset(ArrayDataset.from_numpy(X)).numpy()
    assert out.shape == Y.shape


def test_sparse_lbfgs_intercept_not_penalized(mesh8):
    # strong L2 must not shrink the intercept (reference semantics: dense
    # solver's intercept is the unregularized label mean)
    rng = np.random.RandomState(1)
    n, d = 128, 10
    X = ((rng.rand(n, d) < 0.5) * rng.randn(n, d)).astype(np.float32)
    Y = (X @ np.zeros((d, 1), np.float32) + 3.0).astype(np.float32)
    sp = Sparsify()
    model = SparseLBFGSwithL2(
        fit_intercept=True, num_iterations=300, lam=5.0
    ).fit(HostDataset([sp.apply(x) for x in X]), ArrayDataset.from_numpy(Y))
    np.testing.assert_allclose(model.intercept, [3.0], atol=1e-2)


def test_sparse_lbfgs_misaligned_labels_raise(mesh8):
    X, _, Y = _sparse_problem(n=10)
    sp = Sparsify()
    ds = HostDataset([sp.apply(x) for x in X])
    with pytest.raises(ValueError, match="do not align"):
        SparseLBFGSwithL2(num_iterations=5).fit(
            ds, ArrayDataset.from_numpy(Y[:9]))
