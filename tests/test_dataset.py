"""Dataset/mesh substrate tests (8 simulated devices)."""
import jax
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset, as_dataset
from keystone_tpu.parallel.mesh import get_mesh, make_mesh, num_data_shards


def test_eight_devices_simulated():
    assert len(jax.devices()) == 8


def test_array_dataset_pads_and_masks():
    x = np.arange(10 * 3, dtype=np.float32).reshape(10, 3)
    ds = ArrayDataset.from_numpy(x)
    assert len(ds) == 10
    assert ds.padded_n % num_data_shards() == 0
    assert ds.padded_n >= 10
    np.testing.assert_array_equal(ds.numpy(), x)
    # padded rows are zero
    full = np.asarray(ds.data)
    assert np.all(full[10:] == 0)


def test_map_respects_padding():
    x = np.ones((5, 2), dtype=np.float32)
    ds = ArrayDataset.from_numpy(x)
    out = ds.map(lambda v: v + 41.0)
    np.testing.assert_array_equal(out.numpy(), x + 41.0)
    # mapped padding is re-zeroed so sums stay exact
    assert float(np.asarray(out.data).sum()) == pytest.approx(5 * 2 * 42.0)


def test_dataset_is_sharded_over_mesh():
    x = np.ones((16, 4), dtype=np.float32)
    ds = ArrayDataset.from_numpy(x)
    shards = ds.data.sharding.device_set
    assert len(shards) == 8


def test_zip():
    a = ArrayDataset.from_numpy(np.ones((6, 2), np.float32))
    b = ArrayDataset.from_numpy(np.zeros((6, 3), np.float32))
    z = a.zip(b)
    items = z.numpy()
    assert items[0].shape == (6, 2) and items[1].shape == (6, 3)


def test_host_dataset():
    hd = HostDataset(["a", "bb", "ccc"])
    out = hd.map(len)
    assert out.collect() == [1, 2, 3]


def test_as_dataset_dispatch():
    assert isinstance(as_dataset(np.ones((4, 2))), ArrayDataset)
    assert isinstance(as_dataset(["x", "y"]), HostDataset)


def test_collect_roundtrip():
    x = np.random.RandomState(0).rand(7, 3).astype(np.float32)
    ds = ArrayDataset.from_numpy(x)
    items = ds.collect()
    assert len(items) == 7
    np.testing.assert_allclose(items[3], x[3], rtol=1e-6)
