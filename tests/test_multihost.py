"""Two-process jax.distributed smoke test (VERDICT r2 next#6): proves
``initialize_distributed`` and the cross-process collective wiring work
— a CI-runnable stand-in for the multi-host pod path documented in
CLUSTER.md. Each worker owns 2 virtual CPU devices; a 4-device global
mesh runs a psum-backed normal-equations fit whose all-reduce crosses
the process boundary (reference analogue: Spark cluster attach +
``treeReduce``, SURVEY.md section 2.14)."""
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_fit():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo_root = os.path.dirname(os.path.dirname(worker))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MULTIHOST_OK pid={i}" in out, out
