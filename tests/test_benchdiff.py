"""The statistical bench-regression gate
(``observability/benchdiff.py`` / ``python -m keystone_tpu benchdiff``).

Synthetic-artifact tests pin the band model (median consecutive swing
x 1.5, floored at 8%), the exit codes (0 in-band/improved, 1 usage or
cross-host refusal, 2 regression), the scaled-metric exclusion, and
the cross-host refusal; the acceptance test runs the gate over the
repo's REAL ``BENCH_r03.json`` / ``BENCH_r05.json`` and requires the
76-85k e2e delta to classify as in-band noise (exit 0) — the tool form
of PERFORMANCE.md's hand argument.
"""
import json
import pathlib

import pytest

from keystone_tpu.observability.benchdiff import (
    DEFAULT_BAND,
    compare,
    discover_history,
    load_artifact,
    lower_is_better,
    main as benchdiff_main,
    noise_band,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _artifact(path, n, metrics, meta=None, scaled=()):
    """Write a driver-shaped BENCH artifact: metric lines in the tail,
    the flagship-style summary as ``parsed``."""
    lines = []
    if meta is not None:
        lines.append(json.dumps({"bench_meta": meta}))
    for name, value in metrics.items():
        line = {"metric": name, "value": value, "unit": "u",
                "vs_baseline": 1.0}
        if name in scaled:
            line["scaled"] = 0.5
        lines.append(json.dumps(line))
    first = next(iter(metrics))
    parsed = {"metric": first, "value": metrics[first], "unit": "u",
              "vs_baseline": 1.0, "summary": True}
    blob = {"n": n, "cmd": "bench", "rc": 0,
            "tail": "\n".join(lines) + "\n", "parsed": parsed}
    path.write_text(json.dumps(blob))
    return path


def _history(tmp_path, values_per_round, metric="widgets_per_sec",
             meta=None):
    paths = []
    for i, v in enumerate(values_per_round, start=1):
        paths.append(_artifact(tmp_path / f"BENCH_r{i:02d}.json", i,
                               {metric: v}, meta=meta))
    return paths


# -- artifact parsing --------------------------------------------------------

def test_load_artifact_reads_tail_lines_meta_and_parsed(tmp_path):
    meta = {"hostname": "hostA", "device_kind": "cpu"}
    p = _artifact(tmp_path / "BENCH_r01.json", 1,
                  {"widgets_per_sec": 100.0, "gadget_test_error": 0.1},
                  meta=meta, scaled=("gadget_test_error",))
    art = load_artifact(str(p))
    assert art.value("widgets_per_sec") == 100.0
    assert not art.scaled("widgets_per_sec")
    assert art.scaled("gadget_test_error")
    assert art.meta == meta
    assert art.round_n == 1


def test_load_artifact_backfills_from_parsed_summary(tmp_path):
    """Metrics whose lines scrolled out of the bounded tail survive via
    the parsed summary's extra keys (the real r03 artifact's shape)."""
    blob = {"n": 3, "rc": 0, "tail": "not json\n",
            "parsed": {"metric": "flagship_per_sec", "value": 5.0,
                       "unit": "u", "vs_baseline": 1.0, "summary": True,
                       "other_images_per_sec_per_chip": 7.0,
                       "some_test_error": 0.2,
                       "timing_spread": 0.01}}
    p = tmp_path / "BENCH_r03.json"
    p.write_text(json.dumps(blob))
    art = load_artifact(str(p))
    assert art.value("flagship_per_sec") == 5.0
    assert art.value("other_images_per_sec_per_chip") == 7.0
    assert art.value("some_test_error") == 0.2
    assert art.value("timing_spread") is None  # metadata, not a metric


def test_discover_history_excludes_current(tmp_path):
    paths = _history(tmp_path, [100, 101, 99])
    hist = discover_history(str(paths[-1]))
    assert [a.round_n for a in hist] == [1, 2]  # r03 (current) excluded


# -- band model --------------------------------------------------------------

def test_noise_band_floor_without_history(tmp_path):
    band, n = noise_band("widgets_per_sec", [])
    assert band == DEFAULT_BAND and n == 0


def test_noise_band_median_swing(tmp_path):
    # swings: 10%, ~0.9%, ~0.9% -> median 0.9% -> floor wins
    arts = [load_artifact(str(p)) for p in
            _history(tmp_path, [100.0, 110.0, 111.0, 110.0])]
    band, n = noise_band("widgets_per_sec", arts)
    assert band == DEFAULT_BAND and n == 4
    # swings: 10%, 12% -> median 11% -> 1.5x = 16.5% > floor
    arts = [load_artifact(str(p)) for p in
            _history(tmp_path, [100.0, 110.0, 96.8])]
    band, _ = noise_band("widgets_per_sec", arts[:3])
    assert band > DEFAULT_BAND


def test_direction_markers():
    assert lower_is_better("cifar_randompatch_test_error")
    assert lower_is_better("ingest_stall_share")
    assert not lower_is_better("voc_map")
    assert not lower_is_better("widgets_per_sec")
    # the PR 10 numerics-health keys are failure/cost measures
    assert lower_is_better("streamed_nan_total")
    assert lower_is_better("solver_breakdown_total")
    assert lower_is_better("numerics_drift_score")
    assert lower_is_better("numerics_overhead_share")
    # serving latency (PR 15): landed BEFORE the first serving bench
    # round, the PR 9 _bytes lesson
    assert lower_is_better("serve_p50_ms")
    assert lower_is_better("serve_p99_ms")
    assert lower_is_better("serve_p99")
    assert lower_is_better("serving_request_latency")
    # throughput: _qps is higher-better and WINS over any lower-better
    # substring sharing the name
    assert not lower_is_better("serve_qps_per_chip")
    assert not lower_is_better("p99_bounded_qps")
    assert not lower_is_better("stall_free_qps")
    # the request-path plane (PR 16): phase shares of the request wall
    # and budget burn are costs; availability and batch fill are
    # utilization/goodness fractions whose markers WIN over any
    # lower-better substring in the same name
    assert lower_is_better("serve_queue_wait_share")
    assert lower_is_better("serve_dispatch_share")
    assert lower_is_better("serving_trace_overhead_share")
    assert lower_is_better("serve_error_budget_burn_rate")
    assert not lower_is_better("serve_availability")
    assert not lower_is_better("serve_batch_fill")
    # "availability" outranks a co-occurring lower-better marker
    assert not lower_is_better("availability_error_window")


def test_serving_latency_regression_fixture(tmp_path, capsys):
    """The serving direction markers as an end-to-end synthetic
    fixture: a p99 that RISES 30% exits 2 (regressed), a qps that
    DROPS 30% exits 2, and a qps that rises classifies improved —
    pinned before BENCH_r08 records the first serving baseline."""
    base = _artifact(tmp_path / "BENCH_r01.json", 1,
                     {"serve_qps_per_chip": 1000.0, "serve_p99_ms": 8.0})
    worse = _artifact(tmp_path / "BENCH_r02.json", 2,
                      {"serve_qps_per_chip": 1000.0,
                       "serve_p99_ms": 10.4})
    rc = benchdiff_main([str(base), str(worse)])
    out = capsys.readouterr().out
    assert rc == 2 and "regressed" in out

    slow = _artifact(tmp_path / "BENCH_r03.json", 3,
                     {"serve_qps_per_chip": 700.0, "serve_p99_ms": 8.0})
    rc = benchdiff_main([str(base), str(slow)])
    out = capsys.readouterr().out
    assert rc == 2
    assert any("serve_qps_per_chip" in line and "regressed" in line
               for line in out.splitlines())

    fast = _artifact(tmp_path / "BENCH_r04.json", 4,
                     {"serve_qps_per_chip": 1400.0, "serve_p99_ms": 8.0})
    rc = benchdiff_main([str(base), str(fast)])
    out = capsys.readouterr().out
    assert rc == 0
    assert any("serve_qps_per_chip" in line and "improved" in line
               for line in out.splitlines())


def test_overhead_share_bands_absolutely(tmp_path):
    """A signed share hovering at ~0 cannot use percent-of-base bands:
    a noise flip from -0.037 to +0.01 is a >100% relative move, and a
    base of exactly 0.0 is a meaningful value, not a new baseline."""
    from keystone_tpu.observability.benchdiff import (
        ABSOLUTE_BAND_FLOOR,
        classify,
    )

    m = "numerics_overhead_share"
    band, n = noise_band(m, [])
    assert band == ABSOLUTE_BAND_FLOOR and n == 0
    # zero base classifies normally (absolute delta), never new-baseline
    assert classify(m, 0.0, 0.01, band) == ("in-band", -0.01)
    # a genuine overhead jump past the 2-point bar regresses
    cls, delta = classify(m, 0.0, 0.1, band)
    assert cls == "regressed" and delta == pytest.approx(-0.1)
    # the band learns machine noise in ABSOLUTE units: swings of
    # 4/3 points -> median 3.5 x 1.5 = 5.25 points, so the -0.03 ->
    # +0.01 flip that a relative band called a 127% regression is noise
    arts = [load_artifact(str(p)) for p in
            _history(tmp_path, [-0.03, 0.01, -0.02], metric=m)]
    band, _ = noise_band(m, arts)
    assert band == pytest.approx(1.5 * 0.035)
    assert classify(m, -0.03, 0.01, band)[0] == "in-band"
    # the serving-trace share (PR 16) rides the same absolute banding
    # via the shared "overhead_share" marker
    band16, _ = noise_band("serving_trace_overhead_share", [])
    assert band16 == ABSOLUTE_BAND_FLOOR
    assert classify("serving_trace_overhead_share",
                    0.0, 0.01, band16) == ("in-band", -0.01)


def test_slo_plane_regression_fixtures(tmp_path, capsys):
    """The PR 16 direction markers end to end, pinned BEFORE BENCH_r09
    records the first request-path baseline (the PR 15 `_p99`/`_qps`
    discipline): availability that DROPS regresses, availability that
    rises improves, and a queue-wait share that GROWS (backpressure
    eating the wall) regresses."""
    base = _artifact(tmp_path / "BENCH_r01.json", 1,
                     {"serve_availability": 0.999,
                      "serve_queue_wait_share": 0.2})
    outage = _artifact(tmp_path / "BENCH_r02.json", 2,
                       {"serve_availability": 0.88,
                        "serve_queue_wait_share": 0.2})
    rc = benchdiff_main([str(base), str(outage)])
    out = capsys.readouterr().out
    assert rc == 2
    assert any("serve_availability" in line and "regressed" in line
               for line in out.splitlines())

    # a fresh dir: no learned history, so the default 8% band applies
    # and the +13.5% recovery classifies as a directional improvement
    rec = tmp_path / "rec"
    rec.mkdir()
    rec_base = _artifact(rec / "BENCH_r01.json", 1,
                         {"serve_availability": 0.88})
    recovered = _artifact(rec / "BENCH_r02.json", 2,
                          {"serve_availability": 0.999})
    rc = benchdiff_main([str(rec_base), str(recovered)])
    out = capsys.readouterr().out
    assert rc == 0
    assert any("serve_availability" in line and "improved" in line
               for line in out.splitlines())

    congested = _artifact(tmp_path / "BENCH_r04.json", 4,
                          {"serve_availability": 0.999,
                           "serve_queue_wait_share": 0.31})
    rc = benchdiff_main([str(base), str(congested)])
    out = capsys.readouterr().out
    assert rc == 2
    assert any("serve_queue_wait_share" in line and "regressed" in line
               for line in out.splitlines())


# -- classification + exit codes ---------------------------------------------

def test_in_band_noise_exits_zero(tmp_path, capsys):
    paths = _history(tmp_path, [100.0, 103.0, 98.0, 102.0])
    rc = benchdiff_main([str(paths[0]), str(paths[-1])])
    out = capsys.readouterr().out
    assert rc == 0
    assert "in-band" in out and "regressed" not in out.split("\n")[1]


def test_regression_beyond_band_exits_two(tmp_path, capsys):
    """The synthetic >band regression fixture: tight history, then a
    30% drop — exit 2 and the metric is named regressed."""
    paths = _history(tmp_path, [100.0, 101.0, 99.5, 70.0])
    rc = benchdiff_main([str(paths[-2]), str(paths[-1])])
    out = capsys.readouterr().out
    assert rc == 2
    assert "regressed" in out


def test_error_metric_direction_is_inverted(tmp_path, capsys):
    paths = _history(tmp_path, [0.10, 0.101, 0.099, 0.20],
                     metric="model_test_error")
    rc = benchdiff_main([str(paths[-2]), str(paths[-1])])
    assert rc == 2  # error DOUBLED: regression even though value rose
    paths2 = _history(tmp_path, [0.20, 0.201, 0.199, 0.10],
                      metric="model_test_error")
    assert benchdiff_main([str(paths2[-2]), str(paths2[-1])]) == 0
    assert "improved" in capsys.readouterr().out


def test_scaled_metrics_are_excluded(tmp_path, capsys):
    base = _artifact(tmp_path / "BENCH_r01.json", 1,
                     {"widgets_per_sec": 100.0})
    cur = _artifact(tmp_path / "BENCH_r02.json", 2,
                    {"widgets_per_sec": 50.0}, scaled=("widgets_per_sec",))
    rc = benchdiff_main([str(base), str(cur)])
    out = capsys.readouterr().out
    assert rc == 0  # a 50% drop measured SHRUNK is not a regression
    assert "scaled (excluded)" in out


def test_absent_and_new_metrics_are_visible_not_fatal(tmp_path, capsys):
    base = _artifact(tmp_path / "BENCH_r01.json", 1,
                     {"widgets_per_sec": 100.0, "old_per_sec": 5.0})
    cur = _artifact(tmp_path / "BENCH_r02.json", 2,
                    {"widgets_per_sec": 101.0, "fresh_per_sec": 9.0})
    rc = benchdiff_main([str(base), str(cur)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "absent" in out and "new" in out


def test_cross_host_refused_without_force(tmp_path, capsys):
    base = _artifact(tmp_path / "BENCH_r01.json", 1,
                     {"widgets_per_sec": 100.0},
                     meta={"hostname": "hostA"})
    cur = _artifact(tmp_path / "BENCH_r02.json", 2,
                    {"widgets_per_sec": 101.0},
                    meta={"hostname": "hostB"})
    rc = benchdiff_main([str(base), str(cur)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "cross-host" in err and "--force" in err
    assert benchdiff_main([str(base), str(cur), "--force"]) == 0


def test_legacy_artifacts_without_meta_compare_with_note(tmp_path, capsys):
    paths = _history(tmp_path, [100.0, 101.0])
    rc = benchdiff_main([str(paths[0]), str(paths[1])])
    captured = capsys.readouterr()
    assert rc == 0
    assert "bench_meta" in captured.err  # the unverified-host note


def test_usage_errors_exit_one(tmp_path, capsys):
    assert benchdiff_main([]) == 1
    assert benchdiff_main(["--band"]) == 1
    assert benchdiff_main([str(tmp_path / "missing1.json"),
                           str(tmp_path / "missing2.json")]) == 1


def test_band_override(tmp_path):
    paths = _history(tmp_path, [100.0, 94.0])
    # 6% drop: in-band at the default 8% floor, regressed at --band 0.02
    assert benchdiff_main([str(paths[0]), str(paths[1])]) == 0
    assert benchdiff_main([str(paths[0]), str(paths[1]),
                           "--band", "0.02"]) == 2


# -- acceptance: the real r03 vs r05 artifacts -------------------------------

def test_real_r03_vs_r05_e2e_delta_is_in_band(capsys):
    """The PERFORMANCE.md hand argument as an exit code: the 85.4k ->
    76.2k e2e delta (-10.7%) sits inside the band derived from the
    metric's own run-to-run history, so the gate exits 0 and labels it
    in-band — and the genuinely improved imagenet number is not noise."""
    base = REPO / "BENCH_r03.json"
    cur = REPO / "BENCH_r05.json"
    rc = benchdiff_main([str(base), str(cur)])
    out = capsys.readouterr().out
    assert rc == 0
    e2e_row = next(line for line in out.splitlines()
                   if line.startswith("cifar_e2e_images_per_sec_per_chip"))
    assert "in-band" in e2e_row
    imagenet_row = next(
        line for line in out.splitlines()
        if line.startswith("imagenet_rehearsal_images_per_sec_per_chip"))
    assert "improved" in imagenet_row


def test_real_artifacts_compare_api(tmp_path):
    base = load_artifact(str(REPO / "BENCH_r03.json"))
    cur = load_artifact(str(REPO / "BENCH_r05.json"))
    rows = compare(base, cur, discover_history(str(REPO / "BENCH_r05.json")))
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["cifar_e2e_images_per_sec_per_chip"][
        "classification"] == "in-band"
    assert not any(r["classification"] == "regressed" for r in rows)
