"""Elastic multi-host streamed fits (ISSUE 11 tentpole): the CPU
dryrun harness spawns REAL ``jax.distributed`` worlds (gloo
collectives), kills one host mid-fit, relaunches, and resumes from the
shared ``StreamCheckpoint`` — pinning the acceptance criteria:

* kill-one-host-mid-fit resume is BIT-IDENTICAL to the uninterrupted
  2-process run (LinearMap; the auto-solver variant is pinned at the
  1e-5 bar by the parity test),
* a resume at a different world size raises
  ``CheckpointMismatchError`` (both directions, plus wrong-world-size
  world-to-world),
* 1-vs-2-process streamed-fit weight parity <= 1e-5 with identical
  argmax,
* the PR 9 warmup fence stays clean on the distributed path
  (``unexpected_compiles=0`` reported by every worker, fresh AND
  resumed runs).

The heavyweight subprocess worlds are launched ONCE per module
(``elastic_runs`` fixture: uninterrupted / killed / resumed); the
checkpoint-format and fault-kind semantics are unit-tested in-process.
The chaos soak (bounded seeded ``FaultPlan`` sweep across the ingest
sites, every seed ending in a clean finish, a classified failure, or a
resumable checkpoint — never a hang, never silent truncation) runs
in-process too; the host-level kinds ride the dryrun worlds.
"""
import os
import sys

import numpy as np
import pytest

from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.parallel.distributed import DryrunWorld
from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming
from keystone_tpu.resilience import (
    HOST_DEATH_EXIT_CODE,
    CheckpointMismatchError,
    FaultPlan,
    IngestTimeoutError,
    PartitionError,
    RetryExhaustedError,
    StreamCheckpoint,
    fit_fingerprint,
)

N, D, K, CHUNK = 192, 12, 3, 16


def _xy(n=N, d=D, k=K, seed=0):
    rng = np.random.RandomState(seed)
    X = (rng.randn(n, d) * (1.0 + rng.rand(d))).astype(np.float32)
    Y = (X @ rng.randn(d, k) + 0.1 * rng.randn(n, k)).astype(np.float32)
    return X, Y


def _worker_argv(npz, extra=()):
    return [sys.executable, "-m", "keystone_tpu.parallel.dryrun_worker",
            "--data", npz, "--chunk-size", str(CHUNK), *extra]


def _ok_fields(world, pid):
    lines = [l for l in world.output(pid).splitlines()
             if l.startswith("ELASTIC_OK")]
    assert lines, (f"worker {pid} printed no ELASTIC_OK line:\n"
                   f"{world.output(pid)[-2000:]}")
    return dict(kv.split("=", 1) for kv in lines[0].split()[1:])


@pytest.fixture(scope="module")
def elastic_runs(tmp_path_factory):
    """Three 2-process worlds over the same data: uninterrupted,
    killed-at-round-2 (host 1 ``host_death``), and
    relaunched-and-resumed. One launch sequence serves every
    acceptance assertion below."""
    base_dir = tmp_path_factory.mktemp("elastic")
    X, Y = _xy()
    npz = str(base_dir / "data.npz")
    np.savez(npz, X=X, Y=Y)
    ckdir = str(base_dir / "ck")
    out_a = str(base_dir / "uninterrupted.npz")
    out_c = str(base_dir / "resumed.npz")
    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=str(base_dir), grace_s=20)
    runs = {"X": X, "Y": Y, "npz": npz, "ckdir": ckdir, "world": world}

    world.launch(_worker_argv(npz, ["--out", out_a, "--bench"]))
    runs["codes_a"] = world.wait(timeout_s=300)
    runs["fields_a"] = [_ok_fields(world, p) for p in range(2)]
    runs["bench_a"] = [l for l in world.output(0).splitlines()
                       if l.startswith("{")]

    world.launch(_worker_argv(npz, [
        "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--die-process", "1", "--die-at-round", "2"]))
    runs["codes_b"] = world.wait(timeout_s=300)
    runs["snapshot_after_kill"] = sorted(os.listdir(ckdir))

    world.launch(_worker_argv(npz, [
        "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--out", out_c]))
    runs["codes_c"] = world.wait(timeout_s=300)
    runs["fields_c"] = [_ok_fields(world, p) for p in range(2)]
    runs["w_a"] = np.load(out_a)["weights"]
    runs["w_c"] = np.load(out_c)["weights"]
    return runs


def test_kill_one_host_resume_bit_identical(elastic_runs):
    """Acceptance: an N-process streamed LinearMap fit killed
    mid-stream, relaunched, and resumed from the shared
    StreamCheckpoint produces BIT-identical weights to the
    uninterrupted run."""
    r = elastic_runs
    assert r["codes_a"] == [0, 0], r["codes_a"]
    # host 1 died of the injected host_death (exit 117); the launcher's
    # gang semantics reaped the wedged survivor
    assert r["codes_b"][1] == HOST_DEATH_EXIT_CODE, r["codes_b"]
    assert r["codes_b"][0] != 0
    # the killed world left a resumable coordinated snapshot: the world
    # file plus both host sidecars
    assert "stream_fit.ckpt" in r["snapshot_after_kill"]
    assert {"stream_fit.host0.ckpt", "stream_fit.host1.ckpt"} <= set(
        r["snapshot_after_kill"])
    assert r["codes_c"] == [0, 0], r["codes_c"]
    for f in r["fields_c"]:
        assert f["resumed"] == "1", f  # restored, not refit from scratch
    assert (r["w_a"] == r["w_c"]).all(), (
        f"resumed weights diverge: max delta "
        f"{np.abs(r['w_a'] - r['w_c']).max()}")
    # the snapshot cleared after the successful finalize
    assert not os.path.exists(os.path.join(r["ckdir"], "stream_fit.ckpt"))


def test_distributed_path_fence_clean(elastic_runs):
    """Acceptance: the PR 9 warmup fence is clean on the distributed
    path — fresh AND resumed runs compile only in round 1."""
    for f in elastic_runs["fields_a"] + elastic_runs["fields_c"]:
        assert f["unexpected_compiles"] == "0", f


def test_world_weights_replicated_and_ledger_live(elastic_runs):
    """Every host finalizes the same merged carry (identical weight
    digests — asserted in-worker via an allgather, reported here), and
    the conditioning ledger saw the finalize solve on each host."""
    for fields in (elastic_runs["fields_a"], elastic_runs["fields_c"]):
        assert fields[0]["digest"] == fields[1]["digest"]
        for f in fields:
            assert int(f["solves"]) >= 1


def test_one_vs_two_process_weight_parity(elastic_runs):
    """Acceptance: 1-vs-2-process streamed-fit weight parity (the
    cross-host Gram tree-reduce changes only the f32 summation order)
    <= 1e-5 with identical prediction argmax."""
    X, Y = elastic_runs["X"], elastic_runs["Y"]
    m1 = fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=CHUNK, tag="p1"), Y)
    w1 = np.asarray(m1.weights)
    w2 = elastic_runs["w_a"]
    rel = np.abs(w1 - w2).max() / max(np.abs(w1).max(), 1.0)
    assert rel <= 1e-5, f"1-vs-2 process weight delta {rel}"
    np.testing.assert_array_equal(
        np.argmax(X @ w1, axis=1), np.argmax(X @ w2, axis=1))


def test_scaling_metric_emitted(elastic_runs):
    """The harness emits the images/sec metric line MULTICHIP_r06+
    records (benchdiff-parseable JSON)."""
    import json

    lines = [json.loads(l) for l in elastic_runs["bench_a"]]
    metrics = [l for l in lines
               if l.get("metric") == "elastic_streamed_images_per_sec"]
    assert metrics and metrics[0]["value"] > 0
    assert metrics[0]["processes"] == 2


def test_two_process_sharded_apply_parity(tmp_path):
    """ISSUE 18 tentpole b: ``sharded_apply`` over the WORLD mesh —
    weights row-sharded across both hosts, batches entering as
    host-local rows through the real ``host_local_array_to_global_array``
    path — matches the single-host ``model.apply`` <= 1e-5 with
    identical argmax, across buckets including ragged tails (asserted
    in-worker, see ``spmd_apply_worker.py``)."""
    worker = os.path.join(os.path.dirname(__file__),
                          "spmd_apply_worker.py")
    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=str(tmp_path), grace_s=20)
    codes = world.launch([sys.executable, worker]).wait(timeout_s=300)
    for p in range(2):
        assert codes[p] == 0, (p, codes, world.output(p)[-2000:])
        assert f"SPMD_APPLY_OK pid={p}" in world.output(p)


# -- world-size / checkpoint-format semantics (in-process) -------------------

def _world_snapshot(ckdir, fingerprints, cursors, carries):
    ckpt = StreamCheckpoint(str(ckdir))
    for pid, (fp, cur, carry) in enumerate(
            zip(fingerprints, cursors, carries)):
        ckpt.save_host(fp, pid, cur, carry)
    ckpt.merge_hosts(len(fingerprints))
    return ckpt


def test_single_process_resume_of_world_snapshot_refuses(tmp_path):
    """Acceptance: a resume at a different world size raises
    CheckpointMismatchError — here the single-process direction,
    through the real fit_streaming resume path."""
    X, Y = _xy(n=96)
    stream = StreamingDataset.from_numpy(X, chunk_size=CHUNK, tag="ws")
    fp = fit_fingerprint(LinearMapEstimator(lam=0.1), stream, Y)
    carry = (np.zeros((D, D), np.float32), np.zeros((D, K), np.float32),
             np.zeros((D,), np.float32), np.zeros((K,), np.float32), 0)
    _world_snapshot(tmp_path, [fp, fp], [2, 2], [carry, carry])
    with pytest.raises(CheckpointMismatchError, match="2-process world"):
        fit_streaming(LinearMapEstimator(lam=0.1), stream, Y,
                      checkpoint_dir=str(tmp_path), checkpoint_every=1)


def test_world_resume_of_single_snapshot_refuses(tmp_path):
    ckpt = StreamCheckpoint(str(tmp_path))
    ckpt.save("fp0", 3, (np.zeros(4, np.float32),))
    with pytest.raises(CheckpointMismatchError,
                       match="single-process fit"):
        ckpt.load_world("fp0", process_id=0, processes=2)


def test_world_resume_at_wrong_world_size_refuses(tmp_path):
    carry = (np.ones(4, np.float32),)
    _world_snapshot(tmp_path, ["fp", "fp"], [1, 1], [carry, carry])
    ckpt = StreamCheckpoint(str(tmp_path))
    with pytest.raises(CheckpointMismatchError, match="2-process world"):
        ckpt.load_world("fp", process_id=0, processes=4)


def test_world_snapshot_roundtrip_and_clear(tmp_path):
    """Per-host slices restore exactly (cursor, carry, per-host
    fingerprint checked), and clear() removes the sidecars too."""
    carries = [(np.arange(4, dtype=np.float32),),
               (np.arange(4, 8, dtype=np.float32),)]
    ckpt = _world_snapshot(tmp_path, ["fpA", "fpB"], [3, 5], carries)
    h0 = ckpt.load_world("fpA", process_id=0, processes=2)
    h1 = ckpt.load_world("fpB", process_id=1, processes=2)
    assert h0["cursor"] == 3 and h1["cursor"] == 5
    np.testing.assert_array_equal(h0["carry"][0], carries[0][0])
    np.testing.assert_array_equal(h1["carry"][0], carries[1][0])
    with pytest.raises(CheckpointMismatchError, match="fingerprint"):
        ckpt.load_world("fpA", process_id=1, processes=2)
    ckpt.clear()
    assert os.listdir(str(tmp_path)) == []


def test_fit_fingerprint_folds_topology(monkeypatch):
    """The fingerprint changes with the world size (so even without
    the explicit topology check, a wrong-size resume mismatches)."""
    import keystone_tpu.parallel.distributed as dist

    X, Y = _xy(n=96)
    stream = StreamingDataset.from_numpy(X, chunk_size=CHUNK, tag="fp")
    est = LinearMapEstimator(lam=0.1)
    fp1 = fit_fingerprint(est, stream, Y)
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    fp2 = fit_fingerprint(est, stream, Y)
    assert fp1 != fp2


# -- host-level fault kinds (in-process semantics) ---------------------------

def test_host_death_gated_to_other_process_is_dormant():
    """A host_death rule aimed at another process index never fires —
    the SPMD contract: every host installs the same plan, the gate
    picks the victim (process_index is 0 here, the rule aims at 1)."""
    X, Y = _xy(n=96)
    plan = FaultPlan().add("ingest.produce", kind="host_death",
                           after=0, count=1, process_id=1)
    with plan:
        model = fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=CHUNK), Y)
    assert plan.injections() == 0
    assert np.isfinite(np.asarray(model.weights)).all()


def test_partition_kind_raises_connection_error():
    plan = FaultPlan().add("ingest.stage", kind="partition", count=1,
                           process_id=0)
    from keystone_tpu.resilience.faults import inject

    with plan:
        with pytest.raises(PartitionError):
            inject("ingest.stage", context="t")
    assert isinstance(PartitionError("x"), ConnectionError)


def test_straggler_kind_delays_but_completes():
    import time

    plan = FaultPlan().add("ingest.produce", kind="straggler", count=2,
                           delay_s=0.15)
    X, Y = _xy(n=96)
    t0 = time.perf_counter()
    with plan:
        fit_streaming(LinearMapEstimator(lam=0.1),
                      StreamingDataset.from_numpy(X, chunk_size=CHUNK), Y)
    assert time.perf_counter() - t0 >= 0.3  # both delays served
    assert plan.injections() == 2


# -- chaos soak (satellite): bounded seeded sweep ----------------------------

def _soak_plan(seed):
    """A seeded random plan over the ingest sites: retryable errors,
    partitions, value corruption at the staging site; latency /
    straggler / bounded hangs in the producer loop. host_death is
    deliberately aimed at process 1 — dormant in-process (tier-1 runs
    single-process), LIVE in the dryrun worlds that reuse this shape."""
    rng = np.random.RandomState(1000 + seed)
    plan = FaultPlan(seed=seed)
    stage_kinds = ("error", "corrupt", "partition")
    produce_kinds = ("latency", "straggler", "hang")
    for _ in range(1 + rng.randint(3)):
        if rng.rand() < 0.5:
            plan.add("ingest.stage",
                     kind=stage_kinds[rng.randint(len(stage_kinds))],
                     rate=float(0.3 + 0.5 * rng.rand()),
                     after=int(rng.randint(3)),
                     count=int(1 + rng.randint(3)))
        else:
            plan.add("ingest.produce",
                     kind=produce_kinds[rng.randint(len(produce_kinds))],
                     rate=float(0.3 + 0.5 * rng.rand()),
                     after=int(rng.randint(3)),
                     count=int(1 + rng.randint(2)), delay_s=0.1)
    plan.add("coord.step", kind="host_death", process_id=1, count=1)
    # the overlap window (ISSUE 18): a second host_death aimed at the
    # AWAIT point — between a round's dispatch and its await, when the
    # allgather and the lagged carry snapshot are both in flight. Same
    # process gate: dormant here, live in the dryrun worlds.
    plan.add("coord.await", kind="host_death", process_id=1, count=1)
    return plan


@pytest.mark.parametrize("seed", range(6))
def test_chaos_soak_bounded_outcomes(tmp_path, seed):
    """Satellite: every seed ends in a clean finish, a CLASSIFIED
    failure (retry exhaustion / ingest timeout / numerics tripwire —
    each of which leaves a resumable checkpoint), and the follow-up
    fit converges to the fault-free weights bit for bit. Any other
    exception, any hang (the producer watchdog is armed), or any
    silent truncation fails the test."""
    from keystone_tpu.observability.numerics import NumericsError

    X, Y = _xy(n=128, d=8, seed=seed)

    def stream():
        return StreamingDataset.from_numpy(
            X, chunk_size=32, tag=f"soak{seed}", stall_timeout_s=15.0)

    clean = np.asarray(fit_streaming(
        LinearMapEstimator(lam=0.1), stream(), Y).weights)
    ckdir = str(tmp_path / "ck")
    outcome = "clean"
    try:
        with _soak_plan(seed):
            fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y,
                          checkpoint_dir=ckdir, checkpoint_every=1)
    except (RetryExhaustedError, IngestTimeoutError, NumericsError):
        outcome = "failed-classified"
    # clean finish cleared the snapshot (fresh refit); a classified
    # failure left a resumable one — either way the follow-up run must
    # land on the fault-free weights exactly
    resumed = np.asarray(fit_streaming(
        LinearMapEstimator(lam=0.1), stream(), Y,
        checkpoint_dir=ckdir, checkpoint_every=1).weights)
    assert (resumed == clean).all(), (
        f"seed {seed} ({outcome}): weights diverged by "
        f"{np.abs(resumed - clean).max()}")


# -- shard-local ingest + analysis flag --------------------------------------

def test_sharded_spec_flag_and_lint_message():
    """stream_tar_shards marks its stream process-sharded; the spec
    carries the flag (repr included) and the non-streamable-fit lint
    names the shard-local provenance instead of suggesting a
    materialize() of one host's fraction."""
    import jax

    from keystone_tpu.analysis.diagnostics import check_graph
    from keystone_tpu.analysis.spec import dataset_spec
    from keystone_tpu.nodes.learning.pca import ColumnPCAEstimator

    X, _ = _xy(n=80)
    stream = StreamingDataset.from_numpy(X, chunk_size=40)
    stream.process_sharded = True
    spec = dataset_spec(stream)
    assert spec.sharded and "sharded" in repr(spec)
    # derived views keep the provenance
    assert dataset_spec(stream.map_chunks(lambda ad: ad)).sharded
    p = ColumnPCAEstimator(4).with_data(stream)
    rep = check_graph(
        p._graph, {p._source: jax.ShapeDtypeStruct((D,), np.float32)},
        name="sharded-stream")
    hits = [d for d in rep.diagnostics if d.code == "non-streamable-fit"]
    assert len(hits) == 1
    assert "shard-local" in hits[0].message
    assert "CLUSTER.md" in hits[0].message


def _make_image_tars(tar_dir, shards=2, per_shard=12, side=8, seed=0):
    import io
    import tarfile

    from PIL import Image as PILImage

    rng = np.random.RandomState(seed)
    os.makedirs(tar_dir, exist_ok=True)
    imgs = []
    for t in range(shards):
        with tarfile.open(os.path.join(tar_dir, f"shard{t}.tar"),
                          "w") as tf:
            for i in range(per_shard):
                arr = (rng.rand(side, side, 3) * 255).astype(np.uint8)
                imgs.append(arr)
                buf = io.BytesIO()
                PILImage.fromarray(arr).save(buf, format="PNG")
                info = tarfile.TarInfo(f"img{t}_{i:02d}.png")
                info.size = buf.getbuffer().nbytes
                buf.seek(0)
                tf.addfile(info, buf)
    return imgs


def test_shard_local_tar_ingest_two_hosts(tmp_path):
    """Sharded streaming ingest over a real 2-process world: each host
    decodes ONLY its process-strided tar shard, the moment carries
    tree-reduce at finalize, and the merged scaler equals the resident
    computation over ALL images."""
    tar_dir = str(tmp_path / "tars")
    imgs = _make_image_tars(tar_dir)
    out = str(tmp_path / "scaler.npz")
    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=str(tmp_path), grace_s=20)
    world.launch([sys.executable, "-m",
                  "keystone_tpu.parallel.dryrun_worker",
                  "--tar-dir", tar_dir, "--chunk-size", "8",
                  "--out", out])
    codes = world.wait(timeout_s=300)
    assert codes == [0, 0], [world.output(p)[-1500:] for p in range(2)]
    fields = [_ok_fields(world, p) for p in range(2)]
    # shard-locality: host 0 touched only shard0, host 1 only shard1
    assert fields[0]["archives"] == "shard0.tar"
    assert fields[1]["archives"] == "shard1.tar"
    assert fields[0]["digest"] == fields[1]["digest"]
    for f in fields:
        assert f["unexpected_compiles"] == "0"
    flat = np.stack(imgs).reshape(len(imgs), -1).astype(np.float32)
    got = np.load(out)["weights"]
    mean, std = got[:flat.shape[1]], got[flat.shape[1]:]
    assert np.abs(mean - flat.mean(0)).max() <= 1e-4
    assert np.abs(std - flat.std(0, ddof=1)).max() <= 1e-3


@pytest.mark.slow
def test_straggler_world_completes_with_parity(tmp_path):
    """Host-level chaos in the dryrun harness: a straggling host 0 plus
    the coordination barriers — the world completes with replicated
    weights (the straggler just makes everyone wait)."""
    X, Y = _xy()
    npz = str(tmp_path / "data.npz")
    np.savez(npz, X=X, Y=Y)
    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=str(tmp_path), grace_s=25)
    world.launch(_worker_argv(npz, ["--straggle-process", "0"]))
    codes = world.wait(timeout_s=300)
    assert codes == [0, 0], [world.output(p)[-1500:] for p in range(2)]
    fields = [_ok_fields(world, p) for p in range(2)]
    assert fields[0]["digest"] == fields[1]["digest"]


@pytest.mark.slow
def test_partitioned_world_relaunches_and_resumes(tmp_path):
    """A network partition at a coordination round kills the step (the
    injected PartitionError crashes host 1); the relaunched world
    resumes from the coordinated snapshot — same recovery story as
    host death, different failure mode."""
    X, Y = _xy()
    npz = str(tmp_path / "data.npz")
    np.savez(npz, X=X, Y=Y)
    ckdir = str(tmp_path / "ck")
    out_a = str(tmp_path / "a.npz")
    out_c = str(tmp_path / "c.npz")
    world = DryrunWorld(num_processes=2, devices_per_process=2,
                        workdir=str(tmp_path), grace_s=20)
    world.launch(_worker_argv(npz, ["--out", out_a]))
    assert world.wait(300) == [0, 0]
    world.launch(_worker_argv(npz, [
        "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--partition-process", "1", "--partition-at-round", "2"]))
    codes = world.wait(300)
    assert codes[1] not in (0, HOST_DEATH_EXIT_CODE), codes
    assert os.path.exists(os.path.join(ckdir, "stream_fit.ckpt"))
    world.launch(_worker_argv(npz, [
        "--checkpoint-dir", ckdir, "--checkpoint-every", "1",
        "--out", out_c]))
    assert world.wait(300) == [0, 0]
    fields = [_ok_fields(world, p) for p in range(2)]
    assert all(f["resumed"] == "1" for f in fields)
    assert (np.load(out_a)["weights"] == np.load(out_c)["weights"]).all()


# -- the divergent-collective hazard, reproduced for real --------------------

@pytest.mark.slow
def test_divergent_collective_deadlocks_and_is_reaped(tmp_path):
    """ISSUE 12 satellite: the hazard class the `collective-divergence`
    pass (analysis/spmd.py) flags statically — a barrier under an
    `if process_index() == 0:` branch — reproduced dynamically: the
    deliberately divergent worker (tests/spmd_divergent_worker.py,
    flagged by tests/test_spmd_passes.py) enters a collective its peer
    never matches. The divergent host makes NO progress and raises NO
    error (the silent gang-schedule hang); the peer finishes, exits 0,
    and the DryrunWorld launcher's gang grace reaps the wedged
    member."""
    worker = os.path.join(os.path.dirname(__file__),
                          "spmd_divergent_worker.py")
    world = DryrunWorld(num_processes=2, devices_per_process=1,
                        workdir=str(tmp_path), grace_s=8)
    world.launch([sys.executable, worker])
    codes = world.wait(timeout_s=180)
    # the straight host completed the matched barrier and exited clean
    assert codes[1] == 0, world.output(1)[-1500:]
    assert "DIVERGE_DONE pid=1" in world.output(1)
    # the divergent host entered the world (the matched barrier), then
    # wedged in the host-0-only collective: never printed its done
    # line, never errored on its own — it was killed by gang grace
    out0 = world.output(0)
    assert "DIVERGE_ENTER pid=0" in out0, out0[-1500:]
    assert "DIVERGE_DONE pid=0" not in out0, (
        "the divergent host was expected to wedge in the unmatched "
        "collective, but it completed — the hazard did not reproduce")
    assert codes[0] != 0, codes
