"""Launch-layer tests: CLI multi-host wiring + launch scripts.

Covers the cluster launch story (reference ``bin/run-pipeline.sh:6-56``,
``bin/keystone-ec2.sh``, ``EC2.md:17-31``) — here ``bin/run-pipeline.sh``,
``bin/keystone-tpu-pod.sh``, and the ``python -m keystone_tpu``
``--coordinator/--num-processes/--process-id`` flags documented in
CLUSTER.md.
"""
import os
import subprocess

import pytest

import keystone_tpu.__main__ as cli
from keystone_tpu.parallel import mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cli_lists_apps(capsys):
    assert cli.main([]) == 0
    out = capsys.readouterr().out
    for app in ("cifar.random_patch", "imagenet.sift_lcs_fv",
                "nlp.stupid_backoff"):
        assert app in out


def test_cli_unknown_app():
    assert cli.main(["no.such.app"]) == 2


def test_cli_distributed_flags_routed(monkeypatch):
    """--coordinator/--num-processes/--process-id are stripped from app
    argv and forwarded to initialize_distributed."""
    seen = {}
    monkeypatch.setattr(
        "keystone_tpu.parallel.mesh.initialize_distributed",
        lambda **kw: seen.update(kw))
    ran = {}

    class FakeModule:
        @staticmethod
        def main(rest):
            ran["rest"] = rest

    monkeypatch.setattr("importlib.import_module",
                        lambda name: FakeModule)
    rc = cli.main(["cifar.random_patch", "--coordinator", "h0:1234",
                   "--num-processes", "4", "--process-id", "2",
                   "--num-filters", "8"])
    assert rc == 0
    assert seen == {"coordinator_address": "h0:1234",
                    "num_processes": 4, "process_id": 2}
    assert ran["rest"] == ["--num-filters", "8"]


def test_initialize_distributed_noop_when_initialized(monkeypatch):
    """Second call must not re-initialize (idempotent per-process)."""
    import jax

    calls = []
    monkeypatch.setattr(jax.distributed, "is_initialized",
                        lambda: True, raising=False)
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda *a, **k: calls.append(1))
    mesh.initialize_distributed()
    assert calls == []


def test_mesh_model_env(monkeypatch):
    """KEYSTONE_MESH_MODEL sizes the model axis of the default mesh."""
    monkeypatch.setenv("KEYSTONE_MESH_MODEL", "2")
    mesh.set_mesh(None)
    try:
        m = mesh.get_mesh()
        assert m.shape["model"] == 2
        assert m.shape["data"] * 2 == len(jax.devices())
    finally:
        mesh.set_mesh(None)


import jax  # noqa: E402  (used above after monkeypatching)


@pytest.mark.parametrize("script", ["run-pipeline.sh", "keystone-tpu-pod.sh"])
def test_launch_scripts_parse(script):
    """bash -n: the launch scripts are syntactically valid."""
    path = os.path.join(REPO, "bin", script)
    assert os.path.exists(path)
    subprocess.run(["bash", "-n", path], check=True)


def test_pod_script_usage_without_args():
    """No args → usage text, nonzero exit, and NO gcloud invocation."""
    path = os.path.join(REPO, "bin", "keystone-tpu-pod.sh")
    r = subprocess.run(["bash", path], capture_output=True, text=True)
    assert r.returncode != 0
    assert "create" in r.stdout


def test_run_pipeline_script_lists_apps():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(["bash", os.path.join(REPO, "bin", "run-pipeline.sh")],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0
    assert "cifar.random_patch" in r.stdout


def test_cli_distributed_flag_missing_value():
    assert cli.main(["cifar.random_patch", "--coordinator"]) == 2


def test_cli_partial_distributed_flags_rejected():
    assert cli.main(["cifar.random_patch", "--num-processes", "4"]) == 2
