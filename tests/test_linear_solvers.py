"""Linear model node tests (mirrors BlockLinearMapperSuite /
LinearMapperSuite)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.parallel.dataset import ArrayDataset


def make_problem(n=200, d=24, k=3, seed=0):
    rng = np.random.RandomState(seed)
    A = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    Y = (A @ W + b + 0.01 * rng.randn(n, k)).astype(np.float32)
    return A, Y


def centered_ridge(A, Y, lam):
    Am, Ym = A.mean(0), Y.mean(0)
    Ac = (A - Am).astype(np.float64)
    Yc = (Y - Ym).astype(np.float64)
    W = np.linalg.solve(Ac.T @ Ac + lam * np.eye(A.shape[1]), Ac.T @ Yc)
    return W, Am, Ym


def test_linear_map_estimator_matches_centered_ridge():
    A, Y = make_problem()
    model = LinearMapEstimator(lam=0.5).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.5)
    np.testing.assert_allclose(model.weights, W, rtol=2e-3, atol=2e-3)
    out = model(A).numpy()
    expect = (A - Am) @ W + Ym
    np.testing.assert_allclose(out, expect, rtol=2e-3, atol=2e-3)


def test_block_least_squares_single_block_matches_ridge():
    A, Y = make_problem()
    model = BlockLeastSquaresEstimator(block_size=64, num_iter=1, lam=0.3).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.3)
    np.testing.assert_allclose(model.weights, W, rtol=5e-3, atol=5e-3)


def test_block_least_squares_multi_block_converges():
    """Block solver approaches the exact joint solve with iterations
    (reference BlockLinearMapperSuite:17-55)."""
    A, Y = make_problem(n=400, d=30, k=2, seed=3)
    lam = 0.4
    model = BlockLeastSquaresEstimator(block_size=10, num_iter=25, lam=lam).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, lam)
    np.testing.assert_allclose(model.weights, W, rtol=3e-2, atol=3e-2)
    out = model(A).numpy()
    expect = (A - Am) @ W + Ym
    np.testing.assert_allclose(out, expect, rtol=5e-2, atol=5e-2)


def test_block_linear_mapper_apply_blocks_equivalent():
    rng = np.random.RandomState(0)
    blocks = [rng.randn(8, 3).astype(np.float32) for _ in range(3)]
    x = rng.randn(5, 24).astype(np.float32)
    mapper = BlockLinearMapper(blocks, 8)
    out = mapper(x).numpy()
    expect = x @ np.concatenate(blocks, 0)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)


def test_weight_property():
    est = BlockLeastSquaresEstimator(block_size=10, num_iter=4, lam=0)
    assert est.weight == 13  # 3*numIter+1, BlockLinearMapper.scala:204


def test_padding_does_not_corrupt_solve():
    # n=101 deliberately not divisible by 8
    A, Y = make_problem(n=101, d=16, k=2, seed=5)
    model = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.2).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.2)
    np.testing.assert_allclose(model.weights, W, rtol=5e-3, atol=5e-3)


def test_linear_compute_cost_matches_numpy():
    """LinearMapEstimator.computeCost (reference LinearMapper.scala:124-161):
    objective = ||AW + b - Y||^2/(2n) + lam/2 ||W||^2."""
    A, Y = make_problem(n=120, d=10, k=3, seed=5)
    rng = np.random.RandomState(6)
    W = rng.randn(10, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    lam = 0.7
    got = LinearMapEstimator.compute_cost(A, Y, lam, W, b)
    want = (np.linalg.norm(A @ W + b - Y) ** 2) / (2 * A.shape[0]) + (
        lam / 2
    ) * np.sum(W**2)
    np.testing.assert_allclose(got, want, rtol=1e-4)
    # lam=0 branch and no intercept
    got0 = LinearMapEstimator.compute_cost(A, Y, 0.0, W, None)
    want0 = (np.linalg.norm(A @ W - Y) ** 2) / (2 * A.shape[0])
    np.testing.assert_allclose(got0, want0, rtol=1e-4)


def test_block_compute_cost_matches_numpy():
    """BlockLeastSquaresEstimator.computeCost (BlockLinearMapper.scala:144-187)."""
    A, Y = make_problem(n=100, d=12, k=2, seed=7)
    rng = np.random.RandomState(8)
    bounds = [(0, 5), (5, 10), (10, 12)]
    Ws = [rng.randn(hi - lo, 2).astype(np.float32) for lo, hi in bounds]
    b = rng.randn(2).astype(np.float32)
    lam = 0.3
    blocks = [A[:, lo:hi] for lo, hi in bounds]
    got = BlockLeastSquaresEstimator.compute_cost(blocks, Y, lam, Ws, b)
    pred = sum(blk @ w for blk, w in zip(blocks, Ws)) + b
    want = (np.linalg.norm(pred - Y) ** 2) / (2 * A.shape[0]) + (lam / 2) * sum(
        np.sum(w**2) for w in Ws
    )
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_apply_and_evaluate_incremental(mesh8):
    """BlockLinearMapper.applyAndEvaluate (BlockLinearMapper.scala:105-142):
    evaluator sees the cumulative per-block predictions; the last call
    equals full apply()."""
    A, Y = make_problem(n=96, d=12, k=3, seed=9)
    mapper = BlockLeastSquaresEstimator(block_size=4, num_iter=4, lam=0.1).fit(
        A, Y
    )
    bounds = mapper._block_bounds()
    blocks = [A[:, lo:hi] for lo, hi in bounds]

    seen = []
    mapper.apply_and_evaluate(blocks, lambda ds: seen.append(ds.numpy()))
    assert len(seen) == len(mapper.block_weights)

    # incremental partials match the cumulative numpy sums (+ intercept)
    partial = np.zeros((A.shape[0], 3), np.float64)
    for i, ((lo, hi), w) in enumerate(zip(bounds, mapper.block_weights)):
        x = blocks[i]
        if mapper.feature_means is not None:
            x = x - mapper.feature_means[lo:hi]
        partial = partial + x.astype(np.float64) @ np.asarray(w, np.float64)
        want = partial + (0 if mapper.intercept is None else mapper.intercept)
        np.testing.assert_allclose(seen[i], want, rtol=2e-3, atol=2e-3)

    # final evaluation == full apply
    np.testing.assert_allclose(seen[-1], mapper(A).numpy(), rtol=2e-3, atol=2e-3)


def test_apply_and_evaluate_pad_rows_stay_zero(mesh8):
    """Pad rows of the emitted datasets must honor ArrayDataset's zero-pad
    invariant even though centering/intercept would otherwise fill them."""
    from keystone_tpu.parallel.dataset import ArrayDataset

    A, Y = make_problem(n=101, d=8, k=2, seed=11)  # 101 % 8 != 0 -> padding
    mapper = BlockLeastSquaresEstimator(block_size=4, num_iter=2, lam=0.1).fit(
        A, Y
    )
    blocks = [
        ArrayDataset.from_numpy(A[:, lo:hi]) for lo, hi in mapper._block_bounds()
    ]
    outs = []
    mapper.apply_and_evaluate(blocks, lambda ds: outs.append(ds))
    for ds in outs:
        data = np.asarray(ds.data)
        assert data.shape[0] > ds.n  # padding actually present
        np.testing.assert_array_equal(data[ds.n:], 0.0)


def test_block_least_squares_staged_core_matches_estimator(mesh8):
    """The public staged core (block_least_squares, what bench.py jits
    into its end-to-end program) must produce exactly the model the
    estimator's _fit path returns, including means and intercept."""
    import jax.numpy as jnp

    from keystone_tpu.nodes.learning.linear import block_least_squares

    A, Y = make_problem(n=160, d=24, k=3, seed=5)
    bounds = tuple((i, min(24, i + 8)) for i in range(0, 24, 8))

    model = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=0.3).fit(
        A, Y)
    Ws, x_mean, y_mean = block_least_squares(
        jnp.asarray(A), jnp.asarray(Y), 160, 0.3, bounds, 2)

    np.testing.assert_allclose(
        np.asarray(model.weights),
        np.concatenate([np.asarray(w) for w in Ws], axis=0),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(model.feature_means), np.asarray(x_mean),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(model.intercept), np.asarray(y_mean),
        rtol=1e-5, atol=1e-5)
    # prediction identity: (x - x_mean) @ W + y_mean == model.apply(x)
    pred = (A - np.asarray(x_mean)) @ np.concatenate(
        [np.asarray(w) for w in Ws], axis=0) + np.asarray(y_mean)
    np.testing.assert_allclose(
        np.asarray(model(A).numpy()), pred, rtol=1e-4, atol=1e-4)


def test_fitted_mapper_eq_key_is_device_cheap():
    """eq_key must not serialize the full weight matrix (that is a full
    d2h of a fitted model during fusion/CSE); equal models compare
    equal, different models differ."""
    A, Y = make_problem(seed=7)
    m1 = LinearMapEstimator(lam=0.5).fit(A, Y)
    m2 = LinearMapEstimator(lam=0.5).fit(A, Y)
    m3 = LinearMapEstimator(lam=5.0).fit(A, Y)
    assert m1.eq_key() == m2.eq_key()
    assert m1.eq_key() != m3.eq_key()

    # the key may carry small host vectors (scaler means) but never the
    # weight-matrix payload
    def payload(t):
        for x in t:
            if isinstance(x, tuple):
                yield from payload(x)
            elif isinstance(x, bytes):
                yield len(x)
            elif isinstance(x, np.ndarray):
                yield x.nbytes
    assert sum(payload(m1.eq_key())) < m1.weights.size * 4

    b1 = BlockLeastSquaresEstimator(block_size=8, num_iter=1, lam=0.2).fit(A, Y)
    b2 = BlockLeastSquaresEstimator(block_size=8, num_iter=1, lam=0.2).fit(A, Y)
    assert b1.eq_key() == b2.eq_key()
    assert sum(payload(b1.eq_key())) < np.asarray(b1.weights).size * 4


def test_nan_weights_token_is_cache_stable(caplog):
    """A fitted model with non-finite weights must still equal an
    identically-valued copy (NaN != NaN would make models unequal to
    themselves, silently defeating CSE/fusion/jit caches), and the
    non-finite solve must be loudly flagged."""
    import logging

    from keystone_tpu.nodes.learning.linear import BlockLinearMapper

    W = np.full((4, 3), np.nan, np.float32)
    with caplog.at_level(logging.WARNING):
        a = BlockLinearMapper([W], 4)
        b = BlockLinearMapper([W.copy()], 4)
        assert a.eq_key() == b.eq_key()
        assert hash(a) == hash(b)
    assert any("non-finite" in r.message for r in caplog.records)


def test_nan_token_distinguishes_different_broken_models():
    """Two NaN-containing models with different finite content must NOT
    collapse to one eq_key (a cache substituting one broken model for
    another would serve wrong predictions with no error)."""
    from keystone_tpu.nodes.learning.linear import BlockLinearMapper

    Wa = np.arange(12, dtype=np.float32).reshape(4, 3)
    Wb = Wa * 2.0
    Wa[0, 0] = np.nan
    Wb[0, 0] = np.nan
    a = BlockLinearMapper([Wa], 4)
    b = BlockLinearMapper([Wb], 4)
    assert a.eq_key() != b.eq_key()


def test_cholesky_breakdown_recovers_finite_solution(mesh8):
    """kappa >> 1/eps_f32 with tiny lambda NaNs the f32 Cholesky; the
    eigh-clamped fallback must recover finite weights whose predictions
    beat chance (the reference's f64 solver survived this regime; a
    silent all-NaN model predicts one constant class)."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicatorsFromIntLabels
    from keystone_tpu.parallel.dataset import ArrayDataset

    rng = np.random.RandomState(0)
    n, d, k = 128, 512, 10
    # huge-scale rank-deficient features: Gram kappa ~ 1e10 at lam 1e-2
    y = rng.randint(0, k, n)
    protos = rng.randn(k, d).astype(np.float32) * 300.0
    X = (protos[y] + 30.0 * rng.randn(n, d)).astype(np.float32)
    ds = ArrayDataset.from_numpy(X)
    labels = ClassLabelIndicatorsFromIntLabels(k)(
        ArrayDataset.from_numpy(y.astype(np.int32)))
    # prove this fixture genuinely breaks the plain f32 Cholesky (so a
    # pass below means the fallback produced the weights)
    from keystone_tpu.ops import linalg as L
    import jax.numpy as jnp
    import jax.scipy.linalg as jsl

    Xc = X - X.mean(0)
    G = jnp.asarray(np.asarray(L.gram(jnp.asarray(Xc)))
                    + 1e-2 * np.eye(d, dtype=np.float32))
    plain = np.asarray(jsl.cho_solve(
        jsl.cho_factor(G, lower=True),
        jnp.ones((d, k), jnp.float32)))
    assert not np.all(np.isfinite(plain)), "fixture no longer breaks down"

    model = BlockLeastSquaresEstimator(d, 1, 1e-2).fit(ds, labels)
    W = np.asarray(model.weights)
    assert np.all(np.isfinite(W))
    preds = np.asarray(model.apply_dataset(ds).numpy()).argmax(axis=1)
    assert (preds == y).mean() > 0.5  # far above the 0.1 chance floor


def test_finite_or_eigh_fallback_fires_directly():
    """Direct unit pin of the fallback branch: a NaN primary result must
    yield the eigh-clamped solution, and a finite one must pass through
    untouched."""
    import jax.numpy as jnp

    from keystone_tpu.ops.linalg import _finite_or_eigh_solve

    rng = np.random.RandomState(0)
    d, k = 16, 3
    M = rng.randn(d, d).astype(np.float32)
    reg = M @ M.T + 0.5 * np.eye(d, dtype=np.float32)  # well-conditioned
    rhs = rng.randn(d, k).astype(np.float32)
    expect = np.linalg.solve(reg, rhs)

    bad = jnp.full((d, k), np.nan, jnp.float32)
    out = np.asarray(_finite_or_eigh_solve(
        bad, lambda: jnp.asarray(reg), jnp.asarray(rhs)))
    np.testing.assert_allclose(out, expect, rtol=1e-3, atol=1e-3)

    good = jnp.asarray(expect + 1.0)  # any finite array passes through
    out2 = np.asarray(_finite_or_eigh_solve(
        good, lambda: jnp.asarray(reg), jnp.asarray(rhs)))
    np.testing.assert_array_equal(out2, np.asarray(good))


def test_block_least_squares_mesh_switch():
    """Regression (the MULTICHIP_r06 weighted-solver phase failure):
    ``_block_solve`` was one module-lifetime jit, and ``bcd_core``
    reads the ambient mesh through ``_class_spec`` — so the first
    mesh's class-sharding constraints baked into the cached trace and
    replayed against a second mesh's arguments at the same shapes
    ("incompatible devices: argument ... device ids [0] ...
    sharding_constraint ... [0..7]"). The per-mesh
    ``_block_solve_for`` factory keys the trace cache by mesh: an
    8-device ('data' x 'model') fit followed by a 1-device fit at
    IDENTICAL shapes must both run, and agree to f32 rounding (the
    dryrun_multichip parity bar)."""
    import jax

    from keystone_tpu.parallel.mesh import make_mesh, mesh_scope

    A, Y = make_problem(n=64, d=16, k=2, seed=1)
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=0.2)
    devices = jax.devices()[:8]
    with mesh_scope(make_mesh(devices, data=4, model=2)):
        w_n = np.asarray(est.fit(A, Y).weights)
    with mesh_scope(make_mesh(devices[:1], data=1, model=1)):
        w_1 = np.asarray(est.fit(A, Y).weights)
    scale = max(float(np.max(np.abs(w_1))), 1e-6)
    assert float(np.max(np.abs(w_n - w_1))) / scale < 5e-3
