"""Linear model node tests (mirrors BlockLinearMapperSuite /
LinearMapperSuite)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.parallel.dataset import ArrayDataset


def make_problem(n=200, d=24, k=3, seed=0):
    rng = np.random.RandomState(seed)
    A = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    b = rng.randn(k).astype(np.float32)
    Y = (A @ W + b + 0.01 * rng.randn(n, k)).astype(np.float32)
    return A, Y


def centered_ridge(A, Y, lam):
    Am, Ym = A.mean(0), Y.mean(0)
    Ac = (A - Am).astype(np.float64)
    Yc = (Y - Ym).astype(np.float64)
    W = np.linalg.solve(Ac.T @ Ac + lam * np.eye(A.shape[1]), Ac.T @ Yc)
    return W, Am, Ym


def test_linear_map_estimator_matches_centered_ridge():
    A, Y = make_problem()
    model = LinearMapEstimator(lam=0.5).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.5)
    np.testing.assert_allclose(model.weights, W, rtol=2e-3, atol=2e-3)
    out = model(A).numpy()
    expect = (A - Am) @ W + Ym
    np.testing.assert_allclose(out, expect, rtol=2e-3, atol=2e-3)


def test_block_least_squares_single_block_matches_ridge():
    A, Y = make_problem()
    model = BlockLeastSquaresEstimator(block_size=64, num_iter=1, lam=0.3).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.3)
    np.testing.assert_allclose(model.weights, W, rtol=5e-3, atol=5e-3)


def test_block_least_squares_multi_block_converges():
    """Block solver approaches the exact joint solve with iterations
    (reference BlockLinearMapperSuite:17-55)."""
    A, Y = make_problem(n=400, d=30, k=2, seed=3)
    lam = 0.4
    model = BlockLeastSquaresEstimator(block_size=10, num_iter=25, lam=lam).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, lam)
    np.testing.assert_allclose(model.weights, W, rtol=3e-2, atol=3e-2)
    out = model(A).numpy()
    expect = (A - Am) @ W + Ym
    np.testing.assert_allclose(out, expect, rtol=5e-2, atol=5e-2)


def test_block_linear_mapper_apply_blocks_equivalent():
    rng = np.random.RandomState(0)
    blocks = [rng.randn(8, 3).astype(np.float32) for _ in range(3)]
    x = rng.randn(5, 24).astype(np.float32)
    mapper = BlockLinearMapper(blocks, 8)
    out = mapper(x).numpy()
    expect = x @ np.concatenate(blocks, 0)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)


def test_weight_property():
    est = BlockLeastSquaresEstimator(block_size=10, num_iter=4, lam=0)
    assert est.weight == 13  # 3*numIter+1, BlockLinearMapper.scala:204


def test_padding_does_not_corrupt_solve():
    # n=101 deliberately not divisible by 8
    A, Y = make_problem(n=101, d=16, k=2, seed=5)
    model = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.2).fit(A, Y)
    W, Am, Ym = centered_ridge(A, Y, 0.2)
    np.testing.assert_allclose(model.weights, W, rtol=5e-3, atol=5e-3)
