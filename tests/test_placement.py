"""Property-style suite for the fleet placement solver
(``serving/placement.py``): budget safety, QPS-monotone replication,
determinism, N=1 degradation, and loud refusal — the contract the
fleet controller and ``check --budget --replicas N`` both lean on."""
from __future__ import annotations

import numpy as np
import pytest

from keystone_tpu.serving.placement import (ModelDemand, Placement,
                                            PlacementError,
                                            plan_placement)

MiB = 1 << 20


def _demands_from_rng(rng: np.random.RandomState, n_models: int):
    """A seeded demand set: charges 1-64 MiB, half the models hot."""
    out = []
    for i in range(n_models):
        hot = rng.rand() < 0.5
        out.append(ModelDemand(
            name=f"m{i:02d}",
            charge_nbytes=float(rng.randint(1, 65)) * MiB,
            qps=float(rng.randint(10, 2000)) if hot else 0.0,
            warmup_s=float(rng.rand() * 3.0) if hot else 0.0))
    return out


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("n_replicas", [1, 2, 3, 5])
def test_never_exceeds_any_replica_budget(seed, n_replicas):
    rng = np.random.RandomState(2000 + seed)
    demands = _demands_from_rng(rng, n_models=10)
    budgets = {f"r{i}": float(rng.randint(128, 512)) * MiB
               for i in range(n_replicas)}
    try:
        placement = plan_placement(demands, budgets)
    except PlacementError:
        return  # refusal is the other legal outcome, tested below
    by_name = {d.name: d for d in demands}
    for replica, budget in budgets.items():
        charged = sum(by_name[m].charge_nbytes
                      for m in placement.models_on(replica))
        assert charged <= budget + 1e-6, (
            f"{replica} charged {charged / MiB:.1f} MiB over its "
            f"{budget / MiB:.1f} MiB budget (seed {seed})")
        assert placement.loads[replica] == pytest.approx(charged)


@pytest.mark.parametrize("seed", range(6))
def test_every_model_is_placed_exactly_or_refused(seed):
    rng = np.random.RandomState(3000 + seed)
    demands = _demands_from_rng(rng, n_models=8)
    budgets = {f"r{i}": float(rng.randint(96, 384)) * MiB
               for i in range(3)}
    try:
        placement = plan_placement(demands, budgets)
    except PlacementError as exc:
        assert exc.model is not None  # the refusal names the model
        assert any(d.name == exc.model for d in demands)
        return
    for d in demands:
        reps = placement.replicas_for(d.name)
        assert len(reps) >= 1, f"{d.name} silently dropped"
        assert len(set(reps)) == len(reps), "duplicate copies"


def test_hot_model_replication_monotone_in_qps():
    """Raising ONE model's QPS (everything else fixed) never loses it
    copies — the replication value is monotone in observed demand."""
    budgets = {f"r{i}": 256.0 * MiB for i in range(3)}
    fixed = [
        ModelDemand("anchor", 64.0 * MiB, qps=100.0, warmup_s=1.0),
        ModelDemand("cold", 32.0 * MiB, qps=0.0),
    ]
    copies_at = []
    for qps in (0.0, 50.0, 200.0, 1000.0, 5000.0):
        hot = ModelDemand("hot", 48.0 * MiB, qps=qps, warmup_s=2.0)
        placement = plan_placement(fixed + [hot], budgets)
        copies_at.append(len(placement.replicas_for("hot")))
    assert copies_at == sorted(copies_at), (
        f"replication not monotone in QPS: {copies_at}")
    assert copies_at[0] == 1, "a cold model must stay single-homed"
    assert copies_at[-1] > 1, (
        "a hot model with fleet-wide spare capacity must replicate")


def test_cold_models_never_replicate():
    budgets = {"r0": 512.0 * MiB, "r1": 512.0 * MiB}
    demands = [ModelDemand(f"m{i}", 8.0 * MiB, qps=0.0)
               for i in range(4)]
    placement = plan_placement(demands, budgets)
    for d in demands:
        assert len(placement.replicas_for(d.name)) == 1, (
            "replication must be bought with observed demand, "
            "never speculation")


@pytest.mark.parametrize("seed", range(6))
def test_deterministic_under_fixed_inputs(seed):
    rng = np.random.RandomState(4000 + seed)
    demands = _demands_from_rng(rng, n_models=9)
    budgets = {f"r{i}": float(rng.randint(128, 512)) * MiB
               for i in range(3)}
    first = plan_placement(list(demands), budgets)
    for _ in range(3):
        again = plan_placement(list(reversed(demands)), dict(budgets))
        assert again.assignments == first.assignments
        assert again.loads == first.loads


def test_degrades_to_single_replica_at_n1():
    """N=1 is exactly the single-plane admission story: every model on
    the one replica, no replication, same budget arithmetic."""
    budget = 256.0 * MiB
    demands = [
        ModelDemand("a", 64.0 * MiB, qps=900.0, warmup_s=2.0),
        ModelDemand("b", 32.0 * MiB, qps=10.0, warmup_s=0.5),
        ModelDemand("c", 16.0 * MiB),
    ]
    placement = plan_placement(demands, {"r0": budget})
    assert placement.assignments == {
        "a": ("r0",), "b": ("r0",), "c": ("r0",)}
    assert placement.loads["r0"] == pytest.approx(112.0 * MiB)


def test_refusal_names_the_model():
    demands = [ModelDemand("tiny", 4.0 * MiB),
               ModelDemand("whale", 900.0 * MiB, qps=50.0)]
    with pytest.raises(PlacementError) as err:
        plan_placement(demands, {"r0": 128.0 * MiB, "r1": 128.0 * MiB})
    assert err.value.model == "whale"
    assert "whale" in str(err.value)


def test_unbounded_budget_places_everything_without_replication():
    demands = [ModelDemand("a", 512.0 * MiB, qps=1e4, warmup_s=5.0),
               ModelDemand("b", 512.0 * MiB)]
    placement = plan_placement(demands, {"r0": None, "r1": None})
    for d in demands:
        assert len(placement.replicas_for(d.name)) == 1


def test_duplicate_names_refused():
    demands = [ModelDemand("a", MiB), ModelDemand("a", MiB)]
    with pytest.raises(ValueError):
        plan_placement(demands, {"r0": None})


def test_no_replicas_refused():
    with pytest.raises(ValueError):
        plan_placement([ModelDemand("a", MiB)], {})


def test_diff_admits_before_evicting():
    """The migration contract: capacity is briefly double-charged,
    never zero-charged — every admit step precedes every evict step."""
    have = Placement(assignments={"m": ("r0",)}, loads={"r0": 1.0})
    want = Placement(assignments={"m": ("r1",)}, loads={"r1": 1.0})
    steps = have.diff(want)
    assert steps == [("admit", "m", "r1"), ("evict", "m", "r0")]
    kinds = [k for k, _, _ in steps]
    assert kinds.index("evict") > kinds.index("admit")


def test_diff_identity_is_empty():
    rng = np.random.RandomState(7)
    demands = _demands_from_rng(rng, 6)
    budgets = {f"r{i}": 512.0 * MiB for i in range(2)}
    placement = plan_placement(demands, budgets)
    assert placement.diff(placement) == []
