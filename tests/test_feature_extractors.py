"""Feature extractor tests: SIFT statistical/structural properties, LCS
vs direct numpy computation, FisherVector vs a literal numpy port of the
reference formula (mirrors ConvolverSuite-style golden testing and the
EncEvalSuite FV check)."""
import numpy as np
import pytest

from keystone_tpu.nodes.images import (
    FisherVector,
    GMMFisherVectorEstimator,
    LCSExtractor,
    ScalaGMMFisherVectorEstimator,
    SIFTExtractor,
)
from keystone_tpu.nodes.images.fisher_vector import (
    EncEvalGMMFisherVectorEstimator,
)
from keystone_tpu.nodes.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.sift import dense_sift, sift_descriptor_count
from keystone_tpu.parallel.dataset import HostDataset


def _test_image(h=64, w=64, seed=0):
    rng = np.random.RandomState(seed)
    # smooth random image with some structure
    img = rng.rand(h, w).astype(np.float32)
    from scipy.ndimage import gaussian_filter

    return gaussian_filter(img, 2.0).astype(np.float32)


def test_sift_shape_and_count():
    img = _test_image()
    ext = SIFTExtractor(step=4, bin_size=6, num_scales=2)
    out = np.asarray(ext.apply(img))
    assert out.shape[0] == 128
    assert out.shape[1] == sift_descriptor_count(64, 64, 4, 6, 2)
    assert out.shape[1] > 0


def test_sift_range_and_nonzero():
    img = _test_image()
    out = np.asarray(dense_sift(img, step=8, bin_size=4, num_scales=1))
    assert out.min() >= 0.0 and out.max() <= 255.0
    assert np.count_nonzero(out) > 0


def test_sift_low_contrast_zeroed():
    # a constant image has zero gradients everywhere -> all descriptors 0
    img = np.full((48, 48), 0.5, np.float32)
    out = np.asarray(dense_sift(img, step=4, bin_size=4, num_scales=1))
    np.testing.assert_array_equal(out, 0.0)


def test_sift_rotation_moves_orientations():
    # rotating the image 90 degrees must permute orientation energy, not
    # destroy it: total descriptor mass is approximately preserved
    img = _test_image()
    out1 = np.asarray(dense_sift(img, step=8, bin_size=4, num_scales=1))
    out2 = np.asarray(dense_sift(np.rot90(img).copy(), step=8, bin_size=4,
                                 num_scales=1))
    assert out2.sum() == pytest.approx(out1.sum(), rel=0.15)


def test_lcs_shape_and_values():
    rng = np.random.RandomState(0)
    img = rng.rand(64, 64, 3).astype(np.float32)
    ext = LCSExtractor(stride=8, stride_start=20, sub_patch_size=6)
    out = np.asarray(ext.apply(img))
    xs = np.arange(20, 64 - 20, 8)
    assert out.shape == (96, len(xs) * len(xs))

    # check one mean value directly: keypoint (20,20), first sub-patch
    # offset start = -2*6+3-1 = -10 -> position (10, 10); box mean over
    # the window centred there (separable uniform filter, zero padded)
    from scipy.ndimage import uniform_filter

    m0 = uniform_filter(img[:, :, 0], size=6, mode="constant")
    # scipy centers even windows differently (offset by one for even
    # sizes); accept either centering convention
    got = out[0, 0]
    cands = [m0[10, 10], m0[9, 9], m0[10, 9], m0[9, 10]]
    assert min(abs(got - c) for c in cands) < 2e-3


def _np_fisher_vector(X, means, variances, weights, thr=1e-4):
    """Literal numpy port of FisherVector.scala:33-52."""
    D, n = X.shape
    k = weights.shape[0]
    # posteriors
    q = np.zeros((n, k))
    for i in range(n):
        x = X[:, i]
        llh = np.array([
            -0.5 * D * np.log(2 * np.pi)
            - 0.5 * np.sum(np.log(variances[:, j]))
            + np.log(weights[j])
            - 0.5 * np.sum((x - means[:, j]) ** 2 / variances[:, j])
            for j in range(k)
        ])
        e = np.exp(llh - llh.max())
        p = e / e.sum()
        p[p <= thr] = 0.0
        q[i] = p / p.sum()
    s0 = q.mean(axis=0)
    s1 = X @ q / n
    s2 = (X * X) @ q / n
    fv1 = (s1 - means * s0) / (np.sqrt(variances) * np.sqrt(weights))
    fv2 = (s2 - 2 * means * s1 + (means ** 2 - variances) * s0) / (
        variances * np.sqrt(2 * weights))
    return np.concatenate([fv1, fv2], axis=1)


def test_fisher_vector_matches_numpy_golden():
    rng = np.random.RandomState(3)
    D, n, k = 6, 40, 4
    means = rng.randn(D, k).astype(np.float64)
    variances = (0.5 + rng.rand(D, k)).astype(np.float64)
    weights = np.full(k, 1.0 / k)
    X = rng.randn(D, n).astype(np.float32)

    gmm = GaussianMixtureModel(means, variances, weights)
    fv = np.asarray(FisherVector(gmm).apply(X))
    golden = _np_fisher_vector(
        X.astype(np.float64), means, variances, weights)
    assert fv.shape == (D, 2 * k)
    np.testing.assert_allclose(fv, golden, rtol=2e-3, atol=2e-3)


def test_gmm_fisher_vector_estimator(mesh8):
    rng = np.random.RandomState(0)
    # two clusters of descriptor columns
    items = []
    for i in range(4):
        a = rng.randn(5, 30) * 0.1 + 2.0
        b = rng.randn(5, 30) * 0.1 - 2.0
        items.append(np.concatenate([a, b], axis=1).astype(np.float32))
    fitted = ScalaGMMFisherVectorEstimator(2).fit(HostDataset(items))
    out = np.asarray(fitted.apply(items[0]))
    assert out.shape == (5, 4)
    assert np.isfinite(out).all()


def test_gmm_fv_estimator_choice():
    est = GMMFisherVectorEstimator(64)
    choice = est.optimize(HostDataset([np.zeros((4, 4), np.float32)]), 1, 8)
    assert isinstance(choice.node, EncEvalGMMFisherVectorEstimator)
    est2 = GMMFisherVectorEstimator(16)
    choice2 = est2.optimize(HostDataset([np.zeros((4, 4), np.float32)]), 1, 8)
    assert isinstance(choice2.node, ScalaGMMFisherVectorEstimator)


def _np_hog(img, bin_size):
    """Literal numpy port of HogExtractor.scala for golden comparison."""
    H, W, C = img.shape
    nx = int(round(H / bin_size))
    ny = int(round(W / bin_size))
    uu = np.array([1.0, 0.9397, 0.7660, 0.5, 0.1736,
                   -0.1736, -0.5, -0.7660, -0.9397])
    vv = np.array([0.0, 0.3420, 0.6428, 0.8660, 0.9848,
                   0.9848, 0.8660, 0.6428, 0.3420])
    hist = np.zeros(nx * ny * 18)
    for x in range(1, nx * bin_size - 1):
        for y in range(1, ny * bin_size - 1):
            best = (-np.inf, None, None)
            for c in (2, 1, 0):
                dx = img[x + 1, y, c] - img[x - 1, y, c]
                dy = img[x, y + 1, c] - img[x, y - 1, c]
                m2 = dx * dx + dy * dy
                if m2 > best[0]:
                    best = (m2, dx, dy)
            m2, dx, dy = best
            mag = np.sqrt(m2)
            bo, bd = 0, 0.0
            for o in range(9):
                dot = uu[o] * dy + vv[o] * dx
                if dot > bd:
                    bo, bd = o, dot
                elif -dot > bd:
                    bo, bd = o + 9, -dot
            yp = (y + 0.5) / bin_size - 0.5
            xp = (x + 0.5) / bin_size - 0.5
            iyp, ixp = int(np.floor(yp)), int(np.floor(xp))
            vy0, vx0 = yp - iyp, xp - ixp
            vy1, vx1 = 1 - vy0, 1 - vx0
            for (cx, cy, w) in [(ixp, iyp, vy1 * vx1), (ixp, iyp + 1, vy0 * vx1),
                                (ixp + 1, iyp, vy1 * vx0),
                                (ixp + 1, iyp + 1, vy0 * vx0)]:
                if 0 <= cx < nx and 0 <= cy < ny:
                    hist[cx + cy * nx + bo * nx * ny] += w * mag
    norm = np.zeros(nx * ny)
    for o in range(9):
        for y in range(ny):
            for x in range(nx):
                v = hist[x + y * nx + o * nx * ny] + \
                    hist[x + y * nx + (o + 9) * nx * ny]
                norm[x + y * nx] += v * v
    nxf, nyf = max(nx - 2, 0), max(ny - 2, 0)
    feats = np.zeros((nxf * nyf, 32))
    eps = 1e-4
    for x in range(nxf):
        for y in range(nyf):
            row = y + x * nyf
            def blocksum(bx, by):
                return (norm[bx + by * nx] + norm[bx + 1 + by * nx]
                        + norm[bx + (by + 1) * nx] + norm[bx + 1 + (by + 1) * nx])
            n1 = 1 / np.sqrt(blocksum(x + 1, y + 1) + eps)
            n2 = 1 / np.sqrt(blocksum(x, y + 1) + eps)
            n3 = 1 / np.sqrt(blocksum(x + 1, y) + eps)
            n4 = 1 / np.sqrt(blocksum(x, y) + eps)
            t = np.zeros(4)
            for o in range(18):
                hv = hist[(x + 1) + (y + 1) * nx + o * nx * ny]
                hs = [min(hv * n, 0.2) for n in (n1, n2, n3, n4)]
                feats[row, o] = 0.5 * sum(hs)
                t += hs
            for o in range(9):
                hv = hist[(x + 1) + (y + 1) * nx + o * nx * ny] + \
                    hist[(x + 1) + (y + 1) * nx + (o + 9) * nx * ny]
                feats[row, 18 + o] = 0.5 * sum(min(hv * n, 0.2)
                                               for n in (n1, n2, n3, n4))
            feats[row, 27:31] = [0.2357 * ti for ti in t]
            feats[row, 31] = 0.0
    return feats


def test_hog_matches_numpy_golden():
    from keystone_tpu.nodes.images import HogExtractor

    rng = np.random.RandomState(0)
    img = rng.rand(24, 24, 3).astype(np.float32)
    got = np.asarray(HogExtractor(bin_size=8).apply(img))
    want = _np_hog(img.astype(np.float64), 8)
    assert got.shape == want.shape == (1, 32)
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_hog_larger_grid_matches():
    from keystone_tpu.nodes.images import HogExtractor

    rng = np.random.RandomState(7)
    img = rng.rand(32, 40, 3).astype(np.float32)
    got = np.asarray(HogExtractor(bin_size=8).apply(img))
    want = _np_hog(img.astype(np.float64), 8)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_daisy_shape_and_normalization():
    from keystone_tpu.nodes.images import DaisyExtractor

    rng = np.random.RandomState(0)
    img = rng.rand(64, 64).astype(np.float32)
    ext = DaisyExtractor()
    out = np.asarray(ext.apply(img))
    xs = np.arange(16, 64 - 16, 4)
    assert out.shape == (ext.feature_size, len(xs) * len(xs))
    # every 8-bin histogram is L2-normalized (or zero)
    hists = out.reshape(8, -1, out.shape[1], order="F")
    norms = np.linalg.norm(out.T.reshape(-1, ext.feature_size // 8, 8), axis=2)
    assert np.all((np.abs(norms - 1.0) < 1e-4) | (norms < 1e-6))


def _np_daisy(img, T=8, Q=3, R=7, H=8, border=16, stride=4):
    """Direct numpy DAISY via scipy convolve2d (true convolution, zero
    padded 'same' like ImageUtils.conv2D for odd kernels)."""
    from scipy.signal import convolve2d

    from keystone_tpu.nodes.images.daisy import _daisy_kernels

    def conv(a, fx, fy):
        return convolve2d(
            convolve2d(a, np.asarray(fx)[:, None], mode="same"),
            np.asarray(fy)[None, :], mode="same")

    f1, f2 = [1.0, 0.0, -1.0], [1.0, 2.0, 1.0]
    ix = conv(img, f1, f2)
    iy = conv(img, f2, f1)
    kernels = _daisy_kernels(Q, R)
    layers = {}
    for h in range(H):
        ang = 2 * np.pi * h / H
        g = np.maximum(np.cos(ang) * ix + np.sin(ang) * iy, 0.0)
        lvl = conv(g, kernels[0], kernels[0])
        layers[(0, h)] = lvl
        for l in range(1, Q):
            lvl = conv(lvl, kernels[l], kernels[l])
            layers[(l, h)] = lvl

    def norm(v):
        n = np.linalg.norm(v)
        return v / n if n > 1e-8 else np.zeros_like(v)

    xs = range(border, img.shape[0] - border, stride)
    ys = range(border, img.shape[1] - border, stride)
    cols = []
    for x in xs:
        for y in ys:
            feat = np.zeros(H * (T * Q + 1))
            feat[:H] = norm(np.array([layers[(0, h)][x, y] for h in range(H)]))
            for t in range(T):
                theta = 2 * np.pi * (t - 1) / T
                for l in range(Q):
                    rad = R * (1.0 + l) / Q
                    px = x + int(round(rad * np.sin(theta)))
                    py = y + int(round(rad * np.cos(theta)))
                    v = norm(np.array(
                        [layers[(l, h)][px, py] for h in range(H)]))
                    feat[H + t * Q * H + l * H: H + t * Q * H + (l + 1) * H] = v
            cols.append(feat)
    return np.stack(cols, axis=1)


def test_daisy_matches_numpy_golden():
    from keystone_tpu.nodes.images import DaisyExtractor

    rng = np.random.RandomState(0)
    img = rng.rand(48, 48).astype(np.float32)
    got = np.asarray(DaisyExtractor(pixel_border=16, stride=8).apply(img))
    want = _np_daisy(img.astype(np.float64), border=16, stride=8)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-4)
