"""Distributed linalg vs numpy golden solutions (mirrors the reference's
solver suites, e.g. BlockLinearMapperSuite / LeastSquaresEstimatorSuite)."""
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.ops import linalg


def make_problem(n=256, d=32, k=4, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    A = rng.randn(n, d).astype(dtype)
    W = rng.randn(d, k).astype(dtype)
    Y = (A @ W + 0.01 * rng.randn(n, k)).astype(dtype)
    return A, Y, W


def ridge_numpy(A, Y, lam):
    d = A.shape[1]
    return np.linalg.solve(
        A.astype(np.float64).T @ A.astype(np.float64) + lam * np.eye(d),
        A.astype(np.float64).T @ Y.astype(np.float64),
    )


def test_gram_exact_with_padding():
    A, _, _ = make_problem(n=100)  # 100 not divisible by 8 -> padded
    ds = ArrayDataset.from_numpy(A)
    G = np.asarray(linalg.gram(ds.data))
    np.testing.assert_allclose(G, A.T @ A, rtol=1e-4)


def test_normal_equations_matches_numpy():
    A, Y, _ = make_problem()
    ds = ArrayDataset.from_numpy(A)
    ys = ArrayDataset.from_numpy(Y)
    W = np.asarray(linalg.normal_equations(ds.data, ys.data, lam=0.1))
    expect = ridge_numpy(A, Y, 0.1)
    np.testing.assert_allclose(W, expect, rtol=2e-3, atol=2e-3)


def test_local_least_squares_dual_matches_primal():
    # d >> n regime
    A, Y, _ = make_problem(n=32, d=128)
    W = np.asarray(linalg.local_least_squares_dual(A, Y, lam=0.5))
    expect = ridge_numpy(A, Y, 0.5)
    np.testing.assert_allclose(W, expect, rtol=5e-3, atol=5e-3)


def test_bcd_single_block_equals_normal_equations():
    A, Y, _ = make_problem()
    ds = ArrayDataset.from_numpy(A)
    ys = ArrayDataset.from_numpy(Y)
    Ws = linalg.block_coordinate_descent([ds.data], ys.data, lam=0.1, num_passes=1)
    expect = ridge_numpy(A, Y, 0.1)
    np.testing.assert_allclose(np.asarray(Ws[0]), expect, rtol=2e-3, atol=2e-3)


def test_bcd_converges_to_full_solve():
    """Multi-pass BCD over blocks approaches the joint ridge solution
    (reference BlockLinearMapperSuite: block solver vs single-matrix)."""
    A, Y, _ = make_problem(n=512, d=48, k=3, seed=1)
    lam = 0.5
    blocks_np = [A[:, :16], A[:, 16:32], A[:, 32:]]
    blocks = [ArrayDataset.from_numpy(b).data for b in blocks_np]
    ys = ArrayDataset.from_numpy(Y)
    Ws = linalg.block_coordinate_descent(blocks, ys.data, lam=lam, num_passes=30)
    W = np.concatenate([np.asarray(w) for w in Ws], axis=0)
    expect = ridge_numpy(A, Y, lam)
    np.testing.assert_allclose(W, expect, rtol=2e-2, atol=2e-2)


def test_bcd_one_pass_reduces_objective():
    A, Y, _ = make_problem(n=512, d=48, k=3, seed=2)
    blocks_np = [A[:, :24], A[:, 24:]]
    blocks = [ArrayDataset.from_numpy(b).data for b in blocks_np]
    ys = ArrayDataset.from_numpy(Y)
    Ws = linalg.solve_one_pass_l2(blocks, ys.data, lam=0.1)
    W = np.concatenate([np.asarray(w) for w in Ws], axis=0)
    resid = np.linalg.norm(A @ W - Y)
    assert resid < 0.5 * np.linalg.norm(Y)


def test_tsqr_r_matches_numpy():
    A, _, _ = make_problem(n=512, d=16)
    ds = ArrayDataset.from_numpy(A)
    R = np.asarray(linalg.tsqr_r(ds.data))
    # Compare via A^T A = R^T R and sign-fixed R against numpy
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-3, atol=1e-3)
    Rnp = np.linalg.qr(A, mode="r")
    Rnp = Rnp * np.sign(np.diag(Rnp))[:, None]
    np.testing.assert_allclose(np.abs(R), np.abs(Rnp), rtol=2e-3, atol=2e-3)
    assert np.all(np.diag(R) >= 0)


def test_tsqr_short_shards_pad_and_stay_distributed():
    # 10 rows over 8 shards would leave shards shorter than d=6; the
    # pad-and-mask path zero-pads to 6 rows/shard and stays exact.
    A = np.random.RandomState(0).randn(10, 6).astype(np.float32)
    R = np.asarray(linalg.tsqr_r(ArrayDataset.from_numpy(A).data))
    assert R.shape == (6, 6)
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-3, atol=1e-3)


def test_tsqr_uneven_rows_match_numpy():
    # n not divisible by the shard count: the zero-pad branch inside
    # tsqr_r must fire (raw array, not ArrayDataset, which would
    # pre-pad) and agree with a plain host QR up to the sign convention.
    import jax.numpy as jnp

    A = np.random.RandomState(1).randn(173, 12).astype(np.float32)
    R = np.asarray(linalg.tsqr_r(jnp.asarray(A)))
    assert R.shape == (12, 12)
    Rnp = np.linalg.qr(A, mode="r")
    Rnp = Rnp * np.sign(np.diag(Rnp))[:, None]
    np.testing.assert_allclose(R, Rnp, rtol=2e-3, atol=2e-3)
    assert np.all(np.diag(R) >= 0)


def test_tsqr_wide_matrix_replicated_fallback():
    # n < d is not tall-skinny; R is (n, d) from the replicated path.
    A = np.random.RandomState(2).randn(5, 9).astype(np.float32)
    padded = np.asarray(ArrayDataset.from_numpy(A).data)  # rows padded to shards
    R = np.asarray(linalg.tsqr_r(ArrayDataset.from_numpy(A).data))
    assert R.shape == (padded.shape[0], 9) and R.shape[0] < 9
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-3, atol=1e-3)


def test_distributed_mean_with_padding():
    A, _, _ = make_problem(n=100, d=8)
    ds = ArrayDataset.from_numpy(A)
    m = np.asarray(linalg.distributed_mean(ds.data, ds.n))
    np.testing.assert_allclose(m, A.mean(axis=0), rtol=1e-4, atol=1e-5)


def test_bcd_class_columns_shard_over_model_axis():
    """VERDICT r1 next#4 for the PLAIN solver: with a ('data','model')
    mesh, bcd_core shards label columns over 'model' (cross-products,
    cho_solve RHS, prediction updates split by class group) and matches
    the single-axis result exactly."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.parallel.mesh import (
        DATA_AXIS, MODEL_AXIS, make_mesh, mesh_scope,
    )

    devs = jax.devices()[:8]
    A, Y, _ = make_problem(n=160, d=24, k=8, seed=7)
    lam = 0.3

    with mesh_scope(make_mesh(devs, data=8, model=1)):
        W1 = linalg.block_coordinate_descent(
            [jax.numpy.asarray(A[:, :12]), jax.numpy.asarray(A[:, 12:])],
            jax.numpy.asarray(Y), lam, num_passes=3)
        W1 = np.concatenate([np.asarray(w) for w in W1])

    mesh = make_mesh(devs, data=4, model=2)
    with mesh_scope(mesh):
        Aj = jax.device_put(A, NamedSharding(mesh, P(DATA_AXIS, None)))
        Yj = jax.device_put(Y, NamedSharding(mesh, P(DATA_AXIS, None)))
        Ws = linalg.block_coordinate_descent(
            [Aj[:, :12], Aj[:, 12:]], Yj, lam, num_passes=3)
        # returned block weights are sharded over 'model' (k split 2-ways)
        shard_shapes = {s.data.shape for s in Ws[0].addressable_shards}
        assert shard_shapes == {(12, 4)}
        W2 = np.concatenate([np.asarray(w) for w in Ws])

    np.testing.assert_allclose(W1, W2, rtol=2e-4, atol=2e-4)
    # both solutions agree with the full normal-equations solve
    ref = ridge_numpy(A, Y, lam)
    for W in (W1, W2):
        assert np.linalg.norm(W - ref) / np.linalg.norm(ref) < 0.05


def test_gram_symmetric_tiled_path_matches_full():
    # d >= _GRAM_SYM_MIN_D with an admissible tile takes the
    # upper-triangle syrk assembly; must equal the fused einsum exactly
    # in structure and to f32 tolerance in value, and be symmetric
    rng = np.random.RandomState(7)
    A = rng.randn(96, 2048).astype(np.float32)
    import jax.numpy as jnp
    G = np.asarray(linalg.gram(jnp.asarray(A)))
    ref = A.T @ A
    assert G.shape == (2048, 2048)
    assert np.array_equal(G, G.T)
    assert np.allclose(G, ref, rtol=2e-5, atol=2e-4)


def test_gram_sym_tile_selection():
    # cap on the unrolled tile grid: tile widens for very wide A, and
    # non-divisible widths fall back (None) to the fused einsum
    from keystone_tpu.ops.linalg import _gram_sym_tile

    assert _gram_sym_tile(4096) == 512       # 8 tiles
    assert _gram_sym_tile(8192) == 512       # 16 tiles (at the cap)
    assert _gram_sym_tile(16384) == 1024     # cap doubles the tile
    assert _gram_sym_tile(2304) is None      # 512 does not divide


def test_near_breakdown_finite_factor_takes_eigh_fallback():
    # A near-duplicate column makes the Gram near-exactly-singular: f32
    # Cholesky returns a FINITE factor whose last pivot collapsed to
    # rounding noise (the "tiny positive pivot instead of a negative
    # one" regime ADVICE r2 flagged), and the raw solve produces wild
    # ~1e5-norm weights. The conditioning gate must route the solve to
    # the eigh-clamped recovery instead.
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    n, d, k = 256, 64, 3
    A = rng.randn(n, d).astype(np.float32)
    A[:, -1] = A[:, 0] + 1e-5 * rng.randn(n).astype(np.float32)
    G = (A.T @ A).astype(np.float32)
    rhs = rng.randn(d, k).astype(np.float32)

    W = np.asarray(linalg.ridge_cho_solve(
        jnp.asarray(G), jnp.asarray(rhs), 0.0))
    assert np.isfinite(W).all()

    V, wc = linalg.clamped_eigh(jnp.asarray(G))
    expected = np.asarray((V * (1.0 / wc)) @ (V.T @ jnp.asarray(rhs)))
    assert np.allclose(W, expected, rtol=1e-3, atol=1e-3), (
        np.abs(W - expected).max())
    # and the recovery is the point: bounded weights, not the raw
    # solve's ~1e5-norm blowup
    assert np.linalg.norm(W) < 1e3, np.linalg.norm(W)


def test_healthy_conditioning_keeps_cholesky_path():
    # kappa ~ 1e4 (well inside reference conditioning) must NOT take the
    # more-strongly-regularized fallback: the solve stays the accurate
    # Cholesky result, far from the clamped-eigh answer.
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    d, k = 64, 3
    Q = np.linalg.qr(rng.randn(d, d))[0]
    eig = np.logspace(0, -4, d)
    G = ((Q * eig) @ Q.T).astype(np.float32)
    rhs = rng.randn(d, k).astype(np.float32)

    W = np.asarray(linalg.ridge_cho_solve(
        jnp.asarray(G), jnp.asarray(rhs), 0.0))
    W64 = np.linalg.solve(G.astype(np.float64), rhs.astype(np.float64))
    assert np.abs(W - W64).max() / np.abs(W64).max() < 1e-2


def test_badly_scaled_well_conditioned_keeps_cholesky_path():
    # G = D C D with C well-conditioned and diagonal scales spanning
    # 1e4: raw-kappa looks ~1e8 but the f32 Cholesky solve is accurate
    # to ~1e-7 — the scale-free pivot gate must NOT misroute it to the
    # much-more-regularized eigh fallback (review r3 finding).
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    n, d, k = 256, 64, 3
    B = rng.randn(n, d)
    C = B.T @ B / n
    D = np.logspace(0, -4, d)
    G = ((C * D[None, :]) * D[:, None]).astype(np.float32)
    rhs = (rng.randn(d, k) * D[:, None]).astype(np.float32)

    W = np.asarray(linalg.ridge_cho_solve(
        jnp.asarray(G), jnp.asarray(rhs), 0.0))
    W64 = np.linalg.solve(G.astype(np.float64), rhs.astype(np.float64))
    rel = np.abs(W - W64).max() / np.abs(W64).max()
    assert rel < 1e-3, rel


def test_bcd_scan_matches_unrolled():
    # 4+ equal-width blocks route through bcd_core's lax.scan body (the
    # dispatch itself is exercised here, not just the body); the scan
    # result must be numerically identical (same sequential update
    # order) to the unrolled path, which ragged/small lists still use
    import jax.numpy as jnp

    rng = np.random.RandomState(11)
    n, k = 192, 3
    X = rng.randn(n, 128).astype(np.float32)
    Y = rng.randn(n, k).astype(np.float32)
    blocks = tuple(jnp.asarray(X[:, i:i + 32]) for i in range(0, 128, 32))
    lam = jnp.float32(0.05)
    # through the public dispatch: 4 equal blocks -> scan body
    via_core = linalg.bcd_core(blocks, jnp.asarray(Y), lam, num_passes=3)
    # direct bodies for the equivalence claim
    scan_out = linalg._bcd_scan_body(blocks, jnp.asarray(Y), lam,
                                     num_passes=3)
    unrolled = linalg._bcd_core_body(blocks, jnp.asarray(Y), lam,
                                     num_passes=3)
    for a, b, c in zip(via_core, scan_out, unrolled):
        assert np.allclose(np.asarray(a), np.asarray(b), rtol=0, atol=0), \
            "bcd_core must dispatch 4 equal blocks to the scan body"
        assert np.allclose(np.asarray(b), np.asarray(c),
                           rtol=1e-5, atol=1e-5)
    # ragged lists stay on the unrolled path (scan would crash on
    # stack); values must match a direct unrolled-body call
    ragged = (jnp.asarray(X[:, :48]), jnp.asarray(X[:, 48:96]),
              jnp.asarray(X[:, 96:]), jnp.asarray(X[:, 96:]))
    out = linalg.bcd_core(ragged, jnp.asarray(Y), lam, num_passes=1)
    ref = linalg._bcd_core_body(ragged, jnp.asarray(Y), lam, num_passes=1)
    assert len(out) == 4
    for a, b in zip(out, ref):
        assert np.allclose(np.asarray(a), np.asarray(b),
                           rtol=1e-5, atol=1e-5)
