"""NodeOptimizationRule + cost-model auto-solver tests (mirrors the
reference's NodeOptimizationRuleSuite and LeastSquaresEstimatorSuite:
"Big n small d dense" etc. check the cost-model choice itself)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    DenseLBFGSwithL2,
    LeastSquaresEstimator,
    LinearMapEstimator,
    SparseLBFGSwithL2,
)
from keystone_tpu.nodes.learning.least_squares import (
    REFERENCE_EC2_WEIGHTS,
    estimate_sparsity,
)
from keystone_tpu.nodes.learning.pca import (
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    LocalColumnPCAEstimator,
)
from keystone_tpu.nodes.util import MaxClassifier
from keystone_tpu.nodes.util.sparse import Sparsify, SparseVector
from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
from keystone_tpu.workflow.optimizable import NodeChoice
from keystone_tpu.workflow.transformer import transformer


def _dense_sample(n=8, d=4, k=2, seed=0):
    rng = np.random.RandomState(seed)
    return (ArrayDataset.from_numpy(rng.rand(n, d).astype(np.float32)),
            ArrayDataset.from_numpy(rng.rand(n, k).astype(np.float32)))


def test_cost_choice_big_n_small_d_dense(mesh8):
    # n=1M, d=1000, k=1000, 16 machines -> exact distributed solve
    # (reference LeastSquaresEstimatorSuite "Big n small d dense").
    # Parity tests pin the REFERENCE cost surface, so they run under the
    # reference's EC2 calibration; the TPU-calibrated default surface is
    # pinned by test_tpu_crossover_matches_measured_fastest.
    est = LeastSquaresEstimator(**REFERENCE_EC2_WEIGHTS)
    sample, labels = _dense_sample(d=1000, k=1000)
    choice = est.optimize(sample, labels, n=1_000_000, num_machines=16)
    assert isinstance(choice.node, LinearMapEstimator)


def test_cost_choice_big_n_big_d_dense(mesh8):
    # n=1M, d=10000, k=1000 -> block solver (reference "big n big d dense")
    est = LeastSquaresEstimator()
    sample, labels = _dense_sample(d=10_000, k=1000, n=4)
    choice = est.optimize(sample, labels, n=1_000_000, num_machines=16)
    assert isinstance(choice.node, BlockLeastSquaresEstimator)


def test_cost_choice_big_n_big_d_sparse(mesh8):
    # n=1M, d=10000, k=2, sparsity=0.01 -> sparse LBFGS
    # (reference "big n big d sparse")
    est = LeastSquaresEstimator(**REFERENCE_EC2_WEIGHTS)  # see above
    rng = np.random.RandomState(0)
    items = [SparseVector(np.arange(100), np.ones(100, np.float32), 10_000)
             for _ in range(8)]
    labels = ArrayDataset.from_numpy(rng.randn(8, 2).astype(np.float32))
    choice = est.optimize(HostDataset(items), labels,
                          n=1_000_000, num_machines=16)
    assert isinstance(choice.node, SparseLBFGSwithL2)
    assert any(isinstance(t, Sparsify) for t in choice.prefix)


def test_cost_choice_small_n_big_d_exact(mesh8):
    # small n, moderate d, dense -> exact normal equations or block solve
    est = LeastSquaresEstimator()
    sample, labels = _dense_sample(d=4)
    choice = est.optimize(sample, labels, n=100, num_machines=1)
    assert isinstance(choice.node,
                      (LinearMapEstimator, BlockLeastSquaresEstimator,
                       DenseLBFGSwithL2))


def test_tpu_crossover_matches_measured_fastest(mesh8):
    """VERDICT r4 next#4 crossover test: with the SHIPPED TPU-calibrated
    weights (the defaults), the auto-solver's choice must match the
    solver measured fastest end-to-end on the bench chip. Measured
    2026-07-31 (tools/calibrate_cost_model.py, TPU v5 lite, k=10):

        n=65536 d=256  : block_ls  73 ms | exact 171 ms | lbfgs 336 ms
        n=65536 d=1024 : block_ls  84 ms | exact 193 ms | lbfgs 334 ms
        n=32768 d=4096 : block_ls  91 ms | exact 185 ms | lbfgs 288 ms

    The reference's EC2 surface picks `exact` at all three shapes (its
    latency-free cost terms cannot express why the one-program
    scan-based BCD beats a ~10-round exact solve); the TPU surface's
    dispatch-latency term can, and the calibration run validated the
    model-vs-measurement agreement at 3/3 shapes."""
    est = LeastSquaresEstimator()  # shipped TPU defaults
    for n, d in ((65_536, 256), (65_536, 1_024), (32_768, 4_096)):
        sample, labels = _dense_sample(d=d, k=10)
        choice = est.optimize(sample, labels, n=n, num_machines=1)
        assert isinstance(choice.node, BlockLeastSquaresEstimator), (
            n, d, type(choice.node))


def test_estimate_sparsity():
    items = [SparseVector([0], [1.0], 10), SparseVector([0, 1, 2], [1.] * 3, 10)]
    assert estimate_sparsity(HostDataset(items)) == pytest.approx(0.2)


def test_column_pca_optimize_small_prefers_local(mesh8):
    items = [np.random.RandomState(i).rand(8, 4).astype(np.float32)
             for i in range(3)]
    est = ColumnPCAEstimator(dims=2)
    choice = est.optimize(HostDataset(items), n=3, num_machines=8)
    assert isinstance(choice.node, (LocalColumnPCAEstimator,
                                    DistributedColumnPCAEstimator))


def test_node_optimization_rule_splices_in_pipeline(mesh8):
    """End-to-end: a pipeline holding a LeastSquaresEstimator is optimized
    so the fitted pipeline uses the cost-chosen solver + prefix on both
    the fit path and the runtime path."""
    rng = np.random.RandomState(0)
    n, d, k = 32, 6, 3
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    train = ArrayDataset.from_numpy(X)
    labels = ArrayDataset.from_numpy(Y)

    ident = transformer(lambda x: x * 1.0)
    pipe = ident.and_then(
        LeastSquaresEstimator(num_iterations=100), train, labels)
    preds = pipe(train).get().numpy()
    np.testing.assert_allclose(preds, Y, atol=5e-2)


def test_optimizable_default_without_rule(mesh8):
    # calling .fit directly (no DAG, no rule) uses the default solver
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 2)).astype(np.float32)
    model = LeastSquaresEstimator(num_iterations=100).fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    np.testing.assert_allclose(
        np.asarray(model.apply_dataset(ArrayDataset.from_numpy(X)).numpy()),
        Y, atol=5e-2)
