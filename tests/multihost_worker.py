"""Worker process for the 2-process multi-host smoke test (VERDICT r2
next#6): each process owns 2 virtual CPU devices; together they form a
4-device global mesh. Exercises the REAL multi-host wiring —
``initialize_distributed`` (jax.distributed over a local coordinator),
global-mesh construction, ``make_array_from_process_local_data``
ingestion, and a psum-backed normal-equations fit whose Gram/cross
all-reduce crosses the process boundary — the analogue of the
reference's Spark cluster attach + treeReduce
(``bin/run-pipeline.sh``, ``BlockLinearMapper.scala:234-240``).

Usage: multihost_worker.py <process_id> <num_processes> <coordinator_port>
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # sitecustomize imports jax early

import numpy as np  # noqa: E402


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from keystone_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc, jax.process_count()
    devices = jax.devices()
    assert len(devices) == 2 * nproc, devices  # global device view

    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.ops import linalg
    from keystone_tpu.parallel.mesh import make_mesh, mesh_scope

    n, d, k = 64, 16, 3
    rng = np.random.RandomState(0)  # same data on every host (SPMD)
    A = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (A @ W).astype(np.float32)

    mesh = make_mesh(devices)  # data axis spans BOTH processes
    with mesh_scope(mesh):
        sh = NamedSharding(mesh, P("data"))
        rows = n // (2 * nproc)  # rows per device

        def local(arr):
            # this host's contiguous row shard (device order == mesh
            # data order: process 0 owns devices 0-1, process 1 owns 2-3)
            lo = pid * 2 * rows
            return arr[lo:lo + 2 * rows]

        Ag = jax.make_array_from_process_local_data(sh, local(A), (n, d))
        Yg = jax.make_array_from_process_local_data(sh, local(Y), (n, k))

        # Gram + cross all-reduce crosses the process boundary here
        W_fit = linalg.normal_equations(Ag, Yg, lam=1e-6)
        W_np = np.linalg.solve(A.T @ A + 1e-6 * np.eye(d), A.T @ Y)
        err = np.abs(np.asarray(W_fit) - W_np).max()
        assert err < 1e-3, f"cross-process solve mismatch: {err}"

        mean = np.asarray(linalg.distributed_mean(Ag, n))
        assert np.allclose(mean, A.mean(0), atol=1e-5)

    print(f"MULTIHOST_OK pid={pid} err={err:.2e}", flush=True)


if __name__ == "__main__":
    main()
