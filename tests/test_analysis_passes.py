"""Donation-safety and recompile-hazard static passes
(keystone_tpu/analysis/diagnostics + utils/donation): each rule fires
on its synthetic offender fixture (tests/lint_fixtures — the pre-PR-2
``_bcd_jit_for`` bug shape, use/checkpoint-after-donate, the
``_CAST_JIT_CACHE`` per-instance-memo lesson) and reports today's tree
clean; the eval_shape donation-shape gate pins every donated carry
argument to a shape-compatible output (the static promotion of
``_gram_bcd``'s old per-finalize runtime warning)."""
import ast
import pathlib
import warnings

import jax
import numpy as np
import pytest

from keystone_tpu.analysis.diagnostics import (
    donating_names,
    donation_hazards,
    metric_name_drift,
    recompile_hazards,
)
from keystone_tpu.utils.donation import (
    DonationSite,
    donation_shape_mismatches,
    registered_donations,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"


def _tree(name):
    return ast.parse((FIXTURES / f"{name}.py").read_text())


# -- offenders fire ----------------------------------------------------------

def test_use_after_donate_fires_on_offender():
    hits = donation_hazards(_tree("donation_offender"))
    codes = {c for _, c, _ in hits}
    assert codes == {"use-after-donate", "checkpoint-after-donate"}
    # one hit each: the safe rebind-in-loop pattern is NOT flagged
    assert len(hits) == 2
    use = next(h for h in hits if h[1] == "use-after-donate")
    assert "`carry`" in use[2] and "dead" in use[2]


def test_checkpoint_after_donate_names_the_save():
    hits = donation_hazards(_tree("donation_offender"))
    ckpt = next(h for h in hits if h[1] == "checkpoint-after-donate")
    assert "checkpoint save" in ckpt[2]


def test_mesh_closure_jit_fires_on_pre_pr2_shape():
    """The fixture reproduces the exact historical bug: a module-level
    jit of a solver that reads the ambient mesh one call away
    (bcd_core -> _class_spec -> get_mesh)."""
    hits = recompile_hazards(_tree("mesh_closure_offender"))
    assert [c for _, c, _ in hits] == ["mesh-closure-jit"]
    assert "_bcd_jit_for" in hits[0][2]  # the fix is named in the hint


def test_mesh_closure_exempts_per_mesh_factory():
    # today's ops/linalg.py: the jit lives inside a factory taking the
    # mesh as a parameter (lru_cache keyed per mesh) — clean
    src = (REPO / "keystone_tpu/ops/linalg.py").read_text()
    assert "_bcd_jit_for" in src
    assert recompile_hazards(ast.parse(src)) == []


def test_per_instance_jit_memo_fires_on_offender():
    hits = recompile_hazards(_tree("per_instance_memo_offender"))
    assert [c for _, c, _ in hits] == ["per-instance-jit-memo"]


def test_per_instance_memo_blessed_by_global_cache():
    # the _cached_jit pattern: the same scope also puts the program in
    # a module-level memo, so the self attr is only a fast path — clean
    src = (REPO / "keystone_tpu/workflow/transformer.py").read_text()
    hits = [h for h in recompile_hazards(ast.parse(src))
            if h[1] == "per-instance-jit-memo"]
    assert hits == []


def test_unstable_jit_tag_still_detected():
    src = (
        "class T:\n"
        "    def f(self, tag):\n"
        "        return self._cached_jit('ok', lambda: None)\n"
        "    def g(self, tag):\n"
        "        return self._cached_jit(tag + 'x', lambda: None)\n")
    hits = recompile_hazards(ast.parse(src))
    assert [c for _, c, _ in hits] == ["unstable-jit-cache-tag"]


def test_donating_names_parses_both_spellings():
    src = (
        "a = donating_jit(impl, donate_argnums=(0, 1))\n"
        "b = donating_jit(impl2, (2,))\n"
        "c = other(impl3)\n")
    names = donating_names(ast.parse(src))
    assert names == {"a": frozenset({0, 1}), "b": frozenset({2})}


# -- metric-name drift (PR 8 satellite) --------------------------------------

def test_metric_name_drift_fires_on_offender():
    """The fixture's three drifted sites fire; the catalogued literal,
    the catalogued f-string prefix, and the fully dynamic name do not."""
    hits = metric_name_drift(_tree("metric_name_offender"))
    assert len(hits) == 3, hits
    assert {c for _, c, _ in hits} == {"metric-name-drift"}
    msgs = " ".join(m for _, _, m in hits)
    assert "streaming.chunk_total" in msgs   # the typo'd counter
    assert "ingest.depth" in msgs            # uncatalogued gauge
    assert "pool.wait_s." in msgs            # undeclared prefix family
    assert "observability/names.py" in msgs  # fix hint names the catalogue


def test_metric_catalogue_matches_registry_usage():
    """Every catalogued exact name is plausible (non-empty, dotted) and
    the prefix families end with a separator — the catalogue is an
    interface file, keep it well-formed."""
    from keystone_tpu.observability.names import (
        METRIC_NAMES,
        METRIC_PREFIXES,
        is_catalogued,
        is_catalogued_prefix,
    )

    assert all("." in n for n in METRIC_NAMES)
    assert all(p.endswith(".") for p in METRIC_PREFIXES)
    assert is_catalogued("streaming.chunks_total")
    assert is_catalogued("resilience.retry")       # prefix family
    assert not is_catalogued("streaming.chunk_total")
    assert is_catalogued_prefix("lock.wait_s.")
    assert not is_catalogued_prefix("")            # bare f-string head


# -- silent-nan-silencer (PR 10 satellite) -----------------------------------

def test_nan_silencer_fires_on_offender():
    """The fixture's two silent suppressions fire; the accounted
    spellings (record_numerics_event in scope, a numerics.* counter in
    scope) and errstate(all='raise') do not."""
    from keystone_tpu.analysis.diagnostics import silent_nan_silencers

    hits = silent_nan_silencers(_tree("nan_silencer_offender"))
    assert len(hits) == 2, hits
    whats = {w for _, w in hits}
    assert whats == {"nan_to_num(...)", "errstate(...='ignore')"}


def test_nan_silencer_scoped_tree_is_clean():
    """The numeric compute trees ship with zero unaccounted NaN
    suppressions (the scopes tools/lint.py enforces)."""
    from keystone_tpu.analysis.diagnostics import (
        NAN_SILENCER_SCOPES,
        silent_nan_silencers,
    )

    hits = []
    for scope in NAN_SILENCER_SCOPES:
        for path in sorted((REPO / "keystone_tpu" / scope).rglob("*.py")):
            for lineno, what in silent_nan_silencers(
                    ast.parse(path.read_text())):
                hits.append(f"{path}:{lineno}: {what}")
    assert hits == [], hits


def test_nan_silencer_nested_defs_are_separate_scopes():
    # a recorder in the outer body must not bless a silencer inside a
    # nested def (and vice versa) — same scope rule as cast-before-
    # transfer: false co-occurrence across closures is worse than a
    # missed split pattern
    from keystone_tpu.analysis.diagnostics import silent_nan_silencers

    src = (
        "def outer(x):\n"
        "    record_numerics_event('nonfinite', count=1)\n"
        "    def inner(y):\n"
        "        return np.nan_to_num(y)\n"
        "    return inner(x)\n")
    hits = silent_nan_silencers(ast.parse(src))
    assert [w for _, w in hits] == ["nan_to_num(...)"]


# -- the whole tree is clean -------------------------------------------------

@pytest.mark.parametrize(
    "pass_fn", [donation_hazards, recompile_hazards, metric_name_drift])
def test_package_tree_is_clean(pass_fn):
    hits = []
    for path in sorted((REPO / "keystone_tpu").rglob("*.py")):
        for lineno, code, msg in pass_fn(ast.parse(path.read_text())):
            hits.append(f"{path}:{lineno}: {code}")
    assert hits == [], hits


# -- donation shape gate (satellite: the _gram_bcd pin) ----------------------

def test_registered_donation_sites_are_shape_compatible():
    """Every donating_jit site in the linear family + scaler donates
    only arguments with a shape-compatible output — the static pin for
    the old `_gram_bcd` (d,d)-donation warning. Probes make this
    checkable via eval_shape on any backend, devices untouched."""
    import keystone_tpu.nodes.learning.linear  # noqa: F401  (registers)
    import keystone_tpu.nodes.stats  # noqa: F401

    probed = [s for s in registered_donations() if s.probe is not None]
    assert {s.name for s in probed} >= {
        "_gram_carry_update_impl", "_finalize_normal_equations_impl",
        "_gram_bcd_impl", "_accum_moments_impl"}
    for site in probed:
        assert donation_shape_mismatches(site) == [], site.name


def test_shape_gate_catches_a_bad_donation():
    # the pre-fix _gram_bcd shape: donating a (d, d) Gram with no
    # matching output must be reported
    def impl(G, sx):
        return sx / G.shape[0]  # only a (d,) output exists

    S = jax.ShapeDtypeStruct
    site = DonationSite(
        fn=impl, donate_argnums=(0, 1), static_argnames=(),
        probe=lambda: ((S((8, 8), np.float32), S((8,), np.float32)), {}),
        name="impl", module="test")
    bad = donation_shape_mismatches(site)
    assert len(bad) == 1 and "arg 0" in bad[0]


def test_streamed_finalize_emits_no_donation_warnings(mesh8):
    """Satellite pin: a full streamed BlockLS fit + finalize runs with
    ZERO donation warnings — no 'donated buffer not usable' (shape
    mismatch) and no donated-buffer reuse errors — on this backend and,
    via the shape gate above, provably on the backends where donation
    is real."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    rng = np.random.RandomState(0)
    X = rng.rand(512, 64).astype(np.float32)
    L = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 512)]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        model = fit_streaming(
            BlockLeastSquaresEstimator(32, 1, lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=128), L)
    donation_warnings = [w for w in caught
                        if "donat" in str(w.message).lower()]
    assert donation_warnings == []
    assert np.asarray(model.weights).shape == (64, 4)
