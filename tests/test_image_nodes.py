"""Image node tests vs numpy golden implementations (mirrors
ConvolverSuite / PoolerSuite / WindowerSuite etc.)."""
import numpy as np
import pytest

from keystone_tpu.nodes.images.core import (
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.nodes.learning.zca import ZCAWhitenerEstimator
from keystone_tpu.ops.image_ops import (
    extract_windows,
    filter_bank_convolve,
    normalize_rows,
)
from keystone_tpu.parallel.dataset import ArrayDataset


def rand_images(n=4, h=10, w=10, c=3, seed=0):
    return np.random.RandomState(seed).rand(n, h, w, c).astype(np.float32) * 255


def im2col_patches(img, size):
    """Golden im2col in (dy, dx, c) feature order (the reference's
    makePatches packing)."""
    H, W, C = img.shape
    out = []
    for y in range(H - size + 1):
        for x in range(W - size + 1):
            out.append(img[y : y + size, x : x + size, :].ravel())
    return np.array(out)


def test_extract_windows_matches_im2col():
    img = rand_images(1, 8, 8, 2)[0]
    wins = np.asarray(extract_windows(img, 3, 1))
    flat = wins.reshape(-1, 3 * 3 * 2)
    np.testing.assert_allclose(flat, im2col_patches(img, 3), rtol=1e-6)


def test_normalize_rows_golden():
    rng = np.random.RandomState(0)
    m = rng.rand(5, 12).astype(np.float32)
    out = np.asarray(normalize_rows(m, 10.0))
    means = m.mean(1, keepdims=True)
    var = ((m - means) ** 2).sum(1, keepdims=True) / (m.shape[1] - 1)
    expect = (m - means) / np.sqrt(var + 10.0)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_convolver_matches_im2col_gemm():
    """Conv-based path == materialized patches @ filters (the reference
    algorithm, Convolver.scala:120-190), incl. patch normalization and
    whitener means."""
    rng = np.random.RandomState(1)
    img = rng.rand(10, 10, 3).astype(np.float32)
    K, S, C = 7, 4, 3
    filters = rng.rand(K, S * S * C).astype(np.float32)
    means = rng.rand(S * S * C).astype(np.float32) * 0.1

    out = np.asarray(
        filter_bank_convolve(img, filters, S, C, True, means, 10.0)
    )

    patches = im2col_patches(img, S)
    pn = np.asarray(normalize_rows(patches, 10.0)) - means
    expect = (pn @ filters.T).reshape(10 - S + 1, 10 - S + 1, K)
    np.testing.assert_allclose(out, expect, rtol=2e-3, atol=2e-3)


def test_convolver_no_normalization():
    rng = np.random.RandomState(2)
    img = rng.rand(8, 8, 1).astype(np.float32)
    filters = rng.rand(2, 9).astype(np.float32)
    out = np.asarray(filter_bank_convolve(img, filters, 3, 1, False, None))
    patches = im2col_patches(img, 3)
    expect = (patches @ filters.T).reshape(6, 6, 2)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)


def test_symmetric_rectifier():
    img = np.array([[[1.0, -2.0]]], np.float32)
    out = SymmetricRectifier(alpha=0.25)(img[None]).numpy()[0]
    np.testing.assert_allclose(out[0, 0], [0.75, 0.0, 0.0, 1.75])


def test_pooler_cifar_geometry():
    """poolSize=14, stride=13 on 27x27 -> 2x2 pools, regions [0,14) and
    [13,27) (reference Pooler.scala strideStart semantics)."""
    img = np.ones((27, 27, 2), np.float32)
    out = Pooler(13, 14, "identity", "sum")(img[None]).numpy()[0]
    assert out.shape == (2, 2, 2)
    np.testing.assert_allclose(out[0, 0], 14 * 14)
    np.testing.assert_allclose(out[1, 1], 14 * 14)


def test_pooler_sum_golden():
    rng = np.random.RandomState(3)
    img = rng.rand(9, 9, 1).astype(np.float32)
    out = Pooler(4, 4, "identity", "sum")(img[None]).numpy()[0]
    # strideStart=2; xs = 2, 6; region [0,4), [4,8)
    expect00 = img[0:4, 0:4, 0].sum()
    expect11 = img[4:8, 4:8, 0].sum()
    np.testing.assert_allclose(out[0, 0, 0], expect00, rtol=1e-5)
    np.testing.assert_allclose(out[1, 1, 0], expect11, rtol=1e-5)


def test_windower_flatmap_count():
    imgs = rand_images(3, 8, 8, 1)
    ds = ArrayDataset.from_numpy(imgs)
    out = Windower(2, 4)(ds).get()
    npos = ((8 - 4) // 2 + 1) ** 2
    assert len(out) == 3 * npos
    got = out.numpy()
    assert got.shape == (3 * npos, 4, 4, 1)
    # first window of first image is the top-left crop
    np.testing.assert_allclose(got[0], imgs[0][:4, :4, :], rtol=1e-6)


def test_random_patcher_shapes_and_determinism():
    imgs = rand_images(2, 12, 12, 3)
    ds = ArrayDataset.from_numpy(imgs)
    out1 = RandomPatcher(4, 5, 5, seed=1)(ds).numpy()
    out2 = RandomPatcher(4, 5, 5, seed=1)(ds).numpy()
    assert out1.shape == (8, 5, 5, 3)
    np.testing.assert_array_equal(out1, out2)


def test_center_corner_patcher():
    imgs = rand_images(2, 8, 8, 1)
    ds = ArrayDataset.from_numpy(imgs)
    out = CenterCornerPatcher(4, 4, horizontal_flips=True)(ds).numpy()
    assert out.shape == (20, 4, 4, 1)
    np.testing.assert_allclose(out[0], imgs[0][:4, :4, :], rtol=1e-6)
    # flipped variant
    np.testing.assert_allclose(out[5], imgs[0][:4, :4, ::1][:, ::-1, :], rtol=1e-6)


def test_grayscale_weights():
    img = np.zeros((1, 1, 1, 3), np.float32)
    img[0, 0, 0] = [100, 200, 50]
    out = GrayScaler()(img).numpy()
    expect = 0.2989 * 100 + 0.5870 * 200 + 0.1140 * 50
    np.testing.assert_allclose(out[0, 0, 0, 0], expect, rtol=1e-4)


def test_zca_whitener_decorrelates():
    rng = np.random.RandomState(4)
    base = rng.randn(500, 6).astype(np.float32)
    mix = rng.randn(6, 6).astype(np.float32)
    data = base @ mix
    w = ZCAWhitenerEstimator(eps=1e-5).fit_single(data)
    out = (data - w.means) @ w.whitener
    cov = np.cov(out.T)
    np.testing.assert_allclose(cov, np.eye(6), atol=0.15)
    # whitener is symmetric
    np.testing.assert_allclose(w.whitener, w.whitener.T, atol=1e-4)


def test_grayscale_uint8_promotes():
    """Packed-u8 images: luma weights must not truncate to zero."""
    import numpy as np

    from keystone_tpu.ops.image_ops import to_grayscale

    img = np.full((4, 4, 3), 100, np.uint8)
    out = np.asarray(to_grayscale(img))
    np.testing.assert_allclose(out, 100.0 * 0.9999, rtol=1e-3)
    assert out.dtype == np.float32
