"""Flight recorder, telemetry sampler, Prometheus exposition, and
crash post-mortems (PR 8 observability plane).

Covers: ring-buffer bounding and wraparound order; Chrome-trace export
round-tripping through ``json.loads`` with strictly non-overlapping
``ts``/``dur`` per exported lane (nested/overlapping spans overflow to
sub-lanes); the ``--trace-out *.perfetto.json`` dispatch; real streamed
runs feeding prefetch/H2D/compute lanes; sampler start/stop idempotency
and bounded series; the ``/metrics`` scrape endpoint; and post-mortem
dumps attached to ``IngestTimeoutError`` / ``RetryExhaustedError`` /
HBM-budget ``MemoryError`` with the artifact path named in the message.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.observability.sampler import TelemetrySampler, serve_metrics
from keystone_tpu.observability.timeline import (
    FlightRecorder,
    flight_recorder,
    write_trace_artifact,
)


def _nonoverlap_per_lane(blob):
    """Assert the strictly-non-overlapping invariant for every exported
    lane: complete events sorted by ts never start before the previous
    one ended."""
    lanes = {}
    for e in blob["traceEvents"]:
        if e.get("ph") == "X":
            lanes.setdefault(e["tid"], []).append((e["ts"], e["dur"]))
    assert lanes, "no complete events exported"
    for tid, events in lanes.items():
        events.sort()
        for (t1, d1), (t2, d2) in zip(events, events[1:]):
            assert t2 >= t1 + d1 - 1e-6, (
                f"lane {tid}: span at {t2} overlaps previous "
                f"[{t1}, {t1 + d1}]")
    return lanes


# -- ring buffer -------------------------------------------------------------

def test_ring_bounds_and_wraparound_order():
    rec = FlightRecorder(capacity=4, enabled=True)
    t0 = time.perf_counter()
    for i in range(7):
        rec.record(f"s{i}", "test", t0 + i, 0.5)
    spans = rec.spans()
    assert [s.name for s in spans] == ["s3", "s4", "s5", "s6"]  # oldest out
    assert rec.total_recorded == 7
    assert rec.dropped() == 3


def test_ring_clear_and_partial_fill():
    rec = FlightRecorder(capacity=8, enabled=True)
    rec.record("a", "test", 0.0, 1.0)
    rec.record("b", "test", 1.0, 1.0)
    assert [s.name for s in rec.spans()] == ["a", "b"]
    rec.clear()
    assert rec.spans() == [] and rec.dropped() == 0


def test_disabled_recorder_records_nothing():
    rec = FlightRecorder(capacity=8, enabled=False)
    rec.record("a", "test", 0.0, 1.0)
    with rec.span("b", "test"):
        pass
    assert rec.spans() == [] and rec.total_recorded == 0


def test_env_disable_via_global(monkeypatch):
    from keystone_tpu.observability.timeline import reset_flight_recorder

    monkeypatch.setenv("KEYSTONE_FLIGHT_RECORDER", "0")
    reset_flight_recorder()
    rec = flight_recorder()
    rec.record("a", "test", 0.0, 1.0)
    assert rec.spans() == []
    monkeypatch.delenv("KEYSTONE_FLIGHT_RECORDER")
    reset_flight_recorder()
    assert flight_recorder().enabled


def test_span_context_records_on_raise():
    rec = FlightRecorder(capacity=8, enabled=True)
    with pytest.raises(ValueError):
        with rec.span("doomed", "test"):
            raise ValueError("boom")
    assert [s.name for s in rec.spans()] == ["doomed"]


# -- chrome-trace export -----------------------------------------------------

def test_chrome_trace_roundtrips_with_nonoverlapping_lanes():
    """Overlapping spans recorded on ONE thread (the nested-executor
    shape) must come back on separate sub-lanes, each lane strictly
    non-overlapping, through a full json round-trip."""
    rec = FlightRecorder(capacity=64, enabled=True)
    t0 = time.perf_counter()
    rec.record("parent", "node", t0, 1.0)        # [0, 1]
    rec.record("child", "node", t0 + 0.2, 0.5)   # nested inside parent
    rec.record("next", "node", t0 + 1.5, 0.5)    # disjoint: same lane ok
    rec.record_instant("marker", "resilience", args={"k": "v"})
    blob = json.loads(rec.to_chrome_json())
    lanes = _nonoverlap_per_lane(blob)
    assert len(lanes) == 2  # parent+next on lane 0, child overflowed
    names = {e["name"] for e in blob["traceEvents"]}
    assert {"parent", "child", "next", "marker"} <= names
    # thread metadata names every lane, nested ones marked as such
    th_meta = [e for e in blob["traceEvents"]
               if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert len(th_meta) == 2
    assert any("(nested 1)" in e["args"]["name"] for e in th_meta)


def test_chrome_trace_multi_thread_lanes():
    rec = FlightRecorder(capacity=64, enabled=True)

    def worker():
        rec.record("w", "test", time.perf_counter(), 0.01)

    t = threading.Thread(target=worker, name="side-thread")
    t.start()
    t.join()
    rec.record("m", "test", time.perf_counter(), 0.01)
    blob = rec.to_chrome_trace()
    lane_names = {e["args"]["name"] for e in blob["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "side-thread" in lane_names
    assert any("MainThread" in n for n in lane_names)


def test_write_trace_artifact_dispatch(tmp_path):
    from keystone_tpu.observability import PipelineTrace

    rec = flight_recorder()
    rec.record("x", "test", time.perf_counter(), 0.01)
    perfetto = tmp_path / "run.perfetto.json"
    assert write_trace_artifact(str(perfetto)) == "perfetto"
    blob = json.loads(perfetto.read_text())
    assert any(e.get("name") == "x" for e in blob["traceEvents"])
    with PipelineTrace("t") as tr:
        pass
    plain = tmp_path / "trace.json"
    assert write_trace_artifact(str(plain), tr) == "trace"
    assert json.loads(plain.read_text())["name"] == "t"
    with pytest.raises(ValueError):
        write_trace_artifact(str(tmp_path / "other.json"))  # needs a trace


# -- streamed run feeds the lanes -------------------------------------------

def test_streamed_fit_produces_ingest_h2d_compute_lanes(mesh8):
    """The acceptance shape: a streamed fit leaves stage spans on the
    prefetch thread, h2d spans on the pool lanes, accumulate spans on
    the consumer — distinct lanes in the export, non-overlapping each."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming

    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype(np.float32)
    L = rng.randn(256, 4).astype(np.float32)
    stream = StreamingDataset.from_numpy(
        X, chunk_size=64, mesh=mesh8, tag="lane-test")
    fit_streaming(LinearMapEstimator(lam=0.1), stream, L)
    rec = flight_recorder()
    cats = {s.cat for s in rec.spans()}
    assert {"ingest", "compute"} <= cats
    by_cat_thread = {(s.cat, s.thread) for s in rec.spans()}
    # stage spans ride the prefetch thread, accumulate the main thread
    assert any(c == "ingest" and "prefetch" in t
               for c, t in by_cat_thread)
    assert any(c == "compute" and "prefetch" not in t
               for c, t in by_cat_thread)
    blob = json.loads(rec.to_chrome_json())
    _nonoverlap_per_lane(blob)
    # the valid-Chrome-trace contract benchdiff's acceptance names:
    # top-level traceEvents, complete events with ts/dur, metadata names
    assert isinstance(blob["traceEvents"], list)
    assert blob["displayTimeUnit"] == "ms"


def test_contended_traced_lock_feeds_recorder():
    from keystone_tpu.utils.guarded import TracedLock

    lock = TracedLock("timeline.contention")
    release = threading.Event()

    def holder():
        with lock:
            release.wait(2.0)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.05)  # let the holder take it
    release_timer = threading.Timer(0.1, release.set)
    release_timer.start()
    with lock:  # contended: records a span on this (losing) thread
        pass
    t.join()
    spans = [s for s in flight_recorder().spans()
             if s.cat == "lock" and "timeline.contention" in s.name]
    assert spans and spans[0].dur_s > 0


# -- sampler ----------------------------------------------------------------

def test_sampler_sample_once_records_probes_and_gauges():
    reg = MetricsRegistry.get_or_create()
    reg.gauge("streaming.prefetch_occupancy").set(2.0)
    sampler = TelemetrySampler(interval_s=0.05)
    values = sampler.sample_once()
    assert values["process.rss_bytes"] > 0
    assert "h2d.pool_queue_depth" in values
    assert values["streaming.prefetch_occupancy"] == 2.0
    # probe values published back as gauges -> scrapeable
    assert reg.gauge("process.rss_bytes").value > 0
    rss = sampler.series("process.rss_bytes")
    assert len(rss) == 1 and rss[0][1] > 0


def test_sampler_series_is_bounded():
    sampler = TelemetrySampler(interval_s=0.01, capacity=5)
    for _ in range(12):
        sampler.sample_once()
    for name in sampler.series_names():
        assert len(sampler.series(name)) <= 5


def test_sampler_start_stop_idempotent_and_restartable():
    sampler = TelemetrySampler(interval_s=0.01)
    assert not sampler.running
    sampler.stop()          # stop before start: no-op
    sampler.start()
    first = sampler._thread
    sampler.start()         # idempotent: same thread
    assert sampler._thread is first and sampler.running
    deadline = time.monotonic() + 5.0
    while not sampler.series_names() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sampler.series_names(), "sampler thread never sampled"
    sampler.stop()
    sampler.stop()          # idempotent
    assert not sampler.running
    sampler.start()         # restartable
    assert sampler.running
    sampler.stop()


def test_sampler_broken_probe_is_skipped():
    sampler = TelemetrySampler(interval_s=0.01)
    sampler.add_probe("broken.probe", lambda: 1 / 0)
    values = sampler.sample_once()
    assert "broken.probe" not in values
    assert "process.rss_bytes" in values  # the rest still sampled


def test_sampler_validates_args():
    with pytest.raises(ValueError):
        TelemetrySampler(interval_s=0)
    with pytest.raises(ValueError):
        TelemetrySampler(capacity=0)


def test_sampler_racing_starts_leave_one_thread():
    # regression: gating start() on is_alive() saw a created-but-unstarted
    # thread as "not running" and spawned a second, unstoppable sampler
    sampler = TelemetrySampler(interval_s=0.05)
    barrier = threading.Barrier(8)

    def go():
        barrier.wait()
        sampler.start()

    workers = [threading.Thread(target=go) for _ in range(8)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    alive = [t for t in threading.enumerate()
             if t.name == "keystone-telemetry-sampler"]
    sampler.stop()
    assert len(alive) == 1
    deadline = time.monotonic() + 5.0
    while alive[0].is_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not alive[0].is_alive(), "stop() left a sampler thread behind"


# -- prometheus exposition ---------------------------------------------------

def test_to_prometheus_exposition_format():
    reg = MetricsRegistry.get_or_create()
    reg.counter("streaming.chunks_total").inc(3)
    reg.gauge("streaming.prefetch_occupancy").set(1.5)
    h = reg.histogram("streaming.ingest_stall_s")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    text = reg.to_prometheus()
    assert "# TYPE keystone_streaming_chunks_total_total counter" in text
    assert "keystone_streaming_chunks_total_total 3" in text
    assert "keystone_streaming_prefetch_occupancy 1.5" in text
    assert "# TYPE keystone_streaming_ingest_stall_s summary" in text
    assert 'keystone_streaming_ingest_stall_s{quantile="0.5"}' in text
    assert "keystone_streaming_ingest_stall_s_count 3" in text
    # sanitized charset: no dots survive
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert "." not in line.split("{")[0].split(" ")[0]


def test_serve_metrics_endpoint():
    reg = MetricsRegistry.get_or_create()
    reg.counter("streaming.chunks_total").inc()
    server = serve_metrics(port=0)
    try:
        base = f"http://127.0.0.1:{server.server_port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            assert resp.status == 200
            body = resp.read().decode()
        assert "keystone_streaming_chunks_total_total" in body
        with urllib.request.urlopen(base + "/healthz", timeout=5) as resp:
            assert resp.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        server.shutdown()
        server.server_close()


def test_serve_metrics_shutdown_releases_port():
    # regression: plain ThreadingHTTPServer.shutdown() left the listening
    # socket bound, so a same-port restart raised EADDRINUSE
    server = serve_metrics(port=0)
    port = server.server_port
    server.shutdown()
    server2 = serve_metrics(port=port)
    try:
        url = f"http://127.0.0.1:{port}/healthz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.read() == b"ok\n"
    finally:
        server2.shutdown()


# -- post-mortems ------------------------------------------------------------

def test_dump_postmortem_contents(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    from keystone_tpu.observability.postmortem import dump_postmortem

    flight_recorder().record("evidence", "test", time.perf_counter(), 0.1)
    MetricsRegistry.get_or_create().counter("streaming.chunks_total").inc()
    path = dump_postmortem("unit_test", {"chunk": 7})
    assert path is not None
    blob = json.loads(open(path).read())
    assert blob["reason"] == "unit_test"
    assert blob["context"]["chunk"] == 7
    assert blob["metrics"]["counters"]["streaming.chunks_total"] == 1
    names = {e.get("name") for e in blob["flight_recorder"]["traceEvents"]}
    assert "evidence" in names


def test_postmortem_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("KEYSTONE_POSTMORTEM", "0")
    from keystone_tpu.observability.postmortem import dump_postmortem

    assert dump_postmortem("nope") is None
    assert list(tmp_path.iterdir()) == []


def test_retry_exhausted_names_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    from keystone_tpu.resilience.retry import (
        RetryExhaustedError,
        RetryPolicy,
        TransientError,
    )

    policy = RetryPolicy(max_attempts=2, backoff_s=0.001)

    def always_fails():
        raise TransientError("flaky disk")

    with pytest.raises(RetryExhaustedError) as exc_info:
        policy.call(always_fails, site="test.site")
    exc = exc_info.value
    assert exc.postmortem_path is not None
    assert f"[post-mortem: {exc.postmortem_path}]" in str(exc)
    blob = json.loads(open(exc.postmortem_path).read())
    assert blob["reason"] == "retry_exhausted"
    assert blob["context"]["site"] == "test.site"
    # the retry instants are in the dumped timeline
    names = [e.get("name") for e in blob["flight_recorder"]["traceEvents"]]
    assert "retry" in names


def test_ingest_timeout_names_postmortem(tmp_path, monkeypatch, mesh8):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    from keystone_tpu.parallel.streaming import StreamingDataset
    from keystone_tpu.resilience.retry import IngestTimeoutError

    block = threading.Event()

    def hung_source():
        yield np.ones((8, 4), np.float32)
        block.wait(30)  # hangs past the stall deadline
        yield np.ones((8, 4), np.float32)

    stream = StreamingDataset(
        lambda: hung_source(), chunk_size=8, mesh=mesh8,
        stall_timeout_s=0.3, tag="hung")
    with pytest.raises(IngestTimeoutError) as exc_info:
        for _ in stream.chunks():
            pass
    block.set()
    exc = exc_info.value
    assert exc.postmortem_path is not None
    assert "[post-mortem:" in str(exc)
    blob = json.loads(open(exc.postmortem_path).read())
    assert blob["reason"] == "ingest_timeout"
    assert blob["context"]["reason"] == "stall_deadline"


def test_hbm_budget_memoryerror_names_postmortem(tmp_path, monkeypatch,
                                                 mesh8):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming

    X = np.random.RandomState(0).randn(64, 16).astype(np.float32)
    L = np.random.RandomState(1).randn(64, 2).astype(np.float32)
    stream = StreamingDataset.from_numpy(
        X, chunk_size=16, mesh=mesh8, tag="tiny-budget")
    with pytest.raises(MemoryError) as exc_info:
        fit_streaming(LinearMapEstimator(lam=0.1), stream, L, hbm_budget=1.0)
    exc = exc_info.value
    assert exc.postmortem_path is not None
    assert "[post-mortem:" in str(exc)
    blob = json.loads(open(exc.postmortem_path).read())
    assert blob["reason"] == "hbm_budget"


def test_postmortem_failure_never_masks_the_crash(tmp_path, monkeypatch):
    """A dump failure (the target dir path is blocked by a FILE, so
    mkdir cannot succeed — even as root) leaves the exception intact
    with no path attached — evidence collection must not mask the
    failure."""
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the dump dir should be")
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(blocker / "sub"))
    from keystone_tpu.observability.postmortem import attach_postmortem

    exc = attach_postmortem(ValueError("the real failure"), "unit_test")
    assert str(exc) == "the real failure"
    assert exc.postmortem_path is None


# -- streamed-fit gauges the sampler scrapes ---------------------------------

def test_streamed_fit_publishes_residency_and_carry_gauges(mesh8):
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming

    rng = np.random.RandomState(0)
    X = rng.randn(128, 16).astype(np.float32)
    L = rng.randn(128, 4).astype(np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=32, mesh=mesh8)
    fit_streaming(LinearMapEstimator(lam=0.1), stream, L)
    reg = MetricsRegistry.get_or_create()
    # carry = Gram (d,d) + cross (d,k) + sums: > d*d*4 bytes
    assert reg.gauge("streaming.carry_bytes").value >= 16 * 16 * 4
    # residency gauge was written (last chunk may have drained to 0,
    # but the gauge must exist and be finite)
    assert "streaming.resident_bytes" in reg.snapshot()["gauges"]
