"""End-to-end text pipeline + loader tests (mirrors the reference's
NewsgroupsPipeline/AmazonReviewsPipeline usage and loader suites)."""
import json
import os

import numpy as np
import pytest

from keystone_tpu.loaders import (
    LabeledData,
    amazon_reviews_loader,
    newsgroups_loader,
    timit_features_loader,
)
from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
from keystone_tpu.pipelines.nlp.stupid_backoff_pipeline import (
    StupidBackoffConfig,
    run as run_backoff,
)
from keystone_tpu.pipelines.text.amazon_reviews import (
    AmazonReviewsConfig,
    run as run_amazon,
)
from keystone_tpu.pipelines.text.newsgroups import (
    NewsgroupsConfig,
    run as run_newsgroups,
)

SPORTS = [
    "the home team won the hockey game last night",
    "a great baseball game with two home runs",
    "the playoffs start tonight with a big hockey match",
    "our team scored twice and won the baseball series",
    "the goalie made many saves in the hockey final",
]
TECH = [
    "the new graphics card renders the screen quickly",
    "install the driver to fix the windows graphics issue",
    "my computer monitor has a high screen resolution",
    "the software update broke the graphics driver again",
    "upgrade your computer memory for faster software",
]


def _mini_newsgroups(tmp_path, split):
    root = tmp_path / split
    for cls, docs in [("rec.sport.hockey", SPORTS), ("comp.graphics", TECH)]:
        d = root / cls
        d.mkdir(parents=True)
        for i, doc in enumerate(docs):
            (d / f"{i}.txt").write_text(doc)
    return str(root)


def test_newsgroups_loader_and_pipeline(tmp_path, mesh8):
    train_dir = _mini_newsgroups(tmp_path, "train")
    classes = ["rec.sport.hockey", "comp.graphics"]
    train = newsgroups_loader(train_dir, classes)
    assert len(train.data) == 10
    labels = np.asarray(train.labels.numpy())
    assert (labels == 0).sum() == 5 and (labels == 1).sum() == 5

    _, metrics = run_newsgroups(
        NewsgroupsConfig(n_grams=2, common_features=500),
        train=train, test=train, num_classes=2)
    assert metrics.total_error == 0.0  # separable toy corpus


def test_amazon_loader_and_pipeline(tmp_path, mesh8):
    reviews = [
        ("great product works perfectly love it", 5.0),
        ("excellent quality very happy recommend", 5.0),
        ("terrible broke immediately waste of money", 1.0),
        ("awful quality very disappointed bad", 1.0),
        ("great value excellent love the quality", 4.0),
        ("bad product terrible experience broke", 2.0),
    ] * 2
    path = tmp_path / "reviews.json"
    with open(path, "w") as f:
        for text, score in reviews:
            f.write(json.dumps({"reviewText": text, "overall": score}) + "\n")

    data = amazon_reviews_loader(str(path), threshold=3.5)
    labels = np.asarray(data.labels.numpy())
    assert labels.sum() == 6  # 6 positives
    _, metrics = run_amazon(
        AmazonReviewsConfig(common_features=200, num_iters=50),
        train=data, test=data)
    assert metrics.accuracy == 1.0


def test_timit_loader(tmp_path):
    feats = np.arange(12, dtype=np.float32).reshape(4, 3)
    np.savetxt(tmp_path / "train.csv", feats, delimiter=",")
    np.savetxt(tmp_path / "test.csv", feats[:2], delimiter=",")
    with open(tmp_path / "train.lab", "w") as f:
        for i, lab in enumerate([3, 1, 2, 147]):
            f.write(f"{i + 1} {lab}\n")
    with open(tmp_path / "test.lab", "w") as f:
        f.write("1 5\n2 6\n")
    data = timit_features_loader(
        str(tmp_path / "train.csv"), str(tmp_path / "train.lab"),
        str(tmp_path / "test.csv"), str(tmp_path / "test.lab"))
    np.testing.assert_array_equal(
        np.asarray(data.train.labels.numpy()), [2, 0, 1, 146])
    np.testing.assert_array_equal(
        np.asarray(data.test.labels.numpy()), [4, 5])
    np.testing.assert_allclose(np.asarray(data.train.data.numpy()), feats)


def test_stupid_backoff_pipeline(tmp_path):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "the cat sat on the mat\nthe cat ran\nthe dog sat on the rug\n")
    model = run_backoff(StupidBackoffConfig(str(corpus), n=3))
    assert model.num_tokens == 15
    assert len(model.unigram_counts) == 8
    # every pre-scored ngram is a valid relative frequency
    for s in model.scores.values():
        assert 0.0 <= s <= 1.0
