"""The request-path tracing & SLO plane (ISSUE 16):

* the telescoping invariant — the four-phase decomposition of a traced
  request sums EXACTLY to its ``request_ms`` (float epsilon only),
  both on a hand-stamped trace and through the real serving plane;
* Chrome-trace flow links: request spans carry ``flow_out``, their
  batch span the matching ``flow_in`` list, exported as ``ph:"s"`` /
  ``ph:"f"`` events that anchor to existing lanes without ever
  violating the strictly-non-overlapping-per-lane invariant;
* the bounded slowest-N exemplar reservoir;
* SLO accounting: rolling windows, min_count cold-start guard, the
  one-post-mortem-per-violated-window discipline, and the embedded
  exemplar evidence;
* the HTTP surface (``X-Keystone-Trace`` header, ``GET /slo``,
  ``GET /debug/slow``);
* per-model 429 accounting (``serving.rejected_total.<model>``);
* submit/take/done under the deterministic scheduler: two clients
  racing the worker lose no span and cross-attribute none, under a
  scripted regression schedule AND a seeded sweep.
"""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import jax

from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.observability.reqtrace import (
    PHASES,
    ExemplarReservoir,
    ReqTrace,
    exemplar_reservoir,
    mint_trace_id,
    tracing_active,
    tracing_suppressed,
)
from keystone_tpu.observability.slo import (
    SloPolicy,
    SloTracker,
    SloViolation,
)
from keystone_tpu.observability.timeline import (
    FlightRecorder,
    flight_recorder,
)
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.serving import MicroBatcher, QueueFullError, ServingPlane


def _make_fitted(d, k, seed=0, n=96):
    r = np.random.RandomState(seed)
    X = r.rand(n, d).astype(np.float32)
    Y = r.rand(n, k).astype(np.float32)
    fitted = LinearMapEstimator(lam=1e-3).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()
    return fitted, X


def _sample(d):
    return jax.ShapeDtypeStruct((d,), np.float32)


def _stamped(model="m", n=4, base=100.0,
             deltas=(0.001, 0.002, 0.003, 0.0005)):
    tr = ReqTrace.new(model, n)
    tr.enqueued_s = base
    tr.taken_s = base + deltas[0]
    tr.dispatch_s = tr.taken_s + deltas[1]
    tr.done_s = tr.dispatch_s + deltas[2]
    tr.responded_s = tr.done_s + deltas[3]
    return tr


# -- the trace record ---------------------------------------------------------

def test_phases_telescope_to_request_ms():
    tr = _stamped()
    ph = tr.phases_ms()
    assert tuple(ph) == PHASES
    assert sum(ph.values()) == pytest.approx(tr.request_ms(), abs=1e-9)
    assert all(v >= 0 for v in ph.values())


def test_incomplete_trace_has_no_phases():
    tr = ReqTrace.new("m", 2)
    assert not tr.complete()
    assert tr.phases_ms() == {}
    assert tr.request_ms() is None
    tr.taken_s = tr.enqueued_s + 0.001
    assert tr.phases_ms() == {}  # still missing later stamps


def test_trace_ids_are_process_unique_and_prefixed():
    ids = {mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(i.startswith(f"req-{os.getpid():x}-") for i in ids)
    assert mint_trace_id("coord").startswith("coord-")


def test_tracing_suppression_and_env_gate(monkeypatch):
    assert tracing_active()
    with tracing_suppressed():
        assert not tracing_active()
        with tracing_suppressed():  # depth-counted, not a boolean
            assert not tracing_active()
        assert not tracing_active()
    assert tracing_active()
    monkeypatch.setenv("KEYSTONE_REQTRACE", "0")
    assert not tracing_active()


# -- the exemplar reservoir ---------------------------------------------------

def test_reservoir_is_bounded_and_keeps_the_slowest():
    res = ExemplarReservoir(cap=3)
    for ms in (5, 1, 9, 3, 7, 2, 8):
        tr = _stamped(deltas=(ms / 4e3,) * 4)  # request_ms == ms
        res.offer(tr)
    kept = [round(t.request_ms()) for t in res.slowest(10, model="m")]
    assert kept == [9, 8, 7]  # slowest three, slowest first
    # a fast trace offered into a full reservoir is refused
    assert res.offer(_stamped(deltas=(0.0001,) * 4)) is False
    # incomplete traces are never retained
    assert res.offer(ReqTrace.new("m", 1)) is False


def test_reservoir_merges_across_models_and_filters():
    res = ExemplarReservoir(cap=4)
    res.offer(_stamped(model="a", deltas=(0.001,) * 4))
    res.offer(_stamped(model="b", deltas=(0.002,) * 4))
    merged = res.slowest(10)
    assert [t.model for t in merged] == ["b", "a"]
    assert [t.model for t in res.slowest(10, model="a")] == ["a"]
    trees = res.slowest_trees(1)
    assert trees[0]["model"] == "b" and "phases_ms" in trees[0]
    res.clear()
    assert res.slowest(10) == []


# -- flow-event export --------------------------------------------------------

def test_chrome_trace_emits_flow_links_at_anchor_positions():
    rec = FlightRecorder(capacity=64, enabled=True)
    rec.record("request:m", "serving", 1.0, 0.5,
               args={"flow_out": 7, "trace_id": "req-x-7"})
    rec.record("batch:m", "serving", 1.2, 0.4,
               args={"flow_in": [7], "batch": 1})
    events = rec.to_chrome_trace()["traceEvents"]
    anchors = {e["name"]: e for e in events if e.get("ph") == "X"}
    starts = [e for e in events if e.get("ph") == "s"]
    finishes = [e for e in events if e.get("ph") == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    # flow events anchor to their span's ts and lane, share the id,
    # and the finish binds to the enclosing slice (bp: "e")
    assert starts[0]["id"] == finishes[0]["id"] == 7
    assert starts[0]["ts"] == anchors["request:m"]["ts"]
    assert starts[0]["tid"] == anchors["request:m"]["tid"]
    assert finishes[0]["ts"] == anchors["batch:m"]["ts"]
    assert finishes[0]["tid"] == anchors["batch:m"]["tid"]
    assert finishes[0]["bp"] == "e"


def _assert_no_lane_overlap(trace):
    by_lane = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "X":
            by_lane.setdefault(e["tid"], []).append(
                (e["ts"], e["ts"] + e["dur"]))
    for lane, spans in by_lane.items():
        spans.sort()
        for (s0, e0), (s1, _) in zip(spans, spans[1:]):
            assert s1 >= e0, f"lane {lane} overlaps: {spans}"


def test_flow_links_do_not_break_lane_nonoverlap():
    rec = FlightRecorder(capacity=64, enabled=True)
    lanes_without_flows = None
    for with_flows in (False, True):
        rec.clear()
        for i in range(4):
            args = ({"flow_out": i + 1} if with_flows else None)
            rec.record(f"request:{i}", "serving", 1.0 + i * 0.1, 0.5,
                       args=args)  # overlapping -> sub-lanes
        trace = rec.to_chrome_trace()
        _assert_no_lane_overlap(trace)
        lanes = {e["tid"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
        if lanes_without_flows is None:
            lanes_without_flows = lanes
        else:
            # flow events never mint lanes of their own
            assert lanes == lanes_without_flows


# -- through the real serving plane ------------------------------------------

@pytest.fixture
def plane_factory():
    planes = []

    def make(**kw):
        kw.setdefault("max_batch", 16)
        plane = ServingPlane(**kw)
        planes.append(plane)
        return plane

    yield make
    for plane in planes:
        plane.close()


def test_served_request_reconciles_and_links(plane_factory):
    """The acceptance pin: a request served by the REAL plane carries a
    complete trace whose phase sum reconciles with its request_ms, the
    phase histograms observed it, the reservoir retained it, and the
    Perfetto export links its span into the batch span it rode."""
    fitted, X = _make_fitted(8, 3, seed=0)
    plane = plane_factory()
    plane.start()
    plane.admit("m", fitted, _sample(8))
    out, trace_id = plane.predict_traced("m", X[:5])
    assert np.asarray(out).shape == (5, 3)
    assert trace_id.startswith("req-")

    # reservoir offers and phase observes are deferred onto the
    # recorder's flush path (the serving hot path only stamps)
    flight_recorder().flush()
    traces = exemplar_reservoir().slowest(4, model="m")
    assert len(traces) == 1
    tr = traces[0]
    assert tr.trace_id == trace_id and tr.complete()
    ph = tr.phases_ms()
    assert sum(ph.values()) == pytest.approx(tr.request_ms(), abs=1e-6)
    assert tr.bucket == 8 and tr.fill == pytest.approx(5 / 8)
    assert tr.batch_id is not None

    reg = MetricsRegistry.get_or_create()
    for phase in PHASES:
        assert reg.histogram(f"serving.phase_ms.{phase}").count == 1
        assert reg.histogram(f"serving.phase_ms.{phase}.m").count == 1
        # the histogram observed the SAME decomposition the trace holds
        assert reg.histogram(f"serving.phase_ms.{phase}").total == \
            pytest.approx(ph[phase], abs=1e-6)
    assert reg.histogram("serving.request_ms").total == \
        pytest.approx(tr.request_ms(), abs=1e-6)

    trace = flight_recorder().to_chrome_trace()
    events = trace["traceEvents"]
    req_span = next(e for e in events if e.get("ph") == "X"
                    and e["name"] == "request:m")
    batch_span = next(e for e in events if e.get("ph") == "X"
                      and e["name"] == "batch:m")
    assert req_span["args"]["trace_id"] == trace_id
    assert req_span["args"]["flow_out"] == tr.flow_id
    assert tr.flow_id in batch_span["args"]["flow_in"]
    flow_ids = {e["id"] for e in events if e.get("ph") in ("s", "f")}
    assert tr.flow_id in flow_ids
    _assert_no_lane_overlap(trace)


def test_suppressed_request_leaves_no_trace(plane_factory):
    fitted, X = _make_fitted(8, 3, seed=0)
    plane = plane_factory()
    plane.start()
    plane.admit("m", fitted, _sample(8))
    with tracing_suppressed():
        out, trace_id = plane.predict_traced("m", X[:3])
    assert np.asarray(out).shape == (3, 3)
    assert trace_id == ""
    assert exemplar_reservoir().slowest(4) == []
    reg = MetricsRegistry.get_or_create()
    assert reg.histogram("serving.phase_ms.queue_wait").count == 0
    # the coarse PR 15 funnels still fire on the untraced path
    assert reg.histogram("serving.request_ms").count == 1
    assert plane.slo.totals() == (1, 0)


def test_rejection_increments_per_model_counter():
    batcher = MicroBatcher(queue_depth=1, submit_timeout_s=0.01)
    batcher.submit("alpha", np.zeros((1, 2)), 1)  # fills the only slot
    with pytest.raises(QueueFullError):
        batcher.submit("alpha", np.zeros((1, 2)), 1)
    reg = MetricsRegistry.get_or_create()
    assert reg.counter("serving.rejected_total").value == 1
    assert reg.counter("serving.rejected_total.alpha").value == 1
    batcher.close()


# -- SLO accounting -----------------------------------------------------------

def test_slo_policy_validates_and_computes_burn_rate():
    p = SloPolicy(latency_threshold_ms=100, availability_target=0.9,
                  window=10, min_count=5)
    assert p.burn_rate(1.0) == 0.0
    assert p.burn_rate(0.9) == pytest.approx(1.0)
    assert p.burn_rate(0.8) == pytest.approx(2.0)
    for bad in (dict(latency_threshold_ms=0),
                dict(availability_target=1.0),
                dict(availability_target=0.0),
                dict(window=0),
                dict(min_count=0),
                dict(window=4, min_count=5)):
        with pytest.raises(ValueError):
            SloPolicy(**bad)


def test_slo_cold_window_never_trips():
    """min_count: 1 bad request out of 3 is not a 33% outage."""
    tracker = SloTracker(SloPolicy(
        latency_threshold_ms=10, availability_target=0.99,
        window=16, min_count=8))
    assert tracker.record("m", 50.0) is None  # slow, but window is cold
    assert tracker.record("m", None, ok=False) is None
    assert tracker.state()["violations"] == []
    assert tracker.availability() == pytest.approx(0.0)


def test_slo_trip_escalates_once_and_resets_window(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("KEYSTONE_POSTMORTEM_DIR", str(tmp_path))
    policy = SloPolicy(latency_threshold_ms=10,
                       availability_target=0.95, window=8, min_count=4)
    tracker = SloTracker(policy)
    # a slow request lands in the reservoir first, so the post-mortem
    # has an exemplar to embed
    slow = _stamped(model="m", deltas=(0.02, 0.002, 0.003, 0.001))
    exemplar_reservoir().offer(slow)
    for _ in range(3):
        tracker.record("m", 1.0)
    tripped = tracker.record("m", 500.0)  # 3 good + 1 bad: 0.75 < 0.95
    assert tripped is not None and tripped["model"] == "m"
    assert tripped["window"]["count"] == 4
    assert tripped["window"]["bad"] == 1
    assert tripped["burn_rate"] == pytest.approx(
        policy.burn_rate(0.75), abs=1e-4)
    # the violated window RESET: the very next bad request cannot
    # re-trip until the window refills to min_count
    assert tracker.record("m", 500.0) is None
    assert isinstance(tracker.last_violation, SloViolation)

    pm_path = tripped["postmortem"]
    assert pm_path and os.path.exists(pm_path)
    with open(pm_path) as f:
        pm = json.load(f)
    ctx = pm["context"]
    assert ctx["model"] == "m" and ctx["window"]["count"] == 4
    exemplars = ctx["exemplars"]
    assert exemplars and exemplars[0]["trace_id"] == slow.trace_id
    assert exemplars[0]["phases_ms"]  # the span tree rode along

    reg = MetricsRegistry.get_or_create()
    assert reg.counter("serving.slo_violations_total").value == 1
    assert reg.counter("slo.violation").value == 1
    state = tracker.state()
    assert len(state["violations"]) == 1
    assert state["violations"][0]["postmortem"] == pm_path


def test_slo_gauges_publish_aggregate_and_per_model():
    tracker = SloTracker(SloPolicy(
        latency_threshold_ms=10, availability_target=0.9,
        window=8, min_count=8))
    for _ in range(3):
        tracker.record("a", 1.0)
    tracker.record("b", 99.0)  # over threshold: bad
    reg = MetricsRegistry.get_or_create()
    assert reg.gauge("serving.availability").value == pytest.approx(0.75)
    assert reg.gauge("serving.availability.b").value == 0.0
    assert reg.gauge("serving.error_budget_burn_rate").value == \
        pytest.approx(2.5)
    state = tracker.state()
    assert state["models"]["a"]["availability"] == 1.0
    assert state["models"]["b"]["bad"] == 1
    assert state["totals"] == {"good": 3, "bad": 1}


# -- the HTTP surface ---------------------------------------------------------

def test_http_trace_header_slo_and_debug_slow(plane_factory):
    from keystone_tpu.serving.http import serve

    fitted, X = _make_fitted(8, 3, seed=1)
    plane = plane_factory(slo_policy=SloPolicy(
        latency_threshold_ms=5000, availability_target=0.99,
        window=16, min_count=4))
    plane.start()
    plane.admit("m", fitted, _sample(8))
    server = serve(plane)
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        req = urllib.request.Request(
            base + "/predict/m",
            data=json.dumps({"instances": X[:3].tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as rsp:
            header = rsp.headers.get("X-Keystone-Trace")
            out = json.loads(rsp.read())
        assert out["rows"] == 3
        assert header and header.startswith("req-")

        with urllib.request.urlopen(base + "/slo") as rsp:
            slo = json.loads(rsp.read())
        assert slo["availability"] == 1.0
        assert slo["policy"]["availability_target"] == 0.99
        assert slo["models"]["m"]["good"] == 1
        assert slo["violations"] == []

        with urllib.request.urlopen(base + "/debug/slow?n=2") as rsp:
            slow = json.loads(rsp.read())
        assert len(slow["slowest"]) == 1
        tree = slow["slowest"][0]
        assert tree["trace_id"] == header  # joins on the echoed header
        assert sum(tree["phases_ms"].values()) == pytest.approx(
            tree["request_ms"], abs=1e-2)

        with urllib.request.urlopen(
                base + "/debug/slow?n=4&model=ghost") as rsp:
            assert json.loads(rsp.read())["slowest"] == []
    finally:
        server.shutdown()


# -- submit/take/done under the deterministic scheduler -----------------------

@pytest.mark.parametrize("schedule", [
    {"picks": ["client-a", "client-b", "worker"] * 60},
    {"picks": ["client-a", "client-a", "worker", "client-b"] * 60},
    {"seed": 0}, {"seed": 1}, {"seed": 2}, {"seed": 3}, {"seed": 4},
])
def test_two_clients_race_worker_no_span_lost_or_crossed(schedule):
    """Two clients race the ONE worker across submit/take/done on the
    real TracedLock/TracedSemaphore yield points: every request's
    future resolves with ITS OWN model's result (no cross-attribution),
    every trace completes with its stamps in lifecycle order (no span
    lost), and all trace ids stay distinct."""
    from tests.sched import DeterministicScheduler

    batcher = MicroBatcher(queue_depth=16, submit_timeout_s=5.0)
    per_client = 3
    requests = {"a": [], "b": []}
    served = []

    def client(model):
        for _ in range(per_client):
            requests[model].append(
                batcher.submit_request(model, np.zeros((2, 4)), 2))

    sched = DeterministicScheduler(**schedule)

    def worker():
        spins = 0
        while len(served) < 2 * per_client and spins < 2000:
            spins += 1
            batch = batcher.take(max_rows=8, timeout_s=0.0)
            sched.yield_point("worker-idle")
            if not batch:
                continue
            t0 = time.perf_counter()
            assert len({r.model for r in batch}) == 1  # same-model only
            for req in batch:
                if req.trace is not None:
                    req.trace.dispatch_s = t0
                    req.trace.done_s = time.perf_counter()
                    req.trace.responded_s = time.perf_counter()
                req.future.set_result(req.model)
            batcher.done(len(batch))
            served.extend(batch)

    sched.spawn(client, "a", name="client-a")
    sched.spawn(client, "b", name="client-b")
    sched.spawn(worker, name="worker")
    with sched:
        sched.run()

    assert len(served) == 2 * per_client  # no request lost
    all_ids = set()
    for model, reqs in requests.items():
        assert len(reqs) == per_client
        for req in reqs:
            assert req.future.result(timeout=1) == model  # no crossing
            tr = req.trace
            assert tr is not None and tr.complete()
            assert tr.model == model and tr.trace_id not in all_ids
            all_ids.add(tr.trace_id)
            assert tr.enqueued_s <= tr.taken_s <= tr.dispatch_s
            assert sum(tr.phases_ms().values()) == pytest.approx(
                tr.request_ms(), abs=1e-6)
    batcher.close()
