"""shard_map data-parallel apply (``parallel/spmd_apply.py``, ISSUE 18
tentpole b): batch rows shard ``P('data')``, LinearMapper /
BlockLinearMapper weights row-shard AT REST and gather transiently
inside the body — the 8-virtual-device conftest mesh is the
single-process stand-in for the world mesh (the cross-host case rides
the dryrun worlds in ``test_elastic.py``).

Pins the acceptance bar: parity with the single-host ``model.apply``
<= 1e-5 with IDENTICAL prediction argmax, across bucket sizes
including ragged tails (rows not divisible by the shard count, weight
rows not divisible either — the zero-pad must never reach the math);
plus the compile-once discipline (refits of the same shapes add no
``_PROGRAMS`` entries, the serving warmup fence's contract).
"""
import numpy as np
import pytest

import jax

from keystone_tpu.nodes.learning.linear import (
    BlockLinearMapper,
    LinearMapper,
)
from keystone_tpu.nodes.stats import StandardScalerModel
from keystone_tpu.parallel import spmd_apply
from keystone_tpu.parallel.mesh import make_mesh, mesh_scope, num_data_shards
from keystone_tpu.parallel.spmd_apply import (
    shard_batch,
    shard_rows,
    sharded_apply,
    sharded_chain_apply,
    unshard_batch,
)
from keystone_tpu.workflow.optimizer.fusion import fused_transformer

D, K = 37, 5  # 37: divides NEITHER 8 shards NOR the 16-row blocks

# bucket ladder with ragged tails: 13 and 50 are not multiples of 8,
# 64 spans several rows per shard, 1 exercises the degenerate pad
BUCKETS = (1, 8, 13, 50, 64)


def _affine_model(seed=0, scaled=True):
    rng = np.random.RandomState(seed)
    w = rng.randn(D, K).astype(np.float32)
    b = rng.randn(K).astype(np.float32)
    scaler = None
    if scaled:
        scaler = StandardScalerModel(
            rng.randn(D).astype(np.float32),
            (0.5 + rng.rand(D)).astype(np.float32))
    return LinearMapper(w, intercept=b, feature_scaler=scaler)


def _block_model(seed=1):
    rng = np.random.RandomState(seed)
    w = rng.randn(D, K).astype(np.float32)
    blocks = [w[lo:lo + 16] for lo in range(0, D, 16)]  # 16/16/5
    return BlockLinearMapper(
        blocks, block_size=16,
        intercept=rng.randn(K).astype(np.float32),
        feature_means=rng.randn(D).astype(np.float32))


def _x(n, seed=7):
    return np.random.RandomState(seed + n).randn(n, D).astype(np.float32)


def _assert_parity(ref, got, n):
    ref, got = np.asarray(ref), np.asarray(got)
    assert got.shape == ref.shape
    rel = np.abs(ref - got).max() / max(float(np.abs(ref).max()), 1.0)
    assert rel <= 1e-5, f"bucket {n}: sharded-apply delta {rel}"
    np.testing.assert_array_equal(
        np.argmax(ref, axis=1), np.argmax(got, axis=1))


# -- shard/unshard plumbing ---------------------------------------------------

def test_shard_rows_pads_to_shard_multiple(mesh8):
    w = np.arange(D * K, dtype=np.float32).reshape(D, K)
    placed = shard_rows(w, mesh8)
    shards = num_data_shards(mesh8)
    assert placed.shape[0] % shards == 0 and placed.shape[0] >= D
    # pad rows are zero, payload rows untouched
    host = np.asarray(placed)
    np.testing.assert_array_equal(host[:D], w)
    assert (host[D:] == 0).all()


@pytest.mark.parametrize("n", BUCKETS)
def test_shard_batch_roundtrip(mesh8, n):
    x = _x(n)
    xg, true_n = shard_batch(x, mesh8)
    assert true_n == n and xg.shape[0] % num_data_shards(mesh8) == 0
    np.testing.assert_array_equal(
        np.asarray(unshard_batch(xg, true_n, mesh8)), x)


# -- parity across buckets (the acceptance pin) ------------------------------

@pytest.mark.parametrize("n", BUCKETS)
def test_affine_parity_across_buckets(mesh8, n):
    """LinearMapper (scaler + intercept, d=37 indivisible by the 8
    shards): sharded apply == single-host apply <= 1e-5, same argmax."""
    model = _affine_model()
    x = _x(n)
    _assert_parity(model.apply(x), sharded_apply(model, x, mesh8), n)


def test_affine_parity_without_scaler(mesh8):
    model = _affine_model(seed=3, scaled=False)
    x = _x(50)
    _assert_parity(model.apply(x), sharded_apply(model, x, mesh8), 50)


@pytest.mark.parametrize("n", BUCKETS)
def test_block_parity_uneven_blocks(mesh8, n):
    """BlockLinearMapper with a ragged last block (16/16/5 over d=37),
    feature means + intercept: the one-block-at-a-time gather body
    must match the concatenated single-host GEMM."""
    model = _block_model()
    x = _x(n, seed=11)
    _assert_parity(model.apply(x), sharded_apply(model, x, mesh8), n)


def test_quantized_mapper_batch_only_parity(mesh8):
    """Quantized mappers keep the fused dequant program — only the
    batch shards. Sharded output must equal the mapper's own quantized
    apply EXACTLY (same program, same params, just a sharded batch)."""
    rng = np.random.RandomState(5)
    model = LinearMapper(rng.randn(D, K).astype(np.float32),
                         intercept=rng.randn(K).astype(np.float32),
                         weight_dtype="bf16")
    x = _x(13)
    np.testing.assert_allclose(
        np.asarray(sharded_apply(model, x, mesh8)),
        np.asarray(model.apply(x)), rtol=0, atol=0)


def test_chain_parity_fused_featurize(mesh8):
    """A fused featurize chain rides batch sharding: GSPMD partitions
    the one param-threaded program, parity holds at the same bar."""
    rng = np.random.RandomState(9)
    scaler = StandardScalerModel(rng.randn(D).astype(np.float32),
                                 (0.5 + rng.rand(D)).astype(np.float32))
    mapper = LinearMapper(rng.randn(D, K).astype(np.float32),
                          intercept=rng.randn(K).astype(np.float32))
    fused = fused_transformer([scaler, mapper])
    x = _x(50, seed=21)
    ref = mapper.apply(scaler.apply(x))
    _assert_parity(ref, sharded_chain_apply(fused, x, mesh8), 50)


def test_single_vs_eight_shard_mesh_parity():
    """The same model applied on a 1-device mesh and the 8-device mesh
    agrees <= 1e-5 with identical argmax — the shard count changes only
    the f32 summation layout, never the math."""
    model = _affine_model(seed=13)
    x = _x(64, seed=17)
    with mesh_scope(make_mesh(jax.devices()[:1])) as m1:
        out1 = np.asarray(sharded_apply(model, x, m1))
    with mesh_scope(make_mesh(jax.devices()[:8])) as m8:
        out8 = np.asarray(sharded_apply(model, x, m8))
    _assert_parity(out1, out8, 64)


# -- compile discipline -------------------------------------------------------

def test_programs_cached_per_mesh_and_static_dims(mesh8):
    """Refits of the same shapes reuse the shard_map program: params
    ride as arguments (the ``_affine_apply_batch`` content-free
    discipline), so repeated applies and NEW model instances with the
    same static dims add no ``_PROGRAMS`` entries — which is what
    keeps the serving warmup fence clean across refits."""
    model = _affine_model(seed=23)
    x = _x(8)
    sharded_apply(model, x, mesh8)
    assert (mesh8, "affine", D) in spmd_apply._PROGRAMS
    after_first = len(spmd_apply._PROGRAMS)
    # same instance, new bucket: row count is not a static dim
    sharded_apply(model, _x(64), mesh8)
    # a refit (new instance, same shapes) reuses the program
    sharded_apply(_affine_model(seed=29), x, mesh8)
    assert len(spmd_apply._PROGRAMS) == after_first
    # the block flavor keys on its bounds, not the model instance
    blk = _block_model(seed=31)
    sharded_apply(blk, x, mesh8)
    assert (mesh8, "block", tuple(blk._block_bounds())) \
        in spmd_apply._PROGRAMS
    n_with_block = len(spmd_apply._PROGRAMS)
    sharded_apply(_block_model(seed=37), _x(13), mesh8)
    assert len(spmd_apply._PROGRAMS) == n_with_block


def test_sharded_params_cached_on_model(mesh8):
    """The at-rest placement is cached per (model, mesh) under a
    ``_jit_`` attribute (pickling strips it); a second apply reuses
    the placed shards instead of re-transferring."""
    model = _affine_model(seed=41)
    sharded_apply(model, _x(8), mesh8)
    cached = model.__dict__["_jit_sharded_params"]
    assert cached[0] is mesh8
    sharded_apply(model, _x(13), mesh8)
    assert model.__dict__["_jit_sharded_params"] is cached
