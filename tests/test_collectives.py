"""Communication-shape assertions on the virtual 8-device mesh
(VERDICT r3 #5): compile — don't run — the BCD, TSQR, and weighted
block solver programs and assert the partitioned HLO contains the
EXPECTED collectives with the expected byte volumes. This is the
replacement for the visibility the reference got from the Spark UI's
shuffle accounting (SURVEY.md section 2.14): a silent
replicate-everything regression (e.g. a lost sharding constraint
all-gathering the full feature matrix to every device) passes every
numeric test but fails here on bytes.

Reference communication model being pinned: one treeReduce of a
(bs, bs) Gram + a (bs, k) cross-product per block step
(BlockLinearMapper.scala:234-240), one R-factor gather for TSQR
(mlmatrix TSQR.qrR), per-class-chunk statistics reductions for the
weighted solver (BlockWeightedLeastSquares.scala:102-320).
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.ops import linalg
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    mesh_scope,
)

_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_COLL_KINDS = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
               "collective-permute")


def _component_bytes(segment: str):
    """Bytes of every typed shape token in an HLO result segment — one
    entry per tuple component for fused collectives like
    ``(f32[32,32], f32[32,8]) all-reduce(...)``."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in _DTYPE_BYTES:
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        out.append(count * _DTYPE_BYTES[dtype])
    return out


def collectives_of(compiled_text: str):
    """[(kind, [component_bytes...], line)] for every collective
    instruction — including async ``-start`` forms (``-done`` halves
    carry no new transfer and are skipped via the lhs partition); XLA
    may fuse several logical reductions into one tuple-shaped op, hence
    bytes per component."""
    out = []
    for line in compiled_text.splitlines():
        for kind in _COLL_KINDS:
            marker = f" {kind}("
            start_marker = f" {kind}-start("
            if marker in line or start_marker in line:
                lhs, _, _ = line.partition(
                    marker if marker in line else start_marker)
                _, _, result = lhs.partition("=")
                out.append((kind, _component_bytes(result), line.strip()))
                break
    return out


def _compiled(fn, *args, **kw):
    return fn.lower(*args, **kw).compile().as_text()


@pytest.fixture
def mesh8_flat():
    with mesh_scope(make_mesh(jax.devices()[:8])) as m:
        yield m


def test_bcd_collectives_are_blocksized(mesh8_flat):
    """Scan BCD on an 8-way row-sharded design matrix: the ONLY
    collectives are all-reduces of the (bs, bs) Gram and the (bs, k)
    cross-product — never a gather of the (n, bs) blocks."""
    mesh = mesh8_flat
    n, bs, B, k = 2048, 32, 4, 8
    shard = NamedSharding(mesh, P(DATA_AXIS, None))
    blocks = tuple(jax.ShapeDtypeStruct((n, bs), jnp.float32, sharding=shard)
                   for _ in range(B))
    Y = jax.ShapeDtypeStruct((n, k), jnp.float32, sharding=shard)
    lam = jax.ShapeDtypeStruct((), jnp.float32)
    fn = jax.jit(linalg.bcd_core, static_argnames=("num_passes",))
    colls = collectives_of(_compiled(fn, blocks, Y, lam, num_passes=2))

    assert colls, "no collectives at all: the solve stopped being sharded"
    gram_bytes = bs * bs * 4
    cross_bytes = bs * k * 4
    legit = {gram_bytes, cross_bytes}
    sizes = set()
    for kind, comps, line in colls:
        assert kind == "all-reduce", (kind, line)
        for nbytes in comps:
            assert nbytes in legit, (
                f"unexpected all-reduce component of {nbytes} B "
                f"(legit: {legit}): {line}")
            sizes.add(nbytes)
    assert gram_bytes in sizes and cross_bytes in sizes, sizes
    # a replicate-everything regression would gather a full (n, bs)
    # block: 2048*32*4 = 256 KiB — two orders above the legit sizes


def test_unrolled_bcd_collectives_match_scan(mesh8_flat):
    """The 2-block unrolled body (below the scan gate) pins the same
    communication shape: per-block Gram + cross all-reduces only."""
    mesh = mesh8_flat
    n, bs, k = 2048, 64, 16
    shard = NamedSharding(mesh, P(DATA_AXIS, None))
    blocks = tuple(jax.ShapeDtypeStruct((n, bs), jnp.float32, sharding=shard)
                   for _ in range(2))
    Y = jax.ShapeDtypeStruct((n, k), jnp.float32, sharding=shard)
    lam = jax.ShapeDtypeStruct((), jnp.float32)
    fn = jax.jit(linalg.bcd_core, static_argnames=("num_passes",))
    colls = collectives_of(_compiled(fn, blocks, Y, lam, num_passes=1))
    assert colls
    legit = {bs * bs * 4, bs * k * 4}
    for kind, comps, line in colls:
        assert kind == "all-reduce", (kind, line)
        for nbytes in comps:
            assert nbytes in legit, (nbytes, line)


def test_tsqr_gathers_r_factors_only(mesh8_flat):
    """TSQR's single collective is the all-gather of the per-shard
    (d, d) R factors — shards² x d² bytes — NOT the (n, d) matrix."""
    mesh = mesh8_flat
    n, d = 4096, 32
    shard = NamedSharding(mesh, P(DATA_AXIS, None))
    A = jax.ShapeDtypeStruct((n, d), jnp.float32, sharding=shard)
    colls = collectives_of(_compiled(linalg._tsqr_run(mesh), A))
    gathers = [c for c in colls if c[0] == "all-gather"]
    assert gathers, "TSQR lost its R-factor gather"
    nshards = mesh.shape[DATA_AXIS]
    r_stack_bytes = nshards * d * d * 4  # gathered result on each shard
    full_matrix_bytes = n * d * 4
    for kind, comps, line in colls:
        for nbytes in comps:
            assert nbytes <= r_stack_bytes, (nbytes, line)
            assert nbytes < full_matrix_bytes // 4, (
                f"collective moved a full-matrix-scale buffer: {line}")
    assert any(r_stack_bytes in comps for _, comps, _ in gathers), (
        [c[1] for c in gathers])


@pytest.mark.parametrize("solver,S,dfull,d_b,bound_div", [
    # cholesky's regime: many slots per class, narrow blocks — the
    # per-class (d_b, d_b) covariance reductions are tiny next to the
    # (C, S, dfull) class-major feature tensor
    ("cholesky", 512, 64, 32, 8),
    # woodbury's regime (the ImageNet FV shape, scaled): few slots per
    # class, wide blocks ((S+2)*2 <= d_b, the auto gate) — legit
    # traffic is the per-class rank factors and (S+2)^2 capacitance
    # systems, bounded by the BLOCK slice (dfull/d_b of the tensor)
    ("woodbury", 32, 1024, 128, 4),
])
def test_weighted_solver_collectives_bounded(solver, S, dfull, d_b,
                                             bound_div):
    """The class-parallel weighted block solve on a ('model' x 'data')
    mesh reduces per-class/chunk statistics — nothing within
    ``bound_div``x of the class-major feature tensor may ride a
    collective (each solver probed in the regime its auto gate selects
    it for; outside its regime the other one wins by design)."""
    mesh = make_mesh(jax.devices()[:8], data=4, model=2)
    with mesh_scope(mesh):
        from keystone_tpu.nodes.learning import block_weighted as bw

        C_pad, k = 16, 16
        cm = NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS, None))
        m2 = NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS))
        rep = NamedSharding(mesh, P())
        args = (
            jax.ShapeDtypeStruct((C_pad, S, dfull), jnp.float32, sharding=cm),
            jax.ShapeDtypeStruct((C_pad, S, k), jnp.float32, sharding=cm),
            jax.ShapeDtypeStruct((d_b, k), jnp.float32, sharding=rep),
            jax.ShapeDtypeStruct((C_pad, S), jnp.float32, sharding=m2),
            jax.ShapeDtypeStruct((C_pad,), jnp.float32, sharding=rep),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.float32),
            None,
            None,
        )
        smodel = mesh.shape[MODEL_AXIS]
        chunk = bw._class_chunk(
            C_pad, d_b, smodel, S=S if solver == "woodbury" else 0)
        nch = -(-C_pad // chunk)
        chunk = -(-(-(-C_pad // nch)) // smodel) * smodel
        if solver == "woodbury":
            assert (S + 2) * 2 <= d_b, "shape outside woodbury's gate"
        feature_tensor_bytes = C_pad * S * dfull * 4
        colls = collectives_of(_compiled(
            bw._block_pass_full, *args,
            d_b=d_b, n=4000, k=k, chunk=chunk, nch=nch,
            solver=solver, with_stats=True))
        assert colls, f"{solver}: solve stopped being sharded"
        assert any(kind == "all-reduce" for kind, _, _ in colls), solver
        worst = max(max(comps) for _, comps, _ in colls if comps)
        assert worst <= feature_tensor_bytes // bound_div, (
            f"{solver}: a collective moved {worst} B — within "
            f"{bound_div}x of the full {feature_tensor_bytes} B "
            "class-major feature tensor; replicate-everything regression")
