"""Mesh-native weighted solver tests: the flagship solvers must keep the
feature matrix sharded (no host collect — the round-1 implementation's
``ds.numpy()`` is banned here by monkeypatch) and must produce correct
solutions when collectives cross BOTH mesh axes (classes over ``model``,
within-class slots over ``data``)."""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.nodes.learning.block_weighted import (
    BlockWeightedLeastSquaresEstimator,
    _class_major_perm,
    _to_class_major,
)
from keystone_tpu.nodes.learning.per_class_weighted import (
    PerClassWeightedLeastSquaresEstimator,
)
from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    mesh_scope,
)


def make_problem(n=240, d=12, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, k, n)
    L = -np.ones((n, k), np.float32)
    L[np.arange(n), y] = 1.0
    return X, L, y


def weighted_gradient(X, L, W, b, lam, mw):
    X = X.astype(np.float64)
    L = L.astype(np.float64)
    n, k = L.shape
    y = np.argmax(L, axis=1)
    counts = np.bincount(y, minlength=k)
    neg = (1.0 - mw) / n
    wts = np.full((n, k), neg)
    wts[np.arange(n), y] = neg + mw / counts[y]
    resid = X @ W + b - L
    return X.T @ (resid * wts) + lam * W


@pytest.mark.parametrize(
    "est_cls",
    [BlockWeightedLeastSquaresEstimator, PerClassWeightedLeastSquaresEstimator],
)
def test_weighted_fit_never_collects_features(mesh8, est_cls, monkeypatch):
    """The VERDICT round-1 finding: _fit must not gather the feature
    matrix to host. numpy()/collect() on the feature dataset raise here,
    so the fit passes only if X stays on the mesh end to end."""
    X, L, y = make_problem(n=160, d=12, k=4, seed=1)
    ds = ArrayDataset.from_numpy(X)
    labels = ArrayDataset.from_numpy(L)

    def _banned(self, *a, **k):
        raise AssertionError("feature dataset was collected to host")

    monkeypatch.setattr(ArrayDataset, "numpy", _banned)
    monkeypatch.setattr(HostDataset, "collect", _banned, raising=False)

    model = est_cls(
        block_size=6, num_iter=5, lam=0.1, mixture_weight=0.3
    )._fit(ds, labels)
    g = weighted_gradient(
        X, L, np.asarray(model.weights, np.float64),
        np.asarray(model.intercept, np.float64), 0.1, 0.3,
    )
    assert np.linalg.norm(g.ravel()) < 5e-2


def test_block_weighted_on_2d_mesh_crosses_both_axes():
    """data=4 x model=2 mesh: per-class Grams contract the 'data'-sharded
    slot axis (psum over data) while classes parallelize over 'model'.
    The solution must match the single-axis mesh run and have ~zero
    objective gradient."""
    devs = jax.devices()[:8]
    X, L, y = make_problem(n=200, d=10, k=4, seed=2)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=5, num_iter=6, lam=0.2, mixture_weight=0.4
    )
    with mesh_scope(make_mesh(devs, data=8, model=1)):
        m1 = est.fit_arrays(X, L)
    with mesh_scope(make_mesh(devs, data=4, model=2)):
        m2 = est.fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(m1.weights), np.asarray(m2.weights), rtol=1e-4, atol=1e-4
    )
    g = weighted_gradient(
        X, L, np.asarray(m2.weights, np.float64),
        np.asarray(m2.intercept, np.float64), 0.2, 0.4,
    )
    assert np.linalg.norm(g.ravel()) < 5e-2


def test_class_major_layout_sharded_over_both_axes():
    """The (C_pad, S, d) class-major tensor really is distributed: classes
    over 'model', slots over 'data' — each device holds a (C_pad/2, S/4, d)
    brick, never the full tensor."""
    devs = jax.devices()[:8]
    mesh = make_mesh(devs, data=4, model=2)
    X, L, y = make_problem(n=96, d=6, k=4, seed=3)
    class_idx = y.astype(np.int32)
    counts = np.bincount(class_idx, minlength=4).astype(np.int64)
    perm, C_pad, S = _class_major_perm(class_idx, counts, 4, mesh)
    assert C_pad % 2 == 0 and S % 4 == 0

    with mesh_scope(mesh):
        Xj = jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, None)))
        perm_j = jax.device_put(
            perm, NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS))
        )
        cm_sharding = NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS, None))
        Xcm = _to_class_major(Xj, perm_j, out_sharding=cm_sharding)

    assert Xcm.shape == (C_pad, S, 6)
    shard_shapes = {s.data.shape for s in Xcm.addressable_shards}
    assert shard_shapes == {(C_pad // 2, S // 4, 6)}
    # content: row s of class c is the s-th example of class c
    dense = np.asarray(Xcm)
    for c in range(4):
        rows = X[class_idx == c]
        np.testing.assert_allclose(dense[c, : len(rows)], rows, rtol=1e-6)
        np.testing.assert_array_equal(dense[c, len(rows):], 0.0)


def test_perm_out_of_bounds_fills_zero():
    mesh = make_mesh(jax.devices()[:8], data=8, model=1)
    class_idx = np.array([0, 0, 1], np.int32)
    counts = np.array([2, 1], np.int64)
    perm, C_pad, S = _class_major_perm(class_idx, counts, 2, mesh)
    X = np.arange(12, dtype=np.float32).reshape(3, 4) + 1.0
    with mesh_scope(mesh):
        Xcm = np.asarray(_to_class_major(jax.numpy.asarray(X), perm))
    np.testing.assert_allclose(Xcm[0, 0], X[0])
    np.testing.assert_allclose(Xcm[0, 1], X[1])
    np.testing.assert_allclose(Xcm[1, 0], X[2])
    assert (Xcm[0, 2:] == 0).all() and (Xcm[1, 1:] == 0).all()


def test_class_chunking_matches_unchunked(mesh8, monkeypatch):
    """The memory-bounded class-chunked solve must equal the one-shot
    batched solve (chunk forced down to the model-axis size)."""
    import keystone_tpu.nodes.learning.block_weighted as bw

    X, L, y = make_problem(n=160, d=12, k=6, seed=4)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=6, num_iter=4, lam=0.15, mixture_weight=0.35
    )
    m_full = est.fit_arrays(X, L)
    monkeypatch.setattr(bw, "_CLASS_CHUNK_BYTES", 1)  # => chunk == smodel
    m_chunked = est.fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(m_full.weights), np.asarray(m_chunked.weights),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(m_full.intercept), np.asarray(m_chunked.intercept),
        rtol=1e-5, atol=1e-5,
    )
