"""Deliberately DIVERGENT SPMD worker — the hazard class the
``collective-divergence`` pass (``analysis/spmd.py``) exists to catch,
reproduced for real: every host enters a matched world barrier, then
host 0 takes a barrier its peers never reach. Host 0 wedges in the
unmatched collective (the silent gang-schedule hang — no error, no
progress), its peers finish and exit, and the
:class:`~keystone_tpu.parallel.distributed.DryrunWorld` launcher's
gang grace reaps the wedged member.

Dual-use by the test suite:

* ``tests/test_spmd_passes.py`` PARSES this file and asserts the
  static pass flags the ``if process_index() == 0:`` barrier;
* the ``@slow`` divergence test in ``tests/test_elastic.py`` LAUNCHES
  it under a ``DryrunWorld`` and asserts the dynamic classification:
  the divergent host never prints its done line and is killed by gang
  grace, the straight host exits 0.

Usage (the launcher appends the positionals)::

    python tests/spmd_divergent_worker.py <process_id> <num_processes> \
        <coordinator_port>
"""
import os
import sys
import time


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")

    from keystone_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(f"127.0.0.1:{port}", nproc, pid)
    from jax.experimental.multihost_utils import sync_global_devices

    # matched on every host: proves the world is up and collectives
    # work before the deliberate divergence below
    sync_global_devices("keystone-diverge-enter")
    print(f"DIVERGE_ENTER pid={pid}", flush=True)

    if jax.process_index() == 0:
        # THE BUG UNDER TEST (never copy this shape): a collective
        # under host-divergent control flow. Peers never match it, so
        # this host wedges here until the launcher's gang grace reaps
        # it — exactly what `collective-divergence` flags statically.
        sync_global_devices("keystone-diverge-host0-only")

    # give the divergent host time to be firmly inside the unmatched
    # collective before this host's exit starts the gang-grace clock
    if pid != 0:
        time.sleep(1.0)
    print(f"DIVERGE_DONE pid={pid}", flush=True)
    sys.stdout.flush()
    # hard exit, like dryrun_worker's failure path: a normal
    # interpreter exit wedges in the distributed runtime's teardown
    # (the coordinator-client shutdown waits on the peer that is stuck
    # in the collective this test deliberately diverged), and a worker
    # that neither exits nor progresses would defeat the launcher's
    # dead-member detection this test exists to demonstrate
    os._exit(0)


if __name__ == "__main__":
    main()
