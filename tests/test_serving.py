"""The serving plane (``keystone_tpu/serving`` + ``python -m
keystone_tpu serve``): warm device-resident executables, pad-to-bucket
micro-batching behind the slot-gated bounded queue, HBM-budgeted
multi-model residency, and the funnel wiring (per-model latency/fill
histograms, drift scoring, the readiness-gated scrape surface).

The acceptance pins (ISSUE 15):

* load test — >= 3 pipelines hot under an ASSERTED HBM budget, with
  the over-budget admission REFUSED (and nothing mutated);
* eviction + readmission round-trips to bit-identical predictions;
* zero steady-state recompiles per bucket, asserted via the compile
  observatory fence (``compile.unexpected_total`` delta == 0 across a
  multi-shape request storm);
* the admission-vs-eviction interleaving, pinned under the
  deterministic scheduler (``tests/sched.py``) on the real TracedLock
  yield points.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.parallel.dataset import ArrayDataset, bucketed_dataset
from keystone_tpu.parallel.mesh import get_mesh, num_data_shards
from keystone_tpu.serving import (
    AdmissionError,
    BucketPolicy,
    MicroBatcher,
    ModelCharge,
    ModelNotAdmitted,
    QueueFullError,
    ServingPlane,
    model_charge,
)


def _make_fitted(d, k, seed=0, n=96, **est_kw):
    r = np.random.RandomState(seed)
    X = r.rand(n, d).astype(np.float32)
    Y = r.rand(n, k).astype(np.float32)
    fitted = LinearMapEstimator(lam=1e-3, **est_kw).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()
    return fitted, X, Y


def _sample(d):
    return jax.ShapeDtypeStruct((d,), np.float32)


@pytest.fixture
def plane_factory():
    planes = []

    def make(**kw):
        kw.setdefault("max_batch", 16)
        plane = ServingPlane(**kw)
        planes.append(plane)
        return plane

    yield make
    for plane in planes:
        plane.close()


# -- bucket policy & pad-to-bucket -------------------------------------------

def test_bucket_policy_ladder_is_shard_rounded():
    policy = BucketPolicy(max_batch=64)
    rows = policy.rows(8)
    assert rows == (8, 16, 32, 64)
    assert all(b % 8 == 0 for b in rows)
    # non-power-of-two ceiling is included exactly (shard-rounded)
    assert BucketPolicy(max_batch=48).rows(8)[-1] == 48
    assert BucketPolicy(max_batch=5).rows(1) == (1, 2, 4, 5)


def test_bucket_for_picks_smallest_fit_and_refuses_overflow():
    policy = BucketPolicy(max_batch=64)
    assert policy.bucket_for(1, 8) == 8
    assert policy.bucket_for(9, 8) == 16
    assert policy.bucket_for(64, 8) == 64
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        policy.bucket_for(65, 8)


def test_bucketed_dataset_pads_to_bucket_with_true_n():
    X = np.arange(5 * 3, dtype=np.float32).reshape(5, 3)
    ds = bucketed_dataset(X, 5, 16)
    assert ds.padded_n == 16 and ds.n == 5
    np.testing.assert_array_equal(ds.numpy(), X)  # pad stripped
    assert bool(np.asarray(ds.mask).sum() == 5)
    with pytest.raises(ValueError, match="multiple of the mesh"):
        bucketed_dataset(X, 5, 10)  # 10 is not a multiple of 8 shards
    with pytest.raises(ValueError, match="do not fit"):
        bucketed_dataset(X, 5, 0)


# -- admission charges --------------------------------------------------------

def test_model_charge_uses_static_plan():
    fitted, _, _ = _make_fitted(32, 4)
    charge = model_charge(fitted, _sample(32), bucket_rows=16)
    assert charge.source == "static-plan"
    # fitted linear model: W (32,4) + intercept (4,) + scaler mean (32,)
    assert charge.model_nbytes >= 4 * (32 * 4 + 4 + 32)
    assert charge.item_nbytes > 0
    assert charge.total_nbytes() == pytest.approx(
        charge.model_nbytes + 16 * charge.item_nbytes)


def test_model_charge_per_host_arithmetic():
    """``data_shards > 1`` turns total_nbytes into the PER-HOST charge
    (ISSUE 18): the shardable fitted state divides across the data
    axis, ONE gather transient is added, and the activation is this
    host's row shard of the bucket (ceil division)."""
    c = ModelCharge(model_nbytes=1000.0, item_nbytes=4.0, bucket_rows=16,
                    data_shards=8, shardable_nbytes=800.0,
                    gather_nbytes=100.0)
    assert c.activation_nbytes() == pytest.approx(4.0 * 2)  # ceil(16/8)
    assert c.total_nbytes() == pytest.approx(
        (1000.0 - 800.0) + 800.0 / 8 + 100.0 + 8.0)
    # the replicated (shards=1) charge ignores the gather transient and
    # keeps the full model plus the full bucket's activation
    c1 = ModelCharge(model_nbytes=1000.0, item_nbytes=4.0, bucket_rows=16,
                     shardable_nbytes=800.0, gather_nbytes=100.0)
    assert c1.total_nbytes() == pytest.approx(1000.0 + 16 * 4.0)


def _make_block_fitted(d, k, block_size, seed=0, n=96):
    from keystone_tpu.nodes.learning.linear import (
        BlockLeastSquaresEstimator,
    )

    r = np.random.RandomState(seed)
    X = r.rand(n, d).astype(np.float32)
    Y = r.rand(n, k).astype(np.float32)
    return BlockLeastSquaresEstimator(
        block_size, num_iter=2, lam=1e-3).with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)).fit()


def test_sharded_charge_admits_model_exceeding_one_hosts_budget(
        plane_factory):
    """Acceptance (ISSUE 18): a BlockLinearMapper whose total
    ``model_nbytes`` exceeds ONE host's budget is admitted on a
    ``data_shards=8`` plane — the at-rest state divides across the
    data axis and the gather transient is one block, not the whole
    matrix — and the SAME budget refuses it on a replicated plane."""
    fitted = _make_block_fitted(256, 16, block_size=64)
    charge1 = model_charge(fitted, _sample(256), 16)
    charge8 = model_charge(fitted, _sample(256), 16, data_shards=8)
    assert charge8.data_shards == 8 and charge8.shardable_nbytes > 0
    # one block gathers at a time: the transient is smaller than the
    # at-rest shardable state it reassembles slices of
    assert 0 < charge8.gather_nbytes < charge8.shardable_nbytes
    assert charge8.total_nbytes() < charge1.total_nbytes()
    # a budget BETWEEN the per-host and the replicated charge: too
    # small for the whole model, roomy for one host's shard
    budget = (charge8.total_nbytes() + charge1.total_nbytes()) / 2
    assert charge8.model_nbytes > budget

    replicated = plane_factory(hbm_budget=budget)
    replicated.start()
    with pytest.raises(AdmissionError, match="refusing"):
        replicated.admit("blk", fitted, _sample(256))

    sharded = plane_factory(hbm_budget=budget, data_shards=8)
    sharded.start()
    sharded.admit("blk", fitted, _sample(256))
    state = sharded.state()
    assert [m["name"] for m in state["models"]] == ["blk"]
    assert state["hbm_charged_bytes"] <= budget


# -- the load test (acceptance) ----------------------------------------------

def test_three_models_hot_under_asserted_budget(plane_factory):
    """>= 3 pipelines warm under an asserted HBM budget; the 4th
    (over-budget) admission is REFUSED without mutating the plane;
    eviction + readmission round-trips bit-identical; zero steady-state
    recompiles across every bucket of every model."""
    dims = [(24, 3, 1), (32, 4, 2), (40, 5, 3)]
    models = {f"m{d}": _make_fitted(d, k, seed) for d, k, seed in dims}
    # the over-budget model AND the reference outputs are built before
    # the steady-state fence arms: the fence is process-global, so a
    # mid-test fit would honestly count as an unexpected compile
    big, _, _ = _make_fitted(512, 64, seed=9)
    sizes = (1, 3, 7, 8, 9, 15, 16)
    refs = {
        (name, n): fitted.apply(ArrayDataset.from_numpy(X[:n])).numpy()
        for name, (fitted, X, _) in models.items() for n in (*sizes, 6)}
    charges = {
        name: model_charge(fitted, _sample(fitted_x.shape[1]), 16,
                           name=name)
        for name, (fitted, fitted_x, _) in models.items()}
    budget = sum(c.total_nbytes() for c in charges.values()) + 1024
    plane = plane_factory(hbm_budget=budget, queue_depth=64)
    plane.start()
    for name, (fitted, X, _) in models.items():
        plane.admit(name, fitted, _sample(X.shape[1]))
    state = plane.state()
    assert state["ready"] and len(state["models"]) == 3
    assert state["hbm_charged_bytes"] <= budget  # the asserted budget

    # over-budget admission refused, nothing mutated
    reg = MetricsRegistry.get_or_create()
    rejected0 = reg.counter("serving.admission_rejected_total").value
    with pytest.raises(AdmissionError, match="refusing"):
        plane.admit("big", big, _sample(512))
    assert reg.counter(
        "serving.admission_rejected_total").value == rejected0 + 1
    after = plane.state()
    assert sorted(m["name"] for m in after["models"]) == sorted(models)
    assert after["hbm_charged_bytes"] == state["hbm_charged_bytes"]

    # zero steady-state recompiles: every model, every bucket, many n
    u0 = plane.unexpected_recompiles()
    outputs = {}
    for name, (fitted, X, _) in models.items():
        for n in sizes:
            out = plane.predict(name, X[:n])
            np.testing.assert_allclose(out, refs[(name, n)],
                                       rtol=1e-5, atol=1e-5)
        outputs[name] = plane.predict(name, X[:6])
    assert plane.unexpected_recompiles() - u0 == 0, (
        "steady-state serving recompiled — the pad-to-bucket warmup "
        "missed a program")

    # eviction + readmission round-trips bit-identical
    victim = "m32"
    plane.evict(victim)
    with pytest.raises(ModelNotAdmitted):
        plane.predict(victim, models[victim][1][:2])
    plane.readmit(victim)
    again = plane.predict(victim, models[victim][1][:6])
    assert np.array_equal(outputs[victim], again), (
        "evicted+readmitted model must serve bit-identical predictions")
    final = plane.state()
    assert victim not in final["evicted"], (
        "a readmitted model must leave the evicted set (stale blob "
        "retention + double-listing in /models)")


def test_admission_evicts_lowest_value_resident(plane_factory):
    """When space runs out, admission evicts by LRU-with-cost: the
    model with the lowest observed-QPS x recompute-cost value goes
    first, and the admission then succeeds."""
    a, aX, _ = _make_fitted(24, 3, seed=1)
    b, bX, _ = _make_fitted(24, 3, seed=2)
    c, cX, _ = _make_fitted(24, 3, seed=3)
    ca = model_charge(a, _sample(24), 16)
    cb = model_charge(b, _sample(24), 16)
    # equal-dim models: room for exactly two of the three
    budget = ca.total_nbytes() + cb.total_nbytes() + 64
    plane = plane_factory(hbm_budget=budget)
    plane.start()
    plane.admit("a", a, _sample(24))
    plane.admit("b", b, _sample(24))
    for _ in range(4):  # give b observed QPS (a stays idle: value 0)
        plane.predict("b", bX[:4])
    plane.admit("c", c, _sample(24))
    state = plane.state()
    names = sorted(m["name"] for m in state["models"])
    assert "c" in names and "b" in names and "a" not in names
    assert state["evicted"] == ["a"]
    assert state["hbm_charged_bytes"] <= budget


def test_refused_admission_leaves_existing_models_serving(plane_factory):
    fitted, X, _ = _make_fitted(24, 3, seed=5)
    charge = model_charge(fitted, _sample(24), 16)
    plane = plane_factory(hbm_budget=charge.total_nbytes() + 64)
    plane.start()
    plane.admit("only", fitted, _sample(24))
    big, _, _ = _make_fitted(256, 32, seed=6)
    with pytest.raises(AdmissionError):
        plane.admit("big", big, _sample(256))
    out = plane.predict("only", X[:3])
    assert out.shape == (3, 3)


def test_unpicklable_pipeline_admission_names_the_constraint(
        plane_factory):
    """A lambda-bearing pipeline cannot round-trip through the
    canonical pickle; admission must say WHY instead of leaking a raw
    PicklingError (found by the verify drive)."""
    from keystone_tpu.workflow.transformer import transformer

    fitted, X, _ = _make_fitted(16, 3, seed=6)
    pipe = transformer(lambda x: x * 2.0).to_pipeline().and_then(
        fitted.to_pipeline())
    plane = plane_factory()
    with pytest.raises(TypeError, match="not picklable"):
        plane.admit("bad", pipe, _sample(16))


# -- quantized predict --------------------------------------------------------

def test_default_weight_dtype_quantizes_and_round_trips(plane_factory):
    fitted, X, _ = _make_fitted(32, 4, seed=7)
    plane = plane_factory(default_weight_dtype="bf16")
    plane.start()
    entry = plane.admit("q", fitted, _sample(32))
    assert entry.weight_dtype == "bf16"
    quantized = plane.predict("q", X[:8])
    f32 = fitted.apply(ArrayDataset.from_numpy(X[:8])).numpy()
    # bf16 weights: close but not equal to the f32 path
    np.testing.assert_allclose(quantized, f32, rtol=0.05, atol=0.05)
    plane.evict("q")
    plane.readmit("q")
    assert np.array_equal(quantized, plane.predict("q", X[:8])), (
        "re-quantization after readmission must be deterministic")


def test_explicit_model_weight_dtype_wins_over_plane_default(
        plane_factory):
    fitted, X, _ = _make_fitted(32, 4, seed=8, weight_dtype="int8")
    plane = plane_factory(default_weight_dtype="bf16")
    plane.start()
    entry = plane.admit("m", fitted, _sample(32))
    ops = [entry.fitted.graph.get_operator(n)
           for n in entry.fitted.graph.nodes]
    dtypes = {getattr(op, "weight_dtype", None) for op in ops
              if hasattr(op, "weight_dtype")}
    assert dtypes == {"int8"}  # the fit-time choice survives admission


# -- micro-batcher ------------------------------------------------------------

def test_batcher_coalesces_same_model_fifo_for_others():
    batcher = MicroBatcher(queue_depth=16)
    futs = [batcher.submit("a", np.zeros((2, 4)), 2) for _ in range(3)]
    batcher.submit("b", np.zeros((1, 4)), 1)
    batcher.submit("a", np.zeros((2, 4)), 2)
    batch = batcher.take(max_rows=16)
    # oldest request's model wins; later same-model requests coalesce
    # around the interleaved b, which keeps its FIFO position
    assert [r.model for r in batch] == ["a"] * 4
    assert sum(r.n for r in batch) == 8
    nxt = batcher.take(max_rows=16)
    assert [r.model for r in nxt] == ["b"]
    batcher.done(len(batch) + len(nxt))
    assert len(futs) == 3  # futures are per-request handles


def test_batcher_respects_bucket_ceiling():
    batcher = MicroBatcher(queue_depth=16)
    for _ in range(5):
        batcher.submit("a", np.zeros((3, 2)), 3)
    batch = batcher.take(max_rows=8)
    assert sum(r.n for r in batch) <= 8 and len(batch) == 2
    assert batcher.depth() == 3  # overflow kept, FIFO intact
    batcher.done(len(batch))


def test_batcher_slot_gate_bounds_queue_and_rejects_fast():
    batcher = MicroBatcher(queue_depth=2, submit_timeout_s=0.05)
    reg = MetricsRegistry.get_or_create()
    rejected0 = reg.counter("serving.rejected_total").value
    batcher.submit("a", np.zeros((1, 2)), 1)
    batcher.submit("a", np.zeros((1, 2)), 1)
    with pytest.raises(QueueFullError):
        batcher.submit("a", np.zeros((1, 2)), 1)
    assert reg.counter("serving.rejected_total").value == rejected0 + 1
    taken = batcher.take(max_rows=8)
    batcher.done(len(taken))  # slots freed -> submit admits again
    batcher.submit("a", np.zeros((1, 2)), 1)


def test_batcher_close_drains_and_refuses():
    batcher = MicroBatcher(queue_depth=4)
    fut = batcher.submit("a", np.zeros((1, 2)), 1)
    drained = batcher.close()
    assert [r.future for r in drained] == [fut]
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit("a", np.zeros((1, 2)), 1)


def test_concurrent_submits_coalesce_into_batches(plane_factory):
    """Real threads + the worker: concurrent requests for one model
    coalesce (batches < requests) and every future resolves to its own
    rows."""
    fitted, X, _ = _make_fitted(24, 3, seed=11)
    plane = plane_factory(queue_depth=64)
    plane.start()
    plane.admit("m", fitted, _sample(24))
    reg = MetricsRegistry.get_or_create()
    req0 = reg.counter("serving.requests_total").value
    batch0 = reg.counter("serving.batches_total").value
    results = {}
    errors = []

    def client(i):
        try:
            results[i] = plane.predict("m", X[i:i + 2])
        except Exception as exc:  # noqa: BLE001 - recorded for assert
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    for i, out in results.items():
        ref = fitted.apply(ArrayDataset.from_numpy(X[i:i + 2])).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    served = reg.counter("serving.requests_total").value - req0
    batches = reg.counter("serving.batches_total").value - batch0
    assert served == 12
    assert batches <= served  # coalescing can only shrink the count
    assert reg.histogram("serving.batch_fill.m").count >= 1
    assert reg.histogram("serving.request_ms.m").count >= 12


# -- scripted admission-vs-eviction interleaving (tests/sched.py) ------------

def _interleaving_invariants(plane, budget):
    state = plane.state()
    assert state["hbm_charged_bytes"] <= budget
    assert "a" in state["evicted"]
    names = sorted(m["name"] for m in state["models"])
    assert "c" in names and "b" in names and "a" not in names


@pytest.mark.parametrize("schedule", [
    {"picks": ["admit-c", "evict-a", "admit-c", "evict-a"] * 40},
    {"seed": 0}, {"seed": 1}, {"seed": 2}, {"seed": 3}, {"seed": 4},
])
def test_admission_vs_eviction_interleaving(schedule, plane_factory):
    """An admission that must evict `a` races an explicit evict of
    `a`: under scripted AND seeded schedules on the real TracedLock
    yield points, exactly one eviction wins (the loser sees
    ModelNotAdmitted), the ledger never exceeds the budget, and the
    plane converges to {b, c} resident with `a` evicted once."""
    from tests.sched import DeterministicScheduler

    a, _, _ = _make_fitted(24, 3, seed=1)
    b, _, _ = _make_fitted(24, 3, seed=2)
    c, _, _ = _make_fitted(24, 3, seed=3)
    ca = model_charge(a, _sample(24), 16)
    cb = model_charge(b, _sample(24), 16)
    budget = ca.total_nbytes() + cb.total_nbytes() + 64
    plane = plane_factory(hbm_budget=budget, steady_fence=False)
    plane.admit("a", a, _sample(24))
    plane.admit("b", b, _sample(24))
    # touch b so LRU-with-cost prefers evicting the idle a
    plane.start()
    outcomes = {}

    def admit_c():
        plane.admit("c", c, _sample(24))

    def evict_a():
        try:
            plane.evict("a")
            outcomes["explicit-evict"] = "won"
        except ModelNotAdmitted:
            outcomes["explicit-evict"] = "lost"

    sched = DeterministicScheduler(**({"picks": schedule["picks"]}
                                      if "picks" in schedule
                                      else {"seed": schedule["seed"]}))
    sched.spawn(admit_c, name="admit-c")
    sched.spawn(evict_a, name="evict-a")
    with sched:
        sched.run()
    assert outcomes["explicit-evict"] in ("won", "lost")
    _interleaving_invariants(plane, budget)
    reg = MetricsRegistry.get_or_create()
    # exactly one eviction of `a` happened, whichever thread won
    assert reg.counter("serving.evictions_total").value >= 1


# -- readiness ----------------------------------------------------------------

def test_drift_scoring_is_warm_on_every_bucket(plane_factory):
    """Drift scoring compiles per (bucket, d) shape like the apply
    programs: a drift-enabled model serving a request that lands in a
    LARGER bucket than the smallest must not compile under the armed
    fence (review finding)."""
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    r = np.random.RandomState(1)
    X = r.rand(128, 24).astype(np.float32)
    Y = r.rand(128, 3).astype(np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=32,
                                         tag="serve-drift-buckets")
    model = fit_streaming(LinearMapEstimator(lam=1e-3), stream, Y)
    plane = plane_factory(drift_every=1)  # max_batch=16: buckets 8, 16
    plane.start()
    plane.admit("m", model, _sample(24))
    u0 = plane.unexpected_recompiles()
    plane.predict("m", X[:10])  # lands in bucket 16, not buckets[0]=8
    deadline = time.monotonic() + 10.0
    reg = MetricsRegistry.get_or_create()
    while time.monotonic() < deadline:  # wait for the async scoring
        if reg.snapshot()["gauges"].get("numerics.drift_score") \
                is not None:
            break
        time.sleep(0.02)
    assert plane.unexpected_recompiles() - u0 == 0


def test_startup_eviction_does_not_wedge_readiness(plane_factory):
    """expect_models counts COMPLETED admissions, not residents: a
    startup admission that evicts an earlier expected model must not
    leave /healthz at 503 forever (review finding)."""
    a, _, _ = _make_fitted(24, 3, seed=1)
    b, _, _ = _make_fitted(24, 3, seed=2)
    ca = model_charge(a, _sample(24), 16)
    plane = plane_factory(hbm_budget=ca.total_nbytes() + 64)
    plane.expect_models(2)
    plane.admit("a", a, _sample(24))
    assert not plane.ready()
    plane.admit("b", b, _sample(24))  # evicts a: only room for one
    state = plane.state()
    assert [m["name"] for m in state["models"]] == ["b"]
    assert state["evicted"] == ["a"]
    assert plane.ready(), (
        "both expected admissions completed their warmups — readiness "
        "must not require the evicted model to still be resident")


def test_ready_waits_for_expected_admissions(plane_factory):
    fitted, _, _ = _make_fitted(24, 3, seed=4)
    plane = plane_factory()
    plane.expect_models(2)
    assert not plane.ready()
    plane.admit("one", fitted, _sample(24))
    assert not plane.ready()  # one of two expected
    fitted2, _, _ = _make_fitted(24, 4, seed=5)
    plane.admit("two", fitted2, _sample(24))
    assert plane.ready()


def test_serve_metrics_ready_probe_gates_healthz():
    from keystone_tpu.observability.sampler import serve_metrics

    ready = {"v": False}
    server = serve_metrics(port=0, ready_probe=lambda: ready["v"])
    try:
        url = f"http://127.0.0.1:{server.server_port}/healthz"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 503
        assert exc.value.read() == b"warming\n"
        ready["v"] = True
        with urllib.request.urlopen(url) as rsp:
            assert rsp.status == 200
    finally:
        server.shutdown()


def test_serve_metrics_without_probe_keeps_liveness_semantics():
    from keystone_tpu.observability.sampler import serve_metrics

    server = serve_metrics(port=0)
    try:
        url = f"http://127.0.0.1:{server.server_port}/healthz"
        with urllib.request.urlopen(url) as rsp:
            assert rsp.status == 200
    finally:
        server.shutdown()


def test_serve_metrics_raising_probe_fails_closed():
    from keystone_tpu.observability.sampler import serve_metrics

    def broken():
        raise RuntimeError("probe exploded")

    server = serve_metrics(port=0, ready_probe=broken)
    try:
        url = f"http://127.0.0.1:{server.server_port}/healthz"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 503
    finally:
        server.shutdown()


# -- HTTP data plane ----------------------------------------------------------

def test_http_predict_two_models_and_error_statuses(plane_factory):
    from keystone_tpu.serving.http import serve

    f1, X1, _ = _make_fitted(24, 3, seed=1)
    f2, X2, _ = _make_fitted(32, 4, seed=2)
    plane = plane_factory(queue_depth=32)
    plane.start()
    plane.admit("alpha", f1, _sample(24))
    plane.admit("beta", f2, _sample(32))
    server = serve(plane)
    base = f"http://127.0.0.1:{server.server_port}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as rsp:
            return rsp.status, json.loads(rsp.read())

    try:
        for name, X, fitted in (("alpha", X1, f1), ("beta", X2, f2)):
            status, out = post(f"/predict/{name}",
                               {"instances": X[:3].tolist()})
            assert status == 200 and out["rows"] == 3
            ref = fitted.apply(ArrayDataset.from_numpy(X[:3])).numpy()
            np.testing.assert_allclose(
                np.asarray(out["predictions"]), ref, rtol=1e-5,
                atol=1e-5)
        # bare-array body works too
        status, out = post("/predict/alpha", X1[:2].tolist())
        assert status == 200 and out["rows"] == 2
        with urllib.request.urlopen(base + "/models") as rsp:
            state = json.loads(rsp.read())
        assert sorted(m["name"] for m in state["models"]) == \
            ["alpha", "beta"]
        for path, payload, expect in (
                ("/predict/ghost", {"instances": [[0.0] * 24]}, 404),
                ("/predict/alpha", {"instances": []}, 400),
                ("/predict/alpha", {"instances": [[0.0] * 7]}, 400)):
            with pytest.raises(urllib.error.HTTPError) as exc:
                post(path, payload)
            assert exc.value.code == expect
    finally:
        server.shutdown()


# -- drift wiring -------------------------------------------------------------

def test_serving_scores_drift_against_fit_baseline(plane_factory):
    """A model fitted through the streamed path carries its fit-time
    sketch; serving scores live inputs every ``drift_every`` batches —
    shifted traffic raises ``numerics.drift_score`` and fires the
    drift_warn event, while the scoring programs compile during warmup
    (the steady-state fence stays clean)."""
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    r = np.random.RandomState(0)
    X = r.rand(128, 24).astype(np.float32)
    Y = r.rand(128, 3).astype(np.float32)
    stream = StreamingDataset.from_numpy(X, chunk_size=32,
                                         tag="serve-drift")
    model = fit_streaming(LinearMapEstimator(lam=1e-3), stream, Y)
    assert getattr(model, "numerics_baseline", None) is not None
    plane = plane_factory(drift_every=1)
    plane.start()
    plane.admit("m", model, _sample(24))
    u0 = plane.unexpected_recompiles()
    plane.predict("m", X[:6] + 3.0)  # shifted: must register as drift
    reg = MetricsRegistry.get_or_create()
    deadline = time.monotonic() + 10.0
    score = None
    while time.monotonic() < deadline:  # scoring is post-reply, async
        score = reg.snapshot()["gauges"].get("numerics.drift_score")
        if score is not None:
            break
        time.sleep(0.02)
    assert score is not None and score > 0.2
    assert reg.counter("numerics.drift_warn").value >= 1
    plane.predict("m", X[:6])
    assert plane.unexpected_recompiles() - u0 == 0, (
        "drift scoring must compile during warmup, not steady state")


# -- residency planner shares the auto-cache greedy ---------------------------

def test_greedy_select_maximizes_value_under_budget():
    from keystone_tpu.workflow.optimizer.auto_cache import greedy_select

    mem = {"a": 4.0, "b": 4.0, "c": 4.0}
    value = {"a": 10.0, "b": 6.0, "c": 1.0}

    def candidates(selected, space_left):
        return [n for n in mem if n not in selected
                and mem[n] < space_left]

    keep = greedy_select(
        (), candidates, mem.get,
        lambda sel: -sum(value[n] for n in sel), budget=9.0)
    assert keep == frozenset({"a", "b"})
    # empty-budget edge: nothing fits, nothing selected
    assert greedy_select((), candidates, mem.get,
                         lambda sel: 0.0, budget=0.0) == frozenset()


# -- PR 17 hot-path findings: pinned regressions ------------------------------
# Each true positive the first hotpath tree scan found rides with an
# UN-FIXED offender copy (the pre-fix method body, verbatim) that
# reproduces the pathology deterministically, plus the HEAD behavior
# surviving the same sequence — the static rule points at the line, the
# dynamic pin proves the line mattered.

from keystone_tpu.serving.batcher import Request as _Request
from keystone_tpu.serving.plane import _evicted_record
from keystone_tpu.utils.guarded import published_fields


class _UnfixedBatcher(MicroBatcher):
    """``submit_request`` as it stood before the published lock-free
    ``_closed`` fast-fail: the slot gate is paid FIRST, so a closed
    batcher whose slots are still held (taken-but-not-done requests)
    costs callers the full submit timeout and reports shutdown as a
    QueueFullError 429. ``deadline_ms`` is accepted (the base
    ``submit`` passes it through) and ignored, as pre-deadline code
    would."""

    def submit_request(self, model, x, n, timeout_s=None,
                       deadline_ms=None):
        timeout = self.submit_timeout_s if timeout_s is None else timeout_s
        if not self._slots.acquire(timeout=timeout):
            raise QueueFullError(
                f"serving queue full ({self.queue_depth} slots) — "
                f"request for {model!r} rejected after {timeout:.1f}s")
        req = _Request(model=model, x=x, n=int(n))
        with self._lock:
            if self._closed:
                self._slots.release()
                raise RuntimeError("batcher is closed")
            self._pending.append(req)
        self._ready.set()
        return req


def _closed_batcher_with_held_slots(cls):
    """A closed batcher whose every slot is held by an in-flight
    (taken, not yet done) request — the shutdown shape that exposed the
    bug: close() only releases DRAINED slots."""
    batcher = cls(queue_depth=2, submit_timeout_s=0.3)
    batcher.submit("m", np.zeros((1, 2)), 1)
    batcher.submit("m", np.zeros((1, 2)), 1)
    taken = batcher.take(max_rows=8)
    assert len(taken) == 2 and batcher.close() == []
    return batcher


def test_closed_batcher_masquerades_as_429_on_unfixed_copy():
    batcher = _closed_batcher_with_held_slots(_UnfixedBatcher)
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        batcher.submit("m", np.zeros((1, 2)), 1)
    # the pathology, both halves: the wrong verdict (shutdown shaped as
    # an overload 429) at the price of the full submit timeout
    assert time.perf_counter() - t0 >= 0.3


def test_closed_batcher_fast_fails_on_head():
    batcher = _closed_batcher_with_held_slots(MicroBatcher)
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit("m", np.zeros((1, 2)), 1)
    # the published read refuses BEFORE the slot gate: honest verdict,
    # immediately (QueueFullError is a RuntimeError too — the match
    # pins the type apart)
    assert time.perf_counter() - t0 < 0.1


class _UnfixedPublishPlane(ServingPlane):
    """``_publish_locked`` as an in-place mutation instead of the
    reference flip: a lock-free reader holding the ``_live`` dict
    observes it change under them (momentarily EMPTY mid-republish) —
    the torn publication the ``@published_by`` pass forbids."""

    def _publish_locked(self):
        self._live.clear()
        self._live.update(
            {n: e for n, e in self._models.items() if e.ready})
        reg = MetricsRegistry.get_or_create()
        reg.gauge("serving.models_resident").set(len(self._live))
        reg.gauge("serving.models_warming").set(self._warming)


def test_live_snapshot_mutated_under_readers_on_unfixed_copy():
    plane = _UnfixedPublishPlane(max_batch=8)
    try:
        fitted, X, _ = _make_fitted(6, 2)
        plane.admit("m", fitted, _sample(6))
        snapshot = plane._live  # what a lock-free reader holds
        plane.evict("m")
        assert plane._live is snapshot  # same object republished...
        assert "m" not in snapshot  # ...so the reader's view tore
    finally:
        plane.close()


def test_live_snapshot_flips_atomically_on_head():
    plane = ServingPlane(max_batch=8)
    try:
        fitted, X, _ = _make_fitted(6, 2)
        plane.admit("m", fitted, _sample(6))
        snapshot = plane._live
        assert "m" in snapshot
        plane.evict("m")
        assert plane._live is not snapshot  # a NEW dict was bound
        assert "m" in snapshot  # the reader's snapshot never mutates
        assert "m" not in plane._live
        # the discipline is DECLARED, so the static pass guards it
        assert published_fields(ServingPlane) == {"_live": "_lock"}
    finally:
        plane.close()


class _CountingLock:
    def __init__(self, inner):
        self._inner = inner
        self.acquires = 0

    def __enter__(self):
        self.acquires += 1
        return self._inner.__enter__()

    def __exit__(self, *exc):
        return self._inner.__exit__(*exc)


def test_steady_state_submit_skips_the_plane_lock():
    """The point of publishing ``_live``: a ready-model request never
    acquires the plane lock (and never queues behind an admission
    holding it); only the miss path pays it for the honest
    warming-vs-unknown verdict. No worker is started, so the submitted
    request parks in the batcher and nothing else touches the lock."""
    plane = ServingPlane(max_batch=8)
    try:
        fitted, X, _ = _make_fitted(6, 2)
        plane.admit("m", fitted, _sample(6))
        real = plane._lock
        counting = _CountingLock(real)
        plane._lock = counting
        try:
            plane.submit_request("m", X[:2])
        finally:
            plane._lock = real
        assert counting.acquires == 0
        plane._lock = counting
        try:
            with pytest.raises(ModelNotAdmitted):
                plane.submit_request("ghost", X[:2])
        finally:
            plane._lock = real
        assert counting.acquires == 1
    finally:
        plane.close()


class _UnfixedEvictPlane(ServingPlane):
    """``evict`` as it stood before PR 17: the ``_phase_hists`` entry
    outlives its model — one cached histogram-handle pair per model
    name EVER served, the per-model leak the first hotpath tree scan
    flagged as ``hotpath-unbounded-growth``."""

    def evict(self, name):
        with self._lock:
            if name not in self._models:
                raise ModelNotAdmitted(f"model {name!r} is not resident")
            entry = self._models.pop(name)
            self.ledger.release(name)
            self._evicted[name] = _evicted_record(entry)
            self._publish_locked()


def _churn_phase_hists(plane, fitted, names):
    for name in names:
        plane.admit(name, fitted, _sample(6))
        plane._phase_instruments(name)  # the worker's first-use fill
        assert name in plane._phase_hists
        plane.evict(name)


def test_phase_hist_cache_leaks_on_unfixed_copy():
    plane = _UnfixedEvictPlane(max_batch=8)
    try:
        fitted, _, _ = _make_fitted(6, 2)
        _churn_phase_hists(plane, fitted, ["m0", "m1", "m2"])
        # one entry per model name ever served, none of them resident
        assert sorted(plane._phase_hists) == ["m0", "m1", "m2"]
    finally:
        plane.close()


def test_phase_hist_cache_is_pruned_with_its_model_on_head():
    plane = ServingPlane(max_batch=8)
    try:
        fitted, _, _ = _make_fitted(6, 2)
        _churn_phase_hists(plane, fitted, ["m0", "m1", "m2"])
        assert plane._phase_hists == {}
    finally:
        plane.close()
