"""The serving fleet (ISSUE 20): rendezvous routing with honest spill,
the canonical-bytes fleet controller, and the autoscaling reactor.

The acceptance pins:

* routing is rendezvous hashing — deterministic, coordination-free,
  and stable under membership change (removing a non-primary replica
  never re-routes a model; removing the primary re-routes ONLY it);
* spill is measured and honest — a congested or refusing primary
  loses the request to the least-loaded sibling and the router counts
  it (``router.spill_total``); when every replica refuses, the LAST
  classified verdict surfaces (429/503 with Retry-After over HTTP),
  never an unclassified error;
* migration is bit-identical or aborted — the controller's
  admit -> sha-verify -> evict order, with the impostor copy evicted
  before anything routes to it;
* death recovery is a verified migration, not a guess — the corpse
  leaves the membership, ``fleet.replica_deaths_total`` counts it, and
  the lost models re-admit from canonical bytes on the survivors;
* the reactor acts only on sustained measured signals (queue depth,
  failed probes, demand drift) — one bursty scrape must not flap the
  fleet;
* the whole loadgen trace is pinned by sha256 of its canonical
  serialization — an RNG draw-order refactor reshuffles every
  scenario's traffic and must fail here by value, not by eyeball.

Every router/controller test runs on duck-typed fake replicas (the
real transports are exercised end-to-end by ``tools/fleet_gate.py``
and the fleet chaos scenarios) — these tests pin the routing and
placement LOGIC at unit speed.
"""
import hashlib
import json

import numpy as np
import pytest

import jax

from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.observability.metrics import MetricsRegistry
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.serving.batcher import QueueFullError
from keystone_tpu.serving.fleet import (
    FleetAutoscaler,
    FleetController,
    FleetError,
)
from keystone_tpu.serving.loadgen import LoadSpec, generate_trace
from keystone_tpu.serving.plane import ModelNotAdmitted
from keystone_tpu.serving.router import FleetRouter, _rendezvous_score

D, K = 6, 2


class FakeReplica:
    """A duck-typed replica client: the same surface Local/Http
    clients implement, with dial-a-behavior knobs for depth, refusal,
    and death."""

    def __init__(self, replica_id, depth=0):
        self.replica_id = replica_id
        self.depth = depth
        self.hosted = {}          # name -> sha256 of the admitted blob
        self.dead = False
        self.refuse = None        # exception submit_request raises
        self.served = []

    def _check_alive(self):
        if self.dead:
            raise ConnectionError(f"replica {self.replica_id} is down")

    def models(self):
        self._check_alive()
        return tuple(sorted(self.hosted))

    def model_shas(self):
        self._check_alive()
        return dict(self.hosted)

    def queue_depth(self):
        self._check_alive()
        return self.depth

    def submit_request(self, name, x, timeout_s=None, deadline_ms=None):
        self._check_alive()
        if self.refuse is not None:
            raise self.refuse
        self.served.append(name)
        return (self.replica_id, name)

    def predict_raw(self, name, raw):
        self._check_alive()
        if self.refuse is not None:
            return 429, b'{"error": "full"}\n', None
        self.served.append(name)
        return 200, b'{"predictions": []}\n', None

    def admit_blob(self, name, blob, sample, weight_dtype):
        self._check_alive()
        sha = hashlib.sha256(blob).hexdigest()
        self.hosted[name] = sha
        return sha

    def evict(self, name):
        self._check_alive()
        self.hosted.pop(name, None)

    def probe(self):
        return "dead" if self.dead else "ready"


_FITTED = {}


def _fitted(seed=0):
    if seed not in _FITTED:
        r = np.random.RandomState(seed)
        X = r.rand(64, D).astype(np.float32)
        Y = r.rand(64, K).astype(np.float32)
        _FITTED[seed] = LinearMapEstimator(lam=1e-3).with_data(
            ArrayDataset.from_numpy(X),
            ArrayDataset.from_numpy(Y)).fit()
    return _FITTED[seed]


def _sample():
    return jax.ShapeDtypeStruct((D,), np.float32)


def _fleet(n=3, names=("m",), depth=0):
    replicas = [FakeReplica(f"r{i}", depth=depth) for i in range(n)]
    for rep in replicas:
        for name in names:
            rep.hosted[name] = "sha-" + name
    router = FleetRouter(replicas, spill_queue_depth=8)
    return replicas, router


# -- rendezvous routing -----------------------------------------------------

def test_rendezvous_score_is_stable_and_salted_by_pair():
    assert _rendezvous_score("m", "r0") == _rendezvous_score("m", "r0")
    assert _rendezvous_score("m", "r0") != _rendezvous_score("m", "r1")
    assert _rendezvous_score("m", "r0") != _rendezvous_score("n", "r0")


def test_primary_is_deterministic_and_stable_under_membership():
    replicas, router = _fleet(n=4)
    _, primary = router._route("m")
    assert router._route("m")[1] is primary
    # removing a NON-primary replica must not re-route the model
    bystander = next(r for r in replicas if r is not primary)
    router.remove_replica(bystander.replica_id)
    assert router._route("m")[1] is primary
    # removing the primary re-routes to the next-highest score —
    # deterministically
    router.remove_replica(primary.replica_id)
    survivors = [r for r in replicas
                 if r not in (primary, bystander)]
    expected = max(survivors, key=lambda r: _rendezvous_score(
        "m", r.replica_id))
    assert router._route("m")[1] is expected


def test_unknown_model_refuses_honestly():
    _, router = _fleet()
    with pytest.raises(ModelNotAdmitted):
        router.submit_request("ghost", None)
    status, _, _ = router.predict_raw("ghost", b"{}")
    assert status == 404


# -- spill ------------------------------------------------------------------

def test_congested_primary_spills_to_shallow_sibling():
    replicas, router = _fleet(n=2)
    _, primary = router._route("m")
    sibling = next(r for r in replicas if r is not primary)
    primary.depth = 100          # >= spill_queue_depth, sibling at 0
    reg = MetricsRegistry.get_or_create()
    spills0 = reg.counter("router.spill_total").value
    rid, _ = router.submit_request("m", None)
    assert rid == sibling.replica_id
    assert reg.counter("router.spill_total").value == spills0 + 1
    assert reg.counter("router.spill_total.m").value >= 1


def test_refusing_primary_spills_and_counts():
    replicas, router = _fleet(n=2)
    _, primary = router._route("m")
    sibling = next(r for r in replicas if r is not primary)
    primary.refuse = QueueFullError("full", retry_after_s=0.5)
    rid, _ = router.submit_request("m", None)
    assert rid == sibling.replica_id


def test_dead_primary_routes_around_without_crashing():
    replicas, router = _fleet(n=2)
    _, primary = router._route("m")
    sibling = next(r for r in replicas if r is not primary)
    primary.dead = True          # stats probe AND submit now raise
    rid, _ = router.submit_request("m", None)
    assert rid == sibling.replica_id


def test_all_refusing_surfaces_last_classified_verdict():
    replicas, router = _fleet(n=2)
    for rep in replicas:
        rep.refuse = QueueFullError("full", retry_after_s=0.5)
    reg = MetricsRegistry.get_or_create()
    unavail0 = reg.counter("router.unavailable_total").value
    with pytest.raises(QueueFullError):
        router.submit_request("m", None)
    assert reg.counter("router.unavailable_total").value == unavail0 + 1
    # over HTTP the same outcome must carry Retry-After — a 429/503
    # without WHEN is an unclassified shrug
    status, _, headers = router.predict_raw("m", b"{}")
    assert status in (429, 503)
    assert "Retry-After" in (headers or {})


def test_all_dead_refuses_with_retry_after():
    replicas, router = _fleet(n=2)
    for rep in replicas:
        rep.dead = True
    with pytest.raises(QueueFullError):
        router.submit_request("m", None)
    status, _, headers = router.predict_raw("m", b"{}")
    assert status == 503
    assert "Retry-After" in (headers or {})


def test_refresh_rebuilds_from_what_replicas_host_now():
    replicas, router = _fleet(n=2, names=("a", "b"))
    replicas[0].hosted.pop("a")
    replicas[1].dead = True
    router.refresh()
    table = router.state()["models"]
    assert table.get("b") == ["r0"]
    assert "a" not in table      # r0 dropped it, r1 is dead
    replicas[1].dead = False
    router.refresh()
    assert set(router.state()["models"]["a"]) == {"r1"}


# -- the fleet controller ---------------------------------------------------

def _controller(n=2, budget_mults=3.3):
    replicas = [FakeReplica(f"r{i}") for i in range(n)]
    router = FleetRouter(replicas)
    controller = FleetController(router)
    return replicas, router, controller


def test_register_canonicalizes_and_rejects_duplicates():
    _, _, controller = _controller()
    model = controller.register("m", _fitted(), _sample())
    assert model.sha256 == hashlib.sha256(model.blob).hexdigest()
    assert model.charge_nbytes > 0
    with pytest.raises(ValueError):
        controller.register("m", _fitted(), _sample())


def test_rebalance_places_all_models_sha_verified():
    replicas, router, controller = _controller(n=2)
    charges = []
    for i, name in enumerate(("a", "b", "c")):
        model = controller.register(name, _fitted(i), _sample())
        charges.append(model.charge_nbytes)
    for rep in replicas:
        controller.set_budget(rep.replica_id, 3.3 * max(charges))
    steps = controller.rebalance()
    assert steps and all(kind == "admit" for kind, _, _ in steps)
    table = router.state()["models"]
    assert set(table) == {"a", "b", "c"}
    canonical = {m: controller._models[m].sha256 for m in table}
    for rep in replicas:
        for name, sha in rep.model_shas().items():
            assert sha == canonical[name]


def test_migration_aborts_on_sha_mismatch_and_evicts_impostor():
    replicas, _, controller = _controller(n=1)
    controller.register("m", _fitted(), _sample())

    def bad_admit(name, blob, sample, weight_dtype):
        replicas[0].hosted[name] = "not-the-canonical-sha"
        return "not-the-canonical-sha"

    replicas[0].admit_blob = bad_admit
    with pytest.raises(FleetError, match="bit-identical"):
        controller.rebalance()
    # the impostor copy must not be left routable
    assert "m" not in replicas[0].hosted


def test_handle_death_readmits_from_canonical_bytes():
    replicas, router, controller = _controller(n=3)
    for i, name in enumerate(("a", "b")):
        controller.register(name, _fitted(i), _sample())
    controller.rebalance()
    reg = MetricsRegistry.get_or_create()
    deaths0 = reg.counter("fleet.replica_deaths_total").value
    # kill whoever hosts model "a"
    victim = controller.placement.assignments["a"][0]
    corpse = next(r for r in replicas if r.replica_id == victim)
    corpse.dead = True
    steps = controller.handle_death(victim)
    assert reg.counter(
        "fleet.replica_deaths_total").value == deaths0 + 1
    assert victim not in router.replica_ids()
    table = router.state()["models"]
    assert set(table) == {"a", "b"}
    assert all(victim not in reps for reps in table.values())
    # recovery re-admitted (a migration, not a guess): the survivors'
    # copies carry the canonical shas
    canonical = {m: controller._models[m].sha256 for m in ("a", "b")}
    for rep in replicas:
        if rep is corpse:
            continue
        for name, sha in rep.model_shas().items():
            assert sha == canonical[name]
    assert any(kind == "admit" for kind, _, _ in steps) or not steps


def test_drain_refuses_the_last_replica():
    _, _, controller = _controller(n=1)
    controller.register("m", _fitted(), _sample())
    controller.rebalance()
    with pytest.raises(FleetError, match="last replica"):
        controller.drain_replica("r0")


def test_drain_migrates_then_retires():
    replicas, router, controller = _controller(n=2)
    controller.register("m", _fitted(), _sample())
    controller.rebalance()
    controller.drain_replica("r1")
    assert router.replica_ids() == ("r0",)
    assert "m" in replicas[0].hosted
    assert "m" not in replicas[1].hosted
    assert router.state()["models"]["m"] == ["r0"]


def test_note_demand_buys_replication_on_next_rebalance():
    _, router, controller = _controller(n=2)
    model = controller.register("m", _fitted(), _sample())
    controller.register("other", _fitted(1), _sample())
    for rid in ("r0", "r1"):
        controller.set_budget(rid, 3.3 * model.charge_nbytes)
    controller.rebalance()
    assert len(controller.placement.replicas_for("m")) == 1
    controller.note_demand("m", qps=5000.0, warmup_s=2.0)
    controller.rebalance()
    assert len(controller.placement.replicas_for("m")) == 2
    assert len(router.state()["models"]["m"]) == 2


# -- the autoscaling reactor ------------------------------------------------

def test_reactor_classifies_a_failed_probe_as_death():
    replicas, router, controller = _controller(n=2)
    controller.register("m", _fitted(), _sample())
    controller.rebalance()
    scaler = FleetAutoscaler(controller, sustain_ticks=10**6)
    replicas[0].dead = True
    assert scaler.tick() == "death"
    assert "r0" not in router.replica_ids()


def test_reactor_scales_up_only_on_sustained_congestion():
    replicas, router, controller = _controller(n=1)
    controller.register("m", _fitted(), _sample())
    controller.rebalance()
    minted = []

    def provision():
        rep = FakeReplica(f"r{len(replicas) + len(minted)}")
        minted.append(rep)
        return rep

    scaler = FleetAutoscaler(controller, provisioner=provision,
                             scale_up_queue_depth=16,
                             sustain_ticks=2, max_replicas=4)
    replicas[0].depth = 100
    assert scaler.tick() is None          # one hot scrape: no flap
    assert scaler.tick() == "scale_up"    # sustained: act
    assert len(router.replica_ids()) == 2
    # the new replica was rebalanced onto, not joined empty forever
    assert minted[0].replica_id in router.replica_ids()


def test_reactor_scales_down_a_sustained_idle_fleet():
    replicas, router, controller = _controller(n=2)
    controller.register("m", _fitted(), _sample())
    controller.rebalance()
    scaler = FleetAutoscaler(controller, scale_down_queue_depth=2,
                             sustain_ticks=2, min_replicas=1)
    assert scaler.tick() is None
    assert scaler.tick() == "scale_down"
    # drains the HIGHEST-numbered replica, models migrated first
    assert router.replica_ids() == ("r0",)
    assert "m" in replicas[0].hosted


def test_reactor_applies_demand_drift_as_rebalance():
    _, router, controller = _controller(n=2)
    model = controller.register("m", _fitted(), _sample())
    for rid in ("r0", "r1"):
        controller.set_budget(rid, 3.3 * model.charge_nbytes)
    controller.rebalance()
    scaler = FleetAutoscaler(controller, scale_up_queue_depth=10**6,
                             scale_down_queue_depth=-1,
                             sustain_ticks=10**6)
    controller.note_demand("m", qps=5000.0, warmup_s=2.0)
    assert scaler.tick() == "rebalance"
    assert len(router.state()["models"]["m"]) == 2


# -- the loadgen trace pin --------------------------------------------------

def test_trace_sha_pinned():
    """The WHOLE trace, pinned by sha256 of a canonical serialization
    (floats via repr — Python's shortest round-trip form). The chaos
    floors and the fleet gate's recorded behavior are only meaningful
    against this exact traffic; an RNG draw-order change must fail
    here by value."""
    spec = LoadSpec(seed=31, duration_s=3.0, rate_rps=90.0,
                    arrival="poisson",
                    models=("alpha", "beta", "gamma"),
                    zipf_s=1.2, sizes=(1, 2, 4))
    trace = generate_trace(spec)
    canon = json.dumps(
        [[repr(ev.t_s), ev.model, ev.n, ev.seq]
         for ev in trace.arrivals],
        separators=(",", ":")).encode()
    assert len(trace.arrivals) > 200
    assert hashlib.sha256(canon).hexdigest() == (
        "5d3894809a7c3fb96666558c4f4829061e5125a79cd76a4e0cbdfbe7bc02c59e")
