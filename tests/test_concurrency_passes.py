"""Concurrency-safety static passes (analysis/concurrency.py): each of
the three families — guarded-by race, lock-order/deadlock +
blocking-under-lock, non-atomic guarded sequence — fires on its
synthetic offender fixture (tests/lint_fixtures) and reports the scoped
package tree clean; the declarations themselves (``@guarded_by`` +
``GUARDED_FIELDS``) are introspectable at runtime."""
import ast
import pathlib

import pytest

from keystone_tpu.analysis.concurrency import (
    CONCURRENCY_SCOPES,
    blocking_under_lock,
    find_lock_cycles,
    guarded_classes,
    guarded_field_races,
    guarded_sequence_hazards,
    known_locks,
    lock_order_edges,
    scan_package,
)
from keystone_tpu.utils.guarded import GUARDED_FIELDS, guarded_fields

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"


def _tree(name):
    return ast.parse((FIXTURES / f"{name}.py").read_text())


# -- declarations ------------------------------------------------------------

def test_guarded_by_decorator_lands_on_class_and_ast():
    from lint_fixtures.guarded_offender import RacyLedger

    assert guarded_fields(RacyLedger) == {
        "count": "_lock", "tail": "_lock", "stats": "_lock"}
    classes = guarded_classes(_tree("guarded_offender"))
    assert classes["RacyLedger"] == {
        "count": "_lock", "tail": "_lock", "stats": "_lock"}


def test_guarded_fields_table_merges_for_undecorated_classes():
    from keystone_tpu.utils.lru import LruMemo

    assert guarded_fields(LruMemo) == {"_entries": "_lock"}
    # and the analyzer sees table entries off a bare AST too
    src = "class LruMemo:\n    def f(self):\n        self._entries.clear()\n"
    hits = guarded_field_races(ast.parse(src))
    assert [c for _, c, _ in hits] == ["guarded-field-race"]


def test_shipped_declarations_cover_the_shared_state_inventory():
    """The registry covers the classes worker threads actually mutate
    (the README 'Concurrency model' inventory)."""
    from keystone_tpu.observability.metrics import (
        Counter,
        Histogram,
        MetricsRegistry,
    )
    from keystone_tpu.observability.trace import PipelineTrace
    from keystone_tpu.parallel.streaming import _Residency
    from keystone_tpu.resilience.quarantine import Quarantine

    assert guarded_fields(Histogram)["_tail"] == "_lock"
    assert guarded_fields(Counter)["value"] == "_lock"
    assert guarded_fields(MetricsRegistry)["_counters"] == "_lock"
    assert guarded_fields(Quarantine)["bad_count"] == "_lock"
    assert guarded_fields(PipelineTrace)["resilience_stats"] == \
        "_resilience_lock"
    assert guarded_fields(PipelineTrace)["lock_waits"] == "_lock_wait_lock"
    assert guarded_fields(_Residency)["peak"] == "_lock"
    assert set(GUARDED_FIELDS) >= {"LruMemo", "RetryPolicy", "FaultPlan"}


# -- pass 1: guarded-by race -------------------------------------------------

def test_guarded_race_fires_on_offender_fixture():
    hits = guarded_field_races(_tree("guarded_offender"))
    codes = {c for _, c, _ in hits}
    assert codes == {"guarded-field-race"}
    # one per racy method: RMW, compound append, dict RMW — and NOT the
    # locked method, NOT the plain rebind, NOT __init__
    assert len(hits) == 3
    by_msg = " ".join(m for _, _, m in hits)
    assert "read-modify-write" in by_msg
    assert ".append()" in by_msg
    assert "item assignment" in by_msg
    assert "locked_bump" not in by_msg
    assert "rebind" not in by_msg


def test_guarded_race_allowlist_suppresses_with_entry():
    hits = guarded_field_races(
        _tree("guarded_offender"),
        allowlist={"RacyLedger.bump:count", "RacyLedger.push:tail"})
    assert len(hits) == 1  # only the dict RMW remains
    assert "merge" in hits[0][2]


def test_guarded_race_catches_the_pre_pr4_trace_shape():
    """The exact record_resilience read-modify-write PR 4's review
    caught by hand is now machine-found."""
    src = (
        "class PipelineTrace:\n"
        "    def record_resilience(self, entry):\n"
        "        ev = str(entry.get('event', 'other'))\n"
        "        self.resilience_stats[ev] = "
        "self.resilience_stats.get(ev, 0) + 1\n"
        "        self.resilience.append(entry)\n")
    extra = {"PipelineTrace": {"resilience": "_resilience_lock",
                               "resilience_stats": "_resilience_lock"}}
    hits = guarded_field_races(ast.parse(src), extra=extra)
    assert len(hits) == 2
    assert {c for _, c, _ in hits} == {"guarded-field-race"}


def test_guarded_race_catches_the_pre_pr7_histogram_shape():
    src = (
        "from keystone_tpu.utils.guarded import guarded_by\n"
        "@guarded_by('_lock', 'count', '_tail')\n"
        "class Histogram:\n"
        "    def observe(self, value):\n"
        "        self.count += 1\n"
        "        self._tail.append(value)\n"
        "        if len(self._tail) > 256:\n"
        "            del self._tail[:1]\n")
    hits = guarded_field_races(ast.parse(src))
    assert len(hits) == 3


# -- pass 2: lock order + blocking-under-lock --------------------------------

def test_lock_order_cycle_fires_on_offender_fixture():
    tree = _tree("lock_order_offender")
    edges = lock_order_edges(tree, "lint_fixtures.lock_order_offender")
    cycles = find_lock_cycles(edges)
    assert len(cycles) == 1
    path, sites = cycles[0]
    assert set(path) == {"DeadlockPair._ingest", "DeadlockPair._ledger"}
    assert "producer_side" in sites and "consumer_side" in sites


def test_module_level_lock_edges_are_tracked():
    tree = _tree("lock_order_offender")
    mod_locks, cls_locks = known_locks(tree)
    assert mod_locks == {"_MODULE_LOCK"}
    assert cls_locks["DeadlockPair"] == {"_ingest", "_ledger"}
    edges = lock_order_edges(tree, "m")
    assert ("m._MODULE_LOCK", "DeadlockPair._ingest") in {
        (a, b) for a, b, _, _ in edges}


def test_blocking_under_lock_fires_on_offender_fixture():
    hits = blocking_under_lock(_tree("lock_order_offender"), "m")
    attrs = sorted(m.split("`")[1] for _, _, m in hits)
    assert attrs == ["device_put()", "get()", "wait()"]
    assert all(c == "blocking-under-lock" for _, c, _ in hits)


def test_blocking_under_lock_ignores_dict_get():
    # `.get` is only blocking on queue-shaped receivers: dict lookups
    # under a lock are the normal registry pattern, never flagged
    src = (
        "import threading\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def lookup(self, table, name):\n"
        "        with self._lock:\n"
        "            return table.get(name)\n")
    assert blocking_under_lock(ast.parse(src), "m") == []


# -- pass 3: non-atomic guarded sequence -------------------------------------

def test_sequence_hazard_fires_on_offender_fixture():
    hits = guarded_sequence_hazards(_tree("atomicity_offender"))
    assert len(hits) == 1
    lineno, code, msg = hits[0]
    assert code == "non-atomic-guarded-sequence"
    assert "drain_one" in msg and "items" in msg
    assert "drain_one_atomic" not in msg


def test_sequence_hazard_allowlist():
    hits = guarded_sequence_hazards(
        _tree("atomicity_offender"),
        allowlist={"SplitCheckThenAct.drain_one:items"})
    assert hits == []


# -- the tree is clean -------------------------------------------------------

def test_package_tree_is_concurrency_clean():
    """All three families over the shipped tree: zero diagnostics (the
    satellite fixes landed; deliberate exceptions live in the commented
    CONCURRENCY_ALLOWLIST)."""
    hits = scan_package(REPO / "keystone_tpu")
    assert hits == [], hits


def test_scan_package_reports_offenders_when_present(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "parallel" / "racy.py").write_text(
        (FIXTURES / "guarded_offender.py").read_text())
    hits = scan_package(pkg)
    assert {h["code"] for h in hits} == {"guarded-field-race"}
    assert all(h["file"].endswith("racy.py") for h in hits)


def test_scopes_cover_the_threaded_subsystems():
    assert set(CONCURRENCY_SCOPES) >= {
        "loaders", "observability", "parallel", "resilience", "utils"}


# -- wiring: lint + check CLI ------------------------------------------------

def test_lint_gate_runs_concurrency_passes(tmp_path, monkeypatch):
    """tools/lint.py fails when a scoped module has a concurrency
    diagnostic (wired like SWALLOW_ALL_SCOPES)."""
    import sys

    sys.path.insert(0, str(REPO / "tools"))
    try:
        import lint
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "keystone_tpu"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "parallel" / "bad.py").write_text(
        (FIXTURES / "lock_order_offender.py").read_text())
    monkeypatch.setattr(lint, "REPO", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    assert lint.run_concurrency_rules() > 0


@pytest.mark.slow
def test_check_cli_includes_concurrency_diagnostics(tmp_path):
    """`python -m keystone_tpu check <app> --json` carries the
    tree-wide concurrency scan AND the metric-name-drift scan (both
    clean today) and exits 0."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "check",
         "mnist.random_fft", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    blob = json.loads(out.read_text())
    assert blob["concurrency"] == []
    assert blob["metrics_names"] == []
