"""End-to-end CIFAR pipelines on synthetic data."""
import numpy as np

from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.pipelines.images.cifar.linear_pixels import (
    LinearPixelsConfig,
    run as run_linear,
)
from keystone_tpu.pipelines.images.cifar.random_patch_cifar import (
    RandomCifarConfig,
    run as run_patch,
)

CENTERS = np.random.RandomState(7).rand(10, 32, 32, 3).astype(np.float32) * 255


def synthetic_cifar(n, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n)
    imgs = CENTERS[labels] + 20 * rng.randn(n, 32, 32, 3).astype(np.float32)
    imgs = np.clip(imgs, 0, 255)
    return LabeledData(
        data=ArrayDataset.from_numpy(imgs),
        labels=ArrayDataset.from_numpy(labels.astype(np.int32)),
    )


def test_linear_pixels_end_to_end():
    train = synthetic_cifar(300, 0)
    test = synthetic_cifar(80, 1)
    _, train_eval, test_eval = run_linear(
        LinearPixelsConfig(lam=10.0), train=train, test=test
    )
    assert train_eval.total_error < 0.05
    assert test_eval.total_error < 0.2


def test_random_patch_cifar_end_to_end():
    train = synthetic_cifar(200, 2)
    test = synthetic_cifar(60, 3)
    config = RandomCifarConfig(
        num_filters=32, lam=100.0, patch_steps=3, seed=0
    )
    _, train_eval, test_eval = run_patch(config, train=train, test=test)
    assert train_eval.total_error < 0.05
    assert test_eval.total_error < 0.25
