"""The accuracy surrogate must stay informative (VERDICT r2 next#2).

The procedurally generated CIFAR stand-in (bench.make_surrogate_cifar)
is built so that the RandomPatchCifar pipeline's conv+pool featurization
beats the raw-pixel LinearPixels baseline by a wide margin, with BOTH
errors off the 0%/100% rails — a numerics regression anywhere in the
patch-whitening / convolution / pooling / solver path collapses the gap
and fails this test, where a saturated 0.00% metric would hide it
(reference anchor: RandomPatchCifar.scala:59-69 targets the published
~85%-accuracy CIFAR pipeline; the real-data path reports against that
bar in bench.py's accuracy section).
"""
import numpy as np
import pytest


@pytest.mark.slow
def test_randompatch_beats_linear_pixels_on_surrogate():
    from bench import make_surrogate_cifar
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.pipelines.images.cifar.random_patch_cifar import (
        RandomCifarConfig,
        run,
    )
    from keystone_tpu.pipelines.images.cifar.linear_pixels import (
        LinearPixelsConfig,
        run as run_linear,
    )

    (tr_x, tr_y), (te_x, te_y) = make_surrogate_cifar(768, 192)
    train = LabeledData(ArrayDataset.from_numpy(tr_x),
                        ArrayDataset.from_numpy(tr_y.astype(np.int32)))
    test = LabeledData(ArrayDataset.from_numpy(te_x),
                       ArrayDataset.from_numpy(te_y.astype(np.int32)))

    _, _, rp_eval = run(RandomCifarConfig(num_filters=48, lam=10.0, seed=0),
                        train=train, test=test)
    _, _, lin_eval = run_linear(LinearPixelsConfig(lam=10.0),
                                train=train, test=test)
    rp_err = float(rp_eval.total_error)
    lin_err = float(lin_eval.total_error)

    # non-saturated: both sit strictly inside the informative band
    assert 0.02 < rp_err < 0.90, rp_err
    assert 0.30 < lin_err < 0.98, lin_err
    # the gap IS the signal: featurization must buy a wide margin
    assert rp_err < lin_err - 0.15, (rp_err, lin_err)
