"""Graph surgery tests, mirroring ``workflow/graph/GraphSuite.scala``."""
import numpy as np
import pytest

from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.graph_ids import NodeId, SinkId, SourceId
from keystone_tpu.workflow.operators import DatumOperator, Operator


class Op(Operator):
    def __init__(self, tag):
        self.tag = tag

    def execute(self, deps):
        raise NotImplementedError


def build_chain():
    g = Graph()
    g, src = g.add_source()
    g, a = g.add_node(Op("a"), (src,))
    g, b = g.add_node(Op("b"), (a,))
    g, sink = g.add_sink(b)
    return g, src, a, b, sink


def test_add_node_and_sink():
    g, src, a, b, sink = build_chain()
    assert g.sources == {src}
    assert set(g.nodes) == {a, b}
    assert g.get_sink_dependency(sink) == b
    assert g.get_dependencies(b) == (a,)


def test_ids_are_fresh():
    g, src, a, b, sink = build_chain()
    ids = {src.id, a.id, b.id, sink.id}
    assert len(ids) == 4


def test_set_operator_and_dependencies():
    g, src, a, b, sink = build_chain()
    g2 = g.set_operator(a, Op("c"))
    assert g2.get_operator(a).tag == "c"
    assert g.get_operator(a).tag == "a"  # immutability
    g3 = g.set_dependencies(b, (src,))
    assert g3.get_dependencies(b) == (src,)


def test_replace_dependency():
    g, src, a, b, sink = build_chain()
    g2 = g.replace_dependency(a, src)
    assert g2.get_dependencies(b) == (src,)


def test_remove_node():
    g, src, a, b, sink = build_chain()
    g2 = g.replace_dependency(b, a).remove_sink(sink)
    g2, k2 = g2.add_sink(a)
    g2 = g2.remove_node(b)
    assert set(g2.nodes) == {a}
    assert g2.get_sink_dependency(k2) == a


def test_add_graph_remaps_ids():
    g1, src1, a1, b1, sink1 = build_chain()
    g2, src2, a2, b2, sink2 = build_chain()
    union, smap, kmap = g1.add_graph(g2)
    assert len(union.sources) == 2
    assert len(union.nodes) == 4
    assert len(union.sinks) == 2
    # the remapped ids are fresh
    assert smap[src2] != src1
    new_b = union.get_sink_dependency(kmap[sink2])
    assert union.get_operator(new_b).tag == "b"
    # structure preserved under remap
    (new_a,) = union.get_dependencies(new_b)
    assert union.get_operator(new_a).tag == "a"
    assert union.get_dependencies(new_a) == (smap[src2],)


def test_connect_graph_splices_source_to_sink():
    g1, src1, a1, b1, sink1 = build_chain()
    g2, src2, a2, b2, sink2 = build_chain()
    merged, smap, kmap = g1.connect_graph(g2, {src2: sink1})
    # g2's source is gone; g1's sink is gone
    assert len(merged.sources) == 1 and src1 in merged.sources
    assert sink1 not in merged.sinks
    assert len(merged.sinks) == 1
    # the chain now runs a->b->a'->b'
    final_sink = kmap[sink2]
    nb2 = merged.get_sink_dependency(final_sink)
    (na2,) = merged.get_dependencies(nb2)
    assert merged.get_dependencies(na2) == (b1,)


def test_ancestors_descendants_linearize():
    g, src, a, b, sink = build_chain()
    assert g.get_ancestors(sink) == {b, a, src}
    assert g.get_descendants(src) == {a, b, sink}
    order = g.linearize()
    assert order.index(a) < order.index(b)
    assert order.index(src) < order.index(a)


def test_to_dot():
    g, *_ = build_chain()
    dot = g.to_dot()
    assert "digraph" in dot and "->" in dot


def test_induce_subgraph():
    g, src, a, b, sink = build_chain()
    sub = g.induce(frozenset({a, src}))
    assert set(sub.nodes) == {a}
    assert sub.sources == {src}
    assert not sub.sinks
