"""f32-vs-f64 solver parity bounds (VERDICT r1 weak#6 / next#9).

The reference runs its least-squares solvers in f64 (Breeze DenseMatrix
[Double]; SURVEY.md section 7 "Numerics parity"), while every solver here
is f32 for the MXU. These tests bound the resulting solution gap at the
reference's own operating point — ill-conditioned features with the
ImageNet regularizer lambda = 6e-5 (reference
``ImageNetSiftLcsFV.scala:153-174``) — against an independent f64 NumPy
implementation of the same math.

Measured result (documented bound, asserted below): with ridge
regularization the Gram spectrum is floored at lambda, so the f32
objective matches f64 to ~1e-6 relative even when the raw feature matrix
has condition number 1e6. Weight-space differences are larger (~3e-4
relative) because ill-conditioned ridge has near-flat directions, but the
*predictions* and the *training objective* — what the reference's own
``computeCost`` (LinearMapper.scala:124-161) measures — are at parity.
Conclusion recorded per VERDICT: the gap is NOT material at reference
conditions; no f64-on-host fallback is required. The extreme-scaling test
documents where f32 WOULD degrade (unstandardized features with 1e4 column
scales) and that the framework's standard pipeline position for the solver
— after StandardScaler, as in every reference app — avoids that regime.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops import linalg

LAM = 6e-5  # reference ImageNet regularizer


def _ill_conditioned(n, d, k, cond, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    U, _ = np.linalg.qr(rng.standard_normal((n, d)))
    V, _ = np.linalg.qr(rng.standard_normal((d, d)))
    s = np.logspace(0, -np.log10(cond), d) * scale
    X = (U * s) @ V.T
    W = rng.standard_normal((d, k))
    Y = X @ W + 0.01 * rng.standard_normal((n, k))
    return X, Y


def _objective(W, X, Y, lam=LAM):
    W = np.asarray(W, np.float64)
    R = X @ W - Y
    return 0.5 * np.sum(R * R) + 0.5 * lam * np.sum(W * W)


def _bcd_f64(blocks, Y, lam, passes):
    """Independent f64 implementation of mlmatrix BCD semantics."""
    k = Y.shape[1]
    Ws = [np.zeros((b.shape[1], k)) for b in blocks]
    pred = np.zeros_like(Y)
    for _ in range(passes):
        for i, A in enumerate(blocks):
            T = Y - pred + A @ Ws[i]
            G = A.T @ A + lam * np.eye(A.shape[1])
            Wi = np.linalg.solve(G, A.T @ T)
            pred = pred + A @ (Wi - Ws[i])
            Ws[i] = Wi
    return np.concatenate(Ws)


@pytest.mark.parametrize("passes", [1, 3])
def test_bcd_f32_objective_parity_at_reference_conditioning(passes):
    n, d, k = 2048, 256, 5
    X, Y = _ill_conditioned(n, d, k, cond=1e6)
    blocks64 = [X[:, : d // 2], X[:, d // 2 :]]
    W64 = _bcd_f64(blocks64, Y, LAM, passes)

    blocks32 = tuple(jnp.asarray(b, jnp.float32) for b in blocks64)
    W32 = np.concatenate(
        [
            np.asarray(w, np.float64)
            for w in linalg.block_coordinate_descent(
                blocks32, jnp.asarray(Y, jnp.float32), LAM, passes
            )
        ]
    )

    j64, j32 = _objective(W64, X, Y), _objective(W32, X, Y)
    # documented bound: f32 objective within 1e-5 relative of f64
    assert abs(j32 - j64) / j64 < 1e-5
    # prediction-space parity (what the evaluators consume)
    p64, p32 = X @ W64, X @ W32
    assert np.linalg.norm(p32 - p64) / np.linalg.norm(p64) < 1e-3


def test_normal_equations_f32_objective_parity():
    n, d, k = 2048, 192, 4
    X, Y = _ill_conditioned(n, d, k, cond=1e6, seed=1)
    G = X.T @ X + LAM * np.eye(d)
    W64 = np.linalg.solve(G, X.T @ Y)
    W32 = np.asarray(
        linalg.normal_equations(
            jnp.asarray(X, jnp.float32), jnp.asarray(Y, jnp.float32), LAM
        ),
        np.float64,
    )
    j64, j32 = _objective(W64, X, Y), _objective(W32, X, Y)
    assert abs(j32 - j64) / j64 < 1e-5


def test_f32_degradation_regime_is_outside_pipeline_position():
    """Document WHERE f32 degrades: unstandardized features whose column
    scales span 1e4 push the f32 Gram past 2^24 dynamic range. Every
    reference app standardizes (StandardScaler) before the solver
    (RandomPatchCifar.scala:63-66), and so do ours — after scaling the
    same data is back at parity."""
    n, d, k = 1024, 64, 3
    rng = np.random.default_rng(2)
    X = rng.standard_normal((n, d)) * np.logspace(4, -2, d)
    Y = rng.standard_normal((n, k))

    def f32_gap(Xu):
        G = Xu.T @ Xu + LAM * np.eye(d)
        W64 = np.linalg.solve(G, Xu.T @ Y)
        W32 = np.asarray(
            linalg.normal_equations(
                jnp.asarray(Xu, jnp.float32), jnp.asarray(Y, jnp.float32), LAM
            ),
            np.float64,
        )
        j64 = _objective(W64, Xu, Y)
        return abs(_objective(W32, Xu, Y) - j64) / j64

    raw_gap = f32_gap(X)
    Xs = (X - X.mean(0)) / X.std(0)
    scaled_gap = f32_gap(Xs)
    # after StandardScaler the gap collapses to the parity bound
    assert scaled_gap < 1e-5
    # and is at least no worse than the raw-feature gap (documentation
    # assert: the raw regime is the one to avoid)
    assert scaled_gap <= raw_gap + 1e-12


def test_solver_precision_env_knob():
    """KEYSTONE_SOLVER_PRECISION overrides the solver matmul precision
    (PERFORMANCE.md documents the measured HIGH-vs-HIGHEST trade)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "from keystone_tpu.ops import linalg; "
         "print(linalg.SOLVER_PRECISION_NAME, linalg.SOLVER_PRECISION)"],
        env={**__import__("os").environ,
             "KEYSTONE_SOLVER_PRECISION": "high",
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, check=True,
    )
    name, prec = out.stdout.split()
    assert name == "high"
    assert prec == "HIGH"  # str(Precision.HIGH) == "HIGH", not "HIGHEST"
