"""Resilient execution (ISSUE 4): deterministic fault injection,
retry/backoff ingest, corrupt-record quarantine, the producer watchdog,
and checkpoint/resume for streaming fits."""
import io
import json
import os
import pickle
import tarfile
import time

import numpy as np
import pytest

from keystone_tpu.loaders.image_loader_utils import (
    iter_decoded_chunks,
    iter_tar_images,
    stream_tar_images,
)
from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.nodes.learning.linear import LinearMapEstimator
from keystone_tpu.nodes.stats import StandardScaler
from keystone_tpu.observability import MetricsRegistry, PipelineTrace
from keystone_tpu.parallel.dataset import ArrayDataset, ensure_array
from keystone_tpu.parallel.streaming import StreamingDataset, fit_streaming
from keystone_tpu.resilience import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    CorruptRecordError,
    FaultPlan,
    IngestTimeoutError,
    InjectedFaultError,
    Quarantine,
    QuarantineBudgetExceededError,
    RetryExhaustedError,
    RetryPolicy,
    StreamCheckpoint,
    TransientError,
    fit_fingerprint,
    inject,
)


def _xy(n=240, d=12, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = (rng.randn(n, d) * (1.0 + rng.rand(d))).astype(np.float32)
    Y = (X @ rng.randn(d, k) + 0.1 * rng.randn(n, k)).astype(np.float32)
    return X, Y


def _make_tar(path, n_images=10, corrupt=(), side=8, seed=0):
    """A tar of PNGs; indices in ``corrupt`` hold garbage bytes."""
    rng = np.random.RandomState(seed)
    from PIL import Image as PILImage

    with tarfile.open(path, "w") as tf:
        for i in range(n_images):
            if i in corrupt:
                data = b"definitely not an image"
            else:
                arr = (rng.rand(side, side, 3) * 255).astype(np.uint8)
                buf = io.BytesIO()
                PILImage.fromarray(arr).save(buf, format="PNG")
                data = buf.getvalue()
            info = tarfile.TarInfo(f"img{i:03d}.png")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return str(path)


# -- RetryPolicy -------------------------------------------------------------

def test_retry_succeeds_after_transients():
    calls = []
    policy = RetryPolicy(max_attempts=3, backoff_s=0.001)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("flaky disk")
        return "ok"

    with PipelineTrace("r") as tr:
        assert policy.call(flaky, site="unit") == "ok"
    assert len(calls) == 3
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.retry"] >= 2
    assert tr.resilience_stats.get("retry") == 2
    assert all(e["site"] == "unit" for e in tr.resilience)


def test_retry_non_retryable_propagates_immediately():
    calls = []
    policy = RetryPolicy(max_attempts=5, backoff_s=0.001)

    def broken():
        calls.append(1)
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        policy.call(broken, site="unit")
    assert len(calls) == 1  # no useless retries

    # corrupt records are explicitly non-retryable: quarantine, don't spin
    def corrupt():
        calls.append(1)
        raise CorruptRecordError("bad jpeg")

    calls.clear()
    with pytest.raises(CorruptRecordError):
        policy.call(corrupt, site="unit")
    assert len(calls) == 1


def test_retry_exhaustion_raises_with_cause():
    policy = RetryPolicy(max_attempts=3, backoff_s=0.001)

    def always():
        raise TransientError("still down")

    with pytest.raises(RetryExhaustedError) as exc:
        policy.call(always, site="ingest.read")
    assert "ingest.read" in str(exc.value)
    assert "3 attempt" in str(exc.value)
    assert isinstance(exc.value.__cause__, TransientError)
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.retry_exhausted"] >= 1


def test_retry_backoff_deterministic_and_capped():
    a = RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=0.3,
                    jitter=0.5, seed=7)
    b = RetryPolicy(backoff_s=0.1, multiplier=2.0, max_backoff_s=0.3,
                    jitter=0.5, seed=7)
    seq_a = [a.backoff(i) for i in range(1, 6)]
    seq_b = [b.backoff(i) for i in range(1, 6)]
    assert seq_a == seq_b  # seeded jitter
    # base is capped at max_backoff_s; jitter stretches by at most 50%
    assert all(d <= 0.3 * 1.5 for d in seq_a)
    assert all(d >= 0.1 for d in seq_a)


def test_retry_attempt_timeout_counts_as_transient():
    calls = []
    policy = RetryPolicy(max_attempts=2, backoff_s=0.001,
                         attempt_timeout_s=0.2)

    def slow_then_fast():
        calls.append(1)
        if len(calls) == 1:
            time.sleep(1.0)  # overruns the per-attempt timeout
        return "done"

    assert policy.call(slow_then_fast, site="unit") == "done"
    assert len(calls) == 2


# -- fault-injection harness -------------------------------------------------

def test_fault_plan_seeded_and_deterministic():
    def run(seed):
        hits = 0
        with FaultPlan(seed=seed).add("site", rate=0.3):
            for i in range(200):
                try:
                    inject("site", i)
                except InjectedFaultError:
                    hits += 1
        return hits

    h1, h2 = run(11), run(11)
    assert h1 == h2 and 20 < h1 < 100  # same seed, ~30% rate
    assert run(12) != h1  # a different seed lands differently


def test_fault_plan_after_and_count_are_exact():
    plan = FaultPlan().add("site", after=3, count=2)
    seen = []
    with plan:
        for i in range(10):
            try:
                inject("site", i)
                seen.append(i)
            except InjectedFaultError:
                pass
    # visits 4 and 5 injected (after=3 skips the first 3), count caps at 2
    assert seen == [0, 1, 2, 5, 6, 7, 8, 9]
    assert plan.injections("site") == 2


def test_inject_is_noop_without_plan_and_plans_do_not_nest():
    inject("anything", context="no plan active")  # must not raise
    with FaultPlan():
        with pytest.raises(RuntimeError, match="already active"):
            FaultPlan().__enter__()


# -- quarantine --------------------------------------------------------------

def test_quarantine_budget_exceeded_names_source():
    q = Quarantine(max_bad_fraction=0.01, min_records=10, label="cifar")
    q.record_ok(500)
    q.quarantine("a.tar::img1.png", "undecodable")  # 1 of 501: fine
    for i in range(2, 6):
        q.quarantine(f"a.tar::img{i}.png", "undecodable")
    with pytest.raises(QuarantineBudgetExceededError) as exc:
        q.quarantine("a.tar::img6.png", "undecodable")
    msg = str(exc.value)
    assert "cifar" in msg and "a.tar::img6.png" in msg
    assert "max_bad_fraction" in msg


def test_quarantine_idempotent_manifest_and_state(tmp_path):
    manifest = str(tmp_path / "quarantine.jsonl")
    q = Quarantine(max_bad_fraction=0.5, min_records=1,
                   manifest_path=manifest, label="t")
    q.record_ok(10)
    q.quarantine("tar::a.png", "bad bytes")
    q.quarantine("tar::a.png", "bad bytes")  # replay: same identity
    q.quarantine("tar::b.png", "bad bytes")
    assert q.bad_count == 2 and q.ok_count == 10
    lines = [json.loads(ln) for ln in open(manifest)]
    assert [e["source"] for e in lines] == ["tar::a.png", "tar::b.png"]
    # checkpoint round-trip: bad records persist, oks reset (a resume
    # replays the stream and recounts them)
    state = q.state()
    q2 = Quarantine(max_bad_fraction=0.5, min_records=1, label="t")
    q2.restore(state)
    assert q2.bad_count == 2 and q2.ok_count == 0
    q2.quarantine("tar::a.png", "bad bytes")  # replayed: still deduped
    assert q2.bad_count == 2


# -- tar decode pool under faults (satellite) --------------------------------

def test_tar_one_corrupt_member_streamed_not_fatal_not_silent(tmp_path):
    """One corrupt member in a tar stream is quarantined: the stream
    completes with the other images, and the bad record is COUNTED
    (quarantine manifest + metrics), never silently dropped."""
    tar = _make_tar(tmp_path / "imgs.tar", n_images=10, corrupt={4})
    with PipelineTrace("tar") as tr:
        stream = stream_tar_images([tar], chunk_size=4)
        rows = sum(c.n for c in stream.chunks())
    assert rows == 9  # not fatal: the other nine images arrive
    assert stream.quarantine.bad_count == 1
    assert stream.quarantine.ok_count == 9
    (rec,) = stream.quarantine.records
    assert rec["source"].endswith("imgs.tar::img004.png")
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.quarantine"] >= 1
    assert tr.resilience_stats.get("quarantine") == 1


@pytest.mark.parametrize("serial", [True, False])
def test_tar_one_corrupt_member_serial_and_pooled(tmp_path, monkeypatch,
                                                  serial):
    """The same guarantee under serial iteration (iter_tar_images) and
    the single-threaded decode pool."""
    tar = _make_tar(tmp_path / "imgs.tar", n_images=8, corrupt={2})
    q = Quarantine(label="t")
    if serial:
        imgs = list(iter_tar_images(tar, quarantine=q))
    else:
        monkeypatch.setenv("KEYSTONE_LOADER_THREADS", "1")
        imgs = [item for chunk in iter_decoded_chunks(
            [tar], 4, quarantine=q) for item in chunk]
    assert len(imgs) == 7
    assert q.bad_count == 1 and q.ok_count == 7
    assert q.records[0]["source"].endswith("::img002.png")


def test_tar_quarantine_budget_fails_loudly(tmp_path):
    tar = _make_tar(tmp_path / "imgs.tar", n_images=10,
                    corrupt={1, 3, 5, 7})
    q = Quarantine(max_bad_fraction=0.1, min_records=10, label="imgs")
    stream = stream_tar_images([tar], chunk_size=4, quarantine=q)
    with pytest.raises(QuarantineBudgetExceededError) as exc:
        list(stream.chunks())
    assert "imgs.tar::img" in str(exc.value)


def test_tar_transient_decode_faults_are_retried(tmp_path):
    """Seeded transient faults at the decode site: every image still
    arrives (the retry absorbed the fault) and the retries are counted
    in metrics and the trace."""
    tar = _make_tar(tmp_path / "imgs.tar", n_images=12)
    policy = RetryPolicy(max_attempts=5, backoff_s=0.001)
    plan = FaultPlan(seed=5).add("ingest.decode", rate=0.3)
    with PipelineTrace("faulty") as tr:
        with plan:
            stream = stream_tar_images([tar], chunk_size=4,
                                       retry_policy=policy)
            rows = sum(c.n for c in stream.chunks())
    assert rows == 12  # nothing lost to transient faults
    assert plan.injections("ingest.decode") > 0
    assert stream.quarantine.bad_count == 0
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.retry"] >= plan.injections()
    assert tr.resilience_stats.get("retry", 0) >= 1
    assert tr.resilience_stats.get("fault_injected", 0) >= 1


def test_tar_transient_read_faults_are_retried(tmp_path):
    tar = _make_tar(tmp_path / "imgs.tar", n_images=6)
    policy = RetryPolicy(max_attempts=4, backoff_s=0.001)
    plan = FaultPlan(seed=2).add("ingest.read", rate=0.4)
    with plan:
        q = Quarantine(label="t")
        imgs = list(iter_tar_images(tar, quarantine=q,
                                    retry_policy=policy))
    assert len(imgs) == 6
    assert plan.injections("ingest.read") > 0


# -- staging retry + producer watchdog ---------------------------------------

def test_staging_transient_faults_retried_with_exact_results():
    """Transient device-staging failures are retried; the fit's result
    is bit-identical to a fault-free run (a retried upload re-stages the
    same chunk)."""
    X, Y = _xy()
    clean = fit_streaming(LinearMapEstimator(lam=0.1),
                          StreamingDataset.from_numpy(X, chunk_size=64), Y)
    policy = RetryPolicy(max_attempts=5, backoff_s=0.001)
    plan = FaultPlan(seed=9).add("ingest.stage", rate=0.3)
    with plan:
        faulty = fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=64,
                                        retry_policy=policy), Y)
    assert plan.injections("ingest.stage") > 0
    np.testing.assert_array_equal(np.asarray(clean.weights),
                                  np.asarray(faulty.weights))


def test_staging_retry_exhaustion_fails_loudly():
    X, _ = _xy(n=128)
    policy = RetryPolicy(max_attempts=2, backoff_s=0.001)
    with FaultPlan().add("ingest.stage", rate=1.0):  # every attempt fails
        stream = StreamingDataset.from_numpy(X, chunk_size=64,
                                             retry_policy=policy)
        with pytest.raises(RetryExhaustedError, match="ingest.stage"):
            list(stream.chunks())


def test_watchdog_converts_hung_producer_to_clear_error():
    X, _ = _xy(n=256)
    plan = FaultPlan().add("ingest.produce", kind="hang", after=1,
                           count=1, delay_s=30.0)
    t0 = time.monotonic()
    with plan:
        stream = StreamingDataset.from_numpy(
            X, chunk_size=64, tag="hung", stall_timeout_s=0.5)
        with pytest.raises(IngestTimeoutError) as exc:
            list(stream.chunks())
    assert time.monotonic() - t0 < 10.0  # no indefinite block
    msg = str(exc.value)
    assert "hung" in msg and "stall_timeout_s" in msg
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.watchdog_trip"] >= 1


def test_latency_spike_stalls_but_completes():
    """A latency spike (not a hang) shows up as ingest stall, not an
    error — the stream completes with every row."""
    X, _ = _xy(n=256)
    plan = FaultPlan().add("ingest.produce", kind="latency", after=1,
                           count=1, delay_s=0.3)
    with plan:
        stream = StreamingDataset.from_numpy(
            X, chunk_size=64, stall_timeout_s=5.0)
        rows = sum(c.n for c in stream.chunks())
    assert rows == 256
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["histograms"]["streaming.ingest_stall_s"]["max"] >= 0.2


# -- checkpoint/resume -------------------------------------------------------

@pytest.mark.parametrize("chunk_size", [48, 64, 96])
def test_kill_and_resume_matches_uninterrupted(tmp_path, chunk_size):
    """Acceptance: a streamed fit killed mid-stream (injected fault
    after chunk k) and resumed from its last checkpoint yields weights
    within 1e-5 (identical argmax) of the uninterrupted fit, across
    chunk sizes including a ragged tail."""
    X, Y = _xy(n=200)  # 200/48, 200/64, 200/96 all leave ragged tails

    def stream():
        return StreamingDataset.from_numpy(X, chunk_size=chunk_size,
                                           tag="kr")

    uninterrupted = fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y)
    ckdir = str(tmp_path / f"ck{chunk_size}")
    plan = FaultPlan().add("ingest.produce", after=2, count=1,
                           error=RuntimeError)
    with plan:
        with pytest.raises(RuntimeError, match="injected fault"):
            fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y,
                          checkpoint_dir=ckdir, checkpoint_every=1)
    assert os.path.exists(os.path.join(ckdir, "stream_fit.ckpt"))
    with PipelineTrace("resume") as tr:
        resumed = fit_streaming(LinearMapEstimator(lam=0.1), stream(), Y,
                                checkpoint_dir=ckdir, checkpoint_every=1)
    assert tr.resilience_stats.get("checkpoint_restore") == 1
    w_u = np.asarray(uninterrupted.weights)
    w_r = np.asarray(resumed.weights)
    assert np.abs(w_u - w_r).max() <= 1e-5 * max(np.abs(w_u).max(), 1.0)
    ds = ArrayDataset.from_numpy(X)
    pred_u = np.argmax(np.asarray(
        ensure_array(uninterrupted.apply_dataset(ds)).numpy()), axis=1)
    pred_r = np.argmax(np.asarray(
        ensure_array(resumed.apply_dataset(ds)).numpy()), axis=1)
    np.testing.assert_array_equal(pred_u, pred_r)
    # the snapshot is cleared after a successful finalize
    assert not os.path.exists(os.path.join(ckdir, "stream_fit.ckpt"))


def test_kill_and_resume_auto_solver(tmp_path):
    """The LeastSquares auto-solver resumes through the same carry."""
    X, Y = _xy(n=160, d=8)

    def stream():
        return StreamingDataset.from_numpy(X, chunk_size=48, tag="auto")

    base = fit_streaming(LeastSquaresEstimator(lam=0.1), stream(), Y)
    ckdir = str(tmp_path / "ck")
    with FaultPlan().add("ingest.produce", after=2, count=1,
                         error=RuntimeError):
        with pytest.raises(RuntimeError):
            fit_streaming(LeastSquaresEstimator(lam=0.1), stream(), Y,
                          checkpoint_dir=ckdir, checkpoint_every=1)
    resumed = fit_streaming(LeastSquaresEstimator(lam=0.1), stream(), Y,
                            checkpoint_dir=ckdir, checkpoint_every=1)
    w_b, w_r = np.asarray(base.weights), np.asarray(resumed.weights)
    assert np.abs(w_b - w_r).max() <= 1e-5 * max(np.abs(w_b).max(), 1.0)


def test_checkpoint_fingerprint_mismatch_refuses_resume(tmp_path):
    X, Y = _xy(n=160)
    ckdir = str(tmp_path / "ck")
    with FaultPlan().add("ingest.produce", after=2, count=1,
                         error=RuntimeError):
        with pytest.raises(RuntimeError):
            fit_streaming(
                LinearMapEstimator(lam=0.1),
                StreamingDataset.from_numpy(X, chunk_size=48), Y,
                checkpoint_dir=ckdir, checkpoint_every=1)
    # different lam -> different fingerprint -> refuse
    with pytest.raises(CheckpointMismatchError, match="refusing to resume"):
        fit_streaming(
            LinearMapEstimator(lam=0.5),
            StreamingDataset.from_numpy(X, chunk_size=48), Y,
            checkpoint_dir=ckdir)
    # different chunk geometry -> refuse too
    with pytest.raises(CheckpointMismatchError):
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=96), Y,
            checkpoint_dir=ckdir)


def test_stream_checkpoint_corrupt_file_raises(tmp_path):
    ckdir = str(tmp_path / "ck")
    ck = StreamCheckpoint(ckdir)
    with open(ck.path, "wb") as f:
        f.write(b"\x80garbage not a pickle")
    with pytest.raises(CheckpointCorruptError, match="stream_fit.ckpt"):
        ck.load("anything")
    # an unrelated complete pickle is "wrong format", also corrupt
    with open(ck.path, "wb") as f:
        pickle.dump({"some": "dict"}, f)
    with pytest.raises(CheckpointCorruptError, match="format header"):
        ck.load("anything")


def test_checkpoint_persists_quarantine_state(tmp_path):
    X, Y = _xy(n=200)
    q = Quarantine(max_bad_fraction=0.5, min_records=10, label="t")
    q.quarantine("tar::bad.png", "bad")
    ckdir = str(tmp_path / "ck")
    with FaultPlan().add("ingest.produce", after=2, count=1,
                         error=RuntimeError):
        with pytest.raises(RuntimeError):
            fit_streaming(
                LinearMapEstimator(lam=0.1),
                StreamingDataset.from_numpy(X, chunk_size=48), Y,
                checkpoint_dir=ckdir, checkpoint_every=1, quarantine=q)
    q2 = Quarantine(max_bad_fraction=0.5, min_records=10, label="t")
    fit_streaming(LinearMapEstimator(lam=0.1),
                  StreamingDataset.from_numpy(X, chunk_size=48), Y,
                  checkpoint_dir=ckdir, checkpoint_every=1, quarantine=q2)
    assert q2.bad_count == 1  # restored from the snapshot
    assert q2.records[0]["source"] == "tar::bad.png"


def test_checkpoint_every_requires_dir_and_validates():
    X, Y = _xy(n=96)
    with pytest.raises(ValueError, match="requires checkpoint_dir"):
        fit_streaming(LinearMapEstimator(lam=0.1),
                      StreamingDataset.from_numpy(X, chunk_size=48), Y,
                      checkpoint_every=2)


def test_estimator_fit_forwards_stream_options(tmp_path):
    """The resilience options ride Estimator.fit / LabelEstimator.fit;
    resident fits reject them with a clear error."""
    X, Y = _xy(n=160)
    ckdir = str(tmp_path / "ck")
    model = LinearMapEstimator(lam=0.1).fit(
        StreamingDataset.from_numpy(X, chunk_size=48), Y,
        checkpoint_dir=ckdir, checkpoint_every=2)
    resident = LinearMapEstimator(lam=0.1)._fit(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    assert np.abs(np.asarray(model.weights)
                  - np.asarray(resident.weights)).max() <= 1e-4
    scaler = StandardScaler().fit(
        StreamingDataset.from_numpy(X, chunk_size=48),
        checkpoint_dir=str(tmp_path / "ck2"))
    assert scaler is not None
    with pytest.raises(TypeError, match="require a StreamingDataset"):
        LinearMapEstimator(lam=0.1).fit(X, Y, checkpoint_dir=ckdir)
    with pytest.raises(TypeError, match="require a StreamingDataset"):
        StandardScaler().fit(ArrayDataset.from_numpy(X),
                             checkpoint_dir=ckdir)


# -- acceptance: mixed faults at CIFAR scale ---------------------------------

def test_streamed_fit_completes_under_mixed_faults():
    """Acceptance: seeded 10%+ transient staging faults plus one
    producer stall — the streamed fit completes with results identical
    to the fault-free run, and retry counts land in metrics and the
    PipelineTrace."""
    X, Y = _xy(n=1024, d=24, k=10, seed=3)
    clean = fit_streaming(
        LinearMapEstimator(lam=0.1),
        StreamingDataset.from_numpy(X, chunk_size=64), Y)
    policy = RetryPolicy(max_attempts=6, backoff_s=0.001)
    plan = (FaultPlan(seed=7)
            .add("ingest.stage", rate=0.1)
            .add("ingest.produce", kind="latency", after=2, count=1,
                 delay_s=0.2))
    MetricsRegistry.reset()
    with PipelineTrace("mixed-faults") as tr:
        with plan:
            model = fit_streaming(
                LinearMapEstimator(lam=0.1),
                StreamingDataset.from_numpy(
                    X, chunk_size=64, retry_policy=policy,
                    stall_timeout_s=30.0), Y)
    assert plan.injections("ingest.stage") > 0
    np.testing.assert_array_equal(np.asarray(clean.weights),
                                  np.asarray(model.weights))
    snap = MetricsRegistry.get_or_create().snapshot()
    assert snap["counters"]["resilience.retry"] >= plan.injections(
        "ingest.stage")
    assert snap["counters"]["resilience.fault_injected"] == (
        plan.injections())
    assert tr.resilience_stats.get("retry", 0) >= 1
    assert "resilience events" in tr.summary()
    # round trip keeps the resilience stream
    rt = PipelineTrace.from_json(tr.to_json())
    assert rt.resilience_stats == tr.resilience_stats
    assert rt.resilience[-1]["event"] == tr.resilience[-1]["event"]


def test_streamed_tar_fit_quarantines_and_completes(tmp_path):
    """End-to-end over the tar path: a corrupt member plus transient
    decode faults; the fit completes on the 15 good images and the
    quarantine/retry counts are visible."""
    tar = _make_tar(tmp_path / "imgs.tar", n_images=16, corrupt={5},
                    side=8)
    policy = RetryPolicy(max_attempts=5, backoff_s=0.001)
    plan = FaultPlan(seed=4).add("ingest.decode", rate=0.2)
    with PipelineTrace("tar-fit") as tr:
        with plan:
            root = stream_tar_images([tar], chunk_size=4,
                                     retry_policy=policy)
            stream = root.map_chunks(lambda ad: ArrayDataset(
                ad.data.reshape(ad.padded_n, -1), ad.n, ad.mesh,
                _already_sharded=True))
            # derived views carry the loader's quarantine, and
            # fit_streaming picks it up without being told
            assert stream.quarantine is root.quarantine
            scaler = fit_streaming(StandardScaler(), stream)
    assert np.asarray(scaler.mean).shape == (8 * 8 * 3,)
    assert root.quarantine.bad_count == 1
    assert root.quarantine.ok_count == 15
    assert tr.resilience_stats.get("quarantine") == 1
    assert tr.resilience_stats.get("retry", 0) >= 1


# -- utils/checkpoint hardening (satellite) ----------------------------------

def test_resident_labels_content_change_refuses_resume(tmp_path):
    """The fingerprint digests RESIDENT label content: resuming with
    different labels of the same shape refuses instead of silently
    folding the stale carry into new data."""
    X, Y = _xy(n=160)
    ckdir = str(tmp_path / "ck")
    with FaultPlan().add("ingest.produce", after=2, count=1,
                         error=RuntimeError):
        with pytest.raises(RuntimeError):
            fit_streaming(
                LinearMapEstimator(lam=0.1),
                StreamingDataset.from_numpy(X, chunk_size=48), Y,
                checkpoint_dir=ckdir, checkpoint_every=1)
    Y2 = Y.copy()
    Y2[0, 0] += 1.0  # same shape/dtype, different content
    with pytest.raises(CheckpointMismatchError):
        fit_streaming(
            LinearMapEstimator(lam=0.1),
            StreamingDataset.from_numpy(X, chunk_size=48), Y2,
            checkpoint_dir=ckdir)


def test_pipeline_checkpoint_corrupt_file_raises(tmp_path):
    from keystone_tpu.utils import load_pipeline, load_state

    path = str(tmp_path / "model.pkl")
    with open(path, "wb") as f:
        f.write(b"\x80\x04 truncated pickle garbage")
    with pytest.raises(CheckpointCorruptError, match="model.pkl"):
        load_pipeline(path)
    with pytest.raises(CheckpointCorruptError):
        load_state(path)
    with pytest.raises(FileNotFoundError):
        load_pipeline(str(tmp_path / "missing.pkl"))


def test_pipeline_checkpoint_wrong_kind_and_legacy(tmp_path):
    from keystone_tpu.utils import load_pipeline, load_state, save_state
    from keystone_tpu.utils.checkpoint import _FORMAT, _VERSION

    state_path = str(tmp_path / "state.pkl")
    assert save_state(state_path) == 0  # fresh env: zero entries, valid
    assert load_state(state_path) == 0
    # a state artifact is not a pipeline artifact
    with pytest.raises(CheckpointCorruptError, match="state"):
        load_pipeline(state_path)
    # future versions are refused with a clear error, not a traceback
    vpath = str(tmp_path / "future.pkl")
    with open(vpath, "wb") as f:
        pickle.dump({"format": _FORMAT, "version": _VERSION + 1,
                     "kind": "state", "payload": {}}, f)
    with pytest.raises(CheckpointCorruptError, match="version"):
        load_state(vpath)
    # legacy headerless artifacts (pre-resilience) still load
    legacy = str(tmp_path / "legacy.pkl")
    with open(legacy, "wb") as f:
        pickle.dump({}, f)
    assert load_state(legacy) == 0


def test_save_state_write_is_atomic(tmp_path, monkeypatch):
    """A crash mid-write must leave the previous artifact intact: the
    dump goes to a temp file first, then os.replace."""
    from keystone_tpu.utils import checkpoint as cp

    path = str(tmp_path / "state.pkl")
    cp.save_state(path)
    before = open(path, "rb").read()

    real_dump = pickle.dump

    def exploding_dump(obj, f, *a, **kw):
        f.write(b"partial")
        raise OSError("disk full")

    monkeypatch.setattr(cp.pickle, "dump", exploding_dump)
    with pytest.raises(OSError):
        cp.save_state(path)
    monkeypatch.setattr(cp.pickle, "dump", real_dump)
    assert open(path, "rb").read() == before  # untouched
    assert cp.load_state(path) == 0


# -- bench durations validation (satellite) ----------------------------------

def test_bench_durations_discard_corrupt_and_invalid(tmp_path, monkeypatch,
                                                     capsys):
    import bench

    path = str(tmp_path / ".bench_durations.json")
    monkeypatch.setattr(bench, "_DURATIONS_PATH", path)
    # missing file: empty, silent
    assert bench._load_durations() == {}
    # bad JSON: discarded with a warning, not a crash
    with open(path, "w") as f:
        f.write("{not json at all")
    assert bench._load_durations() == {}
    assert "discarding unreadable" in capsys.readouterr().err
    # non-dict JSON
    with open(path, "w") as f:
        json.dump([1, 2, 3], f)
    assert bench._load_durations() == {}
    assert "expected a JSON object" in capsys.readouterr().err
    # hand-edited entries: negative / non-numeric / non-finite dropped,
    # valid ones kept
    with open(path, "w") as f:
        json.dump({"good": 12.5, "negative": -3, "words": "fast",
                   "inf": 1e999, "bool": True}, f)
    assert bench._load_durations() == {"good": 12.5}
    err = capsys.readouterr().err
    assert "invalid duration" in err
    # the regeneration path: recording overwrites cleanly
    bench._record_duration("good", 9.9)
    assert bench._load_durations() == {"good": 9.9}


# -- swallow-all-handler lint (satellite) ------------------------------------

def test_swallow_all_handler_lint_fires_on_offenders():
    import ast

    from keystone_tpu.analysis.diagnostics import swallow_all_handlers

    src = (
        "try:\n    x()\nexcept Exception:\n    pass\n"
        "try:\n    y()\nexcept:\n    z = 1\n"
        "try:\n    w()\nexcept ValueError:\n    pass\n"          # narrow: ok
        "try:\n    v()\nexcept Exception as e:\n    raise\n"     # re-raise: ok
        "try:\n    u()\nexcept (OSError, Exception):\n    ...\n"
    )
    hits = swallow_all_handlers(ast.parse(src))
    assert len(hits) == 3
    kinds = [what for _, what in hits]
    assert any("bare" in k for k in kinds)
    assert sum("Exception" in k for k in kinds) == 2


def test_ingest_and_workflow_tree_has_no_swallow_all_handlers():
    """The repo gate's own invariant: zero offenders in the scoped
    directories (tools/lint.py enforces this before every PR)."""
    import ast
    import pathlib

    from keystone_tpu.analysis.diagnostics import (
        SWALLOW_ALL_SCOPES,
        swallow_all_handlers,
    )

    pkg = pathlib.Path(__file__).resolve().parent.parent / "keystone_tpu"
    offenders = []
    for scope in SWALLOW_ALL_SCOPES:
        for path in sorted((pkg / scope).rglob("*.py")):
            tree = ast.parse(path.read_text())
            offenders += [(str(path), lineno, what)
                          for lineno, what in swallow_all_handlers(tree)]
    assert not offenders, offenders


# -- quarantine/label alignment helper (ISSUE 11 satellite) ------------------

def test_drop_quarantined_rows_pairs_corrupt_tar_with_full_labels(tmp_path):
    """The PR 4 footgun, closed: a corrupt-member tar SHRINKS the
    stream, so labels sized for the full member count (the natural way
    to build them — one row per tar member) misalign. The misalignment
    error now names drop_quarantined_rows; applying it makes the fit
    succeed with exactly the surviving rows."""
    from keystone_tpu.resilience import drop_quarantined_rows

    n_images, corrupt_idx = 12, {4}
    tar = _make_tar(tmp_path / "imgs.tar", n_images=n_images,
                    corrupt=corrupt_idx)
    # labels built for EVERY member, keyed the way the loader keys
    # quarantine entries: "<tar>::<member>"
    keys = [f"{tar}::img{i:03d}.png" for i in range(n_images)]
    rng = np.random.RandomState(0)
    y_full = rng.randn(n_images, 3).astype(np.float32)

    def prepare(batch):
        return np.stack([img for _, img in batch]).reshape(
            len(batch), -1).astype(np.float32)

    # pass 1: consume the stream so the quarantine fills, then prove
    # the misalignment error points at the helper
    stream = stream_tar_images([tar], chunk_size=4, prepare=prepare,
                               quarantine=Quarantine(max_bad_fraction=0.5,
                                                     min_records=1))
    with pytest.raises(ValueError, match="drop_quarantined_rows"):
        fit_streaming(LinearMapEstimator(lam=0.1), stream, y_full,
                      quarantine=stream.quarantine)
    assert stream.quarantine.bad_count == len(corrupt_idx)

    # pass 2: drop the quarantined rows -> aligned fit succeeds
    y_aligned = drop_quarantined_rows(y_full, keys, stream.quarantine)
    assert y_aligned.shape[0] == n_images - len(corrupt_idx)
    stream2 = stream_tar_images([tar], chunk_size=4, prepare=prepare,
                                quarantine=stream.quarantine)
    model = fit_streaming(LinearMapEstimator(lam=0.1), stream2, y_aligned,
                          quarantine=stream2.quarantine)
    assert np.isfinite(np.asarray(model.weights)).all()


def test_drop_quarantined_rows_validates_key_count():
    from keystone_tpu.resilience import drop_quarantined_rows

    q = Quarantine()
    with pytest.raises(ValueError, match="record keys"):
        drop_quarantined_rows(np.zeros((4, 2)), ["a", "b"], q)


# -- RetryPolicy repr (ISSUE 11 satellite) -----------------------------------

def test_retry_policy_repr_names_the_policy_in_force():
    """Post-mortems and logs print the policy; the repr must name the
    effective attempts/backoff/timeout instead of an address."""
    r = repr(RetryPolicy(max_attempts=5, backoff_s=0.1, multiplier=3.0,
                         max_backoff_s=4.0, jitter=0.25,
                         attempt_timeout_s=2.5))
    assert "attempts=5" in r and "0.1s*3^k<=4s" in r
    assert "jitter=0.25" in r and "attempt_timeout=2.5s" in r
    assert "0x" not in r  # no memory addresses
    assert "attempt_timeout=none" in repr(RetryPolicy())


def test_retry_exhausted_postmortem_names_policy(tmp_path):
    """The retry-exhausted post-mortem context carries the one-line
    policy identity."""
    policy = RetryPolicy(max_attempts=2, backoff_s=0.001)

    def always_fails():
        raise TransientError("nope")

    with pytest.raises(RetryExhaustedError) as exc_info:
        policy.call(always_fails, site="t")
    pm = getattr(exc_info.value, "postmortem_path", None)
    if pm:  # postmortem dumping enabled in this environment
        blob = json.load(open(pm))
        assert "attempts=2" in blob.get("context", {}).get("policy", "")
