"""Tests for the linguistic annotation nodes (reference
CoreNLPFeatureExtractor/POSTagger/NER suites — the reference tests these
through pipeline usage; here each node surface gets direct coverage)."""
import numpy as np

from keystone_tpu.nodes.nlp import (
    CoreNLPFeatureExtractor,
    NER,
    POSTagger,
    RuleBasedNerModel,
    RuleBasedPosModel,
    english_lemmatize,
)


# ------------------------------------------------------------- lemmatizer


def test_lemmatizer_irregulars():
    assert english_lemmatize("was") == "be"
    assert english_lemmatize("children") == "child"
    assert english_lemmatize("wrote") == "write"
    assert english_lemmatize("geese") == "goose"


def test_lemmatizer_suffix_rules():
    assert english_lemmatize("cities") == "city"
    assert english_lemmatize("churches") == "church"
    assert english_lemmatize("dogs") == "dog"
    assert english_lemmatize("running") == "run"       # undoubling
    assert english_lemmatize("making") == "make"       # CVC e-restore
    assert english_lemmatize("jumped") == "jump"
    assert english_lemmatize("studied") == "study"
    assert english_lemmatize("tried") == "try"
    assert english_lemmatize("stopped") == "stop"


def test_lemmatizer_pos_gated_comparatives():
    # -er stripping only for adjective/adverb tags
    assert english_lemmatize("faster", "JJR") == "fast"
    assert english_lemmatize("biggest", "JJS") == "big"
    assert english_lemmatize("corner", "NN") == "corner"
    assert english_lemmatize("water") == "water"


def test_lemmatizer_keeps_short_and_safe_words():
    assert english_lemmatize("is") == "be"  # irregular, not s-stripped
    assert english_lemmatize("bus") == "bus"
    assert english_lemmatize("class") == "class"
    assert english_lemmatize("analysis") == "analysis"


# -------------------------------------------------------------------- POS


def test_pos_tagger_sentence():
    tagged = POSTagger().apply(
        "The quick dogs are running quickly".split()
    )
    assert tagged.words[0] == "The"
    got = dict(tagged.pairs())
    assert got["The"] == "DT"
    assert got["dogs"] == "NNS"
    assert got["are"] == "VBP"
    assert got["running"] == "VBG"
    assert got["quickly"] == "RB"


def test_pos_tagger_numbers_and_proper_nouns():
    tagged = RuleBasedPosModel().best_sequence(
        ["She", "saw", "Paris", "in", "1999"]
    )
    got = dict(tagged.pairs())
    assert got["She"] == "PRP"
    assert got["Paris"] == "NNP"
    assert got["in"] == "IN"
    assert got["1999"] == "CD"


def test_pos_tagger_pluggable_model():
    class Upper:
        def best_sequence(self, words):
            from keystone_tpu.nodes.nlp.corenlp import TaggedSequence

            return TaggedSequence(list(words), ["X"] * len(words))

    assert POSTagger(Upper()).apply(["a", "b"]).tags == ["X", "X"]


# -------------------------------------------------------------------- NER


def test_ner_spans_and_labels():
    seg = NER().apply(
        "Yesterday Dr. Alice Smith flew to Paris with 3 colleagues".split()
    )
    by_label = {label: (start, end) for label, start, end in seg.spans}
    assert "PERSON" in by_label
    start, end = by_label["PERSON"]
    assert seg.words[start:end] == ["Dr.", "Alice", "Smith"]
    assert "LOCATION" in by_label
    lstart, lend = by_label["LOCATION"]
    assert seg.words[lstart:lend] == ["Paris"]
    assert "NUMBER" in by_label
    labels = seg.labels
    assert labels[seg.words.index("to")] == "O"


def test_ner_organization():
    seg = RuleBasedNerModel().best_sequence(
        "He joined Acme Corp last year".split()
    )
    assert ("ORGANIZATION", 2, 4) in seg.spans


def test_ner_sentence_initial_capital_not_entity():
    seg = RuleBasedNerModel().best_sequence("Running is fun".split())
    assert seg.spans == []


# ---------------------------------------------- CoreNLPFeatureExtractor


def test_corenlp_extractor_lemmatizes_and_entity_types():
    out = CoreNLPFeatureExtractor([1]).apply("Alice visited Paris. The dogs were running.")
    assert "PERSON" in out
    assert "LOCATION" in out
    assert "dog" in out            # lemmatized plural
    assert "run" in out            # lemmatized gerund
    assert "be" in out             # were -> be
    assert "dogs" not in out


def test_corenlp_extractor_respects_sentence_boundaries():
    out = CoreNLPFeatureExtractor([2]).apply("Cats sleep. Dogs bark.")
    # no bigram spans the sentence boundary ("sleep dog" must not appear)
    assert "cat sleep" in out
    assert "dog bark" in out
    assert all("sleep dog" != g for g in out)


def test_corenlp_extractor_multiple_orders():
    out = CoreNLPFeatureExtractor([1, 2]).apply("big red cars stopped")
    assert "big" in out and "big red" in out and "red car" in out
    assert "car stop" in out


def test_corenlp_extractor_in_newsgroups_pipeline():
    """The lemmatizing featurizer variant trains end to end."""
    from keystone_tpu.loaders.csv_loader import LabeledData
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
    from keystone_tpu.pipelines.text.newsgroups import (
        NewsgroupsConfig,
        run,
    )

    docs, labels = [], []
    for i in range(12):
        if i % 2 == 0:
            docs.append("The spacecraft orbited Mars. Rockets launched daily.")
            labels.append(0)
        else:
            docs.append("The pitchers threw fastballs. Baseball games ended late.")
            labels.append(1)
    train = LabeledData(
        data=HostDataset(docs), labels=ArrayDataset.from_numpy(
            np.asarray(labels, np.int32))
    )
    _, eval_ = run(
        NewsgroupsConfig(n_grams=2, common_features=500, lemmatize=True),
        train=train, test=train, num_classes=2,
    )
    assert eval_.total_error < 0.2


def test_pos_tagger_comparatives_feed_lemmatizer():
    model = RuleBasedPosModel()
    tagged = model.best_sequence("the faster horses ran".split())
    got = dict(tagged.pairs())
    assert got["faster"] == "JJR"
    assert english_lemmatize("faster", got["faster"]) == "fast"
    # -er nouns stay nouns
    assert dict(model.best_sequence(["the", "computer"]).pairs())["computer"] == "NN"


def test_extractor_rejects_length_mismatched_model():
    import pytest as _pytest

    from keystone_tpu.nodes.nlp.corenlp import TaggedSequence

    class Short:
        def best_sequence(self, words):
            return TaggedSequence(list(words)[:-1], ["NN"] * (len(words) - 1))

    with _pytest.raises(ValueError):
        CoreNLPFeatureExtractor([1], pos_model=Short()).apply("a b c d")


def test_eq_key_distinguishes_custom_models():
    class Custom:
        def best_sequence(self, words):
            from keystone_tpu.nodes.nlp.corenlp import TaggedSequence

            return TaggedSequence(list(words), ["NN"] * len(words))

    a = CoreNLPFeatureExtractor([1], pos_model=Custom())
    b = CoreNLPFeatureExtractor([1], pos_model=Custom())
    assert a.eq_key() != b.eq_key()  # distinct custom instances never merge
    # stateless defaults do merge
    assert (
        CoreNLPFeatureExtractor([1]).eq_key()
        == CoreNLPFeatureExtractor([1]).eq_key()
    )
