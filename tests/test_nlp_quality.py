"""Quantified quality floors for the rule-based linguistic stand-ins
(VERDICT r2 next#7; reference wrapped trained Epic CRF/SemiCRF models,
``POSTagger.scala:24-35``, ``NER.scala:20-31``).

Gold standards are hand-tagged in-tree samples
(tests/resources/pos_tagged_sample.txt — 50 sentences, 423 tokens, Penn
conventions; tests/resources/ner_tagged_sample.txt — 30 sentences,
token-level entity labels). Measured on 2026-07-30 (documented in
PARITY.md): POS token accuracy 0.839, NER token-level F1 0.951. Floors
sit a few points under the measurement so a regression in the
lexicon/suffix/shape rules fails loudly while wording-level churn does
not. Trained models plug in via the same one-method protocol and can
only raise these numbers.
"""
import os

RES = os.path.join(os.path.dirname(__file__), "resources")


def _lines(name):
    return [l.strip() for l in open(os.path.join(RES, name))
            if l.strip() and not l.startswith("#")]


def test_pos_tagger_accuracy_floor():
    from keystone_tpu.nodes.nlp.corenlp import RuleBasedPosModel

    model = RuleBasedPosModel()
    total = correct = 0
    for line in _lines("pos_tagged_sample.txt"):
        pairs = [t.rsplit("_", 1) for t in line.split()]
        words = [w for w, _ in pairs]
        gold = [t for _, t in pairs]
        pred = model.best_sequence(words).tags
        assert len(pred) == len(words)
        total += len(words)
        correct += sum(g == p for g, p in zip(gold, pred))
    accuracy = correct / total
    assert total > 400, total
    assert accuracy >= 0.80, f"POS accuracy regressed: {accuracy:.4f}"


def test_perceptron_pos_beats_rule_based():
    """VERDICT r3 next#9: the TRAINED averaged perceptron (shipped
    weights, trained on the in-tree corpus, evaluated here on the
    held-out gold sample) must clearly beat the rule-based 0.839.
    Shipped artifact measures 0.9764 here (r5: corpus grown to 328
    sentences); floor a few points under."""
    from keystone_tpu.nodes.nlp.perceptron_pos import load_pretrained

    model = load_pretrained()
    assert model is not None, "shipped pos_perceptron.json.gz missing"
    total = correct = 0
    for line in _lines("pos_tagged_sample.txt"):
        pairs = [t.rsplit("_", 1) for t in line.split()]
        words = [w for w, _ in pairs]
        gold = [t for _, t in pairs]
        pred = model.best_sequence(words).tags
        assert len(pred) == len(words)
        total += len(words)
        correct += sum(g == p for g, p in zip(gold, pred))
    accuracy = correct / total
    assert accuracy >= 0.95, f"perceptron POS regressed: {accuracy:.4f}"


def test_pos_tagger_default_is_trained_model():
    """POSTagger() picks the shipped perceptron when present."""
    from keystone_tpu.nodes.nlp.corenlp import POSTagger
    from keystone_tpu.nodes.nlp.perceptron_pos import (
        AveragedPerceptronPosModel,
    )

    assert isinstance(POSTagger().model, AveragedPerceptronPosModel)


def test_perceptron_training_is_reproducible():
    """train() on the in-tree corpus converges and beats the rule-based
    model held-out — the shipped artifact is reproducible from source."""
    from keystone_tpu.nodes.nlp.perceptron_pos import (
        AveragedPerceptronPosModel,
        read_tagged_file,
    )

    train = read_tagged_file(os.path.join(RES, "pos_train_corpus.txt"))
    heldout = read_tagged_file(os.path.join(RES, "pos_tagged_sample.txt"))
    model = AveragedPerceptronPosModel.train(train, epochs=8)
    total = correct = 0
    for sent in heldout:
        pred = model.best_sequence([w for w, _ in sent]).tags
        total += len(sent)
        correct += sum(g == p for (_, g), p in zip(sent, pred))
    assert correct / total >= 0.95, correct / total


def test_ner_token_f1_floor():
    from keystone_tpu.nodes.nlp.corenlp import RuleBasedNerModel

    model = RuleBasedNerModel()
    tp = fp = fn = 0
    for line in _lines("ner_tagged_sample.txt"):
        pairs = [t.split("|") for t in line.split()]
        words = [w for w, _ in pairs]
        gold = [t for _, t in pairs]
        pred = model.best_sequence(words).labels
        assert len(pred) == len(words)
        for g, p in zip(gold, pred):
            if p != "O" and p == g:
                tp += 1
            elif p != "O":
                fp += 1
            if g != "O" and p != g:
                fn += 1
    assert tp + fn >= 55  # the sample must keep a real entity population
    assert tp + fp > 0, "model predicted zero entity tokens"
    precision = tp / (tp + fp)
    recall = tp / (tp + fn)
    f1 = 2 * precision * recall / max(precision + recall, 1e-12)
    assert f1 >= 0.90, f"NER F1 regressed: {f1:.4f} (P={precision:.3f} R={recall:.3f})"


def _ner_token_f1(model):
    tp = fp = fn = 0
    for line in _lines("ner_tagged_sample.txt"):
        pairs = [t.split("|") for t in line.split()]
        words = [w for w, _ in pairs]
        gold = [t for _, t in pairs]
        pred = model.best_sequence(words).labels
        assert len(pred) == len(words)
        for g, p in zip(gold, pred):
            if p != "O" and p == g:
                tp += 1
            elif p != "O":
                fp += 1
            if g != "O" and p != g:
                fn += 1
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    return 2 * precision * recall / max(precision + recall, 1e-12)


def test_perceptron_ner_beats_rule_based():
    """VERDICT r4 next#5: the TRAINED averaged perceptron (shipped
    weights, trained on the in-tree corpus, evaluated here on the
    held-out gold sample) must clearly beat the rule-based 0.9508.
    Shipped artifact measures 1.000 here; floor a few points under."""
    from keystone_tpu.nodes.nlp.perceptron_ner import load_pretrained

    model = load_pretrained()
    assert model is not None, "shipped ner_perceptron.json.gz missing"
    f1 = _ner_token_f1(model)
    assert f1 >= 0.97, f"perceptron NER regressed: {f1:.4f}"


def test_perceptron_ner_trains_from_in_tree_corpus():
    """The full train->evaluate loop stays reproducible offline: train
    on the in-tree corpus, beat the rule-based model on held-out."""
    from keystone_tpu.nodes.nlp.perceptron_ner import (
        AveragedPerceptronNerModel,
        read_labeled_file,
    )

    train = read_labeled_file(os.path.join(RES, "ner_train_corpus.txt"))
    assert len(train) >= 200
    model = AveragedPerceptronNerModel.train(train, epochs=8)
    assert _ner_token_f1(model) >= 0.96


def test_ner_default_is_trained_model():
    """NER() picks the shipped perceptron when present."""
    from keystone_tpu.nodes.nlp.corenlp import NER
    from keystone_tpu.nodes.nlp.perceptron_ner import (
        AveragedPerceptronNerModel,
    )

    assert isinstance(NER().model, AveragedPerceptronNerModel)


def test_ner_adjacent_same_type_entities_merge_into_one_span():
    """Regression pin for the documented span-merge limitation (ADVICE
    r5 low#4, perceptron_ner module docstring): token-level labels are
    exact, but ``best_sequence`` coalesces adjacent same-label tokens,
    so two distinct adjacent PERSON entities come back as ONE span.
    Hand-crafted weights make the decode deterministic; if span
    boundaries between adjacent entities ever become recoverable (BIO
    decoding), this test should be updated alongside the docstring."""
    from keystone_tpu.nodes.nlp.perceptron_ner import (
        AveragedPerceptronNerModel,
    )

    model = AveragedPerceptronNerModel(
        weights={"w=alice": {"PERSON": 5.0}, "w=bob": {"PERSON": 5.0},
                 "w=visited": {"O": 5.0}, "w=paris": {"LOCATION": 5.0}},
        labels=["LOCATION", "O", "PERSON"])
    words = ["Alice", "Bob", "visited", "Paris"]
    # token level: exact
    assert model.label_sequence(words) == [
        "PERSON", "PERSON", "O", "LOCATION"]
    seg = model.best_sequence(words)
    assert seg.labels == ["PERSON", "PERSON", "O", "LOCATION"]
    # span level: Alice and Bob — two people — merge into one span
    assert seg.spans == [("PERSON", 0, 2), ("LOCATION", 3, 4)]
