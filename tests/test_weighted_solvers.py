"""Weighted least-squares solver tests (mirror BlockWeightedLeastSquaresSuite
and PerClassWeightedLeastSquares checks against direct solves)."""
import numpy as np
import pytest

from keystone_tpu.nodes.learning.block_weighted import (
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.nodes.learning.per_class_weighted import (
    PerClassWeightedLeastSquaresEstimator,
)
from keystone_tpu.nodes.stats import CosineRandomFeatures


def make_problem(n=240, d=12, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, k, n)
    L = -np.ones((n, k), np.float32)
    L[np.arange(n), y] = 1.0
    return X, L, y


def direct_per_class_solve(X, L, y, lam, w):
    """Exact single-block solution of the per-class weighted problem."""
    n, d = X.shape
    k = L.shape[1]
    counts = np.bincount(y, minlength=k).astype(np.float64)
    pop_mean = X.mean(0)
    class_means = np.stack([X[y == c].mean(0) for c in range(k)])
    jfm = w * class_means + (1 - w) * pop_mean
    jlm = (counts / n) * 2 * (1 - w) - 1 + 2 * w
    W = np.zeros((d, k))
    for c in range(k):
        b = np.full(n, (1 - w) / n)
        b[y == c] += w / counts[c]
        Xzm = (X - jfm[c]).astype(np.float64)
        yc = (L[:, c] - jlm[c]).astype(np.float64)
        A = Xzm.T @ (Xzm * b[:, None]) + lam * np.eye(d)
        W[:, c] = np.linalg.solve(A, Xzm.T @ (b * yc))
    final_b = jlm - np.sum(jfm.T * W, axis=0)
    return W, final_b


def test_per_class_weighted_single_block_exact():
    X, L, y = make_problem()
    lam, w = 0.3, 0.4
    model = PerClassWeightedLeastSquaresEstimator(
        block_size=12, num_iter=1, lam=lam, mixture_weight=w
    ).fit_arrays(X, L)
    W_expect, b_expect = direct_per_class_solve(X, L, y, lam, w)
    np.testing.assert_allclose(model.weights, W_expect, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(model.intercept, b_expect, rtol=2e-3, atol=2e-3)


def test_per_class_weighted_multi_block_converges():
    X, L, y = make_problem(seed=1)
    lam, w = 0.5, 0.3
    model = PerClassWeightedLeastSquaresEstimator(
        block_size=5, num_iter=30, lam=lam, mixture_weight=w
    ).fit_arrays(X, L)
    W_expect, b_expect = direct_per_class_solve(X, L, y, lam, w)
    np.testing.assert_allclose(model.weights, W_expect, rtol=3e-2, atol=3e-2)


def test_block_weighted_improves_fit_and_runs():
    """BlockWeighted solver: predictions recover the true class on
    separable data."""
    rng = np.random.RandomState(2)
    n, d, k = 300, 16, 3
    y = rng.randint(0, k, n)
    centers = rng.randn(k, d).astype(np.float32) * 3
    X = centers[y] + 0.5 * rng.randn(n, d).astype(np.float32)
    L = -np.ones((n, k), np.float32)
    L[np.arange(n), y] = 1.0
    model = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iter=3, lam=0.1, mixture_weight=0.25
    ).fit_arrays(X, L)
    preds = model(X).numpy()
    acc = (np.argmax(preds, 1) == y).mean()
    assert acc > 0.9


def test_block_weighted_mixture_one_equals_per_class_ridge():
    """With mixture_weight=1 the joint stats collapse to pure class stats."""
    X, L, y = make_problem(n=200, d=10, k=2, seed=3)
    m1 = BlockWeightedLeastSquaresEstimator(
        block_size=10, num_iter=1, lam=0.2, mixture_weight=1.0
    ).fit_arrays(X, L)
    # direct: per class, center by class mean, cov = class cov,
    # xtr = class xtr - classMean * mean(res_class)
    assert np.isfinite(m1.weights).all()
    assert np.isfinite(m1.intercept).all()


def test_block_weighted_weight_property():
    est = BlockWeightedLeastSquaresEstimator(4, 2, 0.1, 0.5)
    assert est.weight == 7


def test_cosine_random_features():
    rng = np.random.RandomState(0)
    x = rng.randn(5, 8).astype(np.float32)
    node = CosineRandomFeatures.create(8, 16, gamma=0.5, seed=1)
    out = node(x).numpy()
    expect = np.cos(x @ node.W.T + node.b)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)
    assert out.shape == (5, 16)
    # cauchy variant
    node2 = CosineRandomFeatures.create(8, 16, gamma=0.5, w_dist="cauchy", seed=2)
    assert node2.W.shape == (16, 8)


def test_woodbury_solver_matches_cholesky():
    """The low-rank (Woodbury) per-class solve is numerically equivalent
    to the direct batched-Cholesky path; 'auto' picks woodbury when the
    padded class size is well under the block width."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, L, y = make_problem(n=240, d=48, k=4, seed=5)
    kw = dict(block_size=48, num_iter=3, lam=0.3, mixture_weight=0.35)
    m_chol = BlockWeightedLeastSquaresEstimator(
        solver="cholesky", **kw).fit_arrays(X, L)
    m_wood = BlockWeightedLeastSquaresEstimator(
        solver="woodbury", **kw).fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(m_chol.weights), np.asarray(m_wood.weights),
        rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(
        np.asarray(m_chol.intercept), np.asarray(m_wood.intercept),
        rtol=2e-3, atol=2e-3)


def test_woodbury_multi_block():
    """Woodbury parity across multiple feature blocks and passes."""
    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, L, y = make_problem(n=300, d=40, k=5, seed=6)
    kw = dict(block_size=16, num_iter=4, lam=0.2, mixture_weight=0.25)
    m_chol = BlockWeightedLeastSquaresEstimator(
        solver="cholesky", **kw).fit_arrays(X, L)
    m_wood = BlockWeightedLeastSquaresEstimator(
        solver="woodbury", **kw).fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(m_chol.weights), np.asarray(m_wood.weights),
        rtol=5e-3, atol=5e-3)


def test_weighted_solver_recovers_from_f32_breakdown(mesh8):
    """Huge-scale rank-deficient features with a tiny regularizer NaN
    the f32 Cholesky; both weighted-solver paths must recover finite,
    better-than-chance models (the reference solved this regime in
    f64)."""
    rng = np.random.RandomState(0)
    n, d, k = 96, 192, 6
    y = rng.randint(0, k, n)
    protos = rng.randn(k, d).astype(np.float32) * 400.0
    X = (protos[y] + 40.0 * rng.randn(n, d)).astype(np.float32)
    L = -np.ones((n, k), np.float32)
    L[np.arange(n), y] = 1.0
    for solver in ("cholesky", "woodbury"):
        est = BlockWeightedLeastSquaresEstimator(
            d, 1, 1e-4, 0.25, solver=solver)
        model = est.fit_arrays(X, L)
        W = np.asarray(model.weights)
        assert np.all(np.isfinite(W)), solver
        scores = X @ W + np.asarray(model.intercept)
        acc = (scores.argmax(1) == y).mean()
        assert acc > 0.5, (solver, acc)

    # the per-class reweighted solver shares the failure mode
    pc = PerClassWeightedLeastSquaresEstimator(d, 1, 1e-4, 0.25)
    model = pc.fit_arrays(X, L)
    W = np.asarray(model.weights)
    assert np.all(np.isfinite(W))
    scores = X @ W + np.asarray(model.intercept)
    assert (scores.argmax(1) == y).mean() > 0.5
