"""End-to-end MnistRandomFFT on synthetic separable data (the reference's
integration test is the app itself, README.md:15-28)."""
import numpy as np

from keystone_tpu.evaluation.multiclass import evaluate_multiclass
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.pipelines.images.mnist.random_fft import (
    MnistRandomFFTConfig,
    run,
)


CENTERS = np.random.RandomState(42).randn(10, 784).astype(np.float32) * 2.0


def synthetic_mnist(n, seed):
    """Linearly separable 784-dim 10-class blobs (shared class centers)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n)
    X = CENTERS[labels] + 0.5 * rng.randn(n, 784).astype(np.float32)
    return LabeledData(
        data=ArrayDataset.from_numpy(X.astype(np.float32)),
        labels=ArrayDataset.from_numpy(labels.astype(np.int32)),
    )


def test_mnist_random_fft_end_to_end():
    train = synthetic_mnist(400, seed=0)
    test = synthetic_mnist(100, seed=1)
    config = MnistRandomFFTConfig(
        num_ffts=2, block_size=512, lam=10.0, seed=0
    )
    pipeline, train_eval, test_eval = run(config, train=train, test=test)
    # Separable blobs through random features must be nearly perfect
    assert train_eval.total_error < 0.05
    assert test_eval.total_error < 0.15


def test_evaluator_exact_values():
    preds = np.array([0, 1, 1, 2, 2, 2])
    actual = np.array([0, 1, 2, 2, 2, 0])
    m = evaluate_multiclass(preds, actual, 3)
    assert m.total == 6
    assert m.confusion[0, 0] == 1 and m.confusion[0, 2] == 1
    assert m.confusion[2, 2] == 2 and m.confusion[2, 1] == 1
    assert abs(m.total_accuracy - 4 / 6) < 1e-9
    p, r, f1 = m.class_metrics(2)
    assert abs(p - 2 / 3) < 1e-9 and abs(r - 2 / 3) < 1e-9
