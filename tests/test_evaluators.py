"""Evaluator tests with exact values (mirrors the reference's
BinaryClassifierEvaluatorSuite, MeanAveragePrecisionSuite,
AugmentedExamplesEvaluatorSuite)."""
import numpy as np
import pytest

from keystone_tpu.evaluation import (
    AVERAGE_POLICY,
    BORDA_POLICY,
    evaluate_augmented,
    evaluate_binary,
    evaluate_mean_average_precision,
)


def test_binary_contingency_table():
    preds = [True, True, False, False, True]
    actual = [True, False, False, True, True]
    m = evaluate_binary(preds, actual)
    assert (m.tp, m.fp, m.tn, m.fn) == (2.0, 1.0, 1.0, 1.0)
    assert m.accuracy == pytest.approx(3 / 5)
    assert m.error == pytest.approx(2 / 5)
    assert m.precision == pytest.approx(2 / 3)
    assert m.recall == pytest.approx(2 / 3)
    assert m.specificity == pytest.approx(1 / 2)
    assert m.f_score() == pytest.approx(2 / 3)
    # beta=2 weighs recall higher
    assert m.f_score(2.0) == pytest.approx(5 * 2.0 / (5 * 2.0 + 4 * 1 + 1))


def test_binary_merge():
    a = evaluate_binary([True], [True])
    b = evaluate_binary([False], [True])
    m = a.merge(b)
    assert (m.tp, m.fn) == (1.0, 1.0)


def test_map_perfect_ranking():
    # 2 classes, 3 items; scores rank the true item first for each class
    actual = [[0], [1], [1]]
    scores = np.array([
        [0.9, 0.1],
        [0.2, 0.8],
        [0.3, 0.7],
    ])
    ap = evaluate_mean_average_precision(actual, scores, 2)
    np.testing.assert_allclose(ap, [1.0, 1.0])


def test_map_known_value():
    # class 0: gt = [1, 0, 1], scores [0.9, 0.8, 0.1] -> ranking: item0(tp),
    # item1(fp), item2(tp). precisions at hits: 1/1, 2/3; recalls: .5, 1.
    actual = [[0], [1], [0]]
    scores = np.array([
        [0.9, 0.1],
        [0.8, 0.2],
        [0.1, 0.9],
    ])
    ap = evaluate_mean_average_precision(actual, scores, 2)
    # 11-point: for t in 0..0.5 -> max precision with recall>=t is 1.0
    # (6 levels); t in 0.6..1.0 -> 2/3 (5 levels)
    expected0 = (6 * 1.0 + 5 * (2 / 3)) / 11
    # class 1: gt=[0,0,1] wait: actual[1]=[1] so gt=[0,1,0]... scores col1 =
    # [.1,.2,.9] -> order item2(fp),item1(tp),item0(fp): precisions [0,.5,.33],
    # recalls [0,1,1] -> all levels max precision 0.5
    np.testing.assert_allclose(ap, [expected0, 0.5], rtol=1e-12)


def test_map_multilabel():
    actual = [[0, 1], [1]]
    scores = np.array([[0.9, 0.9], [0.1, 0.8]])
    ap = evaluate_mean_average_precision(actual, scores, 2)
    np.testing.assert_allclose(ap, [1.0, 1.0])


def test_augmented_average_policy():
    # two source images, two patches each
    names = ["a", "a", "b", "b"]
    preds = [
        np.array([0.6, 0.4]), np.array([0.2, 0.3]),  # avg [0.4, 0.35] -> 0
        np.array([0.1, 0.9]), np.array([0.3, 0.2]),  # avg [0.2, 0.55] -> 1
    ]
    labels = [0, 0, 1, 1]
    m = evaluate_augmented(names, preds, labels, 2, AVERAGE_POLICY)
    assert m.total_error == 0.0


def test_augmented_borda_policy():
    names = ["a", "a"]
    # ranks: patch1 [1, 0], patch2 [1, 0] -> borda [2, 0] -> class 0
    preds = [np.array([0.9, 0.1]), np.array([0.6, 0.5])]
    m = evaluate_augmented(names, preds, [0, 0], 2, BORDA_POLICY)
    assert m.total_error == 0.0


def test_augmented_label_mismatch_raises():
    with pytest.raises(AssertionError):
        evaluate_augmented(
            ["a", "a"], [np.zeros(2), np.zeros(2)], [0, 1], 2)


def test_binary_degenerate_table_never_raises():
    # all-negative predictions: precision is 0/0 -> nan, like JVM doubles
    m = evaluate_binary([False, False], [False, True])
    assert np.isnan(m.precision)
    assert m.recall == 0.0
    assert isinstance(m.summary(), str)  # must not raise


def test_map_boundary_recall_thresholds():
    # recall hits exactly 0.5 with precision 1.0 at the first hit; the
    # t=0.5 level must include it (guards float-threshold drift)
    actual = [[0], [0], [1], [1]]
    scores = np.array([[0.9, 0.0], [0.1, 0.5], [0.4, 0.8], [0.2, 0.6]])
    ap = evaluate_mean_average_precision(actual, scores, 2)
    # class 0 ranking: item0(tp, p=1, r=.5), item2(fp), item3(fp), item1(tp)
    expected0 = (6 * 1.0 + 5 * 0.5) / 11
    assert ap[0] == pytest.approx(expected0)
