"""Auxiliary subsystem tests: checkpoint/resume, CLI, DOT export
(reference SURVEY.md section 5)."""
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.util import MaxClassifier
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.utils import (
    load_pipeline,
    load_state,
    save_pipeline,
    save_state,
)
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.common import Identity


def _fit_toy(mesh8):
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 3)).astype(np.float32)
    train = ArrayDataset.from_numpy(X)
    labels = ArrayDataset.from_numpy(Y)
    pipe = Identity().and_then(
        LinearMapEstimator(0.0), train, labels) >> MaxClassifier()
    return pipe, X


def test_fitted_pipeline_save_load(tmp_path, mesh8):
    pipe, X = _fit_toy(mesh8)
    fitted = pipe.fit()
    want = np.asarray(fitted.apply(ArrayDataset.from_numpy(X)).get().numpy())
    path = str(tmp_path / "model.pkl")
    save_pipeline(fitted, path)

    PipelineEnv.reset()  # fresh session
    loaded = load_pipeline(path)
    got = np.asarray(loaded.apply(ArrayDataset.from_numpy(X)).get().numpy())
    np.testing.assert_array_equal(got, want)
    # datum path too
    one = int(np.asarray(loaded.apply_datum(X[0]).get()))
    assert one == want[0]


class CountingLinearMapEstimator(LinearMapEstimator):
    fits = 0

    def _fit(self, ds, labels):
        CountingLinearMapEstimator.fits += 1
        return super()._fit(ds, labels)

    def eq_key(self):
        return (CountingLinearMapEstimator, self.lam)


def _tagged_pipeline(X, Y):
    # tagged datasets give prefixes a stable cross-session identity
    train = ArrayDataset.from_numpy(X, tag="toy:data")
    labels = ArrayDataset.from_numpy(Y, tag="toy:labels")
    return Identity().and_then(
        CountingLinearMapEstimator(0.0), train, labels) >> MaxClassifier()


def test_prefix_state_save_load_cross_session(tmp_path, mesh8):
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 3)).astype(np.float32)
    CountingLinearMapEstimator.fits = 0

    pipe = _tagged_pipeline(X, Y)
    preds = np.asarray(pipe(ArrayDataset.from_numpy(X)).get().numpy())
    assert CountingLinearMapEstimator.fits == 1
    path = str(tmp_path / "state.pkl")
    n_saved = save_state(path)
    assert n_saved >= 1  # the estimator fit was recorded

    # "new session": fresh env AND a rebuilt pipeline over fresh dataset
    # objects — only the tags carry identity across
    PipelineEnv.reset()
    assert load_state(path) == n_saved
    pipe2 = _tagged_pipeline(X.copy(), Y.copy())
    preds2 = np.asarray(pipe2(ArrayDataset.from_numpy(X)).get().numpy())
    np.testing.assert_array_equal(preds, preds2)
    assert CountingLinearMapEstimator.fits == 1  # warm start: no refit


def test_cli_lists_apps():
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu"],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0
    assert "cifar.random_patch" in out.stdout
    assert "text.newsgroups" in out.stdout


def test_cli_unknown_app():
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "nope.nope"],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 2
    assert "unknown app" in out.stderr


def test_graph_to_dot(mesh8):
    pipe, X = _fit_toy(mesh8)
    dot = pipe.to_pipeline()._graph.to_dot("test")
    assert "digraph" in dot and "->" in dot
