"""Auxiliary subsystem tests: checkpoint/resume, CLI, DOT export
(reference SURVEY.md section 5)."""
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.nodes.learning import LinearMapEstimator
from keystone_tpu.nodes.util import MaxClassifier
from keystone_tpu.parallel.dataset import ArrayDataset
from keystone_tpu.utils import (
    load_pipeline,
    load_state,
    save_pipeline,
    save_state,
)
from keystone_tpu.workflow.env import PipelineEnv
from keystone_tpu.workflow.common import Identity


def _fit_toy(mesh8):
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 3)).astype(np.float32)
    train = ArrayDataset.from_numpy(X)
    labels = ArrayDataset.from_numpy(Y)
    pipe = Identity().and_then(
        LinearMapEstimator(0.0), train, labels) >> MaxClassifier()
    return pipe, X


def test_fitted_pipeline_save_load(tmp_path, mesh8):
    pipe, X = _fit_toy(mesh8)
    fitted = pipe.fit()
    want = np.asarray(fitted.apply(ArrayDataset.from_numpy(X)).get().numpy())
    path = str(tmp_path / "model.pkl")
    save_pipeline(fitted, path)

    PipelineEnv.reset()  # fresh session
    loaded = load_pipeline(path)
    got = np.asarray(loaded.apply(ArrayDataset.from_numpy(X)).get().numpy())
    np.testing.assert_array_equal(got, want)
    # datum path too
    one = int(np.asarray(loaded.apply_datum(X[0]).get()))
    assert one == want[0]


class CountingLinearMapEstimator(LinearMapEstimator):
    fits = 0

    def _fit(self, ds, labels):
        CountingLinearMapEstimator.fits += 1
        return super()._fit(ds, labels)

    def eq_key(self):
        return (CountingLinearMapEstimator, self.lam)


def _tagged_pipeline(X, Y):
    # tagged datasets give prefixes a stable cross-session identity
    train = ArrayDataset.from_numpy(X, tag="toy:data")
    labels = ArrayDataset.from_numpy(Y, tag="toy:labels")
    return Identity().and_then(
        CountingLinearMapEstimator(0.0), train, labels) >> MaxClassifier()


def test_prefix_state_save_load_cross_session(tmp_path, mesh8):
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = (X @ rng.randn(6, 3)).astype(np.float32)
    CountingLinearMapEstimator.fits = 0

    pipe = _tagged_pipeline(X, Y)
    preds = np.asarray(pipe(ArrayDataset.from_numpy(X)).get().numpy())
    assert CountingLinearMapEstimator.fits == 1
    path = str(tmp_path / "state.pkl")
    n_saved = save_state(path)
    assert n_saved >= 1  # the estimator fit was recorded

    # "new session": fresh env AND a rebuilt pipeline over fresh dataset
    # objects — only the tags carry identity across
    PipelineEnv.reset()
    assert load_state(path) == n_saved
    pipe2 = _tagged_pipeline(X.copy(), Y.copy())
    preds2 = np.asarray(pipe2(ArrayDataset.from_numpy(X)).get().numpy())
    np.testing.assert_array_equal(preds, preds2)
    assert CountingLinearMapEstimator.fits == 1  # warm start: no refit


def test_cli_lists_apps():
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu"],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0
    assert "cifar.random_patch" in out.stdout
    assert "text.newsgroups" in out.stdout


def test_cli_unknown_app():
    out = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "nope.nope"],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 2
    assert "unknown app" in out.stderr


def test_graph_to_dot(mesh8):
    pipe, X = _fit_toy(mesh8)
    dot = pipe.to_pipeline()._graph.to_dot("test")
    assert "digraph" in dot and "->" in dot


def test_weighted_solver_checkpoint_resume(tmp_path, monkeypatch):
    """Per-pass checkpoint/resume (CLUSTER.md failure-recovery story):
    a solve crashed mid-pass resumes from the last completed pass and
    lands on the same solution as an uninterrupted run; stale or
    mismatched checkpoints are ignored; a completed solve leaves no
    checkpoint file behind."""
    import os
    import pickle

    import numpy as np
    import pytest

    from keystone_tpu.nodes.learning.block_weighted import (
        BlockWeightedLeastSquaresEstimator,
    )
    from keystone_tpu.utils.checkpoint import SolverCheckpoint

    rng = np.random.RandomState(0)
    n, d, k = 200, 24, 4
    X = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, k, n)
    L = (-np.ones((n, k)) + 2 * np.eye(k)[y]).astype(np.float32)
    path = str(tmp_path / "solver.ckpt")
    kw = dict(block_size=8, num_iter=4, lam=0.2, mixture_weight=0.3)

    full = BlockWeightedLeastSquaresEstimator(**kw).fit_arrays(X, L)

    # crash the solve during pass 2 (after the pass-1 checkpoint lands)
    real_save = SolverCheckpoint.save

    def crash_after_pass_1(self, key, pass_idx, models):
        real_save(self, key, pass_idx, models)
        if pass_idx == 1:
            raise RuntimeError("simulated preemption")

    def fit_crashing(X_, L_):
        monkeypatch.setattr(SolverCheckpoint, "save", crash_after_pass_1)
        try:
            with pytest.raises(RuntimeError, match="simulated preemption"):
                BlockWeightedLeastSquaresEstimator(
                    **kw, checkpoint_path=path).fit_arrays(X_, L_)
        finally:
            monkeypatch.setattr(SolverCheckpoint, "save", real_save)

    fit_crashing(X, L)
    with open(path, "rb") as f:
        assert pickle.load(f)["pass"] == 1

    # resume with the identical config -> same solution as uninterrupted
    resumed = BlockWeightedLeastSquaresEstimator(
        **kw, checkpoint_path=path).fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(full.weights), np.asarray(resumed.weights),
        rtol=1e-4, atol=1e-4)
    # a completed solve clears its checkpoint
    assert not os.path.exists(path)

    # mismatched key -> ignored, fresh fit still correct
    with open(path, "wb") as f:
        pickle.dump({"key": ("bogus",), "pass": 0, "models": []}, f)
    fresh = BlockWeightedLeastSquaresEstimator(
        **kw, checkpoint_path=path).fit_arrays(X, L)
    np.testing.assert_allclose(
        np.asarray(full.weights), np.asarray(fresh.weights),
        rtol=1e-4, atol=1e-4)

    # non-dict pickle at the path -> ignored, not a crash
    with open(path, "wb") as f:
        pickle.dump([1, 2, 3], f)
    BlockWeightedLeastSquaresEstimator(
        **kw, checkpoint_path=path).fit_arrays(X, L)

    # same shapes, DIFFERENT data -> content fingerprint rejects the
    # stale mid-way checkpoint; the fit must match a from-scratch solve
    fit_crashing(X, L)  # mid-way ckpt (pass 1 of 4) for data X
    X2 = rng.randn(n, d).astype(np.float32)
    clean = BlockWeightedLeastSquaresEstimator(**kw).fit_arrays(X2, L)
    poisoned = BlockWeightedLeastSquaresEstimator(
        **kw, checkpoint_path=path).fit_arrays(X2, L)
    np.testing.assert_allclose(
        np.asarray(clean.weights), np.asarray(poisoned.weights),
        rtol=1e-4, atol=1e-4)
