"""Static pipeline analyzer (keystone_tpu/analysis): abstract shape
propagation over every bundled app pipeline, plus targeted tests that
each lint fires on a deliberately broken graph and that the node-level
optimizer consumes statically inferred shapes instead of sampling."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.analysis import (
    DatasetSpec,
    SpecDataset,
    Unknown,
    check_graph,
    spec_dataset,
)
from keystone_tpu.analysis.diagnostics import (
    apply_body_host_coercions,
    fusion_prefix_lint,
)
from keystone_tpu.pipelines import CHECK_APPS, resolve_check_app
from keystone_tpu.workflow.pipeline import Pipeline
from keystone_tpu.workflow.transformer import (
    HostTransformer,
    LambdaTransformer,
    Transformer,
)


def t(fn, name):
    return LambdaTransformer(fn, name)


# -- every bundled app is statically clean ----------------------------------

@pytest.mark.parametrize("app", sorted(CHECK_APPS))
def test_bundled_app_checks_clean(app, mesh8):
    target = CHECK_APPS[app]()
    report = target.pipeline.check(target.input_spec, name=target.name)
    assert report.ok, "\n".join(str(d) for d in report.diagnostics)
    # every app resolves every node's spec — host-featurized text apps
    # included, because Unknown propagation is silent but check_graph
    # still assigns a value to each node
    assert len(report.analysis.values) > 0
    # the JSON form round-trips through the observability report style
    blob = report.to_dict()
    assert blob["name"] == target.name
    assert blob["diagnostics"] == []


def test_check_resolves_all_nodes_for_array_apps(mesh8):
    # dense-array apps resolve 100% of their nodes (no Unknown leaks)
    for app in ("mnist.random_fft", "cifar.linear_pixels", "speech.timit"):
        target = resolve_check_app(app)()
        report = target.pipeline.check(target.input_spec, name=app)
        assert report.resolved_nodes() == len(report.analysis.graph.nodes)


def test_check_allocates_no_device_buffers(mesh8):
    # live_arrays is process-global: other tests' buffers may be alive,
    # so assert check() itself creates none (the CLI path is verified
    # from a clean interpreter by tools/lint.py / `check --all`)
    before = {id(a) for a in jax.live_arrays()}
    target = resolve_check_app("mnist_random_fft")()
    report = target.pipeline.check(target.input_spec)
    assert report.ok
    new = [a for a in jax.live_arrays() if id(a) not in before]
    assert not new, [(a.shape, a.dtype) for a in new[:5]]


def test_spec_dataset_refuses_execution():
    ds = spec_dataset((8,), np.float32, n=16)
    assert len(ds) == 16
    with pytest.raises(RuntimeError, match="static-analysis placeholder"):
        ds.collect()
    with pytest.raises(RuntimeError):
        ds.map(lambda x: x)


# -- lints fire on broken graphs --------------------------------------------

def test_shape_mismatch_lint_fires(mesh8):
    # a 784-wide sign mask applied to a 32-dim input: the einsum-level
    # error surfaces at graph-check time, not minutes into a device run
    from keystone_tpu.nodes.stats import RandomSignNode

    pipe = t(lambda x: x * 2.0, "ok") >> RandomSignNode(np.ones(784))
    report = pipe.check(jax.ShapeDtypeStruct((32,), np.float32))
    codes = {d.code for d in report.diagnostics}
    assert "shape-mismatch" in codes
    bad = [d for d in report.diagnostics if d.code == "shape-mismatch"]
    assert bad[0].operator == "RandomSignNode"


def test_shape_mismatch_does_not_cascade(mesh8):
    # one real error, not one per downstream node
    from keystone_tpu.nodes.stats import RandomSignNode

    pipe = (RandomSignNode(np.ones(784)) >> t(lambda x: x + 1, "a")
            >> t(lambda x: x * 2, "b"))
    report = pipe.check(jax.ShapeDtypeStruct((32,), np.float32))
    assert len([d for d in report.diagnostics
                if d.code == "shape-mismatch"]) == 1


def test_dtype_narrowing_lint_fires(mesh8):
    pipe = (t(lambda x: x + 1.0, "f32")
            >> t(lambda x: x.astype(jnp.bfloat16), "narrow")
            >> t(lambda x: x * 2, "after"))
    report = pipe.check(jax.ShapeDtypeStruct((8,), np.float32))
    narrow = [d for d in report.diagnostics if d.code == "dtype-narrowing"]
    assert len(narrow) == 1 and narrow[0].operator == "narrow"


def test_dtype_narrowing_respects_narrowing_ok(mesh8):
    class DeliberateCast(Transformer):
        narrowing_ok = True

        def apply(self, x):
            return x.astype(jnp.bfloat16)

    pipe = t(lambda x: x + 1.0, "f32") >> DeliberateCast()
    report = pipe.check(jax.ShapeDtypeStruct((8,), np.float32))
    assert not [d for d in report.diagnostics
                if d.code == "dtype-narrowing"]


def test_unbound_source_lint_fires(mesh8):
    pipe = t(lambda x: x + 1.0, "a") >> t(lambda x: x * 2.0, "b")
    report = pipe.check()  # no sample bound to the source
    assert [d for d in report.diagnostics if d.code == "unbound-source"]


def test_dead_branch_lint_fires(mesh8):
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator

    g = Graph()
    g, live = g.add_node(DatasetOperator(spec_dataset((4,), n=8)), ())
    g, sink = g.add_sink(live)
    g, dead = g.add_node(t(lambda x: x + 1, "dead"), (live,))
    report = check_graph(g)
    dead_diags = [d for d in report.diagnostics if d.code == "dead-branch"]
    assert len(dead_diags) == 1 and dead_diags[0].node_id == dead.id


def test_host_sync_lint_fires_dynamically(mesh8):
    # np.asarray on a traced value raises at eval_shape time and is
    # classified as a host-sync hazard, not a generic shape error
    pipe = t(lambda x: np.asarray(x) + 1.0, "hostish")
    report = pipe.check(jax.ShapeDtypeStruct((8,), np.float32))
    assert [d for d in report.diagnostics if d.code == "host-sync"]


def test_host_sync_ast_lint():
    class BadNode(Transformer):
        def apply(self, x):
            return np.asarray(x) * 2.0

    class GoodNode(Transformer):
        def apply(self, x):
            idx = np.arange(4)  # np on static config is fine
            return x[jnp.asarray(idx)]

    class HostNode(HostTransformer):
        def apply(self, x):
            return np.asarray(x).tolist()  # host stages may host-coerce

    assert apply_body_host_coercions(BadNode) == ["np.asarray(x)"]
    assert apply_body_host_coercions(GoodNode) == []
    assert apply_body_host_coercions(HostNode) == []


def test_fusion_prefix_lint_fires_on_noncanonical_fusion(mesh8):
    """The lint guards the canonical-prefix invariant: a fusion rewrite
    whose fused operator does NOT expand back to the unfused chain's
    prefix (here: a plain composite transformer) changes every
    downstream saveable prefix, which the lint must report."""
    from keystone_tpu.workflow.graph_ids import NodeId
    from keystone_tpu.workflow.estimator import LambdaEstimator

    class OpaqueComposite(Transformer):
        def __init__(self, stages):
            self.composite_stages = list(stages)

        def eq_key(self):
            return (OpaqueComposite,
                    tuple(s._cached_eq_key() for s in self.composite_stages))

        def apply(self, x):
            for s in self.composite_stages:
                x = s.apply(x)
            return x

    def bad_fuse(graph):
        # collapse the first two-node chain into an OpaqueComposite
        for b in sorted(graph.nodes, key=lambda n: n.id):
            deps = graph.get_dependencies(b)
            if len(deps) == 1 and isinstance(deps[0], NodeId):
                a = deps[0]
                op_a, op_b = graph.get_operator(a), graph.get_operator(b)
                if not (isinstance(op_a, LambdaTransformer)
                        and isinstance(op_b, LambdaTransformer)):
                    continue
                g = graph.set_operator(b, OpaqueComposite([op_a, op_b]))
                g = g.set_dependencies(b, graph.get_dependencies(a))
                return g.remove_node(a)
        return graph

    def bad_fuse_fixpoint(graph):
        while True:
            nxt = bad_fuse(graph)
            if nxt is graph:
                return graph
            graph = nxt

    est = LambdaEstimator(lambda ds: t(lambda x: x, "id"), "E")
    pipe = (t(lambda x: x + 1, "a") >> t(lambda x: x * 2, "b")).and_then(
        est, spec_dataset((4,), n=8))
    diags = fusion_prefix_lint(pipe.graph, fuse=bad_fuse_fixpoint)
    assert len(diags) == 1
    assert diags[0].code == "fusion-prefix-hazard"

    # the REAL fusion rules are canonical: no hazard
    assert fusion_prefix_lint(pipe.graph) == []


# -- static cost-model provenance -------------------------------------------

def test_node_rule_selects_solver_statically(mesh8):
    """Dense least-squares path: the solver is chosen from statically
    inferred (n, d, k) with NO sampled profile, and the PipelineTrace
    records static provenance (ISSUE 2 acceptance)."""
    from keystone_tpu.nodes.learning import LeastSquaresEstimator
    from keystone_tpu.observability.trace import PipelineTrace
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.transformer import transformer

    rng = np.random.RandomState(0)
    n, d, k = 32, 6, 3
    X = rng.randn(n, d).astype(np.float32)
    Y = (X @ rng.randn(d, k)).astype(np.float32)
    train, labels = ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y)
    ident = transformer(lambda x: x * 1.0)
    with PipelineTrace("static") as tr:
        pipe = ident.and_then(
            LeastSquaresEstimator(num_iterations=100), train, labels)
        preds = pipe(train).get().numpy()
    np.testing.assert_allclose(preds, Y, atol=5e-2)
    assert tr.node_choices and tr.node_choices[0]["provenance"] == "static"
    assert tr.node_choices[0]["full_n"] == n
    assert tr.solver_decisions
    decision = tr.solver_decisions[0]
    assert decision["shape_source"] == "static"
    assert (decision["n"], decision["d"], decision["k"]) == (n, d, k)


def test_optimize_static_declines_on_unknown_sparsity():
    from keystone_tpu.analysis import SparseSpec
    from keystone_tpu.nodes.learning import LeastSquaresEstimator

    est = LeastSquaresEstimator()
    data = DatasetSpec(SparseSpec(1000), n=500, host=True, sparsity=None)
    labels = DatasetSpec(
        jax.ShapeDtypeStruct((3,), np.float32), n=500)
    assert est.optimize_static(data, 500, 8, labels_spec=labels) is None


def test_node_rule_falls_back_to_sampling_for_sparse(mesh8):
    """Sparse host inputs have no static density: the rule must keep the
    reference's sampled path (provenance 'sampled') and still pick the
    sparse solver."""
    from keystone_tpu.nodes.learning import LeastSquaresEstimator
    from keystone_tpu.nodes.learning.lbfgs import SparseLBFGSwithL2
    from keystone_tpu.nodes.util.sparse import SparseVector
    from keystone_tpu.observability.trace import PipelineTrace
    from keystone_tpu.parallel.dataset import ArrayDataset, HostDataset
    from keystone_tpu.workflow.optimizer.node_rule import (
        NodeOptimizationRule,
    )
    from keystone_tpu.workflow.optimizable import OptimizableLabelEstimator

    rng = np.random.RandomState(0)
    items = [SparseVector(np.arange(10), np.ones(10, np.float32), 10_000)
             for _ in range(16)]
    labels = ArrayDataset.from_numpy(rng.randn(16, 2).astype(np.float32))
    est = LeastSquaresEstimator(
        **{"cpu_weight": 3.8e-4, "mem_weight": 2.9e-1,
           "network_weight": 1.32, "lat_weight": 0.0})
    from keystone_tpu.workflow.label_estimator import LabelEstimator  # noqa

    pipe = est.with_data(HostDataset(items), labels)
    with PipelineTrace("sparse") as tr:
        NodeOptimizationRule(num_machines=16).apply(pipe.graph)
    assert tr.node_choices
    assert tr.node_choices[0]["provenance"] == "sampled"


def test_static_shapes_opt_out_keeps_sampled_path(mesh8):
    """`static_shapes=False` (or KEYSTONE_STATIC_NODE_OPT=0) forces the
    reference's sampled behavior even for fully resolvable dense
    shapes — the escape hatch for dense-stored-but-mostly-zero data
    whose measured sparsity should drive the solver choice."""
    from keystone_tpu.nodes.learning import LeastSquaresEstimator
    from keystone_tpu.observability.trace import PipelineTrace
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.optimizer.node_rule import (
        NodeOptimizationRule,
    )

    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)
    pipe = LeastSquaresEstimator().with_data(
        ArrayDataset.from_numpy(X), ArrayDataset.from_numpy(Y))
    with PipelineTrace("optout") as tr:
        NodeOptimizationRule(static_shapes=False).apply(pipe.graph)
    assert tr.node_choices
    assert tr.node_choices[0]["provenance"] == "sampled"


def test_check_summary_and_json(mesh8):
    target = resolve_check_app("speech.timit")()
    report = target.pipeline.check(target.input_spec, name="timit")
    text = report.summary()
    assert "statically clean" in text
    assert "CosineRandomFeatures" in text
    import json

    blob = json.loads(report.to_json())
    assert blob["diagnostics"] == []
