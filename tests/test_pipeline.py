"""Pipeline API tests, mirroring ``workflow/PipelineSuite.scala`` and
``workflow/graph/PipelineSuite.scala``."""
import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu import (
    ArrayDataset,
    Cacher,
    Estimator,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineEnv,
    Transformer,
    transformer,
)
from keystone_tpu.workflow.estimator import LambdaEstimator


class Scale(Transformer):
    def __init__(self, k):
        self.k = k

    def apply(self, x):
        return x * self.k


class AddOne(Transformer):
    def apply(self, x):
        return x + 1


class MeanCenterEstimator(Estimator):
    """Fits the dataset mean, returns a transformer subtracting it."""

    num_fits = 0

    def _fit(self, ds):
        MeanCenterEstimator.num_fits += 1
        data = ds.numpy()
        return Scale(0) if data is None else Shift(-data.mean(axis=0))


class Shift(Transformer):
    def __init__(self, b):
        self.b = np.asarray(b)

    def apply(self, x):
        return x + self.b


class OffsetByLabelMean(LabelEstimator):
    num_fits = 0

    def _fit(self, ds, labels):
        OffsetByLabelMean.num_fits += 1
        return Shift(labels.numpy().mean(axis=0))


def data(n=16, d=4, seed=0):
    rng = np.random.RandomState(seed)
    return rng.rand(n, d).astype(np.float32)


def test_transformer_apply_datum():
    t = Scale(3.0)
    out = t.bind_datum(np.float32(2.0)).get()
    assert float(out) == pytest.approx(6.0)


def test_transformer_apply_dataset():
    x = data()
    out = Scale(2.0)(x).numpy()
    np.testing.assert_allclose(out, x * 2.0, rtol=1e-6)


def test_and_then_chaining():
    x = data()
    pipe = Scale(2.0) >> AddOne() >> Scale(0.5)
    out = pipe.apply(x).numpy()
    np.testing.assert_allclose(out, (x * 2 + 1) * 0.5, rtol=1e-6)


def test_estimator_chain():
    x = data()
    pipe = AddOne().and_then(MeanCenterEstimator(), x)
    out = pipe.apply(x).numpy()
    expect = (x + 1) - (x + 1).mean(axis=0)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_do_not_fit_estimators_multiple_times():
    """Reference: PipelineSuite 'Do not fit estimators multiple times'."""
    MeanCenterEstimator.num_fits = 0
    x = data()
    pipe = AddOne().and_then(MeanCenterEstimator(), x)
    pipe.apply(x).numpy()
    pipe.apply(data(seed=1)).numpy()
    pipe.apply_datum(x[0]).get()
    assert MeanCenterEstimator.num_fits == 1


def test_label_estimator_chain():
    OffsetByLabelMean.num_fits = 0
    x = data()
    y = data(seed=2)
    pipe = Scale(1.0).and_then(OffsetByLabelMean(), x, y)
    out = pipe.apply(x).numpy()
    np.testing.assert_allclose(out, x + y.mean(axis=0), rtol=1e-5, atol=1e-5)
    assert OffsetByLabelMean.num_fits == 1


def test_gather():
    x = data()
    pipe = Pipeline.gather([Scale(1.0), Scale(2.0), Scale(3.0)])
    out = pipe.apply(x).get()
    got = out.numpy()
    assert isinstance(got, tuple) and len(got) == 3
    np.testing.assert_allclose(got[1], x * 2, rtol=1e-6)


def test_fit_returns_serializable_fitted_pipeline():
    import pickle

    x = data()
    pipe = AddOne().and_then(MeanCenterEstimator(), x) >> Scale(2.0)
    fitted = pipe.fit()
    out1 = fitted.apply(x).numpy()
    blob = pickle.dumps(fitted)
    restored = pickle.loads(blob)
    out2 = restored.apply(x).numpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)
    expect = ((x + 1) - (x + 1).mean(axis=0)) * 2
    np.testing.assert_allclose(out1, expect, rtol=1e-5, atol=1e-5)


def test_fitted_pipeline_never_refits():
    MeanCenterEstimator.num_fits = 0
    x = data()
    pipe = AddOne().and_then(MeanCenterEstimator(), x)
    fitted = pipe.fit()
    assert MeanCenterEstimator.num_fits == 1
    fitted.apply(data(seed=3)).numpy()
    fitted.apply(data(seed=4)).numpy()
    assert MeanCenterEstimator.num_fits == 1


def test_incremental_state_reuse_across_pipelines():
    """Reference: graph/PipelineSuite 'Incrementally update execution state'.
    Two pipelines sharing a fitted prefix on the same data fit once."""
    MeanCenterEstimator.num_fits = 0
    x = data()
    ds = ArrayDataset.from_numpy(x)
    p1 = AddOne().and_then(MeanCenterEstimator(), ds)
    p1.apply(ds).numpy()
    assert MeanCenterEstimator.num_fits == 1
    p2 = AddOne().and_then(MeanCenterEstimator(), ds) >> Scale(5.0)
    p2.apply(ds).numpy()
    assert MeanCenterEstimator.num_fits == 1


def test_lambda_transformer():
    x = data()
    pipe = transformer(lambda v: v * 4.0)
    np.testing.assert_allclose(pipe(x).numpy(), x * 4, rtol=1e-6)


def test_identity_and_cacher():
    x = data()
    pipe = Identity() >> Cacher("t") >> Scale(2.0)
    np.testing.assert_allclose(pipe.apply(x).numpy(), x * 2, rtol=1e-6)


def test_pipeline_gather_then_estimator():
    x = data()
    branches = Pipeline.gather([Scale(1.0), Scale(2.0)])

    class Sum(Transformer):
        def apply(self, xs):
            return xs[0] + xs[1]

    pipe = branches >> Sum()
    out = pipe.apply(x).numpy()
    np.testing.assert_allclose(out, x * 3, rtol=1e-6)


def test_apply_datum_through_estimator_pipeline():
    x = data()
    pipe = AddOne().and_then(MeanCenterEstimator(), x)
    out = np.asarray(pipe.apply_datum(x[0]).get())
    expect = (x[0] + 1) - (x + 1).mean(axis=0)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)
