"""Observability layer tests: per-node trace records from the executor,
optimizer decision logs (rules / auto-cache / solver choice), JSON
round-trip, and the zero-overhead-when-disabled contract."""
import json

import numpy as np
import pytest

from keystone_tpu import (
    ArrayDataset,
    Estimator,
    MetricsRegistry,
    Pipeline,
    PipelineTrace,
    Transformer,
    current_trace,
)
from keystone_tpu.observability.trace import NodeRecord, tracing_disabled


class Scale(Transformer):
    def __init__(self, k):
        self.k = k

    def apply(self, x):
        return x * self.k


class AddOne(Transformer):
    def apply(self, x):
        return x + 1


class SumBranches(Transformer):
    def apply(self, xs):
        return xs[0] + xs[1]


class MeanCenterEstimator(Estimator):
    num_fits = 0

    def _fit(self, ds):
        MeanCenterEstimator.num_fits += 1

        class Shift(Transformer):
            def __init__(self, b):
                self.b = np.asarray(b)

            def apply(self, x):
                return x + self.b

        return Shift(-ds.numpy().mean(axis=0))


def data(n=16, d=4, seed=0):
    return np.random.RandomState(seed).rand(n, d).astype(np.float32)


def _estimator_pipeline(ds):
    return AddOne().and_then(MeanCenterEstimator(), ds)


# -- per-node records -----------------------------------------------------


def test_trace_node_set_matches_optimized_graph():
    """Every node of the optimized graph — and nothing else — appears in
    the trace when the sink is fully materialized."""
    x = data()
    # duplicate branches force the CSE rule to fire, so the optimized
    # graph differs from the raw one — the trace must follow the former
    pipe = Pipeline.gather([Scale(2.0), Scale(2.0)]) >> SumBranches()
    with PipelineTrace("t") as tr:
        out = pipe.apply(x)
        result = out.numpy()
    np.testing.assert_allclose(result, x * 4.0, rtol=1e-6)
    optimized_ids = {n.id for n in out._executor.graph.nodes}
    assert tr.node_ids() == optimized_ids
    raw_ids = {n.id for n in out._executor.raw_graph.nodes}
    assert optimized_ids < raw_ids  # CSE actually shrank the graph
    # wall-time accounting is self-time: totals are sane and non-negative
    assert all(r.wall_s >= 0.0 and r.total_s >= r.wall_s for r in tr.nodes)
    assert tr.total_node_wall_s() > 0.0


def test_trace_records_operator_names_and_memory():
    x = data()
    with PipelineTrace() as tr:
        (Scale(3.0) >> AddOne()).apply(x).numpy()
    ops = {r.operator for r in tr.nodes}
    assert "Dataset" in ops
    # dataset-producing nodes carry a real device-memory footprint
    dataset_records = [r for r in tr.nodes if r.kind == "dataset"]
    assert dataset_records
    assert all(r.output_bytes > 0 for r in dataset_records)
    assert all(r.shards >= 1 for r in dataset_records)


def test_trace_records_cache_hit_on_second_apply():
    """The second apply loads the fitted estimator from the prefix state
    (SavedStateLoadRule) — the trace must show it as a cache hit, and
    the optimizer rule log must contain the substitution."""
    MeanCenterEstimator.num_fits = 0
    x = data()
    ds = ArrayDataset.from_numpy(x)
    pipe = _estimator_pipeline(ds)
    with PipelineTrace() as tr:
        pipe.apply(ds).numpy()
        assert not tr.cache_hits()
        pipe.apply(ds).numpy()
    assert MeanCenterEstimator.num_fits == 1
    hits = tr.cache_hits()
    assert hits and any(r.operator == "Saved" for r in hits)
    fired = {e["rule"] for e in tr.optimizer_rules}
    assert "SavedStateLoadRule" in fired


def test_trace_optimizer_rule_entries():
    x = data()
    pipe = Pipeline.gather([Scale(2.0), Scale(2.0)]) >> SumBranches()
    with PipelineTrace() as tr:
        pipe.apply(x).numpy()
    assert len(tr.optimizer_rules) >= 1
    entry = next(e for e in tr.optimizer_rules
                 if e["rule"] == "EquivalentNodeMergeRule")
    assert entry["nodes_before"] > entry["nodes_after"]
    assert entry["wall_s"] >= 0.0
    # the engine also logs the whole optimizer pass
    runs = tr.meta.get("optimizer_runs", [])
    assert runs and runs[0]["optimizer"] == "DefaultOptimizer"
    assert runs[0]["nodes_in"] >= runs[0]["nodes_out"]


def test_trace_json_round_trip():
    x = data()
    ds = ArrayDataset.from_numpy(x)
    pipe = _estimator_pipeline(ds)
    with PipelineTrace("round-trip") as tr:
        pipe.apply(ds).numpy()
        pipe.apply(ds).numpy()
    blob = tr.to_json()
    parsed = json.loads(blob)  # valid JSON
    assert parsed["name"] == "round-trip"
    restored = PipelineTrace.from_json(blob)
    assert restored.name == tr.name
    assert restored.node_ids() == tr.node_ids()
    assert len(restored.cache_hits()) == len(tr.cache_hits())
    assert restored.optimizer_rules == tr.optimizer_rules
    assert restored.to_json() == blob
    # summary renders without raising, and mentions the rule log
    text = tr.summary()
    assert "SavedStateLoadRule" in text and "cached" in text


def test_tracing_disabled_adds_no_entries():
    """With no active trace the executor records nothing — including
    into previously exited traces."""
    x = data()
    ds = ArrayDataset.from_numpy(x)
    with PipelineTrace() as tr:
        pass  # entered and exited before any execution
    assert current_trace() is None
    pipe = _estimator_pipeline(ds)
    pipe.apply(ds).numpy()
    pipe.apply(ds).numpy()
    assert tr.nodes == []
    assert tr.optimizer_rules == []
    assert tr.auto_cache == []
    assert tr.solver_decisions == []


def test_tracing_disabled_context_suppresses_recording():
    x = data()
    with PipelineTrace() as tr:
        with tracing_disabled():
            Scale(2.0)(x).numpy()
        assert current_trace() is None or tr.nodes == []
    assert tr.nodes == []


def test_saved_expression_outlives_its_trace():
    """A lazy fit saved into the prefix state under trace A must not
    write records into A when forced later (trace looked up at call
    time, not captured)."""
    MeanCenterEstimator.num_fits = 0
    x = data()
    ds = ArrayDataset.from_numpy(x)
    pipe = _estimator_pipeline(ds)
    with PipelineTrace() as tr_a:
        lazy = pipe.apply(ds)  # nothing forced inside the trace
    n_before = len(tr_a.nodes)
    lazy.numpy()  # forced OUTSIDE the trace
    assert len(tr_a.nodes) == n_before


# -- optimizer decision logs ----------------------------------------------


def test_auto_cache_report_in_trace(mesh8):
    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import DatasetOperator
    from keystone_tpu.workflow.optimizer.auto_cache import AutoCacheRule
    from keystone_tpu.workflow.transformer import transformer

    ds = ArrayDataset.from_numpy(
        np.arange(32, dtype=np.float32).reshape(32, 1), mesh8)
    g = Graph()
    g, src = g.add_node(DatasetOperator(ds), ())
    g, a = g.add_node(transformer(lambda x: x + 1.0), (src,))
    g, b = g.add_node(transformer(lambda x: x * 2.0), (a,))
    g, c = g.add_node(transformer(lambda x: x * 3.0), (a,))
    g, s1 = g.add_sink(b)
    g, s2 = g.add_sink(c)
    with PipelineTrace() as tr:
        AutoCacheRule(AutoCacheRule.GREEDY, max_mem=1e12).apply(g)
    assert len(tr.auto_cache) == 1
    report = tr.auto_cache[0]
    assert report["strategy"] == "greedy"
    assert report["budget_bytes"] == pytest.approx(1e12)
    # the reused node was profiled and selected
    assert report["profiles"], "sampled profiles must be retained"
    assert all(v["ns"] >= 0 and v["mem"] >= 0
               for v in report["profiles"].values())
    assert a.id in report["selected"]
    assert report["estimated_cached_s"] <= report["estimated_uncached_s"]
    # profiling runs must not leak into the per-node record stream
    assert tr.nodes == []


def test_solver_decision_in_trace():
    from keystone_tpu.nodes.learning.least_squares import (
        LeastSquaresEstimator,
    )

    n, d, k = 4096, 32, 3
    sample = ArrayDataset.from_numpy(data(64, d))
    labels = ArrayDataset.from_numpy(data(64, k, seed=1))
    est = LeastSquaresEstimator(lam=0.1)
    with PipelineTrace() as tr:
        choice = est.optimize(sample, labels, n=n, num_machines=1)
    assert choice is not None
    assert len(tr.solver_decisions) == 1
    dec = tr.solver_decisions[0]
    assert (dec["n"], dec["d"], dec["k"]) == (n, d, k)
    assert 0.0 <= dec["sparsity"] <= 1.0
    # every candidate solver's cost estimate is present, and the pick
    # is the argmin
    assert len(dec["costs"]) == 4
    assert dec["chosen"] == min(dec["costs"], key=dec["costs"].get)
    assert dec["provenance"]["source"] in (
        "shipped_defaults", "artifact", "explicit")
    assert set(dec["weights"]) == {
        "cpu_weight", "mem_weight", "network_weight", "lat_weight"}


def test_solver_decision_through_full_pipeline_optimization():
    """End-to-end: a pipeline containing the optimizable estimator,
    executed under a trace, logs both the node-choice splice and the
    cost table behind it."""
    from keystone_tpu.nodes.learning.least_squares import (
        LeastSquaresEstimator,
    )

    x = data(32, 8)
    y = data(32, 2, seed=1)
    ds = ArrayDataset.from_numpy(x)
    labels = ArrayDataset.from_numpy(y)
    pipe = AddOne().and_then(LeastSquaresEstimator(lam=0.1), ds, labels)
    with PipelineTrace() as tr:
        out = pipe.apply(ds)
        np.asarray(out.numpy())
    assert len(tr.solver_decisions) >= 1
    assert len(tr.node_choices) >= 1
    nc = tr.node_choices[0]
    assert nc["optimizable"] == "LeastSquaresEstimator"
    assert nc["chosen"] == tr.solver_decisions[0]["chosen"]
    assert nc["full_n"] == 32


# -- calibration artifact --------------------------------------------------


def test_cost_weights_load_from_calibration_artifact(tmp_path, monkeypatch):
    from keystone_tpu.nodes.learning import least_squares as ls

    artifact = tmp_path / "cost_model_calibration.json"
    artifact.write_text(json.dumps({
        "cpu_weight": 1e-14, "mem_weight": 2e-11,
        "network_weight": 3e-11, "lat_weight": 4e-4,
        "timestamp": "2026-08-03T00:00:00+00:00",
        "hostname": "test-host", "device": "cpu",
    }))
    monkeypatch.setenv(ls.CALIBRATION_ENV, str(artifact))
    ls.clear_calibration_cache()
    try:
        est = ls.LeastSquaresEstimator(lam=0.1)
        assert est.cpu_weight == pytest.approx(1e-14)
        assert est.lat_weight == pytest.approx(4e-4)
        assert est._weight_provenance["source"] == "artifact"
        assert est._weight_provenance["hostname"] == "test-host"
    finally:
        ls.clear_calibration_cache()


def test_cost_weights_fall_back_when_artifact_invalid(tmp_path, monkeypatch):
    from keystone_tpu.nodes.learning import least_squares as ls

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"cpu_weight": -1.0}))  # negative + missing
    monkeypatch.setenv(ls.CALIBRATION_ENV, str(bad))
    ls.clear_calibration_cache()
    try:
        est = ls.LeastSquaresEstimator(lam=0.1)
        assert est.cpu_weight == pytest.approx(ls.DEFAULT_CPU_WEIGHT)
        assert est._weight_provenance["source"] == "shipped_defaults"
    finally:
        ls.clear_calibration_cache()


def test_explicit_weights_mark_provenance():
    from keystone_tpu.nodes.learning.least_squares import (
        LeastSquaresEstimator,
    )

    est = LeastSquaresEstimator(lam=0.1, cpu_weight=1e-12)
    assert est._weight_provenance["source"] == "explicit"
    assert est._weight_provenance["overrides"] == ["cpu_weight"]


def test_xprof_trace_reuses_active_trace(tmp_path):
    """Nesting xprof_trace inside an explicit PipelineTrace must not
    divert records to a throwaway inner trace."""
    from keystone_tpu.observability import xprof_trace

    x = data()
    with PipelineTrace("outer") as tr:
        with xprof_trace(str(tmp_path)) as inner:
            assert inner is tr
            Scale(2.0)(x).numpy()
    assert tr.nodes  # records landed in the outer trace


def test_sampled_executions_do_not_inflate_counters():
    """Throwaway executions inside tracing_disabled (optimizer sampling)
    must not count as real executor activity."""
    reg = MetricsRegistry.get_or_create()
    x = data()
    with tracing_disabled():
        Scale(2.0)(x).numpy()
    assert reg.snapshot()["counters"].get("executor.nodes_executed", 0) == 0
    Scale(2.0)(x).numpy()
    assert reg.snapshot()["counters"]["executor.nodes_executed"] > 0


def test_low_agreement_calibration_artifact_rejected(tmp_path, monkeypatch):
    from keystone_tpu.nodes.learning import least_squares as ls

    artifact = tmp_path / "low_agreement.json"
    artifact.write_text(json.dumps({
        "cpu_weight": 1e-14, "mem_weight": 2e-11,
        "network_weight": 3e-11, "lat_weight": 4e-4,
        "agreement": "1/3",  # model mis-ranked most validation shapes
    }))
    monkeypatch.setenv(ls.CALIBRATION_ENV, str(artifact))
    ls.clear_calibration_cache()
    try:
        weights, provenance = ls.load_calibration()
        assert provenance["source"] == "shipped_defaults"
        assert weights["cpu_weight"] == pytest.approx(ls.DEFAULT_CPU_WEIGHT)
    finally:
        ls.clear_calibration_cache()


def test_prefix_hits_counted_without_trace():
    """executor.prefix_hits is an always-on counter (README documents it
    alongside nodes_executed), not a traced-only one."""
    MeanCenterEstimator.num_fits = 0
    reg = MetricsRegistry.get_or_create()
    x = data()
    ds = ArrayDataset.from_numpy(x)
    pipe = _estimator_pipeline(ds)
    pipe.apply(ds).numpy()
    assert reg.snapshot()["counters"].get("executor.prefix_hits", 0) == 0
    pipe.apply(ds).numpy()  # fitted state loaded from the prefix memo
    assert MeanCenterEstimator.num_fits == 1
    assert reg.snapshot()["counters"]["executor.prefix_hits"] >= 1


# -- metrics registry ------------------------------------------------------


def test_metrics_registry_counts_executor_activity():
    reg = MetricsRegistry.get_or_create()
    x = data()
    (Scale(2.0) >> AddOne()).apply(x).numpy()
    snap = reg.snapshot()
    # dataset node + the (map-fused) transform chain
    assert snap["counters"]["executor.nodes_executed"] >= 2


def test_metrics_registry_basics():
    reg = MetricsRegistry.get_or_create()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    reg.gauge("g").set(7.5)
    reg.histogram("h").observe(1.0)
    reg.histogram("h").observe(3.0)
    with reg.timer("t"):
        pass
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 7.5
    assert snap["histograms"]["h"]["count"] == 2
    assert snap["histograms"]["h"]["mean"] == pytest.approx(2.0)
    assert snap["histograms"]["t"]["count"] == 1
    # process singleton
    assert MetricsRegistry.get_or_create() is reg


def test_node_record_defaults_round_trip():
    rec = NodeRecord(node_id=3, operator="X")
    tr = PipelineTrace("unit")
    tr.record_node(rec)
    restored = PipelineTrace.from_json(tr.to_json())
    assert restored.nodes[0] == rec


def test_steptimer_deprecated_but_functional():
    """PR 8 satellite: StepTimer is a deprecated shim — constructing
    one warns, the API still works, and the MetricsRegistry.timer
    replacement records the same block timing into the histograms."""
    import warnings

    from keystone_tpu.observability import StepTimer

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        timer = StepTimer()
    assert any(issubclass(w.category, DeprecationWarning)
               and "MetricsRegistry" in str(w.message) for w in caught)
    with timer.step("s"):
        pass
    assert timer.timed("t", lambda: 1 + 1) == 2
    assert set(timer.times) == {"s", "t"} and timer.summary()
    # the replacement path
    reg = MetricsRegistry.get_or_create()
    with reg.timer("streaming.ingest_stall_s"):
        pass
    assert reg.snapshot()["histograms"]["streaming.ingest_stall_s"][
        "count"] == 1


def test_steptimer_compat_reexports_still_work():
    """Both import homes keep working (and both warn on construction)."""
    import warnings

    from keystone_tpu.observability.metrics import StepTimer as direct
    from keystone_tpu.utils.profiling import StepTimer as via_profiling
    from keystone_tpu.utils import StepTimer as via_utils

    assert direct is via_profiling is via_utils
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        via_profiling()
    assert any(issubclass(w.category, DeprecationWarning) for w in caught)
