"""Compile observatory & device-utilization accounting (PR 9).

Covers: compile counting/classification and signature-delta naming,
the warmup fence (runtime recompile detection), `compile:` spans in
the Perfetto export, PipelineTrace compile records + round-trip, the
zero-recompile second-epoch invariant asserted dynamically, AOT
cost/memory capture, MFU/roofline math and the UtilizationWindow,
per-node trace annotation, the plan-vs-XLA cross-check on the real
check apps, the sampler RSS fallback shim, the device-OOM post-mortem
executable table, and benchdiff's artifact-prefix generalization.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.observability import (
    MetricsRegistry,
    PipelineTrace,
    compile_observatory,
    expect_no_compiles,
    observed_jit,
)
from keystone_tpu.observability.compilelog import (
    executable_table,
    is_device_oom,
    registered_sites,
    watch_jit,
)
from keystone_tpu.observability.timeline import flight_recorder
from keystone_tpu.observability.utilization import (
    DevicePeaks,
    UtilizationWindow,
    annotate_trace,
    device_peaks,
    roofline,
)


def _mm_site(name="obs_mm"):
    """A fresh observed matmul site (new function object => new jit
    cache => a real compile on first call)."""
    return observed_jit(lambda x: x @ x.T, name=name)


# -- observatory core --------------------------------------------------------


def test_first_compile_counted_timed_classified():
    obs = compile_observatory()
    reg = MetricsRegistry.get_or_create()
    count0 = obs.count_total()
    mm = _mm_site()
    mm(jnp.ones((8, 8), jnp.float32))
    recs = [r for r in obs.tail() if r["name"] == "obs_mm"]
    assert recs and recs[-1]["trigger"] == "first-compile"
    assert recs[-1]["wall_s"] > 0.0
    assert obs.count_total() > count0
    assert reg.counter("compile.count").value >= 1
    assert reg.histogram("compile.wall_s").count >= 1


def test_repeat_call_records_nothing():
    obs = compile_observatory()
    mm = _mm_site()
    x = jnp.ones((8, 8), jnp.float32)
    mm(x)
    count1 = obs.count_total()
    mm(x)  # warm executable: no compile, no record
    assert obs.count_total() == count1
    site = mm._keystone_site
    assert site.calls == 2 and site.compiles == 1


def test_signature_change_names_the_delta():
    obs = compile_observatory()
    mm = _mm_site()
    mm(jnp.ones((8, 8), jnp.float32))
    mm(jnp.ones((16, 16), jnp.float32))
    rec = [r for r in obs.tail() if r["name"] == "obs_mm"][-1]
    assert rec["trigger"] == "signature-change"
    assert "float32[8,8]" in rec["delta"]
    assert "float32[16,16]" in rec["delta"]


def test_fence_flags_unexpected_recompile_with_span():
    """The acceptance path in one test: an induced shape-change
    recompile under an armed fence is (a) detected and counted, (b)
    named with its signature delta, (c) visible as a ``compile:`` span
    in the Perfetto export."""
    obs = compile_observatory()
    reg = MetricsRegistry.get_or_create()
    mm = _mm_site(name="fenced_mm")
    mm(jnp.ones((8, 8), jnp.float32))     # warmup, outside the fence
    x16 = jnp.ones((16, 16), jnp.float32)  # staged outside the fence
    unexpected0 = obs.unexpected_total()
    with expect_no_compiles("steady-state"):
        mm(x16)                            # induced recompile
    assert obs.unexpected_total() == unexpected0 + 1
    assert reg.counter("compile.unexpected_total").value >= 1
    rec = obs.unexpected_records()[-1]
    assert rec["name"] == "fenced_mm"
    assert rec["fence"] == "steady-state"
    assert "float32[8,8]" in rec["delta"]
    blob = flight_recorder().to_chrome_trace()
    spans = [e for e in blob["traceEvents"]
             if e.get("cat") == "compile"
             and e.get("name") == "compile:fenced_mm"]
    assert len(spans) >= 2  # first-compile + the unexpected one
    assert all(e.get("dur", 0) > 0 for e in spans)
    assert any(e.get("args", {}).get("unexpected") for e in spans)


def test_fence_nesting_composes():
    obs = compile_observatory()
    obs.arm_fence("outer")
    obs.arm_fence("inner")
    obs.disarm_fence()
    assert obs.fenced
    # disarming the inner fence restores the OUTER label: a compile
    # now must be attributed to "outer", not the dead inner fence
    obs.record(name="late", wall_s=0.01, trigger="retrace")
    assert obs.unexpected_records()[-1]["fence"] == "outer"
    obs.disarm_fence()
    assert not obs.fenced


def test_no_compile_outside_fence_is_not_unexpected():
    obs = compile_observatory()
    mm = _mm_site(name="unfenced_mm")
    mm(jnp.ones((8, 8), jnp.float32))
    recs = [r for r in obs.tail() if r["name"] == "unfenced_mm"]
    assert recs and not recs[-1].get("unexpected")


def test_disabled_observation_is_passthrough(monkeypatch):
    monkeypatch.setenv("KEYSTONE_COMPILE_LOG", "0")
    obs = compile_observatory()
    count0 = obs.count_total()
    mm = _mm_site(name="disabled_mm")
    out = mm(jnp.ones((4, 4), jnp.float32))
    assert out.shape == (4, 4)
    assert obs.count_total() == count0


# -- PipelineTrace integration ----------------------------------------------


def test_trace_records_compiles_and_roundtrips():
    mm = _mm_site(name="traced_mm")
    with PipelineTrace("compiles") as tr:
        mm(jnp.ones((8, 8), jnp.float32))
    assert tr.compile_stats["count"] >= 1
    assert tr.compile_stats["wall_s"] > 0
    names = [e["name"] for e in tr.compiles]
    assert "traced_mm" in names
    tr2 = PipelineTrace.from_json(tr.to_json())
    assert tr2.compile_stats == tr.compile_stats
    assert [e["name"] for e in tr2.compiles] == names
    assert "compiles:" in tr.summary()


def test_legacy_trace_json_without_compiles_loads():
    with PipelineTrace("legacy") as tr:
        pass
    blob = json.loads(tr.to_json())
    blob.pop("compiles", None)
    blob.pop("compile_stats", None)
    tr2 = PipelineTrace.from_json(json.dumps(blob))
    assert tr2.compile_stats["count"] == 0


# -- the zero-recompile invariant, dynamically -------------------------------


def _streamed_epoch(imgs, labels, chunk=64):
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    stream = StreamingDataset.from_numpy(
        imgs, chunk_size=chunk, wire_dtype=np.uint8,
        tag="obs-epoch").map_chunks(
            lambda ad: ad.map_batch(
                lambda x: jnp.tanh(x.astype(jnp.float32) / 255.0)))
    return fit_streaming(LinearMapEstimator(lam=0.1), stream, labels)


def test_second_epoch_compiles_nothing():
    """The PR 3 invariant asserted through the observatory (the ci.sh
    recompile gate's tier-1 twin): a second identical streamed fit
    records zero unexpected compiles under an armed fence, and the
    per-fit fence itself saw nothing in either epoch's steady state."""
    rng = np.random.RandomState(0)
    imgs = (rng.rand(256, 48) * 255).astype(np.uint8)
    y = rng.randint(0, 10, 256)
    labels = (-np.ones((256, 10)) + 2.0 * np.eye(10)[y]).astype(np.float32)
    obs = compile_observatory()
    _streamed_epoch(imgs, labels)
    assert obs.unexpected_total() == 0  # steady-state chunks were clean
    before = obs.unexpected_total()
    with expect_no_compiles("second-epoch"):
        _streamed_epoch(imgs, labels)
    assert obs.unexpected_total() - before == 0


def test_streamed_fit_fence_catches_induced_recompile(monkeypatch):
    """A chunk-shape drift mid-fit (the bug class the fence exists
    for) is flagged: accumulate is patched to re-jit a new function
    object per chunk, so chunk 2 compiles under the armed fence."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    obs = compile_observatory()
    rng = np.random.RandomState(0)
    X = rng.rand(256, 16).astype(np.float32)
    Y = rng.rand(256, 3).astype(np.float32)
    orig = LinearMapEstimator.accumulate

    def recompiling_accumulate(self, carry, chunk, labels):
        # a FRESH watched jit per chunk: jax's trace cache keys on the
        # function object, so every call recompiles — the
        # per-instance-memo bug in miniature
        waste = watch_jit(jax.jit(lambda v: v * 2.0), name="drifting")
        waste(jnp.ones((4,), jnp.float32))
        return orig(self, carry, chunk, labels)

    monkeypatch.setattr(LinearMapEstimator, "accumulate",
                        recompiling_accumulate)
    before = obs.unexpected_total()
    fit_streaming(LinearMapEstimator(lam=0.1),
                  StreamingDataset.from_numpy(X, chunk_size=64),
                  Y)
    flagged = [r for r in obs.unexpected_records()
               if r["name"] == "drifting"]
    assert obs.unexpected_total() > before
    assert flagged and flagged[0]["fence"].startswith("fit_streaming:")


# -- cost capture & utilization ----------------------------------------------


def test_capture_stats_resolves_flops_and_memory():
    mm = _mm_site(name="stats_mm")
    mm(jnp.ones((32, 32), jnp.float32))
    stats = mm._keystone_site.capture_stats()
    assert stats is not None
    assert stats["flops"] > 0
    assert stats["bytes_accessed"] > 0
    assert stats["output_bytes"] == 32 * 32 * 4
    # memoized: second resolve returns the cached dict
    assert mm._keystone_site.capture_stats() is stats


def test_capture_does_not_count_as_workload_compile():
    obs = compile_observatory()
    mm = _mm_site(name="swallow_mm")
    mm(jnp.ones((8, 8), jnp.float32))
    count1 = obs.count_total()
    with expect_no_compiles("capture"):
        mm._keystone_site.capture_stats()  # AOT path, swallowed
    assert obs.count_total() == count1
    assert obs.unexpected_total() == 0


def test_executable_table_lists_called_sites():
    mm = _mm_site(name="table_mm")
    mm(jnp.ones((8, 8), jnp.float32))
    rows = executable_table(capture=True)
    row = [r for r in rows if r["name"] == "table_mm"]
    assert row and row[0]["calls"] == 1 and row[0]["compiles"] == 1
    assert row[0]["stats"]  # capture=True resolved memory/cost stats


def test_device_peaks_catalogue_env_fallback(monkeypatch):
    assert device_peaks("TPU v4").flops_per_s == 275e12
    assert device_peaks("NPU x9000").source == "fallback"
    monkeypatch.setenv("KEYSTONE_PEAK_FLOPS", "1e12")
    p = device_peaks("TPU v4")
    assert p.flops_per_s == 1e12 and p.source == "env"


def test_roofline_verdicts():
    peaks = DevicePeaks("test", 100e12, 1e12, "catalogue")
    # intensity 1000 >> ridge 100 -> compute-bound
    r = roofline(1e12, 1e9, 1.0, peaks=peaks)
    assert r["bound"] == "compute"
    assert r["mfu"] == pytest.approx(0.01)
    # intensity 1 << ridge -> memory-bound
    r = roofline(1e9, 1e9, 1.0, peaks=peaks)
    assert r["bound"] == "memory"
    assert r["membw_util"] == pytest.approx(1e-3)


def test_utilization_window_reports_coverage():
    mm = _mm_site(name="window_mm")
    x = jnp.ones((64, 64), jnp.float32)
    mm(x)  # compile outside the window
    with UtilizationWindow() as uw:
        for _ in range(4):
            mm(x)
    rep = uw.report(n_devices=1)
    assert "window_mm" in rep["covered_sites"]
    assert rep["flops_total"] >= 4 * mm._keystone_site.capture_stats()["flops"] * 0.99
    assert rep["mfu"] > 0
    assert rep["bound"] in ("compute", "memory")
    assert rep["peaks_source"] in ("catalogue", "env", "fallback")


def test_annotate_trace_backfills_node_mfu():
    """Executor node context attribution -> per-node MFU on the
    finished trace (the --trace-out annotation path)."""
    from keystone_tpu.parallel.dataset import ArrayDataset
    from keystone_tpu.workflow.transformer import Transformer

    class MatmulNode(Transformer):
        def apply(self, item):
            return item @ jnp.ones((24, 24), jnp.float32)

    _ = ArrayDataset  # per-item path: the executor wraps the node thunk
    x = np.random.RandomState(0).rand(32, 24).astype(np.float32)
    with PipelineTrace("annot") as tr:
        (MatmulNode() >> MatmulNode()).apply(x).numpy()
    node_compiles = [e for e in tr.compiles
                     if str(e.get("context", "")).startswith("node:")]
    assert node_compiles, "executor did not attribute the compile"
    n = annotate_trace(tr)
    assert n >= 1
    annotated = [r for r in tr.nodes if r.mfu > 0]
    assert annotated and annotated[0].flops > 0


# -- plan vs XLA -------------------------------------------------------------


@pytest.mark.parametrize("app", ["mnist.random_fft", "cifar.random_patch"])
def test_plan_vs_xla_on_check_apps(app):
    """Acceptance: plan_vs_xla reported for every planner-resolved
    node with a per-item program on the CIFAR and MNIST check apps,
    and the two memory models agree to within 2x."""
    from keystone_tpu.analysis.resources import (
        format_xla_verify,
        xla_verify_plan,
    )
    from keystone_tpu.pipelines import resolve_check_app

    target = resolve_check_app(app)()
    report = target.pipeline.check(
        target.input_spec, name=target.name, hbm_budget=16 << 30)
    rows = xla_verify_plan(report.analysis, report.plan)
    assert len(rows) == len(report.plan.entries)
    ok = [r for r in rows if r["status"] == "ok"]
    assert len(ok) >= 3, format_xla_verify(rows, app)
    for r in ok:
        assert r["plan_vs_xla"] is not None
        assert 0.5 <= r["plan_vs_xla"] <= 2.0, (r, app)
    # every row has an explicit status: coverage reported, not assumed
    assert all(r.get("status") for r in rows)


def test_xla_verify_uses_planner_charge_not_element_size():
    """The cross-check validates the PLANNER's per-item charge
    (operator resource_effect overrides included), not a recomputed
    raw element size — a divergence between the two is exactly what
    --xla exists to catch."""
    from keystone_tpu.analysis.resources import xla_verify_plan
    from keystone_tpu.pipelines import resolve_check_app

    target = resolve_check_app("mnist.random_fft")()
    report = target.pipeline.check(target.input_spec, name=target.name)
    baseline = {r["node_id"]: r for r in
                xla_verify_plan(report.analysis, report.plan)}
    ok_id = next(nid for nid, r in baseline.items()
                 if r["status"] == "ok")
    # planner suddenly under-charges this node 10x: the ratio must
    # track the plan's number, proving the plan is what is verified
    for e in report.plan.entries:
        if e["node_id"] == ok_id and e.get("item_nbytes"):
            e["item_nbytes"] = e["item_nbytes"] / 10.0
    skewed = {r["node_id"]: r for r in
              xla_verify_plan(report.analysis, report.plan)}
    assert skewed[ok_id]["plan_vs_xla"] == pytest.approx(
        baseline[ok_id]["plan_vs_xla"] / 10.0, rel=0.01)


def test_xla_verify_swallows_its_own_compiles():
    from keystone_tpu.analysis.resources import xla_verify_plan
    from keystone_tpu.pipelines import resolve_check_app

    obs = compile_observatory()
    target = resolve_check_app("mnist.random_fft")()
    report = target.pipeline.check(target.input_spec, name=target.name)
    count0 = obs.count_total()
    with expect_no_compiles("xla-verify"):
        xla_verify_plan(report.analysis, report.plan)
    assert obs.count_total() == count0
    assert obs.unexpected_total() == 0


# -- sampler RSS fallback (satellite) ----------------------------------------


def test_rss_fallback_uses_getrusage(monkeypatch):
    """/proc/self/statm absent (macOS, some containers) -> the
    unit-normalized getrusage peak-RSS shim answers instead."""
    import builtins

    from keystone_tpu.observability import sampler as sm

    real_open = builtins.open

    def broken_open(path, *a, **kw):
        if path == "/proc/self/statm":
            raise OSError("no procfs")
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", broken_open)
    v = sm._rss_bytes()
    assert v > 0  # ru_maxrss of a live python process is never 0
    # linux getrusage reports KB: the shim must have scaled to bytes
    import resource

    raw = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    import sys

    expect = raw if sys.platform == "darwin" else raw * 1024.0
    assert v == pytest.approx(expect, rel=0.5)


def test_ru_maxrss_unit_shim_darwin(monkeypatch):
    from keystone_tpu.observability import sampler as sm

    class FakeUsage:
        ru_maxrss = 2048

    import resource

    monkeypatch.setattr(resource, "getrusage", lambda who: FakeUsage())
    monkeypatch.setattr("sys.platform", "darwin")
    assert sm._ru_maxrss_bytes() == 2048.0  # darwin reports BYTES
    monkeypatch.setattr("sys.platform", "linux")
    assert sm._ru_maxrss_bytes() == 2048.0 * 1024  # linux reports KB


def test_broken_rss_probe_skipped_not_fatal(monkeypatch):
    """Both probe paths broken -> sample_once skips the probe for the
    tick (the broken-probe contract) and keeps sampling the rest."""
    import builtins
    import resource

    from keystone_tpu.observability.sampler import TelemetrySampler

    real_open = builtins.open

    def broken_open(path, *a, **kw):
        if path == "/proc/self/statm":
            raise OSError("no procfs")
        return real_open(path, *a, **kw)

    def broken_rusage(who):
        raise OSError("no getrusage either")

    monkeypatch.setattr(builtins, "open", broken_open)
    monkeypatch.setattr(resource, "getrusage", broken_rusage)
    s = TelemetrySampler(interval_s=0.05)
    values = s.sample_once()  # must not raise
    assert "process.rss_bytes" not in values


# -- device-OOM post-mortem (satellite) --------------------------------------


def test_is_device_oom_classification():
    assert is_device_oom(MemoryError("x"))
    assert is_device_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"))
    assert is_device_oom(RuntimeError("Allocation failure on device"))
    assert not is_device_oom(ValueError("shapes differ"))


def test_device_oom_postmortem_carries_executable_table(monkeypatch):
    """An XLA allocation failure mid-accumulate routes through
    attach_postmortem with the per-executable memory_analysis table in
    the dump: the artifact names WHICH executables held HBM."""
    from keystone_tpu.nodes.learning.linear import LinearMapEstimator
    from keystone_tpu.parallel.streaming import (
        StreamingDataset,
        fit_streaming,
    )

    # a watched executable with resolvable memory stats must exist so
    # the capture path has something to table
    mm = _mm_site(name="oom_mm")
    mm(jnp.ones((16, 16), jnp.float32))

    def exploding_accumulate(self, carry, chunk, labels):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 137438953472 bytes")  # the monkeypatched allocator

    monkeypatch.setattr(LinearMapEstimator, "accumulate",
                        exploding_accumulate)
    X = np.zeros((128, 8), np.float32)
    Y = np.zeros((128, 2), np.float32)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED") as ei:
        fit_streaming(LinearMapEstimator(lam=0.1),
                      StreamingDataset.from_numpy(X, chunk_size=64), Y)
    path = getattr(ei.value, "postmortem_path", None)
    assert path and os.path.exists(path)
    blob = json.load(open(path))
    assert blob["reason"] == "device_oom"
    assert blob["context"]["phase"] == "accumulate"
    assert blob["compiles"]["count"] >= 1
    rows = {r["name"]: r for r in blob["executables"]}
    assert "oom_mm" in rows
    stats = list(rows["oom_mm"]["stats"].values())
    assert stats and "output_bytes" in stats[0]  # memory_analysis table


# -- benchdiff prefix generalization (satellite) -----------------------------


def _artifact(tmp_path, name, metric, value, extra=None):
    line = {"metric": metric, "value": value, "unit": "u",
            "vs_baseline": 1.0}
    line.update(extra or {})
    p = tmp_path / name
    p.write_text(json.dumps({"tail": json.dumps(line)}))
    return str(p)


def test_benchdiff_prefix_discovery(tmp_path):
    from keystone_tpu.observability.benchdiff import (
        artifact_prefix,
        discover_history,
    )

    assert artifact_prefix("MULTICHIP_r05.json") == "MULTICHIP"
    assert artifact_prefix("BENCH_r12.json") == "BENCH"
    assert artifact_prefix("oddball.json") == "BENCH"
    for i in (1, 2, 3):
        _artifact(tmp_path, f"MULTICHIP_r0{i}.json",
                  "parity_images_per_sec", 100.0 + i)
        _artifact(tmp_path, f"BENCH_r0{i}.json",
                  "e2e_images_per_sec", 200.0 + i)
    hist = discover_history(str(tmp_path / "MULTICHIP_r03.json"))
    assert [os.path.basename(a.path) for a in hist] == [
        "MULTICHIP_r01.json", "MULTICHIP_r02.json"]
    hist = discover_history(str(tmp_path / "BENCH_r03.json"))
    assert all("BENCH" in os.path.basename(a.path) for a in hist)
    # explicit prefix argument wins over filename derivation
    hist = discover_history(str(tmp_path / "BENCH_r03.json"),
                            prefix="MULTICHIP")
    assert len(hist) == 3


def test_benchdiff_bands_mfu_companion_keys(tmp_path):
    """*_mfu / *_membw_util companion keys on a metric line band like
    first-class metrics; a large MFU drop classifies as regressed even
    when the headline stays flat."""
    from keystone_tpu.observability.benchdiff import compare, load_artifact

    base = load_artifact(_artifact(
        tmp_path, "BENCH_r01.json", "e2e_images_per_sec", 100.0,
        {"e2e_mfu": 0.20, "e2e_membw_util": 0.40, "compile_s": 1.2}))
    cur = load_artifact(_artifact(
        tmp_path, "BENCH_r02.json", "e2e_images_per_sec", 101.0,
        {"e2e_mfu": 0.10, "e2e_membw_util": 0.41, "compile_s": 9.9}))
    assert base.value("e2e_mfu") == 0.20
    assert base.value("compile_s") is None  # evidence key, not a metric
    rows = {r["metric"]: r for r in compare(base, cur)}
    assert rows["e2e_mfu"]["classification"] == "regressed"
    assert rows["e2e_membw_util"]["classification"] == "in-band"
    assert rows["e2e_images_per_sec"]["classification"] == "in-band"


def test_benchdiff_byte_companion_keys_lower_is_better(tmp_path):
    """h2d_bytes_per_image rides metric lines into banding via the
    companion-key pickup; HALVING it (the PR 5 wire-dtype win) must
    classify as improved, never regressed."""
    from keystone_tpu.observability.benchdiff import (
        compare,
        load_artifact,
        lower_is_better,
    )

    assert lower_is_better("h2d_bytes_per_image")
    base = load_artifact(_artifact(
        tmp_path, "BENCH_r01.json", "e2e_images_per_sec", 100.0,
        {"h2d_bytes_per_image": 12288.0}))
    cur = load_artifact(_artifact(
        tmp_path, "BENCH_r02.json", "e2e_images_per_sec", 100.0,
        {"h2d_bytes_per_image": 3072.0}))
    rows = {r["metric"]: r for r in compare(base, cur)}
    assert rows["h2d_bytes_per_image"]["classification"] == "improved"
