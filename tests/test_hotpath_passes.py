"""Hot-path + atomic-publication static passes (analysis/hotpath.py):
each rule — blocking, host-sync, I/O, lazy-import, unbounded-growth,
lock-held-dispatch, and the three publication clauses — fires on its
synthetic offender fixture (tests/lint_fixtures) with the full call
chain named; the package tree scans CLEAN under the wall budget; every
``HOTPATH_ALLOWLIST`` entry and every ``HOTPATH_COLD`` entry is LIVE
(removing it produces diagnostics — a dead suppression is a lint bug);
and the declarations themselves (``@hotpath`` / ``@published_by``) are
introspectable at runtime on the real serving classes."""
import ast
import pathlib
import time

import pytest

from keystone_tpu.analysis.hotpath import (
    HOTPATH_ALLOWLIST,
    HOTPATH_COLD,
    HOTPATH_SCAN_BUDGET_S,
    build_package,
    hotpath_hazards,
    published_classes,
    published_field_hazards,
    scan_package,
    scan_source,
)
from keystone_tpu.utils.guarded import hotpath, published_fields

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "keystone_tpu"
FIXTURES = pathlib.Path(__file__).resolve().parent / "lint_fixtures"

HOTPATH_FIXTURES = [
    ("hotpath_blocking_offender", "hotpath-blocking", 5),
    ("hotpath_hostsync_offender", "hotpath-host-sync", 3),
    ("hotpath_io_offender", "hotpath-io", 4),
    ("hotpath_import_offender", "hotpath-lazy-import", 1),
    ("hotpath_alloc_offender", "hotpath-unbounded-growth", 2),
]


def _src(name):
    return (FIXTURES / f"{name}.py").read_text()


def _scan(name, **kw):
    # hermetic: fixtures judged against an EMPTY allowlist/cold set so
    # the shipped tables can never mask a fixture regression
    kw.setdefault("allowlist", ())
    kw.setdefault("cold", ())
    return scan_source(_src(name), **kw)


# -- declarations ------------------------------------------------------------

def test_hotpath_marker_is_zero_cost_and_introspectable():
    def f():
        return 1

    marked = hotpath(f)
    assert marked is f  # a marker, not a wrapper: zero call overhead
    assert marked.__hotpath_entry__ is True


def test_serving_entry_points_carry_the_marker():
    """The declared request-path surface: the entry-point registry IS
    the decorated code (README 'Static checking')."""
    from keystone_tpu.observability.reqtrace import ExemplarReservoir, ReqTrace
    from keystone_tpu.serving.batcher import MicroBatcher
    from keystone_tpu.serving.http import ServingHandler
    from keystone_tpu.serving.plane import ServingPlane

    for fn in (MicroBatcher.submit, MicroBatcher.submit_request,
               MicroBatcher.take, MicroBatcher.done,
               ServingPlane.submit, ServingPlane.submit_request,
               ServingPlane.predict, ServingPlane.predict_traced,
               ServingPlane._execute, ServingPlane._serve_batch,
               ReqTrace.new, ExemplarReservoir.offer,
               ServingHandler.do_POST):
        assert getattr(fn, "__hotpath_entry__", False), fn


def test_published_by_lands_on_class_and_ast():
    from lint_fixtures.publication_offender import TornPlane

    assert published_fields(TornPlane) == {
        "_live": "_lock", "_epoch": "_lock"}
    classes = published_classes(ast.parse(_src("publication_offender")))
    assert classes["TornPlane"] == {"_live": "_lock", "_epoch": "_lock"}


def test_serving_classes_declare_their_published_fields():
    """The lock-free read surface the publication pass pins: the
    batcher's closed flag, the plane's ready snapshot, the reservoir's
    admission floor."""
    from keystone_tpu.observability.reqtrace import ExemplarReservoir
    from keystone_tpu.serving.batcher import MicroBatcher
    from keystone_tpu.serving.plane import ServingPlane

    assert published_fields(MicroBatcher) == {"_closed": "_lock"}
    assert published_fields(ServingPlane) == {"_live": "_lock"}
    assert published_fields(ExemplarReservoir) == {"_floor": "_lock"}


# -- per-rule firing on the offender fixtures --------------------------------

@pytest.mark.parametrize("name, code, count", HOTPATH_FIXTURES)
def test_rule_fires_on_offender_fixture(name, code, count):
    hits = _scan(name)
    assert {c for _, c, _ in hits} == {code}
    assert len(hits) == count
    for lineno, _, msg in hits:
        assert lineno > 0
        assert "hot path" in msg  # every diagnostic explains itself


def test_diagnostics_name_the_full_call_chain():
    """The interprocedural contract: a hazard inside a helper is
    attributed to the ENTRY POINT's chain, not just the helper."""
    hits = _scan("hotpath_blocking_offender")
    sleep_hits = [msg for _, _, msg in hits if "sleep" in msg]
    assert len(sleep_hits) == 1
    assert "SlowGate.submit -> SlowGate._stall" in sleep_hits[0]


def test_growth_rule_spares_drained_and_bounded_fields():
    hits = _scan("hotpath_alloc_offender")
    assert all("_seen" in msg for _, _, msg in hits)
    assert not any("_retired" in msg or "_recent" in msg
                   for _, _, msg in hits)


def test_lock_held_dispatch_fires_transitively_and_only_under_lock():
    hits = _scan("hotpath_lockdispatch_offender")
    dispatch = [h for h in hits if h[1] == "hotpath-lock-held-dispatch"]
    assert len(dispatch) == 1  # flush only; flush_unlocked is clean
    assert "holding `self._lock`" in dispatch[0][2]
    assert "DispatchUnderLock._dispatch" in dispatch[0][2]
    # the helper's own sync still fires, on its own line, chain-named
    syncs = [h for h in hits if h[1] == "hotpath-host-sync"]
    assert len(syncs) == 1
    assert "DispatchUnderLock.flush -> " in syncs[0][2]


def test_publication_pass_fires_each_clause_once():
    hits = published_field_hazards(
        ast.parse(_src("publication_offender")), allowlist=())
    assert {c for _, c, _ in hits} == {
        "unpublished-write", "non-atomic-publication", "torn-publication"}
    assert len(hits) == 3  # clean_flip / clean_drop_locked are silent


# -- allowlist / cold semantics ----------------------------------------------

def test_allowlist_suppresses_by_func_and_offender():
    allow = {"SlowGate.handle:acquire", "SlowGate.handle:wait",
             "SlowGate.handle:result", "SlowGate.drain:get",
             "SlowGate._stall:sleep"}
    assert _scan("hotpath_blocking_offender", allowlist=allow) == []
    # a PARTIAL allowlist only suppresses its own keys
    partial = _scan("hotpath_blocking_offender",
                    allowlist={"SlowGate.drain:get"})
    assert len(partial) == 4
    assert not any("q.get" in msg for _, _, msg in partial)


def test_cold_set_prunes_the_traversal():
    hits = _scan("hotpath_blocking_offender", cold={"SlowGate._stall"})
    assert not any("sleep" in msg for _, _, msg in hits)
    assert len(hits) == 4  # the direct hazards are untouched


def test_publication_allowlist_suppresses_by_method_and_field():
    allow = {"TornPlane.unlocked_flip:_live", "TornPlane.piecewise:_live",
             "TornPlane.torn_swap:_live"}
    assert published_field_hazards(
        ast.parse(_src("publication_offender")), allowlist=allow) == []


# -- the package tree --------------------------------------------------------

def test_tree_scan_is_clean_and_under_budget():
    """The PR bar: zero unallowlisted diagnostics over the package,
    inside the wall budget CI asserts (static-layer creep is a measured
    quantity)."""
    t0 = time.perf_counter()
    hits = scan_package(PKG)
    elapsed = time.perf_counter() - t0
    assert hits == [], hits
    assert elapsed < HOTPATH_SCAN_BUDGET_S, (
        f"tree scan took {elapsed:.2f}s >= {HOTPATH_SCAN_BUDGET_S}s")


def test_every_allowlist_entry_is_live():
    """Removing ANY allowlist entry must surface at least one
    diagnostic — a dead entry is a stale suppression waiting to mask a
    real regression. (One shared index; the BFS re-runs per entry.)"""
    pkg = build_package(PKG)
    assert hotpath_hazards(pkg) == []
    for entry in sorted(HOTPATH_ALLOWLIST):
        hits = hotpath_hazards(pkg, allowlist=HOTPATH_ALLOWLIST - {entry})
        assert hits, f"allowlist entry {entry!r} is dead"


def test_every_cold_entry_is_live():
    """Removing a cold entry must pull new reachable code into the
    traversal and fire diagnostics — a cold entry that changes nothing
    is a stale claim."""
    pkg = build_package(PKG)
    for entry in sorted(HOTPATH_COLD):
        hits = hotpath_hazards(pkg, cold=HOTPATH_COLD - {entry})
        assert hits, f"cold entry {entry!r} is dead"


def test_scan_package_reports_the_lint_shape(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "serving").mkdir(parents=True)
    (pkg / "serving" / "bad.py").write_text(_src("hotpath_import_offender"))
    hits = scan_package(pkg)
    assert {h["code"] for h in hits} == {"hotpath-lazy-import"}
    for h in hits:
        assert set(h) == {"file", "lineno", "code", "message"}
        assert h["file"].endswith("bad.py")
        assert isinstance(h["lineno"], int) and h["lineno"] > 0


# -- wiring: lint + check CLI ------------------------------------------------

def test_lint_gate_runs_hotpath_passes(tmp_path, monkeypatch):
    """tools/lint.py fails when a package module has a hot-path
    diagnostic, and its summary line carries the measured runtime
    against the budget (wired like the concurrency/SPMD passes)."""
    import sys

    sys.path.insert(0, str(REPO / "tools"))
    try:
        import lint
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "keystone_tpu"
    (pkg / "serving").mkdir(parents=True)
    (pkg / "serving" / "bad.py").write_text(
        _src("hotpath_blocking_offender"))
    monkeypatch.setattr(lint, "REPO", tmp_path)
    monkeypatch.setattr(lint, "PKG", pkg)
    assert lint.run_hotpath_rules() > 0


@pytest.mark.slow
def test_check_cli_json_carries_hotpath_key(tmp_path):
    """`python -m keystone_tpu check <app> --json` grows the `hotpath`
    key (clean today) next to `concurrency`/`spmd`, exit codes
    preserved — the schema the CI consumers parse."""
    import json
    import os
    import subprocess
    import sys

    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "keystone_tpu", "check",
         "mnist.random_fft", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "hotpath: clean" in proc.stdout
    blob = json.loads(out.read_text())
    assert blob["hotpath"] == []
    assert blob["spmd"] == []  # neighbours unchanged
    assert blob["concurrency"] == []
