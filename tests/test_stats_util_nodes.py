"""Stats/util node tests vs numpy golden implementations (mirrors the
reference's per-node suites)."""
import numpy as np
import pytest

from keystone_tpu.nodes.stats import (
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
)
from keystone_tpu.nodes.util import (
    ClassLabelIndicatorsFromIntArrayLabels,
    ClassLabelIndicatorsFromIntLabels,
    MatrixVectorizer,
    MaxClassifier,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)
from keystone_tpu.parallel.dataset import ArrayDataset


def test_random_sign_node():
    x = np.arange(6, dtype=np.float32)
    node = RandomSignNode(np.array([1, -1, 1, -1, 1, -1], np.float32))
    out = node(x[None, :]).numpy()
    np.testing.assert_array_equal(out[0], x * np.array([1, -1, 1, -1, 1, -1]))


def test_random_sign_create_seeded():
    a = RandomSignNode.create(100, seed=7)
    b = RandomSignNode.create(100, seed=7)
    np.testing.assert_array_equal(a.signs, b.signs)
    assert set(np.unique(a.signs)) <= {-1.0, 1.0}


def test_padded_fft_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 20).astype(np.float32)
    out = PaddedFFT()(x).numpy()
    # next pow2 of 20 = 32 -> first 16 real parts
    padded = np.pad(x, ((0, 0), (0, 12)))
    expect = np.real(np.fft.fft(padded, axis=-1))[:, :16]
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-4)
    assert out.shape == (3, 16)


def test_linear_rectifier():
    x = np.array([[-1.0, 0.5, 2.0]], np.float32)
    out = LinearRectifier(0.0, 0.25)(x).numpy()
    np.testing.assert_allclose(out[0], np.maximum(0.0, x[0] - 0.25))


def test_normalize_rows():
    x = np.array([[3.0, 4.0]], np.float32)
    out = NormalizeRows()(x).numpy()
    np.testing.assert_allclose(out[0], [0.6, 0.8], rtol=1e-6)


def test_signed_hellinger():
    x = np.array([[-4.0, 9.0]], np.float32)
    out = SignedHellingerMapper()(x).numpy()
    np.testing.assert_allclose(out[0], [-2.0, 3.0], rtol=1e-6)


def test_standard_scaler_matches_numpy():
    rng = np.random.RandomState(1)
    x = rng.randn(50, 6).astype(np.float32) * 3 + 1
    model = StandardScaler().fit(x)
    np.testing.assert_allclose(model.mean, x.mean(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        model.std, x.std(0, ddof=1), rtol=1e-3, atol=1e-4
    )
    out = model(x).numpy()
    np.testing.assert_allclose(out.mean(0), 0, atol=1e-4)
    np.testing.assert_allclose(out.std(0, ddof=1), 1, rtol=1e-3)


def test_standard_scaler_degenerate_column():
    x = np.ones((10, 3), np.float32)
    model = StandardScaler().fit(x)
    np.testing.assert_array_equal(model.std, np.ones(3))


def test_standard_scaler_mean_only():
    x = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    model = StandardScaler(normalize_std_dev=False).fit(x)
    assert model.std is None


def test_class_label_indicators():
    node = ClassLabelIndicatorsFromIntLabels(4)
    out = node(np.array([0, 2, 3], np.int32)).numpy()
    np.testing.assert_array_equal(
        out,
        [[1, -1, -1, -1], [-1, -1, 1, -1], [-1, -1, -1, 1]],
    )


def test_class_label_indicators_array():
    node = ClassLabelIndicatorsFromIntArrayLabels(5)
    # padded multi-labels: -1 = absent
    labels = np.array([[0, 2, -1], [4, -1, -1]], np.int32)
    out = node(labels).numpy()
    np.testing.assert_array_equal(out[0], [1, -1, 1, -1, -1])
    np.testing.assert_array_equal(out[1], [-1, -1, -1, -1, 1])


def test_vector_combiner():
    a = np.ones((4, 2), np.float32)
    b = np.zeros((4, 3), np.float32)
    dsa = ArrayDataset.from_numpy(a)
    z = dsa.zip(ArrayDataset.from_numpy(b))
    out = VectorCombiner().apply_dataset(z).numpy()
    assert out.shape == (4, 5)
    np.testing.assert_array_equal(out[:, :2], a)


def test_max_classifier():
    x = np.array([[0.1, 0.9, 0.2], [1.0, -1.0, 0.0]], np.float32)
    out = MaxClassifier()(x).numpy()
    np.testing.assert_array_equal(out, [1, 0])


def test_topk_classifier():
    x = np.array([[0.1, 0.9, 0.5, -0.2]], np.float32)
    out = TopKClassifier(3)(x).numpy()
    np.testing.assert_array_equal(out[0], [1, 2, 0])


def test_vector_splitter():
    x = np.arange(10, dtype=np.float32)[None, :]
    out = VectorSplitter(4)(x).get()
    parts = out.numpy()
    assert len(parts) == 3
    np.testing.assert_array_equal(parts[0][0], [0, 1, 2, 3])
    np.testing.assert_array_equal(parts[2][0], [8, 9])


def test_matrix_vectorizer_column_major():
    x = np.array([[[1.0, 2.0], [3.0, 4.0]]], np.float32)  # one 2x2 matrix
    out = MatrixVectorizer()(x).numpy()
    np.testing.assert_array_equal(out[0], [1, 3, 2, 4])  # column-major


def test_sparse_vector_coalesces_duplicate_indices():
    # Duplicate indices must sum (matching the padded-COO einsum paths),
    # not last-write-win in todense().
    from keystone_tpu.nodes.util.sparse import SparseVector, sparse_batch

    sv = SparseVector([3, 1, 3, 1, 7], [1.0, 2.0, 4.0, 8.0, 0.5], size=10)
    assert sv.indices.tolist() == [1, 3, 7]
    np.testing.assert_allclose(sv.values, [10.0, 5.0, 0.5])
    dense = sv.todense()
    assert dense[1] == 10.0 and dense[3] == 5.0 and dense[7] == 0.5
    # padded-COO scatter-sum of the batch form must equal todense()
    idx, val, size = sparse_batch([sv])
    scattered = np.zeros(size, dtype=np.float32)
    np.add.at(scattered, idx[0], val[0])
    np.testing.assert_allclose(scattered, dense)
