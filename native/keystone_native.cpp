/**
 * keystone_tpu native host runtime (counterpart of the reference's
 * src/main/cpp native layer: the reference keeps its host-side hot loops
 * in C++ behind JNI; here the host-side hot loops are data decode and
 * text featurization, exposed to Python over a C ABI for ctypes).
 *
 * Components:
 *  - CIFAR binary record decode (record = 1 label byte + 3 channel
 *    planes; cifar_loader's layout, reference loaders/CifarLoader.scala)
 *  - JVM String.hashCode + MurmurHash3 ordered ngram hashing, the exact
 *    hash family of nodes/nlp/hashing.py, batched over a token stream
 *  - float32 CSV parsing
 *
 * Build: make -C native   (g++ -O3 -fPIC -fopenmp -shared)
 */
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>

extern "C" {

/* ---------------- CIFAR binary decode ---------------- */

/* raw: n records of (1 + rows*cols*chans) bytes, channel-planar.
 * out_images: n*rows*cols*chans float32 (HWC), out_labels: n int32. */
void cifar_decode(const uint8_t* raw, int64_t n, int rows, int cols,
                  int chans, float* out_images, int32_t* out_labels) {
    const int64_t plane = (int64_t)rows * cols;
    const int64_t rec = 1 + plane * chans;
    #pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* r = raw + i * rec;
        out_labels[i] = (int32_t)r[0];
        const uint8_t* px = r + 1;
        float* out = out_images + i * plane * chans;
        for (int c = 0; c < chans; ++c) {
            for (int64_t p = 0; p < plane; ++p) {
                /* planar (c, row, col) -> interleaved (row, col, c) */
                out[p * chans + c] = (float)px[c * plane + p];
            }
        }
    }
}

/* ---------------- text feature hashing ---------------- */

static inline int32_t rotl32(uint32_t x, int r) {
    return (int32_t)((x << r) | (x >> (32 - r)));
}

static inline uint32_t mmix(uint32_t h, uint32_t k) {
    k *= 0xcc9e2d51u;
    k = (uint32_t)rotl32(k, 15);
    k *= 0x1b873593u;
    h ^= k;
    h = (uint32_t)rotl32(h, 13);
    return h * 5u + 0xe6546b64u;
}

static inline int32_t mfinal(uint32_t h, uint32_t len) {
    h ^= len;
    h ^= h >> 16; h *= 0x85ebca6bu;
    h ^= h >> 13; h *= 0xc2b2ae35u;
    h ^= h >> 16;
    return (int32_t)h;
}

/* JVM String.hashCode over UTF-16 code units of a UTF-8 input string. */
int32_t java_string_hash(const char* s, int64_t len) {
    uint32_t h = 0;  /* unsigned: wraparound is defined (JVM semantics) */
    int64_t i = 0;
    while (i < len) {
        uint32_t cp;
        uint8_t b = (uint8_t)s[i];
        if (b < 0x80) { cp = b; i += 1; }
        else if ((b >> 5) == 0x6) {
            cp = ((b & 0x1Fu) << 6) | ((uint8_t)s[i+1] & 0x3Fu); i += 2;
        } else if ((b >> 4) == 0xE) {
            cp = ((b & 0x0Fu) << 12) | (((uint8_t)s[i+1] & 0x3Fu) << 6)
                 | ((uint8_t)s[i+2] & 0x3Fu); i += 3;
        } else {
            cp = ((b & 0x07u) << 18) | (((uint8_t)s[i+1] & 0x3Fu) << 12)
                 | (((uint8_t)s[i+2] & 0x3Fu) << 6)
                 | ((uint8_t)s[i+3] & 0x3Fu); i += 4;
        }
        if (cp >= 0x10000) {  /* surrogate pair: two UTF-16 units */
            uint32_t v = cp - 0x10000;
            h = h * 31u + (0xD800u + (v >> 10));
            h = h * 31u + (0xDC00u + (v & 0x3FFu));
        } else {
            h = h * 31u + cp;
        }
    }
    return (int32_t)h;
}

static inline int32_t nonneg_mod(int32_t x, int32_t mod) {
    int32_t r = x % mod;
    return r < 0 ? r + mod : r;
}

/* Rolling murmur ngram hashing over one tokenized document
 * (the hot loop of NGramsHashingTF, nodes/nlp/hashing.py).
 * token_hashes: per-token JVM hashes; emits (feature index, count=1)
 * pairs into out_features (caller aggregates counts).
 * Returns number of features written (bounded by cap). */
int64_t ngram_hash_doc(const int32_t* token_hashes, int64_t n_tokens,
                       int32_t min_order, int32_t max_order,
                       int32_t num_features, int32_t seq_seed,
                       int32_t* out_features, int64_t cap) {
    int64_t out = 0;
    for (int64_t i = 0; i + min_order <= n_tokens; ++i) {
        uint32_t h = (uint32_t)seq_seed;
        int32_t order = 0;
        for (int64_t j = i; j < i + min_order; ++j) {
            h = mmix(h, (uint32_t)token_hashes[j]);
        }
        order = min_order;
        if (out >= cap) return out;
        out_features[out++] =
            nonneg_mod(mfinal(h, (uint32_t)order), num_features);
        for (order = min_order + 1;
             order <= max_order && i + order <= n_tokens; ++order) {
            h = mmix(h, (uint32_t)token_hashes[i + order - 1]);
            if (out >= cap) return out;
            out_features[out++] =
                nonneg_mod(mfinal(h, (uint32_t)order), num_features);
        }
    }
    return out;
}

/* Batch JVM hashing of a packed UTF-8 token arena:
 * offsets has n+1 entries delimiting each token in `arena`. */
void java_string_hash_batch(const char* arena, const int64_t* offsets,
                            int64_t n, int32_t* out) {
    #pragma omp parallel for
    for (int64_t i = 0; i < n; ++i) {
        out[i] = java_string_hash(arena + offsets[i],
                                  offsets[i + 1] - offsets[i]);
    }
}

/* ---------------- CSV parsing ---------------- */

/* Parse newline-separated comma-separated floats. Strict about field
 * structure: an empty or non-numeric field returns -1 so the caller
 * falls back to a descriptive parser (consecutive delimiters must not
 * silently shift values across rows). */
int64_t csv_parse_f32(const char* buf, int64_t len, float* out, int64_t cap) {
    int64_t n = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        while (p < end && (*p == '\n' || *p == '\r')) ++p;  /* blank lines */
        if (p >= end) break;
        for (;;) {
            while (p < end && (*p == ' ' || *p == '\t')) ++p;
            /* strtof treats '\n' as skippable whitespace, which would let an
             * empty trailing field swallow the next row's first value. */
            if (p >= end || *p == '\n' || *p == '\r') return -1;
            char* next = nullptr;
            float v = strtof(p, &next);
            if (next == p || n >= cap) return -1;  /* empty/bad field */
            out[n++] = v;
            p = next;
            while (p < end && (*p == ' ' || *p == '\t')) ++p;
            if (p < end && *p == ',') { ++p; continue; }
            break;
        }
        if (p < end && *p != '\n' && *p != '\r') return -1;
    }
    return n;
}

}  /* extern "C" */
