#!/usr/bin/env bash
# Single-host launcher — the analogue of the reference's
# bin/run-pipeline.sh local mode (reference: bin/run-pipeline.sh:6-43).
#
#   bin/run-pipeline.sh <app> [--flags]
#   bin/run-pipeline.sh                 # list apps
#   bin/run-pipeline.sh --check         # repo static gate (bin/ci.sh
#                                       # --no-tests): AST rules + donation
#                                       # shape gate + per-app pipeline
#                                       # checks with budgeted HBM plans
#   bin/run-pipeline.sh check <app>     # static-check one app's DAG
#
# The reference capped OMP_NUM_THREADS to protect OpenBLAS inside Spark
# executors (run-pipeline.sh:12-31). Here TPU compute goes through XLA,
# but host-side stages (image decode, tokenization, numpy in loaders)
# still use OpenBLAS/OpenMP through numpy — same cap, same reason.
set -euo pipefail

KEYSTONE_HOME="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

if [[ -z "${OMP_NUM_THREADS:-}" ]]; then
  ncores="$(getconf _NPROCESSORS_ONLN 2>/dev/null || echo 8)"
  export OMP_NUM_THREADS="$(( ncores < 32 ? ncores : 32 ))"
fi

# Build the native host library on first use (cifar decode, text hashing,
# csv parse — keystone_tpu/native falls back to pure Python without it).
if [[ ! -e "$KEYSTONE_HOME/native/libkeystone_native.so" ]] \
    && command -v make >/dev/null 2>&1; then
  make -C "$KEYSTONE_HOME/native" >/dev/null 2>&1 || true
fi

export PYTHONPATH="$KEYSTONE_HOME${PYTHONPATH:+:$PYTHONPATH}"
PY=python3
command -v python3 >/dev/null 2>&1 || PY=python

# --check: the pre-PR static gate — no data, no device, exit != 0 on
# any diagnostic or predicted HBM-budget violation (bin/ci.sh chains
# tools/lint.py and the budgeted `check --all`; the full gate with
# tier-1 tests is `bin/ci.sh` without flags)
if [[ "${1:-}" == "--check" ]]; then
  shift
  exec "$KEYSTONE_HOME/bin/ci.sh" --no-tests "$@"
fi

exec "$PY" -m keystone_tpu "$@"
