#!/usr/bin/env bash
# One-shot CI gate for this repo — chains the three hermetic checks a PR
# must pass, in fail-fast order of cost:
#
#   1. tools/lint.py --skip-apps   AST rules (host coercions, recompile
#                                  hazards, donation safety, swallow-all,
#                                  cast-before-transfer, the three
#                                  concurrency pass families, the four
#                                  SPMD-safety pass families:
#                                  collective divergence, barrier/
#                                  coordination-shape stability,
#                                  collective axis bindings, world-
#                                  checkpoint consistency, and the
#                                  hot-path + atomic-publication passes:
#                                  interprocedural request-path
#                                  reachability from the @hotpath entry
#                                  points, blocking/host-sync/IO/lazy-
#                                  import/unbounded-growth/lock-held-
#                                  dispatch hazards, @published_by swap
#                                  discipline — the full-tree scan is
#                                  wall-budgeted and its runtime is
#                                  printed in the gate output) + the
#                                  eval_shape donation shape gate (+ ruff
#                                  if present)
#   2. python -m keystone_tpu check --all --budget $KEYSTONE_CI_HBM_BUDGET
#                                  abstract interpretation + graph lints
#                                  (incl. the sharding-flow lattice) +
#                                  static HBM plans over every CHECK_APPS
#                                  app + the concurrency scan + the
#                                  metric-name-drift scan + the SPMD
#                                  scan (the `spmd` key in --json) +
#                                  the hot-path scan (the `hotpath` key),
#                                  device-free; exit 1 on diagnostics,
#                                  exit 2 on a predicted budget violation
#   2a. benchdiff (ADVISORY)       classify the two newest artifacts of
#                                  each family (BENCH_r*.json and
#                                  MULTICHIP_r*.json) against per-metric
#                                  noise bands (observability/benchdiff.py);
#                                  prints the table, never fails the gate
#   2b. recompile gate             tools/recompile_gate.py — a smoke
#                                  streamed fit twice; ANY compile in the
#                                  second epoch fails (compile observatory
#                                  fence, the dynamic recompile-hazard gate)
#   2b'. numerics gate             tools/numerics_gate.py — a clean smoke
#                                  streamed fit must pull health words and
#                                  write NO post-mortem; the same fit with
#                                  one fault-injected NaN chunk must raise
#                                  NumericsError naming chunk+stream with
#                                  a post-mortem carrying the health series
#   2b''. elastic gate             tools/elastic_gate.py — a 2-process CPU
#                                  dryrun streamed fit (jax.distributed +
#                                  gloo); process 1 killed mid-stream by a
#                                  host_death fault, world relaunched,
#                                  resumed from the shared StreamCheckpoint;
#                                  resumed weights must be bit-identical
#   2b'''. serving gate            tools/serving_gate.py — start
#                                  `python -m keystone_tpu serve` on an
#                                  ephemeral port with 2 saved models,
#                                  wait on the readiness-gated /healthz,
#                                  drive requests across >= 2 shapes and
#                                  both models, and fail on any fenced
#                                  steady-state recompile or a
#                                  /healthz-not-ready timeout
#   2b''''. chaos gate             tools/chaos_gate.py — the serving
#                                  scenario catalogue (burst, diurnal,
#                                  zipf-churn, straggler-dispatch,
#                                  poisoned-batch, overload-shed) at
#                                  bounded seeds: deterministic trace
#                                  replay under seeded serve.* faults;
#                                  every run ends clean or CLASSIFIED
#                                  with a post-mortem naming
#                                  scenario+seed; a violated
#                                  p99/availability floor exits 1 by name
#   2b'''''. fleet gate            tools/fleet_gate.py — 3 replica
#                                  subprocesses behind the real-HTTP
#                                  fleet router, placement solved under
#                                  finite budgets; SIGKILL the busiest
#                                  replica mid-replay: the reactor must
#                                  re-place its models sha-verified,
#                                  keep p99 under the drill floor, and
#                                  classify every refusal (429/503),
#                                  never an unclassified error
#   2c. bounded-seed stress        the deterministic-interleaving suite
#                                  (tests/test_concurrency_sched.py):
#                                  historical-race regression schedules +
#                                  a bounded seeded fuzz of the prefetcher
#                                  — cheap, catches schedule-dependent
#                                  breakage before the full tier-1 bill
#   3. tier-1 pytest               tests/ -m 'not slow' on the CPU-simulated
#                                  8-device mesh
#
#   bin/ci.sh                      # the full gate (PR bar)
#   bin/ci.sh --no-tests           # static layers only (what
#                                  # bin/run-pipeline.sh --check runs)
#
# KEYSTONE_CI_HBM_BUDGET (default 16GiB — one v5e chip's HBM) bounds
# every app's statically planned fit-path peak; see README "Static
# checking" for the accounting model.
set -euo pipefail

KEYSTONE_HOME="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$KEYSTONE_HOME${PYTHONPATH:+:$PYTHONPATH}"
PY=python3
command -v python3 >/dev/null 2>&1 || PY=python

run_tests=1
if [[ "${1:-}" == "--no-tests" ]]; then
  run_tests=0
  shift
fi

BUDGET="${KEYSTONE_CI_HBM_BUDGET:-16GiB}"

echo "== ci: lint (AST rules + hot-path/publication passes + donation shape gate) =="
"$PY" "$KEYSTONE_HOME/tools/lint.py" --skip-apps

echo "== ci: static pipeline checks + HBM plans (budget $BUDGET) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PY" -m keystone_tpu check --all --budget "$BUDGET"

# Advisory bench-regression gate: classify the two most recent
# artifacts of each driver family (BENCH_r*.json and MULTICHIP_r*.json
# — benchdiff derives noise bands per family from the artifact's own
# prefix) against the per-metric noise bands
# (observability/benchdiff.py). NON-FATAL by design — CI machines do
# not produce fresh artifacts, so a historical regression verdict
# should inform the PR, not block it; the classification table lands
# in the CI log either way. Exit 2 = regression beyond band.
for prefix in BENCH MULTICHIP; do
  bench_artifacts=$(ls "$KEYSTONE_HOME/${prefix}"_r*.json 2>/dev/null | sort | tail -2 || true)
  if [[ $(echo "$bench_artifacts" | wc -w) -eq 2 ]]; then
    echo "== ci: benchdiff $prefix (advisory) =="
    # shellcheck disable=SC2086
    "$PY" -m keystone_tpu benchdiff $bench_artifacts \
      || echo "benchdiff: advisory verdict exit $? (not failing CI)"
  else
    echo "== ci: benchdiff $prefix skipped (need >= 2 ${prefix}_r*.json artifacts) =="
  fi
done

if (( run_tests )); then
  echo "== ci: recompile gate (second epoch must compile nothing) =="
  # the dynamic complement of the static recompile-hazard lints: a
  # smoke streamed fit runs twice and any compile in the second epoch
  # fails the gate, naming the jit site + signature delta (PR 3's
  # zero-recompile invariant, now asserted by the compile observatory
  # instead of only by one tier-1 test)
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/recompile_gate.py"

  echo "== ci: numerics gate (injected NaN must trip; clean fit must not) =="
  # the dynamic pin for the data-health plane: both directions of the
  # tripwire contract (tools/numerics_gate.py), against the real
  # streamed path with a deterministic kind="corrupt" fault injection
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/numerics_gate.py"

  echo "== ci: elastic gate (kill one host mid-fit, relaunch, resume) =="
  # the dynamic pin for the elastic multi-host plane
  # (tools/elastic_gate.py): a 2-process CPU dryrun streamed fit over
  # real jax.distributed + gloo — process 1 is killed mid-stream by a
  # host_death fault, the world relaunches, resumes from the shared
  # StreamCheckpoint, and the resumed weights must be bit-identical to
  # the uninterrupted run with the warmup fence clean throughout
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/elastic_gate.py"

  echo "== ci: serving gate (2 models, 2 shapes, fence-clean, readiness-gated) =="
  # the dynamic pin for the serving plane (tools/serving_gate.py): the
  # real subprocess + HTTP deployment shape — server binds, /healthz
  # reports warming until every admitted model's warmup compile
  # completed, requests across >= 2 buckets and both models, and the
  # armed observatory fence must record ZERO steady-state recompiles
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/serving_gate.py"

  echo "== ci: chaos gate (scenario catalogue at bounded seeds, SLO floors) =="
  # the dynamic pin for graceful degradation (tools/chaos_gate.py): the
  # full serving/scenarios catalogue — bursty/diurnal/Zipf traffic,
  # churn under load, seeded dispatch/admit faults — replayed in
  # process at bounded seeds; every run must end clean or in a
  # CLASSIFIED failure with a post-mortem naming scenario+seed, and a
  # violated p99/availability floor fails the gate by name
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/chaos_gate.py" --seeds 2

  echo "== ci: fleet gate (3 subprocess replicas, SIGKILL one mid-replay) =="
  # the dynamic pin for the serving fleet (tools/fleet_gate.py): three
  # replica SUBPROCESSES behind the real-HTTP router, placement solved
  # under finite per-replica budgets and admitted sha-verified; mid-
  # replay the busiest replica is SIGKILLed cold — the reactor must
  # count exactly one death, drop the corpse from the membership,
  # re-place its models from canonical bytes (sha-verified again), the
  # p99 must stay under the drill floor, and every refusal in the
  # window must be classified (429/503) — never an unclassified error
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" "$KEYSTONE_HOME/tools/fleet_gate.py"

  echo "== ci: bounded-seed concurrency stress (regression schedules + fuzz) =="
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m pytest "$KEYSTONE_HOME/tests/test_concurrency_sched.py" -q \
    -m 'not slow' -p no:cacheprovider

  echo "== ci: tier-1 tests =="
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m pytest "$KEYSTONE_HOME/tests" -q -m 'not slow' \
    -p no:cacheprovider
fi

echo "== ci: clean =="
