#!/usr/bin/env bash
# One-shot CI gate for this repo — chains the three hermetic checks a PR
# must pass, in fail-fast order of cost:
#
#   1. tools/lint.py --skip-apps   AST rules (host coercions, recompile
#                                  hazards, donation safety, swallow-all,
#                                  cast-before-transfer, the three
#                                  concurrency pass families) + the
#                                  eval_shape donation shape gate (+ ruff
#                                  if present)
#   2. python -m keystone_tpu check --all --budget $KEYSTONE_CI_HBM_BUDGET
#                                  abstract interpretation + graph lints +
#                                  static HBM plans over every CHECK_APPS
#                                  app + the concurrency scan, device-free;
#                                  exit 1 on diagnostics, exit 2 on a
#                                  predicted budget violation
#   2b. bounded-seed stress        the deterministic-interleaving suite
#                                  (tests/test_concurrency_sched.py):
#                                  historical-race regression schedules +
#                                  a bounded seeded fuzz of the prefetcher
#                                  — cheap, catches schedule-dependent
#                                  breakage before the full tier-1 bill
#   3. tier-1 pytest               tests/ -m 'not slow' on the CPU-simulated
#                                  8-device mesh
#
#   bin/ci.sh                      # the full gate (PR bar)
#   bin/ci.sh --no-tests           # static layers only (what
#                                  # bin/run-pipeline.sh --check runs)
#
# KEYSTONE_CI_HBM_BUDGET (default 16GiB — one v5e chip's HBM) bounds
# every app's statically planned fit-path peak; see README "Static
# checking" for the accounting model.
set -euo pipefail

KEYSTONE_HOME="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$KEYSTONE_HOME${PYTHONPATH:+:$PYTHONPATH}"
PY=python3
command -v python3 >/dev/null 2>&1 || PY=python

run_tests=1
if [[ "${1:-}" == "--no-tests" ]]; then
  run_tests=0
  shift
fi

BUDGET="${KEYSTONE_CI_HBM_BUDGET:-16GiB}"

echo "== ci: lint (AST rules + donation shape gate) =="
"$PY" "$KEYSTONE_HOME/tools/lint.py" --skip-apps

echo "== ci: static pipeline checks + HBM plans (budget $BUDGET) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  "$PY" -m keystone_tpu check --all --budget "$BUDGET"

if (( run_tests )); then
  echo "== ci: bounded-seed concurrency stress (regression schedules + fuzz) =="
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m pytest "$KEYSTONE_HOME/tests/test_concurrency_sched.py" -q \
    -m 'not slow' -p no:cacheprovider

  echo "== ci: tier-1 tests =="
  JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    "$PY" -m pytest "$KEYSTONE_HOME/tests" -q -m 'not slow' \
    -p no:cacheprovider
fi

echo "== ci: clean =="
