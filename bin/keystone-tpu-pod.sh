#!/usr/bin/env bash
# Pod-scale cluster launcher — the analogue of the reference's
# bin/keystone-ec2.sh (spark-ec2 provisioning, reference EC2.md:17-31),
# rebuilt for Cloud TPU pod slices.
#
# The reference provisioned a Spark driver + executors and submitted an
# assembly jar. A TPU pod is SPMD instead: every host (worker) runs the
# SAME program; jax.distributed wires the hosts together, jax.devices()
# spans the whole slice, and the mesh's collectives ride ICI within the
# slice (DCN across slices). There is no driver/executor split.
#
# Usage:
#   bin/keystone-tpu-pod.sh create  <name> --zone Z --type v5litepod-64 [--version IMG]
#   bin/keystone-tpu-pod.sh install <name> --zone Z        # rsync repo + deps to all workers
#   bin/keystone-tpu-pod.sh run     <name> --zone Z -- <app> [--flags]
#   bin/keystone-tpu-pod.sh ssh     <name> --zone Z [--worker N]
#   bin/keystone-tpu-pod.sh delete  <name> --zone Z
#
# Requires the `gcloud` CLI, authenticated with a project that has TPU
# quota. See CLUSTER.md for the full walkthrough and env-var contract.
set -euo pipefail

die() { echo "keystone-tpu-pod: $*" >&2; exit 1; }

cmd="${1:-}"; shift || true
name="${1:-}"; shift || true
[[ -n "$cmd" && -n "$name" ]] || {
  grep '^#   bin/' "$0" | sed 's/^# *//'; exit 1; }

zone="" type="v5litepod-16" version="tpu-ubuntu2204-base" worker="all"
passthru=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --zone) zone="$2"; shift 2 ;;
    --type) type="$2"; shift 2 ;;
    --version) version="$2"; shift 2 ;;
    --worker) worker="$2"; shift 2 ;;
    --) shift; passthru=("$@"); break ;;
    *) die "unknown flag $1" ;;
  esac
done
[[ -n "$zone" ]] || die "--zone is required"

gtpu() { gcloud compute tpus tpu-vm "$@"; }

case "$cmd" in
  create)
    gtpu create "$name" --zone "$zone" \
      --accelerator-type "$type" --version "$version"
    ;;
  install)
    # Ship the repo to every worker and build the native host library.
    # (The reference shipped an assembly jar; we rsync the source tree.)
    here="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
    tmp="$(mktemp /tmp/keystone-tpu-XXXX.tar.gz)"
    # exclude locally built artifacts: a shipped .so would look up-to-date
    # to the remote make and the wrong-platform binary would be kept
    tar -C "$here" -czf "$tmp" --exclude .git --exclude __pycache__ \
      --exclude '*.so' --exclude '*.dylib' .
    gtpu scp "$tmp" "$name:/tmp/keystone-tpu.tar.gz" \
      --zone "$zone" --worker=all
    # only the native build is optional (pure-Python fallbacks exist);
    # mkdir/tar/pip failures must fail the install
    gtpu ssh "$name" --zone "$zone" --worker=all --command \
      'mkdir -p ~/keystone-tpu && tar -C ~/keystone-tpu -xzf /tmp/keystone-tpu.tar.gz \
       && { make -C ~/keystone-tpu/native || echo "native build failed; using pure-Python fallbacks" >&2; } \
       && pip install -q "jax[tpu]" flax optax orbax-checkpoint einops chex'
    rm -f "$tmp"
    ;;
  run)
    [[ ${#passthru[@]} -gt 0 ]] || die "run needs '-- <app> [--flags]'"
    # SPMD: the same command on every worker. jax.distributed resolves
    # the coordinator from the TPU metadata environment, so no explicit
    # coordinator address is needed on Cloud TPU. Local KEYSTONE_* env
    # vars (e.g. KEYSTONE_MESH_MODEL) are forwarded to every worker;
    # args are %q-quoted so spaces/metacharacters survive the remote shell.
    envfwd="KEYSTONE_DISTRIBUTED=1"
    while IFS='=' read -r k v; do
      [[ "$k" == KEYSTONE_* && "$k" != KEYSTONE_DISTRIBUTED ]] \
        && envfwd+=" $(printf '%q=%q' "$k" "$v")"
    done < <(env)
    # run-pipeline.sh applies the OMP cap and PYTHONPATH on the worker
    # (CLUSTER.md environment contract) and resolves python3 itself
    gtpu ssh "$name" --zone "$zone" --worker=all --command \
      "cd ~/keystone-tpu && $envfwd \
       bash bin/run-pipeline.sh $(printf '%q ' "${passthru[@]}")"
    ;;
  ssh)
    gtpu ssh "$name" --zone "$zone" --worker="$worker"
    ;;
  delete)
    gtpu delete "$name" --zone "$zone" --quiet
    ;;
  *) die "unknown command '$cmd' (create|install|run|ssh|delete)" ;;
esac
