"""CSV loading (reference ``loaders/CsvDataLoader.scala:10-30``) and the
LabeledData convenience wrapper (reference ``loaders/LabeledData.scala``)."""
from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..parallel.dataset import ArrayDataset


def load_csv(path: str, dtype=np.float32) -> np.ndarray:
    """Load one CSV file, a dir of CSVs, or a glob into a row matrix."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*")))
    else:
        files = sorted(glob.glob(path)) or [path]
    parts = [np.loadtxt(f, delimiter=",", dtype=dtype, ndmin=2) for f in files]
    return np.concatenate(parts, axis=0)


@dataclass
class LabeledData:
    """Bundles a data dataset and its labels (reference
    ``loaders/LabeledData.scala:8-15``)."""

    data: ArrayDataset
    labels: ArrayDataset


def csv_data_loader(path: str) -> ArrayDataset:
    return ArrayDataset.from_numpy(load_csv(path))


def csv_labeled_loader(
    path: str, label_col: int = 0, label_offset: int = 0
) -> LabeledData:
    """Rows of [label, features...]; ``label_offset`` is subtracted from
    the raw label (MNIST CSVs are 1-indexed, reference
    MnistRandomFFT.scala:35-38)."""
    raw = load_csv(path)
    labels = raw[:, label_col].astype(np.int32) - label_offset
    feats = np.delete(raw, label_col, axis=1)
    return LabeledData(
        data=ArrayDataset.from_numpy(feats),
        labels=ArrayDataset.from_numpy(labels),
    )
