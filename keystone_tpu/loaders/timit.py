"""TIMIT pre-featurized data loader (reference
``loaders/TimitFeaturesDataLoader.scala``).

Features are a CSV of numbers (440-dim); labels files hold ``row# label``
pairs with 1-based row numbers and 1-based labels (147 classes).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..parallel.dataset import ArrayDataset
from .csv_loader import LabeledData, load_csv

TIMIT_DIMENSION = 440
NUM_CLASSES = 147


def _parse_sparse_labels(path: str, n: int) -> np.ndarray:
    """'row label' lines, both 1-based (reference
    ``TimitFeaturesDataLoader.scala:22-33,36-44``: stored label minus 1)."""
    labels = np.zeros(n, dtype=np.int32)
    seen = np.zeros(n, dtype=bool)
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            row = int(parts[0]) - 1
            labels[row] = int(parts[1]) - 1
            seen[row] = True
    assert seen.all(), f"labels file {path} is missing rows"
    return labels


@dataclass
class TimitFeaturesData:
    train: LabeledData
    test: LabeledData


def timit_features_loader(
    train_data_path: str,
    train_labels_path: str,
    test_data_path: str,
    test_labels_path: str,
) -> TimitFeaturesData:
    def split(data_path, labels_path):
        feats = load_csv(data_path)
        labels = _parse_sparse_labels(labels_path, feats.shape[0])
        return LabeledData(
            data=ArrayDataset.from_numpy(feats),
            labels=ArrayDataset.from_numpy(labels),
        )

    return TimitFeaturesData(
        train=split(train_data_path, train_labels_path),
        test=split(test_data_path, test_labels_path),
    )
