"""Data loaders (reference ``loaders/``, SURVEY.md section 2.10)."""
from .amazon import amazon_reviews_loader
from .cifar_loader import cifar_loader, load_cifar_numpy
from .csv_loader import LabeledData, csv_data_loader, csv_labeled_loader, load_csv
from .image_loader_utils import (
    LabeledImage,
    MultiLabeledImage,
    decode_image,
    iter_tar_images,
    list_archive_paths,
    load_tar_files,
)
from .imagenet import imagenet_loader, parse_imagenet_labels
from .newsgroups import CLASSES as NEWSGROUPS_CLASSES, newsgroups_loader
from .timit import TimitFeaturesData, timit_features_loader
from .voc import VOCDataPath, VOCLabelPath, parse_voc_labels, voc_loader

__all__ = [
    "amazon_reviews_loader",
    "cifar_loader",
    "load_cifar_numpy",
    "LabeledData",
    "csv_data_loader",
    "csv_labeled_loader",
    "load_csv",
    "LabeledImage",
    "MultiLabeledImage",
    "decode_image",
    "iter_tar_images",
    "list_archive_paths",
    "load_tar_files",
    "imagenet_loader",
    "parse_imagenet_labels",
    "NEWSGROUPS_CLASSES",
    "newsgroups_loader",
    "TimitFeaturesData",
    "timit_features_loader",
    "VOCDataPath",
    "VOCLabelPath",
    "parse_voc_labels",
    "voc_loader",
]
