"""VOC 2007 loader (reference ``loaders/VOCLoader.scala``).

Images come from a tar; the labels CSV has a header row and columns where
column 1 is the 1-based class id and column 4 the quoted image filename —
one row per (image, label) pair, so images accumulate multiple labels.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..parallel.dataset import HostDataset
from .image_loader_utils import (
    MultiLabeledImage,
    list_archive_paths,
    load_tar_files,
)

NUM_CLASSES = 20  # constant of the VOC 2007 dataset


@dataclass
class VOCDataPath:
    images_dir_name: str
    name_prefix: str = "VOCdevkit"
    num_parts: Optional[int] = None


@dataclass
class VOCLabelPath:
    labels_file_name: str


def parse_voc_labels(labels_path: str) -> Dict[str, List[int]]:
    """filename -> 0-based label list (reference ``VOCLoader.scala:33-48``)."""
    labels_map: Dict[str, List[int]] = {}
    with open(labels_path) as f:
        lines = f.read().splitlines()
    for line in lines[1:]:  # drop header
        if not line.strip():
            continue
        parts = line.split(",")
        fname = parts[4].replace('"', "")
        label = int(parts[1]) - 1
        labels_map.setdefault(fname, []).append(label)
    return labels_map


def voc_loader(data_path: VOCDataPath, labels_path: VOCLabelPath) -> HostDataset:
    """RDD[MultiLabeledImage] analogue (reference ``VOCLoader.scala:29-52``).
    Label lookup keys on the entry's basename, matching the CSV filenames."""
    labels_map = parse_voc_labels(labels_path.labels_file_name)

    def lookup(entry_name: str) -> List[int]:
        base = entry_name.split("/")[-1]
        return labels_map.get(base, [])

    return load_tar_files(
        list_archive_paths(data_path.images_dir_name),
        lookup,
        lambda img, labels, name: MultiLabeledImage(img, labels, name),
        name_prefix=data_path.name_prefix or None,
    )
