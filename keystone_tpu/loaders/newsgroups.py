"""20 Newsgroups loader (reference ``loaders/NewsgroupsDataLoader.scala``).

Expects ``data_dir/class_label/docs_as_separate_plaintext_files``; class
directory names define integer labels by position in :data:`CLASSES`.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from ..parallel.dataset import ArrayDataset, HostDataset
from .csv_loader import LabeledData

CLASSES = [
    "comp.graphics",
    "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware",
    "comp.sys.mac.hardware",
    "comp.windows.x",
    "rec.autos",
    "rec.motorcycles",
    "rec.sport.baseball",
    "rec.sport.hockey",
    "sci.crypt",
    "sci.electronics",
    "sci.med",
    "sci.space",
    "misc.forsale",
    "talk.politics.misc",
    "talk.politics.guns",
    "talk.politics.mideast",
    "talk.religion.misc",
    "alt.atheism",
    "soc.religion.christian",
]


def newsgroups_loader(
    data_dir: str, classes: Optional[Sequence[str]] = None
) -> LabeledData:
    """Load a train or test split directory; missing class dirs are
    skipped (the reference unions per-class wholeTextFiles RDDs)."""
    classes = list(classes) if classes is not None else CLASSES
    texts: List[str] = []
    labels: List[int] = []
    for index, name in enumerate(classes):
        class_dir = os.path.join(data_dir, name)
        if not os.path.isdir(class_dir):
            continue
        for fname in sorted(os.listdir(class_dir)):
            path = os.path.join(class_dir, fname)
            if os.path.isfile(path):
                with open(path, "r", errors="replace") as f:
                    texts.append(f.read())
                labels.append(index)
    return LabeledData(
        data=HostDataset(texts),
        labels=ArrayDataset.from_numpy(np.asarray(labels, np.int32)),
    )
