"""ImageNet loader (reference ``loaders/ImageNetLoader.scala``).

``data_path`` holds tar files whose entries live under a directory per
class (``class_name/img.jpeg``); ``labels_path`` maps class names to
numeric labels, one ``class_name label`` pair per line.
"""
from __future__ import annotations

from typing import Dict

from ..parallel.dataset import HostDataset
from .image_loader_utils import (
    LabeledImage,
    list_archive_paths,
    load_tar_files,
)

NUM_CLASSES = 1000


def parse_imagenet_labels(labels_path: str) -> Dict[str, int]:
    labels: Dict[str, int] = {}
    with open(labels_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                labels[parts[0]] = int(parts[1])
    return labels


def imagenet_loader(data_path: str, labels_path: str) -> HostDataset:
    """RDD[LabeledImage] analogue (reference ``ImageNetLoader.scala:27-39``):
    the entry's top-level directory is its class name."""
    labels_map = parse_imagenet_labels(labels_path)

    def lookup(entry_name: str) -> int:
        return labels_map[entry_name.split("/")[0]]

    return load_tar_files(
        list_archive_paths(data_path),
        lookup,
        lambda img, label, name: LabeledImage(img, label, name),
    )
