"""Tar-archive image loading (reference ``loaders/ImageLoaderUtils.scala``).

Streams tar archives of images, decodes with PIL (the reference uses
ImageIO), and yields labeled image items. Images keep the reference's
convention: float32 (H, W, C) arrays with values in [0, 255].

Ragged image sizes stay host-side (HostDataset); pipelines resize/crop
or extract fixed-size features before moving to device arrays.

Resilience (:mod:`keystone_tpu.resilience`): tar-member reads and image
decodes retry transient failures under a :class:`RetryPolicy`
(``ingest.read`` / ``ingest.decode`` fault-injection sites exercise the
real paths), and undecodable records are routed to a
:class:`Quarantine` — skipped but accounted, with the fit failing
loudly once the bad-record budget is exceeded — instead of being
silently dropped.
"""
from __future__ import annotations

import gzip
import io
import logging
import os
import tarfile
import zlib
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from ..parallel.dataset import HostDataset
from ..resilience.faults import inject
from ..resilience.quarantine import Quarantine
from ..resilience.retry import RetryPolicy, default_retry_policy


@dataclass
class LabeledImage:
    """Image + single int label (reference ``utils/images/Image.scala:371-380``)."""

    image: np.ndarray
    label: int
    filename: Optional[str] = None


@dataclass
class MultiLabeledImage:
    """Image + multiple labels (reference ``Image.scala:383-394``)."""

    image: np.ndarray
    labels: List[int] = field(default_factory=list)
    filename: Optional[str] = None


def decode_image(data: bytes,
                 dtype: np.dtype = np.float32) -> Optional[np.ndarray]:
    """JPEG/PNG bytes -> ``dtype`` (H, W, C) in [0, 255]; None if
    undecodable (the reference's loadImage returns Option). The decoder
    works in uint8 underneath, so ``dtype=np.uint8`` is lossless and
    skips the widening copy — the streamed path decodes uint8 and lets
    the device cast (4x fewer host->device wire bytes)."""
    try:
        from PIL import Image as PILImage

        img = PILImage.open(io.BytesIO(data))
        img = img.convert("RGB")
        return np.asarray(img, dtype=dtype)
    except Exception:
        return None


def list_archive_paths(data_path: str, process_shard: bool = True) -> List[str]:
    """All non-directory files under a path (reference
    ``ImageLoaderUtils.getFilePathsRDD`` filters only directories).
    Non-archive files (labels.txt, READMEs) routinely sit alongside the
    archives; :func:`load_tar_files` skips them at open time.

    On a multi-host (SPMD) run each process keeps its
    ``process_index``-strided share of the archives — the analogue of
    HDFS splits landing on different executors (CLUSTER.md "Data").
    ``process_shard=False`` returns the full global listing.
    """
    if os.path.isfile(data_path):
        paths = [data_path]
    else:
        paths = sorted(
            os.path.join(data_path, f)
            for f in os.listdir(data_path)
            if os.path.isfile(os.path.join(data_path, f))
        )
    if process_shard:
        import jax

        pc = jax.process_count()
        if pc > 1:
            # stride over actual archives only — READMEs/labels.txt in
            # the sorted listing must not skew which host gets which
            # share (they'd be skipped at open time anyway)
            archives = [p for p in paths if p.endswith(
                (".tar", ".tar.gz", ".tgz", ".tar.bz2"))]
            mine = archives[jax.process_index()::pc]
            if not mine:
                # an empty share would surface as a collective hang or a
                # shape mismatch far from here — fail at the loader
                raise ValueError(
                    f"host {jax.process_index()}/{pc} has no archives: "
                    f"only {len(archives)} archive(s) under "
                    f"{data_path!r}. Repack the data into >= "
                    "process_count archives, or pass process_shard="
                    "False to load everything on each host."
                )
            paths = mine
    return paths


def _iter_tar_entries(
    tar_path: str, name_prefix: Optional[str] = None,
    retry: Optional[RetryPolicy] = None,
) -> Iterator[tuple]:
    """Yield (entry_name, raw_bytes) for each matching file in a tar —
    the single source of mode selection and entry filtering shared by
    :func:`iter_tar_images` and :func:`load_tar_files`. Per-member
    reads retry transient I/O errors when a ``retry`` policy is given
    (the ``ingest.read`` fault site sits inside the attempt)."""
    mode = "r:gz" if tar_path.endswith(".gz") else "r"
    with tarfile.open(tar_path, mode) as tf:
        for entry in tf:
            if not entry.isfile():
                continue
            if name_prefix and not entry.name.startswith(name_prefix):
                continue

            def read(entry=entry):
                inject("ingest.read",
                       context=f"{tar_path}::{entry.name}")
                fobj = tf.extractfile(entry)
                return None if fobj is None else fobj.read()

            raw = (read() if retry is None
                   else retry.call(read, site="ingest.read"))
            if raw is None:
                continue
            yield entry.name, raw


def _decode_with_retry(raw: bytes, context: str,
                       retry: Optional[RetryPolicy],
                       decode_dtype: np.dtype = np.float32):
    """One record's decode behind the retry policy; the
    ``ingest.decode`` fault site lives inside the attempt so injected
    transient faults exercise the real retry path. Returns None for
    genuinely undecodable bytes (the quarantine case)."""

    def attempt():
        inject("ingest.decode", context=context)
        return decode_image(raw, dtype=decode_dtype)

    if retry is None:
        return attempt()
    return retry.call(attempt, site="ingest.decode")


def iter_tar_images(
    tar_path: str, name_prefix: Optional[str] = None,
    quarantine: Optional[Quarantine] = None,
    retry_policy: Optional[RetryPolicy] = None,
) -> Iterator[tuple]:
    """Yield (entry_name, decoded_image) for each image file in a tar
    (reference ``ImageLoaderUtils.loadFile``) — the serial (unpooled)
    decode path. With a ``quarantine``, undecodable members are
    skipped-but-accounted instead of silently dropped."""
    for name, raw in _iter_tar_entries(tar_path, name_prefix,
                                       retry=retry_policy):
        img = _decode_with_retry(raw, f"{tar_path}::{name}", retry_policy)
        if img is not None:
            if quarantine is not None:
                quarantine.record_ok()
            yield name, img
        elif quarantine is not None:
            quarantine.quarantine(f"{tar_path}::{name}",
                                  "undecodable image bytes")


def _pooled_decoded(
    archive_paths: Sequence[str],
    name_prefix: Optional[str] = None,
    on_archive_end: Optional[Callable[[str, Optional[Exception], int], None]] = None,
    quarantine: Optional[Quarantine] = None,
    retry_policy: Optional[RetryPolicy] = None,
    decode_dtype: np.dtype = np.float32,
) -> Iterator[tuple]:
    """Yield ``(entry_name, decoded_image)`` from every archive, decode
    on a thread pool behind a bounded in-flight window — the ONE home of
    the pool/window/per-archive-recovery machinery shared by
    :func:`iter_decoded_chunks` and :func:`load_tar_files`.

    Order is deterministic (archive order, then entry order). With a
    ``quarantine``, undecodable entries are skipped-but-accounted (and
    the budget enforced); without one they are dropped as before.
    Transient read/decode failures retry under ``retry_policy``. An
    archive that raises mid-stream (non-archive file, truncation) stops
    there but keeps what was read.
    ``on_archive_end(path, error_or_None, n_images_yielded)`` fires per
    archive so callers implement their own skip/warn/raise policy.
    """
    import collections
    from concurrent.futures import ThreadPoolExecutor

    workers = _loader_threads()
    window = 4 * workers
    with ThreadPoolExecutor(workers) as pool:
        pending: collections.deque = collections.deque()

        def drain(n):
            out = []
            while len(pending) > n:
                name, ctx, fut = pending.popleft()
                img = fut.result()  # retry exhaustion re-raises here
                if img is not None:
                    if quarantine is not None:
                        quarantine.record_ok()
                    out.append((name, img))
                elif quarantine is not None:
                    # skipped but accounted — never silently missing
                    # from the counts; raises once the budget is blown
                    quarantine.quarantine(ctx, "undecodable image bytes")
            return out

        for path in archive_paths:
            n_from_archive = 0
            err: Optional[Exception] = None
            try:
                for name, raw in _iter_tar_entries(path, name_prefix,
                                                   retry=retry_policy):
                    ctx = f"{path}::{name}"
                    pending.append((name, ctx, pool.submit(
                        _decode_with_retry, raw, ctx, retry_policy,
                        decode_dtype)))
                    for item in drain(window):
                        n_from_archive += 1
                        yield item
            except (tarfile.ReadError, gzip.BadGzipFile, EOFError,
                    zlib.error) as e:
                err = e
            # archive boundary: drain fully so the per-archive count is
            # exact (a negligible pipeline bubble once per archive)
            for item in drain(0):
                n_from_archive += 1
                yield item
            if on_archive_end is not None:
                on_archive_end(path, err, n_from_archive)


def iter_decoded_chunks(
    archive_paths: Sequence[str],
    chunk_size: int,
    name_prefix: Optional[str] = None,
    quarantine: Optional[Quarantine] = None,
    retry_policy: Optional[RetryPolicy] = None,
    decode_dtype: np.dtype = np.float32,
) -> Iterator[List[tuple]]:
    """Stream archives as chunks of ``chunk_size`` decoded images.

    This is the loader half of the loader/device pipeline: a consumer
    that ``device_put``s + dispatches accelerator work per chunk gets
    decode-compute overlap for free, because JAX dispatch is async and
    the pool keeps decoding the next window while the device runs the
    current chunk (the reference got the same overlap from Spark
    executor threads feeding JNI featurizers,
    ``ImageLoaderUtils.scala:23-94``). Unreadable/truncated archives are
    skipped with a warning, keeping entries read before the error.
    """
    log = logging.getLogger(__name__)

    def on_end(path, err, n):
        if err is not None:
            log.warning(
                "Skipping unreadable/truncated archive %s (%s); kept "
                "%d entries read before the error", path, err, n)

    out: list = []
    for item in _pooled_decoded(archive_paths, name_prefix, on_end,
                                quarantine=quarantine,
                                retry_policy=retry_policy,
                                decode_dtype=decode_dtype):
        out.append(item)
        while len(out) >= chunk_size:
            yield out[:chunk_size]
            del out[:chunk_size]
    while out:
        yield out[:chunk_size]
        del out[:chunk_size]


def _loader_threads() -> int:
    """Decode worker count: the reference got multi-core decode for free
    from Spark executors; here a thread pool does it (PIL releases the
    GIL while decoding). ``KEYSTONE_LOADER_THREADS=1`` forces serial."""
    env = os.environ.get("KEYSTONE_LOADER_THREADS")
    if env:
        return max(1, int(env))
    return min(32, os.cpu_count() or 4)


def stream_tar_images(
    archive_paths: Sequence[str],
    chunk_size: int,
    prepare: Optional[Callable[[List[tuple]], np.ndarray]] = None,
    name_prefix: Optional[str] = None,
    n: Optional[int] = None,
    quarantine: Optional[Quarantine] = None,
    retry_policy: Optional[RetryPolicy] = None,
    decode_dtype: Optional[np.dtype] = None,
    **stream_kw,
):
    """tar archives -> threaded decode pool -> double-buffered device
    stream: the loader half of ``iter_decoded_chunks`` composed with
    ``parallel.streaming.StreamingDataset``, so chunk *i+1*'s decode AND
    upload run behind the prefetch buffer while chunk *i* computes.

    Dtype on the wire: with no ``prepare`` hook, images are decoded
    UINT8 (the decoder's native width — lossless for [0, 255] pixels)
    and shipped uint8 across the host->device link, 1/4 the wire bytes
    of the old f32 staging; consumers still see float32 [0, 255] chunks
    because the stream's ``compute_dtype`` casts on device. A custom
    ``prepare`` keeps the documented float32 decode (its output dtype
    is whatever it returns — return uint8 and the wire stays narrow);
    ``decode_dtype`` overrides the decode width either way, and
    ``wire_dtype=``/``compute_dtype=`` pass through to the stream.

    ``prepare`` maps one decoded chunk (a list of ``(entry_name,
    image)`` pairs) to a stacked fixed-shape host array — the hook for
    resize/crop/grayscale of ragged archive images; the default stacks
    as-is (uniform-size archives). ``n`` is the total image count when
    known (streams from unindexed tars leave it None; a completed pass
    pins it).

    Resilience defaults: reads/decodes retry transients under
    ``retry_policy`` (shared default policy when None) and corrupt
    members land in ``quarantine`` (a fresh default-budget
    :class:`Quarantine` when None) — attached to the returned stream as
    ``.quarantine`` so callers can pass it to ``fit_streaming`` or
    inspect the manifest.
    """
    from ..parallel.streaming import StreamingDataset

    if prepare is None:
        if decode_dtype is None:
            # uint8 on the wire, f32 on device: the default pipeline's
            # consumers keep seeing float32 [0, 255] images while the
            # transfer moves 1/4 the bytes
            decode_dtype = np.uint8
            stream_kw.setdefault("compute_dtype", np.float32)

        def prepare(batch):
            return np.stack([img for _, img in batch])
    elif decode_dtype is None:
        decode_dtype = np.float32  # documented prepare() input contract

    tag = f"tar:{archive_paths[0]}" if archive_paths else "tar"
    if quarantine is None:
        quarantine = Quarantine(label=tag)
    if retry_policy is None:
        retry_policy = default_retry_policy()

    def factory():
        for batch in iter_decoded_chunks(
                archive_paths, chunk_size, name_prefix,
                quarantine=quarantine, retry_policy=retry_policy,
                decode_dtype=decode_dtype):
            yield prepare(batch)

    return StreamingDataset.from_chunks(
        factory, chunk_size, n=n, tag=tag, retry_policy=retry_policy,
        quarantine=quarantine, **stream_kw)


def stream_tar_shards(data_path: str, chunk_size: int,
                      **stream_kw):
    """Per-host SHARD-LOCAL tar streaming: this process's
    ``process_index``-strided share of the archives under ``data_path``
    (:func:`list_archive_paths`) fed through :func:`stream_tar_images`
    on the host-local mesh — the ingest half of the elastic multi-host
    streamed fit (``parallel.distributed``; each host decodes only its
    own shards, carries tree-reduce at finalize).

    The returned stream is tagged ``tarshard:h<process>/<world>`` and
    marked ``process_sharded`` (the static analyzer reports the flag,
    and ``fit_streaming``'s distributed mode is the only fit that
    understands a shard-local ``n``: the stream's row count is THIS
    host's share, not the dataset's). Keyword arguments pass through to
    :func:`stream_tar_images` (``prepare=``, ``wire_dtype=``,
    ``quarantine=``, ...); the mesh defaults to
    :func:`~keystone_tpu.parallel.mesh.local_mesh` so staging never
    targets another host's devices. Single-process this degrades to a
    plain full-listing tar stream.

    An empty share raises at listing time
    (:func:`list_archive_paths`): repack the data into at least
    ``process_count`` archives — silent empty hosts would surface as a
    collective hang far from the cause.
    """
    from ..parallel.distributed import process_count, process_index
    from ..parallel.mesh import local_mesh

    paths = list_archive_paths(data_path, process_shard=True)
    pid, nproc = process_index(), process_count()
    if "mesh" not in stream_kw and nproc > 1:
        stream_kw["mesh"] = local_mesh()
    stream = stream_tar_images(paths, chunk_size, **stream_kw)
    stream.tag = f"tarshard:h{pid}/{nproc}"
    #: consumed by analysis.spec.dataset_spec: the stream's n (when it
    #: pins) is a PER-HOST share, and the non-streamable-fit family
    #: reports the sharded provenance in its diagnostics
    stream.process_sharded = True
    stream.shard_archives = list(paths)
    return stream


def load_tar_files(
    archive_paths: Sequence[str],
    labels_map: Callable[[str], object],
    image_builder: Callable[[np.ndarray, object, str], object],
    name_prefix: Optional[str] = None,
    quarantine: Optional[Quarantine] = None,
    retry_policy: Optional[RetryPolicy] = None,
) -> HostDataset:
    """Load every image from every archive, applying the label mapping
    (reference ``ImageLoaderUtils.loadFiles``).

    Decode machinery (thread pool, bounded window, deterministic order,
    per-archive recovery) is shared with :func:`iter_decoded_chunks` via
    :func:`_pooled_decoded`; this wrapper adds the label mapping plus
    the skip-vs-truncated warning policy and the nothing-opened error."""
    log = logging.getLogger(__name__)
    items: list = []
    opened_any = False

    def on_end(path, err, n):
        nonlocal opened_any
        if err is None:
            opened_any = True  # readable archive, possibly zero images
        elif n == 0:
            # Failed before yielding anything: not a tar (labels.txt,
            # README, checksums) — skip, matching the reference where
            # non-archives simply yield no image records.
            log.warning("Skipping non-archive file %s", path)
        else:
            # Truncated/corrupt mid-stream: keep what was read, but say
            # so — silent partial data is worse than a warning.
            log.warning(
                "Archive %s truncated/corrupt (%s); kept %d items "
                "from it", path, err, n)
            opened_any = True

    for name, img in _pooled_decoded(archive_paths, name_prefix, on_end,
                                     quarantine=quarantine,
                                     retry_policy=retry_policy):
        # only a decoded image proves the path held real data;
        # None-decodes must not suppress the final ReadError
        opened_any = True
        items.append(image_builder(img, labels_map(name), name))
    if archive_paths and not opened_any:
        raise tarfile.ReadError(
            f"None of {len(archive_paths)} file(s) under the data path could be "
            f"opened as tar archives (first: {archive_paths[0]})"
        )
    return HostDataset(items)
