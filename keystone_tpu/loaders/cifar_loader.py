"""CIFAR-10 binary loader (reference ``loaders/CifarLoader.scala:14-51``).

Record layout: 1 label byte + 3072 pixel bytes (1024 R, 1024 G, 1024 B,
each a row-major 32x32 plane). Pixels stay in [0, 255] floats exactly like
the reference's byte-backed image layout.
"""
from __future__ import annotations

import glob
import os

import numpy as np

from ..parallel.dataset import ArrayDataset
from .csv_loader import LabeledData

NROW, NCOL, NCHAN = 32, 32, 3
RECORD = 1 + NROW * NCOL * NCHAN


def load_cifar_numpy(path: str, packed: bool = False):
    """Returns (images (n,32,32,3), labels (n,) int32). Images are
    float32 in [0,255] by default; ``packed=True`` keeps them uint8 —
    the analogue of the reference's byte-packed CIFAR layout
    (``RowColumnMajorByteArrayVectorizedImage``, Image.scala:333-365),
    4x smaller in host and HBM memory. jnp type promotion converts to
    f32 on device inside the first float op, so downstream nodes see
    identical values."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.bin")))
    else:
        files = sorted(glob.glob(path)) or [path]
    from ..native import cifar_decode, cifar_decode_u8

    decode = cifar_decode_u8 if packed else cifar_decode
    imgs, labels = [], []
    for f in files:
        with open(f, "rb") as fh:
            raw = fh.read()
        assert len(raw) % RECORD == 0, f"corrupt CIFAR file {f}"
        i, l = decode(raw, NROW, NCOL, NCHAN)  # native when built
        imgs.append(i)
        labels.append(l)
    return np.concatenate(imgs), np.concatenate(labels)


def cifar_loader(path: str, packed: bool = False) -> LabeledData:
    images, labels = load_cifar_numpy(path, packed=packed)
    pk = ":u8" if packed else ""
    return LabeledData(
        data=ArrayDataset.from_numpy(images, tag=f"cifar:{path}{pk}:data"),
        labels=ArrayDataset.from_numpy(labels, tag=f"cifar:{path}:labels"),
    )
