"""Amazon product reviews loader (reference
``loaders/AmazonReviewsDataLoader.scala``).

Reviews are JSON objects with at least ``reviewText`` and ``overall``
fields, one per line (the common release format; the reference reads the
same via Spark SQL ``jsonFile``). ``overall >= threshold`` is the
positive class.
"""
from __future__ import annotations

import glob
import json
import os
from typing import List

import numpy as np

from ..parallel.dataset import ArrayDataset, HostDataset
from .csv_loader import LabeledData


def amazon_reviews_loader(data_path: str, threshold: float = 3.5) -> LabeledData:
    if os.path.isdir(data_path):
        files = sorted(glob.glob(os.path.join(data_path, "*.json")))
    else:
        files = sorted(glob.glob(data_path)) or [data_path]
    texts: List[str] = []
    labels: List[int] = []
    for path in files:
        with open(path, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                texts.append(obj["reviewText"])
                labels.append(1 if float(obj["overall"]) >= threshold else 0)
    return LabeledData(
        data=HostDataset(texts),
        labels=ArrayDataset.from_numpy(np.asarray(labels, np.int32)),
    )
