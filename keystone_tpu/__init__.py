"""keystone_tpu: a TPU-native large-scale ML pipeline framework.

A ground-up re-design of the capabilities of KeystoneML (AMPLab's
Spark/Scala pipeline framework, surveyed in SURVEY.md) for TPUs: type-safe
composable Transformer/Estimator pipelines over an optimizing DAG, executed
on `jax.sharding.Mesh` device meshes with XLA collectives instead of a
Spark cluster, with distributed linear algebra (normal equations, block
coordinate descent, TSQR) as sharded JAX programs and image/NLP feature
kernels as TPU-friendly ops.
"""
from .observability import (
    MetricsRegistry,
    PipelineTrace,
    current_trace,
    xprof_trace,
)
from .parallel.dataset import ArrayDataset, Dataset, HostDataset, as_dataset
from .parallel.mesh import get_mesh, make_mesh, mesh_scope, set_mesh
from .parallel.streaming import StreamingDataset, fit_streaming, is_streamable
from .resilience import (
    FaultPlan,
    IngestTimeoutError,
    Quarantine,
    RetryPolicy,
)
from .workflow import (
    Cacher,
    Estimator,
    FittedPipeline,
    Identity,
    LabelEstimator,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineEnv,
    Transformer,
    transformer,
)

__version__ = "0.1.0"

__all__ = [
    "MetricsRegistry",
    "PipelineTrace",
    "current_trace",
    "xprof_trace",
    "ArrayDataset",
    "Dataset",
    "HostDataset",
    "StreamingDataset",
    "as_dataset",
    "fit_streaming",
    "is_streamable",
    "FaultPlan",
    "IngestTimeoutError",
    "Quarantine",
    "RetryPolicy",
    "get_mesh",
    "make_mesh",
    "mesh_scope",
    "set_mesh",
    "Cacher",
    "Estimator",
    "FittedPipeline",
    "Identity",
    "LabelEstimator",
    "Pipeline",
    "PipelineDataset",
    "PipelineDatum",
    "PipelineEnv",
    "Transformer",
    "transformer",
    "__version__",
]
