"""Process-wide metrics: counters, gauges, and timing histograms.

The cheap, always-on half of the observability layer (the detailed
per-run structure lives in :mod:`.trace`). A metric update is a dict
lookup plus a float add — safe to leave in hot paths like the DAG
executor. Like :class:`~keystone_tpu.workflow.env.PipelineEnv`, the
registry is a process singleton and relies on the single-threaded
driver model for safety.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Optional


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    """Last-written value."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Streaming aggregates (count/total/min/max) plus a bounded tail of
    raw observations for percentile-ish inspection without unbounded
    memory growth in long-lived processes."""

    __slots__ = ("name", "count", "total", "min", "max", "_tail")

    TAIL = 256

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._tail: List[float] = []

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        self._tail.append(value)
        if len(self._tail) > self.TAIL:
            del self._tail[: len(self._tail) - self.TAIL]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Approximate percentile over the retained tail (the most
        recent ``TAIL`` observations), 0 <= q <= 100."""
        if not self._tail:
            return 0.0
        ordered = sorted(self._tail)
        idx = min(len(ordered) - 1,
                  max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
        return ordered[idx]

    def snapshot(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0, "total": 0.0, "mean": 0.0,
                    "min": 0.0, "max": 0.0}
        return {"count": self.count, "total": self.total, "mean": self.mean,
                "min": self.min, "max": self.max,
                "p50": self.percentile(50), "p99": self.percentile(99)}


class MetricsRegistry:
    """Process-wide named metrics (``MetricsRegistry.get_or_create()``)."""

    _instance: Optional["MetricsRegistry"] = None

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    @classmethod
    def get_or_create(cls) -> "MetricsRegistry":
        if cls._instance is None:
            cls._instance = MetricsRegistry()
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Drop the global registry (tests)."""
        cls._instance = None

    # -- access -----------------------------------------------------------
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            h = self._histograms[name] = Histogram(name)
        return h

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        """Time the enclosed block into histogram ``name`` (seconds).
        Callers timing async device work must block inside the block."""
        t0 = time.perf_counter()
        yield
        self.histogram(name).observe(time.perf_counter() - t0)

    # -- export -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        return {
            "counters": {k: c.value for k, c in sorted(self._counters.items())},
            "gauges": {k: g.value for k, g in sorted(self._gauges.items())},
            "histograms": {
                k: h.snapshot() for k, h in sorted(self._histograms.items())
            },
        }


class StepTimer:
    """Wall-clock step timing (formerly ``utils.profiling.StepTimer``;
    kept API-compatible). ``timed(name, fn, ...)`` blocks on the device
    result before reading the clock — the honest way to time jitted
    programs. ``step(name)`` times the enclosed block as-is (callers
    must block_until_ready inside if the block dispatches async device
    work)."""

    def __init__(self) -> None:
        self.times: Dict[str, list] = {}

    @contextlib.contextmanager
    def step(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        yield
        self.times.setdefault(name, []).append(time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kwargs):
        import jax

        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        out = jax.block_until_ready(out)
        self.times.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    def summary(self) -> str:
        lines = []
        for name, ts in self.times.items():
            lines.append(
                f"{name}: n={len(ts)} mean={sum(ts)/len(ts)*1e3:.2f}ms "
                f"min={min(ts)*1e3:.2f}ms max={max(ts)*1e3:.2f}ms")
        return "\n".join(lines)
