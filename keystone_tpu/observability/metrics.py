"""Process-wide metrics: counters, gauges, and timing histograms.

The cheap, always-on half of the observability layer (the detailed
per-run structure lives in :mod:`.trace`). A metric update is a dict
lookup plus a locked float add — safe to leave in hot paths like the
DAG executor. Unlike the early single-threaded-driver days, metrics are
now fed from worker threads too (the streaming prefetcher, the tar
decode pool, retry helpers — PR 3/4), so every read-modify-write here
takes a lock; the discipline is declared with
:func:`~keystone_tpu.utils.guarded.guarded_by` and checked statically
by ``analysis.concurrency``.

These are deliberately *plain* ``threading.Lock``\\ s, not TracedLocks:
a TracedLock's contended path reports INTO this registry, so tracing
the registry's own locks would re-enter them (see
``utils/guarded.py``). The uncontended cost is ~100 ns per update —
metrics fire per chunk/record/node, never per element.
"""
from __future__ import annotations

import contextlib
import re
import threading
import time
import warnings
from typing import Dict, Iterator, List, Optional

from ..utils.guarded import guarded_by


@guarded_by("_lock", "value")
class Counter:
    """Monotonically increasing count (thread-safe: the ``+=`` is a
    read-modify-write and counters are incremented from ingest worker
    threads — the resilience event funnel, the prefetcher)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    """Last-written value (a plain overwrite — atomic enough without a
    lock; last writer wins is the semantics)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


@guarded_by("_lock", "count", "total", "min", "max", "_tail")
class Histogram:
    """Streaming aggregates (count/total/min/max) plus a bounded tail of
    raw observations for percentile-ish inspection without unbounded
    memory growth in long-lived processes. ``observe`` may be called
    from multiple threads (ingest stalls, lock waits, retry timings);
    the aggregates and the tail trim are guarded so concurrent
    observations can neither lose counts nor corrupt the tail."""

    __slots__ = ("name", "count", "total", "min", "max", "_tail", "_lock")

    TAIL = 256

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._tail: List[float] = []
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if value < self.min:
                self.min = value
            if value > self.max:
                self.max = value
            self._tail.append(value)
            if len(self._tail) > self.TAIL:
                del self._tail[: len(self._tail) - self.TAIL]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Approximate percentile over the retained tail (the most
        recent ``TAIL`` observations), 0 <= q <= 100."""
        with self._lock:
            tail = list(self._tail)
        if not tail:
            return 0.0
        ordered = sorted(tail)
        idx = min(len(ordered) - 1,
                  max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
        return ordered[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self.count:
                return {"count": 0, "total": 0.0, "mean": 0.0,
                        "min": 0.0, "max": 0.0}
            count, total = self.count, self.total
            lo, hi = self.min, self.max
        return {"count": count, "total": total, "mean": total / count,
                "min": lo, "max": hi,
                "p50": self.percentile(50), "p99": self.percentile(99)}


#: guards the singleton create (``get_or_create``/``reset`` may race a
#: worker thread's first metric against the main thread's — a lost
#: registry loses every count the loser wrote)
_REGISTRY_LOCK = threading.Lock()


@guarded_by("_lock", "_counters", "_gauges", "_histograms")
class MetricsRegistry:
    """Process-wide named metrics (``MetricsRegistry.get_or_create()``).
    The lazy per-name creates are check-then-act sequences, hit
    concurrently by ingest worker threads — both the singleton and the
    name maps are locked."""

    _instance: Optional["MetricsRegistry"] = None

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    @classmethod
    def get_or_create(cls) -> "MetricsRegistry":
        inst = cls._instance
        if inst is None:
            with _REGISTRY_LOCK:
                inst = cls._instance
                if inst is None:
                    inst = cls._instance = MetricsRegistry()
        return inst

    @classmethod
    def reset(cls) -> None:
        """Drop the global registry (tests)."""
        with _REGISTRY_LOCK:
            cls._instance = None

    # -- access -----------------------------------------------------------
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.get(name)
                if c is None:
                    c = self._counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.get(name)
                if g is None:
                    g = self._gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.get(name)
                if h is None:
                    h = self._histograms[name] = Histogram(name)
        return h

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        """Time the enclosed block into histogram ``name`` (seconds).
        Callers timing async device work must block inside the block."""
        t0 = time.perf_counter()
        yield
        self.histogram(name).observe(time.perf_counter() - t0)

    # -- export -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        # copy the maps under the lock before iterating: a worker
        # thread lazily creating a metric (a contended TracedLock's
        # first lock.wait_s.<name> histogram) mid-snapshot would
        # otherwise resize the dict under the iteration
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {
                k: h.snapshot() for k, h in sorted(histograms.items())
            },
        }

    def to_prometheus(self) -> str:
        """The registry as Prometheus text exposition (format 0.0.4):
        counters and gauges one sample each, histograms as summaries
        (``_count``/``_sum`` plus p50/p99 quantile samples from the
        retained tail). Names are namespaced ``keystone_`` and
        sanitized to the Prometheus charset (dots become underscores
        — the canonical dotted names live in ``observability/names.py``
        and the mapping is mechanical, so dashboards can be written
        from the catalogue). This is what :func:`~keystone_tpu.\
        observability.sampler.serve_metrics` serves on ``/metrics``."""
        snap = self.snapshot()
        lines: List[str] = []
        for name, value in snap["counters"].items():
            n = _prometheus_name(name) + "_total"
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {_prometheus_value(value)}")
        for name, value in snap["gauges"].items():
            n = _prometheus_name(name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_prometheus_value(value)}")
        for name, h in snap["histograms"].items():
            n = _prometheus_name(name)
            lines.append(f"# TYPE {n} summary")
            for q, key in (("0.5", "p50"), ("0.99", "p99")):
                lines.append(
                    f'{n}{{quantile="{q}"}} '
                    f"{_prometheus_value(h.get(key, 0.0))}")
            lines.append(f"{n}_sum {_prometheus_value(h['total'])}")
            lines.append(f"{n}_count {int(h['count'])}")
        return "\n".join(lines) + "\n"


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prometheus_name(name: str) -> str:
    return "keystone_" + _PROM_BAD.sub("_", name)


def _prometheus_value(value: float) -> str:
    v = float(value)
    if v != v or v in (float("inf"), float("-inf")):
        # Prometheus exposition accepts NaN/+Inf/-Inf literals; a
        # non-finite gauge (numerics observes the pathological cases
        # by design) must not crash the scrape surface
        return "NaN" if v != v else ("+Inf" if v > 0 else "-Inf")
    return str(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)


class StepTimer:
    """DEPRECATED wall-clock step timing (formerly
    ``utils.profiling.StepTimer``; kept API-compatible for external
    callers — constructing one warns). Use
    ``MetricsRegistry.get_or_create().timer(name)`` instead: same
    one-line timing, but the samples land in the process histogram
    (p50/p99, Prometheus exposition) instead of a private dict.
    ``timed(name, fn, ...)`` blocks on the device result before reading
    the clock — the honest way to time jitted programs. ``step(name)``
    times the enclosed block as-is (callers must block_until_ready
    inside if the block dispatches async device work)."""

    def __init__(self) -> None:
        warnings.warn(
            "StepTimer is deprecated; use MetricsRegistry.get_or_create()"
            ".timer(name) (observability/metrics.py) — same block-style "
            "timing, recorded into the process histograms",
            DeprecationWarning, stacklevel=2)
        self.times: Dict[str, list] = {}

    @contextlib.contextmanager
    def step(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        yield
        self.times.setdefault(name, []).append(time.perf_counter() - t0)

    def timed(self, name: str, fn, *args, **kwargs):
        import jax

        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        out = jax.block_until_ready(out)
        self.times.setdefault(name, []).append(time.perf_counter() - t0)
        return out

    def summary(self) -> str:
        lines = []
        for name, ts in self.times.items():
            lines.append(
                f"{name}: n={len(ts)} mean={sum(ts)/len(ts)*1e3:.2f}ms "
                f"min={min(ts)*1e3:.2f}ms max={max(ts)*1e3:.2f}ms")
        return "\n".join(lines)
