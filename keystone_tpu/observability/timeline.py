"""Flight recorder: a bounded, always-on ring buffer of execution spans.

The :class:`~.trace.PipelineTrace` answers "how long did each node
take"; this module answers "WHEN did everything run, on WHICH thread" —
the Dapper/Perfetto-shaped view that makes prefetch-vs-compute overlap
and lock contention visually inspectable instead of argued from
aggregate counters. Every instrumented subsystem feeds it through the
funnels that already exist:

* the DAG executor's node timers (``workflow/executor.py``, only while
  a trace is active — untraced runs do not wrap thunks);
* the streaming prefetcher: one ``stage:<tag>`` span per chunk on the
  producer thread (decode + pad + H2D staging) and one ``stall:<tag>``
  span per chunk on the consumer (time the device-side loop waited);
* per-shard H2D puts on the ``keystone-h2d`` pool lanes
  (``parallel/mesh.shard_put``);
* the resilience event funnel (``resilience/events.py``) as instant
  events: retries, watchdog trips, checkpoint snapshots, quarantines;
* contended :class:`~keystone_tpu.utils.guarded.TracedLock` acquires
  (one span per lost race, on the losing thread);
* ``fit_streaming``'s per-chunk ``accumulate`` spans (the compute lane
  of a streamed fit).

The buffer is a fixed-capacity ring (``KEYSTONE_FLIGHT_SPANS``, default
8192): recording is a lock + two list writes (~1 µs), old spans fall
off the back, and a long-lived process can never grow it. A crash
post-mortem (:mod:`.postmortem`) or an interpreter exit under an active
stream dumps whatever the ring holds — the last N seconds of evidence,
exactly when it matters.

``to_chrome_trace()`` exports the ring as Chrome trace-event JSON
(``chrome://tracing`` / https://ui.perfetto.dev -> Open trace file):
one lane per real thread, with overlapping spans on a thread (nested
executor nodes) overflowing to ``<thread> (nested k)`` sub-lanes so
every exported lane holds strictly non-overlapping ``ts``/``dur``
ranges. ``--trace-out something.perfetto.json`` on
``python -m keystone_tpu <app>`` and ``bench.py`` writes it directly.

Thread model: the ring is mutated from every instrumented thread and
its guard is a PLAIN ``threading.Lock``, never a TracedLock — a
contended TracedLock acquire reports INTO this recorder, so tracing the
recorder's own lock would re-enter it on the same thread and deadlock
(the same boundary as ``observability/metrics.py``, documented once in
``utils/guarded.py``). ``KEYSTONE_FLIGHT_RECORDER=0`` disables
recording entirely (one branch per call — the telemetry-off side of the
PERFORMANCE.md rule 10 overhead bar).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, NamedTuple, Optional

from ..utils.guarded import guarded_by


class Span(NamedTuple):
    """One recorded interval (or instant, when ``ph == "i"``). Times
    are ``time.perf_counter`` seconds (monotonic, process-local)."""

    name: str
    cat: str
    start_s: float
    dur_s: float
    tid: int
    thread: str
    args: Optional[Dict[str, Any]]
    ph: str  # "X" complete event, "i" instant


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) != "0"


def _env_capacity() -> int:
    raw = os.environ.get("KEYSTONE_FLIGHT_SPANS")
    if not raw:
        return 8192
    try:
        cap = int(raw)
    except ValueError:
        raise ValueError(
            f"KEYSTONE_FLIGHT_SPANS must be an integer, got {raw!r}"
        ) from None
    if cap < 1:
        raise ValueError("KEYSTONE_FLIGHT_SPANS must be >= 1")
    return cap


@guarded_by("_lock", "_ring", "_idx", "_total")
class FlightRecorder:
    """Bounded ring buffer of :class:`Span` entries; see module
    docstring. ``record``/``record_instant`` are called from every
    instrumented thread — the ring index bump is a read-modify-write
    and wraparound writes land in shared slots, so both run under the
    (plain) lock; the regression schedule for the unlocked shape lives
    in tests/test_concurrency_sched.py."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.capacity = _env_capacity() if capacity is None else int(capacity)
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.enabled = (_env_flag("KEYSTONE_FLIGHT_RECORDER")
                        if enabled is None else bool(enabled))
        self._ring: List[Optional[Span]] = [None] * self.capacity
        self._idx = 0
        self._total = 0
        self._lock = threading.Lock()  # plain: TracedLock reports in here
        # span-materialization thunks queued by hot paths (the serving
        # worker); drained at the next view/export. deque append and
        # popleft are GIL-atomic, so no lock rides the fast path, and
        # maxlen bounds memory if no view ever runs.
        self._deferred: Deque[Any] = deque(maxlen=self.capacity)
        #: perf_counter epoch for chrome-trace timestamps
        self.t0_s = time.perf_counter()

    # -- recording ---------------------------------------------------------
    def record(self, name: str, cat: str, start_s: float, dur_s: float,
               args: Optional[Dict[str, Any]] = None, ph: str = "X",
               tid: Optional[int] = None,
               thread: Optional[str] = None) -> None:
        """Append one span (cheap: thread lookup + lock + two writes).
        ``tid``/``thread`` override the recording thread's identity —
        deferred materializers pass the identity captured at defer
        time so spans still land on their originating lane."""
        if not self.enabled:
            return
        if tid is None or thread is None:
            t = threading.current_thread()
            tid = t.ident or 0 if tid is None else tid
            thread = t.name if thread is None else thread
        span = Span(name, cat, float(start_s), float(dur_s),
                    tid, thread, args, ph)
        with self._lock:
            self._ring[self._idx] = span
            self._idx = (self._idx + 1) % self.capacity
            self._total += 1

    def defer(self, materialize: Any) -> None:
        """Queue a zero-argument thunk that will ``record`` one or more
        spans when the recorder is next VIEWED (``spans``, export,
        counters) instead of now. This keeps span construction —
        f-strings, args dicts, the ring lock — off latency-critical
        paths: the serving worker queues one thunk per batch between a
        batch's futures resolving and its next ``take`` (the always-on
        <2% bar, PERFORMANCE.md rule 15). Thunks must capture immutable
        data (completed traces) and the originating thread identity."""
        if self.enabled:
            self._deferred.append(materialize)

    def flush(self) -> None:
        """Run queued materializers (oldest first). Every view calls
        this; the serving worker calls it when idle, the HTTP scrape
        surface before serializing, so deferred telemetry (spans AND
        the phase-histogram observes a thunk carries) is visible at
        every read point. Thunks call ``record``, so this never runs
        under the ring lock."""
        while True:
            try:
                fn = self._deferred.popleft()
            except IndexError:
                return
            fn()

    # internal alias so views read naturally
    _drain = flush

    def record_instant(self, name: str, cat: str,
                       ts_s: Optional[float] = None,
                       args: Optional[Dict[str, Any]] = None) -> None:
        """A zero-duration marker event (resilience events, faults)."""
        self.record(name, cat,
                    time.perf_counter() if ts_s is None else ts_s,
                    0.0, args, ph="i")

    @contextlib.contextmanager
    def span(self, name: str, cat: str, **args: Any) -> Iterator[None]:
        """Record the enclosed block as one span (recorded even when the
        block raises — a crashing stage is exactly what a post-mortem
        needs to show)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, cat, t0, time.perf_counter() - t0,
                        args or None)

    # -- views -------------------------------------------------------------
    def spans(self) -> List[Span]:
        """Retained spans, oldest first (at most ``capacity``)."""
        self._drain()
        with self._lock:
            ring = list(self._ring)
            idx = self._idx
            total = self._total
        if total < self.capacity:
            return [s for s in ring[:idx] if s is not None]
        return [s for s in ring[idx:] + ring[:idx] if s is not None]

    @property
    def total_recorded(self) -> int:
        self._drain()
        with self._lock:
            return self._total

    def dropped(self) -> int:
        """Spans that fell off the back of the ring."""
        self._drain()
        with self._lock:
            return max(0, self._total - self.capacity)

    def clear(self) -> None:
        self._deferred.clear()
        with self._lock:
            self._ring = [None] * self.capacity
            self._idx = 0
            self._total = 0

    # -- export ------------------------------------------------------------
    def to_chrome_trace(self) -> Dict[str, Any]:
        """The ring as a Chrome trace-event / Perfetto JSON object.

        Lane assignment: one lane per recording thread, in first-seen
        order. Within a thread, spans are laid greedily onto sub-lanes
        so no exported lane ever holds two overlapping ``"X"`` events
        (nested executor node spans overflow onto ``<thread>
        (nested k)``) — the strictly-non-overlapping-per-lane invariant
        the round-trip test pins, and what keeps the Perfetto render
        unambiguous. Instants ride lane 0 of their thread.

        Flow links (PR 16): a span whose args carry ``flow_out`` (one
        id) emits a flow-start (``ph:"s"``) at its own ts/lane, and a
        span whose args carry ``flow_in`` (a list of ids) emits one
        enclosed flow-finish (``ph:"f"``, ``bp:"e"``) per id — Perfetto
        draws the arrows from each request span into the batch span
        that served it. Flow events anchor to existing lanes and never
        affect lane assignment."""
        spans = self.spans()
        events: List[Dict[str, Any]] = []
        # (os thread id, sublane) -> exported integer tid, plus names
        lane_ids: Dict[tuple, int] = {}
        lane_names: Dict[int, str] = {}

        def lane(tid: int, thread: str, sub: int) -> int:
            key = (tid, sub)
            if key not in lane_ids:
                lane_ids[key] = len(lane_ids) + 1
                lane_names[lane_ids[key]] = (
                    thread if sub == 0 else f"{thread} (nested {sub})")
            return lane_ids[key]

        by_thread: Dict[int, List[Span]] = {}
        for s in spans:
            by_thread.setdefault(s.tid, []).append(s)
        for tid in by_thread:
            # longer spans first at equal start so a nested child (same
            # start, shorter) overflows, not its parent
            complete = sorted(
                (s for s in by_thread[tid] if s.ph == "X"),
                key=lambda s: (s.start_s, -s.dur_s))
            lane_end: List[float] = []  # per sub-lane, last span end
            for s in complete:
                sub = 0
                while sub < len(lane_end) and s.start_s < lane_end[sub]:
                    sub += 1
                if sub == len(lane_end):
                    lane_end.append(0.0)
                lane_end[sub] = s.start_s + s.dur_s
                ts = round((s.start_s - self.t0_s) * 1e6, 3)
                lid = lane(s.tid, s.thread, sub)
                events.append({
                    "name": s.name, "cat": s.cat, "ph": "X",
                    "ts": ts, "dur": round(s.dur_s * 1e6, 3),
                    "pid": 1, "tid": lid,
                    "args": s.args or {},
                })
                flow_args = s.args or {}
                if "flow_out" in flow_args:
                    events.append({
                        "name": "req", "cat": s.cat, "ph": "s",
                        "id": int(flow_args["flow_out"]),
                        "ts": ts, "pid": 1, "tid": lid,
                    })
                for fid in flow_args.get("flow_in", ()):
                    events.append({
                        "name": "req", "cat": s.cat, "ph": "f",
                        "bp": "e", "id": int(fid),
                        "ts": ts, "pid": 1, "tid": lid,
                    })
            for s in by_thread[tid]:
                if s.ph != "i":
                    continue
                events.append({
                    "name": s.name, "cat": s.cat, "ph": "i", "s": "t",
                    "ts": round((s.start_s - self.t0_s) * 1e6, 3),
                    "pid": 1, "tid": lane(s.tid, s.thread, 0),
                    "args": s.args or {},
                })
        meta = [{"name": "process_name", "ph": "M", "pid": 1,
                 "args": {"name": "keystone_tpu"}}]
        for lid, lname in sorted(lane_names.items()):
            meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                         "tid": lid, "args": {"name": lname}})
        return {"traceEvents": meta + events, "displayTimeUnit": "ms",
                "otherData": {"dropped_spans": self.dropped(),
                              "recorded_spans": self.total_recorded}}

    def to_chrome_json(self) -> str:
        return json.dumps(self.to_chrome_trace(), default=str)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_chrome_json())


# -- process-global recorder -------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-global recorder (lazily built; the create is
    double-checked — worker threads record from the first chunk)."""
    global _RECORDER
    rec = _RECORDER
    if rec is None:
        with _RECORDER_LOCK:
            rec = _RECORDER
            if rec is None:
                rec = _RECORDER = FlightRecorder()
    return rec


def reset_flight_recorder() -> None:
    """Drop the global recorder (tests; the next record builds a fresh
    one, re-reading the env knobs)."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None


def record_span(name: str, cat: str, start_s: float, dur_s: float,
                args: Optional[Dict[str, Any]] = None) -> None:
    """Module-level convenience for instrumentation sites."""
    flight_recorder().record(name, cat, start_s, dur_s, args)


def record_instant(name: str, cat: str,
                   args: Optional[Dict[str, Any]] = None) -> None:
    flight_recorder().record_instant(name, cat, args=args)


@contextlib.contextmanager
def flight_span(name: str, cat: str, **args: Any) -> Iterator[None]:
    with flight_recorder().span(name, cat, **args):
        yield


def write_trace_artifact(path: str, trace=None) -> str:
    """The ``--trace-out`` dispatch shared by the app CLI and bench:
    a path ending ``.perfetto.json`` gets the flight recorder's Chrome
    trace (open in https://ui.perfetto.dev); anything else gets the
    :class:`~.trace.PipelineTrace` JSON. Returns which kind was
    written (``"perfetto"`` / ``"trace"``)."""
    if str(path).endswith(".perfetto.json"):
        flight_recorder().dump(path)
        return "perfetto"
    if trace is None:
        raise ValueError(
            "write_trace_artifact needs an active PipelineTrace for "
            "non-perfetto paths")
    with open(path, "w") as f:
        f.write(trace.to_json())
    return "trace"
