"""Structured per-run pipeline tracing.

A :class:`PipelineTrace` is entered as a context manager around pipeline
execution; while active (:func:`current_trace` returns it), the workflow
stack feeds it:

* per-node execution records (``record_node`` — appended by the
  executor's instrumented expression thunks, with wall time measured
  after ``jax.block_until_ready`` on device results, the output's
  device-memory footprint, whether the value came from a cache/prefix
  hit or was computed, and the data shard count);
* optimizer rule logs (``record_rule`` — which rewrite rules fired and
  the graph-size delta per rule);
* the auto-cache rule's report (``record_auto_cache`` — the sampled
  profiles it extrapolated, the cache set it selected, and the memory
  budget it worked under);
* node-level cost-model decisions (``record_node_choice`` /
  ``record_solver_decision`` — the workload shape n/d/k/sparsity, the
  per-solver cost estimates behind each choice, and the calibration
  provenance of the cost-model weights).

Node wall times are *self* times: each instrumented thunk's elapsed time
minus the time spent inside nested instrumented thunks (dependencies are
lazy and memoized, so a parent's first ``get()`` transitively computes
its uncomputed ancestors). Self times therefore sum to the real
aggregate compute time with no double counting, which is what makes
``summary()``'s per-node percentages meaningful.

Tracing is zero-overhead by default: when no trace is active every hook
returns immediately, and the executor does not wrap expression thunks at
all.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from ..utils.guarded import TracedLock, guarded_by

_ACTIVE: Optional["PipelineTrace"] = None


def current_trace() -> Optional["PipelineTrace"]:
    """The active trace, or None when tracing is disabled (the common
    case — instrumentation sites bail out on None)."""
    return _ACTIVE


_SUPPRESS_DEPTH = 0


@contextlib.contextmanager
def tracing_disabled() -> Iterator[None]:
    """Suspend the active trace AND the executor's always-on metrics
    counters for the enclosed block. Used by optimizer sampling
    (node-level optimization, auto-cache profiling): sampled sub-graph
    executions share node ids with the main graph and would pollute the
    per-node record stream and inflate ``executor.*`` counters; their
    aggregate cost is already recorded in the optimizer decision
    entries."""
    global _ACTIVE, _SUPPRESS_DEPTH
    prev = _ACTIVE
    _ACTIVE = None
    _SUPPRESS_DEPTH += 1
    try:
        yield
    finally:
        _ACTIVE = prev
        _SUPPRESS_DEPTH -= 1


def metrics_suppressed() -> bool:
    """True inside a :func:`tracing_disabled` block (throwaway sampled
    executions must not count as real executor activity)."""
    return _SUPPRESS_DEPTH > 0


@dataclass
class NodeRecord:
    """One executed graph node."""

    node_id: int
    operator: str
    wall_s: float = 0.0        # self time (nested node compute excluded)
    total_s: float = 0.0       # inclusive wall time of this node's thunk
    output_bytes: float = 0.0  # device-memory footprint of the output
    cached: bool = False       # value came from the prefix/state memo
    shards: int = 1            # data shards of the output dataset
    kind: str = ""             # expression kind (dataset/datum/transformer)
    # hardware-utilization annotations (observability/utilization.py
    # ``annotate_trace`` back-fills them from the compile observatory's
    # per-executable cost_analysis; zero = not annotated)
    flops: float = 0.0         # XLA cost-model FLOPs of this node's program
    mfu: float = 0.0           # achieved FLOP/s over device peak
    membw_util: float = 0.0    # achieved bytes/s over HBM bandwidth
    plan_vs_xla: float = 0.0   # static HbmPlan bytes / XLA output+temp bytes


class _Frame:
    __slots__ = ("child_s",)

    def __init__(self) -> None:
        self.child_s = 0.0


@guarded_by("_resilience_lock", "resilience", "resilience_stats")
@guarded_by("_lock_wait_lock", "lock_waits")
@guarded_by("_compile_lock", "compiles", "compile_stats")
@guarded_by("_numerics_lock", "numerics", "numerics_stats")
class PipelineTrace:
    """Collects one run's execution telemetry; see module docstring.

    Usage::

        with PipelineTrace("mnist") as tr:
            pipeline.apply(data).numpy()
        print(tr.summary())
        open("trace.json", "w").write(tr.to_json())

    Thread model: the per-node/chunk/optimizer streams are fed by the
    single driver thread; ``record_resilience`` and
    ``record_lock_wait`` are fed by ingest worker threads and take
    locks (declared above, checked by ``analysis.concurrency``).
    """

    def __init__(self, name: str = "pipeline"):
        self.name = name
        self.nodes: List[NodeRecord] = []
        self.optimizer_rules: List[Dict[str, Any]] = []
        self.auto_cache: List[Dict[str, Any]] = []
        self.node_choices: List[Dict[str, Any]] = []
        self.solver_decisions: List[Dict[str, Any]] = []
        #: most recent streamed-ingest chunk entries (bounded tail —
        #: an out-of-core fit can stream millions of chunks, so exact
        #: aggregates live in ``chunk_stats`` and only CHUNK_TAIL raw
        #: entries are retained for inspection)
        self.chunks: List[Dict[str, Any]] = []
        self.chunk_stats: Dict[str, float] = {
            "count": 0, "ingest_stall_s": 0.0, "nbytes": 0.0,
            "occupancy_sum": 0.0, "h2d_bytes": 0.0}
        #: one entry per streamed fit: the static HBM plan next to the
        #: measured residency peak, so the planner model is continuously
        #: validated by every traced out-of-core fit
        self.streamed_fits: List[Dict[str, Any]] = []
        #: resilience events (retries, quarantines, checkpoint
        #: saves/restores, watchdog trips, injected faults) — same
        #: bounded-tail-plus-exact-counts shape as ``chunks``
        self.resilience: List[Dict[str, Any]] = []
        self.resilience_stats: Dict[str, float] = {}
        # resilience events fire from decode/prefetch worker threads
        # concurrently; the read-modify-write on the stats dict needs a
        # real lock for the "counts stay exact" contract to hold — a
        # TracedLock, so its own contention is observable and the
        # schedule harness can interleave at it (the PR 4 race's
        # regression schedule lives in tests/test_concurrency_sched.py)
        self._resilience_lock = TracedLock("trace.resilience")
        #: compile events observed while this trace was active
        #: (``observability/compilelog.py``): site name, wall, trigger
        #: classification, signature delta, unexpected flag — same
        #: bounded-tail-plus-exact-stats shape as ``resilience``.
        #: Compiles can fire from ingest worker threads (the streaming
        #: consumer's wire-cast, decode-side helpers), hence the lock
        #: (plain: compile records also feed metrics/recorder, the
        #: usual boundary).
        self.compiles: List[Dict[str, Any]] = []
        self.compile_stats: Dict[str, float] = {
            "count": 0, "wall_s": 0.0, "unexpected": 0}
        self._compile_lock = threading.Lock()
        #: numerics events (observability/numerics.py): solver
        #: breakdowns, non-finite tripwires, drift scores/warnings —
        #: same bounded-tail-plus-exact-counts shape as ``resilience``.
        #: Solver-ledger events arrive from jax debug-callback threads,
        #: hence the lock (a TracedLock: its contention reports into
        #: metrics/recorder/lock_waits, never back into this stream).
        self.numerics: List[Dict[str, Any]] = []
        self.numerics_stats: Dict[str, float] = {}
        self._numerics_lock = TracedLock("trace.numerics")
        #: contended-lock wait table fed by TracedLock while this trace
        #: is active: {lock name: {"count": n, "wait_s": total}}. Its
        #: own guard is a PLAIN lock — TracedLock reports in here, so a
        #: traced guard would recurse (utils/guarded.py documents the
        #: boundary).
        self.lock_waits: Dict[str, Dict[str, float]] = {}
        self._lock_wait_lock = threading.Lock()
        self.meta: Dict[str, Any] = {}
        self.wall_s: float = 0.0
        self._t0: Optional[float] = None
        self._stack: List[_Frame] = []
        self._prev: Optional["PipelineTrace"] = None

    # -- context ----------------------------------------------------------
    def __enter__(self) -> "PipelineTrace":
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self
        self._t0 = time.perf_counter()
        try:
            import jax

            dev = jax.devices()[0]
            self.meta.setdefault("backend", dev.platform)
            self.meta.setdefault("device_kind", dev.device_kind)
            self.meta.setdefault("num_devices", len(jax.devices()))
        except Exception:
            pass
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        if self._t0 is not None:
            self.wall_s += time.perf_counter() - self._t0
            self._t0 = None
        _ACTIVE = self._prev
        self._prev = None

    # -- recording hooks (called by the workflow stack) -------------------
    @contextlib.contextmanager
    def node_timer(self, record: NodeRecord) -> Iterator[NodeRecord]:
        """Time one node's thunk, attributing nested instrumented node
        time to the children (self-time accounting)."""
        frame = _Frame()
        self._stack.append(frame)
        t0 = time.perf_counter()
        try:
            yield record
        finally:
            total = time.perf_counter() - t0
            self._stack.pop()
            record.total_s = total
            record.wall_s = max(total - frame.child_s, 0.0)
            if self._stack:
                self._stack[-1].child_s += total
            self.nodes.append(record)

    def record_node(self, record: NodeRecord) -> None:
        """Record a node that involved no timed compute (eager constants,
        prefix/state cache hits)."""
        self.nodes.append(record)

    def record_rule(self, optimizer: str, batch: str, rule: str,
                    nodes_before: int, nodes_after: int,
                    wall_s: float) -> None:
        self.optimizer_rules.append({
            "optimizer": optimizer, "batch": batch, "rule": rule,
            "nodes_before": nodes_before, "nodes_after": nodes_after,
            "wall_s": wall_s,
        })

    def record_auto_cache(self, report: Dict[str, Any]) -> None:
        self.auto_cache.append(report)

    def record_node_choice(self, entry: Dict[str, Any]) -> None:
        self.node_choices.append(entry)

    def record_solver_decision(self, entry: Dict[str, Any]) -> None:
        self.solver_decisions.append(entry)

    #: raw per-chunk entries retained (the aggregates in ``chunk_stats``
    #: are exact over ALL chunks regardless)
    CHUNK_TAIL = 512

    def record_chunk(self, entry: Dict[str, Any]) -> None:
        """One streamed ingest chunk (``parallel.streaming``): source
        tag, chunk index, true row count, device footprint (post-cast
        working copy), the wire bytes actually shipped host->device
        (``h2d_bytes`` — narrower than ``nbytes`` when a wire dtype is
        in play), stage-lane occupancy (``stage_lanes`` per-shard H2D
        lanes / ``stage_s`` host stage wall), the time the consumer
        stalled waiting for ingest, and the prefetch-buffer occupancy at
        hand-off. The per-chunk stall attribution is the evidence behind
        'ingest overlaps compute' claims. Aggregates are exact; raw
        entries keep only the most recent ``CHUNK_TAIL`` (an out-of-core
        fit can stream unboundedly many chunks)."""
        s = self.chunk_stats
        s["count"] += 1
        s["ingest_stall_s"] += float(entry.get("ingest_stall_s", 0.0))
        s["nbytes"] += float(entry.get("nbytes", 0.0))
        s["occupancy_sum"] += float(entry.get("prefetch_occupancy", 0.0))
        s["h2d_bytes"] = (s.get("h2d_bytes", 0.0)
                          + float(entry.get("h2d_bytes", 0.0)))
        self.chunks.append(entry)
        if len(self.chunks) > self.CHUNK_TAIL:
            del self.chunks[: len(self.chunks) - self.CHUNK_TAIL]

    #: raw streamed-fit entries retained (same bounded-tail discipline
    #: as ``chunks``/``resilience`` — a long-lived retrain loop under
    #: one trace must not grow it without bound)
    STREAMED_FIT_TAIL = 512

    def record_streamed_fit(self, entry: Dict[str, Any]) -> None:
        """One completed streamed fit (``parallel.streaming``): source
        tag, chunk count, ``static_plan_nbytes`` (the device-free
        residency bound the planner computed — None for opaque
        sources), the ledger's measured ``peak_device_nbytes``, and the
        asserted ``hbm_budget`` if any. ``static_plan_nbytes >=
        peak_device_nbytes`` is the planner's correctness contract;
        bench reports the ratio as ``plan_vs_measured``."""
        self.streamed_fits.append(entry)
        if len(self.streamed_fits) > self.STREAMED_FIT_TAIL:
            del self.streamed_fits[: len(self.streamed_fits)
                                   - self.STREAMED_FIT_TAIL]

    #: raw resilience entries retained (per-event counts in
    #: ``resilience_stats`` stay exact)
    RESILIENCE_TAIL = 512

    def record_resilience(self, entry: Dict[str, Any]) -> None:
        """One resilience event (:mod:`keystone_tpu.resilience.events`):
        ``entry["event"]`` is the kind (retry / retry_exhausted /
        quarantine / checkpoint_save / checkpoint_restore /
        watchdog_trip / fault_injected), the rest is site context. May
        be called from ingest worker threads (append-only under the
        GIL, like ``record_chunk``)."""
        event = str(entry.get("event", "other"))
        with self._resilience_lock:
            self.resilience_stats[event] = (
                self.resilience_stats.get(event, 0) + 1)
            self.resilience.append(entry)
            if len(self.resilience) > self.RESILIENCE_TAIL:
                del self.resilience[: len(self.resilience)
                                    - self.RESILIENCE_TAIL]

    #: raw compile entries retained (``compile_stats`` stays exact)
    COMPILE_TAIL = 512

    def record_compile(self, entry: Dict[str, Any]) -> None:
        """One XLA compile observed while this trace was active
        (:mod:`keystone_tpu.observability.compilelog`): site name,
        compile wall, trigger (first-compile / signature-change /
        mesh-change / retrace / unowned), the signature delta when one
        is nameable, the attributing context (an executor node scope),
        and the ``unexpected`` flag when a warmup fence was armed."""
        with self._compile_lock:
            self.compile_stats["count"] += 1
            self.compile_stats["wall_s"] += float(entry.get("wall_s", 0.0))
            if entry.get("unexpected"):
                self.compile_stats["unexpected"] += 1
            self.compiles.append(entry)
            if len(self.compiles) > self.COMPILE_TAIL:
                del self.compiles[: len(self.compiles) - self.COMPILE_TAIL]

    #: raw numerics entries retained (per-event counts in
    #: ``numerics_stats`` stay exact)
    NUMERICS_TAIL = 512

    def record_numerics(self, entry: Dict[str, Any]) -> None:
        """One numerics event (:mod:`keystone_tpu.observability.\
numerics`): ``entry["event"]`` is the kind (nonfinite /
        nonfinite_model / breakdown / drift_score / drift_warn /
        fit_baseline), the rest is site context — solver site and pivot
        ratio for breakdowns, source/chunk for tripwires, PSI score for
        drift. May fire from jax debug-callback threads (the solver
        ledger), hence the lock."""
        event = str(entry.get("event", "other"))
        with self._numerics_lock:
            self.numerics_stats[event] = (
                self.numerics_stats.get(event, 0) + 1)
            self.numerics.append(entry)
            if len(self.numerics) > self.NUMERICS_TAIL:
                del self.numerics[: len(self.numerics)
                                  - self.NUMERICS_TAIL]

    def record_lock_wait(self, name: str, wait_s: float) -> None:
        """One contended :class:`~keystone_tpu.utils.guarded.TracedLock`
        acquire while this trace was active (called from whichever
        thread lost the race — always under ``_lock_wait_lock``).
        ``summary()`` prints the top contended locks, so a traced
        streamed fit shows WHERE its threads serialized, not just that
        they did."""
        with self._lock_wait_lock:
            entry = self.lock_waits.get(name)
            if entry is None:
                entry = self.lock_waits[name] = {
                    "count": 0, "wait_s": 0.0}
            entry["count"] += 1
            entry["wait_s"] += float(wait_s)

    def ingest_stall_s(self) -> float:
        """Total consumer-side ingest stall across ALL streamed chunks
        (exact aggregate) — compare against ``wall_s`` for the overlap
        share."""
        return float(self.chunk_stats["ingest_stall_s"])

    # -- views ------------------------------------------------------------
    def node_ids(self) -> set:
        return {r.node_id for r in self.nodes}

    def cache_hits(self) -> List[NodeRecord]:
        return [r for r in self.nodes if r.cached]

    def total_node_wall_s(self) -> float:
        return sum(r.wall_s for r in self.nodes)

    # -- export -----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "meta": dict(self.meta),
            "wall_s": self.wall_s,
            "nodes": [asdict(r) for r in self.nodes],
            "optimizer_rules": list(self.optimizer_rules),
            "auto_cache": list(self.auto_cache),
            "node_choices": list(self.node_choices),
            "solver_decisions": list(self.solver_decisions),
            "chunks": list(self.chunks),
            "chunk_stats": dict(self.chunk_stats),
            "streamed_fits": list(self.streamed_fits),
            "resilience": list(self.resilience),
            "resilience_stats": dict(self.resilience_stats),
            "compiles": list(self.compiles),
            "compile_stats": dict(self.compile_stats),
            "numerics": list(self.numerics),
            "numerics_stats": dict(self.numerics_stats),
            "lock_waits": {k: dict(v)
                           for k, v in self.lock_waits.items()},
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=False)

    @classmethod
    def from_json(cls, blob: str) -> "PipelineTrace":
        data = json.loads(blob)
        tr = cls(data.get("name", "pipeline"))
        tr.meta = dict(data.get("meta", {}))
        tr.wall_s = float(data.get("wall_s", 0.0))
        tr.nodes = [NodeRecord(**r) for r in data.get("nodes", [])]
        tr.optimizer_rules = list(data.get("optimizer_rules", []))
        tr.auto_cache = list(data.get("auto_cache", []))
        tr.node_choices = list(data.get("node_choices", []))
        tr.solver_decisions = list(data.get("solver_decisions", []))
        tr.chunks = list(data.get("chunks", []))
        stats = data.get("chunk_stats")
        if stats is None and tr.chunks:  # older artifact: rebuild
            stats = {
                "count": len(tr.chunks),
                "ingest_stall_s": sum(
                    float(c.get("ingest_stall_s", 0.0)) for c in tr.chunks),
                "nbytes": sum(
                    float(c.get("nbytes", 0.0)) for c in tr.chunks),
                "occupancy_sum": sum(
                    float(c.get("prefetch_occupancy", 0.0))
                    for c in tr.chunks),
                "h2d_bytes": sum(
                    float(c.get("h2d_bytes", 0.0)) for c in tr.chunks),
            }
        if stats is not None:
            tr.chunk_stats = dict(stats)
        tr.streamed_fits = list(data.get("streamed_fits", []))
        tr.resilience = list(data.get("resilience", []))
        tr.resilience_stats = dict(data.get("resilience_stats", {}))
        if not tr.resilience_stats and tr.resilience:  # older artifact
            for e in tr.resilience:
                ev = str(e.get("event", "other"))
                tr.resilience_stats[ev] = (
                    tr.resilience_stats.get(ev, 0) + 1)
        tr.compiles = list(data.get("compiles", []))
        cstats = data.get("compile_stats")
        if cstats is None and tr.compiles:  # older artifact: rebuild
            cstats = {
                "count": len(tr.compiles),
                "wall_s": sum(float(c.get("wall_s", 0.0))
                              for c in tr.compiles),
                "unexpected": sum(1 for c in tr.compiles
                                  if c.get("unexpected")),
            }
        if cstats is not None:
            tr.compile_stats = dict(cstats)
        tr.numerics = list(data.get("numerics", []))
        tr.numerics_stats = dict(data.get("numerics_stats", {}))
        if not tr.numerics_stats and tr.numerics:  # older artifact
            for e in tr.numerics:
                ev = str(e.get("event", "other"))
                tr.numerics_stats[ev] = tr.numerics_stats.get(ev, 0) + 1
        tr.lock_waits = {k: dict(v) for k, v in
                         data.get("lock_waits", {}).items()}
        return tr

    def summary(self, top: int = 0) -> str:
        """Human-readable per-node table sorted by self wall time, with
        each node's share of the total, followed by optimizer decisions."""
        lines = [f"PipelineTrace {self.name!r}: "
                 f"{len(self.nodes)} node executions, "
                 f"wall {self.wall_s:.3f}s"]
        total = self.total_node_wall_s()
        lines.append(f"traced node compute: {total:.3f}s "
                     f"({100.0 * total / self.wall_s:.1f}% of wall)"
                     if self.wall_s else
                     f"traced node compute: {total:.3f}s")
        rows = sorted(self.nodes, key=lambda r: -r.wall_s)
        if top:
            rows = rows[:top]
        lines.append(f"{'node':>6} {'operator':<28} {'self ms':>10} "
                     f"{'% total':>8} {'out MiB':>9} {'shards':>6} "
                     f"{'cached':>6}")
        for r in rows:
            pct = 100.0 * r.wall_s / total if total else 0.0
            lines.append(
                f"{r.node_id:>6} {r.operator[:28]:<28} "
                f"{r.wall_s * 1e3:>10.2f} {pct:>7.1f}% "
                f"{r.output_bytes / (1 << 20):>9.2f} {r.shards:>6} "
                f"{'yes' if r.cached else '':>6}")
        if self.optimizer_rules:
            lines.append("optimizer rules fired:")
            for e in self.optimizer_rules:
                lines.append(
                    f"  {e['rule']} [{e['batch']}] nodes "
                    f"{e['nodes_before']} -> {e['nodes_after']} "
                    f"({e['wall_s'] * 1e3:.1f} ms)")
        for rep in self.auto_cache:
            sel = rep.get("selected", [])
            lines.append(
                f"auto-cache[{rep.get('strategy')}]: cached {len(sel)} "
                f"node(s) {sel} under budget "
                f"{rep.get('budget_bytes', 0) / (1 << 20):.0f} MiB "
                f"(profiled {len(rep.get('profiles', {}))} nodes)")
        if self.chunk_stats["count"]:
            count = int(self.chunk_stats["count"])
            stall = self.ingest_stall_s()
            share = (100.0 * stall / self.wall_s) if self.wall_s else 0.0
            h2d = float(self.chunk_stats.get("h2d_bytes", 0.0))
            lines.append(
                f"streamed ingest: {count} chunk(s), "
                f"stall {stall:.3f}s ({share:.1f}% of wall), "
                f"h2d {h2d / (1 << 20):.1f} MiB, "
                f"mean prefetch occupancy "
                f"{self.chunk_stats['occupancy_sum'] / count:.2f}")
        for sf in self.streamed_fits:
            plan = sf.get("static_plan_nbytes")
            peak = float(sf.get("peak_device_nbytes", 0.0))
            mib = 1 << 20
            if plan is None:
                shown = "plan n/a (opaque source)"
            else:
                ratio = (plan / peak) if peak else float("inf")
                shown = (f"plan {plan / mib:.2f} MiB, "
                         f"plan/measured {ratio:.2f}")
            lines.append(
                f"streamed fit [{sf.get('source')}]: "
                f"{sf.get('chunks', 0)} chunk(s), measured peak "
                f"{peak / mib:.2f} MiB, {shown}")
        if self.compile_stats["count"]:
            c = self.compile_stats
            worst = sorted(self.compiles,
                           key=lambda e: -float(e.get("wall_s", 0.0)))[:3]
            shown = ", ".join(
                f"{e.get('name')} ({float(e.get('wall_s', 0.0)):.2f}s, "
                f"{e.get('trigger')})" for e in worst)
            lines.append(
                f"compiles: {int(c['count'])} ({c['wall_s']:.2f}s wall, "
                f"{int(c['unexpected'])} unexpected) — top: {shown}")
        if self.resilience_stats:
            counts = " ".join(
                f"{k}={int(v)}" for k, v in sorted(
                    self.resilience_stats.items()))
            lines.append(f"resilience events: {counts}")
        if self.numerics_stats:
            counts = " ".join(
                f"{k}={int(v)}" for k, v in sorted(
                    self.numerics_stats.items()))
            lines.append(f"numerics events: {counts}")
        if self.lock_waits:
            top = sorted(self.lock_waits.items(),
                         key=lambda kv: -kv[1].get("wait_s", 0.0))[:3]
            shown = ", ".join(
                f"{name} ({int(v.get('count', 0))}x, "
                f"{v.get('wait_s', 0.0) * 1e3:.1f} ms)"
                for name, v in top)
            lines.append(f"contended locks (top {len(top)}): {shown}")
        for d in self.solver_decisions:
            costs = ", ".join(
                f"{k}={v:.3g}s" for k, v in d.get("costs", {}).items())
            sp = d.get("sparsity")
            sp = "?" if sp is None else f"{sp:.3g}"  # trimmed artifacts
            lines.append(
                f"solver choice @ n={d.get('n')} d={d.get('d')} "
                f"k={d.get('k')} sparsity={sp}: "
                f"{d.get('chosen')} ({costs}) "
                f"[weights: {d.get('provenance', {}).get('source', '?')}]")
        return "\n".join(lines)


@contextlib.contextmanager
def xprof_trace(log_dir: str, name: str = "pipeline"
                ) -> Iterator[PipelineTrace]:
    """Capture an XLA profiler trace (xplane, viewable in
    TensorBoard/XProf) for everything in scope, with a
    :class:`PipelineTrace` active so per-node
    ``jax.profiler.TraceAnnotation`` scopes carry pipeline-level
    operator names in the profile.

    When a trace is already active it is reused (yielded as-is), so
    nesting ``xprof_trace`` inside ``with PipelineTrace(...) as tr:``
    keeps every record in ``tr`` instead of diverting it to a throwaway
    inner trace."""
    import jax

    active = current_trace()
    ctx = (contextlib.nullcontext(active) if active is not None
           else PipelineTrace(name))
    with ctx as tr:
        jax.profiler.start_trace(log_dir)
        try:
            yield tr
        finally:
            jax.profiler.stop_trace()
